"""End-to-end smoke: the sync trainer runs on the Mock env, steps advance,
checkpoint round-trips, logs written, test mode evaluates."""

import os

import numpy as np
import pytest

from torchbeast_tpu import monobeast


def make_flags(tmp_path, **overrides):
    argv = [
        "--env", "Mock",
        "--num_actors", "2",
        "--batch_size", "2",
        "--unroll_length", "5",
        "--total_steps", "40",
        "--savedir", str(tmp_path),
        "--xpid", "smoke",
        "--serial_envs",
        "--checkpoint_interval_s", "100000",
    ]
    for k, v in overrides.items():
        argv += [f"--{k}"] if v is True else [f"--{k}", str(v)]
    return monobeast.make_parser().parse_args(argv)


def test_train_smoke_and_resume(tmp_path):
    flags = make_flags(tmp_path)
    stats = monobeast.train(flags)
    assert stats["step"] >= 40

    xpdir = tmp_path / "smoke"
    assert (xpdir / "model.ckpt").exists()
    assert (xpdir / "logs.csv").exists()
    assert (xpdir / "meta.json").exists()

    # Resume: starts from the saved step counter and continues further.
    flags2 = make_flags(tmp_path, total_steps=80)
    stats2 = monobeast.train(flags2)
    assert stats2["step"] >= 80


def test_train_with_lstm(tmp_path):
    flags = make_flags(tmp_path, xpid="smoke-lstm", use_lstm=True)
    stats = monobeast.train(flags)
    assert stats["step"] >= 40
    assert np.isfinite(stats["total_loss"])


def test_train_associative_vtrace(tmp_path):
    """--vtrace_impl associative (log-depth suffix solve) trains through
    the same driver path; numerics parity with the sequential scan is
    pinned in tests/test_vtrace.py."""
    flags = make_flags(
        tmp_path, xpid="smoke-assoc", vtrace_impl="associative"
    )
    stats = monobeast.train(flags)
    assert stats["step"] >= 40
    assert np.isfinite(stats["total_loss"])


def test_test_mode(tmp_path):
    flags = make_flags(tmp_path)
    monobeast.train(flags)
    tflags = make_flags(tmp_path, mode="test", num_test_episodes="2")
    # Mock episodes are 200 steps of reward 1.0.
    returns = monobeast.test(tflags)
    assert len(returns) == 2
    assert all(r == 200.0 for r in returns)


def test_bf16_train_learns_catch(tmp_path):
    """--precision bf16_train LEARNING smoke, tier-1 by design (ISSUE
    8): bf16-resident params + bf16 staged batch + bf16 second moment
    must still solve Catch (return 1.0 measured in the calibration run;
    gated at 0.5 — well above the ~-0.3 chance floor — to absorb
    CPU-container seed noise). The f32 twin of this config is the slow
    test_mono_learns_catch; this is the one end-to-end proof that the
    precision policy changes bytes, not the algorithm."""
    flags = monobeast.make_parser().parse_args([
        "--env", "Catch",
        "--model", "mlp",
        "--num_actors", "16",
        "--batch_size", "16",
        "--unroll_length", "9",
        "--total_steps", "60000",
        "--serial_envs",
        "--learning_rate", "2e-3",
        "--entropy_cost", "0.01",
        "--savedir", str(tmp_path),
        "--xpid", "catch-bf16",
        "--checkpoint_interval_s", "100000",
        "--precision", "bf16_train",
    ])
    stats = monobeast.train(flags)
    assert stats.get("mean_episode_return", -1.0) > 0.5


@pytest.mark.slow
def test_mono_learns_catch(tmp_path):
    """End-to-end learning check on a real task: the sync driver must
    learn Catch well above chance (~-0.3) within a small frame budget."""
    flags = monobeast.make_parser().parse_args([
        "--env", "Catch",
        "--model", "mlp",
        "--num_actors", "16",
        "--batch_size", "16",
        "--unroll_length", "9",
        "--total_steps", "80000",
        "--serial_envs",
        "--learning_rate", "2e-3",
        "--entropy_cost", "0.01",
        "--savedir", str(tmp_path),
        "--xpid", "catch-learn",
        "--checkpoint_interval_s", "100000",
    ])
    stats = monobeast.train(flags)
    assert stats.get("mean_episode_return", -1.0) > 0.5


@pytest.mark.slow
def test_mono_learns_catch_with_lstm(tmp_path):
    """BASELINE config 3's shape (--use_lstm): the recurrent core must
    LEARN, not just run — state carry/reset through the unroll is the
    trickiest on-policy machinery (reference monobeast.py:599-611,
    core_agent_state_test.py). Pilot run solved Catch (return 1.0) by
    ~38k steps with these hyperparameters
    (benchmarks/artifacts/lstm_learning.md)."""
    flags = monobeast.make_parser().parse_args([
        "--env", "Catch",
        "--model", "mlp",
        "--use_lstm",
        "--num_actors", "16",
        "--batch_size", "16",
        "--unroll_length", "20",
        "--total_steps", "60000",
        "--serial_envs",
        "--learning_rate", "2e-3",
        "--entropy_cost", "0.01",
        "--savedir", str(tmp_path),
        "--xpid", "catch-lstm",
        "--checkpoint_interval_s", "100000",
    ])
    stats = monobeast.train(flags)
    assert stats.get("mean_episode_return", -1.0) > 0.5


@pytest.mark.slow
def test_lstm_solves_memory_env(tmp_path):
    """The FF-vs-LSTM differential on the Memory probe (MemoryChainEnv):
    nothing observable at the decision step correlates with the cue and
    the forward-penalty breaks the last-action relay, so feed-forward
    caps at ~0 while a working recurrent core reaches +1. Pilot curves:
    LSTM sustained 1.0 from ~37k steps; FF oscillated in [-0.35, +0.3]
    for 150k (benchmarks/artifacts/lstm_learning.md)."""

    def run(use_lstm, xpid):
        argv = [
            "--env", "Memory",
            "--model", "mlp",
            "--num_actors", "16",
            "--batch_size", "16",
            "--unroll_length", "20",
            "--total_steps", "80000",
            "--serial_envs",
            "--learning_rate", "1e-3",
            "--entropy_cost", "0.01",
            # Pinned cue stream (verified good for BOTH arms): with
            # serial envs + the fixed model seed the whole run is
            # deterministic, so this test cannot flake.
            "--env_seed", "1",
            "--savedir", str(tmp_path),
            "--xpid", xpid,
            "--checkpoint_interval_s", "100000",
        ] + (["--use_lstm"] if use_lstm else [])
        return monobeast.train(monobeast.make_parser().parse_args(argv))

    lstm_stats = run(True, "mem-lstm")
    assert lstm_stats.get("mean_episode_return", -1.0) > 0.6
    ff_stats = run(False, "mem-ff")
    assert ff_stats.get("mean_episode_return", 1.0) < 0.5


@pytest.mark.slow
def test_transformer_solves_memory_env(tmp_path):
    """Attention-as-memory: the transformer policy (no LSTM) solves the
    Memory probe because its segment-masked attention over the KV cache
    retrieves the cue frame at the query step — the same differential
    the LSTM test pins, carried by the OTHER memory mechanism. This
    functionally exercises the acting-path cache (the cue enters the
    cache at t=0 and must survive, segment-masked, to t=length-1) and
    the learner's full-attention replay.

    Hyperparameters matter here: at lr 1e-3 roughly 1 run in 3 locks
    into a query-compliance collapse — the corridor penalty's
    "always forward" habit generalizes to the query frame, the
    deterministic −1 there is predicted exactly by the value head, and
    the zeroed advantage freezes the policy (checkpoint rollouts show
    query_action=2 every episode; lstm_learning.md §4 has the
    corrected analysis). lr 5e-4 + entropy 0.02 escaped in 8/8 pilot
    reps by 150k steps; --env_seed 1 (verified passing) + serial envs
    + the fixed model seed make this run deterministic, so the
    residual trap odds cannot flake the test."""
    flags = monobeast.make_parser().parse_args([
        "--env", "Memory",
        "--model", "transformer",
        "--num_actors", "16",
        "--batch_size", "16",
        "--unroll_length", "20",
        "--total_steps", "150000",
        "--serial_envs",
        "--learning_rate", "5e-4",
        "--entropy_cost", "0.02",
        "--env_seed", "1",
        "--savedir", str(tmp_path),
        "--xpid", "mem-transformer",
        "--checkpoint_interval_s", "100000",
    ])
    stats = monobeast.train(flags)
    assert stats.get("mean_episode_return", -1.0) > 0.6


@pytest.mark.slow
@pytest.mark.parametrize("sp_strategy", ["ring", "ulysses"])
def test_sequence_parallel_solves_memory_env(tmp_path, sp_strategy):
    """Memory under sequence-parallel attention on a 4-way `seq` mesh:
    the learner shards the 19-step unroll over time, so cue-to-query
    attention routinely crosses shard boundaries — through the ppermute
    ring, or through ulysses' head-sharding all-to-alls — a LEARNING
    proof for the sequence-parallel path, beyond its existing
    gradient-parity pins. Pilot: 1.0 by <48k steps for both."""
    flags = monobeast.make_parser().parse_args([
        "--env", "Memory",
        "--model", "transformer",
        "--sequence_parallel", "4",
        "--sp_strategy", sp_strategy,
        "--num_actors", "16",
        "--batch_size", "16",
        "--unroll_length", "19",  # T+1 = 20 divisible by the seq axis
        "--total_steps", "60000",
        "--serial_envs",
        "--learning_rate", "5e-4",
        "--entropy_cost", "0.02",
        "--env_seed", "1",
        "--savedir", str(tmp_path),
        "--xpid", f"mem-sp-{sp_strategy}",
        "--checkpoint_interval_s", "100000",
    ])
    stats = monobeast.train(flags)
    assert stats.get("mean_episode_return", -1.0) > 0.6


@pytest.mark.slow
def test_entropy_anneal_cracks_long_corridor(tmp_path):
    """--entropy_cost_final turns the L41 Memory corridor from
    unsolvable (0/6 constant-entropy configs, lstm_learning.md §4b)
    into solved (3/3 pilot seeds, first crossing ~479k steps): early
    high entropy keeps answer actions sampled at the query until the
    +2 advantage takes hold, and the anneal removes the tax before
    convergence. Deterministic via env_seed + serial envs."""
    flags = monobeast.make_parser().parse_args([
        "--env", "Memory-L41",
        "--model", "transformer",
        "--num_actors", "16",
        "--batch_size", "16",
        "--unroll_length", "47",
        "--total_steps", "1000000",
        "--serial_envs",
        "--learning_rate", "5e-4",
        "--entropy_cost", "0.2",
        "--entropy_cost_final", "0.01",
        "--env_seed", "1",
        "--savedir", str(tmp_path),
        "--xpid", "anneal41",
        "--checkpoint_interval_s", "100000",
    ])
    stats = monobeast.train(flags)
    assert stats.get("mean_episode_return", -1.0) > 0.6


@pytest.mark.slow
def test_env_seed_makes_runs_reproducible(tmp_path):
    """--env_seed + --serial_envs + fixed --seed = bit-reproducible
    training: the only OS entropy in the sync driver is the env draw
    stream, which env_seed pins (env i draws from env_seed+i, keeping
    actors decorrelated). Compare full return curves, not just the
    final value; a third run with a different env_seed must diverge
    (else the flag is silently ignored)."""
    import csv

    def returns(xpid, env_seed):
        flags = make_flags(
            tmp_path, xpid=xpid, env="Catch", model="mlp",
            num_actors="4", batch_size="4", unroll_length="10",
            total_steps="4000", learning_rate="2e-3",
            entropy_cost="0.01", env_seed=str(env_seed),
        )
        monobeast.train(flags)
        with open(tmp_path / xpid / "logs.csv") as f:
            return [
                row["mean_episode_return"] for row in csv.DictReader(f)
            ]

    a = returns("det-a", 7)
    b = returns("det-b", 7)
    c = returns("det-c", 8)
    assert a == b
    assert len(a) > 3
    assert a != c


def test_trunk_channels_validation(tmp_path):
    with pytest.raises(ValueError, match="deep only"):
        monobeast.train(
            make_flags(tmp_path, trunk_channels="32,64,64")
        )  # default model is shallow
    with pytest.raises(ValueError, match="three positive"):
        monobeast.train(
            make_flags(tmp_path, model="deep", trunk_channels="32,64")
        )


def test_unaligned_actors_rejected(tmp_path):
    flags = make_flags(tmp_path, num_actors="3")
    try:
        monobeast.train(flags)
        raised = False
    except ValueError:
        raised = True
    assert raised


@pytest.mark.slow
def test_train_transformer_sequence_parallel(tmp_path):
    """The transformer trains with its unroll attention running as ring
    attention over a 4-way `seq` mesh (T+1 = 8 divisible by 4; acting at
    T=1 falls back to dense with the same params)."""
    flags = make_flags(
        tmp_path,
        xpid="smoke-seqpar",
        model="transformer",
        sequence_parallel=4,
        unroll_length=7,
        env="Catch",
        total_steps=56,
    )
    stats = monobeast.train(flags)
    assert stats["step"] >= 56
    assert np.isfinite(stats["total_loss"])


@pytest.mark.slow
def test_train_transformer_zigzag_sequence_parallel(tmp_path):
    """Sequence-parallel training with the zig-zag ring schedule
    (T+1 = 16 divisible by 2N = 8 on a 4-way seq mesh)."""
    flags = make_flags(
        tmp_path,
        xpid="smoke-zigzag",
        model="transformer",
        sequence_parallel=4,
        ring_schedule="zigzag",
        unroll_length=15,
        env="Catch",
        total_steps=64,
    )
    stats = monobeast.train(flags)
    assert stats["step"] >= 64
    assert np.isfinite(stats["total_loss"])


def test_train_overlap_collect(tmp_path):
    """--overlap_collect (policy lag 1): trains, checkpoints, resumes."""
    flags = make_flags(tmp_path, xpid="smoke-ovl", overlap_collect=True)
    stats = monobeast.train(flags)
    assert stats["step"] >= 40
    assert np.isfinite(stats["total_loss"])
    flags2 = make_flags(
        tmp_path, xpid="smoke-ovl", overlap_collect=True, total_steps=80
    )
    stats2 = monobeast.train(flags2)
    assert stats2["step"] >= 80


@pytest.mark.slow
def test_overlap_collect_learns_catch(tmp_path):
    """Lag-1 acting must not break learning: Catch is solved (or close)
    within the same budget the zero-lag test uses."""
    flags = make_flags(
        tmp_path, xpid="ovl-catch", overlap_collect=True, env="Catch",
        model="mlp", num_actors="16", batch_size="8", unroll_length="20",
        total_steps="60000", learning_rate="2e-3", entropy_cost="0.01",
    )
    stats = monobeast.train(flags)
    assert stats["mean_episode_return"] > 0.8


@pytest.mark.slow
def test_train_sp_x_ep_composite_flags(tmp_path):
    """--sequence_parallel + --expert_parallel through the real flag
    path: one composite (data=1, model=1, seq, expert) mesh shared by
    the attention shard_maps and the MoE constraints (a regression here
    is an XLA 'incompatible devices' compile error)."""
    flags = make_flags(
        tmp_path, xpid="spep", model="transformer",
        sequence_parallel="2", num_experts="4", expert_parallel="2",
        unroll_length="7", total_steps="28",
    )
    stats = monobeast.train(flags)
    assert stats["step"] >= 28
    assert np.isfinite(stats["total_loss"])
    assert stats["aux_loss"] > 0.0


@pytest.mark.slow
def test_train_mono_data_parallel(tmp_path):
    """--num_learner_devices: sync trainer DP over 4 virtual devices,
    incl. checkpoint/resume and composition with --overlap_collect."""
    flags = make_flags(
        tmp_path, xpid="mono-dp", num_learner_devices="4", batch_size="4",
        num_actors="4",
    )
    stats = monobeast.train(flags)
    assert stats["step"] >= 40
    assert np.isfinite(stats["total_loss"])
    flags2 = make_flags(
        tmp_path, xpid="mono-dp", num_learner_devices="4", batch_size="4",
        num_actors="4", total_steps=80, overlap_collect=True,
    )
    stats2 = monobeast.train(flags2)
    assert stats2["step"] >= 80
    # Pin the RESUME (not a silent restart): the appended log's step
    # column must increase monotonically across both runs — a restart
    # would drop back below run 1's final step.
    import csv

    with open(tmp_path / "mono-dp" / "logs.csv") as f:
        steps = [int(r["step"]) for r in csv.DictReader(f)]
    assert steps == sorted(steps) and steps[-1] >= 80, steps


def test_mono_dp_rejects_bad_combos(tmp_path):
    flags = make_flags(
        tmp_path, xpid="mono-dp-bad", num_learner_devices="3",
    )
    with pytest.raises(ValueError, match="not divisible"):
        monobeast.train(flags)
    flags = make_flags(
        tmp_path, xpid="mono-dp-bad2", num_learner_devices="2",
        model="transformer", sequence_parallel="2", unroll_length="7",
    )
    with pytest.raises(ValueError, match="composite meshes"):
        monobeast.train(flags)


def test_superstep_train_bit_identical_to_sequential(tmp_path):
    """--superstep_k 2 must train BIT-identically to --superstep_k 1 on
    the same seeds: the K-scan applies the same updates in the same
    order (schedules tick per-update), acting only sees params between
    collects, and the Mock env + fixed seeds make the whole run
    deterministic. Compared via the serialized checkpoint params/opt
    bytes — any numeric drift anywhere in the superstep path fails.

    MLP+LSTM model: the conv families are NOT bit-stable under a scan
    (XLA fuses the conv differently inside the scan body, ~1e-8 ulp
    drift — same training distribution, different bits), which is why
    the bit-identity contract is pinned on the MLP families."""
    import flax.serialization

    def run(xpid, k):
        flags = make_flags(
            tmp_path, xpid=xpid, superstep_k=str(k),
            num_actors="4", batch_size="2", total_steps="80",
            model="mlp", use_lstm=True,
        )
        stats = monobeast.train(flags)
        with open(tmp_path / xpid / "model.ckpt", "rb") as f:
            payload = flax.serialization.msgpack_restore(f.read())
        return stats, payload

    stats1, ck1 = run("ss-k1", 1)
    stats2, ck2 = run("ss-k2", 2)
    assert ck1["step"] == ck2["step"]
    assert ck1["params"] == ck2["params"]
    assert ck1["opt_state"] == ck2["opt_state"]
    assert stats1["total_loss"] == stats2["total_loss"]


def test_superstep_step_accounting(tmp_path):
    """A K=2 dispatch consumes K*T*batch_size frames: the reported step
    counter must land on a whole number of supersteps, not undercount
    by /K."""
    flags = make_flags(
        tmp_path, xpid="ss-acct", superstep_k="2",
        num_actors="4", batch_size="2", total_steps="40",
    )
    stats = monobeast.train(flags)
    assert stats["step"] >= 40
    assert stats["step"] % (2 * 5 * 2) == 0  # K * T * batch_size


def test_superstep_divisibility_rejected(tmp_path):
    """K must divide the sub-batches per collect (a fixed-K scan cannot
    take a partial group, and spilling across collects would change
    policy lag)."""
    flags = make_flags(
        tmp_path, xpid="ss-bad", superstep_k="3",
        num_actors="4", batch_size="2",
    )
    with pytest.raises(ValueError, match="superstep_k"):
        monobeast.train(flags)
