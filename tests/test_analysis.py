"""beastlint (torchbeast_tpu/analysis): per-rule fixtures, suppression +
baseline mechanics, the cross-language/cross-driver parity rules run in
anger against the real repo, and the tier-1 CI gate itself.

The gate test at the bottom IS the contract from ISSUE 5: `python -m
torchbeast_tpu.analysis --ci` exits 0 on the repo with an EMPTY committed
baseline — new findings are fixed or suppressed inline with a reason,
never grandfathered.
"""

import json
import os
import re
import shutil
import subprocess
import sys
import time

import pytest

from torchbeast_tpu import analysis
from torchbeast_tpu.analysis import config as lint_config
from torchbeast_tpu.analysis.engine import FileContext
from torchbeast_tpu.analysis.parity import (
    FlagParityRule,
    WireParityRule,
    check_flag_parity,
    check_ring_parity,
    check_route_parity,
    check_wire_parity,
)
from torchbeast_tpu.analysis.selftest import run_selftest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(report, name):
    return [f for f in report.findings if f.rule == name]


# ---------------------------------------------------------------------------
# HOTPATH-SYNC


class TestHotpathSync:
    def test_item_flagged_in_hot_function(self):
        src = (
            "import jax.numpy as jnp\n"
            "# beastlint: hot\n"
            "def act(env):\n"
            "    logits = jnp.tanh(env)\n"
            "    return logits.item()\n"
        )
        found = _rules(analysis.analyze_source(src), "HOTPATH-SYNC")
        assert len(found) == 1 and found[0].line == 5

    def test_cold_function_not_flagged(self):
        src = (
            "import jax.numpy as jnp\n"
            "def helper(env):\n"
            "    return jnp.tanh(env).item()\n"
        )
        assert not _rules(analysis.analyze_source(src), "HOTPATH-SYNC")

    def test_hot_module_marks_every_function(self):
        src = (
            "# beastlint: hot-module\n"
            "import jax.numpy as jnp\n"
            "def act(env):\n"
            "    x = jnp.tanh(env)\n"
            "    return float(x)\n"
        )
        assert _rules(analysis.analyze_source(src), "HOTPATH-SYNC")

    def test_taint_propagates_through_derived_names(self):
        src = (
            "import jax.numpy as jnp\n"
            "import numpy as np\n"
            "# beastlint: hot\n"
            "def act(env):\n"
            "    x = jnp.tanh(env)\n"
            "    y = x * 2\n"
            "    return np.asarray(y)\n"
        )
        found = _rules(analysis.analyze_source(src), "HOTPATH-SYNC")
        assert len(found) == 1 and found[0].line == 7

    def test_host_conversions_clean(self):
        """int()/np.asarray on untainted host values never flag — a
        pure-host module (wire.py) can be hot-annotated for free."""
        src = (
            "# beastlint: hot-module\n"
            "import numpy as np\n"
            "def encode(value, batch_dim):\n"
            "    rows = int(np.asarray(value).shape[batch_dim])\n"
            "    return rows\n"
        )
        assert not analysis.analyze_source(src).findings

    def test_jax_tree_util_is_host_side(self):
        """jax.tree_util does pytree plumbing on host: bool() over its
        result is not a device sync (regression: state_table._leaves)."""
        src = (
            "# beastlint: hot-module\n"
            "import jax\n"
            "def has_leaves(tree):\n"
            "    return bool(jax.tree_util.tree_leaves(tree))\n"
        )
        assert not analysis.analyze_source(src).findings

    def test_device_get_result_is_host(self):
        """The fix the rule recommends must itself pass: a value fetched
        via explicit jax.device_get is host-resident, so converting it
        does not re-flag."""
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "# beastlint: hot\n"
            "def act(env):\n"
            "    logits = jnp.tanh(env)\n"
            "    host = jax.device_get(logits)\n"
            "    return float(host)\n"
        )
        assert not analysis.analyze_source(src).findings

    def test_print_flagged_in_hot_path(self):
        src = (
            "# beastlint: hot\n"
            "def act(env):\n"
            "    print(env)\n"
            "    return env\n"
        )
        found = _rules(analysis.analyze_source(src), "HOTPATH-SYNC")
        assert len(found) == 1 and "print" in found[0].message


# ---------------------------------------------------------------------------
# JIT-HAZARD


class TestJitHazard:
    def test_jit_in_loop_flagged(self):
        src = (
            "import jax\n"
            "def train(fs, x):\n"
            "    for f in fs:\n"
            "        x = jax.jit(f)(x)\n"
            "    return x\n"
        )
        found = _rules(analysis.analyze_source(src), "JIT-HAZARD")
        # Both hazards: construction in a loop AND immediately-invoked.
        assert len(found) == 2 and all(f.line == 4 for f in found)

    def test_hoisted_jit_clean(self):
        src = (
            "import jax\n"
            "def train(f, xs):\n"
            "    step = jax.jit(f)\n"
            "    for x in xs:\n"
            "        x = step(x)\n"
            "    return x\n"
        )
        assert not _rules(analysis.analyze_source(src), "JIT-HAZARD")

    def test_scan_in_loop_flagged(self):
        src = (
            "from jax import lax\n"
            "def roll(body, carries, xs):\n"
            "    outs = []\n"
            "    while carries:\n"
            "        outs.append(lax.scan(body, carries.pop(), xs))\n"
            "    return outs\n"
        )
        found = _rules(analysis.analyze_source(src), "JIT-HAZARD")
        assert len(found) == 1 and "scan" in found[0].message

    def test_unhashable_static_default(self):
        src = (
            "import jax\n"
            "def f(x, cfg=[1, 2]):\n"
            "    return x\n"
            "g = jax.jit(f, static_argnums=(1,))\n"
        )
        found = _rules(analysis.analyze_source(src), "JIT-HAZARD")
        assert len(found) == 1 and "unhashable" in found[0].message

    def test_hashable_static_default_clean(self):
        src = (
            "import jax\n"
            "def f(x, cfg=(1, 2)):\n"
            "    return x\n"
            "g = jax.jit(f, static_argnums=(1,))\n"
        )
        assert not _rules(analysis.analyze_source(src), "JIT-HAZARD")


# ---------------------------------------------------------------------------
# DONATE-USE


class TestDonateUse:
    def test_read_after_wrapped_call_flagged(self):
        src = (
            "def drive(update, p, o, batch, state):\n"
            "    step = consume_staged_inputs(update)\n"
            "    out = step(p, o, batch, state)\n"
            "    return out, batch.mean()\n"
        )
        found = _rules(analysis.analyze_source(src), "DONATE-USE")
        assert len(found) == 1 and found[0].line == 4

    def test_read_in_either_branch_flagged(self):
        src = (
            "def drive(x, cond):\n"
            "    x.delete()\n"
            "    if cond:\n"
            "        return 0\n"
            "    return x.shape\n"
        )
        found = _rules(analysis.analyze_source(src), "DONATE-USE")
        assert len(found) == 1 and found[0].line == 5

    def test_rebinding_clears_consumption(self):
        src = (
            "def drive(update, p, o, batch, state, queue):\n"
            "    step = consume_staged_inputs(update)\n"
            "    out = step(p, o, batch, state)\n"
            "    batch = queue.get()\n"
            "    return out, batch.mean()\n"
        )
        assert not _rules(analysis.analyze_source(src), "DONATE-USE")

    def test_loop_back_edge_read_flagged(self):
        src = (
            "def drive(items):\n"
            "    staged = None\n"
            "    for item in items:\n"
            "        use(staged)\n"
            "        staged = stage(item)\n"
            "        staged.delete()\n"
        )
        found = _rules(analysis.analyze_source(src), "DONATE-USE")
        assert len(found) == 1 and found[0].line == 4

    def test_for_target_rebinds_each_iteration(self):
        """Regression: `for leaf in ...: leaf.delete()` is the
        consume-once idiom itself (learner.consume_staged_inputs), not
        a use-after-free — the loop target rebinds per iteration."""
        src = (
            "def consume(leaves):\n"
            "    for leaf in leaves:\n"
            "        if not leaf.is_deleted():\n"
            "            leaf.delete()\n"
        )
        assert not _rules(analysis.analyze_source(src), "DONATE-USE")

    def test_factory_with_donate_batch_true_consumes(self):
        src = (
            "def drive(model, opt, hp, p, o, batch, state):\n"
            "    step = make_update_superstep(\n"
            "        model, opt, hp, 4, donate_batch=True\n"
            "    )\n"
            "    out = step(p, o, batch, state)\n"
            "    return out, state.shape\n"
        )
        found = _rules(analysis.analyze_source(src), "DONATE-USE")
        assert len(found) == 1 and "state" in found[0].message


# ---------------------------------------------------------------------------
# IMPORT-PURITY


class TestExceptSwallow:
    _PATH = "torchbeast_tpu/runtime/fixture.py"

    def test_silent_pass_flagged(self):
        report = analysis.analyze_source(
            "try:\n    f()\nexcept Exception:\n    pass\n",
            path=self._PATH,
        )
        assert _rules(report, "EXCEPT-SWALLOW")

    def test_bare_except_return_flagged(self):
        report = analysis.analyze_source(
            "def g():\n    try:\n        f()\n"
            "    except:\n        return None\n",
            path=self._PATH,
        )
        assert _rules(report, "EXCEPT-SWALLOW")

    def test_baseexception_in_tuple_flagged(self):
        report = analysis.analyze_source(
            "try:\n    f()\nexcept (ValueError, BaseException):\n"
            "    x = 1\n",
            path=self._PATH,
        )
        assert _rules(report, "EXCEPT-SWALLOW")

    def test_logging_clean(self):
        report = analysis.analyze_source(
            "try:\n    f()\nexcept Exception:\n"
            "    log.exception('boom')\n",
            path=self._PATH,
        )
        assert not _rules(report, "EXCEPT-SWALLOW")

    def test_reraise_clean(self):
        report = analysis.analyze_source(
            "try:\n    f()\nexcept BaseException:\n"
            "    cleanup()\n    raise\n",
            path=self._PATH,
        )
        assert not _rules(report, "EXCEPT-SWALLOW")

    def test_counter_clean(self):
        report = analysis.analyze_source(
            "try:\n    f()\nexcept Exception:\n    errors.inc()\n",
            path=self._PATH,
        )
        assert not _rules(report, "EXCEPT-SWALLOW")

    def test_promise_fail_clean(self):
        report = analysis.analyze_source(
            "try:\n    f()\nexcept Exception as e:\n"
            "    batch.fail(e)\n",
            path=self._PATH,
        )
        assert not _rules(report, "EXCEPT-SWALLOW")

    def test_narrow_handler_out_of_contract(self):
        report = analysis.analyze_source(
            "try:\n    f()\nexcept OSError:\n    pass\n",
            path=self._PATH,
        )
        assert not _rules(report, "EXCEPT-SWALLOW")

    def test_outside_scoped_paths_unconstrained(self):
        report = analysis.analyze_source(
            "try:\n    f()\nexcept Exception:\n    pass\n",
            path="benchmarks/fixture.py",
        )
        assert not _rules(report, "EXCEPT-SWALLOW")

    def test_log_in_nested_def_does_not_credit_handler(self):
        """A log call inside a nested def doesn't run as part of the
        handler — defining a logging callback is still a swallow at
        handler time."""
        report = analysis.analyze_source(
            "try:\n    f()\nexcept Exception:\n"
            "    def cb():\n        log.exception('later')\n"
            "    register(cb)\n",
            path=self._PATH,
        )
        assert _rules(report, "EXCEPT-SWALLOW")

    def test_resilience_path_in_scope(self):
        report = analysis.analyze_source(
            "try:\n    f()\nexcept Exception:\n    pass\n",
            path="torchbeast_tpu/resilience/fixture.py",
        )
        assert _rules(report, "EXCEPT-SWALLOW")

    def test_real_runtime_and_resilience_clean(self):
        """The burn-down contract: the real failure-handling layers
        carry no silent broad swallows (and the baseline stays empty)."""
        report = analysis.analyze_paths(
            list(lint_config.EXCEPT_SWALLOW_PATHS), root=REPO
        )
        assert not _rules(report, "EXCEPT-SWALLOW"), [
            f.render() for f in report.findings
        ]


class TestImportPurity:
    def test_numpy_in_telemetry_flagged(self):
        report = analysis.analyze_source(
            "import numpy as np\n",
            path="torchbeast_tpu/telemetry/fixture.py",
        )
        assert _rules(report, "IMPORT-PURITY")

    def test_function_local_import_flagged(self):
        report = analysis.analyze_source(
            "def f():\n    import jax\n    return jax\n",
            path="torchbeast_tpu/telemetry/fixture.py",
        )
        assert _rules(report, "IMPORT-PURITY")

    def test_stdlib_clean(self):
        report = analysis.analyze_source(
            "import json\nimport threading\n",
            path="torchbeast_tpu/telemetry/fixture.py",
        )
        assert not report.findings

    def test_outside_contract_dirs_unconstrained(self):
        report = analysis.analyze_source(
            "import numpy as np\n", path="torchbeast_tpu/learner.py"
        )
        assert not _rules(report, "IMPORT-PURITY")

    def test_real_telemetry_package_is_pure(self):
        """The single source of truth for the PR 2 stdlib-only pin:
        the analyzer's IMPORT-PURITY rule over the real package (the
        hand-rolled regex test in test_telemetry.py is replaced by
        this)."""
        report = analysis.analyze_paths(
            ["torchbeast_tpu/telemetry", "torchbeast_tpu/analysis"],
            root=REPO,
        )
        assert not _rules(report, "IMPORT-PURITY"), [
            f.render() for f in report.findings
        ]


# ---------------------------------------------------------------------------
# LOCK-DISCIPLINE


class TestLockDiscipline:
    GUARDED = (
        "import threading\n"
        "class Q:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._not_empty = threading.Condition(self._lock)\n"
        "        self._items = []  # guarded-by: self._lock\n"
    )

    def test_unlocked_access_flagged(self):
        src = self.GUARDED + (
            "    def size(self):\n"
            "        return len(self._items)\n"
        )
        found = _rules(analysis.analyze_source(src), "LOCK-DISCIPLINE")
        assert len(found) == 1 and found[0].line == 8

    def test_with_lock_clean(self):
        src = self.GUARDED + (
            "    def size(self):\n"
            "        with self._lock:\n"
            "            return len(self._items)\n"
        )
        assert not analysis.analyze_source(src).findings

    def test_condition_acquires_underlying_lock(self):
        src = self.GUARDED + (
            "    def pop(self):\n"
            "        with self._not_empty:\n"
            "            return self._items.pop()\n"
        )
        assert not analysis.analyze_source(src).findings

    def test_holds_annotation_exempts_helper(self):
        src = self.GUARDED + (
            "    # beastlint: holds self._lock\n"
            "    def _drain_locked(self):\n"
            "        self._items.clear()\n"
        )
        assert not analysis.analyze_source(src).findings

    def test_access_inside_except_handler_with_lock(self):
        """Regression: a `with self._lock` nested in try/except must
        still count as holding the lock (actor_pool reconnect path)."""
        src = self.GUARDED + (
            "    def run(self):\n"
            "        while True:\n"
            "            try:\n"
            "                return 1\n"
            "            except OSError:\n"
            "                with self._lock:\n"
            "                    self._items.append(1)\n"
        )
        assert not analysis.analyze_source(src).findings

    def test_annassign_guarded_attr_enforced(self):
        """Regression: `self._x: Dict[...] = {}  # guarded-by: ...`
        (an AnnAssign, the MetricsRegistry._instruments form) must
        register the guard, not silently drop it."""
        src = (
            "import threading\n"
            "from typing import Dict\n"
            "class R:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._table: Dict[str, int] = {}"
            "  # guarded-by: self._lock\n"
            "    def get(self, k):\n"
            "        return self._table.get(k)\n"
        )
        found = _rules(analysis.analyze_source(src), "LOCK-DISCIPLINE")
        assert len(found) == 1 and "_table" in found[0].message

    def test_bare_acquire_flagged(self):
        src = (
            "def f(lock, work):\n"
            "    lock.acquire()\n"
            "    work()\n"
            "    lock.release()\n"
        )
        found = _rules(analysis.analyze_source(src), "LOCK-DISCIPLINE")
        assert len(found) == 1 and found[0].line == 2

    def test_acquire_with_try_finally_clean(self):
        src = (
            "def f(lock, work):\n"
            "    lock.acquire()\n"
            "    try:\n"
            "        work()\n"
            "    finally:\n"
            "        lock.release()\n"
        )
        assert not analysis.analyze_source(src).findings


# ---------------------------------------------------------------------------
# Parity rules, fixtures + in anger


class TestWireParity:
    WIRE_PY = (
        "import numpy as np\n"
        "TAG_ARRAY = 0x01\n"
        "DEFAULT_MAX_FRAME_BYTES = 16 * 1024\n"
        "_DTYPE_CODES = {np.dtype(np.uint8): 0}\n"
    )
    WIRE_H = (
        "constexpr uint8_t kTagArray = 0x01;\n"
        "constexpr size_t kMaxFrameBytes = 16ull * 1024;\n"
    )
    ARRAY_H = (
        "enum class DType : uint8_t {\n  kU8 = 0,\n};\n"
        "inline size_t itemsize(DType dtype) {\n"
        "  switch (dtype) {\n    case DType::kU8:\n      return 1;\n"
        "  }\n  throw 1;\n}\n"
    )
    CLIENT_H = "if (length > wire::kMaxFrameBytes) throw;\n"

    def _ctx(self, src):
        return FileContext("torchbeast_tpu/runtime/wire.py", src)

    def test_matched_tables_clean(self):
        assert not check_wire_parity(
            self._ctx(self.WIRE_PY), self.WIRE_H, self.ARRAY_H,
            self.CLIENT_H, None,
        )

    def test_dtype_code_drift_flagged(self):
        drifted = self.ARRAY_H.replace("kU8 = 0", "kU8 = 3")
        found = check_wire_parity(
            self._ctx(self.WIRE_PY), self.WIRE_H, drifted,
            self.CLIENT_H, None,
        )
        assert any("uint8" in f.message for f in found)

    def test_max_frame_drift_flagged(self):
        drifted = self.WIRE_H.replace("16ull", "8ull")
        found = check_wire_parity(
            self._ctx(self.WIRE_PY), drifted, self.ARRAY_H,
            self.CLIENT_H, None,
        )
        assert any("kMaxFrameBytes" in f.message for f in found)

    def test_itemsize_drift_flagged(self):
        drifted = self.ARRAY_H.replace("return 1;", "return 2;")
        found = check_wire_parity(
            self._ctx(self.WIRE_PY), self.WIRE_H, drifted,
            self.CLIENT_H, None,
        )
        assert any("itemsize" in f.message for f in found)

    def test_unenforced_frame_bound_flagged(self):
        found = check_wire_parity(
            self._ctx(self.WIRE_PY), self.WIRE_H, self.ARRAY_H,
            "// no bound check here\n", None,
        )
        assert any("client.h" in f.message for f in found)

    def test_multiword_tag_names_normalized(self):
        """TAG_NP_SCALAR (py) and kTagNpScalar (C++) are the same tag:
        underscore/case differences must not read as drift."""
        py = self._ctx(
            self.WIRE_PY + "TAG_NP_SCALAR = 0x09\n"
        )
        wire_h = self.WIRE_H + (
            "constexpr uint8_t kTagNpScalar = 0x09;\n"
        )
        assert not check_wire_parity(
            py, wire_h, self.ARRAY_H, self.CLIENT_H, None
        )

    def test_real_repo_in_anger(self):
        """The satellite: the dtype table (incl. bf16 code 12),
        --max_frame_bytes default, and frame tags agree between
        runtime/wire.py and csrc/ RIGHT NOW."""
        report = analysis.analyze_paths(
            [lint_config.WIRE_PY, lint_config.POLYBEAST_PY], root=REPO
        )
        found = _rules(report, "WIRE-PARITY")
        assert not found, [f.render() for f in found]
        # And the parse actually saw the full table (13 dtypes incl.
        # bfloat16=12; 9 tags incl. SNAPSHOT=9), not an empty dict
        # vacuously matching.
        from torchbeast_tpu.analysis.parity import parse_py_wire

        ctx = analysis.load_context(
            os.path.join(REPO, lint_config.WIRE_PY), REPO
        )
        tags, max_frame, codes = parse_py_wire(ctx.tree)
        assert codes.get("bfloat16") == 12 and len(codes) == 13
        assert max_frame == 256 * 1024 * 1024
        assert tags["ARRAY"] == 1 and tags["SNAPSHOT"] == 9
        assert len(tags) == 9


class TestRingParity:
    """WIRE-PARITY's shm ring-layout arm (ISSUE 9 satellite): the drift
    check PR 5 flagged as missing — header word layout, wrap/inline
    markers, doorbell bytes, and the capacity//2-4 eligibility cap
    pinned py<->C++, with unparseable sides surfacing as findings."""

    TRANSPORT_PY = (
        '_DOORBELL_WAKE = b"\\x01"\n'
        '_DOORBELL_INLINE = b"\\x02"\n'
        "class ShmRing:\n"
        "    HEADER_BYTES = 64\n"
        "    _WRAP = 0xFFFFFFFF\n"
        "    _INLINE = 0xFFFFFFFE\n"
        "    _HEAD, _TAIL, _CAP, _WAITING = 0, 1, 2, 3\n"
        "    def max_frame_bytes(self):\n"
        "        return self._capacity // 2 - 4\n"
    )
    SHM_H = (
        "constexpr size_t kRingHeaderBytes = 64;\n"
        "constexpr size_t kRingHeadWord = 0;\n"
        "constexpr size_t kRingTailWord = 1;\n"
        "constexpr size_t kRingCapacityWord = 2;\n"
        "constexpr size_t kRingWaitingWord = 3;\n"
        "constexpr uint32_t kRingWrapMarker = 0xFFFFFFFF;\n"
        "constexpr uint32_t kRingInlineMarker = 0xFFFFFFFE;\n"
        "constexpr uint8_t kDoorbellWake = 0x01;\n"
        "constexpr uint8_t kDoorbellInline = 0x02;\n"
        "size_t max_frame_bytes() const { return capacity_ / 2 - 4; }\n"
    )

    def _ctx(self, src):
        return FileContext("torchbeast_tpu/runtime/transport.py", src)

    def test_matched_layout_clean(self):
        assert not check_ring_parity(self._ctx(self.TRANSPORT_PY),
                                     self.SHM_H)

    def test_cpp_marker_drift_flagged(self):
        drifted = self.SHM_H.replace(
            "kRingInlineMarker = 0xFFFFFFFE", "kRingInlineMarker = 0xFFFFFFFD"
        )
        found = check_ring_parity(self._ctx(self.TRANSPORT_PY), drifted)
        assert any("inline marker" in f.message for f in found)
        assert all(f.rule == "WIRE-PARITY" for f in found)

    def test_py_header_drift_flagged(self):
        drifted = self.TRANSPORT_PY.replace(
            "HEADER_BYTES = 64", "HEADER_BYTES = 32"
        )
        found = check_ring_parity(self._ctx(drifted), self.SHM_H)
        assert any("header size" in f.message for f in found)

    def test_word_index_drift_flagged(self):
        drifted = self.TRANSPORT_PY.replace(
            "_HEAD, _TAIL, _CAP, _WAITING = 0, 1, 2, 3",
            "_HEAD, _TAIL, _CAP, _WAITING = 0, 2, 1, 3",
        )
        found = check_ring_parity(self._ctx(drifted), self.SHM_H)
        assert any("tail counter" in f.message for f in found)
        assert any("capacity word" in f.message for f in found)

    def test_eligibility_cap_drift_flagged(self):
        drifted = self.SHM_H.replace(
            "capacity_ / 2 - 4", "capacity_ / 4 - 8"
        )
        found = check_ring_parity(self._ctx(self.TRANSPORT_PY), drifted)
        assert any("eligibility" in f.message for f in found)

    def test_doorbell_byte_drift_flagged(self):
        drifted = self.TRANSPORT_PY.replace(
            '_DOORBELL_WAKE = b"\\x01"', '_DOORBELL_WAKE = b"\\x03"'
        )
        found = check_ring_parity(self._ctx(drifted), self.SHM_H)
        assert any("WAKE byte" in f.message for f in found)

    def test_unparseable_side_is_a_finding_not_silence(self):
        found = check_ring_parity(
            self._ctx("x = 1\n"), self.SHM_H
        )
        assert found and any("cannot verify" in f.message for f in found)
        found = check_ring_parity(
            self._ctx(self.TRANSPORT_PY), "// nothing here\n"
        )
        assert found and any("cannot verify" in f.message for f in found)

    def test_partially_unparseable_field_is_flagged(self):
        drifted = self.SHM_H.replace(
            "constexpr uint8_t kDoorbellWake = 0x01;\n", ""
        )
        found = check_ring_parity(self._ctx(self.TRANSPORT_PY), drifted)
        assert any(
            "WAKE byte" in f.message and "C++ side" in f.message
            for f in found
        )

    def test_real_repo_in_anger(self):
        """transport.py and csrc/shm.h agree RIGHT NOW, and the parse
        saw every field (no vacuous None==None matches)."""
        report = analysis.analyze_paths(
            [lint_config.TRANSPORT_PY], root=REPO
        )
        found = _rules(report, "WIRE-PARITY")
        assert not found, [f.render() for f in found]
        from torchbeast_tpu.analysis.parity import (
            parse_cpp_ring,
            parse_py_ring,
        )

        ctx = analysis.load_context(
            os.path.join(REPO, lint_config.TRANSPORT_PY), REPO
        )
        ring_py = parse_py_ring(ctx.tree)
        with open(os.path.join(REPO, lint_config.SHM_H)) as f:
            ring_cpp = parse_cpp_ring(f.read())
        assert None not in ring_py.values(), ring_py
        assert None not in ring_cpp.values(), ring_cpp
        assert ring_py == ring_cpp
        assert ring_py["header_bytes"] == 64
        assert ring_py["eligibility_divisor"] == 2
        assert ring_py["eligibility_slack"] == 4


class TestRouteParity:
    """ROUTE-PARITY (ISSUE 16): the splitmix64 slot->slice hash and the
    per-slice telemetry namespace pinned Python<->C++ against the
    ground-truth spec, drift injected in BOTH directions."""

    PLACEMENT_PY = (
        "def _mix64(x):\n"
        "    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF\n"
        "    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9)"
        " & 0xFFFFFFFFFFFFFFFF\n"
        "    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB)"
        " & 0xFFFFFFFFFFFFFFFF\n"
        "    return x ^ (x >> 31)\n"
    )
    ROUTING_H = (
        "constexpr uint64_t kSplitMix64Gamma = 0x9E3779B97F4A7C15ULL;\n"
        "constexpr uint64_t kSplitMix64Mul1 = 0xBF58476D1CE4E5B9ULL;\n"
        "constexpr uint64_t kSplitMix64Mul2 = 0x94D049BB133111EBULL;\n"
        "constexpr int kSplitMix64Shift1 = 30;\n"
        "constexpr int kSplitMix64Shift2 = 27;\n"
        "constexpr int kSplitMix64Shift3 = 31;\n"
        'constexpr const char kSliceSeriesPrefix[] = "inference.slice.";\n'
    )
    SERIES_PY = (
        "def series(i):\n"
        '    return f"inference.slice.{i}.requests"\n'
    )

    def _ctx(self, src, path=lint_config.PLACEMENT_PY):
        return FileContext(path, src)

    def _series(self, src=None):
        return [self._ctx(src or self.SERIES_PY,
                          lint_config.SLICE_SERIES_FILES[0])]

    def test_matched_sides_clean(self):
        assert not check_route_parity(
            self._ctx(self.PLACEMENT_PY), self.ROUTING_H, self._series()
        )

    def test_cpp_constant_drift_flagged(self):
        drifted = self.ROUTING_H.replace(
            "kSplitMix64Mul1 = 0xBF58476D1CE4E5B9ULL",
            "kSplitMix64Mul1 = 0xBF58476D1CE4E5B8ULL",
        )
        found = check_route_parity(
            self._ctx(self.PLACEMENT_PY), drifted, self._series()
        )
        assert any(
            "first multiplier" in f.message and "routing.h" in f.path
            for f in found
        )
        assert all(f.rule == "ROUTE-PARITY" for f in found)

    def test_py_shift_drift_flagged(self):
        drifted = self.PLACEMENT_PY.replace("x >> 30", "x >> 29")
        found = check_route_parity(
            self._ctx(drifted), self.ROUTING_H, self._series()
        )
        assert any(
            "first xor-shift" in f.message
            and f.path == lint_config.PLACEMENT_PY
            for f in found
        )

    def test_py_gamma_drift_flagged(self):
        drifted = self.PLACEMENT_PY.replace(
            "x + 0x9E3779B97F4A7C15", "x + 0x9E3779B97F4A7C16"
        )
        found = check_route_parity(
            self._ctx(drifted), self.ROUTING_H, self._series()
        )
        assert any("gamma" in f.message for f in found)

    def test_lockstep_drift_still_flagged(self):
        """Both sides drifting TOGETHER is still a finding: the check
        is against the pinned spec, not mutual agreement (a lockstep
        rewrite silently remaps every deployed slot assignment)."""
        py = self.PLACEMENT_PY.replace("x >> 27", "x >> 26")
        cpp = self.ROUTING_H.replace("Shift2 = 27", "Shift2 = 26")
        found = check_route_parity(self._ctx(py), cpp, self._series())
        assert any(f.path == lint_config.PLACEMENT_PY for f in found)
        assert any(f.path == lint_config.ROUTING_H for f in found)

    def test_cpp_series_prefix_drift_flagged(self):
        drifted = self.ROUTING_H.replace(
            '"inference.slice."', '"inference.slices."'
        )
        found = check_route_parity(
            self._ctx(self.PLACEMENT_PY), drifted, self._series()
        )
        assert any("kSliceSeriesPrefix" in f.message for f in found)

    def test_py_series_rename_flagged(self):
        renamed = self.SERIES_PY.replace("inference.slice.", "infer.sl.")
        found = check_route_parity(
            self._ctx(self.PLACEMENT_PY), self.ROUTING_H,
            self._series(renamed),
        )
        assert any("pinned per-slice prefix" in f.message for f in found)

    def test_unparseable_side_is_a_finding_not_silence(self):
        found = check_route_parity(
            self._ctx("x = 1\n"), self.ROUTING_H, self._series()
        )
        assert found and any("cannot verify" in f.message for f in found)
        found = check_route_parity(
            self._ctx(self.PLACEMENT_PY), "// nothing\n", self._series()
        )
        assert found and any("cannot verify" in f.message for f in found)

    def test_real_repo_in_anger(self):
        """placement.py, csrc/routing.h, and both per-slice series
        emitters agree RIGHT NOW — and the parse saw every field (no
        vacuous None==None matches)."""
        report = analysis.analyze_paths(
            [lint_config.PLACEMENT_PY, *lint_config.SLICE_SERIES_FILES],
            root=REPO,
        )
        found = _rules(report, "ROUTE-PARITY")
        assert not found, [f.render() for f in found]
        from torchbeast_tpu.analysis.parity import (
            parse_cpp_routing,
            parse_py_splitmix,
        )

        ctx = analysis.load_context(
            os.path.join(REPO, lint_config.PLACEMENT_PY), REPO
        )
        mix_py = parse_py_splitmix(ctx.tree)
        with open(os.path.join(REPO, lint_config.ROUTING_H)) as f:
            mix_cpp, prefix = parse_cpp_routing(f.read())
        assert None not in mix_py.values(), mix_py
        assert mix_py == mix_cpp == lint_config.SPLITMIX64_SPEC
        assert prefix == lint_config.SLICE_SERIES_PREFIX

    def test_native_hash_matches_python_in_anger(self):
        """The executable ground truth behind the textual pin: the C++
        extension's splitmix64 IS placement._mix64 (when the native
        runtime is built)."""
        core = pytest.importorskip("_tbt_core")
        from torchbeast_tpu.runtime.placement import _mix64

        for slot in (0, 1, 7, 63, 255, 2**31, -1):
            assert core.splitmix64(slot) == _mix64(slot & (2**64 - 1))
        for n in (1, 2, 3, 8):
            for slot in range(64):
                assert core.slice_for_slot(
                    slot=slot, n_slices=n
                ) == _mix64(slot) % n


class TestFlagParity:
    def test_default_drift_flagged_at_second_file(self):
        a = FileContext(
            "a.py",
            'p.add_argument("--batch_size", type=int, default=8)\n',
        )
        b = FileContext(
            "b.py",
            'p.add_argument("--batch_size", type=int, default=16)\n',
        )
        found = check_flag_parity(a, b)
        assert len(found) == 1 and found[0].path == "b.py"

    def test_qualified_constant_spelling_normalized(self):
        a = FileContext(
            "a.py",
            'p.add_argument("--m", type=int, default=DEFAULT_MAX)\n',
        )
        b = FileContext(
            "b.py",
            'p.add_argument("--m", type=int, default=wire.DEFAULT_MAX)\n',
        )
        assert not check_flag_parity(a, b)

    def test_float_defaults_compared_exactly(self):
        a = FileContext(
            "a.py", 'p.add_argument("--lr", type=float, default=8.5)\n'
        )
        b = FileContext(
            "b.py", 'p.add_argument("--lr", type=float, default=7.5)\n'
        )
        assert len(check_flag_parity(a, b)) == 1

    def test_issue13_flags_present_and_drift_caught(self):
        """The three ISSUE 13 shared flags (--remat, --opt_impl,
        --hbm_budget_gb) exist in BOTH drivers, agree right now (the
        in-anger test below), and an injected default drift on each is
        CAUGHT by the rule — the parity net actually covers them."""
        with open(os.path.join(
            REPO, "torchbeast_tpu", "monobeast.py"
        )) as f:
            mono_src = f.read()
        with open(os.path.join(
            REPO, "torchbeast_tpu", "polybeast.py"
        )) as f:
            poly_src = f.read()
        drifts = {
            "--remat": (
                '"--remat", default=None',
                '"--remat", default="all"',
            ),
            "--opt_impl": (
                '"--opt_impl", default="xla"',
                '"--opt_impl", default="pallas"',
            ),
            "--hbm_budget_gb": (
                '"--hbm_budget_gb", type=float, default=0.0',
                '"--hbm_budget_gb", type=float, default=15.75',
            ),
        }
        mono = FileContext("torchbeast_tpu/monobeast.py", mono_src)
        for flag, (orig, drifted_frag) in drifts.items():
            assert orig in mono_src and orig in poly_src, flag
            drifted = FileContext(
                "torchbeast_tpu/polybeast.py",
                poly_src.replace(orig, drifted_frag),
            )
            found = check_flag_parity(mono, drifted)
            assert any(flag in f.message for f in found), (
                flag, [f.message for f in found],
            )

    def test_issue18_flags_present_and_drift_caught(self):
        """The three ISSUE 18 shared IMPACT flags (--impact_clip,
        --replay_reuse, --target_refresh_updates) exist in BOTH
        drivers, agree right now, and an injected default drift on each
        is CAUGHT — the parity net covers the lag-tolerant learner's
        knobs."""
        with open(os.path.join(
            REPO, "torchbeast_tpu", "monobeast.py"
        )) as f:
            mono_src = f.read()
        with open(os.path.join(
            REPO, "torchbeast_tpu", "polybeast.py"
        )) as f:
            poly_src = f.read()
        drifts = {
            "--impact_clip": (
                '"--impact_clip", type=float, default=0.2',
                '"--impact_clip", type=float, default=0.3',
            ),
            "--replay_reuse": (
                '"--replay_reuse", type=int, default=1',
                '"--replay_reuse", type=int, default=2',
            ),
            "--target_refresh_updates": (
                '"--target_refresh_updates", type=int, default=8',
                '"--target_refresh_updates", type=int, default=80',
            ),
        }
        mono = FileContext("torchbeast_tpu/monobeast.py", mono_src)
        for flag, (orig, drifted_frag) in drifts.items():
            assert orig in mono_src and orig in poly_src, flag
            drifted = FileContext(
                "torchbeast_tpu/polybeast.py",
                poly_src.replace(orig, drifted_frag),
            )
            found = check_flag_parity(mono, drifted)
            assert any(flag in f.message for f in found), (
                flag, [f.message for f in found],
            )

    def test_real_drivers_in_anger(self):
        """Shared monobeast/polybeast flags agree on type+default; the
        two known-intentional divergences (--model, --num_actors) are
        suppressed inline WITH reasons, so the engine output is clean."""
        report = analysis.analyze_paths(
            list(lint_config.FLAG_PARITY_FILES), root=REPO
        )
        found = _rules(report, "FLAG-PARITY")
        assert not found, [f.render() for f in found]
        suppressed = [
            (f, s) for f, s in report.suppressed
            if f.rule == "FLAG-PARITY"
        ]
        assert {f.message.split(" ")[1] for f, _ in suppressed} == {
            "--model", "--num_actors",
        }
        assert all(s.reason for _, s in suppressed)


# ---------------------------------------------------------------------------
# Suppression + baseline mechanics


class TestSuppressionMechanics:
    HOT_ITEM = (
        "import jax.numpy as jnp\n"
        "# beastlint: hot\n"
        "def act(env):\n"
        "    x = jnp.tanh(env)\n"
        "    return x.item(){}\n"
    )

    def test_trailing_suppression_with_reason(self):
        src = self.HOT_ITEM.format(
            "  # beastlint: disable=HOTPATH-SYNC  boundary fetch"
        )
        report = analysis.analyze_source(src)
        assert not report.findings
        assert len(report.suppressed) == 1
        assert report.suppressed[0][1].reason == "boundary fetch"

    def test_standalone_suppression_covers_next_line(self):
        src = (
            "import jax.numpy as jnp\n"
            "# beastlint: hot\n"
            "def act(env):\n"
            "    x = jnp.tanh(env)\n"
            "    # beastlint: disable=HOTPATH-SYNC  boundary fetch\n"
            "    return x.item()\n"
        )
        report = analysis.analyze_source(src)
        assert not report.findings and len(report.suppressed) == 1

    def test_reasonless_suppression_is_a_finding(self):
        src = self.HOT_ITEM.format("  # beastlint: disable=HOTPATH-SYNC")
        report = analysis.analyze_source(src)
        assert _rules(report, "SUPPRESS-REASON")

    def test_unknown_rule_in_suppression_is_a_finding(self):
        src = "x = 1  # beastlint: disable=NO-SUCH-RULE  whatever\n"
        report = analysis.analyze_source(src)
        found = _rules(report, "SUPPRESS-REASON")
        assert len(found) == 1 and "NO-SUCH-RULE" in found[0].message

    def test_wrong_rule_does_not_suppress(self):
        src = self.HOT_ITEM.format(
            "  # beastlint: disable=JIT-HAZARD  wrong rule"
        )
        report = analysis.analyze_source(src)
        assert _rules(report, "HOTPATH-SYNC")


class TestBaselineMechanics:
    def test_fingerprint_is_line_insensitive(self):
        src1 = (
            "# beastlint: hot\n"
            "def act(env):\n"
            "    return env.item()\n"
        )
        src2 = "\n\n" + src1  # pure code motion
        f1 = analysis.analyze_source(src1).findings[0]
        f2 = analysis.analyze_source(src2).findings[0]
        assert f1.line != f2.line
        assert f1.fingerprint == f2.fingerprint

    def test_write_then_load_roundtrip(self, tmp_path):
        src = (
            "# beastlint: hot\n"
            "def act(env):\n"
            "    return env.item()\n"
        )
        findings = analysis.analyze_source(src).findings
        path = str(tmp_path / "baseline.json")
        analysis.write_baseline(path, findings)
        loaded = analysis.load_baseline(path)
        assert loaded == {f.fingerprint for f in findings}

    def test_committed_baseline_is_empty(self):
        with open(os.path.join(REPO, ".beastlint-baseline.json")) as f:
            data = json.load(f)
        assert data == {"fingerprints": []}


# ---------------------------------------------------------------------------
# Selftest + the tier-1 CI gate


class TestSelftestAndGate:
    def test_selftest_in_process(self):
        verdict = run_selftest()
        assert verdict["ok"], verdict
        assert set(verdict["rules"]) == {
            "HOTPATH-SYNC", "JIT-HAZARD", "DONATE-USE", "IMPORT-PURITY",
            "LOCK-DISCIPLINE", "EXCEPT-SWALLOW", "WIRE-PARITY",
            "ROUTE-PARITY", "FLAG-PARITY", "RACE", "LOCK-ORDER",
            "HOTPATH-SYNC-XPROC", "GIL-DISCIPLINE", "ATOMIC-ORDER",
            "CXX-LOCK-DISCIPLINE", "FLEET-MSG-PARITY",
            "FLEET-TIMEOUT-DISCIPLINE", "TELEMETRY-SCHEMA",
        }
        for name, checks in verdict["rules"].items():
            assert checks["positive"] and checks["clean"], (name, checks)
            assert checks["isolated"], (name, checks)

    def test_list_rules_shows_all_eighteen(self):
        """The 11 -> 14 -> 15 -> 18 rule invariant (ISSUE 10;
        ROUTE-PARITY joined in ISSUE 16; the fleet tier in ISSUE 20):
        every registered rule appears in --list-rules, and every listed
        rule has a selftest fixture pair (the selftest set and the
        registry agree)."""
        proc = subprocess.run(
            [sys.executable, "-m", "torchbeast_tpu.analysis",
             "--list-rules"],
            capture_output=True, text=True, cwd=REPO, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        listed = {
            line.split()[0] for line in proc.stdout.splitlines() if line
        }
        assert len(listed) == 18, sorted(listed)
        verdict = run_selftest()
        assert listed == set(verdict["rules"]), (
            listed ^ set(verdict["rules"])
        )

    def test_ci_gate_clean_and_fast(self):
        """THE acceptance gate (ISSUE 5; re-pinned by ISSUE 7 with the
        whole-program graph layer and by ISSUE 10 with the C++ frontend
        active): `python -m torchbeast_tpu.analysis --ci` exits 0 on the
        repo (empty baseline, reasoned suppressions only, concurrency +
        C++ rules running) in under the 20s budget on this container."""
        t0 = time.monotonic()
        proc = subprocess.run(
            [sys.executable, "-m", "torchbeast_tpu.analysis",
             "--ci", "--json"],
            capture_output=True, text=True, cwd=REPO, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        wall = time.monotonic() - t0
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout.strip().splitlines()[-1])
        assert report["findings"] == [] and report["ci"] == "PASS"
        assert report["files_scanned"] > 100
        # Every surviving suppression carries a reason (the engine also
        # enforces this as SUPPRESS-REASON findings — belt and braces).
        assert all(s["reason"] for s in report["suppressed"])
        # ISSUE 10 acceptance: < 20s repo-wide WITH the graph layer AND
        # the C++ frontend (the RACE and CXX-LOCK-DISCIPLINE burn-down
        # suppressions prove both lanes ran).
        assert report["elapsed_s"] < 20, report["elapsed_s"]
        assert any(
            s["rule"] == "RACE" for s in report["suppressed"]
        ), "concurrency rules did not run in the gate"
        assert any(
            s["rule"] == "CXX-LOCK-DISCIPLINE" for s in report["suppressed"]
        ), "C++ rules did not run in the gate"
        assert wall < 90  # import + scan, generous for a loaded sandbox

    def test_pyproject_packages_complete(self):
        """Every torchbeast_tpu.* subpackage on disk is in pyproject's
        packages list (ISSUE 10 satellite: resilience/ shipped
        unimportable from a wheel for four PRs because the list is
        maintained by hand — this pin makes the next new package fail
        CI instead)."""
        with open(os.path.join(REPO, "pyproject.toml")) as f:
            toml = f.read()
        m = re.search(r"packages\s*=\s*\[(.*?)\]", toml, re.DOTALL)
        assert m, "packages list missing from pyproject.toml"
        declared = set(re.findall(r'"([\w.]+)"', m.group(1)))
        pkg_root = os.path.join(REPO, "torchbeast_tpu")
        on_disk = {"torchbeast_tpu"}
        for entry in sorted(os.listdir(pkg_root)):
            full = os.path.join(pkg_root, entry)
            if os.path.isdir(full) and os.path.isfile(
                os.path.join(full, "__init__.py")
            ):
                on_disk.add(f"torchbeast_tpu.{entry}")
        assert declared == on_disk, (
            f"pyproject packages drift: missing {on_disk - declared}, "
            f"stale {declared - on_disk}"
        )

    def test_cli_exits_nonzero_on_findings(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "# beastlint: hot\n"
            "def act(env):\n"
            "    return env.item()\n"
        )
        proc = subprocess.run(
            [sys.executable, "-m", "torchbeast_tpu.analysis",
             str(bad), "--json"],
            capture_output=True, text=True, cwd=REPO, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        report = json.loads(proc.stdout.strip().splitlines()[-1])
        assert report["findings"][0]["rule"] == "HOTPATH-SYNC"


# ---------------------------------------------------------------------------
# Sanitizer wiring (slow: compiles C++)


@pytest.mark.slow
class TestSanitizerWiring:
    @pytest.fixture(autouse=True)
    def _need_toolchain(self):
        if shutil.which("g++") is None:
            pytest.skip("no g++ toolchain")

    def _run_sanitized(self, sanitizer):
        proc = subprocess.run(
            ["bash", "scripts/build_native.sh",
             f"--sanitize={sanitizer}", "--filter=wire"],
            capture_output=True, text=True, cwd=REPO, timeout=300,
        )
        if proc.returncode != 0 and (
            "cannot find" in proc.stderr
            or "unrecognized" in proc.stderr
            or "Shadow memory" in proc.stderr
        ):
            pytest.skip(
                f"{sanitizer} sanitizer unavailable in this toolchain/"
                f"sandbox: {proc.stderr.strip().splitlines()[-1]}"
            )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "FILTERED NATIVE CORE TESTS PASSED" in proc.stdout

    def test_asan_wire_smoke(self):
        self._run_sanitized("address")

    def test_ubsan_wire_smoke(self):
        self._run_sanitized("undefined")

    def _run_sanitized_filter(self, sanitizer, filt):
        proc = subprocess.run(
            ["bash", "scripts/build_native.sh",
             f"--sanitize={sanitizer}", f"--filter={filt}"],
            capture_output=True, text=True, cwd=REPO, timeout=600,
        )
        if proc.returncode != 0 and (
            "cannot find" in proc.stderr
            or "unrecognized" in proc.stderr
            or "Shadow memory" in proc.stderr
            or "unsupported" in proc.stderr.lower()
        ):
            pytest.skip(
                f"{sanitizer} sanitizer unavailable in this toolchain/"
                f"sandbox: {proc.stderr.strip().splitlines()[-1]}"
            )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "FILTERED NATIVE CORE TESTS PASSED" in proc.stdout
        # TSan reports land on stderr; rc is already non-zero when any
        # race fires, but pin the absence explicitly so a future
        # `halt_on_error=0` env can't mask one.
        assert "ThreadSanitizer" not in proc.stderr, proc.stderr

    def test_tsan_queue_suites(self):
        """ISSUE 7 satellite: the C++ BatchingQueue suites (incl. the
        multi-producer stress test) run clean under ThreadSanitizer."""
        self._run_sanitized_filter("thread", "queue")

    def test_tsan_batcher_suites(self):
        """The batching/dynamic-batcher suites under TSan. Regression
        for the csrc/queues.h timed wait: a steady_clock wait_until
        lowers to pthread_cond_clockwait, which this toolchain's TSan
        does not intercept — the old code produced ~90 bogus
        double-lock/race reports on this exact suite."""
        self._run_sanitized_filter("thread", "atch")
