"""Data-parallel learner over the 8-virtual-device CPU mesh: the sharded
update must produce numerically identical results to the single-device
update (grads all-reduce to the same global sum)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from tests import jax_caps

from torchbeast_tpu import learner as learner_lib
from torchbeast_tpu.models import create_model
from torchbeast_tpu.parallel import (
    create_mesh,
    make_parallel_update_step,
    replicate,
    shard_batch,
)

T, B, A = 4, 8, 4  # B divisible by the 8-device data axis


def make_batch(rng_seed=0):
    rng = np.random.default_rng(rng_seed)
    return {
        "frame": rng.integers(0, 256, (T + 1, B, 48, 48, 1), dtype=np.uint8),
        "reward": rng.standard_normal((T + 1, B)).astype(np.float32),
        "done": rng.random((T + 1, B)) < 0.2,
        "episode_return": rng.standard_normal((T + 1, B)).astype(np.float32),
        "episode_step": rng.integers(0, 99, (T + 1, B)).astype(np.int32),
        "last_action": rng.integers(0, A, (T + 1, B)).astype(np.int32),
        "action": rng.integers(0, A, (T + 1, B)).astype(np.int32),
        "policy_logits": rng.standard_normal((T + 1, B, A)).astype(np.float32),
        "baseline": rng.standard_normal((T + 1, B)).astype(np.float32),
    }


@pytest.fixture(scope="module")
def setup():
    model = create_model("shallow", num_actions=A, use_lstm=True)
    batch = make_batch()
    state = model.initial_state(B)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "action": jax.random.PRNGKey(1)},
        batch,
        state,
    )
    hp = learner_lib.HParams(batch_size=B, unroll_length=T)
    optimizer = learner_lib.make_optimizer(hp)
    return model, params, state, hp, optimizer


def test_mesh_shapes():
    mesh = create_mesh(8)
    assert mesh.devices.shape == (8, 1)
    assert mesh.axis_names == ("data", "model")
    mesh = create_mesh(8, model_parallelism=2)
    assert mesh.devices.shape == (4, 2)
    with pytest.raises(ValueError):
        create_mesh(8, model_parallelism=3)
    with pytest.raises(ValueError, match="visible"):
        create_mesh(64)  # more than the 8 virtual devices


@pytest.mark.slow
def test_parallel_update_matches_single_device(setup):
    model, params, state, hp, optimizer = setup
    batch = make_batch()

    # Single-device reference result.
    single = learner_lib.make_update_step(model, optimizer, hp)
    p1, _, stats1 = single(
        jax.tree_util.tree_map(jnp.copy, params),
        optimizer.init(params),
        batch,
        state,
    )

    # 8-way data-parallel result.
    mesh = create_mesh(8)
    par = make_parallel_update_step(model, optimizer, hp, mesh)
    params_r = replicate(mesh, jax.tree_util.tree_map(jnp.copy, params))
    opt_r = replicate(mesh, optimizer.init(params))
    batch_s, state_s = shard_batch(mesh, batch, state)
    p8, _, stats8 = par(params_r, opt_r, batch_s, state_s)

    np.testing.assert_allclose(
        float(stats1["total_loss"]), float(stats8["total_loss"]),
        rtol=2e-4,
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p8)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5
        )


def test_dp_plus_tp_update_matches_single_device(setup):
    """(data=4, model=2) mesh: dense kernels sharded over the model axis,
    batch over data — numerics must match the single-device update.

    Compiled under tp.shardy_partitioner(): the legacy GSPMD
    partitioner on this container MIScompiles the dense-TP grad path
    (~40%-wrong loss — the failure that survived PRs 8-12; root cause
    and minimal repro in parallel/tp.py's module docstring and
    jax_caps._dense_tp_grad_repro). The skipif drops out the moment
    either partitioner handles the pattern."""
    from torchbeast_tpu.models import create_model
    from torchbeast_tpu.parallel import (
        dense_kernel_shardings,
        place_params,
        shardy_partitioner,
    )

    if not (
        jax_caps.shardy_spmd_dense_tp_grad_sound()
        or jax_caps.legacy_spmd_dense_tp_grad_sound()
    ):  # pragma: no cover - this container has a sound shardy
        pytest.skip(
            "neither SPMD partitioner compiles dense-TP grad programs "
            "correctly on this jax (see parallel/tp.py)"
        )

    model = create_model("mlp", num_actions=A)
    batch = make_batch()
    params = model.init(
        {"params": jax.random.PRNGKey(0), "action": jax.random.PRNGKey(1)},
        batch,
        (),
    )
    hp = learner_lib.HParams(batch_size=B, unroll_length=T)
    optimizer = learner_lib.make_optimizer(hp)

    single = learner_lib.make_update_step(model, optimizer, hp, donate=False)
    p1, _, stats1 = single(params, optimizer.init(params), batch, ())

    mesh = create_mesh(8, model_parallelism=2)
    shardings = dense_kernel_shardings(mesh, params)
    # At least one kernel must actually shard for this test to mean much.
    assert any(
        not s.is_fully_replicated
        for s in jax.tree_util.tree_leaves(shardings)
    )
    with shardy_partitioner():
        par = make_parallel_update_step(
            model, optimizer, hp, mesh, param_shardings=shardings
        )
        params_s = place_params(
            mesh, jax.tree_util.tree_map(jnp.copy, params), shardings
        )
        opt_s = optimizer.init(params_s)
        batch_s, _ = shard_batch(mesh, batch, ())
        p2, _, stats2 = par(params_s, opt_s, batch_s, ())

    np.testing.assert_allclose(
        float(stats1["total_loss"]), float(stats2["total_loss"]), rtol=2e-4
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5
        )


def test_parallel_update_keeps_params_replicated(setup):
    model, params, state, hp, optimizer = setup
    mesh = create_mesh(8)
    par = make_parallel_update_step(model, optimizer, hp, mesh)
    # device_put may alias the source buffer as one replica shard, so hand
    # the donating call copies to keep the shared fixture alive.
    params_r = replicate(mesh, jax.tree_util.tree_map(jnp.copy, params))
    opt_r = replicate(mesh, optimizer.init(params))
    batch_s, state_s = shard_batch(mesh, make_batch(), state)
    p8, o8, _ = par(params_r, opt_r, batch_s, state_s)
    leaf = jax.tree_util.tree_leaves(p8)[0]
    assert leaf.sharding.is_fully_replicated

    # And the batch really was sharded over the data axis.
    frame = batch_s["frame"]
    assert not frame.sharding.is_fully_replicated
    assert len(frame.sharding.device_set) == 8


@pytest.mark.slow
def test_transformer_megatron_tp_matches_single_device():
    """Megatron column/row-paired TP for the transformer on a
    (data=4 x model=2) mesh: the update must match single-device, and
    the pairing must shard exactly the projection/FFN leaves (11 per
    block + their optimizer moments)."""
    from torchbeast_tpu.parallel import transformer_tp_shardings

    mesh = create_mesh(8, model_parallelism=2)
    kwargs = dict(
        num_actions=A, num_layers=1, d_model=16, num_heads=2,
        memory_len=4,
    )
    model = create_model("transformer", **kwargs)
    batch = make_batch(rng_seed=3)
    state = model.initial_state(B)
    params = model.init(
        {"params": jax.random.PRNGKey(6), "action": jax.random.PRNGKey(7)},
        batch,
        state,
    )
    hp = learner_lib.HParams(batch_size=B, unroll_length=T)
    optimizer = learner_lib.make_optimizer(hp)

    step_single = learner_lib.make_update_step(
        model, optimizer, hp, donate=False
    )
    p_ref, _, stats_ref = step_single(
        params, optimizer.init(params), batch, state
    )

    shardings = transformer_tp_shardings(mesh, params)
    flat, _ = jax.tree_util.tree_flatten_with_path(shardings)
    sharded = sorted(
        jax.tree_util.keystr(path)
        for path, s in flat
        if not s.is_fully_replicated
    )
    expected = sorted(
        f"['params']['block_0']{suffix}"
        for suffix in (
            "['q']['kernel']", "['q']['bias']",
            "['k']['kernel']", "['k']['bias']",
            "['v']['kernel']", "['v']['bias']",
            "['out']['kernel']", "['rel_bias']",
            "['Dense_0']['kernel']", "['Dense_0']['bias']",
            "['Dense_1']['kernel']",
        )
    )
    assert sharded == expected, sharded

    step_tp = make_parallel_update_step(
        model, optimizer, hp, mesh, donate=False,
        param_shardings=shardings,
    )
    params_p = jax.tree_util.tree_map(jax.device_put, params, shardings)
    opt_p = optimizer.init(params_p)
    batch_p, state_p = shard_batch(mesh, batch, state)
    p_tp, _, stats_tp = step_tp(params_p, opt_p, batch_p, state_p)

    np.testing.assert_allclose(
        float(stats_tp["total_loss"]), float(stats_ref["total_loss"]),
        rtol=1e-5,
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
        ),
        p_tp,
        p_ref,
    )
    # The new params must keep their TP placement (donation-stable).
    n_sharded_out = sum(
        not leaf.sharding.is_fully_replicated
        for leaf in jax.tree_util.tree_leaves(p_tp)
    )
    assert n_sharded_out == len(expected)


def test_transformer_tp_rejects_indivisible_heads():
    from torchbeast_tpu.parallel import transformer_tp_shardings

    mesh = create_mesh(8, model_parallelism=4)  # 4 does not divide H=2
    model = create_model(
        "transformer", num_actions=A, num_layers=1, d_model=16,
        num_heads=2, memory_len=4,
    )
    batch = make_batch(rng_seed=4)
    params = model.init(
        {"params": jax.random.PRNGKey(8), "action": jax.random.PRNGKey(9)},
        batch,
        model.initial_state(B),
    )
    with pytest.raises(ValueError, match="num_heads"):
        transformer_tp_shardings(mesh, params)


def test_parallel_update_applies_entropy_anneal(setup):
    """The mesh-sharded update must run the SAME loss-side schedule as
    the single-device one (they share learner.update_body): with an
    entropy anneal armed, the entropy_loss stat at the half-horizon
    count is half the count-0 value on identical params/batch."""
    import optax.tree_utils as otu

    model, params, state, hp, _ = setup
    hp = hp._replace(
        entropy_cost=1.0, entropy_cost_final=0.0,
        total_steps=10 * T * B,  # 10-update horizon
    )
    optimizer = learner_lib.make_optimizer(hp)
    mesh = create_mesh(8)
    step = make_parallel_update_step(
        model, optimizer, hp, mesh, donate=False
    )
    batch = make_batch()
    opt_state = optimizer.init(params)
    p = replicate(mesh, params)
    o = replicate(mesh, optimizer.init(params))
    b, s = shard_batch(mesh, batch, state)

    _, _, stats0 = step(p, o, b, s)
    o5 = replicate(
        mesh, otu.tree_set(opt_state, count=jnp.asarray(5, jnp.int32))
    )
    _, _, stats5 = step(p, o5, b, s)
    e0 = float(stats0["entropy_loss"])
    e5 = float(stats5["entropy_loss"])
    assert e0 != 0.0
    np.testing.assert_allclose(e5, 0.5 * e0, rtol=1e-5)
