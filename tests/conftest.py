"""Test environment: force the CPU backend with 8 virtual devices so every
sharding/mesh test runs cluster-free (SURVEY.md §4 implication; the driver's
multi-chip dryrun uses the same mechanism).

This container's sitecustomize registers a remote TPU ("axon") PJRT plugin
and forces jax_platforms="axon,cpu" at interpreter start; tests must not
depend on (or block on) the TPU tunnel, so we override the config back to
cpu here — conftest imports after sitecustomize, before any backend
initialization.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

from torchbeast_tpu.utils.xla_cache import host_keyed_cache_dir  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache: repeat suite runs skip XLA recompiles.
# Host-keyed — a shared dir would load AOT results compiled on another
# machine's ISA (SIGILL risk when the container image moves hosts).
jax.config.update("jax_compilation_cache_dir", host_keyed_cache_dir())
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
