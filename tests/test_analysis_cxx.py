"""beastlint v3 (ISSUE 10): the C++ frontend, the three cross-language
concurrency rules, the spec<->implementation conformance pins, and the
exhaustive shm-protocol model checker.

The conformance tests are the acceptance contract: mutate a header
access (order OR sequence) in a fixture copy of the REAL transport.py /
csrc/shm.h and the ATOMIC-ORDER rule must flag it; run the checker on a
seeded protocol mutation and it must produce a counterexample trace —
including the two historical bugs (the PR 3 fence-less oversized-path
lost-wakeup and the PR 9 metastable wait)."""

import ast
import json
import os
import subprocess
import sys

import pytest

from torchbeast_tpu import analysis
from torchbeast_tpu.analysis import config as lint_config
from torchbeast_tpu.analysis import cxx, cxxrules, protocol
from torchbeast_tpu.analysis import analyze_cxx_sources

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _read(rel):
    with open(os.path.join(REPO, rel)) as f:
        return f.read()


def _rules(report, name):
    return [f for f in report.findings if f.rule == name]


# ---------------------------------------------------------------------------
# Frontend: lexer + extractor


class TestCxxFrontend:
    SRC = """
// a file comment
class Queue {
 public:
  Queue() : total_(0) {}
  void add(int n) {
    std::lock_guard<std::mutex> lock(mu_);
    total_ += n;
    items_.push_back(n);
  }
  int drain() {
    std::unique_lock<std::mutex> l(mu_);
    int t = total_;
    l.unlock();
    total_read_ = t;  /* raw after unlock */
    return t;
  }
  // beastlint: holds mu_
  void clear_locked() { items_.clear(); }

 private:
  std::mutex mu_;
  int total_ = 0;  // guarded-by: mu_
  int total_read_ = 0;
  std::vector<int> items_;  // guarded-by: mu_
};

void spawn_all(Queue* q) {
  std::vector<std::thread> workers;
  for (int i = 0; i < 4; ++i) {
    workers.emplace_back([q] { helper(q); });
  }
}

void helper(Queue* q) { q->add(1); }
"""

    def _ctx(self):
        return cxx.CxxFileContext("csrc/fixture.h", self.SRC)

    def test_class_and_members_extracted(self):
        ctx = self._ctx()
        assert "Queue" in ctx.classes
        cls = ctx.classes["Queue"]
        assert set(cls.members) >= {
            "mu_", "total_", "total_read_", "items_"
        }
        assert cls.members["mu_"].is_mutex
        assert not cls.members["total_"].is_mutex
        assert cls.guarded == {"total_": "mu_", "items_": "mu_"}
        assert set(cls.methods) >= {"Queue", "add", "drain",
                                    "clear_locked"}

    def test_lock_scopes_and_early_unlock(self):
        ctx = self._ctx()
        cls = ctx.classes["Queue"]
        add_accs = cxx.member_accesses(ctx, cls, cls.methods["add"])
        by_attr = {a.attr: a for a in add_accs}
        assert "Queue.mu_" in by_attr["total_"].held
        assert by_attr["total_"].kind == "write" and by_attr["total_"].rmw
        assert by_attr["items_"].kind == "write"  # push_back mutator
        drain_accs = cxx.member_accesses(ctx, cls, cls.methods["drain"])
        held_by_attr = {a.attr: a.held for a in drain_accs}
        assert "Queue.mu_" in held_by_attr["total_"]
        # total_read_ is written AFTER l.unlock(): hold ended.
        assert held_by_attr["total_read_"] == frozenset()

    def test_holds_annotation_recognized(self):
        ctx = self._ctx()
        cls = ctx.classes["Queue"]
        accs = cxx.member_accesses(ctx, cls, cls.methods["clear_locked"])
        items = [a for a in accs if a.attr == "items_"]
        assert items and "Queue.mu_" in items[0].held

    def test_thread_spawns_in_loop_are_multi(self):
        ctx = self._ctx()
        spawns = cxx.thread_spawns(ctx)
        assert len(spawns) == 1
        assert spawns[0].multi  # emplace_back inside a for loop
        assert "helper" in spawns[0].callees

    def test_constructor_is_init_exempt(self):
        ctx = self._ctx()
        cls = ctx.classes["Queue"]
        ctor_accs = cxx.member_accesses(ctx, cls, cls.methods["Queue"])
        assert all(a.in_init for a in ctor_accs)

    def test_comment_stripping_keeps_line_numbers(self):
        ctx = self._ctx()
        # guarded-by annotations land on the declaration lines.
        cls = ctx.classes["Queue"]
        assert cls.members["total_"].line < cls.members["items_"].line


class TestGilEvents:
    SRC = """
void worker() {
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* obj = PyLong_FromLong(1);
  call_nogil([&] { queue->dequeue_many(); cv.wait(lk); });
  Py_DECREF(obj);
  PyGILState_Release(gil);
}
"""

    def test_nogil_span_and_api_calls(self):
        ctx = cxx.CxxFileContext("csrc/fixture.cc", self.SRC)
        fn = ctx.function_named("worker")
        events = cxx.gil_events(fn)
        kinds = [e.kind for e in events]
        assert "ensure" in kinds and "release" in kinds
        assert "nogil_start" in kinds and "nogil_end" in kinds
        api = [e.name for e in events if e.kind == "api_call"]
        assert "PyLong_FromLong" in api and "Py_DECREF" in api
        # The wait inside the call_nogil span sits between the span
        # markers (the rule treats it as released).
        start = next(e.index for e in events if e.kind == "nogil_start")
        end = next(e.index for e in events if e.kind == "nogil_end")
        wait = next(e for e in events if e.kind == "blocking_call")
        assert start < wait.index < end

    def test_signature_is_not_a_self_call(self):
        ctx = cxx.CxxFileContext("csrc/fixture.cc", self.SRC)
        fn = ctx.function_named("worker")
        assert "worker" not in {
            e.name for e in cxx.gil_events(fn) if e.kind == "call"
        }


# ---------------------------------------------------------------------------
# GIL-DISCIPLINE semantics beyond the selftest fixtures


class TestGilDiscipline:
    def test_nogil_wrapped_callee_is_safe_to_call_held(self):
        """A helper that blocks INSIDE call_nogil releases the GIL
        first — calling it with the GIL held must not flag (the
        queue_enqueue/pool_run idiom)."""
        src = """
void safe_helper() {
  call_nogil([&] { cv.wait(lk); });
}
void hook() {
  PyGILState_STATE gil = PyGILState_Ensure();
  safe_helper();
  PyGILState_Release(gil);
}
"""
        report = analyze_cxx_sources({"csrc/actor_pool.h": src})
        assert not _rules(report, "GIL-DISCIPLINE"), [
            f.render() for f in report.findings
        ]

    def test_bare_blocking_callee_flags_when_called_held(self):
        src = """
void raw_helper() { cv.wait(lk); }
void hook() {
  PyGILState_STATE gil = PyGILState_Ensure();
  raw_helper();
  PyGILState_Release(gil);
}
"""
        report = analyze_cxx_sources({"csrc/actor_pool.h": src})
        assert _rules(report, "GIL-DISCIPLINE")

    def test_stl_name_collision_does_not_flag(self):
        """vector::reserve shares a name with ring reserve(): the
        name-based summary must not infect it."""
        src = """
void reserve() { cv.wait(lk); }
void hook() {
  PyGILState_STATE gil = PyGILState_Ensure();
  std::vector<int> v;
  v.reserve(16);
  PyGILState_Release(gil);
}
"""
        report = analyze_cxx_sources({"csrc/actor_pool.h": src})
        assert not _rules(report, "GIL-DISCIPLINE"), [
            f.render() for f in report.findings
        ]

    def test_unbalanced_allow_threads_flags(self):
        src = """
void hook() {
  Py_BEGIN_ALLOW_THREADS
  do_work();
}
"""
        report = analyze_cxx_sources({"csrc/actor_pool.h": src})
        found = _rules(report, "GIL-DISCIPLINE")
        assert found and "unbalanced" in found[0].message

    def test_real_binding_layer_is_clean(self):
        """pymodule.cc + actor_pool.h as shipped: every CPython call is
        GIL-dominated and every blocking call releases first."""
        report = analysis.analyze_paths(["csrc"], root=REPO)
        found = _rules(report, "GIL-DISCIPLINE")
        assert not found, [f.render() for f in found]


# ---------------------------------------------------------------------------
# ATOMIC-ORDER: access discipline + cross-language conformance


class TestAtomicOrder:
    def _both(self, shm_src=None, transport_src=None):
        return analyze_cxx_sources({
            lint_config.SHM_H: shm_src or _read("csrc/shm.h"),
            lint_config.TRANSPORT_PY: (
                transport_src
                or _read("torchbeast_tpu/runtime/transport.py")
            ),
        })

    def test_shipped_implementations_conform(self):
        """The in-anger pin: today's transport.py and csrc/shm.h both
        conform to the model-checked spec (no findings)."""
        report = self._both()
        found = _rules(report, "ATOMIC-ORDER")
        assert not found, [f.render() for f in found]

    def test_cpp_order_weakening_flags(self):
        """Weakening the head publish to relaxed is the PR 3 bug class:
        the documented-order table catches it."""
        mutated = _read("csrc/shm.h").replace(
            "word(kRingHeadWord)->store(publish_head_, "
            "std::memory_order_release);",
            "word(kRingHeadWord)->store(publish_head_, "
            "std::memory_order_relaxed);",
        )
        assert mutated != _read("csrc/shm.h")
        report = self._both(shm_src=mutated)
        found = _rules(report, "ATOMIC-ORDER")
        assert any("memory_order_release" in f.message for f in found)

    def test_cpp_access_reorder_flags(self):
        """Publishing head BEFORE the payload memcpy breaks the
        data-then-head sequence the spec requires."""
        original = (
            "    std::memcpy(data() + pos, &len, 4);\n"
            "    std::memcpy(data() + pos + 4, frame, n);\n"
            "    word(kRingHeadWord)->store(publish_head_, "
            "std::memory_order_release);"
        )
        mutated_block = (
            "    word(kRingHeadWord)->store(publish_head_, "
            "std::memory_order_release);\n"
            "    std::memcpy(data() + pos, &len, 4);\n"
            "    std::memcpy(data() + pos + 4, frame, n);"
        )
        src = _read("csrc/shm.h")
        assert original in src
        report = self._both(shm_src=src.replace(original, mutated_block))
        found = _rules(report, "ATOMIC-ORDER")
        assert any(
            "write_frame" in f.message and "conform" in f.message
            for f in found
        )

    def test_py_access_reorder_flags(self):
        """Same mutation on the Python side: publish before pack."""
        original = (
            '        struct.pack_into("<I", self._data, pos, '
            "self._INLINE)\n"
            "        self._u64[self._HEAD] = self._publish_head"
        )
        mutated = (
            "        self._u64[self._HEAD] = self._publish_head\n"
            '        struct.pack_into("<I", self._data, pos, '
            "self._INLINE)"
        )
        src = _read("torchbeast_tpu/runtime/transport.py")
        assert original in src
        report = self._both(transport_src=src.replace(original, mutated))
        found = _rules(report, "ATOMIC-ORDER")
        assert any(
            "write_inline_marker" in f.message for f in found
        ), [f.render() for f in found]

    def test_py_raw_index_flags(self):
        src = _read("torchbeast_tpu/runtime/transport.py").replace(
            "self._u64[self._HEAD] = self._publish_head",
            "self._u64[0] = self._publish_head",
        )
        report = self._both(transport_src=src)
        found = _rules(report, "ATOMIC-ORDER")
        assert any("raw index" in f.message for f in found)

    def test_recheck_constant_drift_flags(self):
        """The bounded-recheck period is part of the verified spec:
        changing one side must flag against protocol.RECHECK_MS."""
        src = _read("csrc/shm.h").replace(
            "constexpr int kWakeRecheckMs = 20;",
            "constexpr int kWakeRecheckMs = 500;",
        )
        report = self._both(shm_src=src)
        found = _rules(report, "ATOMIC-ORDER")
        assert any("kWakeRecheckMs" in f.message for f in found)

    def test_missing_cpp_side_is_a_finding(self):
        report = analyze_cxx_sources({
            lint_config.TRANSPORT_PY: _read(
                "torchbeast_tpu/runtime/transport.py"
            ),
        })
        found = _rules(report, "ATOMIC-ORDER")
        assert any("unchecked" in f.message for f in found)

    def test_spec_sequences_match_both_languages_directly(self):
        """Belt and braces: the extracted per-method sequences equal
        SPEC_ACCESS verbatim in both languages (not merely 'no
        finding')."""
        shm_ctx = cxx.CxxFileContext(
            lint_config.SHM_H, _read("csrc/shm.h")
        )
        tree = ast.parse(_read("torchbeast_tpu/runtime/transport.py"))
        ring_cls = next(
            n for n in ast.walk(tree)
            if isinstance(n, ast.ClassDef) and n.name == "ShmRing"
        )
        for fn_name, spec in protocol.SPEC_ACCESS.items():
            cpp = tuple(cxx.collapse(
                cxx.access_sequence(shm_ctx, "ShmRing", fn_name)
            ))
            py = tuple(cxx.collapse(
                cxxrules._py_access_sequence(ring_cls, fn_name)
            ))
            assert cpp == spec, (fn_name, cpp, spec)
            assert py == spec, (fn_name, py, spec)


# ---------------------------------------------------------------------------
# CXX-LOCK-DISCIPLINE semantics beyond the selftest fixtures


class TestCxxLockDiscipline:
    def test_guarded_member_unlocked_access_flags(self):
        src = """
class Q {
 public:
  int peek() { return total_; }
 private:
  std::mutex mu_;
  int total_ = 0;  // guarded-by: mu_
};
"""
        report = analyze_cxx_sources({"csrc/fixture.h": src})
        found = _rules(report, "CXX-LOCK-DISCIPLINE")
        assert found and "guarded-by" in found[0].message

    def test_early_unlock_ends_the_hold(self):
        src = """
class Q {
 public:
  int peek() {
    std::unique_lock<std::mutex> l(mu_);
    l.unlock();
    return total_;
  }
 private:
  std::mutex mu_;
  int total_ = 0;  // guarded-by: mu_
};
"""
        report = analyze_cxx_sources({"csrc/fixture.h": src})
        assert _rules(report, "CXX-LOCK-DISCIPLINE")

    def test_cross_root_conflict_without_guard_flags(self):
        src = """
class Pump {
 public:
  void start() {
    threads_.emplace_back([this] { drain(); });
    threads_.emplace_back([this] { publish(); });
  }
  void drain() { seen_ = 1; }
  void publish() { int x = seen_; }
 private:
  std::mutex mu_;
  int seen_ = 0;
  std::vector<std::thread> threads_;
};
"""
        report = analyze_cxx_sources({"csrc/fixture.h": src})
        found = _rules(report, "CXX-LOCK-DISCIPLINE")
        assert found and "no common lock" in found[0].message

    def test_atomic_member_is_exempt(self):
        src = """
class Pump {
 public:
  void start() {
    threads_.emplace_back([this] { drain(); });
    threads_.emplace_back([this] { publish(); });
  }
  void drain() { seen_.store(1); }
  void publish() { int x = seen_.load(); }
 private:
  std::mutex mu_;
  std::atomic<int> seen_{0};
  std::vector<std::thread> threads_;
};
"""
        report = analyze_cxx_sources({"csrc/fixture.h": src})
        assert not _rules(report, "CXX-LOCK-DISCIPLINE"), [
            f.render() for f in report.findings
        ]

    def test_real_csrc_is_clean_with_reasoned_suppressions(self):
        """The shipped C++ core passes: annotations + suppressions
        carry reasons (the burn-down contract)."""
        report = analysis.analyze_paths(["csrc"], root=REPO)
        found = _rules(report, "CXX-LOCK-DISCIPLINE")
        assert not found, [f.render() for f in found]
        for finding, sup in report.suppressed:
            if finding.rule == "CXX-LOCK-DISCIPLINE":
                assert sup.reason


# ---------------------------------------------------------------------------
# The protocol model checker


class TestProtocolChecker:
    def test_shipped_spec_verifies_exhaustively(self):
        result = protocol.check_protocol()
        assert result.ok, result.as_dict()
        assert result.properties == {
            "fifo": True, "error_free": True, "no_wedge": True,
            "success_reachable": True,
        }
        # Exhaustive means a real state space, not a trivial one.
        assert result.states > 500

    def test_safe_slower_variant_also_verifies(self):
        """Coalescing off (ring every send) is the safe-slow variant:
        the checker accepts it — it rejects broken protocols, not
        different ones."""
        result = protocol.check_protocol(
            protocol.Spec(coalesce_wakeups=False)
        )
        assert result.ok, result.as_dict()

    def test_every_seeded_mutant_is_caught_with_a_trace(self):
        for name, spec in protocol.MUTATIONS.items():
            result = protocol.check_protocol(spec)
            assert not result.ok, name
            assert result.violations, name
            for v in result.violations:
                assert v.trace, (name, v.detail)

    def test_metastable_wait_mutant_wedges(self):
        """The PR 9 metastable-wait class: no bounded recheck => a lost
        wakeup parks the reader forever, found as a wedge trace ending
        in a blocked reader with undelivered frames."""
        result = protocol.check_protocol(
            protocol.MUTATIONS["no_wake_recheck"]
        )
        wedges = [v for v in result.violations if v.kind == "wedge"]
        assert wedges
        assert any("reader=blocked" in v.detail for v in wedges)
        assert any("r:block" in step for v in wedges for step in v.trace)

    def test_fenceless_oversized_path_mutant_reproduces_pr3_bug(self):
        """THE historical counterexample: without inline recovery, the
        fence-less waiting-flag race lands the 0x02 byte on a blocked
        reader — the checker must find the exact sequence (sender skips
        the WAKE on stale waiting=0, reader blocks, inline byte
        arrives)."""
        result = protocol.check_protocol(
            protocol.MUTATIONS["no_inline_recovery"]
        )
        assert not result.ok
        traces = [
            v.trace for v in result.violations
            if any("r:inline_byte_blocked" in s for s in v.trace)
        ]
        assert traces, result.as_dict()
        trace = traces[0]
        assert any(s.startswith("w:skip_bell") for s in trace)
        assert any(s == "r:block" for s in trace)
        assert any(s == "w:send_inline_byte" for s in trace)

    def test_acceptance_bundle(self):
        verdict = protocol.verify_shipped_and_mutants()
        assert verdict["ok"], verdict
        assert verdict["shipped"]["ok"]
        assert set(verdict["mutants"]) == set(protocol.MUTATIONS)
        for name, m in verdict["mutants"].items():
            assert not m["ok"] and m["violations"], name

    def test_render_trace_format(self):
        """The README's documented counterexample format: numbered
        actor:action steps, then the violated property."""
        v = protocol.Violation(
            "wedge", "success unreachable",
            ["w:publish[0:ring]", "r:arm_waiting", "r:block"],
        )
        text = protocol.render_trace(v)
        lines = text.splitlines()
        assert lines[0].strip().startswith("1. w:publish")
        assert lines[-1].strip() == "=> WEDGE: success unreachable"

    def test_cli_check_protocol(self):
        proc = subprocess.run(
            [sys.executable, "-m", "torchbeast_tpu.analysis",
             "--check-protocol"],
            capture_output=True, text=True, cwd=REPO, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        verdict = json.loads(proc.stdout.splitlines()[0])
        assert verdict["ok"]
        assert all(
            m["found"] for m in verdict["mutants"].values()
        )
        assert "counterexample" in proc.stdout

    def test_state_cap_raises_instead_of_truncating(self):
        with pytest.raises(RuntimeError, match="state space"):
            protocol.check_protocol(max_states=10)


# ---------------------------------------------------------------------------
# --diff mode covers csrc


def test_diff_patterns_include_cxx():
    from torchbeast_tpu.analysis.__main__ import DIFF_PATTERNS

    assert "*.h" in DIFF_PATTERNS and "*.cc" in DIFF_PATTERNS
    assert "*.py" in DIFF_PATTERNS


# ---------------------------------------------------------------------------
# Adaptive-recheck policy pins (ISSUE 12): the five policy constants are
# part of the verified spec surface, both languages.


class TestAdaptiveRecheckPins:
    def _both(self, shm_src=None, transport_src=None):
        return analyze_cxx_sources({
            lint_config.SHM_H: shm_src or _read("csrc/shm.h"),
            lint_config.TRANSPORT_PY: (
                transport_src
                or _read("torchbeast_tpu/runtime/transport.py")
            ),
        })

    def test_shipped_sources_clean(self):
        assert not _rules(self._both(), "ATOMIC-ORDER")

    def test_cpp_constant_drift_flags(self):
        src = _read("csrc/shm.h").replace(
            "constexpr int kRecheckMinMs = 5;",
            "constexpr int kRecheckMinMs = 1;",
        )
        found = _rules(self._both(shm_src=src), "ATOMIC-ORDER")
        assert any("kRecheckMinMs" in f.message for f in found)

    def test_py_constant_drift_flags(self):
        src = _read("torchbeast_tpu/runtime/transport.py").replace(
            "_RECHECK_WINDOW = 32",
            "_RECHECK_WINDOW = 64",
        )
        found = _rules(self._both(transport_src=src), "ATOMIC-ORDER")
        assert any("_RECHECK_WINDOW" in f.message for f in found)

    def test_missing_constant_flags(self):
        src = _read("csrc/shm.h").replace(
            "constexpr int kRecheckTighten = 16;", ""
        )
        found = _rules(self._both(shm_src=src), "ATOMIC-ORDER")
        assert any(
            "kRecheckTighten" in f.message and "could not parse"
            in f.message for f in found
        )

    def test_spec_range_is_covered(self):
        """The spec's own sanity: the walk stays where the no-wedge
        proof's untimed timeout transition covers it (finite positive
        bound, well-formed hysteresis)."""
        assert protocol.adaptive_recheck_covered()
        assert 0 < protocol.RECHECK_MIN_MS
        assert (
            protocol.RECHECK_MIN_MS
            <= protocol.RECHECK_MS
            <= protocol.RECHECK_MAX_MS
        )

    def test_check_protocol_carries_the_coverage_verdict(self):
        verdict = protocol.verify_shipped_and_mutants()
        assert verdict["adaptive_recheck"]["covered"] is True
        assert verdict["adaptive_recheck"]["min_ms"] == (
            protocol.RECHECK_MIN_MS
        )
        # A degenerate range (bound could park at 0: the timeout
        # transition the proof needs would be disableable) fails the
        # bundle even though the shipped state machine verifies.
        old = protocol.RECHECK_MIN_MS
        try:
            protocol.RECHECK_MIN_MS = 0
            broken = protocol.verify_shipped_and_mutants()
            assert broken["adaptive_recheck"]["covered"] is False
            assert broken["ok"] is False
        finally:
            protocol.RECHECK_MIN_MS = old
