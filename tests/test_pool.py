"""max_pool2d: forward identical to flax max_pool; custom VJP matches the
autodiff (SelectAndScatter) gradient on tie-free inputs."""

import numpy as np
import pytest

import flax.linen as nn
import jax
import jax.numpy as jnp

from torchbeast_tpu.ops.pool import _max_pool2d_tapsum, max_pool2d

CONFIGS = [
    # (shape, window, strides, padding) — the IMPALA trunk pools + extras
    ((4, 84, 84, 16), (3, 3), (2, 2), ((1, 1), (1, 1))),
    ((4, 42, 42, 32), (3, 3), (2, 2), ((1, 1), (1, 1))),
    ((2, 21, 21, 32), (3, 3), (2, 2), ((1, 1), (1, 1))),
    ((2, 16, 16, 8), (2, 2), (2, 2), ((0, 0), (0, 0))),
    ((2, 15, 17, 3), (3, 3), (1, 1), ((1, 1), (1, 1))),
]


@pytest.mark.parametrize("shape,window,strides,padding", CONFIGS)
def test_forward_matches_flax(shape, window, strides, padding):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    ours = max_pool2d(x, window, strides, padding)
    ref = nn.max_pool(x, window, strides, padding)
    np.testing.assert_array_equal(np.asarray(ours), np.asarray(ref))


@pytest.mark.parametrize("shape,window,strides,padding", CONFIGS)
def test_gradient_matches_autodiff(shape, window, strides, padding):
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    # Random cotangent (sum() would hide scaling errors between windows).
    ct = jnp.asarray(
        rng.standard_normal(
            nn.max_pool(x, window, strides, padding).shape
        ).astype(np.float32)
    )

    def ours(x):
        # tap-sum VJP explicitly: on an accelerator max_pool2d's dispatch
        # would compare the native gradient with itself (vacuous).
        return jnp.sum(_max_pool2d_tapsum(x, window, strides, padding) * ct)

    def ref(x):
        return jnp.sum(nn.max_pool(x, window, strides, padding) * ct)

    g_ours = jax.grad(ours)(x)
    g_ref = jax.grad(ref)(x)
    np.testing.assert_allclose(
        np.asarray(g_ours), np.asarray(g_ref), rtol=1e-6, atol=1e-6
    )


def test_tie_gradient_is_a_subgradient():
    # All-equal window: the tap-sum VJP credits every tying position; the
    # window's total credited gradient equals the cotangent times #windows
    # the position wins — still a valid subgradient (non-zero, finite).
    # Pinned on the tap-sum path explicitly: max_pool2d's platform dispatch
    # would use SelectAndScatter (one credit per window) on accelerators.
    x = jnp.ones((1, 4, 4, 1), jnp.float32)
    g = jax.grad(lambda x: jnp.sum(_max_pool2d_tapsum(x, (2, 2), (2, 2),
                                                      ((0, 0), (0, 0)))))(x)
    assert np.isfinite(np.asarray(g)).all()
    # Each non-overlapping 2x2 window distributes 1.0 to its 4 tying
    # members in this formulation.
    np.testing.assert_allclose(np.asarray(g).sum(), 16.0)


def test_jit_and_second_use_under_scan():
    x = jnp.asarray(
        np.random.default_rng(2).standard_normal((2, 8, 8, 4)).astype(
            np.float32
        )
    )
    f = jax.jit(lambda x: max_pool2d(x).sum())
    assert np.isfinite(float(f(x)))
    g = jax.jit(jax.grad(lambda x: max_pool2d(x).sum()))
    assert np.isfinite(np.asarray(g(x)).sum())
