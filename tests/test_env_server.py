"""Env server + actor pool integration over real sockets (reference
strategy: tests/core_agent_state_test.py — real transport, deterministic
counting env, inference/learn loops driven inline; asserts the on-policy
invariants across the full async stack).

Parametrized over BOTH server implementations — the Python EnvServer and
the C++ one (_tbt_core.EnvServer, csrc/env_server.h) — which must speak
an identical protocol (same spec advertisement, step schema, error
frames, stop() semantics)."""

import os
import tempfile
import threading

import numpy as np
import pytest

from torchbeast_tpu.envs import CountingEnv
from torchbeast_tpu.runtime import wire
from torchbeast_tpu.runtime.actor_pool import ActorPool
from torchbeast_tpu.runtime.env_server import EnvServer, parse_address
from torchbeast_tpu.runtime.inference import inference_loop
from torchbeast_tpu.runtime.native import import_native
from torchbeast_tpu.runtime.queues import BatchingQueue, DynamicBatcher

EPISODE_LEN = 5
T = 3

SERVER_IMPLS = ["python"]
if import_native() is not None:
    SERVER_IMPLS.append("native")


class _NativeServerHandle:
    """Python-EnvServer-compatible start()/stop() around the C++ server
    (whose run() blocks, like the reference's Server.run)."""

    def __init__(self, env_init, address):
        self._server = import_native().EnvServer(env_init, address)
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._server.run, daemon=True
        )
        self._thread.start()

    def stop(self):
        self._server.stop()
        if self._thread is not None:
            self._thread.join(5)


def make_server(env_init, address, impl):
    if impl == "native":
        return _NativeServerHandle(env_init, address)
    return EnvServer(env_init, address)


def start_counting_server(path, impl="python"):
    """Start an EnvServer on unix:{path} and wait for it to bind."""
    import time

    server = make_server(
        lambda: CountingEnv(episode_length=EPISODE_LEN), f"unix:{path}", impl
    )
    server.start()
    deadline = time.monotonic() + 5
    while not os.path.exists(path):
        if time.monotonic() > deadline:
            raise TimeoutError("server did not bind")
        time.sleep(0.01)
    return server


@pytest.fixture(params=SERVER_IMPLS)
def server_address(request):
    path = os.path.join(tempfile.mkdtemp(), "env_server")
    server = start_counting_server(path, request.param)
    yield f"unix:{path}"
    server.stop()


def test_stream_protocol(server_address):
    import socket

    family, target = parse_address(server_address)
    sock = socket.socket(family, socket.SOCK_STREAM)
    sock.connect(target)
    try:
        step = wire.recv_message(sock)
        assert step["type"] == "step"
        assert bool(step["done"])  # initial boundary step
        assert np.asarray(step["frame"]).max() == 0
        assert np.asarray(step["reward"]).dtype == np.float32

        for t in range(1, EPISODE_LEN + 1):
            wire.send_message(sock, {"type": "action", "action": 1})
            step = wire.recv_message(sock)
            assert int(step["episode_step"]) == t
        assert bool(step["done"])  # episode boundary
        assert float(step["episode_return"]) == sum(range(1, EPISODE_LEN + 1))

        # Auto-reset: counters restart on the next step.
        wire.send_message(sock, {"type": "action", "action": 0})
        step = wire.recv_message(sock)
        assert int(step["episode_step"]) == 1
    finally:
        sock.close()


def test_remote_env_spec_probe(server_address):
    """Learners probe num_actions/frame spec from the server's initial
    step — split deployments may lack env deps on the learner host."""
    import argparse

    from torchbeast_tpu.polybeast import _probe_env_via_server

    # A locally-unresolvable env id: if the remote probe silently falls
    # back to the local probe, create_env raises and the test fails loudly
    # instead of passing via the fallback.
    flags = argparse.Namespace(env="DefinitelyNotInstalledNoFrameskip-v4")
    num_actions, frame_shape, frame_dtype = _probe_env_via_server(
        flags, server_address, timeout_s=10
    )
    assert num_actions == 2  # CountingEnv default
    assert tuple(frame_shape) == (48, 48, 1)
    assert frame_dtype == np.uint8


def test_fresh_env_per_connection(server_address):
    import socket

    family, target = parse_address(server_address)
    for _ in range(2):
        sock = socket.socket(family, socket.SOCK_STREAM)
        sock.connect(target)
        step = wire.recv_message(sock)
        # A fresh env starts at episode_step 0 every time.
        assert int(step["episode_step"]) == 0
        sock.close()


class CountingPolicyServer:
    """Inference-side counting 'model': state += 1 per forward, reset where
    done — the spec model from the reference agent-state test. State is
    [1, B] (dummy layer dim, batch dim 1) so queue batching/slicing along
    batch_dim=1 applies to it like a real LSTM state."""

    def __call__(self, env_outputs, agent_state, batch_size):
        done = np.asarray(env_outputs["done"])  # [1, B]
        state = np.where(done, 0, np.asarray(agent_state)) + 1  # [1, B]
        outputs = {
            "action": np.zeros_like(done, dtype=np.int32),
            "policy_logits": state[..., None].astype(np.float32),
            "baseline": state.astype(np.float32),
        }
        return outputs, state


def make_counting_state_table(num_slots=1):
    """DeviceStateTable running the counting policy ON DEVICE (the CPU
    backend stands in for the chip): same spec model as
    CountingPolicyServer, but state lives in the table and requests carry
    slot ids — the device-resident acting path end to end."""
    import jax.numpy as jnp

    from torchbeast_tpu.runtime.state_table import DeviceStateTable

    def act_fn(ctx, env_outputs, agent_state):
        done = env_outputs["done"]  # [1, B]
        state = jnp.where(done, 0, agent_state) + 1  # [1, B]
        outputs = {
            "action": jnp.zeros_like(done, dtype=jnp.int32),
            "policy_logits": state[..., None].astype(jnp.float32),
            "baseline": state.astype(jnp.float32),
        }
        return outputs, state

    return DeviceStateTable(
        np.zeros((1, 1), np.int64),
        num_slots=num_slots,
        act_fn=act_fn,
        batch_dim=1,
    )


def run_pool(server_address, num_rollouts=6, state_table=False):
    learner_queue = BatchingQueue(
        batch_dim=1, minimum_batch_size=1, maximum_batch_size=1
    )
    batcher = DynamicBatcher(batch_dim=1, timeout_ms=20)
    table = make_counting_state_table() if state_table else None

    inf_thread = threading.Thread(
        target=inference_loop,
        args=(batcher, None if state_table else CountingPolicyServer(), 8),
        kwargs={"state_table": table},
        daemon=True,
    )
    inf_thread.start()

    pool = ActorPool(
        unroll_length=T,
        learner_queue=learner_queue,
        inference_batcher=batcher,
        env_server_addresses=[server_address],
        initial_agent_state=np.zeros((1, 1), np.int64),
        state_table=table,
    )
    pool_thread = threading.Thread(target=pool.run, daemon=True)
    pool_thread.start()

    items = []
    for item in learner_queue:
        items.append(item)
        if len(items) >= num_rollouts:
            break
    batcher.close()
    learner_queue.close()
    pool_thread.join(5)
    return items


@pytest.mark.parametrize("state_table", [False, True])
def test_actor_pool_invariants(server_address, state_table):
    items = run_pool(server_address, state_table=state_table)
    prev = None
    for item in items:
        batch = item["batch"]
        initial_state = item["initial_agent_state"]
        assert batch["frame"].shape[:2] == (T + 1, 1)

        if prev is not None:
            # Overlap-by-one across the async stack.
            for key in batch:
                np.testing.assert_array_equal(
                    batch[key][0], prev[key][-1], err_msg=key
                )

        # Agent-state bookkeeping: first in-rollout forward consumed slot
        # 0's env output with the recorded initial state.
        done0 = batch["done"][0]  # [B]
        expected = np.where(done0, 0, np.asarray(initial_state)[0]) + 1
        np.testing.assert_array_equal(batch["baseline"][1], expected)

        # Boundary steps carry reset (zero) frames.
        assert (batch["frame"][batch["done"]] == 0).all()

        # Action pairing: stored action at slot i == last_action at slot i.
        np.testing.assert_array_equal(
            batch["action"][1:], batch["last_action"][1:]
        )
        prev = batch


@pytest.mark.parametrize("impl", SERVER_IMPLS)
def test_actor_reconnects_after_server_restart(impl):
    """Elastic actors: killing the env server mid-stream and restarting it
    must not kill the pool when max_reconnects > 0."""
    path = os.path.join(tempfile.mkdtemp(), "elastic_env")
    address = f"unix:{path}"
    server = start_counting_server(path, impl)
    learner_queue = BatchingQueue(
        batch_dim=1, minimum_batch_size=1, maximum_batch_size=1
    )
    batcher = DynamicBatcher(batch_dim=1, timeout_ms=20)
    inf_thread = threading.Thread(
        target=inference_loop,
        args=(batcher, CountingPolicyServer(), 8),
        daemon=True,
    )
    inf_thread.start()

    pool = ActorPool(
        unroll_length=T,
        learner_queue=learner_queue,
        inference_batcher=batcher,
        env_server_addresses=[address],
        initial_agent_state=np.zeros((1, 1), np.int64),
        max_reconnects=3,
    )
    pool_thread = threading.Thread(target=pool.run, daemon=True)
    pool_thread.start()

    it = iter(learner_queue)
    next(it)  # at least one rollout through the first connection

    server.stop()  # cut the stream mid-training
    server = start_counting_server(path, impl)

    # The actor must reconnect and keep producing rollouts.
    for _ in range(3):
        next(it)
    assert pool.errors == []
    assert pool.reconnects >= 1  # the cut stream really forced a reconnect

    batcher.close()
    learner_queue.close()
    pool_thread.join(5)
    server.stop()


@pytest.mark.parametrize("impl", SERVER_IMPLS)
def test_env_exception_surfaces(impl):
    class ExplodingEnv:
        num_actions = 2

        def reset(self):
            return np.zeros((2, 2), np.uint8)

        def step(self, action):
            raise RuntimeError("boom")

    path = os.path.join(tempfile.mkdtemp(), "exploding")
    address = f"unix:{path}"
    server = make_server(ExplodingEnv, address, impl)
    server.start()
    import socket
    import time

    deadline = time.monotonic() + 5
    while not os.path.exists(path):
        time.sleep(0.01)
        if time.monotonic() > deadline:
            raise TimeoutError
    try:
        family, target = parse_address(address)
        sock = socket.socket(family, socket.SOCK_STREAM)
        sock.connect(target)
        wire.recv_message(sock)  # initial step
        wire.send_message(sock, {"type": "action", "action": 0})
        msg = wire.recv_message(sock)
        assert msg["type"] == "error"
        assert "boom" in msg["message"]
    finally:
        sock.close()
        server.stop()


def test_stop_before_run_never_serves():
    """Regression (ISSUE 7 RACE burn-down): a stop() that wins the race
    against a just-starting run() — before the listener is published —
    must still stop it. The old code left run() binding afterwards and
    serving forever with the stop lost."""
    path = os.path.join(tempfile.mkdtemp(), "stopfirst")
    server = EnvServer(lambda: CountingEnv(), f"unix:{path}")
    server.stop()  # latches _stopped before run() ever executes
    done = threading.Event()

    def run_then_flag():
        server.run()
        done.set()

    t = threading.Thread(target=run_then_flag, daemon=True)
    t.start()
    assert done.wait(timeout=10), "run() kept serving after a prior stop()"
    assert not os.path.exists(path), "listener socket left behind"
