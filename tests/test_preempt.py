"""Graceful preemption: SIGTERM to a running driver produces a clean,
checkpointed exit (the k8s/TPU-maintenance path)."""

import os
import signal
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.slow


def test_sigterm_checkpoints_and_exits_cleanly(tmp_path):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    extra = [
        p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep) if p
    ]
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.pathsep.join([repo_root] + extra),
    }
    proc = subprocess.Popen(
        [
            sys.executable, "-u", "-m", "torchbeast_tpu.monobeast",
            "--env", "Catch", "--model", "mlp", "--serial_envs",
            "--num_actors", "2", "--batch_size", "2",
            "--unroll_length", "5", "--total_steps", "100000000",
            "--savedir", str(tmp_path), "--xpid", "preempt",
            "--checkpoint_interval_s", "100000",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
    )
    # Wait for training to actually start (first SPS log line). select()
    # before each read so a silent-but-alive driver fails at the deadline
    # instead of blocking the suite in readline() forever. Read raw bytes
    # via os.read — NOT proc.stdout.readline(): the buffered wrapper can
    # swallow a whole chunk (including the awaited line) while select()
    # keeps reporting the fd itself as idle.
    import select

    deadline = time.time() + 120
    started = False
    buf = ""
    fd = proc.stdout.fileno()
    while time.time() < deadline:
        ready, _, _ = select.select([fd], [], [], 1.0)
        if not ready:
            if proc.poll() is not None:
                break
            continue
        chunk = os.read(fd, 65536).decode(errors="replace")
        if not chunk:  # EOF
            break
        buf += chunk
        if "Steps " in buf:
            started = True
            break
    if not started:
        proc.kill()
    assert started, "driver never started:\n" + buf

    proc.send_signal(signal.SIGTERM)
    try:
        out = proc.communicate(timeout=60)[0]
    except subprocess.TimeoutExpired:
        proc.kill()
        raise
    assert proc.returncode == 0, out
    assert "shutting down gracefully" in out
    assert (tmp_path / "preempt" / "model.ckpt").exists()
