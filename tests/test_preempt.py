"""Graceful preemption: SIGTERM to a running driver produces a clean,
checkpointed exit (the k8s/TPU-maintenance path)."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.slow


def _wait_for_output(proc, needle, deadline_s=120):
    """Accumulate the driver's stdout until `needle` appears (select()
    + raw os.read: a buffered readline can swallow the awaited line
    while select keeps reporting the fd idle — see the mono test)."""
    import select

    deadline = time.time() + deadline_s
    buf = ""
    fd = proc.stdout.fileno()
    while time.time() < deadline:
        ready, _, _ = select.select([fd], [], [], 1.0)
        if not ready:
            if proc.poll() is not None:
                break
            continue
        chunk = os.read(fd, 65536).decode(errors="replace")
        if not chunk:  # EOF
            break
        buf += chunk
        if needle in buf:
            return True, buf
    return False, buf


def test_sigterm_checkpoints_and_exits_cleanly(tmp_path):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    extra = [
        p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep) if p
    ]
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.pathsep.join([repo_root] + extra),
    }
    proc = subprocess.Popen(
        [
            sys.executable, "-u", "-m", "torchbeast_tpu.monobeast",
            "--env", "Catch", "--model", "mlp", "--serial_envs",
            "--num_actors", "2", "--batch_size", "2",
            "--unroll_length", "5", "--total_steps", "100000000",
            "--savedir", str(tmp_path), "--xpid", "preempt",
            "--checkpoint_interval_s", "100000",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
    )
    # Wait for training to actually start (first SPS log line).
    started, buf = _wait_for_output(proc, "Steps ")
    if not started:
        proc.kill()
    assert started, "driver never started:\n" + buf

    proc.send_signal(signal.SIGTERM)
    try:
        out = proc.communicate(timeout=60)[0]
    except subprocess.TimeoutExpired:
        proc.kill()
        raise
    assert proc.returncode == 0, out
    assert "shutting down gracefully" in out
    assert (tmp_path / "preempt" / "model.ckpt").exists()


def test_polybeast_sigterm_resume_roundtrip(tmp_path):
    """The full preemption contract on the ASYNC driver (ISSUE 6):
    SIGTERM a poly run mid-training -> clean checkpointed exit with the
    preemption recorded in telemetry; relaunch the same xpid -> it
    resumes FROM the checkpoint step (never from zero) and finishes."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    extra = [
        p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep) if p
    ]
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.pathsep.join([repo_root] + extra),
    }
    argv = [
        sys.executable, "-u", "-m", "torchbeast_tpu.polybeast",
        "--env", "Mock", "--model", "mlp",
        "--num_servers", "2", "--batch_size", "2",
        "--unroll_length", "5",
        "--savedir", str(tmp_path), "--xpid", "poly-preempt",
        "--pipes_basename", f"unix:{tmp_path}/pipes",
        "--num_inference_threads", "1",
        "--max_inference_batch_size", "4",
        "--checkpoint_interval_s", "100000",
    ]
    proc = subprocess.Popen(
        argv + ["--total_steps", "100000000"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env,
    )
    started, buf = _wait_for_output(proc, "Step ")
    if not started:
        proc.kill()
    assert started, "driver never started:\n" + buf

    proc.send_signal(signal.SIGTERM)
    try:
        out = buf + proc.communicate(timeout=120)[0]
    except subprocess.TimeoutExpired:
        proc.kill()
        raise
    assert proc.returncode == 0, out[-4000:]
    assert "shutting down gracefully" in out
    ckpt = tmp_path / "poly-preempt" / "model.ckpt"
    assert ckpt.exists()

    # Telemetry recorded the preemption on the final snapshot line.
    tele_path = tmp_path / "poly-preempt" / "telemetry.jsonl"
    lines = [
        json.loads(ln)
        for ln in tele_path.read_text().splitlines() if ln.strip()
    ]
    assert lines[-1]["counters"].get("preempt.sigterm_received") == 1

    # The checkpoint holds real progress to resume from. (Raw msgpack
    # read: load_checkpoint wants param templates this test doesn't
    # need just for the step counter.)
    from flax import serialization

    def _ckpt_step():
        return int(
            serialization.msgpack_restore(ckpt.read_bytes())["step"]
        )

    ckpt_step = _ckpt_step()
    assert ckpt_step > 0

    # Relaunch the same xpid: must resume from ckpt_step, then finish
    # a short remainder and exit 0 — never restart from step 0.
    proc2 = subprocess.run(
        argv + ["--total_steps", str(ckpt_step + 40)],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert proc2.returncode == 0, (proc2.stdout + proc2.stderr)[-4000:]
    out2 = proc2.stdout + proc2.stderr
    assert "Resuming preempted job" in out2
    assert _ckpt_step() >= ckpt_step + 40
