"""PipelinedMLPNet: the pipeline-parallel torso must match the sequential
torso with identical parameters, and the FULL IMPALA learner step must
train it over a `pipe` mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from torchbeast_tpu import learner as learner_lib
from torchbeast_tpu.models import create_model
from torchbeast_tpu.parallel.pp import stage_param_shardings

pytestmark = pytest.mark.slow

T, B, A = 4, 8, 5


def _batch(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "frame": rng.integers(0, 256, (T + 1, B, 6, 6, 1), dtype=np.uint8),
        "reward": rng.standard_normal((T + 1, B)).astype(np.float32),
        "done": rng.random((T + 1, B)) < 0.15,
        "episode_return": rng.standard_normal((T + 1, B)).astype(np.float32),
        "episode_step": rng.integers(0, 9, (T + 1, B)).astype(np.int32),
        "last_action": rng.integers(0, A, (T + 1, B)).astype(np.int32),
        "action": rng.integers(0, A, (T + 1, B)).astype(np.int32),
        "policy_logits": rng.standard_normal((T + 1, B, A)).astype(
            np.float32
        ),
        "baseline": rng.standard_normal((T + 1, B)).astype(np.float32),
    }


def _models(n_stages=4, use_lstm=False):
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("pipe",))
    kwargs = dict(
        num_actions=A, use_lstm=use_lstm, num_stages=n_stages, d_model=32
    )
    seq = create_model("pipelined_mlp", **kwargs)
    pipe = create_model("pipelined_mlp", mesh=mesh, **kwargs)
    return seq, pipe, mesh


def test_pipelined_model_matches_sequential():
    seq, pipe, _ = _models()
    batch = _batch()
    state = seq.initial_state(B)
    params = seq.init(
        {"params": jax.random.PRNGKey(0), "action": jax.random.PRNGKey(1)},
        batch,
        state,
    )
    out_seq, _ = seq.apply(params, batch, state, sample_action=False)
    out_pipe, _ = pipe.apply(params, batch, state, sample_action=False)
    np.testing.assert_allclose(
        out_pipe.policy_logits, out_seq.policy_logits, rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        out_pipe.baseline, out_seq.baseline, rtol=1e-5, atol=1e-5
    )
    np.testing.assert_array_equal(out_pipe.action, out_seq.action)


def test_pipelined_model_update_step_matches_sequential():
    """One full V-trace/RMSProp update: pipelined gradients == sequential
    gradients through the whole IMPALA loss."""
    seq, pipe, mesh = _models()
    batch = _batch(seed=1)
    state = seq.initial_state(B)
    params = seq.init(
        {"params": jax.random.PRNGKey(2), "action": jax.random.PRNGKey(3)},
        batch,
        state,
    )
    hp = learner_lib.HParams(batch_size=B, unroll_length=T)
    optimizer = learner_lib.make_optimizer(hp)

    step_seq = learner_lib.make_update_step(seq, optimizer, hp, donate=False)
    step_pipe = learner_lib.make_update_step(
        pipe, optimizer, hp, donate=False
    )

    p_seq, _, stats_seq = step_seq(
        params, optimizer.init(params), batch, state
    )
    # The pipelined run places stage params sharded one-per-device (the
    # real deployment layout).
    shardings = stage_param_shardings(
        mesh, params["params"], axis="pipe"
    )
    from torchbeast_tpu.models import PipelinedMLPNet

    placed = {
        "params": {
            k: (
                jax.device_put(v, shardings[k])
                if k in PipelinedMLPNet.STAGE_PARAM_NAMES
                else v
            )
            for k, v in params["params"].items()
        }
    }
    p_pipe, _, stats_pipe = step_pipe(
        placed, optimizer.init(placed), batch, state
    )

    np.testing.assert_allclose(
        float(stats_pipe["total_loss"]),
        float(stats_seq["total_loss"]),
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        float(stats_pipe["grad_norm"]),
        float(stats_seq["grad_norm"]),
        rtol=1e-4,
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        p_pipe,
        p_seq,
    )


def test_pipelined_model_with_lstm_head():
    seq, pipe, _ = _models(use_lstm=True)
    batch = _batch(seed=2)
    state = seq.initial_state(B)
    assert len(state) == 2  # (h, c)
    params = seq.init(
        {"params": jax.random.PRNGKey(4), "action": jax.random.PRNGKey(5)},
        batch,
        state,
    )
    out_seq, st_seq = seq.apply(params, batch, state, sample_action=False)
    out_pipe, st_pipe = pipe.apply(params, batch, state, sample_action=False)
    np.testing.assert_allclose(
        out_pipe.policy_logits, out_seq.policy_logits, rtol=1e-5, atol=1e-5
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
        ),
        st_pipe,
        st_seq,
    )


def test_pipelined_model_microbatch_count():
    """T*B tokens split into more microbatches than stages still match."""
    n_stages = 4
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("pipe",))
    kwargs = dict(num_actions=A, num_stages=n_stages, d_model=32)
    seq = create_model("pipelined_mlp", **kwargs)
    pipe = create_model(
        "pipelined_mlp", mesh=mesh, n_microbatches=8, **kwargs
    )
    batch = _batch(seed=3)
    params = seq.init(
        {"params": jax.random.PRNGKey(6), "action": jax.random.PRNGKey(7)},
        batch,
        (),
    )
    out_seq, _ = seq.apply(params, batch, (), sample_action=False)
    out_pipe, _ = pipe.apply(params, batch, (), sample_action=False)
    np.testing.assert_allclose(
        out_pipe.policy_logits, out_seq.policy_logits, rtol=1e-5, atol=1e-5
    )


def test_pipelined_model_more_stages_than_devices():
    """num_stages = 2x the pipe axis: the looped schedule must match the
    sequential 8-stage tower."""
    n_dev, n_stages = 4, 8
    mesh = Mesh(np.asarray(jax.devices()[:n_dev]), ("pipe",))
    kwargs = dict(num_actions=A, num_stages=n_stages, d_model=32)
    seq = create_model("pipelined_mlp", **kwargs)
    pipe = create_model("pipelined_mlp", mesh=mesh, **kwargs)
    batch = _batch(seed=9)
    params = seq.init(
        {"params": jax.random.PRNGKey(30), "action": jax.random.PRNGKey(31)},
        batch,
        (),
    )
    out_seq, _ = seq.apply(params, batch, (), sample_action=False)
    out_pipe, _ = pipe.apply(params, batch, (), sample_action=False)
    np.testing.assert_allclose(
        out_pipe.policy_logits, out_seq.policy_logits, rtol=1e-5, atol=1e-5
    )


# ---------------------------------------------------------------------------
# PipelinedTransformerNet: the long-context family under the same schedule.
# ---------------------------------------------------------------------------

def _tf_models(n_dev=4, num_layers=4, n_microbatches=None):
    mesh = Mesh(np.asarray(jax.devices()[:n_dev]), ("pipe",))
    kwargs = dict(
        num_actions=A, num_layers=num_layers, d_model=32, num_heads=2,
        memory_len=8,
    )
    seq = create_model("pipelined_transformer", **kwargs)
    pipe = create_model(
        "pipelined_transformer", mesh=mesh,
        n_microbatches=n_microbatches, **kwargs
    )
    return seq, pipe, mesh


def test_pipelined_transformer_matches_sequential_with_cache():
    """Two chained unrolls: outputs AND the rolled KV-cache state must
    match the sequential stack bitwise-close (the cache rides the
    pipeline as resident stage carry)."""
    seq, pipe, _ = _tf_models()
    b1, b2 = _batch(seed=4), _batch(seed=5)
    state0 = seq.initial_state(B)
    params = seq.init(
        {"params": jax.random.PRNGKey(8), "action": jax.random.PRNGKey(9)},
        b1,
        state0,
    )
    out_s1, st_s = seq.apply(params, b1, state0, sample_action=False)
    out_p1, st_p = pipe.apply(params, b1, state0, sample_action=False)
    np.testing.assert_allclose(
        out_p1.policy_logits, out_s1.policy_logits, rtol=1e-5, atol=1e-5
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
        ),
        st_p,
        st_s,
    )
    # Second unroll from the carried (non-zero) cache.
    out_s2, _ = seq.apply(params, b2, st_s, sample_action=False)
    out_p2, _ = pipe.apply(params, b2, st_p, sample_action=False)
    np.testing.assert_allclose(
        out_p2.policy_logits, out_s2.policy_logits, rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        out_p2.baseline, out_s2.baseline, rtol=1e-5, atol=1e-5
    )


def test_pipelined_transformer_update_step_matches_sequential():
    """Full V-trace/RMSProp update: pipelined gradients == sequential
    gradients, with stage params placed sharded over the pipe axis."""
    from torchbeast_tpu.models import PipelinedTransformerNet

    seq, pipe, mesh = _tf_models()
    batch = _batch(seed=6)
    state = seq.initial_state(B)
    params = seq.init(
        {"params": jax.random.PRNGKey(10), "action": jax.random.PRNGKey(11)},
        batch,
        state,
    )
    hp = learner_lib.HParams(batch_size=B, unroll_length=T)
    optimizer = learner_lib.make_optimizer(hp)
    step_seq = learner_lib.make_update_step(seq, optimizer, hp, donate=False)
    step_pipe = learner_lib.make_update_step(
        pipe, optimizer, hp, donate=False
    )
    p_seq, _, stats_seq = step_seq(
        params, optimizer.init(params), batch, state
    )
    shardings = stage_param_shardings(mesh, params["params"], axis="pipe")
    placed = {
        "params": {
            k: (
                jax.device_put(v, shardings[k])
                if k in PipelinedTransformerNet.STAGE_PARAM_NAMES
                else v
            )
            for k, v in params["params"].items()
        }
    }
    p_pipe, _, stats_pipe = step_pipe(
        placed, optimizer.init(placed), batch, state
    )
    np.testing.assert_allclose(
        float(stats_pipe["total_loss"]), float(stats_seq["total_loss"]),
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        float(stats_pipe["grad_norm"]), float(stats_seq["grad_norm"]),
        rtol=1e-4,
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        p_pipe,
        p_seq,
    )


def test_pipelined_transformer_looped_and_microbatched():
    """8 layers on 4 devices (looped schedule) with M=8 microbatches."""
    seq, pipe, _ = _tf_models(n_dev=4, num_layers=8, n_microbatches=8)
    batch = _batch(seed=7)
    state = seq.initial_state(B)
    params = seq.init(
        {"params": jax.random.PRNGKey(12), "action": jax.random.PRNGKey(13)},
        batch,
        state,
    )
    out_seq, _ = seq.apply(params, batch, state, sample_action=False)
    out_pipe, _ = pipe.apply(params, batch, state, sample_action=False)
    np.testing.assert_allclose(
        out_pipe.policy_logits, out_seq.policy_logits, rtol=1e-5, atol=1e-5
    )


def test_pipelined_transformer_acting_fallback():
    """T=1, B=1 acting batch (indivisible by microbatches): the mesh
    model must fall back to the sequential loop, not crash, and agree
    with the no-mesh model."""
    seq, pipe, _ = _tf_models()
    rng = np.random.default_rng(8)
    inputs = {
        "frame": rng.integers(0, 256, (1, 1, 6, 6, 1), dtype=np.uint8),
        "reward": np.zeros((1, 1), np.float32),
        "done": np.zeros((1, 1), bool),
        "last_action": np.zeros((1, 1), np.int32),
    }
    state = seq.initial_state(1)
    batch = _batch(seed=9)
    params = seq.init(
        {"params": jax.random.PRNGKey(14), "action": jax.random.PRNGKey(15)},
        batch,
        seq.initial_state(B),
    )
    out_s, st_s = seq.apply(params, inputs, state, sample_action=False)
    out_p, st_p = pipe.apply(params, inputs, state, sample_action=False)
    np.testing.assert_allclose(
        out_p.policy_logits, out_s.policy_logits, rtol=1e-5, atol=1e-5
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
        ),
        st_p,
        st_s,
    )


def test_pipelined_transformer_remat_matches():
    """remat=True on the pipelined transformer: same outputs from both
    the pipelined and the sequential path (the jax.checkpoint wrapper
    applies to both, keeping the parity oracle exact)."""
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("pipe",))
    kwargs = dict(
        num_actions=A, num_layers=4, d_model=32, num_heads=2,
        memory_len=8,
    )
    plain = create_model("pipelined_transformer", **kwargs)
    remat_seq = create_model("pipelined_transformer", remat=True, **kwargs)
    remat_pipe = create_model(
        "pipelined_transformer", remat=True, mesh=mesh, **kwargs
    )
    batch = _batch(seed=11)
    state = plain.initial_state(B)
    params = plain.init(
        {"params": jax.random.PRNGKey(42), "action": jax.random.PRNGKey(43)},
        batch,
        state,
    )
    out_plain, _ = plain.apply(params, batch, state, sample_action=False)
    out_rs, _ = remat_seq.apply(params, batch, state, sample_action=False)
    out_rp, _ = remat_pipe.apply(params, batch, state, sample_action=False)
    np.testing.assert_allclose(
        out_rs.policy_logits, out_plain.policy_logits, rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        out_rp.policy_logits, out_plain.policy_logits, rtol=1e-5, atol=1e-5
    )
