"""Loss functions and their gradients against hand-derived formulas
(reference strategy: tests/polybeast_loss_functions_test.py — values AND
gradients, e.g. the softmax jacobian for the pg loss, and the requirement
that advantages receive no gradient)."""

import numpy as np

import jax
import jax.numpy as jnp

from torchbeast_tpu.ops import (
    compute_baseline_loss,
    compute_entropy_loss,
    compute_policy_gradient_loss,
)


def _softmax(x):
    e = np.exp(x - x.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def test_baseline_loss_value_and_grad():
    adv = np.array([[1.0, -2.0], [3.0, 0.5]], dtype=np.float32)
    loss = compute_baseline_loss(jnp.asarray(adv))
    np.testing.assert_allclose(loss, 0.5 * (adv ** 2).sum(), rtol=1e-6)
    # d/dx 0.5 x^2 = x
    grad = jax.grad(lambda a: compute_baseline_loss(a))(jnp.asarray(adv))
    np.testing.assert_allclose(grad, adv, rtol=1e-6)


def test_entropy_loss_value():
    rng = np.random.default_rng(3)
    logits = rng.standard_normal((4, 3, 5)).astype(np.float32)
    p = _softmax(logits)
    expected = (p * np.log(p)).sum()
    loss = compute_entropy_loss(jnp.asarray(logits))
    np.testing.assert_allclose(loss, expected, rtol=1e-5)


def test_entropy_loss_uniform_is_minimal():
    # Uniform policy has maximal entropy -> minimal (most negative) loss.
    uniform = jnp.zeros((1, 1, 8))
    peaked = jnp.asarray([[np.eye(8)[0] * 10]])
    assert compute_entropy_loss(uniform) < compute_entropy_loss(peaked)
    np.testing.assert_allclose(
        compute_entropy_loss(uniform), -np.log(8), rtol=1e-6
    )


def test_pg_loss_value():
    rng = np.random.default_rng(4)
    T, B, A = 5, 3, 4
    logits = rng.standard_normal((T, B, A)).astype(np.float32)
    actions = rng.integers(0, A, size=(T, B))
    adv = rng.standard_normal((T, B)).astype(np.float32)

    log_p = np.log(_softmax(logits))
    ce = -np.take_along_axis(log_p, actions[..., None], -1)[..., 0]
    expected = (ce * adv).sum()

    loss = compute_policy_gradient_loss(
        jnp.asarray(logits), jnp.asarray(actions), jnp.asarray(adv)
    )
    np.testing.assert_allclose(loss, expected, rtol=1e-4)


def test_pg_loss_grad_is_weighted_softmax_jacobian():
    # d/dlogits [-log pi(a) * adv] = (softmax(logits) - onehot(a)) * adv
    # (hand-derived, same check as reference
    # tests/polybeast_loss_functions_test.py:136-163).
    rng = np.random.default_rng(5)
    T, B, A = 3, 2, 5
    logits = rng.standard_normal((T, B, A)).astype(np.float32)
    actions = rng.integers(0, A, size=(T, B))
    adv = rng.standard_normal((T, B)).astype(np.float32)

    grad = jax.grad(compute_policy_gradient_loss)(
        jnp.asarray(logits), jnp.asarray(actions), jnp.asarray(adv)
    )
    onehot = np.eye(A)[actions]
    expected = (_softmax(logits) - onehot) * adv[..., None]
    np.testing.assert_allclose(grad, expected, rtol=1e-4, atol=1e-5)


def test_pg_loss_advantages_get_no_gradient():
    # Advantages are stop_gradient'ed inside the loss
    # (reference .detach(); tests/polybeast_loss_functions_test.py:165-177).
    rng = np.random.default_rng(6)
    logits = jnp.asarray(rng.standard_normal((3, 2, 4)).astype(np.float32))
    actions = jnp.asarray(rng.integers(0, 4, size=(3, 2)))

    def loss_of_adv(adv):
        return compute_policy_gradient_loss(logits, actions, adv)

    grad = jax.grad(loss_of_adv)(jnp.ones((3, 2)))
    np.testing.assert_allclose(grad, np.zeros((3, 2)))
