"""Env adapter bookkeeping: episode counters, auto-reset, initial-state
conventions (reference core/environment.py semantics)."""

import numpy as np

from torchbeast_tpu.envs import CountingEnv, Environment, MockEnv
from torchbeast_tpu.envs.vec import SerialEnvPool


def test_initial_conventions():
    env = Environment(CountingEnv(episode_length=3))
    out = env.initial()
    assert out["done"] is True or out["done"] == True  # noqa: E712
    assert out["reward"] == 0.0
    assert out["last_action"] == 0
    assert out["episode_step"] == 0
    assert (out["frame"] == 0).all()


def test_episode_accounting_and_auto_reset():
    env = Environment(CountingEnv(episode_length=3))
    env.initial()
    rewards = []
    for t in range(1, 4):
        out = env.step(0)
        rewards.append(out["reward"])
        assert out["episode_step"] == t
    # Episode ended at step 3: totals reported WITH the done step.
    assert out["done"]
    assert out["episode_return"] == sum(rewards) == 1 + 2 + 3
    # Frame already reset to zeros on the done step.
    assert (out["frame"] == 0).all()
    # Counters restart on the following step.
    out = env.step(1)
    assert out["episode_step"] == 1
    assert out["episode_return"] == 1.0
    assert out["last_action"] == 1


def test_mock_env_fixed_length():
    env = Environment(MockEnv(episode_length=5, frame_shape=(4, 4, 1)))
    env.initial()
    dones = [env.step(0)["done"] for _ in range(10)]
    assert dones == [False] * 4 + [True] + [False] * 4 + [True]


def test_serial_pool_stacks():
    pool = SerialEnvPool(
        [lambda: CountingEnv(episode_length=4) for _ in range(3)]
    )
    out = pool.initial()
    assert out["frame"].shape == (3, 48, 48, 1)
    assert out["done"].shape == (3,)
    out = pool.step(np.zeros(3, np.int32))
    assert out["episode_step"].tolist() == [1, 1, 1]
    pool.close()


def test_memory_chain_mechanics():
    """Cue visible ONLY at reset; corridor/query frames cue-independent;
    forward required before the query (−0.5 otherwise, breaking any
    last-action relay); the query-step action decides ±1."""
    import numpy as np

    from torchbeast_tpu.envs import MemoryChainEnv, create_env

    import pytest

    # Floor: below length 6 the one-branch asymmetric relay matches or
    # beats honest play, so short probes are rejected outright.
    with pytest.raises(ValueError, match="length must be >= 6"):
        MemoryChainEnv(length=5, seed=0)

    env = MemoryChainEnv(length=6, seed=0)
    fwd = env.FORWARD
    seen = set()
    for _ in range(20):
        frame = env.reset()
        cue = int(np.argmax(frame[:2, 0, 0]))
        assert frame[2, 0, 0] == 0 and frame[3, 0, 0] == 0
        seen.add(cue)
        for t in range(1, env.length + 1):
            act = cue if t == env.length else fwd
            frame, reward, done = env.step(act)
            # Post-cue frames carry NO cue information.
            assert frame[0, 0, 0] == 0 and frame[1, 0, 0] == 0
            if t < env.length:
                assert reward == 0.0 and not done
                if t == env.length - 1:
                    assert frame[3, 0, 0] == 255  # query beacon
                else:
                    assert frame[2, 0, 0] == 255  # corridor beacon
            else:
                assert done and reward == 1.0  # matched the cue
    assert seen == {0, 1}  # both cues drawn

    # Mismatched query answer -> -1; non-forward corridor step -> -0.5
    # (the relay tax: even the asymmetric one-branch relay expects
    # 1 - (length-1)*0.25 < 0 at length >= 6, strictly worse than
    # honest coin-flipping).
    env2 = MemoryChainEnv(length=6, seed=1)
    frame = env2.reset()
    cue = int(np.argmax(frame[:2, 0, 0]))
    _, reward, done = env2.step(cue)  # announcing the cue = violation
    assert reward == -0.5 and not done
    for t in range(2, env2.length):
        _, reward, done = env2.step(fwd)
        assert reward == 0.0 and not done
    _, reward, done = env2.step(1 - cue)
    assert done and reward == -1.0

    assert create_env("Memory").num_actions == 3


def test_create_env_seed_plumbing():
    """create_env(seed=) pins the env's draw stream; two same-seed
    instances replay identical episodes, different seeds diverge."""
    import numpy as np

    from torchbeast_tpu.envs import create_env

    def cues(seed, n=12):
        env = create_env("Memory", seed=seed)
        out = []
        for _ in range(n):
            frame = env.reset()
            out.append(int(np.argmax(frame[:2, 0, 0])))
        return out

    assert cues(7) == cues(7)
    assert cues(7) != cues(8)  # 2^-12 false-failure odds

    # Parameterized corridor ids: "Memory-L<n>" sets the length (same
    # >= 6 floor as the bare constructor); malformed suffixes get the
    # grammar error, not a bare int() failure.
    assert create_env("Memory-L41").length == 41
    import pytest

    with pytest.raises(ValueError, match="length must be >= 6"):
        create_env("Memory-L5")
    with pytest.raises(ValueError, match="Bad Memory id"):
        create_env("Memory-Lstm")

    def catch_frames(seed):
        env = create_env("Catch", seed=seed)
        return [env.reset().tobytes() for _ in range(8)]

    assert catch_frames(3) == catch_frames(3)


class _CrashOnceEnv:
    """Crashes the WORKER PROCESS (os._exit) on its 3rd step unless the
    flag file exists — the revived instance finds the file (the crashing
    instance creates it just before dying) and runs clean."""

    def __init__(self, flag_path):
        self._flag_path = flag_path
        self._inner = CountingEnv(episode_length=4)
        self.num_actions = self._inner.num_actions
        self._steps = 0

    def reset(self):
        return self._inner.reset()

    def step(self, action):
        import os

        self._steps += 1
        if self._steps == 3 and not os.path.exists(self._flag_path):
            open(self._flag_path, "w").close()
            os._exit(1)  # simulate a segfault/OOM-kill of the worker
        return self._inner.step(action)


def test_process_pool_revives_crashed_worker(tmp_path):
    """ProcessEnvPool supervision: a worker hard-crash mid-step must
    respawn with a fresh env, with the crashed slot emitting the
    episode-boundary output (done=True, reward 0) and every other slot
    unaffected; subsequent steps run normally. Budget 0 = fail fast."""
    import functools
    import pytest

    from torchbeast_tpu.envs.vec import ProcessEnvPool

    flag = str(tmp_path / "crashed-once")
    fns = [
        functools.partial(_CrashOnceEnv, flag),
        functools.partial(CountingEnv, episode_length=4),
    ]
    pool = ProcessEnvPool(fns)
    try:
        pool.initial()
        pool.step([0, 0])
        pool.step([0, 0])
        out = pool.step([0, 0])  # slot 0's worker dies here
        assert pool.restarts == 1
        assert bool(out["done"][0]) is True  # boundary substitution
        assert out["reward"][0] == 0.0
        assert out["episode_step"][0] == 0
        # Slot 1 was unaffected (its real step-3 output).
        assert out["episode_step"][1] == 3
        # The revived worker serves normally afterwards.
        out = pool.step([0, 0])
        assert out["episode_step"][0] == 1
        assert out["episode_step"][1] == 4
    finally:
        pool.close()

    # Exhausted budget fails loudly, chaining the transport error.
    flag2 = str(tmp_path / "never-created-two")
    pool = ProcessEnvPool(
        [functools.partial(_CrashOnceEnv, flag2 + "x")],
        max_restarts=0,
    )
    try:
        pool.initial()
        pool.step([0])
        pool.step([0])
        with pytest.raises(RuntimeError, match="restart budget"):
            pool.step([0])
    finally:
        pool.close()


class _AlwaysCrashEnv:
    """Constructor kills the worker process outright — every revival
    dies too (the deterministic-crash case)."""

    num_actions = 2

    def __init__(self):
        import os

        os._exit(1)


def test_process_pool_revival_loop_respects_budget(tmp_path):
    """A replacement that also dies must consume the budget and end in
    the documented RuntimeError — not escape as a raw EOFError."""
    import pytest

    from torchbeast_tpu.envs.vec import ProcessEnvPool

    pool = ProcessEnvPool([_AlwaysCrashEnv], max_restarts=3)
    try:
        with pytest.raises(RuntimeError, match="restart budget"):
            pool.initial()
        assert pool.restarts == 3  # all budget consumed by revivals
    finally:
        pool.close()


def test_process_pool_split_step_matches_step():
    """step_async/step_wait (the lag-1 collector's overlap window) must
    equal the fused step(), phase order enforced."""
    import functools
    import pytest

    from torchbeast_tpu.envs.vec import ProcessEnvPool

    fns = [functools.partial(CountingEnv, episode_length=4)] * 2
    fused, split = ProcessEnvPool(fns), ProcessEnvPool(fns)
    try:
        fused.initial(), split.initial()
        with pytest.raises(RuntimeError, match="without step_async"):
            split.step_wait()
        for _ in range(5):
            out_fused = fused.step([0, 0])
            split.step_async([0, 0])
            with pytest.raises(RuntimeError, match="in flight"):
                split.step_async([0, 0])
            out_split = split.step_wait()
            for key in out_fused:
                np.testing.assert_array_equal(
                    out_fused[key], out_split[key]
                )
    finally:
        fused.close()
        split.close()


def test_process_pool_split_step_revives_crashed_worker(tmp_path):
    """A worker dying inside the async window still gets the boundary
    substitution + revival in step_wait — supervision is phase-split
    like the step itself."""
    import functools

    from torchbeast_tpu.envs.vec import ProcessEnvPool

    flag = str(tmp_path / "crashed-async")
    fns = [
        functools.partial(_CrashOnceEnv, flag),
        functools.partial(CountingEnv, episode_length=4),
    ]
    pool = ProcessEnvPool(fns)
    try:
        pool.initial()
        for _ in range(2):
            pool.step_async([0, 0])
            pool.step_wait()
        pool.step_async([0, 0])  # slot 0's worker dies in this step
        out = pool.step_wait()
        assert pool.restarts == 1
        assert bool(out["done"][0]) is True
        assert out["episode_step"][1] == 3
        pool.step_async([0, 0])
        out = pool.step_wait()
        assert out["episode_step"][0] == 1
    finally:
        pool.close()
