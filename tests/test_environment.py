"""Env adapter bookkeeping: episode counters, auto-reset, initial-state
conventions (reference core/environment.py semantics)."""

import numpy as np

from torchbeast_tpu.envs import CountingEnv, Environment, MockEnv
from torchbeast_tpu.envs.vec import SerialEnvPool


def test_initial_conventions():
    env = Environment(CountingEnv(episode_length=3))
    out = env.initial()
    assert out["done"] is True or out["done"] == True  # noqa: E712
    assert out["reward"] == 0.0
    assert out["last_action"] == 0
    assert out["episode_step"] == 0
    assert (out["frame"] == 0).all()


def test_episode_accounting_and_auto_reset():
    env = Environment(CountingEnv(episode_length=3))
    env.initial()
    rewards = []
    for t in range(1, 4):
        out = env.step(0)
        rewards.append(out["reward"])
        assert out["episode_step"] == t
    # Episode ended at step 3: totals reported WITH the done step.
    assert out["done"]
    assert out["episode_return"] == sum(rewards) == 1 + 2 + 3
    # Frame already reset to zeros on the done step.
    assert (out["frame"] == 0).all()
    # Counters restart on the following step.
    out = env.step(1)
    assert out["episode_step"] == 1
    assert out["episode_return"] == 1.0
    assert out["last_action"] == 1


def test_mock_env_fixed_length():
    env = Environment(MockEnv(episode_length=5, frame_shape=(4, 4, 1)))
    env.initial()
    dones = [env.step(0)["done"] for _ in range(10)]
    assert dones == [False] * 4 + [True] + [False] * 4 + [True]


def test_serial_pool_stacks():
    pool = SerialEnvPool(
        [lambda: CountingEnv(episode_length=4) for _ in range(3)]
    )
    out = pool.initial()
    assert out["frame"].shape == (3, 48, 48, 1)
    assert out["done"].shape == (3,)
    out = pool.step(np.zeros(3, np.int32))
    assert out["episode_step"].tolist() == [1, 1, 1]
    pool.close()
