"""Pipeline parallelism: the GPipe schedule in parallel/pp.py must be a
drop-in replacement for running the stages sequentially — identical
outputs, identical carried state, identical gradients (the bubbles'
masked computations must contribute zero grad)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from tests import jax_caps

from torchbeast_tpu.parallel.pp import (
    pipeline_apply,
    stack_stages,
    stage_param_shardings,
)

# The GPipe shard_map passes check_vma= (newer jax); running the
# schedule on an older jax TypeErrors before any numerics — skip those
# tests on version skew (validation-only tests still run).
requires_pipeline_shard_map = pytest.mark.skipif(
    not jax_caps.shard_map_supports_check_vma(),
    reason="this jax's shard_map lacks check_vma "
           "(parallel/pp.pipeline_apply passes it)",
)

D = 16


def _mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("pipe",))


def _make_stage_params(key, n_stages):
    keys = jax.random.split(key, n_stages)
    return stack_stages(
        [
            {
                "w": jax.random.normal(k, (D, D)) / np.sqrt(D),
                "b": jnp.zeros((D,)),
            }
            for k in keys
        ]
    )


def _stage_fn(params, x, carry, shared):
    """Residual MLP stage; consumes per-stage carry and a shared input so
    all three data paths are exercised."""
    h = jnp.tanh(x @ params["w"] + params["b"])
    if shared is not None:
        h = h * shared["scale"]
    if carry is None:
        return x + h, None
    new_carry = {"acc": carry["acc"] + h.sum(axis=-1)}
    return x + h + carry["acc"][:, None] * 0.01, new_carry


def _sequential(stage_params, x, carry=None, shared=None):
    n_stages = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    new_carries = []
    for s in range(n_stages):
        p = jax.tree_util.tree_map(lambda leaf: leaf[s], stage_params)
        c = (
            None
            if carry is None
            else jax.tree_util.tree_map(lambda leaf: leaf[s], carry)
        )
        x, nc = _stage_fn(p, x, c, shared)
        new_carries.append(nc)
    if carry is None:
        return x, None
    return x, jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *new_carries
    )


@pytest.mark.parametrize("n_microbatches", [None, 8])
@requires_pipeline_shard_map
def test_pipeline_matches_sequential(n_microbatches):
    n_stages, B = 4, 8
    mesh = _mesh(n_stages)
    params = _make_stage_params(jax.random.PRNGKey(0), n_stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, D))

    y_seq, _ = _sequential(params, x)
    y_pipe, _ = pipeline_apply(
        lambda p, xb, c, s: (_stage_fn(p, xb, None, None)[0], None),
        params,
        x,
        mesh=mesh,
        n_microbatches=n_microbatches,
    )
    np.testing.assert_allclose(y_pipe, y_seq, rtol=1e-6, atol=1e-6)


@requires_pipeline_shard_map
def test_pipeline_carry_and_shared():
    n_stages, B = 4, 8
    mesh = _mesh(n_stages)
    params = _make_stage_params(jax.random.PRNGKey(2), n_stages)
    x = jax.random.normal(jax.random.PRNGKey(3), (B, D))
    carry = {
        "acc": jax.random.normal(jax.random.PRNGKey(4), (n_stages, B))
    }
    shared = {
        "scale": 1.0
        + 0.1 * jax.random.normal(jax.random.PRNGKey(5), (B, 1))
    }

    y_seq, carry_seq = _sequential(params, x, carry, shared)
    y_pipe, carry_pipe = pipeline_apply(
        _stage_fn, params, x, mesh=mesh, stage_carry=carry, shared=shared
    )
    np.testing.assert_allclose(y_pipe, y_seq, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(
        carry_pipe["acc"], carry_seq["acc"], rtol=1e-6, atol=1e-6
    )


@pytest.mark.slow
@requires_pipeline_shard_map
def test_pipeline_gradients_match_sequential():
    """Backprop through the schedule == backprop through the stack; the
    fill/drain bubble computations must be gradient-invisible."""
    n_stages, B = 8, 8
    mesh = _mesh(n_stages)
    params = _make_stage_params(jax.random.PRNGKey(6), n_stages)
    x = jax.random.normal(jax.random.PRNGKey(7), (B, D))
    target = jax.random.normal(jax.random.PRNGKey(8), (B, D))

    def loss_seq(p):
        y, _ = _sequential(p, x)
        return jnp.mean((y - target) ** 2)

    def loss_pipe(p):
        y, _ = pipeline_apply(
            lambda pp_, xb, c, s: (_stage_fn(pp_, xb, None, None)[0], None),
            p,
            x,
            mesh=mesh,
        )
        return jnp.mean((y - target) ** 2)

    g_seq = jax.grad(loss_seq)(params)
    g_pipe = jax.grad(loss_pipe)(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        g_seq,
        g_pipe,
    )


@requires_pipeline_shard_map
def test_pipeline_under_jit_with_shardings():
    """jit + explicitly placed stage params (the dryrun/driver path)."""
    n_stages, B = 4, 8
    mesh = _mesh(n_stages)
    params = _make_stage_params(jax.random.PRNGKey(9), n_stages)
    shardings = stage_param_shardings(mesh, params)
    params_placed = jax.tree_util.tree_map(
        jax.device_put, params, shardings
    )
    x = jax.random.normal(jax.random.PRNGKey(10), (B, D))

    @jax.jit
    def fwd(p, x):
        y, _ = pipeline_apply(
            lambda pp_, xb, c, s: (_stage_fn(pp_, xb, None, None)[0], None),
            p,
            x,
            mesh=mesh,
        )
        return y

    y_seq, _ = _sequential(params, x)
    np.testing.assert_allclose(
        fwd(params_placed, x), y_seq, rtol=1e-6, atol=1e-6
    )


def test_pipeline_rejects_bad_microbatching():
    mesh = _mesh(4)
    params = _make_stage_params(jax.random.PRNGKey(11), 4)
    x = jnp.zeros((6, D))
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(
            lambda p, xb, c, s: (xb, None),
            params,
            x,
            mesh=mesh,
            n_microbatches=4,
        )


@requires_pipeline_shard_map
def test_multi_pass_pipeline_matches_sequential():
    """8 stages on 4 devices: the looped schedule (2 passes of the
    4-stage pipeline) must equal the sequential 8-stage tower, carries
    included."""
    from torchbeast_tpu.parallel.pp import pipeline_apply_multi

    n_stages, n_dev, B = 8, 4, 8
    mesh = _mesh(n_dev)
    params = _make_stage_params(jax.random.PRNGKey(20), n_stages)
    x = jax.random.normal(jax.random.PRNGKey(21), (B, D))
    carry = {
        "acc": jax.random.normal(jax.random.PRNGKey(22), (n_stages, B))
    }
    shared = {
        "scale": 1.0
        + 0.1 * jax.random.normal(jax.random.PRNGKey(23), (B, 1))
    }

    y_seq, carry_seq = _sequential(params, x, carry, shared)
    y_pipe, carry_pipe = pipeline_apply_multi(
        _stage_fn, params, x, mesh=mesh, stage_carry=carry, shared=shared
    )
    np.testing.assert_allclose(y_pipe, y_seq, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(
        carry_pipe["acc"], carry_seq["acc"], rtol=1e-6, atol=1e-6
    )


@pytest.mark.slow
@requires_pipeline_shard_map
def test_multi_pass_pipeline_gradients_match_sequential():
    from torchbeast_tpu.parallel.pp import pipeline_apply_multi

    n_stages, n_dev, B = 8, 4, 8
    mesh = _mesh(n_dev)
    params = _make_stage_params(jax.random.PRNGKey(24), n_stages)
    x = jax.random.normal(jax.random.PRNGKey(25), (B, D))
    target = jax.random.normal(jax.random.PRNGKey(26), (B, D))

    def loss_seq(p):
        y, _ = _sequential(p, x)
        return jnp.mean((y - target) ** 2)

    def loss_pipe(p):
        y, _ = pipeline_apply_multi(
            lambda pp_, xb, c, s: (_stage_fn(pp_, xb, None, None)[0], None),
            p,
            x,
            mesh=mesh,
        )
        return jnp.mean((y - target) ** 2)

    g_seq = jax.grad(loss_seq)(params)
    g_pipe = jax.grad(loss_pipe)(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        g_seq,
        g_pipe,
    )


def test_multi_pass_rejects_non_multiple():
    from torchbeast_tpu.parallel.pp import pipeline_apply_multi

    mesh = _mesh(4)
    params = _make_stage_params(jax.random.PRNGKey(27), 6)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply_multi(
            lambda p, xb, c, s: (xb, None),
            params,
            jnp.zeros((8, D)),
            mesh=mesh,
        )


def test_can_pipeline_gate():
    """The single divisibility gate the models' fallback and the
    drivers' validation share (parallel/pp.py can_pipeline)."""
    from torchbeast_tpu.parallel import create_mesh
    from torchbeast_tpu.parallel.pp import can_pipeline

    pipe_only = create_mesh(4, pipe_parallelism=4)  # data=1 x pipe=4
    assert can_pipeline(pipe_only, 8, "pipe")
    assert not can_pipeline(pipe_only, 6, "pipe")  # 6 % 4 != 0
    assert can_pipeline(pipe_only, 6, "pipe", n_microbatches=3)
    composite = create_mesh(8, pipe_parallelism=4)  # data=2 x pipe=4
    assert can_pipeline(composite, 8, "pipe", batch_axis="data")
    # 4 rows -> mb=1 per microbatch, not divisible by data=2.
    assert not can_pipeline(composite, 4, "pipe", batch_axis="data")
    # Custom M fixes it: mb=2 rows over data=2.
    assert can_pipeline(
        composite, 4, "pipe", n_microbatches=2, batch_axis="data"
    )
