"""End-to-end async-runtime smoke: polybeast trains on Mock env servers over
unix sockets with the real model, inference bucketing, and the learner
thread; checkpoint written; steps advance."""

import numpy as np
import pytest

from torchbeast_tpu import polybeast



def make_flags(tmp_path, **overrides):
    argv = [
        "--env", "Mock",
        "--num_servers", "2",
        "--batch_size", "2",
        "--unroll_length", "5",
        "--total_steps", "60",
        "--savedir", str(tmp_path),
        "--xpid", "poly-smoke",
        "--model", "shallow",
        "--pipes_basename", f"unix:{tmp_path}/pipes",
        "--num_inference_threads", "1",
        "--max_inference_batch_size", "4",
        "--checkpoint_interval_s", "100000",
    ]
    for k, v in overrides.items():
        argv += [f"--{k}"] if v is True else [f"--{k}", str(v)]
    return polybeast.make_parser().parse_args(argv)


def test_polybeast_train_smoke(tmp_path):
    flags = make_flags(tmp_path)
    stats = polybeast.train(flags)
    assert stats["step"] >= 60
    assert np.isfinite(stats["total_loss"])
    assert (tmp_path / "poly-smoke" / "model.ckpt").exists()
    assert (tmp_path / "poly-smoke" / "logs.csv").exists()


@pytest.mark.slow
def test_polybeast_train_lstm(tmp_path):
    flags = make_flags(tmp_path, xpid="poly-lstm", use_lstm=True)
    stats = polybeast.train(flags)
    assert stats["step"] >= 60
    assert np.isfinite(stats["total_loss"])


@pytest.mark.slow
def test_polybeast_train_native_runtime(tmp_path):
    from torchbeast_tpu.runtime.native import available

    if not available():
        import pytest

        pytest.skip("_tbt_core not built")
    flags = make_flags(tmp_path, xpid="poly-native", native_runtime=True,
                       use_lstm=True)
    stats = polybeast.train(flags)
    assert stats["step"] >= 60
    assert np.isfinite(stats["total_loss"])


@pytest.mark.slow
def test_polybeast_replica_serving(tmp_path):
    """Replica serving end to end through the driver (ISSUE 14): the
    learner publishes versioned snapshots, replica threads answer
    acting requests with policy_lag recorded into the rollout, and the
    run trains to completion with requests actually served from the
    replica path."""
    from torchbeast_tpu import telemetry

    reg = telemetry.get_registry()
    before = {
        name: int(reg.counter(name).value())
        for name in (
            "serving.replica_requests",
            "serving.snapshots_published",
        )
    }
    flags = make_flags(
        tmp_path, xpid="poly-replica", use_lstm=True,
        no_native_runtime=True, replica_refresh_updates="2",
        max_policy_lag="50",
    )
    stats = polybeast.train(flags)
    assert stats["step"] >= 60
    assert np.isfinite(stats["total_loss"])
    published = (
        int(reg.counter("serving.snapshots_published").value())
        - before["serving.snapshots_published"]
    )
    replica_served = (
        int(reg.counter("serving.replica_requests").value())
        - before["serving.replica_requests"]
    )
    assert published >= 2  # v0 + at least one refresh
    assert replica_served > 0  # requests really went to the replica
    # The recorded lag histogram saw real observations (0-lag counts).
    assert reg.histogram("serving.policy_lag").count > 0


@pytest.mark.slow
def test_polybeast_test_mode(tmp_path):
    # Train a checkpoint, then greedy-evaluate it via the poly CLI (the
    # reference's poly test() raises NotImplementedError).
    flags = make_flags(tmp_path)
    polybeast.train(flags)
    tflags = make_flags(tmp_path, mode="test", num_test_episodes="1")
    returns = polybeast.main(tflags)
    assert len(returns) == 1
    assert returns[0] == 200.0  # Mock: 200 steps x reward 1.0


@pytest.mark.slow
def test_polybeast_bf16_trunk(tmp_path):
    flags = make_flags(tmp_path, xpid="poly-bf16", model_dtype="bfloat16")
    stats = polybeast.train(flags)
    assert stats["step"] >= 60
    assert np.isfinite(stats["total_loss"])


@pytest.mark.slow
def test_polybeast_train_data_parallel(tmp_path):
    # 4-way DP learner over the virtual CPU mesh inside the async driver.
    flags = make_flags(
        tmp_path, xpid="poly-dp", num_learner_devices="4", batch_size="4",
        num_servers="4",
    )
    stats = polybeast.train(flags)
    assert stats["step"] >= 60
    assert np.isfinite(stats["total_loss"])


@pytest.mark.slow
def test_polybeast_train_native_feedforward(tmp_path):
    # The default (no-LSTM) path carries an EMPTY agent-state nest through
    # the whole C++ pipeline — distinct empty-nest round-trip coverage.
    from torchbeast_tpu.runtime.native import available

    if not available():
        import pytest

        pytest.skip("_tbt_core not built")
    flags = make_flags(tmp_path, xpid="poly-native-ff", native_runtime=True)
    stats = polybeast.train(flags)
    assert stats["step"] >= 60
    assert np.isfinite(stats["total_loss"])


@pytest.mark.slow
def test_poly_transformer_sequence_parallel(tmp_path):
    """The async driver trains the transformer with ring attention over a
    4-way seq mesh (unroll+1 = 8 divisible by 4; the T=1 inference path
    falls back to dense with the same params)."""
    from torchbeast_tpu import polybeast

    flags = polybeast.make_parser().parse_args([
        "--env", "Mock",
        "--xpid", "seqpar",
        "--num_servers", "2",
        "--batch_size", "2",
        "--unroll_length", "7",
        "--total_steps", "56",
        "--model", "transformer",
        "--sequence_parallel", "4",
        "--savedir", str(tmp_path),
        "--pipes_basename", f"unix:{tmp_path}/pipes",
        "--checkpoint_interval_s", "100000",
    ])
    stats = polybeast.train(flags)
    assert stats["step"] >= 56
    assert np.isfinite(stats["total_loss"])


@pytest.mark.slow
def test_prewarm_inference(tmp_path, caplog):
    """--prewarm_inference compiles every bucket before actors connect
    and the run proceeds normally (the log record proves the prewarm
    actually ran — a no-op would still reach total_steps)."""
    import logging

    flags = make_flags(tmp_path, xpid="prewarm", prewarm_inference=True)
    with caplog.at_level(logging.INFO):
        stats = polybeast.train(flags)
    assert stats["step"] >= flags.total_steps
    assert any(
        "Prewarmed 3 inference buckets" in r.message for r in caplog.records
    ), [r.message for r in caplog.records][:20]


@pytest.mark.slow
def test_poly_lstm_solves_memory_env(tmp_path):
    """The async stack's agent-state path (per-actor state through the
    DynamicBatcher + rollout re-pairing) must carry memory end-to-end:
    the Memory probe is unsolvable without it (see MemoryChainEnv and
    benchmarks/artifacts/lstm_learning.md §2b; pilot hit +0.99 by ~19k
    steps, sustained 1.0 to 150k)."""
    flags = make_flags(
        tmp_path, xpid="poly-mem-lstm", env="Memory", model="mlp",
        use_lstm=True, num_servers="8", num_actors="16",
        batch_size="16", unroll_length="20", total_steps="80000",
        learning_rate="1e-3", entropy_cost="0.01",
        max_inference_batch_size="16", env_seed="1",
    )
    stats = polybeast.train(flags)
    assert stats.get("mean_episode_return", -1.0) > 0.6


@pytest.mark.slow
def test_poly_transformer_solves_memory_env(tmp_path):
    """Attention-as-memory through the ASYNC stack: the transformer's
    incremental KV cache rides per-actor through the DynamicBatcher
    into jitted inference and back (the same route the LSTM state takes
    in test_poly_lstm_solves_memory_env), and must deliver the t=0 cue,
    segment-masked, to the query step. Hyperparameters are the
    saturation-safe pair from the mono twin (lr 5e-4, entropy 0.02 —
    see tests/test_monobeast.py::test_transformer_solves_memory_env);
    pilot sustained 1.0 through 150k at ~960 SPS
    (benchmarks/artifacts/lstm_learning.md §4)."""
    flags = make_flags(
        tmp_path, xpid="poly-mem-transformer", env="Memory",
        model="transformer", num_servers="8", num_actors="16",
        batch_size="16", unroll_length="20", total_steps="150000",
        learning_rate="5e-4", entropy_cost="0.02",
        max_inference_batch_size="16", env_seed="1",
        # env_seed pins each stream's cue sequence (assignment order
        # still follows connections, so poly is variance-reduced, not
        # bit-deterministic like the mono twin).
    )
    stats = polybeast.train(flags)
    assert stats.get("mean_episode_return", -1.0) > 0.6


@pytest.mark.slow
def test_server_supervisor_restarts_dead_server(tmp_path):
    """Chaos: SIGKILL one env server mid-train. The supervisor must
    respawn it on the same address, the affected actors must bridge the
    gap through their reconnect budget, and training must reach
    total_steps. The reference's env driver only LOGS a death — a dead
    gRPC server takes its slot down for good."""
    import multiprocessing as mp
    import threading
    import time as time_lib

    flags = make_flags(
        tmp_path, xpid="supervised", env="Mock", model="mlp",
        num_servers="2", num_actors="4", batch_size="4",
        unroll_length="10", total_steps="40000",
        max_actor_reconnects="10",
    )
    before = {p.pid for p in mp.active_children()}
    killed = {}
    train_done = threading.Event()

    def killer():
        deadline = time_lib.monotonic() + 30
        while time_lib.monotonic() < deadline and not killed:
            victims = [
                p for p in mp.active_children() if p.pid not in before
            ]
            if victims:
                time_lib.sleep(3)  # let training get underway first
                if train_done.is_set():
                    return  # too late — a no-op kill must not count
                victim = victims[0]
                victim.kill()
                killed["pid"] = victim.pid
                # Direct evidence of supervision: a NEW child pid
                # (neither pre-existing nor the victim) must appear
                # while training continues — this is the respawn, and
                # observing it here removes the end-of-run race where a
                # kill lands correctly but train finishes before the
                # supervisor's next poll.
                respawn_deadline = time_lib.monotonic() + 30
                while time_lib.monotonic() < respawn_deadline:
                    fresh = [
                        p for p in mp.active_children()
                        if p.pid not in before and p.pid != victim.pid
                        and p.is_alive()
                    ]
                    if len(fresh) >= flags.num_servers:
                        killed["respawned"] = True
                        return
                    time_lib.sleep(0.2)
                return
            time_lib.sleep(0.2)

    t = threading.Thread(target=killer)
    t.start()
    stats = polybeast.train(flags)
    train_done.set()
    t.join()
    assert killed, (
        "killer never landed mid-train (train finished first or no "
        "server appeared); raise total_steps if machines got faster"
    )
    assert killed.get("respawned"), "no respawned server observed"
    assert stats["step"] >= 40000
    assert stats.get("server_restarts", 0) >= 1


def test_failed_validation_reaps_servers(tmp_path):
    """A post-spawn failure (here: a flag-validation raise) must reap
    the just-spawned env-server group — terminate-without-join used to
    strand spawn-context children as orphans (ppid 1) after every
    validation-failure run."""
    import multiprocessing as mp

    before = {p.pid for p in mp.active_children()}
    flags = make_flags(tmp_path, xpid="leak-check", tensor_parallel="2")
    with pytest.raises(ValueError, match="tensor_parallel"):
        polybeast.train(flags)
    # Order-independent: only processes spawned BY this train call count.
    leftovers = [
        p for p in mp.active_children() if p.pid not in before
    ]
    assert not leftovers, [p.pid for p in leftovers]


def test_polybeast_superstep_smoke(tmp_path):
    """--superstep_k 2: the learner drains rollouts through the K-batch
    arena and dispatches scanned supersteps; steps land on whole
    supersteps (K*T*B per dispatch) and the telemetry accounting shows
    K updates per dispatch with host syncs amortized K-fold."""
    import json

    from torchbeast_tpu import telemetry

    flags = make_flags(
        tmp_path, xpid="poly-ss", superstep_k="2", model="mlp",
        use_lstm=True, total_steps="80",
    )
    # The registry is process-global (other tests' driver runs tick the
    # same counters), so diff snapshots around THIS run.
    before = telemetry.snapshot()
    stats = polybeast.train(flags)
    run = telemetry.delta(telemetry.snapshot(), before)
    assert stats["step"] >= 80
    assert stats["step"] % (2 * 5 * 2) == 0  # K * T * batch_size
    assert np.isfinite(stats["total_loss"])
    # K-fold amortization: updates = K * dispatches, host_syncs =
    # dispatches (every dispatch's stats flushed exactly once).
    updates = run["counters"]["learner.updates"]
    syncs = run["counters"]["learner.host_syncs"]
    dispatches = run["histograms"]["learner.updates_per_dispatch"][
        "count"
    ]
    assert dispatches > 0
    assert updates == 2 * dispatches
    assert syncs == dispatches
    # The snapshot file carries the gauge for post-hoc reads.
    lines = [
        json.loads(ln)
        for ln in (tmp_path / "poly-ss" / "telemetry.jsonl")
        .read_text().splitlines()
    ]
    assert lines[-1]["gauges"]["learner.superstep_k"] == 2


def test_polybeast_superstep_native_smoke(tmp_path):
    """--superstep_k 2 on the NATIVE runtime (ISSUE 9: the C++ queue's
    raw-item intake feeds the same host arena): K-vs-1 accounting holds
    — K updates per dispatch, host syncs amortized K-fold, steps landing
    on whole supersteps — and the native telemetry fold emits the wire/
    step series on the same snapshot."""
    import json

    from torchbeast_tpu import telemetry
    from torchbeast_tpu.runtime.native import available

    if not available():
        pytest.skip("_tbt_core not built")
    flags = make_flags(
        tmp_path, xpid="poly-ss-native", superstep_k="2", model="mlp",
        use_lstm=True, total_steps="80", native_runtime=True,
    )
    before = telemetry.snapshot()
    stats = polybeast.train(flags)
    run = telemetry.delta(telemetry.snapshot(), before)
    assert stats["step"] >= 80
    assert stats["step"] % (2 * 5 * 2) == 0  # K * T * batch_size
    assert np.isfinite(stats["total_loss"])
    updates = run["counters"]["learner.updates"]
    syncs = run["counters"]["learner.host_syncs"]
    dispatches = run["histograms"]["learner.updates_per_dispatch"]["count"]
    assert dispatches > 0
    assert updates == 2 * dispatches
    assert syncs == dispatches
    lines = [
        json.loads(ln)
        for ln in (tmp_path / "poly-ss-native" / "telemetry.jsonl")
        .read_text().splitlines()
    ]
    last = lines[-1]
    assert last["gauges"]["learner.superstep_k"] == 2
    # The native fold's series (C++ pool/batcher/queue stamps).
    assert run["counters"]["wire.bytes_up"] > 0
    assert run["counters"]["actor.env_steps"] > 0
    assert run["histograms"]["actor.request_rtt_s"]["count"] > 0
    assert run["histograms"]["inference.request_wait_s"]["count"] > 0


def test_polybeast_chaos_native_accepted(tmp_path):
    """--chaos_plan with --native_runtime is SUPPORTED since ISSUE 12:
    the controller drives the C++ pool's FaultHooks instead of the
    Python transport wrap (the capability gate this test used to pin is
    gone). An armed-but-empty plan must run to completion and carry the
    chaos summary in the final stats."""
    from torchbeast_tpu.runtime.native import available

    if not available():
        pytest.skip("_tbt_core not built")
    plan_path = tmp_path / "empty_plan.json"
    plan_path.write_text('{"seed": 1, "faults": []}')
    flags = make_flags(
        tmp_path, xpid="poly-chaos-native", native_runtime=True,
        chaos_plan=str(plan_path),
    )
    stats = polybeast.train(flags)
    assert stats["step"] >= 60
    assert stats["chaos"] == {
        "seed": 1, "injected": {}, "abandoned": [], "pending": [],
    }
