"""Native serving plane (ISSUE 16): the C++ SliceRouter/ReplicaRouter
against their Python counterparts.

The parity families:

- routing bit-identity over real transports (unix socket AND shm ring):
  the same slot-framed actor stream through the native pool behind a
  C++ SliceRouter and through the Python pool behind the Python
  SliceRouter produces bit-identical learner batches, and BOTH runs
  land every request on the hash-designated slice (the other slice
  serves nothing);
- per-slice series on native telemetry: NativeTelemetryFolder folds the
  C++ router/batcher counters into the exact `inference.slice.<i>.*`
  schema the Python serving plane emits;
- continuous-batching shed accounting exactness: with the admission
  gate armed and `continuous=True`, every request lands in exactly one
  of served/shed/expired, and the pool's resubmits equal shed+expired;
- replica lag stamping parity: the same snapshot store + hooks behind
  the C++ ReplicaRouter and the Python one stamp bit-identical
  `policy_lag` leaves, and degrade to the central path identically.

Skipped when the extension isn't built (scripts/build_native.sh).
"""

import os
import tempfile
import threading
import time

import numpy as np
import pytest

from torchbeast_tpu.runtime.native import import_native

core = import_native()
pytestmark = pytest.mark.skipif(
    core is None, reason="_tbt_core not built (run scripts/build_native.sh)"
)

T = 4  # unroll length
EPISODE_LEN = 6


# ---------------------------------------------------------------------------
# Routing bit-identity over real transports


class _HostSlotTable:
    """Host-side DeviceStateTable stand-in (same surface the pools use);
    see tests/test_native.py."""

    def __init__(self, num_slots):
        self.num_slots = num_slots
        self.initial_state_host = {"s": np.zeros((1, 1), np.int64)}
        self._values = {}

    @property
    def trash_slot(self):
        return self.num_slots

    def get(self, slot):
        return self._values.get(int(slot), 0)

    def set(self, slot, value):
        self._values[int(slot)] = int(value)

    def reset(self, slots):
        for s in slots:
            self._values[int(s)] = 0

    def read_slot(self, slot):
        return {"s": np.full((1, 1), self.get(slot), np.int64)}


def _serve_slot_batcher(batcher, table):
    """Slice serving thread: CountingEnv dynamics over the slot table."""
    it = iter(batcher)
    while True:
        try:
            batch = next(it)
        except StopIteration:
            return
        inputs = batch.get_inputs()
        slots = np.asarray(inputs["slot"]).reshape(-1)
        advance = np.asarray(inputs["advance"]).reshape(-1)
        done = np.asarray(inputs["env"]["done"])[0].astype(bool)
        prev = np.array([table.get(s) for s in slots], np.int64)
        new = np.where(done, 0, prev) + 1
        for j, slot in enumerate(slots):
            if advance[j]:
                table.set(slot, new[j])
        batch.set_outputs({
            "outputs": {
                "action": np.zeros((1, len(slots)), np.int32),
                "policy_logits": new[None, :, None].astype(np.float32),
                "baseline": new[None].astype(np.float32),
            }
        })


def _py_split(n_slices):
    """A DeviceSplit over opaque placeholder devices: routing only needs
    n_slices and the hash, not real jax devices."""
    from torchbeast_tpu.runtime.placement import DeviceSplit

    return DeviceSplit(
        spec="test",
        inference_devices=tuple(range(n_slices)),
        learner_devices=(n_slices,),
    )


def _collect_sliced_items(pool_kind, address, n_items):
    """One actor in slot mode through TWO slice batchers behind the
    router of `pool_kind`; returns (items, per-slice request counts)."""
    from torchbeast_tpu import nest
    from torchbeast_tpu.telemetry.metrics import MetricsRegistry

    table = _HostSlotTable(num_slots=1)
    if pool_kind == "native":
        learner_queue = core.BatchingQueue(
            batch_dim=1, minimum_batch_size=1, maximum_batch_size=1
        )
        batchers = [
            core.DynamicBatcher(batch_dim=1, timeout_ms=20)
            for _ in range(2)
        ]
        router = core.SliceRouter(slices=batchers)
        pool = core.ActorPool(
            unroll_length=T,
            learner_queue=learner_queue,
            inference_batcher=router,
            env_server_addresses=[address],
            initial_agent_state=table.initial_state_host,
            state_table=table,
        )
        counts = lambda: list(router.telemetry()["requests"])  # noqa: E731
    else:
        from torchbeast_tpu.parallel.sebulba import SliceRouter, SliceStack
        from torchbeast_tpu.runtime.actor_pool import ActorPool
        from torchbeast_tpu.runtime.queues import (
            BatchingQueue,
            DynamicBatcher,
        )

        registry = MetricsRegistry()
        learner_queue = BatchingQueue(
            batch_dim=1, minimum_batch_size=1, maximum_batch_size=1
        )
        batchers = [
            DynamicBatcher(batch_dim=1, timeout_ms=20) for _ in range(2)
        ]
        stacks = [
            SliceStack(i, None, b, None, None, None)
            for i, b in enumerate(batchers)
        ]
        router = SliceRouter(_py_split(2), stacks, registry=registry)
        pool = ActorPool(
            unroll_length=T,
            learner_queue=learner_queue,
            inference_batcher=router,
            env_server_addresses=[address],
            initial_agent_state=table.initial_state_host,
            state_table=table,
        )
        counts = lambda: [  # noqa: E731
            registry.counter(f"inference.slice.{i}.requests").value()
            for i in range(2)
        ]
    servers = [
        threading.Thread(
            target=_serve_slot_batcher, args=(b, table), daemon=True
        )
        for b in batchers
    ]
    for s in servers:
        s.start()
    pool_thread = threading.Thread(target=pool.run, daemon=True)
    pool_thread.start()
    items = []
    it = iter(learner_queue)
    while len(items) < n_items:
        item = next(it)
        items.append(item if not isinstance(item, tuple) else item[0])
    for b in batchers:
        b.close()
    learner_queue.close()
    pool_thread.join(5)
    for s in servers:
        s.join(5)
    flat = [
        [np.asarray(leaf) for leaf in nest.flatten(item)] for item in items
    ]
    return flat, counts()


def _bind_server(kind, tag):
    from torchbeast_tpu.envs import CountingEnv
    from torchbeast_tpu.runtime.env_server import EnvServer

    path = os.path.join(tempfile.mkdtemp(), f"route_{tag}")
    address = f"{kind}:{path}"
    server = EnvServer(
        lambda: CountingEnv(episode_length=EPISODE_LEN), address
    )
    server.start()
    if kind == "unix":
        deadline = time.monotonic() + 10
        while not os.path.exists(path):
            if time.monotonic() > deadline:
                raise TimeoutError("server did not bind")
            time.sleep(0.01)
    else:
        time.sleep(0.3)  # shm attach files appear on first accept
    return server, address


@pytest.mark.parametrize("transport", ["unix", "shm"])
def test_native_routing_bit_identical(transport):
    """Same slot -> same slice -> same reply, either language, over a
    real transport: bit-identical learner batches AND an identical
    all-on-the-hashed-slice request distribution."""
    from torchbeast_tpu.runtime.placement import _mix64

    expected_slice = _mix64(0) % 2  # the single actor serves slot 0
    results = {}
    for kind in ("native", "python"):
        server, address = _bind_server(transport, f"{transport}_{kind}")
        try:
            results[kind] = _collect_sliced_items(kind, address, 5)
        finally:
            server.stop()
    native_items, native_counts = results["native"]
    python_items, python_counts = results["python"]
    # Routing identity: every request on the hash-designated slice.
    assert native_counts[1 - expected_slice] == 0
    assert python_counts[1 - expected_slice] == 0
    assert native_counts[expected_slice] > 0
    assert python_counts[expected_slice] > 0
    # Reply identity: bit-identical learner batches.
    assert len(native_items) == len(python_items)
    for native_item, python_item in zip(native_items, python_items):
        assert len(native_item) == len(python_item)
        for native_leaf, python_leaf in zip(native_item, python_item):
            assert native_leaf.dtype == python_leaf.dtype
            np.testing.assert_array_equal(native_leaf, python_leaf)


def test_slice_router_validation_and_rr():
    with pytest.raises(ValueError):
        core.SliceRouter(slices=[])
    batchers = [core.DynamicBatcher(batch_dim=1) for _ in range(3)]
    router = core.SliceRouter(slices=batchers)
    assert router.n_slices() == 3
    assert router.size() == 0
    assert not router.is_closed()
    router.close()
    assert router.is_closed()


# ---------------------------------------------------------------------------
# Per-slice series on native telemetry


def test_native_per_slice_telemetry_schema():
    """NativeTelemetryFolder folds C++ router/batcher counters into the
    EXACT series the Python serving plane emits: per-slice
    `inference.slice.<i>.requests` counters and `.depth` gauges, the
    replica routing split, and the continuous-batching roll counter."""
    from torchbeast_tpu.runtime.native import NativeTelemetryFolder
    from torchbeast_tpu.telemetry.metrics import MetricsRegistry

    batchers = [core.DynamicBatcher(batch_dim=1) for _ in range(2)]
    router = core.SliceRouter(slices=batchers)
    central = core.DynamicBatcher(batch_dim=1)
    replica = core.DynamicBatcher(batch_dim=1)
    replica_router = core.ReplicaRouter(central=central, replica=replica)

    def one_request(target):
        target.compute({
            "slot": np.zeros((1, 1), np.int32),
            "env": np.zeros((1, 1, 2), np.float32),
        })

    t = threading.Thread(target=one_request, args=(router,), daemon=True)
    t.start()
    # Slot 0 hashes to slice 1 (splitmix64(0) is odd); serve it there.
    batch = next(iter(batchers[1]))
    batch.set_outputs(batch.get_inputs())
    t.join(5)

    registry = MetricsRegistry()
    folder = NativeTelemetryFolder(
        registry,
        slice_batchers=batchers,
        slice_router=router,
        replica_batcher=replica,
        replica_router=replica_router,
    )
    folder.tick()
    assert registry.counter("inference.slice.1.requests").value() == 1
    assert registry.counter("inference.slice.0.requests").value() == 0
    # Depth gauges exist and track batcher.size() (drained -> 0).
    assert registry.gauge("inference.slice.0.depth").value() == 0
    assert registry.gauge("inference.slice.1.depth").value() == 0
    assert registry.counter("serving.replica_requests").value() == 0
    assert registry.counter("serving.central_requests").value() == 0
    assert registry.counter("serving.rolled").value() == 0
    # Delta semantics: a second tick with no new requests credits 0.
    folder.tick()
    assert registry.counter("inference.slice.1.requests").value() == 1
    for b in batchers + [central, replica]:
        b.close()


def test_slice_series_names_match_python_schema():
    """The series the folder creates are EXACTLY the names the Python
    SliceRouter/SebulbaServing register — the ROUTE-PARITY prefix pin,
    checked executably."""
    from torchbeast_tpu.analysis import config as lint_config

    prefix = lint_config.SLICE_SERIES_PREFIX
    assert prefix == "inference.slice."
    from torchbeast_tpu.runtime.native import NativeTelemetryFolder
    from torchbeast_tpu.telemetry.metrics import MetricsRegistry

    batchers = [core.DynamicBatcher(batch_dim=1)]
    router = core.SliceRouter(slices=batchers)
    registry = MetricsRegistry()
    NativeTelemetryFolder(
        registry, slice_batchers=batchers, slice_router=router
    )
    names = set(registry.instruments())
    assert f"{prefix}0.requests" in names
    assert f"{prefix}0.depth" in names
    batchers[0].close()


# ---------------------------------------------------------------------------
# Continuous batching: shed accounting exactness


def test_continuous_shed_accounting_exact():
    """Admission armed + continuous=True: every request lands in exactly
    one of served/shed/expired — client-observed sheds equal the
    batcher's shed+expired, and served+shed+expired covers the total."""
    from torchbeast_tpu.runtime.errors import ShedError

    batcher = core.DynamicBatcher(
        batch_dim=1,
        minimum_batch_size=1,
        maximum_batch_size=4,
        timeout_ms=5,
        shed_max_queue_depth=2,
        continuous=True,
    )
    outcomes = {"served": 0, "shed": 0}
    lock = threading.Lock()

    def client(i):
        try:
            batcher.compute({
                "env": np.full((1, 1, 2), i, np.float32),
            })
            with lock:
                outcomes["served"] += 1
        except ShedError:
            with lock:
                outcomes["shed"] += 1

    def serve():
        it = iter(batcher)
        while True:
            try:
                batch = it.__next__()
            except StopIteration:
                return
            time.sleep(0.002)  # force queue buildup past the gate
            batch.set_outputs(batch.get_inputs())

    server = threading.Thread(target=serve, daemon=True)
    server.start()
    n = 64
    clients = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(n)
    ]
    for c in clients:
        c.start()
    for c in clients:
        c.join(30)
    batcher.close()
    server.join(5)
    tm = batcher.telemetry()
    # Exactness: the typed-shed count the CLIENTS saw is the gate's
    # shed+expired — nothing double-counted, nothing silently dropped.
    assert outcomes["shed"] == tm["shed"] + tm["expired"]
    assert outcomes["served"] + outcomes["shed"] == n
    assert tm["rows"] == outcomes["served"]
    assert tm["admitted"] == tm["rows"] + tm["expired"]
    # The load was engineered to actually shed (depth 2, slow serve).
    assert outcomes["shed"] > 0
    assert tm["rolled"] >= 0  # exposed; exercised in anger by the bench


def test_continuous_rolls_late_arrivals():
    """Directed roll: a request admitted while the serving thread holds
    an under-max batch rides the NEXT dispatch window (rolled counter)
    instead of waiting a full timeout behind a depth bound."""
    batcher = core.DynamicBatcher(
        batch_dim=1,
        minimum_batch_size=2,
        maximum_batch_size=8,
        timeout_ms=2000,
        continuous=True,
    )
    replies = []

    def client(i):
        replies.append(
            batcher.compute({"env": np.full((1, 1), i, np.float32)})
        )

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(4)
    ]
    threads[0].start()
    time.sleep(0.05)
    for t in threads[1:]:
        t.start()
    # All four requests complete well inside the 2s window: the batch
    # waits for min=2, then tops up whatever arrived meanwhile.
    batch = next(iter(batcher))
    got = len(batch)
    batch.set_outputs(batch.get_inputs())
    remaining = 4 - got
    while remaining > 0:
        batch = next(iter(batcher))
        remaining -= len(batch)
        batch.set_outputs(batch.get_inputs())
    for t in threads:
        t.join(10)
    assert len(replies) == 4
    tm = batcher.telemetry()
    assert tm["rows"] == 4
    batcher.close()


# ---------------------------------------------------------------------------
# Replica routing: lag stamping parity and degradation


def _serve_with_hooks(batcher, hooks):
    """Replica serving thread: ctx+annotate per batch, exactly like
    runtime.inference.inference_loop's serving_hooks path."""
    it = iter(batcher)
    while True:
        try:
            batch = it.__next__()
        except StopIteration:
            return
        _ctx, annotate = hooks.begin_batch()
        inputs = batch.get_inputs()
        outputs = {
            "action": np.zeros((1, len(batch)), np.int32),
        }
        if annotate is not None:
            annotate(outputs, len(batch))
        batch.set_outputs(outputs)
        _ = inputs


def _serve_plain(batcher):
    it = iter(batcher)
    while True:
        try:
            batch = it.__next__()
        except StopIteration:
            return
        batch.set_outputs({
            "action": np.zeros((1, len(batch)), np.int32),
        })


def _lag_stamp_through(kind, store, hooks, registry):
    """One request through the replica router of `kind`; returns
    (reply, replica_count, central_count)."""
    if kind == "native":
        central = core.DynamicBatcher(batch_dim=1)
        replica = core.DynamicBatcher(batch_dim=1)
        router = core.ReplicaRouter(central=central, replica=replica)
        router.set_serving(hooks.serving_ok())
    else:
        from torchbeast_tpu.runtime.queues import DynamicBatcher
        from torchbeast_tpu.serving import ReplicaRouter

        central = DynamicBatcher(batch_dim=1)
        replica = DynamicBatcher(batch_dim=1)
        router = ReplicaRouter(central, replica, hooks, registry=registry)
    threads = [
        threading.Thread(
            target=_serve_with_hooks, args=(replica, hooks), daemon=True
        ),
        threading.Thread(target=_serve_plain, args=(central,), daemon=True),
    ]
    for t in threads:
        t.start()
    reply = router.compute({"env": np.zeros((1, 1, 2), np.float32)})
    if kind == "native":
        tm = router.telemetry()
        counts = (tm["replica_requests"], tm["central_requests"])
    else:
        counts = (
            registry.counter("serving.replica_requests").value(),
            registry.counter("serving.central_requests").value(),
        )
    central.close()
    replica.close()
    for t in threads:
        t.join(5)
    return reply, counts


@pytest.mark.parametrize("lag", [0, 3])
def test_replica_lag_stamping_parity(lag):
    """The SAME snapshot store + hooks behind both routers: replies
    carry bit-identical policy_lag stamps and both count the request
    on the replica path."""
    from torchbeast_tpu.serving import PolicySnapshotStore
    from torchbeast_tpu.serving.replica import ReplicaServingHooks
    from torchbeast_tpu.telemetry.metrics import MetricsRegistry

    replies = {}
    for kind in ("native", "python"):
        registry = MetricsRegistry()
        store = PolicySnapshotStore(refresh_updates=1, registry=registry)
        store.publish(0, {"w": np.ones((2,), np.float32)})
        for v in range(1, lag + 1):
            store.note_update(v)
        assert store.lag() == lag
        hooks = ReplicaServingHooks(
            store, max_policy_lag=5, batch_dim=1, registry=registry
        )
        reply, (n_replica, n_central) = _lag_stamp_through(
            kind, store, hooks, registry
        )
        assert n_replica == 1 and n_central == 0, kind
        replies[kind] = reply
    native_stamp = np.asarray(replies["native"]["policy_lag"])
    python_stamp = np.asarray(replies["python"]["policy_lag"])
    assert native_stamp.dtype == python_stamp.dtype == np.int32
    np.testing.assert_array_equal(native_stamp, python_stamp)
    assert int(native_stamp.reshape(-1)[0]) == lag


def test_replica_degradation_parity():
    """Lag beyond budget: BOTH routers send the request to the central
    path (the native gate is the serving_ok flag pushed from the same
    hooks that gate the Python router)."""
    from torchbeast_tpu.serving import PolicySnapshotStore
    from torchbeast_tpu.serving.replica import ReplicaServingHooks
    from torchbeast_tpu.telemetry.metrics import MetricsRegistry

    for kind in ("native", "python"):
        registry = MetricsRegistry()
        store = PolicySnapshotStore(refresh_updates=1, registry=registry)
        store.publish(0, {"w": np.ones((2,), np.float32)})
        for v in range(1, 9):
            store.note_update(v)  # lag 8 > budget 5
        hooks = ReplicaServingHooks(
            store, max_policy_lag=5, batch_dim=1, registry=registry
        )
        reply, (n_replica, n_central) = _lag_stamp_through(
            kind, store, hooks, registry
        )
        assert n_replica == 0 and n_central == 1, kind
        # Central replies carry no stamp; the pool normalizes the
        # missing leaf to lag 0 on both runtimes (record_policy_lag).
        assert "policy_lag" not in reply


# ---------------------------------------------------------------------------
# Remote replica tier behind the NATIVE router: proxy_loop bridges a C++
# replica batcher onto a replica host over the wire stack.


def test_proxy_loop_bridges_native_batcher_to_remote():
    from torchbeast_tpu.serving.replica_server import (
        RemoteReplicaBatcher,
        RemoteSnapshotPublisher,
        ReplicaServer,
        proxy_loop,
    )
    from torchbeast_tpu.telemetry.metrics import MetricsRegistry

    def act_fn(params, inputs):
        n = np.asarray(inputs["env"]).shape[1]
        w = float(np.asarray(params["w"]).reshape(-1)[0])
        return {"action": np.full((1, n), int(w), np.int32)}

    path = os.path.join(tempfile.mkdtemp(), "rs_native")
    address = f"unix:{path}"
    server = ReplicaServer(
        act_fn, address, batch_dim=1, timeout_ms=5,
        registry=MetricsRegistry(),
    )
    server.start()
    publisher = RemoteSnapshotPublisher(address, timeout_s=10)
    remote = RemoteReplicaBatcher(address, timeout_s=10)
    central = core.DynamicBatcher(batch_dim=1)
    replica = core.DynamicBatcher(batch_dim=1)
    router = core.ReplicaRouter(central=central, replica=replica)
    proxy = threading.Thread(
        target=proxy_loop, args=(replica, remote), daemon=True
    )
    proxy.start()
    try:
        publisher.publish(0, {"w": np.full((1,), 6.0, np.float32)})
        router.set_serving(True)
        out = router.compute({"env": np.zeros((1, 1, 3), np.float32)})
        assert int(np.asarray(out["action"]).reshape(-1)[0]) == 6
        stamp = np.asarray(out["policy_lag"])
        assert stamp.dtype == np.int32
        assert int(stamp.reshape(-1)[0]) == 0
        tm = router.telemetry()
        assert tm["replica_requests"] == 1 and tm["central_requests"] == 0
    finally:
        central.close()
        replica.close()
        proxy.join(5)
        remote.close()
        publisher.close()
        server.stop()
