"""Transformer policy: shapes, the cache-consistency invariant (batch
forward == step-by-step forward with carried KV cache), and episode-
boundary isolation."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchbeast_tpu.models import TransformerNet, create_model

T, B, A = 6, 2, 4
FRAME = (8, 8, 1)


def make_inputs(seed=0, t=T, done=None):
    rng = np.random.default_rng(seed)
    if done is None:
        done = np.zeros((t, B), bool)
    return {
        "frame": jnp.asarray(
            rng.integers(0, 256, (t, B) + FRAME, dtype=np.uint8)
        ),
        "reward": jnp.asarray(rng.standard_normal((t, B)).astype(np.float32)),
        "done": jnp.asarray(done),
        "last_action": jnp.asarray(rng.integers(0, A, (t, B))),
    }


def init_model(**kwargs):
    model = TransformerNet(num_actions=A, **kwargs)
    inputs = make_inputs()
    state = model.initial_state(B)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "action": jax.random.PRNGKey(1)},
        inputs,
        state,
    )
    return model, params


def test_shapes_and_state():
    model, params = init_model()
    inputs = make_inputs()
    state = model.initial_state(B)
    out, new_state = model.apply(params, inputs, state, sample_action=False)
    assert out.policy_logits.shape == (T, B, A)
    assert out.baseline.shape == (T, B)
    assert len(new_state) == model.num_layers
    k, v, valid = new_state[0]
    assert k.shape == (model.memory_len, B, model.num_heads,
                       model.d_model // model.num_heads)
    assert valid.shape == (model.memory_len, B)
    # After a done-free unroll from empty cache, exactly T entries valid.
    assert float(np.asarray(valid).sum()) == T * B


def _stepwise_logits(model, params, inputs, state, t_total):
    logits = []
    for t in range(t_total):
        sub = {k: v[t : t + 1] for k, v in inputs.items()}
        out, state = model.apply(params, sub, state, sample_action=False)
        logits.append(out.policy_logits[0])
    return np.stack(logits), state


def test_batch_forward_matches_stepwise_with_cache():
    """The defining invariant: running T steps at once equals running one
    step at a time carrying the KV cache."""
    model, params = init_model()
    inputs = make_inputs(seed=3)
    state = model.initial_state(B)
    full, _ = model.apply(params, inputs, state, sample_action=False)
    logits, _ = _stepwise_logits(model, params, inputs, state, T)
    np.testing.assert_allclose(
        logits, np.asarray(full.policy_logits), rtol=2e-4, atol=2e-5
    )


def test_batch_matches_stepwise_with_small_memory_and_full_cache():
    """The hard regime: memory_len < T AND a pre-filled cache — the batch
    (learner) forward must model the stepwise eviction exactly, or the
    behavior/target logit pairing silently breaks in training."""
    model, params = init_model(memory_len=4)  # < T = 6
    warmup = make_inputs(seed=11)
    inputs = make_inputs(seed=12)

    state0 = model.initial_state(B)
    # Fill the cache with a warmup unroll (both paths identically).
    _, batch_state = model.apply(params, warmup, state0, sample_action=False)
    full, _ = model.apply(params, inputs, batch_state, sample_action=False)

    _, step_state = model.apply(params, warmup, state0, sample_action=False)
    logits, _ = _stepwise_logits(model, params, inputs, step_state, T)
    np.testing.assert_allclose(
        logits, np.asarray(full.policy_logits), rtol=2e-4, atol=2e-5
    )


def test_stepwise_state_equals_batch_state():
    """The cache written by one batch forward must equal the cache from T
    stepwise forwards (it is the next rollout's initial_agent_state)."""
    model, params = init_model(memory_len=4)
    inputs = make_inputs(seed=13)
    state0 = model.initial_state(B)
    _, batch_state = model.apply(params, inputs, state0, sample_action=False)
    s = state0
    for t in range(T):
        sub = {k: v[t : t + 1] for k, v in inputs.items()}
        _, s = model.apply(params, sub, s, sample_action=False)
    for (bk, bv, bval), (sk, sv, sval) in zip(batch_state, s):
        np.testing.assert_allclose(
            np.asarray(bk), np.asarray(sk), rtol=2e-4, atol=2e-5
        )
        np.testing.assert_allclose(
            np.asarray(bv), np.asarray(sv), rtol=2e-4, atol=2e-5
        )
        np.testing.assert_array_equal(np.asarray(bval), np.asarray(sval))


def test_episode_boundary_isolates_past():
    model, params = init_model()
    done = np.zeros((T, B), bool)
    d = 3
    done[d] = True
    inputs = make_inputs(seed=5, done=done)
    state = model.initial_state(B)
    out1, _ = model.apply(params, inputs, state, sample_action=False)

    # Perturb pre-boundary frames: post-boundary outputs must not move.
    frames2 = np.asarray(inputs["frame"]).copy()
    frames2[0] = 0
    frames2[1] = 255
    inputs2 = {**inputs, "frame": jnp.asarray(frames2)}
    out2, _ = model.apply(params, inputs2, state, sample_action=False)
    np.testing.assert_allclose(
        np.asarray(out1.policy_logits)[d:],
        np.asarray(out2.policy_logits)[d:],
        rtol=1e-5, atol=1e-6,
    )
    assert not np.allclose(
        np.asarray(out1.policy_logits)[:d],
        np.asarray(out2.policy_logits)[:d],
    )


def test_cache_invalidated_by_done():
    """A done in unroll k+1 must hide unroll k's cache from later steps."""
    model, params = init_model()
    state = model.initial_state(B)
    # Unroll 1 fills the cache (distinct content per variant).
    u1a = make_inputs(seed=7)
    u1b = make_inputs(seed=8)
    _, state_a = model.apply(params, u1a, state, sample_action=False)
    _, state_b = model.apply(params, u1b, state, sample_action=False)

    # Unroll 2 starts with done at slot 0: the old cache is invisible.
    done = np.zeros((T, B), bool)
    done[0] = True
    u2 = make_inputs(seed=9, done=done)
    out_a, _ = model.apply(params, u2, state_a, sample_action=False)
    out_b, _ = model.apply(params, u2, state_b, sample_action=False)
    np.testing.assert_allclose(
        np.asarray(out_a.policy_logits),
        np.asarray(out_b.policy_logits),
        rtol=1e-5, atol=1e-6,
    )


def test_registry():
    assert isinstance(create_model("transformer", A), TransformerNet)


# ---- sequence-parallel (ring attention) training path ----


def _seq_mesh(n):
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:n]), ("seq",))


def _ring_model(dense_model):
    """Same architecture/params, ring path active over an 8-way seq mesh."""
    return TransformerNet(
        num_actions=dense_model.num_actions,
        num_layers=dense_model.num_layers,
        d_model=dense_model.d_model,
        num_heads=dense_model.num_heads,
        memory_len=dense_model.memory_len,
        mesh=_seq_mesh(8),
    )


@pytest.mark.slow
def test_ring_path_matches_dense_forward_and_state():
    """The ring formulation (band + segments + rel-bias + cache leg,
    online-merged) must reproduce the dense path bit-for-bit-ish — with a
    pre-filled cache, mid-unroll dones, and memory_len < T so the band
    actually clips."""
    t = 16  # divisible by the 8-way mesh
    model, params = init_model(memory_len=8)
    warm = make_inputs(seed=21, t=t)
    done = np.zeros((t, B), bool)
    done[5] = True
    done[11, 0] = True
    inputs = make_inputs(seed=22, t=t, done=done)

    state0 = model.initial_state(B)
    _, cache = model.apply(params, warm, state0, sample_action=False)
    dense_out, dense_state = model.apply(
        params, inputs, cache, sample_action=False
    )

    ring = _ring_model(model)
    ring_out, ring_state = ring.apply(params, inputs, cache,
                                      sample_action=False)

    np.testing.assert_allclose(
        np.asarray(ring_out.policy_logits),
        np.asarray(dense_out.policy_logits),
        rtol=2e-4, atol=2e-5,
    )
    np.testing.assert_allclose(
        np.asarray(ring_out.baseline),
        np.asarray(dense_out.baseline),
        rtol=2e-4, atol=2e-5,
    )
    for (dk, dv, dval), (rk, rv, rval) in zip(dense_state, ring_state):
        np.testing.assert_allclose(np.asarray(rk), np.asarray(dk),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(rv), np.asarray(dv),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_array_equal(np.asarray(rval), np.asarray(dval))


@pytest.mark.slow
def test_ring_path_gradients_match_dense():
    t = 8
    model, params = init_model(memory_len=4)
    inputs = make_inputs(seed=31, t=t)
    state = model.initial_state(B)
    ring = _ring_model(model)

    def loss(m):
        def f(p):
            out, _ = m.apply(p, inputs, state, sample_action=False)
            return jnp.sum(out.policy_logits ** 2) + jnp.sum(
                out.baseline ** 2
            )
        return f

    g_dense = jax.grad(loss(model))(params)
    g_ring = jax.grad(loss(ring))(params)
    flat_d, _ = jax.tree_util.tree_flatten(g_dense)
    flat_r, _ = jax.tree_util.tree_flatten(g_ring)
    for gd, gr in zip(flat_d, flat_r):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), rtol=2e-3, atol=2e-4
        )


def test_ring_path_falls_back_to_dense_for_short_t():
    """Acting at T=1 must use the dense path (T not divisible by the mesh)
    with identical params — one model serves learner and actor."""
    model, params = init_model()
    ring = _ring_model(model)
    inputs = make_inputs(seed=41, t=1)
    state = model.initial_state(B)
    out_d, _ = model.apply(params, inputs, state, sample_action=False)
    out_r, _ = ring.apply(params, inputs, state, sample_action=False)
    np.testing.assert_allclose(
        np.asarray(out_r.policy_logits), np.asarray(out_d.policy_logits),
        rtol=1e-6,
    )


@pytest.mark.slow
def test_zigzag_ring_path_matches_dense():
    """Zig-zag-scheduled sequence-parallel training path: same numerics
    as dense, with cache + dones + band clipping (T=32 over the 8-way
    mesh -> 16 chunks of 2)."""
    t = 32
    model, params = init_model(memory_len=8)
    warm = make_inputs(seed=51, t=t)
    done = np.zeros((t, B), bool)
    done[9] = True
    done[23, 1] = True
    inputs = make_inputs(seed=52, t=t, done=done)

    state0 = model.initial_state(B)
    _, cache = model.apply(params, warm, state0, sample_action=False)
    dense_out, dense_state = model.apply(
        params, inputs, cache, sample_action=False
    )

    zig = TransformerNet(
        num_actions=model.num_actions,
        num_layers=model.num_layers,
        d_model=model.d_model,
        num_heads=model.num_heads,
        memory_len=model.memory_len,
        mesh=_seq_mesh(8),
        ring_schedule="zigzag",
    )
    zig_out, zig_state = zig.apply(params, inputs, cache,
                                   sample_action=False)
    np.testing.assert_allclose(
        np.asarray(zig_out.policy_logits),
        np.asarray(dense_out.policy_logits),
        rtol=2e-4, atol=2e-5,
    )
    for (dk, dv, dval), (zk, zv, zval) in zip(dense_state, zig_state):
        np.testing.assert_allclose(np.asarray(zk), np.asarray(dk),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(zv), np.asarray(dv),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_array_equal(np.asarray(zval), np.asarray(dval))


@pytest.mark.slow
def test_zigzag_ring_path_gradients_match_dense():
    t = 16
    model, params = init_model(memory_len=4)
    inputs = make_inputs(seed=61, t=t)
    state = model.initial_state(B)
    zig = TransformerNet(
        num_actions=model.num_actions,
        num_layers=model.num_layers,
        d_model=model.d_model,
        num_heads=model.num_heads,
        memory_len=model.memory_len,
        mesh=_seq_mesh(8),
        ring_schedule="zigzag",
    )

    def loss(m):
        def f(p):
            out, _ = m.apply(p, inputs, state, sample_action=False)
            return jnp.sum(out.policy_logits ** 2) + jnp.sum(
                out.baseline ** 2
            )
        return f

    g_dense = jax.grad(loss(model))(params)
    g_zig = jax.grad(loss(zig))(params)
    flat_d, _ = jax.tree_util.tree_flatten(g_dense)
    flat_z, _ = jax.tree_util.tree_flatten(g_zig)
    for gd, gz in zip(flat_d, flat_z):
        np.testing.assert_allclose(
            np.asarray(gz), np.asarray(gd), rtol=2e-3, atol=2e-4
        )


@pytest.mark.slow
def test_remat_update_matches_non_remat():
    """--transformer_remat: per-block rematerialization must be a pure
    memory/recompute trade — outputs and one full update identical to
    the non-remat model with the same params (incl. the MoE block whose
    sown aux loss must survive the lifted transform)."""
    import numpy as np

    from torchbeast_tpu import learner as learner_lib

    T, B, A = 4, 3, 5
    rng = np.random.default_rng(21)
    batch = {
        "frame": rng.integers(0, 256, (T + 1, B, 4, 4, 1), dtype=np.uint8),
        "reward": rng.standard_normal((T + 1, B)).astype(np.float32),
        "done": rng.random((T + 1, B)) < 0.2,
        "episode_return": rng.standard_normal((T + 1, B)).astype(
            np.float32
        ),
        "episode_step": rng.integers(0, 9, (T + 1, B)).astype(np.int32),
        "last_action": rng.integers(0, A, (T + 1, B)).astype(np.int32),
        "action": rng.integers(0, A, (T + 1, B)).astype(np.int32),
        "policy_logits": rng.standard_normal((T + 1, B, A)).astype(
            np.float32
        ),
        "baseline": rng.standard_normal((T + 1, B)).astype(np.float32),
    }
    kwargs = dict(
        num_actions=A, num_layers=2, d_model=16, num_heads=2,
        memory_len=4, num_experts=4,
    )
    plain = create_model("transformer", **kwargs)
    remat = create_model("transformer", remat=True, **kwargs)
    state = plain.initial_state(B)
    params = plain.init(
        {"params": jax.random.PRNGKey(40), "action": jax.random.PRNGKey(41)},
        batch,
        state,
    )
    # Identical param trees: remat is a lifted transform, not a rewrite.
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        params,
        remat.init(
            {"params": jax.random.PRNGKey(40),
             "action": jax.random.PRNGKey(41)},
            batch,
            state,
        ),
    )
    hp = learner_lib.HParams(batch_size=B, unroll_length=T)
    optimizer = learner_lib.make_optimizer(hp)
    step_p = learner_lib.make_update_step(plain, optimizer, hp, donate=False)
    step_r = learner_lib.make_update_step(remat, optimizer, hp, donate=False)
    p_p, _, s_p = step_p(params, optimizer.init(params), batch, state)
    p_r, _, s_r = step_r(params, optimizer.init(params), batch, state)
    np.testing.assert_allclose(
        float(s_r["total_loss"]), float(s_p["total_loss"]), rtol=1e-6
    )
    np.testing.assert_allclose(
        float(s_r["aux_loss"]), float(s_p["aux_loss"]), rtol=1e-6
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        ),
        p_r,
        p_p,
    )
