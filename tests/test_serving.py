"""Serving-tier contracts (ISSUE 14): admission control + load
shedding, the shed/retry no-lost-rollout contract, snapshotted policy
replicas, and the policy-lag recording/degradation machinery.

The load-bearing pins:
- a shed is NEVER a lost rollout: a deliberately wedged batcher sheds,
  the actor retries with backoff, and the rollout stream completes
  bit-identical to the unshed run;
- `policy_lag` recorded in a reply matches the snapshot version that
  ACTUALLY served it (version-skew pin);
- serving.resubmitted == serving.shed + serving.expired, exactly.
"""

import os
import tempfile
import threading
import time

import numpy as np
import pytest

from torchbeast_tpu import telemetry
from torchbeast_tpu.envs import CountingEnv
from torchbeast_tpu.resilience.supervisor import PipelineHealth
from torchbeast_tpu.runtime.actor_pool import ActorPool
from torchbeast_tpu.runtime.env_server import EnvServer
from torchbeast_tpu.runtime.errors import ShedError
from torchbeast_tpu.runtime.inference import inference_loop
from torchbeast_tpu.runtime.native import import_native
from torchbeast_tpu.runtime.queues import BatchingQueue, DynamicBatcher
from torchbeast_tpu.serving import (
    AdmissionController,
    PolicySnapshotStore,
    ReplicaRouter,
    ReplicaServingHooks,
)

EPISODE_LEN = 5
T = 3


# ---------------------------------------------------------------------------
# AdmissionController unit surface


def _registry():
    return telemetry.MetricsRegistry()


def test_admission_depth_gate_sheds():
    reg = _registry()
    adm = AdmissionController(
        deadline_ms=1000, max_queue_depth=2, registry=reg
    )
    assert adm.admit(0) is not None  # absolute deadline returned
    assert adm.admit(1) is not None
    with pytest.raises(ShedError) as e:
        adm.admit(2)
    assert not e.value.expired
    assert adm.counts() == {"admitted": 2, "shed": 1, "expired": 0}


def test_admission_deadline_disarmed_returns_none():
    adm = AdmissionController(
        deadline_ms=0, max_queue_depth=4, registry=_registry()
    )
    assert adm.deadline_s is None
    assert adm.admit(0) is None


def test_admission_split_expired_and_slo_gauges():
    reg = _registry()
    adm = AdmissionController(deadline_ms=50, registry=reg)
    now = time.perf_counter()
    deadlines = [now - 1.0, now + 10.0, now - 0.5, None]
    enqueued = [now - 1.1, now - 0.01, now - 0.6, now - 0.2]
    live, expired = adm.split_expired(deadlines, enqueued)
    assert live == [1, 3] and expired == [0, 2]
    counts = adm.counts()
    assert counts["expired"] == 2
    # Queue-delay histogram observed for EVERY dequeued request; the
    # p99-vs-SLO gauges refresh every N splits (strictly throttled —
    # refreshed explicitly here).
    assert reg.histogram("serving.queue_delay_s").count == 4
    adm.refresh_gauges()
    p99 = reg.gauge("serving.queue_delay_p99_s").value()
    assert p99 > 0
    assert reg.gauge("serving.slo_ratio").value() == pytest.approx(
        p99 / 0.05
    )
    err = adm.expired_error()
    assert isinstance(err, ShedError) and err.expired


def test_batcher_sheds_at_depth_and_expires_in_queue():
    """End-to-end through the Python DynamicBatcher: depth shed at
    compute(), deadline expiry at dequeue, live rows still served."""
    reg = _registry()
    adm = AdmissionController(
        deadline_ms=80, max_queue_depth=2, registry=reg
    )
    batcher = DynamicBatcher(
        batch_dim=1, maximum_batch_size=8, timeout_ms=10, admission=adm
    )
    results = {}

    def submit(name):
        try:
            results[name] = batcher.compute(
                {"x": np.full((1, 1), ord(name), np.float32)}
            )
        except ShedError as e:
            results[name] = e

    threads = [
        threading.Thread(target=submit, args=(n,), daemon=True)
        for n in "ab"
    ]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 2
    while batcher.size() < 2 and time.monotonic() < deadline:
        time.sleep(0.005)
    # Depth gate: the third concurrent request sheds immediately.
    with pytest.raises(ShedError):
        batcher.compute({"x": np.zeros((1, 1), np.float32)})
    # Let both queued requests rot past their deadline, then start the
    # consumer: it fails the stale two as expired and loops back to
    # blocking (the whole batch expired). A fresh request — admitted
    # now that the expired ones were drained — is served normally.
    time.sleep(0.15)
    served = {}

    def consume():
        batch = next(batcher)
        served["rows"] = len(batch)
        batch.set_outputs({"y": np.zeros((1, len(batch)), np.float32)})

    consumer = threading.Thread(target=consume, daemon=True)
    consumer.start()
    deadline = time.monotonic() + 2
    while batcher.size() > 0 and time.monotonic() < deadline:
        time.sleep(0.005)  # expired pair drained by the consumer
    threading.Thread(target=submit, args=("c",), daemon=True).start()
    consumer.join(2)
    assert served["rows"] == 1  # only the fresh request was served
    for t in threads:
        t.join(2)
    assert isinstance(results["a"], ShedError) and results["a"].expired
    assert isinstance(results["b"], ShedError) and results["b"].expired
    counts = adm.counts()
    assert counts == {"admitted": 3, "shed": 1, "expired": 2}
    batcher.close()


# ---------------------------------------------------------------------------
# Shed/retry contract: a shed is never a lost rollout


class CountingPolicyServer:
    """The deterministic counting 'model' from test_env_server: state +=
    1 per forward, reset where done — policy-independent of params, so
    shed-and-resubmitted steps reproduce the unshed run exactly."""

    def __call__(self, env_outputs, agent_state, batch_size):
        done = np.asarray(env_outputs["done"])  # [1, B]
        state = np.where(done, 0, np.asarray(agent_state)) + 1
        outputs = {
            "action": np.zeros_like(done, dtype=np.int32),
            "policy_logits": state[..., None].astype(np.float32),
            "baseline": state.astype(np.float32),
        }
        return outputs, state


def _start_counting_server(path):
    server = EnvServer(
        lambda: CountingEnv(episode_length=EPISODE_LEN), f"unix:{path}"
    )
    server.start()
    deadline = time.monotonic() + 5
    while not os.path.exists(path):
        if time.monotonic() > deadline:
            raise TimeoutError("server did not bind")
        time.sleep(0.01)
    return server


def _collect_rollouts(address, admission=None, wedge=None,
                      num_rollouts=5):
    """Run one actor against the counting server; return the rollout
    items. `wedge` (a threading.Event) stalls the serving thread while
    set — with `admission` armed that manufactures real sheds."""
    learner_queue = BatchingQueue(
        batch_dim=1, minimum_batch_size=1, maximum_batch_size=1
    )
    batcher = DynamicBatcher(
        batch_dim=1, timeout_ms=20, admission=admission
    )

    def throttle():
        while wedge is not None and wedge.is_set():
            time.sleep(0.01)

    inf_thread = threading.Thread(
        target=inference_loop,
        args=(batcher, CountingPolicyServer(), 8),
        kwargs={"throttle_fn": throttle if wedge is not None else None},
        daemon=True,
    )
    inf_thread.start()

    pool = ActorPool(
        unroll_length=T,
        learner_queue=learner_queue,
        inference_batcher=batcher,
        env_server_addresses=[address],
        initial_agent_state=np.zeros((1, 1), np.int64),
    )
    pool_thread = threading.Thread(target=pool.run, daemon=True)
    pool_thread.start()

    items = []
    for item in learner_queue:
        items.append(item)
        if wedge is not None and len(items) == 2:
            # Wedge mid-stream: the actor's next requests expire in the
            # queue (or shed at depth) and must be re-submitted.
            wedge.set()
            time.sleep(0.35)
            wedge.clear()
        if len(items) >= num_rollouts:
            break
    batcher.close()
    learner_queue.close()
    pool_thread.join(5)
    return items


@pytest.mark.slow
def test_shed_retry_rollouts_bit_identical():
    """THE no-lost-rollout pin: a wedged batcher sheds mid-run; the
    actor re-submits; the resulting rollout stream is bit-identical to
    the unshed run, and resubmitted == shed + expired exactly."""
    reg = telemetry.get_registry()
    base = int(reg.counter("serving.resubmitted").value())

    tmp = tempfile.mkdtemp()
    path_a = os.path.join(tmp, "srv_a")
    server = _start_counting_server(path_a)
    try:
        clean = _collect_rollouts(f"unix:{path_a}")
    finally:
        server.stop()

    path_b = os.path.join(tmp, "srv_b")
    server = _start_counting_server(path_b)
    adm = AdmissionController(
        deadline_ms=60, max_queue_depth=2, registry=reg
    )
    wedge = threading.Event()
    try:
        shed = _collect_rollouts(
            f"unix:{path_b}", admission=adm, wedge=wedge
        )
    finally:
        server.stop()

    counts = adm.counts()
    shed_total = counts["shed"] + counts["expired"]
    assert shed_total > 0, "the wedge produced no sheds; test is vacuous"
    resubmitted = int(reg.counter("serving.resubmitted").value()) - base
    assert resubmitted == shed_total

    assert len(clean) == len(shed)
    for a, b in zip(clean, shed):
        for key in a["batch"]:
            np.testing.assert_array_equal(
                a["batch"][key], b["batch"][key], err_msg=key
            )
        np.testing.assert_array_equal(
            np.asarray(a["initial_agent_state"]),
            np.asarray(b["initial_agent_state"]),
        )


# ---------------------------------------------------------------------------
# PolicySnapshotStore


def test_snapshot_store_bf16_roundtrip_restores_dtypes():
    import jax.numpy as jnp

    store = PolicySnapshotStore(4, registry=_registry())
    params = {
        "w": np.arange(8, dtype=np.float32) / 7.0,
        "n": np.arange(4, dtype=np.int32),
        "h": np.ones(3, dtype=jnp.bfloat16),
    }
    assert store.latest() is None
    store.note_update(0)
    store.publish(0, params)
    version, restored = store.latest()
    assert version == 0
    assert restored["w"].dtype == np.float32  # restored, bf16-rounded
    assert restored["n"].dtype == np.int32
    np.testing.assert_array_equal(np.asarray(restored["n"]), params["n"])
    assert restored["h"].dtype == jnp.bfloat16
    # f32 values round-trip through bf16 rounding (not bit-exact, but
    # within one bf16 ulp).
    np.testing.assert_allclose(
        np.asarray(restored["w"]), params["w"], rtol=1e-2
    )
    # The cache is per-version: same object back on a second read.
    assert store.latest()[1] is restored


def test_snapshot_store_refresh_due_and_failure_hook():
    reg = _registry()
    store = PolicySnapshotStore(4, registry=reg)
    assert store.note_update(0)  # nothing published yet: due
    store.publish(0, {"w": np.zeros(2, np.float32)})
    assert store.lag() == 0
    assert not store.note_update(3)  # 3 < refresh period
    assert store.note_update(4)  # due again
    store.fail_next_refreshes(2)
    assert not store.publish(4, {"w": np.zeros(2, np.float32)})
    assert store.version == 0 and store.lag() == 4
    assert store.note_update(5)  # STILL due — the drop retries
    assert not store.publish(5, {"w": np.zeros(2, np.float32)})
    assert store.note_update(6)
    assert store.publish(6, {"w": np.zeros(2, np.float32)})
    assert store.version == 6 and store.lag() == 0
    assert (
        int(reg.counter("serving.snapshot_refresh_failures").value()) == 2
    )


# ---------------------------------------------------------------------------
# Replica hooks: lag recording + degradation


def test_replica_lag_matches_snapshot_actually_used():
    """Version-skew pin: the policy_lag stamped into a reply equals
    head - (the version of the params handed out for THAT batch) —
    checked by encoding the version into the params themselves."""
    reg = _registry()
    store = PolicySnapshotStore(2, registry=reg)
    hooks = ReplicaServingHooks(
        store, max_policy_lag=10, registry=reg, batch_dim=1
    )
    store.note_update(3)
    store.publish(3, {"v": np.full(1, 3.0, np.float32)})
    store.note_update(5)  # head runs ahead: lag 2

    (params, _key), annotate = hooks.begin_batch()
    assert float(np.asarray(params["v"])[0]) == 3.0
    out = annotate({"action": np.zeros((1, 4), np.int32)}, 4)
    assert out["policy_lag"].shape == (1, 4)
    assert out["policy_lag"].dtype == np.int32
    assert (out["policy_lag"] == 5 - 3).all()

    # A fresh publish drops the lag for the NEXT batch atomically.
    store.note_update(6)
    store.publish(6, {"v": np.full(1, 6.0, np.float32)})
    (params, _key), annotate = hooks.begin_batch()
    assert float(np.asarray(params["v"])[0]) == 6.0
    out = annotate({"action": np.zeros((1, 2), np.int32)}, 2)
    assert (out["policy_lag"] == 0).all()


def test_replica_degrades_and_recovers_via_health():
    reg = _registry()
    store = PolicySnapshotStore(2, registry=reg)
    health = PipelineHealth(registry=reg)
    hooks = ReplicaServingHooks(
        store, max_policy_lag=3, health=health, registry=reg
    )
    assert not hooks.serving_ok()  # nothing published yet
    assert health.state_name == "DEGRADED"
    store.note_update(0)
    store.publish(0, {"w": np.zeros(1, np.float32)})
    assert hooks.serving_ok()
    assert health.state_name == "HEALTHY"  # first publish recovers it

    store.note_update(4)  # lag 4 > budget 3
    assert not hooks.serving_ok()
    assert health.state_name == "DEGRADED"
    store.publish(4, {"w": np.zeros(1, np.float32)})
    assert hooks.serving_ok()
    assert health.state_name == "HEALTHY"
    assert int(reg.counter("serving.replica_degradations").value()) == 2


def test_replica_router_routes_by_health():
    reg = _registry()
    store = PolicySnapshotStore(2, registry=reg)
    hooks = ReplicaServingHooks(store, max_policy_lag=2, registry=reg)

    class FakeBatcher:
        def __init__(self, tag):
            self.tag, self.calls = tag, 0

        def compute(self, inputs, trace=None):
            self.calls += 1
            return {"served_by": self.tag}

        def size(self):
            return 0

        def is_closed(self):
            return False

    central, replica = FakeBatcher("central"), FakeBatcher("replica")
    router = ReplicaRouter(central, replica, hooks, registry=reg)
    # No snapshot yet: central.
    assert router.compute({})["served_by"] == "central"
    store.note_update(0)
    store.publish(0, {"w": np.zeros(1, np.float32)})
    assert router.compute({})["served_by"] == "replica"
    store.note_update(10)  # lag blows the budget: back to central
    assert router.compute({})["served_by"] == "central"
    assert int(reg.counter("serving.replica_requests").value()) == 1
    assert int(reg.counter("serving.central_requests").value()) == 2


def test_replica_router_falls_back_on_replica_failure():
    from torchbeast_tpu.runtime.queues import AsyncError

    reg = _registry()
    store = PolicySnapshotStore(2, registry=reg)
    hooks = ReplicaServingHooks(store, max_policy_lag=2, registry=reg)
    store.note_update(0)
    store.publish(0, {"w": np.zeros(1, np.float32)})

    class DeadReplica:
        def compute(self, inputs, trace=None):
            raise AsyncError("replica thread died")

        def size(self):
            return 0

        def is_closed(self):
            return False

    class Central:
        def compute(self, inputs, trace=None):
            return {"served_by": "central"}

        def size(self):
            return 0

        def is_closed(self):
            return False

    router = ReplicaRouter(Central(), DeadReplica(), hooks, registry=reg)
    assert router.compute({})["served_by"] == "central"

    class SheddingReplica(DeadReplica):
        def compute(self, inputs, trace=None):
            raise ShedError("over capacity")

    router = ReplicaRouter(
        Central(), SheddingReplica(), hooks, registry=reg
    )
    # Sheds keep their retry contract — NOT swallowed by the fallback.
    with pytest.raises(ShedError):
        router.compute({})


# ---------------------------------------------------------------------------
# Replica serving end-to-end through inference_loop (legacy act path)


def test_replica_serving_stamps_lag_into_reply():
    """inference_loop + serving_hooks: the reply's policy_lag leaf
    matches the snapshot served, end to end through the batcher."""
    reg = _registry()
    store = PolicySnapshotStore(2, registry=reg)
    hooks = ReplicaServingHooks(
        store, max_policy_lag=10, registry=reg, batch_dim=1
    )
    store.note_update(7)
    store.publish(7, {"v": np.full(1, 7.0, np.float32)})
    store.note_update(9)  # lag 2 at serve time

    batcher = DynamicBatcher(batch_dim=1, timeout_ms=10)

    def act_fn(env_outputs, agent_state, batch_size, ctx):
        params, _key = ctx
        value = float(np.asarray(params["v"])[0])
        done = np.asarray(env_outputs["done"])
        outputs = {
            "action": np.zeros_like(done, dtype=np.int32),
            "policy_logits": np.full(
                done.shape + (2,), value, np.float32
            ),
            "baseline": np.full(done.shape, value, np.float32),
        }
        return outputs, np.asarray(agent_state)

    thread = threading.Thread(
        target=inference_loop,
        args=(batcher, act_fn, 8),
        kwargs={"serving_hooks": hooks},
        daemon=True,
    )
    thread.start()
    reply = batcher.compute({
        "env": {
            "frame": np.zeros((1, 1, 2, 2), np.uint8),
            "reward": np.zeros((1, 1), np.float32),
            "done": np.zeros((1, 1), bool),
            "last_action": np.zeros((1, 1), np.int32),
        },
        "agent_state": np.zeros((1, 1), np.int64),
    })
    batcher.close()
    thread.join(5)
    out = reply["outputs"]
    # The baseline (params value) and the lag must describe the SAME
    # snapshot: params v7 served at head 9 -> lag 2.
    assert float(out["baseline"][0, 0]) == 7.0
    assert out["policy_lag"].shape == (1, 1)
    assert int(out["policy_lag"][0, 0]) == 2


# ---------------------------------------------------------------------------
# Native twin (when built): shed protocol across the GIL boundary


needs_native = pytest.mark.skipif(
    import_native() is None, reason="_tbt_core not built"
)


@needs_native
def test_native_api_version_and_shed_type():
    core = import_native()
    from torchbeast_tpu.runtime.native import REQUIRED_API_VERSION, gap_reason

    assert getattr(core, "API_VERSION", 0) >= REQUIRED_API_VERSION
    assert gap_reason() is None
    # One except-clause catches sheds from either runtime.
    assert issubclass(core.ShedError, ShedError)
    assert issubclass(core.ShedError, core.AsyncError)


@needs_native
def test_native_batcher_sheds_at_depth_and_expires():
    core = import_native()
    batcher = core.DynamicBatcher(
        batch_dim=1, maximum_batch_size=8, timeout_ms=10,
        shed_max_queue_depth=2, request_deadline_ms=80.0,
    )
    results = {}

    def submit(name):
        try:
            results[name] = batcher.compute(
                {"x": np.full((1, 1), float(ord(name)), np.float32)}
            )
        except Exception as e:  # noqa: BLE001
            results[name] = e

    threads = [
        threading.Thread(target=submit, args=(n,), daemon=True)
        for n in "ab"
    ]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 2
    while batcher.size() < 2 and time.monotonic() < deadline:
        time.sleep(0.005)
    with pytest.raises(core.ShedError):
        batcher.compute({"x": np.zeros((1, 1), np.float32)})
    time.sleep(0.15)  # let the queued two expire
    served = {}

    def consume():
        batch = next(iter(batcher))
        served["rows"] = len(batch)
        batch.set_outputs({"y": np.zeros((1, len(batch)), np.float32)})

    consumer = threading.Thread(target=consume, daemon=True)
    consumer.start()
    deadline = time.monotonic() + 2
    while batcher.size() > 0 and time.monotonic() < deadline:
        time.sleep(0.005)  # expired pair drained by the consumer
    threading.Thread(target=submit, args=("c",), daemon=True).start()
    consumer.join(2)
    assert served["rows"] == 1
    for t in threads:
        t.join(2)
    assert isinstance(results["a"], ShedError)
    assert isinstance(results["b"], ShedError)
    tm = batcher.telemetry()
    assert tm["admitted"] == 3
    assert tm["shed"] == 1
    assert tm["expired"] == 2
    assert tm["queue_delay_s"]["count"] >= 3
    batcher.close()


# ---------------------------------------------------------------------------
# Remote replica serving (ISSUE 16): the replica tier over the wire/shm
# transport stack — same hooks, same stamps, other side of a socket.


class TestReplicaServer:
    @staticmethod
    def _act_fn(params, inputs):
        """Toy policy: action = round(w) per row, logits carry w so the
        reply proves WHICH snapshot served it."""
        n = np.asarray(inputs["env"]).shape[1]
        w = float(np.asarray(params["w"]).reshape(-1)[0])
        return {
            "action": np.full((1, n), int(w), np.int32),
            "policy_logits": np.full((1, n, 2), w, np.float32),
        }

    def _server(self, address, **kwargs):
        from torchbeast_tpu.serving.replica_server import ReplicaServer
        from torchbeast_tpu.telemetry.metrics import MetricsRegistry

        server = ReplicaServer(
            self._act_fn, address,
            max_policy_lag=5, batch_dim=1, timeout_ms=5,
            registry=MetricsRegistry(), **kwargs,
        )
        server.start()
        return server

    @staticmethod
    def _request(i=0):
        return {"env": np.full((1, 1, 3), i, np.float32)}

    @pytest.mark.parametrize("transport", ["unix", "shm"])
    def test_publish_then_serve_stamps_lag(self, transport):
        """Round-trip over a REAL transport (socket and shm ring): the
        reply carries the serving snapshot's outputs and the true
        policy_lag stamp from the server-side store."""
        from torchbeast_tpu.serving.replica_server import (
            RemoteReplicaBatcher,
            RemoteSnapshotPublisher,
        )

        path = os.path.join(tempfile.mkdtemp(), f"rs_{transport}")
        address = f"{transport}:{path}"
        server = self._server(address)
        publisher = RemoteSnapshotPublisher(address, timeout_s=10)
        client = RemoteReplicaBatcher(address, timeout_s=10)
        try:
            publisher.publish(0, {"w": np.full((1,), 7.0, np.float32)})
            for v in (1, 2, 3):
                publisher.note_update(v)  # head runs 3 past the snapshot
            out = client.compute(self._request())
            assert int(np.asarray(out["action"]).reshape(-1)[0]) == 7
            stamp = np.asarray(out["policy_lag"])
            assert stamp.dtype == np.int32
            assert int(stamp.reshape(-1)[0]) == 3
            # A fresh publish drops the stamp back to zero.
            publisher.publish(3, {"w": np.full((1,), 9.0, np.float32)})
            out = client.compute(self._request())
            assert int(np.asarray(out["action"]).reshape(-1)[0]) == 9
            assert int(np.asarray(out["policy_lag"]).reshape(-1)[0]) == 0
        finally:
            client.close()
            publisher.close()
            server.stop()

    def test_remote_leg_in_replica_router(self):
        """The remote batcher drops into serving.ReplicaRouter as the
        replica leg: healthy -> served remotely with stamps; the local
        hooks' lag budget still gates the route to central."""
        from torchbeast_tpu.serving.replica_server import (
            RemoteReplicaBatcher,
            RemoteSnapshotPublisher,
        )
        from torchbeast_tpu.telemetry.metrics import MetricsRegistry

        path = os.path.join(tempfile.mkdtemp(), "rs_router")
        address = f"unix:{path}"
        server = self._server(address)
        registry = MetricsRegistry()
        # The learner-side store: publishes mirror to the remote host.
        store = PolicySnapshotStore(refresh_updates=1, registry=registry)
        hooks = ReplicaServingHooks(
            store, max_policy_lag=2, batch_dim=1, registry=registry
        )
        publisher = RemoteSnapshotPublisher(address, timeout_s=10)
        remote = RemoteReplicaBatcher(address, timeout_s=10)
        central = DynamicBatcher(batch_dim=1, timeout_ms=5)

        def serve_central():
            for batch in iter(central):
                batch.set_outputs({
                    "action": np.full((1, len(batch)), -1, np.int32),
                })

        central_thread = threading.Thread(
            target=serve_central, daemon=True
        )
        central_thread.start()
        router = ReplicaRouter(central, remote, hooks, registry=registry)
        try:
            store.publish(0, {"w": np.full((1,), 4.0, np.float32)})
            publisher.publish(0, {"w": np.full((1,), 4.0, np.float32)})
            out = router.compute(self._request())
            assert int(np.asarray(out["action"]).reshape(-1)[0]) == 4
            assert (
                registry.counter("serving.replica_requests").value() == 1
            )
            # Blow the local lag budget: the router degrades to central
            # without touching the remote host.
            for v in range(1, 5):
                store.note_update(v)
            out = router.compute(self._request())
            assert int(np.asarray(out["action"]).reshape(-1)[0]) == -1
            assert (
                registry.counter("serving.central_requests").value() == 1
            )
        finally:
            remote.close()
            publisher.close()
            central.close()
            central_thread.join(2)
            server.stop()

    def test_unpublished_store_fails_loud_not_silent(self):
        """A request before the first publish is an error reply (the
        hooks refuse to serve nothing), surfaced as a raised error on
        the client — never a hang or an unstamped reply."""
        from torchbeast_tpu.serving.replica_server import (
            RemoteReplicaBatcher,
        )

        path = os.path.join(tempfile.mkdtemp(), "rs_empty")
        address = f"unix:{path}"
        server = self._server(address)
        client = RemoteReplicaBatcher(address, timeout_s=10)
        try:
            with pytest.raises((RuntimeError, ConnectionError)):
                client.compute(self._request())
        finally:
            client.close()
            server.stop()

    def test_shed_propagates_as_typed_error(self):
        """An admission-gated server sheds overload as a typed ShedError
        on the CLIENT side, keeping the pool's shed/retry contract
        across the wire."""
        from torchbeast_tpu.serving.replica_server import (
            RemoteReplicaBatcher,
            RemoteSnapshotPublisher,
        )

        path = os.path.join(tempfile.mkdtemp(), "rs_shed")
        address = f"unix:{path}"
        server = self._server(
            address, shed_max_queue_depth=1, max_batch_size=1
        )
        # Wedge the serving loop: grab the batcher's dispatch lock by
        # never publishing — no, simpler: flood with concurrent
        # requests so depth 1 must shed some.
        publisher = RemoteSnapshotPublisher(address, timeout_s=10)
        client = RemoteReplicaBatcher(address, timeout_s=10)
        outcomes = {"served": 0, "shed": 0}
        lock = threading.Lock()

        def one(i):
            try:
                client.compute(self._request(i))
                with lock:
                    outcomes["served"] += 1
            except ShedError:
                with lock:
                    outcomes["shed"] += 1

        try:
            publisher.publish(0, {"w": np.full((1,), 1.0, np.float32)})
            threads = [
                threading.Thread(target=one, args=(i,), daemon=True)
                for i in range(16)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(20)
            assert outcomes["served"] + outcomes["shed"] == 16
            assert outcomes["served"] > 0
        finally:
            client.close()
            publisher.close()
            server.stop()
