"""bench.py's robustness contract: a JSON line is always emitted inside
the budget. These tests pin the fast paths (replay fallback, schema,
forced-CPU failure semantics); the probe/timeout paths are exercised by
running the real supervisor with a starved budget."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


def test_base_result_schema():
    r = bench._base_result(platform="cpu", note="x")
    assert set(r) >= {"metric", "value", "unit", "vs_baseline",
                      "platform", "note", "fresh", "measured_age_days"}
    assert r["unit"] == "frames/sec/chip"
    # Staleness defaults are the conservative not-a-measurement values;
    # only _live_fields() flips them.
    assert r["fresh"] is False
    assert r["measured_age_days"] is None
    assert json.dumps(r).startswith('{"metric"')  # supervisor line match


def test_live_fields_mark_fresh_measurements():
    """Both live emit sites (preliminary + final) stamp their line via
    _live_fields(): fresh and zero days old."""
    live = bench._base_result(**bench._live_fields())
    assert live["fresh"] is True
    assert live["measured_age_days"] == 0


def test_age_days():
    import time as _time

    stamp = _time.strftime(
        "%Y-%m-%d %H:%M:%S", _time.localtime(_time.time() - 3 * 86400)
    )
    age = bench._age_days(stamp)
    assert age is not None and 2.8 <= age <= 3.2
    assert bench._age_days("not a date") is None
    assert bench._age_days("") is None


def test_strip_staleness_for_persisted_artifact():
    """What bench persists to last_tpu_bench.json must not carry the
    live-run staleness stamps — the artifact ages in git while a stored
    fresh:true would not."""
    live = bench._base_result(value=1.0, **bench._live_fields())
    stored = bench._strip_staleness(live)
    assert "fresh" not in stored
    assert "measured_age_days" not in stored
    assert stored["value"] == 1.0


def test_replay_fallback_replays_committed_artifact(capsys):
    bench._replay_fallback("unit test reason")
    line = capsys.readouterr().out.strip().splitlines()[-1]
    parsed = json.loads(line)
    assert parsed["platform"] == "tpu(replayed)"
    assert parsed["value"] and parsed["value"] > 0
    assert parsed["vs_baseline"] and parsed["vs_baseline"] > 1
    assert "unit test reason" in parsed["note"]
    assert "last_tpu_bench.json" in parsed["note"]
    # A replay is machine-readably stale: fresh false, a real age from
    # the artifact's measured_at stamp.
    assert parsed["fresh"] is False
    assert parsed["measured_age_days"] is not None
    assert parsed["measured_age_days"] >= 0


def test_replay_fallback_without_artifact(tmp_path, monkeypatch):
    monkeypatch.setattr(
        bench, "LAST_TPU_PATH", str(tmp_path / "missing.json")
    )
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        bench._replay_fallback("gone")
    parsed = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert parsed["value"] is None
    assert parsed["platform"] == "none"
    assert parsed["fresh"] is False


class _FakeProc:
    def __init__(self, returncode, stdout="", stderr=""):
        self.returncode = returncode
        self.stdout = stdout
        self.stderr = stderr


def _run_supervisor(monkeypatch, capsys, proc):
    """Drive bench.main() with a successful probe and a scripted child."""
    monkeypatch.setattr(bench, "_probe_backend", lambda t: ("tpu", "v5e"))
    monkeypatch.setattr(
        bench.subprocess, "run", lambda *a, **k: proc
    )
    monkeypatch.delenv("_TB_BENCH_CHILD", raising=False)
    monkeypatch.delenv("BENCH_FORCE_CPU", raising=False)
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    return json.loads(
        [ln for ln in out if ln.startswith('{"metric"')][-1]
    )


def test_child_crash_emits_error_record_not_replay(monkeypatch, capsys):
    """ADVICE r3: a child that crashes (rc!=0, no metric line) while the
    tunnel is UP must NOT be papered over with last-known-good chip
    numbers — that would report a broken bench as success forever."""
    parsed = _run_supervisor(
        monkeypatch, capsys,
        _FakeProc(1, stdout="", stderr="Traceback\nBoomError: x\n"),
    )
    assert parsed["platform"] == "error"
    assert parsed["value"] is None
    assert parsed["fresh"] is False
    assert "crashed" in parsed["error"]
    assert "BoomError" in parsed["note"]


def test_child_crash_with_dead_backend_replays(monkeypatch, capsys):
    """A child that dies while the backend STOPPED answering is a tunnel
    drop mid-run (drops can raise rather than hang) — infra, not a code
    regression, so the replay contract applies."""
    probes = iter([("tpu", "v5e"), None])  # up before, dead after
    monkeypatch.setattr(
        bench, "_probe_backend", lambda t: next(probes)
    )
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: _FakeProc(1, stderr="RuntimeError: conn reset\n"),
    )
    monkeypatch.delenv("_TB_BENCH_CHILD", raising=False)
    monkeypatch.delenv("BENCH_FORCE_CPU", raising=False)
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    parsed = json.loads(
        [ln for ln in out if ln.startswith('{"metric"')][-1]
    )
    assert parsed["platform"] == "tpu(replayed)"
    assert parsed["fresh"] is False
    assert "tunnel dropped mid-run" in parsed["note"]


def test_child_crash_with_cpu_fallback_probe_replays(monkeypatch, capsys):
    """When the tunnel drops FAST (conn refused, not a hang), jax falls
    back to the cpu platform, so the post-crash probe answers — with the
    WRONG platform. That must still count as a tunnel drop (replay), not
    a code crash."""
    probes = iter([("tpu", "v5e"), ("cpu", "cpu")])
    monkeypatch.setattr(
        bench, "_probe_backend", lambda t: next(probes)
    )
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: _FakeProc(1, stderr="ConnectionRefusedError\n"),
    )
    monkeypatch.delenv("_TB_BENCH_CHILD", raising=False)
    monkeypatch.delenv("BENCH_FORCE_CPU", raising=False)
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    parsed = json.loads(
        [ln for ln in out if ln.startswith('{"metric"')][-1]
    )
    assert parsed["platform"] == "tpu(replayed)"
    assert "tunnel dropped mid-run" in parsed["note"]


def test_child_crash_with_recovered_tunnel_still_replays(
    monkeypatch, capsys
):
    """A transient blip can drop the child and RECOVER before the
    supervisor's reprobe; the connection-error signature in the child's
    stderr must still classify it as infra (replay), not code — but
    ONLY when the line is attributable to the device transport
    (jaxlib/XLA/PJRT/grpc), the way a real drop surfaces."""
    monkeypatch.setattr(
        bench, "_probe_backend", lambda t: ("tpu", "v5e")
    )  # up before AND after
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: _FakeProc(
            1,
            stderr=(
                "jax.errors.JaxRuntimeError: UNAVAILABLE: "
                "Connection reset by peer\n"
            ),
        ),
    )
    monkeypatch.delenv("_TB_BENCH_CHILD", raising=False)
    monkeypatch.delenv("BENCH_FORCE_CPU", raising=False)
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    parsed = json.loads(
        [ln for ln in out if ln.startswith('{"metric"')][-1]
    )
    assert parsed["platform"] == "tpu(replayed)"
    assert "tunnel dropped mid-run" in parsed["note"]


def test_multiline_transport_traceback_still_replays(monkeypatch, capsys):
    """A drop can surface as a bare builtin exception
    (`ConnectionResetError:` carries no marker) whose traceback frames
    (`File ".../axon/..."`) do. Block-scoped attribution must classify
    that as infra even when the tunnel has recovered by reprobe time."""
    monkeypatch.setattr(
        bench, "_probe_backend", lambda t: ("tpu", "v5e")
    )  # up before AND after
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: _FakeProc(
            1,
            stderr=(
                "Traceback (most recent call last):\n"
                '  File "/root/.axon_site/axon/register/__init__.py",'
                " line 619, in _axon_get_backend_uncached\n"
                "ConnectionResetError: [Errno 104] Connection reset "
                "by peer\n"
            ),
        ),
    )
    monkeypatch.delenv("_TB_BENCH_CHILD", raising=False)
    monkeypatch.delenv("BENCH_FORCE_CPU", raising=False)
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    parsed = json.loads(
        [ln for ln in out if ln.startswith('{"metric"')][-1]
    )
    assert parsed["platform"] == "tpu(replayed)"
    assert "tunnel dropped mid-run" in parsed["note"]


def test_marker_outside_traceback_block_does_not_attribute():
    """Routine jaxlib/xla_bridge warning lines appear in EVERY child's
    stderr; they must not attribute an unrelated IPC EOFError traceback
    to the device transport."""
    stderr = (
        "WARNING:jax._src.xla_bridge:905: Platform 'axon' is "
        "experimental\n"
        "Traceback (most recent call last):\n"
        '  File "runtime/queues.py", line 40, in get\n'
        "EOFError\n"
    )
    assert bench._is_transport_connection_error(stderr) is False

    # No traceback at all: a logged repo-IPC failure must not borrow
    # markers from earlier warning lines.
    stderr = (
        "WARNING:jax._src.xla_bridge:905: Platform 'axon' is "
        "experimental\n"
        "env server: send failed: Broken pipe\n"
    )
    assert bench._is_transport_connection_error(stderr) is False

    # A signature AFTER an unrelated marker-bearing traceback has
    # closed must not inherit that block's markers.
    stderr = (
        "Traceback (most recent call last):\n"
        '  File "/opt/venv/lib/python3.12/site-packages/jaxlib/x.py",'
        " line 1, in f\n"
        "ValueError: unrelated\n"
        "EOFError\n"
    )
    assert bench._is_transport_connection_error(stderr) is False

    # Positive control: the same signature INSIDE a transport-attributed
    # traceback still attributes.
    stderr = (
        "Traceback (most recent call last):\n"
        '  File "/opt/venv/lib/python3.12/site-packages/jaxlib/x.py",'
        " line 1, in f\n"
        "ConnectionResetError: [Errno 104] Connection reset by peer\n"
    )
    assert bench._is_transport_connection_error(stderr) is True

    # C++/glog FATAL transport failure: the process died inside the
    # transport, no Python traceback exists — the F-line attributes.
    stderr = (
        "F0730 12:34:56.789012 123 tcp_posix.cc:123] "
        "Socket closed\n"
    )
    assert bench._is_transport_connection_error(stderr) is True

    # E-level glog connection noise is AMBIENT (grpc/TSL log it during
    # ordinary channel teardown); it must not turn a code crash into a
    # stale-chip-number replay.
    stderr = (
        "E0730 12:34:56.789012 123 tcp_posix.cc:123] recvmsg: "
        "Connection reset by peer\n"
    )
    assert bench._is_transport_connection_error(stderr) is False


def test_unattributed_connection_error_is_code_not_infra(
    monkeypatch, capsys
):
    """An EOFError/Broken-pipe from the repo's OWN IPC (a queue bug, an
    env-server pipe broken by a learner crash) carries no jaxlib/XLA/
    PJRT marker on its line. With the tunnel up before and after, that
    is a code regression: it must emit the no-replay error record, not
    serve last-known-good chip numbers."""
    monkeypatch.setattr(
        bench, "_probe_backend", lambda t: ("tpu", "v5e")
    )  # up before AND after
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: _FakeProc(
            1,
            stderr=(
                "Traceback (most recent call last):\n"
                '  File "runtime/queues.py", line 40, in get\n'
                "EOFError\n"
            ),
        ),
    )
    monkeypatch.delenv("_TB_BENCH_CHILD", raising=False)
    monkeypatch.delenv("BENCH_FORCE_CPU", raising=False)
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    parsed = json.loads(
        [ln for ln in out if ln.startswith('{"metric"')][-1]
    )
    assert parsed["platform"] == "error"
    assert parsed["fresh"] is False
    assert "no replay" in parsed["note"]


def test_child_success_line_passes_through(monkeypatch, capsys):
    good = json.dumps(bench._base_result(
        value=1.0, platform="tpu", step_ms=5.0, **bench._live_fields()
    ))
    parsed = _run_supervisor(
        monkeypatch, capsys, _FakeProc(0, stdout=good + "\n")
    )
    assert parsed["platform"] == "tpu"
    assert parsed["fresh"] is True
    assert parsed["measured_age_days"] == 0


def test_forced_cpu_starved_budget_never_replays_tpu():
    """BENCH_FORCE_CPU with no budget must fail FAST with a cpu-labeled
    line — serving TPU numbers for an explicitly-CPU run would mislead
    the caller, and hanging would defeat the whole contract."""
    env = dict(
        os.environ, BENCH_FORCE_CPU="1", BENCH_BUDGET_S="50",
        JAX_PLATFORMS="cpu",
    )
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=60, cwd=REPO,
    )
    assert out.returncode == 0
    line = [
        ln for ln in out.stdout.splitlines() if ln.startswith('{"metric"')
    ][-1]
    parsed = json.loads(line)
    assert parsed["platform"] == "cpu"
    assert parsed["value"] is None
    assert "tpu" not in (parsed.get("note") or "").split("FORCE")[0].lower()
