"""bench.py's robustness contract: a JSON line is always emitted inside
the budget. These tests pin the fast paths (replay fallback, schema,
forced-CPU failure semantics); the probe/timeout paths are exercised by
running the real supervisor with a starved budget."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


def test_base_result_schema():
    r = bench._base_result(platform="cpu", note="x")
    assert set(r) >= {"metric", "value", "unit", "vs_baseline",
                      "platform", "note"}
    assert r["unit"] == "frames/sec/chip"
    assert json.dumps(r).startswith('{"metric"')  # supervisor line match


def test_replay_fallback_replays_committed_artifact(capsys):
    bench._replay_fallback("unit test reason")
    line = capsys.readouterr().out.strip().splitlines()[-1]
    parsed = json.loads(line)
    assert parsed["platform"] == "tpu(replayed)"
    assert parsed["value"] and parsed["value"] > 0
    assert parsed["vs_baseline"] and parsed["vs_baseline"] > 1
    assert "unit test reason" in parsed["note"]
    assert "last_tpu_bench.json" in parsed["note"]


def test_replay_fallback_without_artifact(tmp_path, monkeypatch):
    monkeypatch.setattr(
        bench, "LAST_TPU_PATH", str(tmp_path / "missing.json")
    )
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        bench._replay_fallback("gone")
    parsed = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert parsed["value"] is None
    assert parsed["platform"] == "none"


def test_forced_cpu_starved_budget_never_replays_tpu():
    """BENCH_FORCE_CPU with no budget must fail FAST with a cpu-labeled
    line — serving TPU numbers for an explicitly-CPU run would mislead
    the caller, and hanging would defeat the whole contract."""
    env = dict(
        os.environ, BENCH_FORCE_CPU="1", BENCH_BUDGET_S="50",
        JAX_PLATFORMS="cpu",
    )
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=60, cwd=REPO,
    )
    assert out.returncode == 0
    line = [
        ln for ln in out.stdout.splitlines() if ln.startswith('{"metric"')
    ][-1]
    parsed = json.loads(line)
    assert parsed["platform"] == "cpu"
    assert parsed["value"] is None
    assert "tpu" not in (parsed.get("note") or "").split("FORCE")[0].lower()
