"""Device-resident agent-state table (runtime/state_table.py): the
gather -> act -> merge-by-advance -> scatter step, slot isolation
(including the trash slot bucket padding scatters to), reset/read_slot,
the inference_loop integration (slot-framed requests, state-free
replies), and the transfer-guard regression test pinning the tentpole
property: agent state performs ZERO host round trips per env step.

Everything here runs on the CPU backend (conftest forces
JAX_PLATFORMS=cpu) — the CPU device is the "fake device" standing in for
the chip, so tier-1 covers the whole device-resident path without TPU
access.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchbeast_tpu.runtime.inference import (
    inference_loop,
    pad_advance,
    pad_slots,
    pad_to,
)
from torchbeast_tpu.runtime.queues import DynamicBatcher
from torchbeast_tpu.runtime.state_table import DeviceStateTable

H = 2  # state feature width


def _act_fn(ctx, env_outputs, agent_state):
    """outputs = frame + state (so outputs prove WHICH state each row
    gathered), new_state = state + 1 (so persistence is observable)."""
    frame = env_outputs["frame"]  # [1, B, H]
    state = agent_state["h"]  # [1, B, H]
    return {"out": frame + state}, {"h": state + 1}


def make_table(num_slots=4, context_fn=None):
    return DeviceStateTable(
        {"h": np.zeros((1, 1, H), np.float32)},
        num_slots=num_slots,
        act_fn=_act_fn,
        context_fn=context_fn,
        batch_dim=1,
    )


def _env(values):
    """env nest for len(values) rows, frame row i == values[i]."""
    v = np.asarray(values, np.float32)
    return {"frame": np.tile(v[None, :, None], (1, 1, H))}


def _step_out(table, slots, advance, env):
    out = table.step(
        np.asarray(slots, np.int32), np.asarray(advance, bool), env
    )
    return np.asarray(jax.device_get(out["out"]))


def slot_state(table, slot):
    return np.asarray(table.read_slot(slot)["h"]).reshape(H)


class TestDeviceStateTable:
    def test_validation(self):
        with pytest.raises(ValueError, match="num_slots"):
            make_table(num_slots=0)
        with pytest.raises(ValueError, match="non-empty"):
            DeviceStateTable(
                {}, num_slots=2, act_fn=_act_fn, batch_dim=1
            )
        with pytest.raises(ValueError, match="size 1 along"):
            DeviceStateTable(
                {"h": np.zeros((1, 3, H), np.float32)},
                num_slots=2,
                act_fn=_act_fn,
                batch_dim=1,
            )

    def test_step_advances_only_requested_slots(self):
        table = make_table()
        # Slots 0 and 2 step (advance), slots 1 and 3 untouched.
        out = _step_out(table, [0, 2], [True, True], _env([10.0, 20.0]))
        # All slots start at state 0: outputs == frames.
        np.testing.assert_array_equal(out[0, 0], np.full(H, 10.0))
        np.testing.assert_array_equal(out[0, 1], np.full(H, 20.0))
        np.testing.assert_array_equal(slot_state(table, 0), np.full(H, 1.0))
        np.testing.assert_array_equal(slot_state(table, 1), np.zeros(H))
        np.testing.assert_array_equal(slot_state(table, 2), np.full(H, 1.0))
        # Second step for slot 0 only: output reflects the advanced state.
        out = _step_out(table, [0], [True], _env([5.0]))
        np.testing.assert_array_equal(out[0, 0], np.full(H, 6.0))
        np.testing.assert_array_equal(slot_state(table, 0), np.full(H, 2.0))

    def test_advance_false_computes_without_persisting(self):
        """The actor pool's priming call: outputs from the CURRENT state,
        state NOT advanced (reference monobeast.py advance=False path)."""
        table = make_table()
        _step_out(table, [1], [True], _env([0.0]))  # slot 1 -> state 1
        out = _step_out(table, [1], [False], _env([7.0]))
        np.testing.assert_array_equal(out[0, 0], np.full(H, 8.0))  # 7 + 1
        np.testing.assert_array_equal(slot_state(table, 1), np.full(H, 1.0))

    def test_input_filter_drops_extra_leaves_without_recompile(self):
        """polybeast's prewarm builds dummy envs from the 4-key model
        schema while real actor traffic carries the full 6-key nest
        (episode stats included). The host-side input_filter must make
        both hit ONE compiled signature — and keep the ignored leaves
        out of the dispatch entirely."""
        traces = []

        def counting_act(ctx, env_outputs, agent_state):
            traces.append(sorted(env_outputs))
            return _act_fn(ctx, env_outputs, agent_state)

        table = DeviceStateTable(
            {"h": np.zeros((1, 1, H), np.float32)},
            num_slots=2,
            act_fn=counting_act,
            batch_dim=1,
            input_filter=lambda env: {"frame": env["frame"]},
        )
        slots = np.asarray([0], np.int32)
        advance = np.ones(1, bool)
        # Prewarm-shaped (model schema only)...
        out1 = table.step(slots, advance, _env([3.0]))
        # ...then wire-shaped (extra leaves the model never reads).
        wire_env = dict(
            _env([4.0]), episode_step=np.zeros((1, 1), np.int32)
        )
        out2 = table.step(slots, advance, wire_env)
        assert traces == [["frame"]]  # one trace; filtered nest only
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(out2["out"]))[0, 0],
            np.full(H, 5.0),  # frame 4 + advanced state 1
        )
        del out1

    def test_failed_step_poisons_table(self):
        """The table buffer is donated into every step dispatch, so a
        step that raises may have consumed it — the table must refuse
        further use (use-after-free would serve garbage state) instead
        of letting the serving loop retry per-batch."""

        def bad_ctx():
            raise RuntimeError("params fetch exploded")

        table = make_table(context_fn=bad_ctx)
        with pytest.raises(RuntimeError, match="params fetch exploded"):
            table.step(
                np.zeros(1, np.int32), np.ones(1, bool), _env([1.0])
            )
        # context_fn runs before the donating dispatch, so the table
        # survives a context failure...
        assert not table.poisoned
        table._context_fn = None

        real_jit = table._step_jit

        def exploding_jit(*args, **kwargs):
            raise RuntimeError("dispatch died")

        table._step_jit = exploding_jit
        with pytest.raises(RuntimeError, match="dispatch died"):
            table.step(
                np.zeros(1, np.int32), np.ones(1, bool), _env([1.0])
            )
        # ...but a failure of the dispatch itself poisons it for every
        # entry point, with a diagnosable error.
        assert table.poisoned
        table._step_jit = real_jit
        for call in (
            lambda: table.step(
                np.zeros(1, np.int32), np.ones(1, bool), _env([1.0])
            ),
            lambda: table.read_slot(0),
            lambda: table.reset([0]),
        ):
            with pytest.raises(RuntimeError, match="poisoned"):
                call()

    def test_trash_slot_padding_never_disturbs_real_slots(self):
        table = make_table(num_slots=2)
        trash = table.trash_slot
        assert trash == 2
        # A padded batch: one real row + three trash rows, advance padded
        # False — exactly what inference_loop builds for bucket padding.
        slots = pad_slots(np.asarray([0]), 4, trash)
        advance = pad_advance(np.asarray([True]), 4)
        out = _step_out(table, slots, advance, _env([1.0, 9.0, 9.0, 9.0]))
        np.testing.assert_array_equal(out[0, 0], np.full(H, 1.0))
        np.testing.assert_array_equal(slot_state(table, 0), np.full(H, 1.0))
        np.testing.assert_array_equal(slot_state(table, 1), np.zeros(H))
        # Even an ADVANCING trash row (duplicate ids, last-writer-wins)
        # only ever writes the trash slot.
        _step_out(
            table,
            np.asarray([trash, trash], np.int32),
            np.asarray([True, True]),
            _env([3.0, 4.0]),
        )
        np.testing.assert_array_equal(slot_state(table, 0), np.full(H, 1.0))
        np.testing.assert_array_equal(slot_state(table, 1), np.zeros(H))

    def test_reset_restores_initial_state(self):
        table = make_table()
        for _ in range(3):
            _step_out(table, [0, 1], [True, True], _env([0.0, 0.0]))
        table.reset([0])
        np.testing.assert_array_equal(slot_state(table, 0), np.zeros(H))
        np.testing.assert_array_equal(slot_state(table, 1), np.full(H, 3.0))

    def test_read_slot_shape_matches_initial_state(self):
        table = make_table()
        piece = table.read_slot(3)
        assert np.shape(piece["h"]) == (1, 1, H)

    def test_context_fn_threads_fresh_ctx_without_recompile(self):
        calls = []

        def context_fn():
            calls.append(None)
            return jnp.float32(len(calls))

        def act_with_ctx(ctx, env_outputs, agent_state):
            return (
                {"out": env_outputs["frame"] + ctx},
                {"h": agent_state["h"]},
            )

        table = DeviceStateTable(
            {"h": np.zeros((1, 1, H), np.float32)},
            num_slots=2,
            act_fn=act_with_ctx,
            context_fn=context_fn,
            batch_dim=1,
        )
        out1 = np.asarray(
            jax.device_get(
                table.step(
                    np.asarray([0], np.int32),
                    np.asarray([True]),
                    _env([0.0]),
                )["out"]
            )
        )
        out2 = np.asarray(
            jax.device_get(
                table.step(
                    np.asarray([0], np.int32),
                    np.asarray([True]),
                    _env([0.0]),
                )["out"]
            )
        )
        # ctx is traced, not baked in: the second call sees ctx=2.
        np.testing.assert_array_equal(out1[0, 0], np.full(H, 1.0))
        np.testing.assert_array_equal(out2[0, 0], np.full(H, 2.0))


class TestInferenceLoopIntegration:
    def test_slot_framed_requests_route_and_replies_carry_no_state(self):
        table = make_table(num_slots=8)
        batcher = DynamicBatcher(
            batch_dim=1, minimum_batch_size=1, maximum_batch_size=8,
            timeout_ms=5,
        )
        server = threading.Thread(
            target=inference_loop,
            args=(batcher, None, 8),
            kwargs={"state_table": table},
            daemon=True,
        )
        server.start()

        results, errors = {}, []

        def producer(i):
            try:
                for _ in range(3):  # 3 advancing steps per slot
                    out = batcher.compute(
                        {
                            "env": {
                                "frame": np.full((1, 1, H), float(i),
                                                 np.float32)
                            },
                            "slot": np.full((1, 1), i, np.int32),
                            "advance": np.full((1, 1), True, bool),
                        }
                    )
                results[i] = out
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=producer, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        assert len(results) == 8
        for i, out in results.items():
            # Reply framing: outputs only — no agent-state leaves.
            assert set(out.keys()) == {"outputs"}
            # Third step saw state 2: out = frame + 2.
            np.testing.assert_array_equal(
                np.asarray(out["outputs"]["out"]),
                np.full((1, 1, H), float(i) + 2.0, np.float32),
            )
            np.testing.assert_array_equal(
                slot_state(table, i), np.full(H, 3.0)
            )
        batcher.close()
        server.join(timeout=10)
        assert not server.is_alive()


class TestTransferGuard:
    def test_state_never_crosses_host_boundary_per_step(self):
        """The tentpole regression test: a full padded unroll of table
        steps under jax.transfer_guard("disallow") — only the EXPLICIT
        device_put of observations/ids (inside DeviceStateTable.step) and
        the EXPLICIT device_get of outputs (fetch) are allowed; any
        agent-state leaf crossing the boundary would be an implicit
        transfer and raise."""
        table = make_table(num_slots=4)
        # Warm the compile caches outside the guard (compilation itself
        # may transfer constants; the guarded property is the per-step
        # hot path, not the one-time compile).
        slots = pad_slots(np.asarray([0, 1]), 4, table.trash_slot)
        advance = pad_advance(np.asarray([True, True]), 4)
        env = pad_to(_env([1.0, 2.0]), 4, batch_dim=1)
        out = table.step(slots, advance, env)
        table.fetch(out, 2)
        table.read_slot(0)

        with jax.transfer_guard("disallow"):
            for t in range(5):  # one unroll's worth of acting steps
                out = table.step(slots, advance, env)
                fetched = table.fetch(out, 2)
            # Rollout-boundary state read: one explicit fetch per unroll.
            boundary = table.read_slot(0)
        # Warmup advanced slot 0 once; guarded steps 1..5 saw states
        # 1..5, so the last output is frame + 5 and the boundary state 6.
        np.testing.assert_array_equal(
            np.asarray(fetched["out"])[0, 0], np.full(H, 1.0 + 5.0)
        )
        np.testing.assert_array_equal(
            np.asarray(boundary["h"]).reshape(H), np.full(H, 6.0)
        )

    def test_pipelined_unroll_state_stays_on_device(self):
        """Lag-1 collector variant of the guard test: a device-side
        policy's recurrent state flows device -> device across a whole
        collect() with implicit transfers disallowed; only the action
        fetch and the end-of-unroll bulk fetch cross, explicitly."""
        from torchbeast_tpu.envs import CountingEnv
        from torchbeast_tpu.envs.vec import SerialEnvPool
        from torchbeast_tpu.rollout import PipelinedRolloutCollector
        from torchbeast_tpu.types import AgentOutput

        B = 2

        @jax.jit
        def policy_step(done, state):
            state = jnp.where(done, 0, state) + 1
            out = AgentOutput(
                action=jnp.zeros(done.shape, jnp.int32),
                policy_logits=state.astype(jnp.float32)[..., None],
                baseline=state.astype(jnp.float32),
            )
            return out, state

        def policy(env_output, agent_state):
            done = jax.device_put(np.asarray(env_output["done"]))
            out, state = policy_step(done, agent_state)
            assert isinstance(state, jax.Array)  # never left the device
            return out, state

        pool = SerialEnvPool(
            [lambda: CountingEnv(episode_length=5) for _ in range(B)]
        )
        state0 = jax.device_put(np.zeros(B, np.int64))
        # Warm the compile outside the guard.
        policy_step(jnp.zeros(B, bool), state0)

        collector = PipelinedRolloutCollector(
            pool, policy, state0, unroll_length=3
        )
        with jax.transfer_guard("disallow"):
            for _ in range(3):
                batch, initial_state = collector.collect()
        assert isinstance(initial_state, jax.Array)
        # Invariants still hold under the guard (spot check: the policy
        # writes its post-increment state into baseline).
        done0 = batch["done"][0]
        expected = np.where(done0, 0, np.asarray(initial_state)) + 1
        np.testing.assert_array_equal(batch["baseline"][1], expected)
