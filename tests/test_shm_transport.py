"""Shared-memory transport (ISSUE 3): ring data plane, doorbell flow
control, the shm:// address scheme through EnvServer/ActorPool, and the
crash-recovery contract (killing an env-server process mid-ring tears
down one connection and revives it — the same contract
tests/test_env_server.py pins for sockets)."""

import multiprocessing as mp
import os
import socket
import struct
import tempfile
import threading
import time

import numpy as np
import pytest

from torchbeast_tpu.envs import CountingEnv
from torchbeast_tpu.runtime import transport, wire
from torchbeast_tpu.runtime.actor_pool import ActorPool
from torchbeast_tpu.runtime.env_server import EnvServer
from torchbeast_tpu.runtime.inference import inference_loop
from torchbeast_tpu.runtime.queues import BatchingQueue, DynamicBatcher

EPISODE_LEN = 5
T = 3


# ---------------------------------------------------------------------------
# Address scheme


def test_parse_address_shm():
    fam, path = transport.parse_address("shm:/tmp/x.0")
    assert fam == socket.AF_UNIX and path == "/tmp/x.0"
    fam, path = transport.parse_address("shm:///tmp/y")
    assert fam == socket.AF_UNIX and path == "/tmp/y"
    assert transport.is_shm_address("shm:/tmp/x")
    assert not transport.is_shm_address("unix:/tmp/x")
    assert not transport.is_shm_address("127.0.0.1:4444")


def test_server_address_suffixes_shm():
    from torchbeast_tpu.polybeast_env import host_scoped_basename, server_address

    assert server_address("shm:/tmp/tbt", 2) == "shm:/tmp/tbt.2"
    assert host_scoped_basename("shm:/tmp/tbt", 1, 4) == "shm:/tmp/tbt-h1"


# ---------------------------------------------------------------------------
# Ring data plane (shm_pipe harness)


def fuzz_pipe(server, client, rng, rounds=60):
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_wire import assert_nest_equal, random_nest

    for _ in range(rounds):
        value = random_nest(rng)
        expected = len(wire.encode_legacy(value))
        result = {}

        def echo():
            got, nbytes = server.recv_sized()
            result["nbytes"] = nbytes
            server.send(got if not isinstance(got, np.ndarray) else got.copy())

        t = threading.Thread(target=echo)
        t.start()
        sent = client.send(value)
        back, _ = client.recv_sized()
        t.join()
        assert sent == expected
        assert result["nbytes"] == expected
        assert_nest_equal(value, _deep_copy(back))


def _deep_copy(value):
    if isinstance(value, np.ndarray):
        return value.copy()
    if isinstance(value, list):
        return [_deep_copy(v) for v in value]
    if isinstance(value, dict):
        return {k: _deep_copy(v) for k, v in value.items()}
    return value


def test_shm_pipe_fuzz_roundtrip():
    server, client = transport.shm_pipe(
        obs_ring_bytes=1 << 16, act_ring_bytes=1 << 16
    )
    try:
        fuzz_pipe(server, client, np.random.default_rng(23))
    finally:
        server.close()
        client.close()


def test_ring_wait_counters():
    """ring.doorbell_waits / ring.recheck_wakeups (ISSUE 10): a blocked
    recv that never sees a doorbell byte rides the bounded recheck, and
    both counters record it — the metastability hunt's data source."""
    from torchbeast_tpu import telemetry

    reg = telemetry.get_registry()
    waits0 = reg.counter("ring.doorbell_waits").value()
    rechecks0 = reg.counter("ring.recheck_wakeups").value()
    server, client = transport.shm_pipe(
        obs_ring_bytes=4096, act_ring_bytes=4096
    )
    try:
        client._recv_timeout_s = 0.08
        with pytest.raises(socket.timeout):
            client.recv_sized()
        waits = reg.counter("ring.doorbell_waits").value() - waits0
        rechecks = reg.counter("ring.recheck_wakeups").value() - rechecks0
        assert rechecks >= 1  # at least one bounded recheck fired
        assert waits >= rechecks  # every recheck rode an armed wait
        # A frame that arrives while unblocked is consumed without
        # touching the doorbell: the counters are wait-path-only.
        waits1 = reg.counter("ring.doorbell_waits").value()
        server.send({"x": 1})
        value, _ = client.recv_sized()
        assert value == {"x": 1}
        assert reg.counter("ring.doorbell_waits").value() == waits1
    finally:
        server.close()
        client.close()


def test_shm_ring_wraparound():
    """Many variable-size frames through a tiny ring force every wrap
    case (marker wrap, <4-byte implicit wrap, exact fit)."""
    server, client = transport.shm_pipe(
        obs_ring_bytes=4096, act_ring_bytes=4096
    )
    rng = np.random.default_rng(5)
    try:
        done = []

        def echo(n):
            for _ in range(n):
                got, _ = server.recv_sized()
                server.send({"n": got["n"], "arr": got["arr"].copy()})
            done.append(True)

        N = 300
        t = threading.Thread(target=echo, args=(N,))
        t.start()
        for i in range(N):
            n = int(rng.integers(0, 900))
            client.send({"n": n, "arr": np.full(n, i % 250, np.uint8)})
            back, _ = client.recv_sized()
            assert back["n"] == n and back["arr"].shape == (n,)
        t.join()
        assert done
    finally:
        server.close()
        client.close()


def test_shm_oversized_frame_rides_inline():
    server, client = transport.shm_pipe(
        obs_ring_bytes=8192, act_ring_bytes=8192
    )
    big = np.arange(1 << 16, dtype=np.uint8)  # 64 KiB >> 8 KiB rings
    try:
        result = {}

        def echo():
            got, nbytes = server.recv_sized()
            result["nbytes"] = nbytes
            server.send({"ok": True})

        t = threading.Thread(target=echo)
        t.start()
        sent = client.send({"x": big})
        back, _ = client.recv_sized()
        t.join()
        assert back["ok"] is True
        assert sent == result["nbytes"] == len(wire.encode_legacy({"x": big}))
    finally:
        server.close()
        client.close()


def test_shm_frame_lifetime_rule():
    """Ring frames are released at the next recv: a decoded view from
    frame 1 is overwritten once a later frame wraps into its ring space
    (pins the consume-before-next-recv contract)."""
    server, client = transport.shm_pipe(
        obs_ring_bytes=4096, act_ring_bytes=4096
    )
    first = second = third = None
    try:
        # ~1520B frames in a 4096B ring: two fit; the third wraps into
        # the first's slot once the first's space has been released.
        server.send(np.full(1500, 1, np.uint8))
        first, _ = client.recv_sized()
        assert int(first[0]) == 1
        with pytest.raises((ValueError, TypeError)):
            first[0] = 9  # read-only view into the ring

        server.send(np.full(1500, 2, np.uint8))
        second, _ = client.recv_sized()  # entry releases frame 1's space
        server.send(np.full(1500, 3, np.uint8))  # wraps into that space
        third, _ = client.recv_sized()
        assert int(second[0]) == 2 and int(third[0]) == 3
        # THE CONTRACT: the stale view now shows frame 3's bytes.
        assert int(first[0]) == 3
    finally:
        first = second = third = None  # drop ring views before close
        server.close()
        client.close()


def test_shm_bad_doorbell_byte_raises():
    server, client = transport.shm_pipe()
    try:
        client._sock.sendall(b"\x7f")
        with pytest.raises(wire.WireError, match="doorbell"):
            server.recv_sized()
    finally:
        server.close()
        client.close()


def test_shm_corrupt_ring_length_raises_wire_error():
    """A bit-flipped frame length in the ring must surface as WireError
    (the teardown exception), never struct.error/ValueError."""
    server, client = transport.shm_pipe(
        obs_ring_bytes=8192, act_ring_bytes=8192
    )
    try:
        client.send({"a": np.arange(64, dtype=np.uint8)})
        # Corrupt the just-written frame's u32 length prefix in place.
        ring = server._recv_ring
        struct.pack_into("<I", ring._data, 0, 0xFFFF0000)
        with pytest.raises(wire.WireError):
            server.recv_sized()
    finally:
        server.close()
        client.close()


def test_shm_corrupt_payload_raises_wire_error():
    """Bit flips inside the payload (structural bytes) must also fail as
    WireError via decode's malformed-frame trap."""
    server, client = transport.shm_pipe(
        obs_ring_bytes=8192, act_ring_bytes=8192
    )
    try:
        client.send({"a": np.arange(64, dtype=np.uint8)})
        ring = server._recv_ring
        ring._data[8] = 0xFF  # inside the payload: smash a tag byte
        with pytest.raises(wire.WireError):
            server.recv_sized()
    finally:
        server.close()
        client.close()


def test_shm_truncated_by_peer_death_raises_or_eofs():
    """Peer death between doorbell and consumption: the socket cut must
    surface as clean EOF (None) or ConnectionError/WireError — never a
    hang or an unrelated exception type."""
    server, client = transport.shm_pipe()
    client.send({"x": 1})
    client._sock.close()  # peer dies with a frame still in the ring
    got, nbytes = server.recv_sized()  # doorbell already queued: delivered
    assert got == {"x": 1}
    assert server.recv_sized() == (None, 0)  # then clean EOF
    server.close()
    client.close()


def test_shm_max_frame_bytes_enforced():
    server, client = transport.shm_pipe(max_frame_bytes=4096)
    try:
        client.send({"a": np.zeros(8192, np.uint8)})
        with pytest.raises(wire.WireError, match="max_frame_bytes"):
            server.recv_sized()
    finally:
        server.close()
        client.close()


def test_shm_half_capacity_frames_route_inline_and_stay_ordered():
    """Frames above capacity/2 are position-dependently unplaceable in
    the ring (wrap skip + frame can exceed total capacity), so the
    transport must route them inline — and mixed ring/inline traffic
    must arrive in order (the in-ring marker is the ordering slot)."""
    server, client = transport.shm_pipe(
        obs_ring_bytes=8192, act_ring_bytes=8192
    )
    assert server._send_ring.max_frame_bytes() == 8192 // 2 - 4
    sizes = [100, 5000, 200, 6000, 5000, 50]  # 5000/6000 > 4092: inline
    try:
        def producer():
            for i, n in enumerate(sizes):
                server.send({"i": i, "arr": np.full(n, i, np.uint8)})

        t = threading.Thread(target=producer)
        t.start()
        for i, n in enumerate(sizes):
            got, nbytes = client.recv_sized()
            assert got["i"] == i and got["arr"].shape == (n,)
            assert nbytes == len(wire.encode_legacy(
                {"i": i, "arr": np.full(n, i, np.uint8)}
            ))
        t.join()
    finally:
        server.close()
        client.close()


def test_shm_inline_byte_on_blocked_reader_recovers():
    """THE lost-wakeup race on the oversized path: the sender can read a
    stale waiting=0, skip the WAKE byte, and land the 0x02 inline byte
    directly on a blocked reader. The reader must deliver the message
    via the (by-then-visible) ring marker, not tear the stream down."""
    server, client = transport.shm_pipe(
        obs_ring_bytes=8192, act_ring_bytes=8192
    )
    big = np.arange(6000, dtype=np.uint8)
    result = {}
    try:
        reader = threading.Thread(
            target=lambda: result.update(got=client.recv_sized())
        )
        reader.start()
        deadline = time.monotonic() + 5
        while not client._recv_ring.reader_waiting():  # reader blocked
            if time.monotonic() > deadline:
                raise TimeoutError("reader never blocked")
            time.sleep(0.001)
        # Emulate the racy sender verbatim, minus the WAKE byte.
        views, total = wire.encode_into({"x": big}, wire.SendBuffer())
        server._send_ring.write_inline_marker()
        server._sock.sendall(b"\x02")
        wire._sendmsg_all(server._sock, views, total)
        reader.join(5)
        assert not reader.is_alive()
        got, nbytes = result["got"]
        np.testing.assert_array_equal(got["x"].copy(), big)
        assert nbytes == total
    finally:
        server.close()
        client.close()


def test_transport_recv_timeout_bounds_silent_server():
    """connect_transport(recv_timeout_s=...): a server that accepts but
    never sends must surface as socket.timeout/OSError, not a hang (the
    env-spec probe's fallback path depends on it)."""
    path = os.path.join(tempfile.mkdtemp(), "silent")
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(path)
    listener.listen(1)
    accepted = []
    t = threading.Thread(target=lambda: accepted.append(listener.accept()))
    t.start()
    stream = transport.connect_transport(
        f"unix:{path}", timeout_s=5, recv_timeout_s=0.2
    )
    t.join(5)
    t0 = time.monotonic()
    with pytest.raises(OSError):
        stream.recv()
    assert time.monotonic() - t0 < 3
    stream.close()
    for conn, _ in accepted:
        conn.close()
    listener.close()


def test_shm_recv_timeout_bounds_silent_server():
    """Same bound through the shm transport's waiting loop."""
    server, client = transport.shm_pipe()
    client._recv_timeout_s = 0.2
    t0 = time.monotonic()
    with pytest.raises(OSError):
        client.recv_sized()
    assert time.monotonic() - t0 < 3
    server.close()
    client.close()


def test_shm_blocked_writer_fails_fast_on_peer_death():
    """Crash-detection parity with sockets for a ring-blocked WRITER: a
    sender stuck waiting for ring space must notice the peer's death via
    the doorbell-socket probe within ~ms, not after the 120s ring-wait
    timeout (the old behavior pinned threads for 2 minutes per stream)."""
    server, client = transport.shm_pipe(
        obs_ring_bytes=4096, act_ring_bytes=4096
    )
    errs = []

    def pump():
        msg = {"x": np.zeros(1200, np.uint8)}
        try:
            for _ in range(50):  # ring fills after ~3 frames (no reader)
                server.send(msg)
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=pump)
    t.start()
    time.sleep(0.3)  # the writer is now blocked in the ring wait
    t0 = time.monotonic()
    client._sock.close()  # peer dies
    t.join(10)
    assert not t.is_alive()
    assert time.monotonic() - t0 < 5
    assert errs and isinstance(errs[0], ConnectionError)
    server.close()
    client.close()


def test_shm_inline_path_honors_recv_timeout():
    """recv_timeout_s must bound the INLINE receive path too: a peer
    that sends the inline marker + 0x02 byte but stalls before the
    payload surfaces as a timeout, not a hang (the spec probe's
    contract is 'bounds every receive')."""
    server, client = transport.shm_pipe(
        obs_ring_bytes=8192, act_ring_bytes=8192
    )
    client._recv_timeout_s = 0.2
    server._send_ring.write_inline_marker()
    server._sock.sendall(b"\x02")  # ...but never the frame bytes
    t0 = time.monotonic()
    with pytest.raises(OSError):
        client.recv_sized()
    assert time.monotonic() - t0 < 3
    server.close()
    client.close()


def test_shm_ring_full_times_out_as_wire_error():
    """A stalled reader must surface as WireError after the write
    timeout, not a silent hang."""
    ring = transport.ShmRing.create(256)
    try:
        view = memoryview(bytes(200))
        ring.write_frame([view], 200)
        with pytest.raises(wire.WireError, match="full"):
            ring.write_frame([view], 200, timeout_s=0.2)
    finally:
        ring.close()


# ---------------------------------------------------------------------------
# EnvServer + ActorPool over shm://


def _start_counting_server(path, **kwargs):
    server = EnvServer(
        lambda: CountingEnv(episode_length=EPISODE_LEN), f"shm:{path}",
        **kwargs,
    )
    server.start()
    deadline = time.monotonic() + 10
    while not os.path.exists(path):
        if time.monotonic() > deadline:
            raise TimeoutError("server did not bind")
        time.sleep(0.01)
    return server


@pytest.fixture
def shm_server_address():
    path = os.path.join(tempfile.mkdtemp(), "shm_env")
    server = _start_counting_server(path)
    yield f"shm:{path}"
    server.stop()


def test_shm_stream_protocol(shm_server_address):
    stream = transport.connect_transport(shm_server_address, timeout_s=10)
    try:
        step = stream.recv()
        assert step["type"] == "step"
        assert bool(step["done"])  # initial boundary step
        assert np.asarray(step["reward"]).dtype == np.float32
        assert step["num_actions"] == 2  # spec advertisement works on shm

        for t in range(1, EPISODE_LEN + 1):
            stream.send({"type": "action", "action": 1})
            step = stream.recv()
            assert int(step["episode_step"]) == t
        assert bool(step["done"])
        assert float(step["episode_return"]) == sum(
            range(1, EPISODE_LEN + 1)
        )
    finally:
        step = None  # lifetime rule: drop ring views before close
        stream.close()


def test_shm_fresh_env_per_connection(shm_server_address):
    for _ in range(2):
        stream = transport.connect_transport(shm_server_address, timeout_s=10)
        step = stream.recv()
        assert int(step["episode_step"]) == 0
        step = None  # lifetime rule: drop ring views before close
        stream.close()


def test_shm_env_exception_surfaces():
    class ExplodingEnv:
        num_actions = 2

        def reset(self):
            return np.zeros((2, 2), np.uint8)

        def step(self, action):
            raise RuntimeError("boom")

    path = os.path.join(tempfile.mkdtemp(), "shm_exploding")
    server = EnvServer(ExplodingEnv, f"shm:{path}")
    server.start()
    deadline = time.monotonic() + 10
    while not os.path.exists(path):
        time.sleep(0.01)
        if time.monotonic() > deadline:
            raise TimeoutError
    try:
        stream = transport.connect_transport(f"shm:{path}", timeout_s=10)
        stream.recv()  # initial step
        stream.send({"type": "action", "action": 0})
        msg = stream.recv()
        assert msg["type"] == "error" and "boom" in msg["message"]
        msg = None  # lifetime rule: drop ring views before close
        stream.close()
    finally:
        server.stop()


def _run_pool(address, num_rollouts=6, max_reconnects=0):
    class CountingPolicyServer:
        def __call__(self, env_outputs, agent_state, batch_size):
            done = np.asarray(env_outputs["done"])  # [1, B]
            state = np.where(done, 0, np.asarray(agent_state)) + 1
            outputs = {
                "action": np.zeros_like(done, dtype=np.int32),
                "policy_logits": state[..., None].astype(np.float32),
                "baseline": state.astype(np.float32),
            }
            return outputs, state

    learner_queue = BatchingQueue(
        batch_dim=1, minimum_batch_size=1, maximum_batch_size=1
    )
    batcher = DynamicBatcher(batch_dim=1, timeout_ms=20)
    inf_thread = threading.Thread(
        target=inference_loop, args=(batcher, CountingPolicyServer(), 8),
        daemon=True,
    )
    inf_thread.start()
    pool = ActorPool(
        unroll_length=T,
        learner_queue=learner_queue,
        inference_batcher=batcher,
        env_server_addresses=[address],
        initial_agent_state=np.zeros((1, 1), np.int64),
        max_reconnects=max_reconnects,
    )
    pool_thread = threading.Thread(target=pool.run, daemon=True)
    pool_thread.start()
    return learner_queue, batcher, pool, pool_thread


def test_shm_actor_pool_invariants(shm_server_address):
    """The full async stack over shm must preserve the same rollout
    invariants the socket transport pins (overlap-by-one, boundary
    resets, action pairing) — and the per-step copies mean nothing
    aliases the ring by the time batches reach the learner queue."""
    learner_queue, batcher, pool, pool_thread = _run_pool(shm_server_address)
    items = []
    for item in learner_queue:
        items.append(item)
        if len(items) >= 6:
            break
    batcher.close()
    learner_queue.close()
    pool_thread.join(5)
    assert pool.errors == []
    prev = None
    for item in items:
        batch = item["batch"]
        assert batch["frame"].shape[:2] == (T + 1, 1)
        assert batch["frame"].flags["OWNDATA"] or batch[
            "frame"
        ].base is not None  # stacked copies, not ring views
        if prev is not None:
            for key in batch:
                np.testing.assert_array_equal(
                    batch[key][0], prev[key][-1], err_msg=key
                )
        assert (batch["frame"][batch["done"]] == 0).all()
        np.testing.assert_array_equal(
            batch["action"][1:], batch["last_action"][1:]
        )
        prev = batch


# ---------------------------------------------------------------------------
# Crash recovery: env-server PROCESS killed mid-ring


def _serve_counting_shm(path):
    """Child-process body (spawn-safe: module-level, imports inside)."""
    from torchbeast_tpu.envs import CountingEnv
    from torchbeast_tpu.runtime.env_server import EnvServer

    EnvServer(lambda: CountingEnv(episode_length=5), f"shm:{path}").run()


def _spawn_server_proc(path):
    ctx = mp.get_context("spawn")
    proc = ctx.Process(target=_serve_counting_shm, args=(path,), daemon=True)
    proc.start()
    deadline = time.monotonic() + 30
    while not os.path.exists(path):
        if time.monotonic() > deadline:
            proc.kill()
            raise TimeoutError("spawned server did not bind")
        time.sleep(0.05)
    return proc


@pytest.mark.slow
def test_shm_actor_revives_after_server_process_kill():
    """THE crash contract: SIGKILL an env-server process mid-ring; the
    actor must tear down that one connection (ring + doorbell) and
    revive it against the restarted server, same as the socket pool."""
    path = os.path.join(tempfile.mkdtemp(), "shm_crash")
    proc = _spawn_server_proc(path)
    learner_queue, batcher, pool, pool_thread = _run_pool(
        f"shm:{path}", max_reconnects=3
    )
    try:
        it = iter(learner_queue)
        next(it)  # at least one rollout through the first connection

        proc.kill()  # SIGKILL: no cleanup, ring abandoned mid-stream
        proc.join(10)
        os.unlink(path)  # dead server's socket file lingers
        proc = _spawn_server_proc(path)

        for _ in range(3):
            next(it)
        assert pool.errors == []
        assert pool.reconnects >= 1
    finally:
        batcher.close()
        learner_queue.close()
        pool_thread.join(5)
        proc.kill()
        proc.join(10)


def _shm_segments():
    if not os.path.isdir("/dev/shm"):
        return set()
    return {n for n in os.listdir("/dev/shm") if n.startswith("psm_")}


@pytest.mark.slow
def test_shm_no_segment_leak_after_server_kill():
    """SIGKILL'd env servers can strand SharedMemory segments (the
    resource_tracker caveats from ISSUE 3 — the dead owner never runs
    its unlink): the actor pool's teardown sweep must leave /dev/shm
    clean after the connection dies (ISSUE 6 satellite)."""
    before = _shm_segments()
    path = os.path.join(tempfile.mkdtemp(), "shm_leak")
    proc = _spawn_server_proc(path)
    learner_queue, batcher, pool, pool_thread = _run_pool(
        f"shm:{path}", max_reconnects=0
    )
    try:
        it = iter(learner_queue)
        next(it)  # the ring pair is live and mid-stream

        proc.kill()  # SIGKILL: the owner never unlinks
        proc.join(10)
        # Budget 0: the actor retires after the failure; its teardown
        # sweep is the only thing standing between this kill and a
        # leaked ring pair.
        pool_thread.join(30)
        assert not pool_thread.is_alive()
    finally:
        batcher.close()
        learner_queue.close()
        pool_thread.join(5)
        proc.kill()
        proc.join(10)
    leaked = _shm_segments() - before
    assert leaked == set(), f"leaked /dev/shm segments: {leaked}"


def test_shm_server_stop_severs_streams():
    """stop() on a shm server must cut live doorbells so clients see a
    transport failure immediately (reconnect budget path), and must
    remove the doorbell socket."""
    path = os.path.join(tempfile.mkdtemp(), "shm_stop")
    server = _start_counting_server(path)
    stream = transport.connect_transport(f"shm:{path}", timeout_s=10)
    stream.recv()
    server.stop()
    with pytest.raises((wire.WireError, ConnectionError, OSError)):
        for _ in range(10):  # EOF may take one in-flight step to surface
            stream.send({"type": "action", "action": 0})
            msg = stream.recv()
            if msg is None:
                raise ConnectionError("clean EOF")
    stream.close()
    assert not os.path.exists(path)


def test_shm_handshake_garbage_raises():
    """A server that speaks the plain protocol on a socket the client
    believes is shm must fail the handshake as WireError, not decode
    garbage."""
    path = os.path.join(tempfile.mkdtemp(), "not_shm")
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.bind(path)
    sock.listen(1)

    def fake_server():
        conn, _ = sock.accept()
        wire.send_message(conn, {"type": "step", "frame": np.zeros(3)})
        conn.close()

    t = threading.Thread(target=fake_server)
    t.start()
    with pytest.raises(wire.WireError, match="handshake"):
        transport.connect_transport(f"shm:{path}", timeout_s=5)
    t.join()
    sock.close()


# ---------------------------------------------------------------------------
# Adaptive doorbell recheck (ISSUE 12): the Python policy unit — the
# cross-language behavioral pin lives in tests/test_native.py (needs the
# extension); the constants are pinned by beastlint ATOMIC-ORDER.


class TestAdaptiveRecheck:
    def test_tighten_relax_and_bounds(self):
        from torchbeast_tpu.runtime import transport as transport_lib

        policy = transport_lib.AdaptiveRecheck()
        init = policy.bound_ms
        assert init == int(transport_lib._WAKE_RECHECK_S * 1000)
        # A forced recheck-heavy window HALVES the bound...
        for _ in range(transport_lib._RECHECK_WINDOW):
            policy.record(True)
        assert policy.bound_ms == init // 2
        # ...down to (and never past) the floor.
        for _ in range(8 * transport_lib._RECHECK_WINDOW):
            policy.record(True)
        assert policy.bound_ms == transport_lib._RECHECK_MIN_MS
        # Quiet windows relax it back up to (and never past) the cap.
        for _ in range(12 * transport_lib._RECHECK_WINDOW):
            policy.record(False)
        assert policy.bound_ms == transport_lib._RECHECK_MAX_MS
        assert policy.timeout_s() == transport_lib._RECHECK_MAX_MS / 1000.0

    def test_hysteresis_band_holds_the_bound(self):
        from torchbeast_tpu.runtime import transport as transport_lib

        policy = transport_lib.AdaptiveRecheck()
        init = policy.bound_ms
        # Between relax and tighten thresholds: neither direction moves.
        rechecks = transport_lib._RECHECK_TIGHTEN - 1
        for i in range(transport_lib._RECHECK_WINDOW):
            policy.record(i < rechecks)
        assert policy.bound_ms == init

    def test_transport_owns_a_policy(self):
        """Every ShmTransport carries per-connection adaptive state and
        starts at the verified initial bound."""
        from torchbeast_tpu.runtime import transport as transport_lib

        server, client = transport_lib.shm_pipe()
        try:
            for end in (server, client):
                assert isinstance(
                    end._recheck, transport_lib.AdaptiveRecheck
                )
                assert end._recheck.timeout_s() == (
                    transport_lib._WAKE_RECHECK_S
                )
        finally:
            server.close()
            client.close()
