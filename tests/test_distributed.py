"""Multi-process data parallelism: 2 jax.distributed processes (gloo CPU
collectives), 2 virtual devices each, one global 4-device mesh; the DP
update over process-local batch shards must match a single-device update
(tests/distributed_worker.py)."""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_dp_update_matches_single_device():
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "distributed_worker.py")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    extra = [
        p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep) if p
    ]
    env = {
        **os.environ,
        "PYTHONPATH": os.pathsep.join([repo_root] + extra),
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(2)
    ]
    outputs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outputs.append(out)
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert "distributed update matches single-device OK" in out
        assert (
            "composite data x expert update matches single-device OK"
            in out
        )


def _run_poly_workers(
    tmp_path, total_steps, timeout=420, mode="dp", n_procs=2
):
    port = _free_port()
    worker = os.path.join(
        os.path.dirname(__file__), "poly_distributed_worker.py"
    )
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    extra = [
        p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep) if p
    ]
    env = {
        **os.environ,
        "PYTHONPATH": os.pathsep.join([repo_root] + extra),
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), str(port), str(tmp_path),
             str(total_steps), mode, str(n_procs)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(n_procs)
    ]
    outputs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outputs.append(out)
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
    return outputs


def test_poly_driver_two_hosts_end_to_end(tmp_path):
    """The FULL async driver across 2 jax.distributed processes: each host
    runs its own env servers/actors/inference, updates are collective over
    the global 4-device mesh, the lead host checkpoints, and a second
    launch resumes from that checkpoint."""
    total = 400  # 20 collective updates of 5*4 global frames
    outputs = _run_poly_workers(tmp_path, total)
    for i, out in enumerate(outputs):
        assert f"worker {i}: final step" in out

    # Host-aware layout: both hosts trained and logged...
    assert (tmp_path / "poly-dist" / "logs.csv").exists()
    assert (tmp_path / "poly-dist-host1" / "logs.csv").exists()
    # ...but only the lead host wrote the checkpoint.
    ckpt = tmp_path / "poly-dist" / "model.ckpt"
    assert ckpt.exists()
    assert not (tmp_path / "poly-dist-host1" / "model.ckpt").exists()

    import flax.serialization

    saved = flax.serialization.msgpack_restore(ckpt.read_bytes())
    assert saved["step"] >= total

    # Resume: both hosts load the lead's checkpoint and continue.
    outputs = _run_poly_workers(tmp_path, 2 * total)
    for out in outputs:
        assert "Resuming preempted job" in out
    saved = flax.serialization.msgpack_restore(ckpt.read_bytes())
    assert saved["step"] >= 2 * total


def test_poly_driver_four_host_pod_miniature(tmp_path):
    """BASELINE config 5's topology in miniature: the FULL async driver
    across 4 jax.distributed processes (2 virtual CPU devices each, one
    global 8-device data mesh), each host running its own env-server
    group — multi-host DP with per-host actor groups, the largest
    no-TPU step toward the 16-host v5e-64 story (reference README.md:10
    cross-machine training; polybeast_learner.py:436-444 address
    fan-out). Lead-host checkpoint + all-host resume included."""
    total = 240  # 6 collective updates of 5*8 global frames
    outputs = _run_poly_workers(
        tmp_path, total, timeout=900, mode="dp_pod", n_procs=4
    )
    for i, out in enumerate(outputs):
        assert f"worker {i}: final step" in out

    # Host-aware layout: every host trained and logged...
    assert (tmp_path / "poly-dist-dp_pod" / "logs.csv").exists()
    for host in range(1, 4):
        assert (
            tmp_path / f"poly-dist-dp_pod-host{host}" / "logs.csv"
        ).exists()
    # ...but only the lead host wrote the checkpoint.
    ckpt = tmp_path / "poly-dist-dp_pod" / "model.ckpt"
    assert ckpt.exists()
    for host in range(1, 4):
        assert not (
            tmp_path / f"poly-dist-dp_pod-host{host}" / "model.ckpt"
        ).exists()

    import flax.serialization

    saved = flax.serialization.msgpack_restore(ckpt.read_bytes())
    assert saved["step"] >= total

    # Resume: all 4 hosts load the lead's checkpoint and continue.
    outputs = _run_poly_workers(
        tmp_path, 2 * total, timeout=900, mode="dp_pod", n_procs=4
    )
    for out in outputs:
        assert "Resuming preempted job" in out
    saved = flax.serialization.msgpack_restore(ckpt.read_bytes())
    assert saved["step"] >= 2 * total


def test_poly_driver_four_host_pod_dp_x_tp(tmp_path):
    """Composite pod topology: (data=4 x model=2) across 4
    jax.distributed processes. The data axis spans hosts (grad
    all-reduce over the DCN-style gloo backend) while each host's local
    2 devices hold the Megatron-paired transformer TP shard — the
    layout a real v5e pod would use (TP inside the host's ICI, DP
    across hosts). Checkpoint must hold FULL kernels assembled by the
    lead host."""
    total = 240
    outputs = _run_poly_workers(
        tmp_path, total, timeout=900, mode="dp_pod_tp", n_procs=4
    )
    for i, out in enumerate(outputs):
        assert f"worker {i}: final step" in out
    ckpt = tmp_path / "poly-dist-dp_pod_tp" / "model.ckpt"
    assert ckpt.exists()

    import flax.serialization

    saved = flax.serialization.msgpack_restore(ckpt.read_bytes())
    assert saved["step"] >= total
    params = flax.serialization.msgpack_restore(saved["params"])
    wq = params["params"]["block_0"]["q"]["kernel"]
    # Full head count (4 by default; TP shards the head axis): not a
    # model-axis shard — local_view assembled across the host-local
    # TP axis.
    assert wq.shape[1] == 4


def test_poly_driver_two_hosts_dp_x_ep(tmp_path):
    """DP x EP across 2 jax.distributed processes: the global
    (data=2, expert=2) mesh spans both hosts, so one collective update
    carries the gradient all-reduce AND the MoE dispatch/combine
    all-to-alls over the cross-process gloo backend — the multi-host
    analog of the single-process composite-mesh tests."""
    total = 200  # 10 collective updates of 5*4 global frames
    outputs = _run_poly_workers(tmp_path, total, mode="dp_ep")
    for i, out in enumerate(outputs):
        assert f"worker {i}: final step" in out
    ckpt = tmp_path / "poly-dist-dp_ep" / "model.ckpt"
    assert ckpt.exists()

    import flax.serialization

    saved = flax.serialization.msgpack_restore(ckpt.read_bytes())
    assert saved["step"] >= total
    # The checkpoint holds the FULL (unsharded) expert stack: the lead
    # host's local_view must assemble all 4 experts from its local
    # shards, not write its half of the expert axis.
    params = flax.serialization.msgpack_restore(saved["params"])
    w_in = params["params"]["block_0"]["moe"]["w_in"]
    assert w_in.shape[0] == 4


def test_poly_driver_two_hosts_dp_x_tp(tmp_path):
    """DP x TP across 2 jax.distributed processes: Megatron-paired
    transformer kernels shard over the host-local `model` axis while the
    data axis spans hosts; the checkpoint must hold FULL kernels
    assembled by the lead host's local_view."""
    total = 200
    outputs = _run_poly_workers(tmp_path, total, mode="dp_tp")
    for i, out in enumerate(outputs):
        assert f"worker {i}: final step" in out
    ckpt = tmp_path / "poly-dist-dp_tp" / "model.ckpt"
    assert ckpt.exists()

    import flax.serialization

    saved = flax.serialization.msgpack_restore(ckpt.read_bytes())
    params = flax.serialization.msgpack_restore(saved["params"])
    wq = params["params"]["block_0"]["q"]["kernel"]
    # Full head dim (128 d_model / 4 heads default): not a model-axis shard.
    assert wq.shape[1] == 4


def test_poly_driver_two_hosts_dp_x_sp(tmp_path):
    """DP x SP across 2 jax.distributed processes: the ring-attention
    shard_map's ppermute spans hosts over the gloo backend while the
    data axis shards the batch; acting (T=1) uses the unmeshed twin's
    dense fallback."""
    total = 240  # unroll 5 -> T+1=6 divides the seq axis of 2
    outputs = _run_poly_workers(tmp_path, total, mode="dp_sp")
    for i, out in enumerate(outputs):
        assert f"worker {i}: final step" in out
    assert (tmp_path / "poly-dist-dp_sp" / "model.ckpt").exists()
