"""Multi-process data parallelism: 2 jax.distributed processes (gloo CPU
collectives), 2 virtual devices each, one global 4-device mesh; the DP
update over process-local batch shards must match a single-device update
(tests/distributed_worker.py)."""

import os
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_dp_update_matches_single_device():
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "distributed_worker.py")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    extra = [
        p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep) if p
    ]
    env = {
        **os.environ,
        "PYTHONPATH": os.pathsep.join([repo_root] + extra),
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(2)
    ]
    outputs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outputs.append(out)
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert "matches single-device OK" in out
