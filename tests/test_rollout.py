"""On-policy bookkeeping invariants — the trickiest part of the framework
(SURVEY.md §7 hard parts). Mirrors the reference's agent-state integration
test (tests/core_agent_state_test.py): a deterministic counting env + a
'model' that increments its state every forward and resets it where done,
asserting (a) rollout overlap-by-one, (b) initial_agent_state equals the
state entering each rollout, (c) boundary steps carry reset frames.

Every invariant runs against BOTH schedules: the synchronous
RolloutCollector and the lag-1 PipelinedRolloutCollector (which must
produce bit-identical batches — the lag is in when the host retrieves
results, never in what the policy saw)."""

import numpy as np
import pytest

from torchbeast_tpu.envs import CountingEnv
from torchbeast_tpu.envs.vec import SerialEnvPool
from torchbeast_tpu.rollout import (
    PipelinedRolloutCollector,
    RolloutCollector,
)
from torchbeast_tpu.types import AgentOutput

B = 2
EPISODE_LEN = 5
T = 3  # deliberately not a divisor of EPISODE_LEN: boundaries move around

COLLECTORS = [RolloutCollector, PipelinedRolloutCollector]


def counting_policy(env_output, agent_state):
    """State += 1 per forward, reset to 0 where done (before the step),
    like the reference test's model (core_agent_state_test.py:26-44)."""
    done = np.asarray(env_output["done"])
    state = np.where(done, 0, agent_state) + 1
    out = AgentOutput(
        action=np.zeros(done.shape, np.int32),
        policy_logits=state.astype(np.float32)[..., None],
        baseline=state.astype(np.float32),
    )
    return out, state


def make_collector(collector_cls=RolloutCollector):
    pool = SerialEnvPool(
        [lambda: CountingEnv(episode_length=EPISODE_LEN) for _ in range(B)]
    )
    return collector_cls(
        pool, counting_policy, np.zeros(B, np.int64), unroll_length=T
    )


@pytest.mark.parametrize("collector_cls", COLLECTORS)
def test_overlap_by_one(collector_cls):
    collector = make_collector(collector_cls)
    prev, _ = collector.collect()
    for _ in range(4):
        batch, _ = collector.collect()
        for key in batch:
            np.testing.assert_array_equal(
                batch[key][0], prev[key][-1],
                err_msg=f"slot 0 of rollout != slot T of previous ({key})",
            )
        prev = batch


@pytest.mark.parametrize("collector_cls", COLLECTORS)
def test_initial_agent_state_is_rollout_entry_state(collector_cls):
    collector = make_collector(collector_cls)
    for k in range(6):
        batch, initial_state = collector.collect()
        # The counting policy writes its post-increment state into
        # baseline; the state entering the first in-rollout forward must be
        # consistent: first forward consumes slot 0's env output, so
        # baseline[1] == (0 if done[0] else initial_state) + 1.
        done0 = batch["done"][0]
        expected_first = np.where(done0, 0, np.asarray(initial_state)) + 1
        np.testing.assert_array_equal(batch["baseline"][1], expected_first)


@pytest.mark.parametrize("collector_cls", COLLECTORS)
def test_boundary_frames_are_reset_frames(collector_cls):
    collector = make_collector(collector_cls)
    for _ in range(8):
        batch, _ = collector.collect()
        done = batch["done"]
        frames = batch["frame"]
        # Wherever done is set, the env auto-reset: the frame stored with
        # the done step is the reset (all-zero) frame.
        assert (frames[done] == 0).all()


@pytest.mark.parametrize("collector_cls", COLLECTORS)
def test_frames_count_within_episode(collector_cls):
    collector = make_collector(collector_cls)
    batch, _ = collector.collect()
    # CountingEnv frames equal episode_step (0 after reset).
    np.testing.assert_array_equal(
        batch["frame"][..., 0, 0, 0],
        np.where(batch["done"], 0, batch["episode_step"]),
    )


@pytest.mark.parametrize("collector_cls", COLLECTORS)
def test_action_pairing(collector_cls):
    """The action stored at slot i was computed from slot i-1's env output
    and equals slot i's last_action input."""
    collector = make_collector(collector_cls)
    batch, _ = collector.collect()
    np.testing.assert_array_equal(
        batch["action"][1:], batch["last_action"][1:]
    )


def test_pipelined_batches_bit_identical_to_sync():
    """Lag-1 is a retrieval schedule, not a data change: both collectors
    over identical env/policy sequences emit identical batches and
    initial states, rollout after rollout."""
    sync = make_collector(RolloutCollector)
    lag1 = make_collector(PipelinedRolloutCollector)
    for _ in range(6):
        b_sync, s_sync = sync.collect()
        b_lag, s_lag = lag1.collect()
        assert set(b_sync) == set(b_lag)
        for key in b_sync:
            np.testing.assert_array_equal(
                b_sync[key], b_lag[key], err_msg=f"batch key {key}"
            )
        np.testing.assert_array_equal(
            np.asarray(s_sync), np.asarray(s_lag)
        )


def test_pipelined_falls_back_without_split_step():
    """A pool exposing only step() (no step_async/step_wait) degrades to
    the synchronous phase order with the same results."""

    class StepOnlyPool:
        def __init__(self, inner):
            self._inner = inner

        def initial(self):
            return self._inner.initial()

        def step(self, actions):
            return self._inner.step(actions)

    pool = SerialEnvPool(
        [lambda: CountingEnv(episode_length=EPISODE_LEN) for _ in range(B)]
    )
    lag1 = PipelinedRolloutCollector(
        StepOnlyPool(pool),
        counting_policy,
        np.zeros(B, np.int64),
        unroll_length=T,
    )
    sync = make_collector(RolloutCollector)
    for _ in range(3):
        b_sync, _ = sync.collect()
        b_lag, _ = lag1.collect()
        for key in b_sync:
            np.testing.assert_array_equal(b_sync[key], b_lag[key])


class TestSplitStepContract:
    """The step_async/step_wait split phase the lag-1 collector overlaps
    against (envs/vec.py)."""

    def make_pool(self):
        return SerialEnvPool(
            [lambda: CountingEnv(episode_length=EPISODE_LEN)
             for _ in range(B)]
        )

    def test_async_then_wait_equals_step(self):
        a = self.make_pool()
        b = self.make_pool()
        a.initial(), b.initial()
        actions = np.zeros(B, np.int64)
        for _ in range(4):
            out_sync = a.step(actions)
            b.step_async(actions)
            out_split = b.step_wait()
            for key in out_sync:
                np.testing.assert_array_equal(out_sync[key], out_split[key])

    def test_double_async_raises(self):
        pool = self.make_pool()
        pool.initial()
        pool.step_async(np.zeros(B, np.int64))
        with pytest.raises(RuntimeError, match="in flight"):
            pool.step_async(np.zeros(B, np.int64))
        pool.step_wait()

    def test_wait_without_async_raises(self):
        pool = self.make_pool()
        pool.initial()
        with pytest.raises(RuntimeError, match="without step_async"):
            pool.step_wait()
