"""On-policy bookkeeping invariants — the trickiest part of the framework
(SURVEY.md §7 hard parts). Mirrors the reference's agent-state integration
test (tests/core_agent_state_test.py): a deterministic counting env + a
'model' that increments its state every forward and resets it where done,
asserting (a) rollout overlap-by-one, (b) initial_agent_state equals the
state entering each rollout, (c) boundary steps carry reset frames."""

import numpy as np

from torchbeast_tpu.envs import CountingEnv
from torchbeast_tpu.envs.vec import SerialEnvPool
from torchbeast_tpu.rollout import RolloutCollector
from torchbeast_tpu.types import AgentOutput

B = 2
EPISODE_LEN = 5
T = 3  # deliberately not a divisor of EPISODE_LEN: boundaries move around


def counting_policy(env_output, agent_state):
    """State += 1 per forward, reset to 0 where done (before the step),
    like the reference test's model (core_agent_state_test.py:26-44)."""
    done = np.asarray(env_output["done"])
    state = np.where(done, 0, agent_state) + 1
    out = AgentOutput(
        action=np.zeros(done.shape, np.int32),
        policy_logits=state.astype(np.float32)[..., None],
        baseline=state.astype(np.float32),
    )
    return out, state


def make_collector():
    pool = SerialEnvPool(
        [lambda: CountingEnv(episode_length=EPISODE_LEN) for _ in range(B)]
    )
    return RolloutCollector(
        pool, counting_policy, np.zeros(B, np.int64), unroll_length=T
    )


def test_overlap_by_one():
    collector = make_collector()
    prev, _ = collector.collect()
    for _ in range(4):
        batch, _ = collector.collect()
        for key in batch:
            np.testing.assert_array_equal(
                batch[key][0], prev[key][-1],
                err_msg=f"slot 0 of rollout != slot T of previous ({key})",
            )
        prev = batch


def test_initial_agent_state_is_rollout_entry_state():
    collector = make_collector()
    for k in range(6):
        batch, initial_state = collector.collect()
        # The counting policy writes its post-increment state into
        # baseline; the state entering the first in-rollout forward must be
        # consistent: first forward consumes slot 0's env output, so
        # baseline[1] == (0 if done[0] else initial_state) + 1.
        done0 = batch["done"][0]
        expected_first = np.where(done0, 0, initial_state) + 1
        np.testing.assert_array_equal(batch["baseline"][1], expected_first)


def test_boundary_frames_are_reset_frames():
    collector = make_collector()
    for _ in range(8):
        batch, _ = collector.collect()
        done = batch["done"]
        frames = batch["frame"]
        # Wherever done is set, the env auto-reset: the frame stored with
        # the done step is the reset (all-zero) frame.
        assert (frames[done] == 0).all()


def test_frames_count_within_episode():
    collector = make_collector()
    batch, _ = collector.collect()
    # CountingEnv frames equal episode_step (0 after reset).
    np.testing.assert_array_equal(
        batch["frame"][..., 0, 0, 0],
        np.where(batch["done"], 0, batch["episode_step"]),
    )


def test_action_pairing():
    """The action stored at slot i was computed from slot i-1's env output
    and equals slot i's last_action input."""
    collector = make_collector()
    batch, _ = collector.collect()
    np.testing.assert_array_equal(
        batch["action"][1:], batch["last_action"][1:]
    )
