"""Remat planner (runtime/remat_plan.py): lattice mechanics, the
synthetic-headroom planning contract, driver flag resolution, and the
model-level remat levers.

The planning contract pinned here (the ISSUE 13 acceptance): over a
synthetic headroom matrix the chosen plan (a) NEVER exceeds the budget
whenever any candidate fits, (b) has the minimum recompute among
fitting candidates — strictly fewer recompute bytes than all-remat
whenever the budget allows anything less, and (c) falls back to
all-remat (today's static default) when nothing fits.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchbeast_tpu.runtime import remat_plan as rp

# ---------------------------------------------------------------------------
# Lattice mechanics


def test_stages_for_families():
    deep = rp.stages_for("deep", use_lstm=True)
    assert [s.name for s in deep] == [
        "stage0", "stage1", "stage2", "core",
    ]
    assert deep[0].options == (False, "front", True)
    assert deep[-1].options == (False, True)
    assert [s.name for s in rp.stages_for("transformer", False)] == [
        "blocks"
    ]
    assert rp.stages_for("mlp", use_lstm=False) == []
    assert [s.name for s in rp.stages_for("mlp", True)] == ["core"]


def test_model_kwargs_mapping():
    assert rp.model_kwargs("deep", {
        "stage0": "front", "stage1": True, "stage2": False,
        "core": True,
    }) == {"remat": ("front", True, False), "core_remat": True}
    assert rp.model_kwargs("transformer", {"blocks": True}) == {
        "remat": True
    }
    assert rp.model_kwargs("mlp", {"core": False}) == {
        "core_remat": False
    }
    assert rp.model_kwargs("mlp", {}) == {}


def test_enumerate_order_min_recompute_first():
    stages = rp.stages_for("deep", use_lstm=False)
    cands = rp.enumerate_assignments(stages)
    assert len(cands) == 27
    assert cands[0] == rp.no_remat(stages)
    assert cands[-1] == rp.all_remat(stages)
    # Rank (sum of option indices) is non-decreasing along the order.
    def rank(a):
        return sum(
            s.options.index(a[s.name]) for s in stages
        )
    ranks = [rank(c) for c in cands]
    assert ranks == sorted(ranks)


def test_parse_spec_round_trip_and_errors():
    stages = rp.stages_for("deep", use_lstm=True)
    spec = "stage0=front,stage1=all,stage2=none,core=all"
    parsed = rp.parse_spec(spec, stages)
    assert parsed == {
        "stage0": "front", "stage1": True, "stage2": False,
        "core": True,
    }
    assert rp.parse_spec(rp.spell(parsed), stages) == parsed
    with pytest.raises(ValueError, match="unknown stage"):
        rp.parse_spec("bogus=all", stages)
    with pytest.raises(ValueError, match="must be one of"):
        rp.parse_spec("stage0=sometimes", stages)
    with pytest.raises(ValueError, match="misses stages"):
        rp.parse_spec("stage0=all", stages)
    with pytest.raises(ValueError, match="repeats"):
        rp.parse_spec(
            "stage0=all,stage0=none,stage1=all,stage2=all,core=all",
            stages,
        )
    with pytest.raises(ValueError, match="no 'front' option"):
        rp.parse_spec(
            "stage0=all,stage1=all,stage2=all,core=front", stages
        )


# ---------------------------------------------------------------------------
# The synthetic-headroom planning contract


def _synthetic_cost(stages):
    """Deterministic headroom matrix: each remat level frees 10 units
    of peak and costs 7 units of recompute; the no-remat peak is 100."""
    def cost(assignment):
        level = sum(
            s.options.index(assignment[s.name]) for s in stages
        )
        return float(100 - 10 * level), float(7 * level)
    return cost


@pytest.mark.parametrize("budget", [25.0, 45.0, 65.0, 85.0, 100.0, 500.0])
def test_never_exceeds_budget_and_min_recompute(budget):
    stages = rp.stages_for("deep", use_lstm=True)  # 54 candidates
    cost = _synthetic_cost(stages)
    plan = rp.plan_remat(stages, cost, budget)
    peak, recompute = cost(plan.assignment)
    all_peak, all_recompute = cost(rp.all_remat(stages))
    fits_exist = any(
        cost(a)[0] <= budget
        for a in rp.enumerate_assignments(stages)
    )
    if fits_exist:
        assert plan.source == "auto"
        # (a) never exceeds the budget
        assert peak <= budget
        assert plan.peak_bytes == peak
        # (b) true minimum recompute among fitting candidates
        best = min(
            cost(a)[1]
            for a in rp.enumerate_assignments(stages)
            if cost(a)[0] <= budget
        )
        assert recompute == best
        # The ISSUE gate: strictly fewer recompute bytes than
        # all-remat whenever the budget allows anything less.
        if budget > all_peak:
            assert recompute < all_recompute
    else:
        # (c) all-remat fallback
        assert plan.source == "fallback"
        assert plan.assignment == rp.all_remat(stages)


def test_fallback_when_nothing_fits():
    stages = rp.stages_for("mlp", use_lstm=True)
    plan = rp.plan_remat(stages, _synthetic_cost(stages), 1.0)
    assert plan.source == "fallback"
    assert plan.assignment == rp.all_remat(stages)
    # The fallback's own figures surface in the result (it was
    # evaluated as a candidate even though it does not fit).
    assert plan.peak_bytes is not None


def test_unmeasurable_candidates_never_chosen():
    stages = rp.stages_for("mlp", use_lstm=True)

    def cost(assignment):
        if not assignment["core"]:
            return None, None  # oracle failure for the tempting plan
        return 10.0, 7.0

    plan = rp.plan_remat(stages, cost, 1000.0)
    assert plan.assignment == {"core": True}
    table = {r["assignment"]: r for r in plan.table}
    assert table["core=none"]["fits"] is False


def test_lazy_walk_stops_at_first_fit():
    stages = rp.stages_for("deep", use_lstm=False)
    calls = []
    cost = _synthetic_cost(stages)

    def counting(assignment):
        calls.append(dict(assignment))
        return cost(assignment)

    plan = rp.plan_remat(stages, counting, 500.0, lazy=True)
    assert len(calls) == 1  # huge budget: the first candidate fits
    assert plan.assignment == rp.no_remat(stages)


# ---------------------------------------------------------------------------
# Real-model lever sanity + driver flag resolution


def test_lstm_core_remat_is_numerically_transparent():
    from torchbeast_tpu.models import create_model

    rng = np.random.default_rng(0)
    t, b, a = 5, 3, 4
    batch = {
        "frame": rng.integers(0, 256, (t, b, 4, 4, 1), dtype=np.uint8),
        "reward": rng.standard_normal((t, b)).astype(np.float32),
        "done": rng.random((t, b)) < 0.2,
        "last_action": rng.integers(0, a, (t, b)).astype(np.int32),
    }
    outs = {}
    for remat in (False, True):
        model = create_model(
            "mlp", num_actions=a, use_lstm=True, core_remat=remat
        )
        state = model.initial_state(b)
        params = model.init(
            {
                "params": jax.random.PRNGKey(0),
                "action": jax.random.PRNGKey(1),
            },
            batch,
            state,
        )

        def loss(p):
            (out, _), _ = model.apply(
                p, batch, state, sample_action=False,
                mutable=["losses"],
            )
            return (
                jnp.sum(out.policy_logits ** 2) + jnp.sum(out.baseline)
            )

        value, grads = jax.value_and_grad(loss)(params)
        outs[remat] = (value, grads)
    # Same params tree either way (nn.remat must not rescope), same
    # forward, same grads to reassociation tolerance.
    assert (
        jax.tree_util.tree_structure(outs[False][1])
        == jax.tree_util.tree_structure(outs[True][1])
    )
    np.testing.assert_allclose(
        float(outs[False][0]), float(outs[True][0]), rtol=1e-6
    )
    for g0, g1 in zip(
        jax.tree_util.tree_leaves(outs[False][1]),
        jax.tree_util.tree_leaves(outs[True][1]),
    ):
        np.testing.assert_allclose(
            np.asarray(g0), np.asarray(g1), rtol=1e-4, atol=1e-6
        )


def _flags(args):
    from torchbeast_tpu import monobeast

    return monobeast.make_parser().parse_args(args)


def _resolve(flags):
    from torchbeast_tpu import monobeast
    from torchbeast_tpu import precision as precision_lib
    from torchbeast_tpu.models import create_model

    policy = precision_lib.resolve_flags(flags)
    hp = monobeast.hparams_from_flags(flags)
    return rp.resolve_from_flags(
        flags, hp, 4, (4, 4, 1), np.uint8, policy,
        build_model=lambda kw: create_model(
            flags.model, num_actions=4, use_lstm=flags.use_lstm,
            dtype=policy.compute_dtype, **kw,
        ),
    )


def test_resolve_default_matches_pre_planner_behavior():
    plan = _resolve(_flags(["--model", "deep", "--use_lstm"]))
    assert plan.source == "default"
    assert plan.assignment == {
        "stage0": True, "stage1": True, "stage2": True, "core": False,
    }
    # --transformer_remat keeps working as the deprecated spelling.
    plan = _resolve(_flags(["--model", "transformer"]))
    assert plan.assignment == {"blocks": False}
    plan = _resolve(
        _flags(["--model", "transformer", "--transformer_remat"])
    )
    assert plan.assignment == {"blocks": True}


def test_resolve_all_none_spec_and_conflict():
    plan = _resolve(_flags(["--model", "deep", "--remat", "none"]))
    assert plan.source == "none"
    assert plan.assignment == {
        "stage0": False, "stage1": False, "stage2": False,
    }
    plan = _resolve(_flags(["--model", "deep", "--remat", "all"]))
    assert plan.assignment == {
        "stage0": True, "stage1": True, "stage2": True,
    }
    plan = _resolve(_flags([
        "--model", "deep", "--remat",
        "stage0=front,stage1=all,stage2=none",
    ]))
    assert plan.source == "spec"
    assert plan.assignment == {
        "stage0": "front", "stage1": True, "stage2": False,
    }
    with pytest.raises(ValueError, match="deprecated"):
        _resolve(_flags([
            "--model", "transformer", "--transformer_remat",
            "--remat", "all",
        ]))


def test_resolve_auto_runs_planner_and_caches():
    """`--remat auto` on the tiny LSTM picks the no-recompute plan
    under the huge default budget, exports a non-empty table, and the
    second resolution (polybeast's acting-twin rebuild) is served from
    the cache."""
    flags = _flags([
        "--model", "mlp", "--use_lstm", "--remat", "auto",
        "--unroll_length", "4", "--batch_size", "2",
        "--num_actors", "2",
    ])
    plan = _resolve(flags)
    assert plan.source == "auto"
    assert plan.assignment == {"core": False}
    assert plan.peak_bytes is not None and plan.peak_bytes > 0
    assert plan.table
    assert rp.last_plan() is plan
    assert _resolve(flags) is plan  # memoized


def test_driver_model_init_applies_plan():
    """_init_model_and_params threads the resolved plan into the
    constructed model for both a spec and the legacy default."""
    from torchbeast_tpu import monobeast

    flags = _flags([
        "--model", "mlp", "--use_lstm", "--remat", "core=all",
        "--unroll_length", "4", "--batch_size", "2",
        "--num_actors", "2",
    ])
    model, params = monobeast._init_model_and_params(
        flags, 4, 2, (4, 4, 1)
    )
    assert model.core_remat is True
    assert params is not None
    flags = _flags(["--model", "mlp", "--use_lstm"])
    model, _ = monobeast._init_model_and_params(
        flags, 4, 2, (4, 4, 1), init_params=False
    )
    assert model.core_remat is False


def test_superstep_cost_oracle_reports_peak_and_recompute():
    """The driver's cost oracle measures the real (super)step: peak and
    recompute both populated, and the all-remat LSTM plan reads MORE
    pre-opt bytes (the recompute is visible) while saving temp
    allocation."""
    from torchbeast_tpu import learner as learner_lib
    from torchbeast_tpu.models import create_model

    hp = learner_lib.HParams(unroll_length=8, batch_size=4)
    stages = rp.stages_for("mlp", use_lstm=True)
    cost_fn = rp.superstep_cost_fn(
        lambda kw: create_model(
            "mlp", num_actions=4, use_lstm=True, **kw
        ),
        hp, 2,
        rp.learner_batch_structs(hp, 4, (4, 4, 1), np.uint8),
        hp.batch_size, "mlp",
    )
    peak_none, rec_none = cost_fn({"core": False})
    peak_all, rec_all = cost_fn({"core": True})
    assert all(
        v is not None for v in (peak_none, rec_none, peak_all, rec_all)
    )
    assert rec_all > rec_none  # recompute shows up in pre-opt bytes
