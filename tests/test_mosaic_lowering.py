"""Mosaic TPU lowering regression tests — no chip required.

`jax.export` with `platforms=["tpu"]` runs the full Pallas→Mosaic
lowering pipeline (including the block-mapping legality checks in
jax/_src/pallas/mosaic/lowering.py) client-side on any backend. The
round-5 chip smoke caught two lowering failures that every CPU
interpret-mode test had missed (block shapes whose trailing dims were
neither (8,128)-divisible nor full-extent; a scoped-VMEM overflow at
trunk shape); this file pins the lowering of both kernels at both the
unit-test and flagship shapes so the class of bug is caught in CI, not
on chip day. (The scoped-VMEM budget itself is enforced analytically by
pallas_pool._auto_block_n — backend compilation, which export does NOT
run, is still only exercised by benchmarks/pallas_smoke.py on a real
tunnel.)
"""

import numpy as np
import pytest

import jax
import jax.export
import jax.numpy as jnp
from jax import lax

from tests import jax_caps

from torchbeast_tpu.ops.pallas_attention import transformer_attention
from torchbeast_tpu.ops.pallas_pool import (
    _VMEM_BLOCK_BUDGET,
    _auto_block_n,
    pool_bwd,
)


def _attn_inputs(b, t, h, d, m, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((b, t, h, d)).astype(np.float32))
    k = jnp.asarray(
        rng.standard_normal((b, m + t, h, d)).astype(np.float32)
    )
    v = jnp.asarray(
        rng.standard_normal((b, m + t, h, d)).astype(np.float32)
    )
    done = rng.random((t, b)) < 0.15
    seg = jnp.asarray(np.cumsum(done, axis=0).T.astype(np.int32))
    cache_valid = jnp.asarray((rng.random((b, m)) < 0.7).astype(np.float32))
    no_done = jnp.asarray(np.cumsum(done, axis=0).T == 0)
    rel_bias = jnp.asarray(
        rng.standard_normal((h, m + 1)).astype(np.float32) * 0.1
    )
    return q, k, v, seg, cache_valid, no_done, rel_bias


@pytest.mark.parametrize(
    "b,t,h,d,m",
    [
        (2, 12, 4, 16, 8),    # unit-test shape (pre-fix: block-shape fail)
        (8, 20, 4, 64, 40),   # flagship transformer unroll shape
        (1, 1, 4, 64, 40),    # stepwise acting (T=1)
    ],
)
@pytest.mark.skipif(
    not jax_caps.mosaic_lowers_stop_gradient(),
    reason="this jax's Mosaic lowering has no stop_gradient rule "
           "(the attention kernel uses it)",
)
def test_attention_lowers_for_tpu(b, t, h, d, m):
    args = _attn_inputs(b, t, h, d, m)
    jax.export.export(
        jax.jit(lambda *a: transformer_attention(m, False, *a)),
        platforms=["tpu"],
    )(*args)


@pytest.mark.parametrize(
    "shape",
    [
        (2, 21, 21, 32),   # unit-test shape
        (8, 84, 84, 32),   # trunk stage-1 (pre-fix: scoped-VMEM OOM)
        (640, 84, 84, 32), # full T*B learner batch
    ],
)
def test_pool_bwd_lowers_for_tpu(shape):
    def fwd(x):
        return lax.reduce_window(
            x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
            ((0, 0), (1, 1), (1, 1), (0, 0)),
        )

    # Lowering only needs avals — abstract args keep the (640, 84, 84,
    # 32) case allocation-free instead of materializing ~580 MB.
    x = jax.ShapeDtypeStruct(shape, jnp.float32)
    y = jax.eval_shape(fwd, x)
    g = jax.ShapeDtypeStruct(y.shape, jnp.float32)
    jax.export.export(
        jax.jit(lambda x, y, g: pool_bwd(x, y, g)), platforms=["tpu"]
    )(x, y, g)


@pytest.mark.parametrize("param_dtype", ["f32", "bf16"])
def test_opt_tail_lowers_for_tpu(param_dtype):
    """The fused optimizer tail (ops/pallas_opt.py) lowers to Mosaic at
    the real leaf-shape zoo — odd 1-D biases, non-128 last dims, a
    trunk-fc-sized matrix that takes the chunked-grid path — in both
    resident dtypes, momentum on (the widest kernel arity)."""
    from torchbeast_tpu.ops.pallas_opt import fused_rmsprop_tail

    dt = jnp.bfloat16 if param_dtype == "bf16" else jnp.float32
    shapes = [(532,), (133, 532), (16, 128), (1,), (3872, 256)]
    params = {
        f"leaf{i}": jax.ShapeDtypeStruct(s, dt)
        for i, s in enumerate(shapes)
    }
    grads = params
    opt = fused_rmsprop_tail(
        4.8e-4, decay=0.99, eps=0.01, momentum=0.9, max_norm=40.0,
        param_dtype=param_dtype,
        state_dtype=jnp.bfloat16 if param_dtype == "bf16" else None,
        interpret=False,
    )
    state = jax.eval_shape(opt.init, params)
    jax.export.export(
        jax.jit(opt.update), platforms=["tpu"]
    )(grads, state, params)


def test_auto_block_n_respects_vmem_budget():
    # Trunk stage-1: one batch row's buffers are ~3.7 MB against the
    # 5 MB budget, so the auto choice must be 1; the tiny test shape
    # should batch several rows.
    assert _auto_block_n(84, 84 * 32, 42, (2 * 42 + 2) * 32) == 1
    assert _auto_block_n(21, 21 * 32, 11, (2 * 11 + 2) * 32) > 1
    # The chosen block never exceeds the budget.
    for (H, WC, Ho, WoC2) in [
        (84, 84 * 32, 42, 86 * 32),
        (21, 21 * 32, 11, 24 * 32),
        (210, 210 * 64, 105, 212 * 64),
    ]:
        bn = _auto_block_n(H, WC, Ho, WoC2)
        per_n = 4 * (2 * H * WC + 2 * (2 * Ho + 2) * WoC2)
        assert bn * per_n <= max(_VMEM_BLOCK_BUDGET, per_n)
