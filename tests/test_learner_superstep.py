"""Learner superstep contracts (ISSUE 4): K scanned updates must be
BIT-identical (CPU backend) to K sequential make_update_step dispatches
on the same batches — including the optimizer `count` clock that the LR
decay and entropy anneal divide by (the easy off-by-K bug) — plus the
consume-once batch-donation semantics and the host-side staging
helpers."""

import warnings

import numpy as np
import pytest

import jax

from torchbeast_tpu import learner as learner_lib
from torchbeast_tpu.models import create_model

T, B, A = 4, 2, 3
FRAME = (4, 4, 1)


def make_batch(rng, t=T, b=B):
    return {
        "frame": rng.integers(0, 256, (t + 1, b) + FRAME, dtype=np.uint8),
        "reward": rng.standard_normal((t + 1, b)).astype(np.float32),
        "done": rng.random((t + 1, b)) < 0.2,
        "episode_return": rng.standard_normal((t + 1, b)).astype(
            np.float32
        ),
        "episode_step": rng.integers(0, 100, (t + 1, b)).astype(np.int32),
        "last_action": rng.integers(0, A, (t + 1, b)).astype(np.int32),
        "action": rng.integers(0, A, (t + 1, b)).astype(np.int32),
        "policy_logits": rng.standard_normal((t + 1, b, A)).astype(
            np.float32
        ),
        "baseline": rng.standard_normal((t + 1, b)).astype(np.float32),
    }


def _setup(use_lstm, entropy_anneal, seed=0):
    # A short total_steps horizon makes the schedules move VISIBLY
    # between consecutive updates, so a schedule clock that ticked
    # per-dispatch instead of per-update could not stay bit-identical.
    hp = learner_lib.HParams(
        unroll_length=T,
        batch_size=B,
        total_steps=20 * T * B,
        entropy_cost_final=0.00001 if entropy_anneal else None,
    )
    model = create_model("mlp", num_actions=A, use_lstm=use_lstm)
    state = model.initial_state(B)
    rng = np.random.default_rng(seed)
    dummy = make_batch(rng, t=0)
    params = model.init(
        {"params": jax.random.PRNGKey(seed),
         "action": jax.random.PRNGKey(seed + 1)},
        dummy,
        state,
    )
    optimizer = learner_lib.make_optimizer(hp)
    opt_state = optimizer.init(params)
    return hp, model, optimizer, params, opt_state, rng


def _np_state(model, b=B):
    return jax.tree_util.tree_map(
        np.asarray, model.initial_state(b)
    )


def _stack(trees):
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *trees)


def assert_trees_bit_equal(a, b, what):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=what
        )


@pytest.mark.parametrize("use_lstm", [False, True],
                         ids=["mlp", "lstm"])
@pytest.mark.parametrize("entropy_anneal", [False, True],
                         ids=["const", "anneal"])
def test_superstep_bit_identical_to_sequential(use_lstm, entropy_anneal):
    """K in {1, 2, 4} scanned updates == the first K sequential
    dispatches of the same batch sequence, bit for bit: params,
    opt_state, AND every per-update stats leaf (scan slot i ==
    sequential update i)."""
    hp, model, optimizer, params, opt_state, rng = _setup(
        use_lstm, entropy_anneal
    )
    ks = (1, 2, 4)
    n = max(ks)
    batches = [make_batch(rng) for _ in range(n)]
    states = [_np_state(model) for _ in range(n)]

    update_step = learner_lib.make_update_step(
        model, optimizer, hp, donate=False
    )
    seq_params, seq_opt = [], []
    seq_stats = []
    p, o = params, opt_state
    for i in range(n):
        p, o, st = update_step(p, o, batches[i], states[i])
        seq_params.append(p)
        seq_opt.append(o)
        seq_stats.append(jax.device_get(st))

    for k in ks:
        superstep = learner_lib.make_update_superstep(
            model, optimizer, hp, k, donate=False
        )
        stacked_b = {
            key: np.stack([batches[i][key] for i in range(k)])
            for key in batches[0]
        }
        stacked_s = _stack(states[:k])
        p_k, o_k, stats_k = superstep(
            params, opt_state, stacked_b, stacked_s
        )
        assert_trees_bit_equal(
            p_k, seq_params[k - 1], f"params diverge at K={k}"
        )
        assert_trees_bit_equal(
            o_k, seq_opt[k - 1],
            f"opt_state (incl. schedule count) diverges at K={k}",
        )
        stats_k = jax.device_get(stats_k)
        for i in range(k):
            for key, v in seq_stats[i].items():
                np.testing.assert_array_equal(
                    np.asarray(stats_k[key])[i], np.asarray(v),
                    err_msg=f"stats[{key}] scan slot {i} at K={k}",
                )


def test_superstep_schedule_ticks_per_update_not_per_dispatch():
    """After one K=4 dispatch the optimizer count must read 4: a clock
    that ticked once per dispatch would anneal the LR/entropy 4x too
    slowly (the off-by-K bug the issue calls out)."""
    import optax

    hp, model, optimizer, params, opt_state, rng = _setup(
        use_lstm=False, entropy_anneal=True
    )
    superstep = learner_lib.make_update_superstep(
        model, optimizer, hp, 4, donate=False
    )
    batches = [make_batch(rng) for _ in range(4)]
    stacked_b = {
        key: np.stack([b[key] for b in batches]) for key in batches[0]
    }
    stacked_s = _stack([_np_state(model) for _ in range(4)])
    _, opt_after, _ = superstep(params, opt_state, stacked_b, stacked_s)
    count = optax.tree_utils.tree_get(jax.device_get(opt_after), "count")
    assert int(count) == 4


def test_donate_batch_superstep_no_warning_and_use_after_free():
    """donate_batch=True on the superstep must (a) produce the same
    numbers as the undonated run, (b) emit NO 'donated buffers were not
    usable' XLA warning (the staging stack is consumed host-side, never
    handed to donate_argnums — it has no batch-shaped output to alias),
    and (c) enforce consume-once: re-reading the staged stack after
    dispatch raises instead of silently training on stale data."""
    hp, model, optimizer, params, opt_state, rng = _setup(
        use_lstm=True, entropy_anneal=False
    )
    k = 2
    batches = [make_batch(rng) for _ in range(k)]
    stacked_b = {
        key: np.stack([b[key] for b in batches]) for key in batches[0]
    }
    stacked_s = _stack([_np_state(model) for _ in range(k)])

    ref = learner_lib.make_update_superstep(
        model, optimizer, hp, k, donate=False
    )
    p_ref, o_ref, stats_ref = ref(params, opt_state, stacked_b, stacked_s)

    donating = learner_lib.make_update_superstep(
        model, optimizer, hp, k, donate=True, donate_batch=True
    )
    staged_b = jax.device_put(stacked_b)
    staged_s = jax.device_put(stacked_s)
    p_in = jax.device_put(params)
    o_in = jax.device_put(opt_state)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        p_d, o_d, stats_d = donating(p_in, o_in, staged_b, staged_s)
        jax.block_until_ready(p_d)
    donation_warnings = [
        str(w.message) for w in caught
        if "donated buffers were not usable" in str(w.message).lower()
    ]
    assert donation_warnings == []

    assert_trees_bit_equal(p_d, p_ref, "donated params differ")
    assert_trees_bit_equal(o_d, o_ref, "donated opt_state differs")
    assert_trees_bit_equal(
        jax.device_get(stats_d), jax.device_get(stats_ref),
        "donated stats differ",
    )

    # Consume-once: every staged batch leaf is dead after dispatch.
    # beastlint: disable=DONATE-USE  this test IS the use-after-free pin: reads must raise
    for leaf in jax.tree_util.tree_leaves((staged_b, staged_s)):
        with pytest.raises(RuntimeError, match="deleted"):
            np.asarray(leaf)


def test_make_update_superstep_rejects_bad_k():
    hp, model, optimizer, *_ = _setup(False, False)
    with pytest.raises(ValueError, match="superstep k"):
        learner_lib.make_update_superstep(model, optimizer, hp, 0)


def test_stack_superstep_columns_matches_slices():
    """The sync driver's staging helper: [K, T+1, cols] stacks must be
    exactly the K consecutive column-group slices, and the staged
    arrays must be fresh (not views of the collector's batch)."""
    rng = np.random.default_rng(3)
    wide = make_batch(rng, b=8)
    state = (rng.standard_normal((1, 8, 6)).astype(np.float32),)
    stacked, stacked_state = learner_lib.stack_superstep_columns(
        wide, state, k=2, columns=2, offset=4
    )
    for key, v in wide.items():
        assert stacked[key].shape[:2] == (2, T + 1)
        np.testing.assert_array_equal(stacked[key][0], v[:, 4:6])
        np.testing.assert_array_equal(stacked[key][1], v[:, 6:8])
        assert not np.shares_memory(stacked[key], v)
    np.testing.assert_array_equal(stacked_state[0][0], state[0][:, 4:6])
    np.testing.assert_array_equal(stacked_state[0][1], state[0][:, 6:8])


def test_episode_stat_postprocess_scalar_and_stacked_agree():
    """[K]-stacked stats must aggregate to exactly what K per-update
    flushes would have produced: episode sums/counts SUM, losses MEAN."""
    per_update = [
        {"total_loss": 2.0, "episode_returns_sum": 3.0,
         "episode_count": 2.0},
        {"total_loss": 4.0, "episode_returns_sum": 1.0,
         "episode_count": 0.0},
    ]
    stacked = {
        key: np.asarray([s[key] for s in per_update])
        for key in per_update[0]
    }
    out = learner_lib.episode_stat_postprocess(stacked)
    assert out["total_loss"] == pytest.approx(3.0)
    assert out["episodes_finished"] == pytest.approx(2.0)
    # Sum over the stack / sum of counts — not mean-of-means.
    assert out["mean_episode_return"] == pytest.approx(4.0 / 2.0)
    # Scalar leaves keep their exact legacy behavior.
    legacy = learner_lib.episode_stat_postprocess(
        {"total_loss": 2.0, "episode_returns_sum": 3.0,
         "episode_count": 2.0}
    )
    assert legacy["total_loss"] == 2.0
    assert legacy["mean_episode_return"] == 1.5


def test_instrument_update_step_superstep_accounting():
    """K updates per dispatch must land in the counters as K (no /K
    undercount), with the amortization visible: superstep_k gauge,
    updates_per_dispatch histogram, and a host_syncs counter the driver
    ticks per stats flush."""
    from torchbeast_tpu.telemetry import MetricsRegistry

    reg = MetricsRegistry()
    calls = []

    def fake_update(params, opt_state, batch, state):
        calls.append(1)
        return params, opt_state, {}

    wrapped = learner_lib.instrument_update_step(
        fake_update, registry=reg, superstep_k=4
    )
    batch = {"x": np.zeros((4, 5, 2), np.float32)}
    for _ in range(3):
        wrapped(None, None, batch, ())
        wrapped.count_host_sync()
    assert len(calls) == 3
    assert reg.counter("learner.updates").value() == 12
    assert reg.counter("learner.host_syncs").value() == 3
    stats = reg.histogram("learner.updates_per_dispatch").stats()
    assert stats["count"] == 3 and stats["mean"] == pytest.approx(4.0)
    assert reg.gauge("learner.superstep_k").value() == 4
