"""Precision-policy layer (torchbeast_tpu/precision.py + the learner's
bf16-resident training path): policy resolution incl. the deprecated
--model_dtype alias, staging casts, the f32-accumulate optimizer
contracts (bf16 second moment, f32 master params, factored state), the
fused-loss parity pin, and the bytes-accessed accounting."""

import argparse
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchbeast_tpu import learner as learner_lib
from torchbeast_tpu import precision as precision_lib
from torchbeast_tpu.models import create_model

T, B, A = 8, 4, 3
FRAME = (4, 4, 1)


def make_batch(rng, t=T, b=B):
    return {
        "frame": rng.integers(0, 256, (t + 1, b) + FRAME, dtype=np.uint8),
        "reward": rng.standard_normal((t + 1, b)).astype(np.float32),
        "done": rng.random((t + 1, b)) < 0.1,
        "episode_return": rng.standard_normal((t + 1, b)).astype(
            np.float32
        ),
        "episode_step": rng.integers(0, 200, (t + 1, b)).astype(np.int32),
        "last_action": rng.integers(0, A, (t + 1, b)).astype(np.int32),
        "action": rng.integers(0, A, (t + 1, b)).astype(np.int32),
        "policy_logits": rng.standard_normal((t + 1, b, A)).astype(
            np.float32
        ),
        "baseline": rng.standard_normal((t + 1, b)).astype(np.float32),
    }


def _flags(**kw):
    ns = argparse.Namespace(precision="f32", model_dtype=None)
    for k, v in kw.items():
        setattr(ns, k, v)
    return ns


def _build(precision, use_lstm=False, **hp_kw):
    pol = precision_lib.get(precision)
    hp = learner_lib.HParams(
        unroll_length=T, batch_size=B, total_steps=1_000_000,
        opt_state_dtype=pol.opt_state_dtype,
        param_dtype=pol.param_dtype, **hp_kw,
    )
    model = create_model(
        "mlp", num_actions=A, use_lstm=use_lstm,
        dtype=pol.compute_dtype, head_dtype=pol.head_dtype,
    )
    rng = np.random.default_rng(0)
    params = model.init(
        {
            "params": jax.random.PRNGKey(0),
            "action": jax.random.PRNGKey(1),
        },
        make_batch(rng, t=0),
        model.initial_state(B),
    )
    params = precision_lib.cast_params(params, pol)
    optimizer = learner_lib.make_optimizer(hp)
    return pol, hp, model, params, optimizer, rng


class TestPolicyResolution:
    def test_table(self):
        assert precision_lib.get("f32").compute_dtype == jnp.float32
        bt = precision_lib.get("bf16_train")
        assert bt.compute_dtype == jnp.bfloat16
        assert bt.head_dtype == jnp.bfloat16
        assert bt.param_dtype == "bf16"
        assert bt.opt_state_dtype == "bf16"
        with pytest.raises(ValueError, match="Unknown precision"):
            precision_lib.get("fp8")

    def test_legacy_model_dtype_aliases_bf16_compute(self, caplog):
        precision_lib.resolve_flags._warned_model_dtype = False
        with caplog.at_level("WARNING"):
            pol = precision_lib.resolve_flags(
                _flags(model_dtype="bfloat16")
            )
        assert pol.name == "bf16_compute"
        assert any(
            "deprecated" in r.message for r in caplog.records
        )

    def test_legacy_conflicts_with_explicit_bf16_train(self):
        with pytest.raises(ValueError, match="conflicts"):
            precision_lib.resolve_flags(
                _flags(precision="bf16_train", model_dtype="bfloat16")
            )

    def test_float32_legacy_is_silent_noop(self):
        pol = precision_lib.resolve_flags(
            _flags(model_dtype="float32")
        )
        assert pol.name == "f32"


class TestCasts:
    def test_cast_batch_converts_only_f32(self):
        rng = np.random.default_rng(1)
        batch = make_batch(rng)
        cast = precision_lib.cast_batch(
            batch, precision_lib.get("bf16_train").batch_dtype
        )
        import ml_dtypes

        assert cast["reward"].dtype == ml_dtypes.bfloat16
        assert cast["policy_logits"].dtype == ml_dtypes.bfloat16
        assert cast["frame"].dtype == np.uint8
        assert cast["action"].dtype == np.int32
        assert cast["done"].dtype == bool
        # None policy: identity.
        same = precision_lib.cast_batch(batch, None)
        assert same["reward"].dtype == np.float32

    def test_cast_params_bf16_resident(self):
        pol, _, _, params, _, _ = _build("bf16_train")
        for leaf in jax.tree_util.tree_leaves(params):
            assert leaf.dtype in (jnp.bfloat16, jnp.int32), leaf.dtype

    def test_arena_float_dtype_staging(self):
        """BatchArena(float_dtype=bf16): the write-through copy IS the
        cast; non-float leaves keep their dtype."""
        import ml_dtypes

        from torchbeast_tpu.runtime.queues import (
            BatchArena,
            BatchingQueue,
        )

        rng = np.random.default_rng(2)
        queue = BatchingQueue(batch_dim=1)
        arena = BatchArena(
            k=2, rows=2, batch_dim=1, float_dtype=ml_dtypes.bfloat16
        )
        items = [
            {
                "x": rng.standard_normal((3, 1)).astype(np.float32),
                "n": rng.integers(0, 9, (3, 1)).astype(np.int32),
            }
            for _ in range(4)
        ]
        for item in items:
            queue.enqueue(item)
        stacked, release = arena.assemble_from(queue)
        assert stacked["x"].dtype == ml_dtypes.bfloat16
        assert stacked["n"].dtype == np.int32
        # Values equal to a direct cast of the concatenated columns.
        want = np.stack([
            np.concatenate([items[0]["x"], items[1]["x"]], axis=1),
            np.concatenate([items[2]["x"], items[3]["x"]], axis=1),
        ]).astype(ml_dtypes.bfloat16)
        np.testing.assert_array_equal(
            np.asarray(stacked["x"]), want
        )
        release()


class TestOptimizerState:
    def test_bf16_second_moment_tracks_f32_within_tolerance(self):
        """bf16 nu storage with f32 EMA accumulate: a short update
        trajectory stays within bf16 rounding of the all-f32 one."""
        hp32 = learner_lib.HParams(
            unroll_length=T, batch_size=B, total_steps=1_000_000
        )
        hp16 = hp32._replace(opt_state_dtype="bf16")
        grads = {
            "w": jnp.asarray(
                np.random.default_rng(0).standard_normal((8, 8)),
                jnp.float32,
            )
        }
        params = {"w": jnp.zeros((8, 8), jnp.float32)}
        o32 = learner_lib.make_optimizer(hp32)
        o16 = learner_lib.make_optimizer(hp16)
        s32, s16 = o32.init(params), o16.init(params)
        p32, p16 = params, params
        import optax

        for _ in range(5):
            u32, s32 = o32.update(grads, s32, p32)
            p32 = optax.apply_updates(p32, u32)
            u16, s16 = o16.update(grads, s16, p16)
            p16 = optax.apply_updates(p16, u16)
        np.testing.assert_allclose(
            p16["w"], p32["w"], rtol=2e-2, atol=1e-4
        )

    def test_bf16_nu_stored_half_width(self):
        hp16 = learner_lib.HParams(
            unroll_length=T, batch_size=B, total_steps=1_000_000,
            opt_state_dtype="bf16",
        )
        params = {"w": jnp.zeros((4, 4), jnp.float32)}
        state = learner_lib.make_optimizer(hp16).init(params)
        nus = [
            leaf for leaf in jax.tree_util.tree_leaves(state)
            if getattr(leaf, "shape", None) == (4, 4)
        ]
        assert nus and all(n.dtype == jnp.bfloat16 for n in nus)

    def test_factored_state_is_row_col(self):
        hp = learner_lib.HParams(
            unroll_length=T, batch_size=B, total_steps=1_000_000,
            opt_factored=True,
        )
        params = {
            "w": jnp.zeros((6, 4), jnp.float32),
            "b": jnp.zeros((4,), jnp.float32),
        }
        opt = learner_lib.make_optimizer(hp)
        state = opt.init(params)
        leaves = [
            s for s in jax.tree_util.tree_leaves(state)
            if hasattr(s, "shape")
        ]
        shapes = {tuple(leaf.shape) for leaf in leaves}
        # Matrix leaf: row (6,) + col (4,) EMAs, NO (6, 4) accumulator;
        # vector leaf keeps its full (4,) nu.
        assert (6,) in shapes and (4,) in shapes
        assert (6, 4) not in shapes
        # And it optimizes: a few steps shrink a quadratic.
        import optax

        def loss(p):
            return jnp.sum(jnp.square(p["w"] - 1.0)) + jnp.sum(
                jnp.square(p["b"] + 2.0)
            )

        p = params
        before = float(loss(p))
        for _ in range(20):
            g = jax.grad(loss)(p)
            u, state = opt.update(g, state, p)
            p = optax.apply_updates(p, u)
        assert float(loss(p)) < before

    def test_bf16_resident_master_round_trip(self):
        """Resident params after an update == bf16(new f32 master); the
        master itself never sees bf16 rounding."""
        pol, hp, model, params, optimizer, rng = _build("bf16_train")
        opt_state = optimizer.init(params)
        assert isinstance(opt_state, learner_lib.MasterParamsState)
        for leaf in jax.tree_util.tree_leaves(opt_state.master):
            assert leaf.dtype == jnp.float32
        update_step = learner_lib.make_update_step(
            model, optimizer, hp, donate=False
        )
        batch = precision_lib.cast_batch(
            make_batch(rng), pol.batch_dtype
        )
        new_params, new_opt, stats = update_step(
            params, opt_state, batch, ()
        )
        assert np.isfinite(float(stats["total_loss"]))
        for got, master in zip(
            jax.tree_util.tree_leaves(new_params),
            jax.tree_util.tree_leaves(new_opt.master),
        ):
            assert got.dtype == jnp.bfloat16
            np.testing.assert_array_equal(
                np.asarray(got),
                np.asarray(master.astype(jnp.bfloat16)),
            )

    def test_bf16_train_close_to_f32_one_step(self):
        """One bf16_train update lands within bf16 tolerance of the f32
        update from the same start — the policy changes precision, not
        the algorithm."""
        _, hp32, model32, params32, opt32, rng32 = _build("f32")
        pol, hp16, model16, params16, opt16, rng16 = _build(
            "bf16_train"
        )
        batch = make_batch(np.random.default_rng(7))
        step32 = learner_lib.make_update_step(
            model32, opt32, hp32, donate=False
        )
        step16 = learner_lib.make_update_step(
            model16, opt16, hp16, donate=False
        )
        p32, _, s32 = step32(
            params32, opt32.init(params32), batch, ()
        )
        p16, _, s16 = step16(
            params16, opt16.init(params16),
            precision_lib.cast_batch(batch, pol.batch_dtype), (),
        )
        assert np.isfinite(float(s16["total_loss"]))
        np.testing.assert_allclose(
            float(s16["total_loss"]), float(s32["total_loss"]),
            rtol=5e-2,
        )
        w32 = jax.tree_util.tree_leaves(p32)[0]
        w16 = jax.tree_util.tree_leaves(p16)[0]
        np.testing.assert_allclose(
            np.asarray(w16, np.float32), np.asarray(w32),
            rtol=3e-2, atol=3e-2,
        )


class TestFusedLoss:
    def test_fused_equals_composed_values_and_grads(self):
        """ops.vtrace_policy_losses == from_logits + the two composed
        losses, in value AND gradient (the default-update-path fusion
        must be a pure refactor)."""
        from torchbeast_tpu.ops import losses as losses_lib
        from torchbeast_tpu.ops import vtrace

        rng = np.random.default_rng(3)
        t, b = 9, 4
        behavior = jnp.asarray(
            rng.standard_normal((t, b, A)).astype(np.float32)
        )
        target = jnp.asarray(
            rng.standard_normal((t, b, A)).astype(np.float32)
        )
        actions = jnp.asarray(rng.integers(0, A, (t, b)))
        discounts = jnp.asarray(
            ((rng.random((t, b)) > 0.1) * 0.99).astype(np.float32)
        )
        rewards = jnp.asarray(
            rng.standard_normal((t, b)).astype(np.float32)
        )
        values = jnp.asarray(
            rng.standard_normal((t, b)).astype(np.float32)
        )
        boot = jnp.asarray(rng.standard_normal((b,)).astype(np.float32))

        def composed(tl, vals):
            vr = vtrace.from_logits(
                behavior, tl, actions, discounts, rewards, vals, boot,
                scan_impl="associative",
            )
            return (
                losses_lib.compute_policy_gradient_loss(
                    tl, actions, vr.pg_advantages
                )
                + 0.5 * losses_lib.compute_baseline_loss(vr.vs - vals)
            )

        def fused(tl, vals):
            pg, base = losses_lib.vtrace_policy_losses(
                behavior, tl, actions, discounts, rewards, vals, boot,
                scan_impl="associative",
            )
            return pg + 0.5 * base

        v1, g1 = jax.value_and_grad(composed, argnums=(0, 1))(
            target, values
        )
        v2, g2 = jax.value_and_grad(fused, argnums=(0, 1))(
            target, values
        )
        np.testing.assert_allclose(float(v1), float(v2), rtol=1e-6)
        np.testing.assert_allclose(g1[0], g2[0], rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(g1[1], g2[1], rtol=1e-6, atol=1e-7)


class TestBytesAccounting:
    def test_bytes_accessed_sees_dtype(self):
        """The lowered-HLO figure must be dtype-faithful: a bf16 matmul
        reads half the bytes of the f32 one."""
        x32 = jax.ShapeDtypeStruct((128, 128), jnp.float32)
        x16 = jax.ShapeDtypeStruct((128, 128), jnp.bfloat16)
        f = jax.jit(lambda a: a @ a)
        b32 = precision_lib.bytes_accessed(f, x32)
        b16 = precision_lib.bytes_accessed(f, x16)
        assert b32 and b16 and b32 == pytest.approx(2 * b16)

    def test_bytes_accessed_none_on_unloweraable(self):
        assert precision_lib.bytes_accessed(lambda x: x, 1) is None

    def _measure_k1_gauge(self):
        from torchbeast_tpu import telemetry

        pol, hp, model, params, optimizer, rng = _build("f32")
        registry = telemetry.MetricsRegistry()
        update_step = learner_lib.instrument_update_step(
            learner_lib.make_update_step(
                model, optimizer, hp, donate=False
            ),
            registry=registry,
        )
        batch = make_batch(rng)
        update_step(params, optimizer.init(params), batch, ())
        gauge = registry.gauge("learner.hbm_bytes_per_update")
        deadline = time.time() + 20
        while time.time() < deadline and gauge.value() == 0:
            time.sleep(0.05)
        return gauge.value()

    def test_hbm_gauge_via_instrument(self):
        """instrument_update_step publishes learner.hbm_bytes_per_update
        from the first dispatch (daemon thread — poll briefly)."""
        assert self._measure_k1_gauge() > 0

    def test_hbm_gauge_superstep_is_per_update(self):
        """The lowered HLO counts the superstep scan body ONCE, so the
        K=2 gauge must be ~the K=1 figure (per-update), NOT half of it
        — the regression the /K division bug produced."""
        from torchbeast_tpu import telemetry

        k1 = self._measure_k1_gauge()
        pol, hp, model, params, optimizer, rng = _build("f32")
        registry = telemetry.MetricsRegistry()
        k = 2
        update_step = learner_lib.instrument_update_step(
            learner_lib.make_update_superstep(
                model, optimizer, hp, k, donate=False
            ),
            registry=registry,
            superstep_k=k,
        )
        b1 = make_batch(rng)
        batch = {key: np.stack([v] * k) for key, v in b1.items()}
        update_step(params, optimizer.init(params), batch, ())
        gauge = registry.gauge("learner.hbm_bytes_per_update")
        deadline = time.time() + 20
        while time.time() < deadline and gauge.value() == 0:
            time.sleep(0.05)
        # Body-once semantics: within the K-stack staging margin of the
        # K=1 figure, and far above the /K-bug's halved value.
        assert gauge.value() == pytest.approx(k1, rel=0.15)
        assert gauge.value() > 0.75 * k1


class TestTransformerBF16Head:
    """ISSUE 13: the transformer families thread head_dtype — bf16_train
    no longer silently falls back to bf16-trunk-only (the PR 8 logged
    exception is gone)."""

    def _tiny_transformer_batch(self, rng, t=4, b=2):
        return {
            "frame": rng.integers(0, 256, (t, b) + FRAME, dtype=np.uint8),
            "reward": rng.standard_normal((t, b)).astype(np.float32),
            "done": rng.random((t, b)) < 0.2,
            "last_action": rng.integers(0, A, (t, b)).astype(np.int32),
        }

    @pytest.mark.parametrize(
        "family", ["transformer", "pipelined_transformer"]
    )
    def test_bf16_head_outputs_stay_f32(self, family):
        pol = precision_lib.get("bf16_train")
        model = create_model(
            family, num_actions=A, dtype=pol.compute_dtype,
            head_dtype=pol.head_dtype, num_layers=1, d_model=16,
            num_heads=2, memory_len=4,
        )
        assert model.head_dtype == jnp.bfloat16
        rng = np.random.default_rng(0)
        batch = self._tiny_transformer_batch(rng)
        state = model.initial_state(2)
        params = model.init(
            {
                "params": jax.random.PRNGKey(0),
                "action": jax.random.PRNGKey(1),
            },
            batch,
            state,
        )
        (out, _), _ = model.apply(
            params, batch, state, sample_action=False,
            mutable=["losses"],
        )
        # The head boundary contract: compute bf16, outputs f32 (the
        # loss side, wire schema, and sampling never see bf16).
        assert out.policy_logits.dtype == jnp.float32
        assert out.baseline.dtype == jnp.float32

    def test_driver_threads_transformer_head_dtype(self):
        """_init_model_and_params under --precision bf16_train builds
        the transformer with a bf16 head (no fallback branch left)."""
        from torchbeast_tpu import monobeast

        flags = monobeast.make_parser().parse_args([
            "--model", "transformer", "--precision", "bf16_train",
            "--unroll_length", "4", "--batch_size", "2",
            "--num_actors", "2",
        ])
        model, _ = monobeast._init_model_and_params(
            flags, A, 2, FRAME, init_params=False
        )
        assert model.head_dtype == jnp.bfloat16
        assert model.dtype == jnp.bfloat16
