"""Ring attention == dense attention, on the 8-device CPU mesh: causal,
with and without segment (episode-boundary) masking, odd head dims, and
gradient equivalence."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tests import jax_caps

from torchbeast_tpu.ops.attention import (
    causal_attention,
    ring_attention,
    segment_ids_from_done,
)
from torchbeast_tpu.parallel import create_mesh

# ring_attention needs the top-level jax.shard_map AND bare-string
# partition specs (newer jax canonicalizes them); skip the ring family
# on version skew instead of failing before any numerics run.
requires_ring_shard_map = pytest.mark.skipif(
    not (jax_caps.has_top_level_shard_map()
         and jax_caps.namedsharding_accepts_str_specs()),
    reason="this jax lacks top-level shard_map / string partition "
           "specs (ops/attention.ring_attention requires both)",
)

B, T, H, D = 2, 16, 4, 8  # T divisible by the 8-way ring


def make_qkv(seed=0, t=T):
    rng = np.random.default_rng(seed)
    shape = (B, t, H, D)
    return tuple(
        jnp.asarray(rng.standard_normal(shape).astype(np.float32))
        for _ in range(3)
    )


def seq_sharded(mesh, x):
    return jax.device_put(
        x, NamedSharding(mesh, P(None, "data") + P(*(None,) * (x.ndim - 2)))
    )


def test_causal_attention_is_causal():
    q, k, v = make_qkv()
    out1 = causal_attention(q, k, v)
    # Changing the future must not change the past.
    v2 = v.at[:, -1].set(123.0)
    out2 = causal_attention(q, k, v2)
    np.testing.assert_allclose(out1[:, :-1], out2[:, :-1], rtol=1e-6)
    assert not np.allclose(out1[:, -1], out2[:, -1])


def test_segment_mask_blocks_cross_episode():
    q, k, v = make_qkv()
    done = np.zeros((T, B), bool)
    done[T // 2] = True  # episode boundary mid-sequence
    seg = segment_ids_from_done(jnp.asarray(done)).T  # [B, T]
    out = causal_attention(q, k, v, segment_ids=seg)
    # Changing pre-boundary values must not affect post-boundary outputs.
    v2 = v.at[:, 0].set(55.0)
    out2 = causal_attention(q, k, v2, segment_ids=seg)
    np.testing.assert_allclose(
        out[:, T // 2 :], out2[:, T // 2 :], rtol=1e-6
    )


@pytest.mark.parametrize("with_segments", [False, True])
@requires_ring_shard_map
def test_ring_matches_dense(with_segments):
    mesh = create_mesh(8)
    q, k, v = make_qkv()
    seg = None
    if with_segments:
        done = np.zeros((T, B), bool)
        done[5] = True
        done[11, 0] = True
        seg = segment_ids_from_done(jnp.asarray(done)).T

    dense = causal_attention(q, k, v, segment_ids=seg)

    qs, ks, vs = (seq_sharded(mesh, x) for x in (q, k, v))
    segs = None
    if seg is not None:
        segs = jax.device_put(seg, NamedSharding(mesh, P(None, "data")))
    ring = ring_attention(qs, ks, vs, mesh, axis="data", segment_ids=segs)

    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=2e-4, atol=2e-5
    )


@pytest.mark.slow
@requires_ring_shard_map
def test_ring_gradients_match_dense():
    mesh = create_mesh(8)
    q, k, v = make_qkv(seed=3)

    def dense_loss(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    def ring_loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, axis="data") ** 2)

    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    qs, ks, vs = (seq_sharded(mesh, x) for x in (q, k, v))
    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(qs, ks, vs)
    for gd, gr in zip(g_dense, g_ring):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), rtol=2e-3, atol=2e-4
        )


@requires_ring_shard_map
def test_ring_long_sequence():
    # 512 tokens over the 8-way ring: 64-token blocks, no full [T, T]
    # materialization per device.
    mesh = create_mesh(8)
    q, k, v = make_qkv(seed=4, t=512)
    dense = causal_attention(q, k, v)
    qs, ks, vs = (seq_sharded(mesh, x) for x in (q, k, v))
    ring = ring_attention(qs, ks, vs, mesh, axis="data")
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=2e-4, atol=2e-5
    )


@pytest.mark.parametrize("with_segments", [False, True])
@requires_ring_shard_map
def test_zigzag_ring_matches_dense(with_segments):
    mesh = create_mesh(8)
    q, k, v = make_qkv(seed=5)
    seg = None
    if with_segments:
        done = np.zeros((T, B), bool)
        done[5] = True
        done[11, 0] = True
        seg = segment_ids_from_done(jnp.asarray(done)).T

    dense = causal_attention(q, k, v, segment_ids=seg)
    qs, ks, vs = (seq_sharded(mesh, x) for x in (q, k, v))
    segs = None
    if seg is not None:
        segs = jax.device_put(seg, NamedSharding(mesh, P(None, "data")))
    zig = ring_attention(
        qs, ks, vs, mesh, axis="data", segment_ids=segs, schedule="zigzag"
    )
    np.testing.assert_allclose(
        np.asarray(zig), np.asarray(dense), rtol=2e-4, atol=2e-5
    )


@pytest.mark.slow
@requires_ring_shard_map
def test_zigzag_ring_gradients_match_dense():
    mesh = create_mesh(8)
    q, k, v = make_qkv(seed=6)

    def dense_loss(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    def zig_loss(q, k, v):
        return jnp.sum(
            ring_attention(q, k, v, mesh, axis="data", schedule="zigzag")
            ** 2
        )

    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    qs, ks, vs = (seq_sharded(mesh, x) for x in (q, k, v))
    g_zig = jax.grad(zig_loss, argnums=(0, 1, 2))(qs, ks, vs)
    for gd, gz in zip(g_dense, g_zig):
        np.testing.assert_allclose(
            np.asarray(gz), np.asarray(gd), rtol=2e-3, atol=2e-4
        )


@pytest.mark.parametrize("with_segments", [False, True])
@pytest.mark.slow
@requires_ring_shard_map
def test_zigzag_ring_long_sequence(with_segments):
    # T=512 on the 8-way mesh -> chunk size 32: exercises the intra-chunk
    # tril-and-segment interaction at c > 1 (T=16 degenerates to c=1).
    t = 512
    mesh = create_mesh(8)
    q, k, v = make_qkv(seed=7, t=t)
    seg = None
    if with_segments:
        done = np.zeros((t, B), bool)
        done[50] = True
        done[200, 0] = True
        done[470] = True
        seg = segment_ids_from_done(jnp.asarray(done)).T
    dense = causal_attention(q, k, v, segment_ids=seg)
    qs, ks, vs = (seq_sharded(mesh, x) for x in (q, k, v))
    segs = None
    if seg is not None:
        segs = jax.device_put(seg, NamedSharding(mesh, P(None, "data")))
    zig = ring_attention(
        qs, ks, vs, mesh, axis="data", segment_ids=segs, schedule="zigzag"
    )
    np.testing.assert_allclose(
        np.asarray(zig), np.asarray(dense), rtol=2e-4, atol=2e-5
    )
    # Contract: output keeps the input's T-sharding (a replicated output
    # would mean the in-op permutation all-gathered the sequence).
    assert zig.sharding.is_equivalent_to(qs.sharding, zig.ndim)


def test_zigzag_rejects_indivisible_t():
    mesh = create_mesh(8)
    q, k, v = make_qkv(seed=8, t=24)  # 24 % 16 != 0
    with pytest.raises(ValueError, match="divisible"):
        ring_attention(q, k, v, mesh, axis="data", schedule="zigzag")


def test_unknown_schedule_rejected():
    mesh = create_mesh(8)
    q, k, v = make_qkv(seed=9)
    with pytest.raises(ValueError, match="schedule"):
        ring_attention(q, k, v, mesh, axis="data", schedule="spiral")
