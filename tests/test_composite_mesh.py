"""Composite (data x expert) mesh: a data-parallel learner with
expert-sharded MoE layers in ONE update step must match the
single-device update numerically — XLA lays the gradient all-reduce on
`data` and the MoE dispatch/combine all-to-alls on `expert`."""

import jax
import numpy as np
import pytest

from torchbeast_tpu import learner as learner_lib
from torchbeast_tpu.models import create_model
from torchbeast_tpu.parallel import (
    create_mesh,
    expert_param_shardings,
    make_parallel_update_step,
    shard_batch,
)

pytestmark = pytest.mark.slow

T, B, A = 4, 8, 5


def _batch(seed=0, t=T):
    rng = np.random.default_rng(seed)
    return {
        "frame": rng.integers(0, 256, (t + 1, B, 6, 6, 1), dtype=np.uint8),
        "reward": rng.standard_normal((t + 1, B)).astype(np.float32),
        "done": rng.random((t + 1, B)) < 0.15,
        "episode_return": rng.standard_normal((t + 1, B)).astype(
            np.float32
        ),
        "episode_step": rng.integers(0, 9, (t + 1, B)).astype(np.int32),
        "last_action": rng.integers(0, A, (t + 1, B)).astype(np.int32),
        "action": rng.integers(0, A, (t + 1, B)).astype(np.int32),
        "policy_logits": rng.standard_normal((t + 1, B, A)).astype(
            np.float32
        ),
        "baseline": rng.standard_normal((t + 1, B)).astype(np.float32),
    }


def test_create_mesh_axes():
    mesh = create_mesh(8, expert_parallelism=2)
    assert mesh.shape == {"data": 4, "model": 1, "expert": 2}
    plain = create_mesh(8)
    assert plain.shape == {"data": 8, "model": 1}


def test_dp_x_ep_update_matches_single_device():
    mesh = create_mesh(8, expert_parallelism=2)
    kwargs = dict(
        num_actions=A, num_layers=1, d_model=16, num_heads=2,
        memory_len=4, num_experts=4,
    )
    single = create_model("transformer", **kwargs)
    composite = create_model("transformer", moe_mesh=mesh, **kwargs)

    batch = _batch()
    state = single.initial_state(B)
    params = single.init(
        {"params": jax.random.PRNGKey(0), "action": jax.random.PRNGKey(1)},
        batch,
        state,
    )
    hp = learner_lib.HParams(batch_size=B, unroll_length=T)
    optimizer = learner_lib.make_optimizer(hp)

    step_single = learner_lib.make_update_step(
        single, optimizer, hp, donate=False
    )
    p_ref, _, stats_ref = step_single(
        params, optimizer.init(params), batch, state
    )

    shardings = expert_param_shardings(mesh, params)
    # 4 experts over a 2-wide axis: the expert kernels must shard.
    n_sharded = sum(
        not s.is_fully_replicated
        for s in jax.tree_util.tree_leaves(shardings)
    )
    assert n_sharded == 2  # w_in + w_out of the single block

    step_comp = make_parallel_update_step(
        composite, optimizer, hp, mesh, donate=False,
        param_shardings=shardings,
    )
    params_p = jax.tree_util.tree_map(jax.device_put, params, shardings)
    batch_p, state_p = shard_batch(mesh, batch, state)
    p_comp, _, stats_comp = step_comp(
        params_p, optimizer.init(params_p), batch_p, state_p
    )

    np.testing.assert_allclose(
        float(stats_comp["total_loss"]),
        float(stats_ref["total_loss"]),
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        float(stats_comp["aux_loss"]),
        float(stats_ref["aux_loss"]),
        rtol=1e-5,
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
        ),
        p_comp,
        p_ref,
    )


def test_dp_x_sp_update_matches_single_device():
    """Composite (data x seq) mesh: data-parallel learner with the
    transformer's in-unroll attention sequence-sharded — both the
    zig-zag ring and the Ulysses strategy — must match the single-device
    update numerically."""
    mesh = create_mesh(8, seq_parallelism=2)
    assert mesh.shape == {"data": 4, "model": 1, "seq": 2}
    T_ = 7  # model sees T+1 = 8 steps: zigzag chunks of 2, ulysses 4
    kwargs = dict(
        num_actions=A, num_layers=1, d_model=16, num_heads=2,
        memory_len=4,
    )
    single = create_model("transformer", **kwargs)

    batch = _batch(seed=1, t=T_)
    state = single.initial_state(B)
    params = single.init(
        {"params": jax.random.PRNGKey(2), "action": jax.random.PRNGKey(3)},
        batch,
        state,
    )
    hp = learner_lib.HParams(batch_size=B, unroll_length=T_)
    optimizer = learner_lib.make_optimizer(hp)
    step_single = learner_lib.make_update_step(
        single, optimizer, hp, donate=False
    )
    p_ref, _, stats_ref = step_single(
        params, optimizer.init(params), batch, state
    )

    for strategy, extra in (
        ("ring", {"ring_schedule": "zigzag"}),
        ("ulysses", {}),
    ):
        comp = create_model(
            "transformer", mesh=mesh, sp_strategy=strategy,
            batch_axis="data", **extra, **kwargs
        )
        step_comp = make_parallel_update_step(
            comp, optimizer, hp, mesh, donate=False
        )
        batch_p, state_p = shard_batch(mesh, batch, state)
        params_p = jax.device_put(
            params, jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec()
            )
        )
        p_comp, _, stats_comp = step_comp(
            params_p, optimizer.init(params_p), batch_p, state_p
        )
        np.testing.assert_allclose(
            float(stats_comp["total_loss"]),
            float(stats_ref["total_loss"]),
            rtol=1e-5,
            err_msg=strategy,
        )
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5,
                err_msg=strategy,
            ),
            p_comp,
            p_ref,
        )


def test_dp_x_sp_x_ep_update_matches_single_device():
    """THREE-axis composite (data x seq x expert) mesh: data-parallel
    learner, sequence-sharded attention (zigzag ring AND ulysses), and
    expert-sharded MoE in ONE update step must match the single-device
    update numerically. Attention partitions over (data, seq) leaving
    `expert` unmentioned; the MoE constraints use `expert` — the two
    collective families coexist in one jitted program."""
    mesh = create_mesh(8, expert_parallelism=2, seq_parallelism=2)
    assert mesh.shape == {"data": 2, "model": 1, "seq": 2, "expert": 2}
    T_ = 7  # T+1 = 8: zigzag chunks of 2, ulysses T blocks of 4
    kwargs = dict(
        num_actions=A, num_layers=1, d_model=16, num_heads=2,
        memory_len=4, num_experts=4,
    )
    single = create_model("transformer", **kwargs)

    batch = _batch(seed=2, t=T_)
    state = single.initial_state(B)
    params = single.init(
        {"params": jax.random.PRNGKey(4), "action": jax.random.PRNGKey(5)},
        batch,
        state,
    )
    hp = learner_lib.HParams(batch_size=B, unroll_length=T_)
    optimizer = learner_lib.make_optimizer(hp)
    step_single = learner_lib.make_update_step(
        single, optimizer, hp, donate=False
    )
    p_ref, _, stats_ref = step_single(
        params, optimizer.init(params), batch, state
    )

    shardings = expert_param_shardings(mesh, params)
    n_sharded = sum(
        not s.is_fully_replicated
        for s in jax.tree_util.tree_leaves(shardings)
    )
    assert n_sharded == 2  # w_in + w_out of the single block

    for strategy, extra in (
        ("ring", {"ring_schedule": "zigzag"}),
        ("ulysses", {}),
    ):
        comp = create_model(
            "transformer", mesh=mesh, sp_strategy=strategy,
            batch_axis="data", moe_mesh=mesh, **extra, **kwargs
        )
        step_comp = make_parallel_update_step(
            comp, optimizer, hp, mesh, donate=False,
            param_shardings=shardings,
        )
        params_p = jax.tree_util.tree_map(
            jax.device_put, params, shardings
        )
        batch_p, state_p = shard_batch(mesh, batch, state)
        p_comp, _, stats_comp = step_comp(
            params_p, optimizer.init(params_p), batch_p, state_p
        )
        np.testing.assert_allclose(
            float(stats_comp["total_loss"]),
            float(stats_ref["total_loss"]),
            rtol=1e-5,
            err_msg=strategy,
        )
        np.testing.assert_allclose(
            float(stats_comp["aux_loss"]),
            float(stats_ref["aux_loss"]),
            rtol=1e-5,
            err_msg=strategy,
        )
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5,
                err_msg=strategy,
            ),
            p_comp,
            p_ref,
        )


def test_dp_x_tp_x_ep_update_matches_single_device():
    """(data x model x expert) mesh: Megatron-paired attention TP and
    expert-sharded MoE merged onto one param tree, data-parallel batch —
    the merged-rule update must match single-device numerically."""
    from torchbeast_tpu.parallel import (
        merge_param_shardings,
        transformer_tp_shardings,
    )

    mesh = create_mesh(8, model_parallelism=2, expert_parallelism=2)
    assert mesh.shape == {"data": 2, "model": 2, "expert": 2}
    kwargs = dict(
        num_actions=A, num_layers=1, d_model=16, num_heads=2,
        memory_len=4, num_experts=4,
    )
    single = create_model("transformer", **kwargs)
    batch = _batch(seed=3)
    state = single.initial_state(B)
    params = single.init(
        {"params": jax.random.PRNGKey(6), "action": jax.random.PRNGKey(7)},
        batch,
        state,
    )
    hp = learner_lib.HParams(batch_size=B, unroll_length=T)
    optimizer = learner_lib.make_optimizer(hp)
    step_single = learner_lib.make_update_step(
        single, optimizer, hp, donate=False
    )
    p_ref, _, stats_ref = step_single(
        params, optimizer.init(params), batch, state
    )

    shardings = merge_param_shardings(
        expert_param_shardings(mesh, params),
        transformer_tp_shardings(mesh, params),
    )
    n_sharded = sum(
        not s.is_fully_replicated
        for s in jax.tree_util.tree_leaves(shardings)
    )
    # 2 expert kernels + 8 attention leaves (q/k/v kernel+bias, out
    # kernel, rel_bias); the MoE block has no dense FFN for TP to claim.
    assert n_sharded == 10, n_sharded

    comp = create_model("transformer", moe_mesh=mesh, **kwargs)
    step_comp = make_parallel_update_step(
        comp, optimizer, hp, mesh, donate=False,
        param_shardings=shardings,
    )
    params_p = jax.tree_util.tree_map(jax.device_put, params, shardings)
    batch_p, state_p = shard_batch(mesh, batch, state)
    p_comp, _, stats_comp = step_comp(
        params_p, optimizer.init(params_p), batch_p, state_p
    )
    np.testing.assert_allclose(
        float(stats_comp["total_loss"]), float(stats_ref["total_loss"]),
        rtol=1e-5,
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
        ),
        p_comp,
        p_ref,
    )


def test_merge_param_shardings_conflict_raises():
    from torchbeast_tpu.parallel import merge_param_shardings
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = create_mesh(8, expert_parallelism=2)
    a = {"w": NamedSharding(mesh, P("expert"))}
    b = {"w": NamedSharding(mesh, P("data"))}
    with pytest.raises(ValueError, match="conflicting"):
        merge_param_shardings(a, b)


def test_dp_x_pp_update_matches_single_device():
    """(data=2 x pipe=4) mesh: each data group runs its own GPipe while
    gradients all-reduce over `data` — the full update must match the
    single-device sequential tower for BOTH pipelined families."""
    mesh = create_mesh(8, pipe_parallelism=4)
    assert mesh.shape == {"data": 2, "model": 1, "pipe": 4}
    for family, kwargs, state_fn in (
        (
            "pipelined_mlp",
            dict(num_actions=A, num_stages=4, d_model=32),
            lambda m: (),
        ),
        (
            "pipelined_transformer",
            dict(
                num_actions=A, num_layers=4, d_model=32, num_heads=2,
                memory_len=8,
            ),
            lambda m: m.initial_state(B),
        ),
    ):
        single = create_model(family, **kwargs)
        comp = create_model(
            family, mesh=mesh, batch_axis="data", **kwargs
        )
        batch = _batch(seed=7)
        state = state_fn(single)
        params = single.init(
            {
                "params": jax.random.PRNGKey(8),
                "action": jax.random.PRNGKey(9),
            },
            batch,
            state,
        )
        hp = learner_lib.HParams(batch_size=B, unroll_length=T)
        optimizer = learner_lib.make_optimizer(hp)
        step_single = learner_lib.make_update_step(
            single, optimizer, hp, donate=False
        )
        p_ref, _, stats_ref = step_single(
            params, optimizer.init(params), batch, state
        )
        step_comp = make_parallel_update_step(
            comp, optimizer, hp, mesh, donate=False
        )
        batch_p, state_p = shard_batch(mesh, batch, state)
        params_p = jax.device_put(
            params,
            jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
        )
        p_comp, _, stats_comp = step_comp(
            params_p, optimizer.init(params_p), batch_p, state_p
        )
        np.testing.assert_allclose(
            float(stats_comp["total_loss"]),
            float(stats_ref["total_loss"]),
            rtol=1e-5,
            err_msg=family,
        )
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5,
                err_msg=family,
            ),
            p_comp,
            p_ref,
        )
