"""The Atari preprocessing stack, executed end-to-end on the dependency-free
ALE-compatible MiniAtari cabinet (no ale_py in the image). Pins the wrapper
composition and the EpisodicLife/FireReset semantics the reference vendors
from baselines (reference atari_wrappers.py:64-118)."""

import numpy as np
import pytest

from torchbeast_tpu.envs import create_env, num_actions_of
from torchbeast_tpu.envs.atari import (
    EpisodicLifeWrapper,
    FireResetWrapper,
    create_atari_env,
)
from torchbeast_tpu.envs.environment import Environment

ENV_ID = "tbt/MiniAtari-v0"


def test_full_stack_output_contract():
    env = create_atari_env(ENV_ID)
    obs, _ = env.reset(seed=0)
    assert obs.shape == (84, 84, 4)  # HWC, TPU-native NHWC layout
    assert obs.dtype == np.uint8
    assert num_actions_of(env) == 4
    obs2, reward, term, trunc, _ = env.step(0)
    assert obs2.shape == (84, 84, 4) and obs2.dtype == np.uint8
    assert isinstance(float(reward), float)


def test_wrapper_composition():
    env = create_atari_env(ENV_ID)
    chain = []
    e = env
    while hasattr(e, "env"):
        chain.append(type(e).__name__)
        e = e.env
    assert "EpisodicLifeWrapper" in chain
    # MiniAtari advertises FIRE, so FireReset must be applied.
    assert "FireResetWrapper" in chain
    assert "AtariPreprocessing" in chain
    assert "FrameStackObservation" in chain
    # FireReset must wrap EpisodicLife (fire after EVERY per-life reset).
    assert chain.index("FireResetWrapper") < chain.index("EpisodicLifeWrapper")

    no_life = create_atari_env(ENV_ID, episodic_life=False)
    chain = []
    e = no_life
    while hasattr(e, "env"):
        chain.append(type(e).__name__)
        e = e.env
    assert "EpisodicLifeWrapper" not in chain


def test_episodic_life_done_per_life_but_reset_per_game():
    env = create_atari_env(ENV_ID, noop_max=0)
    env.reset(seed=1)
    ale = env.unwrapped.ale

    per_life_dones = 0
    start_lives = ale.lives()
    # NOOP forever: auto-serve drops balls that always miss a centered
    # paddle eventually; count per-life dones until the game truly resets.
    for _ in range(3000):
        _, _, terminated, truncated, _ = env.step(0)
        if terminated or truncated:
            per_life_dones += 1
            env.reset()
            if ale.lives() == start_lives:
                break
    # One done per lost life, and the underlying game replenished lives
    # only after all of them were gone.
    assert per_life_dones == start_lives
    assert ale.lives() == start_lives


def test_fire_reset_serves_the_ball():
    env = create_atari_env(ENV_ID, noop_max=0)
    env.reset(seed=2)
    # FireReset pressed FIRE during reset, so the ball is in play without
    # the agent ever choosing action 1.
    assert env.unwrapped.ale.in_play


def test_environment_adapter_over_full_stack():
    e = Environment(create_env(ENV_ID))
    obs = e.initial()
    assert obs["frame"].shape == (84, 84, 4)
    rng = np.random.default_rng(3)
    saw_done = False
    for _ in range(300):
        out = e.step(int(rng.integers(0, 4)))
        assert out["frame"].shape == (84, 84, 4)
        if out["done"]:
            saw_done = True
    assert saw_done  # random play loses lives well within 300 steps
    e.close()


def test_real_atari_id_gives_clear_error_without_ale():
    pytest.importorskip("gymnasium")
    try:
        import ale_py  # noqa: F401

        pytest.skip("ale_py installed; gate not reachable")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="ale_py"):
        create_atari_env("PongNoFrameskip-v4")


def test_miniatari_seeded_serve_is_deterministic():
    a = create_atari_env(ENV_ID, noop_max=0)
    b = create_atari_env(ENV_ID, noop_max=0)
    oa, _ = a.reset(seed=7)
    ob, _ = b.reset(seed=7)
    np.testing.assert_array_equal(oa, ob)
    for _ in range(20):
        sa = a.step(2)
        sb = b.step(2)
        np.testing.assert_array_equal(sa[0], sb[0])
        assert sa[1:3] == sb[1:3]


def test_episodic_life_wrapper_unit():
    """Direct unit semantics on a raw cabinet (no preprocessing)."""
    import gymnasium

    import torchbeast_tpu.envs.miniatari  # noqa: F401 — registers

    env = EpisodicLifeWrapper(
        FireResetWrapper(gymnasium.make(ENV_ID, frameskip=1))
    )
    env.reset(seed=0)
    ale = env.unwrapped.ale
    lives0 = ale.lives()
    # Hide the paddle in a corner; the ball will eventually miss.
    terminated = truncated = False
    for _ in range(5000):
        _, _, terminated, truncated, _ = env.step(3)
        if terminated or truncated:
            break
    assert terminated  # life loss surfaces as termination
    assert ale.lives() == lives0 - 1  # but the game is not over
    assert not env.was_real_done
    env.reset()
    assert ale.lives() == lives0 - 1  # soft reset preserved the game
