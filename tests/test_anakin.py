"""Anakin (fully-on-TPU) trainer: jittable Catch env mechanics, the fused
train step, and an actual learning check — after a few hundred updates the
agent must catch the ball far more often than chance."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchbeast_tpu import anakin
from torchbeast_tpu.envs.jax_env import CatchJax, create_jax_env


class TestCatch:
    def test_episode_mechanics(self):
        env = CatchJax(rows=5, cols=3)
        state = env.reset(jax.random.PRNGKey(0))
        assert int(state.ball_row) == 0
        total_reward = 0.0
        for t in range(4):  # rows-1 steps to the bottom
            state, frame, reward, done = env.step(state, jnp.int32(1))
            total_reward += float(reward)
        assert bool(done)
        assert total_reward in (1.0, -1.0)
        assert frame.shape == (5, 3, 1)

    def test_catching_gives_plus_one(self):
        env = CatchJax(rows=5, cols=3)
        state = env.reset(jax.random.PRNGKey(0))
        # Move the paddle toward the ball every step: guaranteed catch on
        # a 5-row board (paddle starts centered on 3 cols).
        for _ in range(4):
            delta = jnp.sign(state.ball_col - state.paddle_col)
            state, _, reward, done = env.step(state, delta + 1)
        assert bool(done) and float(reward) == 1.0

    def test_wrapper_accounting_and_autoreset(self):
        env = create_jax_env("Catch")
        state, out = env.initial(jax.random.PRNGKey(1))
        assert bool(out["done"])  # boundary convention
        step = jax.jit(env.step)
        for t in range(1, 10):  # 10 rows -> episode ends at step 9
            state, out = step(state, jnp.int32(1))
            assert int(out["episode_step"]) == t
        assert bool(out["done"])
        assert float(out["episode_return"]) in (1.0, -1.0)
        # Auto-reset: counters restart; ball back at the top of the frame.
        state, out = step(state, jnp.int32(1))
        assert int(out["episode_step"]) == 1
        assert not bool(out["done"])


def run_anakin(tmp_path, total_steps, **overrides):
    argv = [
        "--env", "Catch",
        "--batch_size", "32",
        "--unroll_length", "9",
        "--total_steps", str(total_steps),
        "--savedir", str(tmp_path),
        "--xpid", overrides.pop("xpid", "anakin-test"),
        "--log_interval_updates", "5",
        "--checkpoint_interval_s", "100000",
        "--learning_rate", "2e-3",
        "--entropy_cost", "0.01",
    ]
    for k, v in overrides.items():
        argv += [f"--{k}"] if v is True else [f"--{k}", str(v)]
    return anakin.train(anakin.make_parser().parse_args(argv))


@pytest.mark.slow
def test_anakin_learns_catch(tmp_path):
    # Chance-level mean return is ~-0.3 (paddle random walk); a learning
    # agent approaches +1. 700 updates x 32 envs x 9 steps is plenty for
    # the MLP to get solidly positive.
    stats = run_anakin(tmp_path, total_steps=200_000)
    assert stats["step"] >= 200_000
    assert np.isfinite(stats["total_loss"])
    assert stats.get("mean_episode_return", -1.0) > 0.5


@pytest.mark.slow
def test_anakin_resume(tmp_path):
    import csv

    import flax.serialization

    run_anakin(tmp_path, total_steps=5_000, xpid="anakin-resume")
    ckpt = tmp_path / "anakin-resume" / "model.ckpt"
    with open(ckpt, "rb") as f:
        saved_step = flax.serialization.msgpack_restore(f.read())["step"]
    assert saved_step >= 5_000

    with open(tmp_path / "anakin-resume" / "logs.csv") as f:
        rows_before = len(list(csv.DictReader(f)))

    stats = run_anakin(tmp_path, total_steps=10_000, xpid="anakin-resume")
    assert stats["step"] >= 10_000
    # Run 2 RESUMED: its first logged step continues past run 1's
    # checkpoint instead of restarting near zero.
    with open(tmp_path / "anakin-resume" / "logs.csv") as f:
        rows = list(csv.DictReader(f))
    first_new = int(float(rows[rows_before]["step"]))
    assert first_new > saved_step


@pytest.mark.slow
def test_anakin_data_parallel(tmp_path):
    stats = run_anakin(
        tmp_path, total_steps=10_000, xpid="anakin-dp", num_devices="4",
    )
    assert stats["step"] >= 10_000
    assert np.isfinite(stats["total_loss"])


class TestMemoryJax:
    def test_parameterized_corridor_id(self):
        """create_jax_env reads the same Memory-L<n> ids as the host
        create_env, so anakin's --env flag accepts them too."""
        import pytest

        from torchbeast_tpu.envs.jax_env import create_jax_env

        env = create_jax_env("Memory-L41")
        assert env.env.length == 41
        with pytest.raises(ValueError, match="length must be >= 6"):
            create_jax_env("Memory-L5")

    def test_parity_with_host_env(self):
        """MemoryChainJax is a rule-for-rule twin of the host
        MemoryChainEnv: identical frames, rewards, and done flags for
        the same cue and action script (honest, relay, and mixed)."""
        from torchbeast_tpu.envs.jax_env import MemoryChainJax, MemoryState
        from torchbeast_tpu.envs.mock import MemoryChainEnv

        fwd = MemoryChainJax.FORWARD
        for cue in (0, 1):
            scripts = [
                [fwd, fwd, fwd, fwd, fwd, cue],       # honest solve
                [cue, cue, cue, cue, cue, cue],       # full relay (taxed)
                [0, 1, fwd, 0, fwd, 1 - cue],         # mixed + wrong answer
            ]
            for script in scripts:
                host = MemoryChainEnv(length=6, seed=0)
                host.reset()
                host._cue = cue  # force the drawn cue for parity
                jenv = MemoryChainJax(length=6)
                state = MemoryState(
                    cue=jnp.int32(cue), t=jnp.int32(0),
                    key=jax.random.PRNGKey(0),
                )
                np.testing.assert_array_equal(
                    np.asarray(jenv.observe(state)), host._frame()
                )
                for a in script:
                    state, jframe, jr, jd = jenv.step(state, jnp.int32(a))
                    hframe, hr, hd = host.step(a)
                    assert float(jr) == hr, (cue, script, a)
                    assert bool(jd) == hd
                    np.testing.assert_array_equal(
                        np.asarray(jframe), hframe
                    )


@pytest.mark.slow
def test_anakin_lstm_solves_memory(tmp_path):
    """On-device recurrent state carry (lax.scan carry through the fused
    env+policy+update program): the Memory probe is unsolvable without
    it. Pilot: LSTM at +1.0 by the first log point (154k steps); FF
    oscillates around its cap of 0 for 3M steps
    (benchmarks/artifacts/lstm_learning.md §2c)."""
    lstm = run_anakin(
        tmp_path, total_steps=1_000_000, xpid="anakin-mem-lstm",
        env="Memory", use_lstm=True, batch_size="64",
        unroll_length="12", learning_rate="1e-3",
        log_interval_updates="100",
    )
    assert lstm.get("mean_episode_return", -1.0) > 0.6
    ff = run_anakin(
        tmp_path, total_steps=1_000_000, xpid="anakin-mem-ff",
        env="Memory", batch_size="64", unroll_length="12",
        learning_rate="1e-3", log_interval_updates="100",
    )
    assert ff.get("mean_episode_return", 1.0) < 0.5


@pytest.mark.slow
def test_anakin_transformer_solves_memory(tmp_path):
    """Attention-as-memory INSIDE the fused on-device program: the
    transformer's KV cache rides the acting lax.scan as carry (where
    the LSTM test's hidden state rides), so the t=0 cue must survive
    on-device cache updates + segment masking to the query step.
    Completes the {LSTM, transformer} x {mono, poly, anakin} Memory
    matrix. Deterministic: anakin is pure-jax PRNG (fixed --seed).
    Pilot: 1.0 from the second log point (~61k steps), sustained
    through 2M (benchmarks/artifacts/lstm_learning.md §4); lr 5e-4 +
    entropy 0.02 per the saturation-trap note there."""
    stats = run_anakin(
        tmp_path, total_steps=600_000, xpid="anakin-mem-transformer",
        env="Memory", model="transformer", batch_size="64",
        unroll_length="12", learning_rate="5e-4", entropy_cost="0.02",
        log_interval_updates="100",
    )
    assert stats.get("mean_episode_return", -1.0) > 0.6
