"""MoE layer (models/moe.py) + expert parallelism (parallel/ep.py).

Oracles: with identical expert weights and ample capacity the mixture
must equal a single dense FFN (renormalized gates sum to 1); the
expert-sharded run must match the unsharded run bitwise-close; capacity
overflow must drop, not corrupt."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from torchbeast_tpu import learner as learner_lib
from torchbeast_tpu.models import create_model
from torchbeast_tpu.models.moe import MoEFFN
from torchbeast_tpu.parallel.ep import (
    expert_param_shardings,
    place_expert_params,
)

D, FF, E = 8, 16, 4


def _init(key, moe, tokens=16):
    x = jax.random.normal(jax.random.PRNGKey(9), (tokens, D))
    params = moe.init(key, x)
    return params, x


def test_identical_experts_equal_dense_ffn():
    moe = MoEFFN(
        d_model=D, d_ff=FF, num_experts=E, top_k=2, capacity_factor=16.0
    )
    params, x = _init(jax.random.PRNGKey(0), moe)
    p = params["params"]
    # Collapse every expert onto expert 0's weights.
    p = dict(
        p,
        w_in=jnp.broadcast_to(p["w_in"][:1], p["w_in"].shape),
        b_in=jnp.broadcast_to(p["b_in"][:1], p["b_in"].shape),
        w_out=jnp.broadcast_to(p["w_out"][:1], p["w_out"].shape),
        b_out=jnp.broadcast_to(p["b_out"][:1], p["b_out"].shape),
    )
    y = moe.apply({"params": p}, x)
    dense = (
        nn.gelu(x @ p["w_in"][0] + p["b_in"][0]) @ p["w_out"][0]
        + p["b_out"][0]
    )
    np.testing.assert_allclose(y, dense, rtol=1e-5, atol=1e-5)


def test_capacity_overflow_drops_tokens():
    """Router forced onto one expert with capacity 2: exactly 2 tokens
    get expert output, the rest fall back to zero (the residual around
    the layer carries them)."""
    tokens = 8
    moe = MoEFFN(
        d_model=D, d_ff=FF, num_experts=E, top_k=1, capacity_factor=1.0
    )
    params, x = _init(jax.random.PRNGKey(1), moe, tokens=tokens)
    p = dict(params["params"])
    router = np.zeros((D, E), np.float32)
    router[:, 0] = 0.0  # uniform logits -> top_k ties resolve to expert 0
    p["router"] = {"kernel": jnp.asarray(router)}
    # capacity = ceil(1 * 8 / 4 * 1.0) = 2
    y = moe.apply({"params": p}, x)
    nonzero_rows = np.flatnonzero(np.abs(np.asarray(y)).sum(axis=1) > 1e-9)
    assert len(nonzero_rows) == 2, nonzero_rows
    np.testing.assert_array_equal(nonzero_rows, [0, 1])  # token order wins


def test_expert_parallel_matches_unsharded():
    n_dev = 8
    mesh = Mesh(np.asarray(jax.devices()[:n_dev]), ("expert",))
    moe_plain = MoEFFN(d_model=D, d_ff=FF, num_experts=n_dev, top_k=2)
    moe_ep = MoEFFN(
        d_model=D, d_ff=FF, num_experts=n_dev, top_k=2, mesh=mesh
    )
    params, x = _init(jax.random.PRNGKey(2), moe_plain, tokens=32)
    y_plain = moe_plain.apply(params, x)

    placed = {
        "params": place_expert_params(mesh, params["params"])
    }
    shardings = expert_param_shardings(mesh, params["params"])
    assert not shardings["w_in"].is_fully_replicated
    assert shardings["router"]["kernel"].is_fully_replicated
    apply_ep = jax.jit(moe_ep.apply)
    y_ep = apply_ep(placed, x)
    np.testing.assert_allclose(y_ep, y_plain, rtol=1e-5, atol=1e-5)


def test_aux_loss_sown_and_balanced_floor():
    moe = MoEFFN(
        d_model=D, d_ff=FF, num_experts=E, top_k=2, aux_loss_weight=1.0
    )
    params, x = _init(jax.random.PRNGKey(3), moe, tokens=64)
    _, variables = moe.apply(params, x, mutable=["losses"])
    assert "losses" not in params  # init() must not materialize it
    aux = variables["losses"]["moe_load_balance"]
    # E * sum(f_e * p_e) >= 1 with equality iff perfectly uniform.
    assert float(aux) >= 0.99


@pytest.mark.slow
def test_transformer_moe_trains_and_aux_flows():
    T, B, A = 4, 4, 5
    model = create_model(
        "transformer", num_actions=A, num_layers=1, d_model=16,
        num_heads=2, memory_len=4, num_experts=4,
    )
    rng = np.random.default_rng(4)
    batch = {
        "frame": rng.integers(0, 256, (T + 1, B, 4, 4, 1), dtype=np.uint8),
        "reward": rng.standard_normal((T + 1, B)).astype(np.float32),
        "done": rng.random((T + 1, B)) < 0.2,
        "episode_return": rng.standard_normal((T + 1, B)).astype(
            np.float32
        ),
        "episode_step": rng.integers(0, 9, (T + 1, B)).astype(np.int32),
        "last_action": rng.integers(0, A, (T + 1, B)).astype(np.int32),
        "action": rng.integers(0, A, (T + 1, B)).astype(np.int32),
        "policy_logits": rng.standard_normal((T + 1, B, A)).astype(
            np.float32
        ),
        "baseline": rng.standard_normal((T + 1, B)).astype(np.float32),
    }
    state = model.initial_state(B)
    params = model.init(
        {"params": jax.random.PRNGKey(5), "action": jax.random.PRNGKey(6)},
        batch,
        state,
    )
    hp = learner_lib.HParams(batch_size=B, unroll_length=T)
    optimizer = learner_lib.make_optimizer(hp)
    step = learner_lib.make_update_step(model, optimizer, hp, donate=False)
    new_params, _, stats = step(params, optimizer.init(params), batch, state)
    assert np.isfinite(float(stats["total_loss"]))
    assert float(stats["aux_loss"]) > 0.0
    # The aux loss must reach the router: its kernel has to move.
    r_old = params["params"]["block_0"]["moe"]["router"]["kernel"]
    r_new = new_params["params"]["block_0"]["moe"]["router"]["kernel"]
    assert float(jnp.abs(r_new - r_old).max()) > 0.0


def test_acting_path_unaffected_by_sow():
    """model.apply WITHOUT mutable (the act path) still works — sow is a
    no-op when the collection isn't mutable."""
    A = 5
    model = create_model(
        "transformer", num_actions=A, num_layers=1, d_model=16,
        num_heads=2, memory_len=4, num_experts=4,
    )
    B = 2
    rng = np.random.default_rng(7)
    inputs = {
        "frame": rng.integers(0, 256, (1, B, 4, 4, 1), dtype=np.uint8),
        "reward": np.zeros((1, B), np.float32),
        "done": np.zeros((1, B), bool),
        "last_action": np.zeros((1, B), np.int32),
    }
    state = model.initial_state(B)
    params = model.init(
        {"params": jax.random.PRNGKey(7), "action": jax.random.PRNGKey(8)},
        dict(inputs, episode_return=np.zeros((1, B), np.float32),
             episode_step=np.zeros((1, B), np.int32),
             action=np.zeros((1, B), np.int32),
             policy_logits=np.zeros((1, B, A), np.float32),
             baseline=np.zeros((1, B), np.float32)),
        state,
    )
    out, new_state = model.apply(
        params, inputs, state, rngs={"action": jax.random.PRNGKey(9)}
    )
    assert out.action.shape == (1, B)


def _init_model_params(model, A, frame_shape=(4, 4, 1), B=2):
    rng = np.random.default_rng(11)
    dummy = {
        "frame": rng.integers(0, 256, (1, B) + frame_shape, dtype=np.uint8),
        "reward": np.zeros((1, B), np.float32),
        "done": np.zeros((1, B), bool),
        "last_action": np.zeros((1, B), np.int32),
    }
    state = model.initial_state(B)
    return model.init(
        {"params": jax.random.PRNGKey(11), "action": jax.random.PRNGKey(12)},
        dummy,
        state,
    )


def test_expert_sharding_contract_on_real_transformer_tree():
    """The EP sharding rule must fire on exactly the expert kernels of
    the REAL transformer-MoE param tree — by name and count — so a
    rename in models/moe.py fails loudly here instead of silently
    degrading to fully-replicated experts (parallel/ep.py)."""
    num_layers, E = 2, 4
    mesh = Mesh(np.asarray(jax.devices()[:E]), ("expert",))
    model = create_model(
        "transformer", num_actions=5, num_layers=num_layers, d_model=16,
        num_heads=2, memory_len=4, num_experts=E,
    )
    params = _init_model_params(model, A=5)
    shardings = expert_param_shardings(mesh, params["params"])
    flat, _ = jax.tree_util.tree_flatten_with_path(shardings)
    sharded = sorted(
        jax.tree_util.keystr(path)
        for path, s in flat
        if not s.is_fully_replicated
    )
    expected = sorted(
        f"['block_{i}']['moe']['{k}']"
        for i in range(num_layers)
        for k in ("w_in", "w_out")
    )
    assert sharded == expected, (
        f"EP rule fired on {sharded}, expected exactly {expected} — "
        "did models/moe.py rename its expert kernels?"
    )


def test_pipelined_stage_params_not_expert_sharded():
    """PipelinedMLPNet reuses the leaf names w_in/w_out for its stage
    stack [S, d, ff]; the EP rule must NOT shard those over the expert
    axis (no router sibling = not a MoE scope)."""
    S = 4
    mesh = Mesh(np.asarray(jax.devices()[:S]), ("expert",))
    model = create_model(
        "pipelined_mlp", num_actions=5, num_stages=S, d_model=16,
    )
    params = _init_model_params(model, A=5)
    shardings = expert_param_shardings(mesh, params["params"])
    assert all(
        s.is_fully_replicated
        for s in jax.tree_util.tree_leaves(shardings)
    )


def test_expert_sharding_contract_covers_opt_state():
    """polybeast shards the donated optax state with the SAME rule
    (polybeast.py `opt_shardings`); the MoE structural signature must be
    found inside optax's tuple/namedtuple wrappers too, or the [E, d, ff]
    RMSProp moments silently replicate and EP's memory scaling is lost."""
    num_layers, E = 1, 4
    mesh = Mesh(np.asarray(jax.devices()[:E]), ("expert",))
    model = create_model(
        "transformer", num_actions=5, num_layers=num_layers, d_model=16,
        num_heads=2, memory_len=4, num_experts=E,
    )
    params = _init_model_params(model, A=5)
    hp = learner_lib.HParams(batch_size=2, unroll_length=4)
    opt_state = learner_lib.make_optimizer(hp).init(params)
    shardings = expert_param_shardings(mesh, opt_state)
    flat, _ = jax.tree_util.tree_flatten_with_path(shardings)
    sharded = [
        jax.tree_util.keystr(path)
        for path, s in flat
        if not s.is_fully_replicated
    ]
    # Every occurrence of an expert kernel inside the optimizer moments
    # must be sharded (rmsprop: one `nu` accumulator tree; momentum off).
    assert sharded, "no opt_state leaves expert-sharded"
    assert all("['moe']" in p for p in sharded)
    n_kernels_in_params = 2 * num_layers
    assert len(sharded) % n_kernels_in_params == 0
