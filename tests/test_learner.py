"""Learner-step arithmetic: exact optimizer update vs a manual calculation,
weight change directionality, and stats plumbing (reference strategy:
tests/polybeast_learn_function_test.py — mock-driven exact-SGD checks)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from torchbeast_tpu import learner as learner_lib
from torchbeast_tpu.models import create_model

T, B, A = 4, 2, 3


def make_batch(rng_seed=0, t=T, b=B):
    rng = np.random.default_rng(rng_seed)
    return {
        # 48px: the smallest-ish frame the shallow conv stack still accepts.
        "frame": rng.integers(0, 256, (t + 1, b, 48, 48, 1), dtype=np.uint8),
        "reward": rng.standard_normal((t + 1, b)).astype(np.float32),
        "done": rng.random((t + 1, b)) < 0.2,
        "episode_return": rng.standard_normal((t + 1, b)).astype(np.float32),
        "episode_step": rng.integers(0, 100, (t + 1, b)).astype(np.int32),
        "last_action": rng.integers(0, A, (t + 1, b)).astype(np.int32),
        "action": rng.integers(0, A, (t + 1, b)).astype(np.int32),
        "policy_logits": rng.standard_normal((t + 1, b, A)).astype(np.float32),
        "baseline": rng.standard_normal((t + 1, b)).astype(np.float32),
    }


@pytest.fixture(scope="module")
def model_and_params():
    model = create_model("shallow", num_actions=A)
    batch = make_batch()
    params = model.init(
        {"params": jax.random.PRNGKey(0), "action": jax.random.PRNGKey(1)},
        batch,
        (),
    )
    return model, params


def test_update_step_matches_manual_sgd(model_and_params):
    """With plain SGD the update must be exactly params - lr * grad."""
    model, params = model_and_params
    hp = learner_lib.HParams()
    lr = 0.1
    optimizer = optax.sgd(lr)
    opt_state = optimizer.init(params)
    batch = make_batch()

    grads, _ = jax.grad(
        lambda p: learner_lib.compute_loss(model, p, batch, (), hp),
        has_aux=True,
    )(params)
    expected = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)

    update_step = learner_lib.make_update_step(model, optimizer, hp)
    # update_step donates params/opt_state; hand it copies so the shared
    # fixture stays alive.
    donated = jax.tree_util.tree_map(jnp.copy, (params, opt_state))
    new_params, _, _ = update_step(*donated, batch, ())
    for e, n in zip(
        jax.tree_util.tree_leaves(expected),
        jax.tree_util.tree_leaves(new_params),
    ):
        np.testing.assert_allclose(e, n, rtol=1e-5, atol=1e-6)


def test_update_step_returns_stats(model_and_params):
    model, params = model_and_params
    hp = learner_lib.HParams()
    optimizer = learner_lib.make_optimizer(hp)
    opt_state = optimizer.init(params)
    update_step = learner_lib.make_update_step(model, optimizer, hp)
    donated = jax.tree_util.tree_map(jnp.copy, (params, opt_state))
    _, _, stats = update_step(*donated, make_batch(), ())
    for key in (
        "total_loss", "pg_loss", "baseline_loss", "entropy_loss", "grad_norm",
        "episode_returns_sum", "episode_count",
    ):
        assert key in stats
        assert np.isfinite(jax.device_get(stats[key]))
    post = learner_lib.episode_stat_postprocess(jax.device_get(stats))
    assert "episodes_finished" in post


def test_episode_return_aggregation(model_and_params):
    model, params = model_and_params
    hp = learner_lib.HParams()
    batch = make_batch()
    _, stats = learner_lib.compute_loss(model, params, batch, (), hp)
    done = batch["done"][1:]
    expected_sum = batch["episode_return"][1:][done].sum()
    np.testing.assert_allclose(
        stats["episode_returns_sum"], expected_sum, rtol=1e-5
    )
    assert int(stats["episode_count"]) == int(done.sum())


def test_lr_schedule_decays_to_zero():
    hp = learner_lib.HParams(
        total_steps=1000, unroll_length=10, batch_size=10, learning_rate=1.0
    )
    frames_per_update = 100
    schedule = optax.linear_schedule(
        hp.learning_rate, 0.0, hp.total_steps // frames_per_update
    )
    assert schedule(0) == 1.0
    assert schedule(5) == 0.5
    assert schedule(10) == 0.0
    assert schedule(20) == 0.0  # stays at zero past the horizon


@pytest.mark.parametrize("momentum", [0.0, 0.9])
def test_rmsprop_matches_torch_semantics(momentum):
    """Multi-step _rmsprop_torch (the learner's version-portable
    torch-RMSprop: upstream eps_in_sqrt=False where available, composed
    primitives on optax 0.2.3) vs torch.optim.RMSprop on the same
    tensors, with and without momentum."""
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(0)
    w = rng.standard_normal(5).astype(np.float32)
    lr, alpha, eps = 0.01, 0.99, 0.01

    tw = torch.nn.Parameter(torch.tensor(w))
    opt = torch.optim.RMSprop(
        [tw], lr=lr, alpha=alpha, eps=eps, momentum=momentum
    )
    ow = jnp.asarray(w)
    optax_opt = learner_lib._rmsprop_torch(
        lr, decay=alpha, eps=eps, momentum=momentum
    )
    state = optax_opt.init(ow)
    for step in range(3):  # multi-step: exercises nu/momentum carry
        g = rng.standard_normal(5).astype(np.float32)
        tw.grad = torch.tensor(g)
        opt.step()
        updates, state = optax_opt.update(jnp.asarray(g), state, ow)
        ow = optax.apply_updates(ow, updates)

    np.testing.assert_allclose(ow, tw.detach().numpy(), rtol=1e-5, atol=1e-6)


def test_entropy_schedule_anneal_and_constant():
    """entropy_schedule shares the LR decay's update clock: linear from
    entropy_cost to entropy_cost_final over total_steps frames, clamped
    past the horizon; None final = constant (returns None so
    compute_loss uses hp.entropy_cost untouched)."""
    import optax.tree_utils as otu

    from torchbeast_tpu import learner as learner_lib

    hp = learner_lib.HParams(
        entropy_cost=0.2, entropy_cost_final=0.0,
        total_steps=1000, unroll_length=10, batch_size=10,
    )  # 10 updates to anneal over
    opt = learner_lib.make_optimizer(hp)
    state = opt.init({"w": jnp.zeros(3)})
    at = learner_lib.entropy_schedule(hp)

    def with_count(n):
        return otu.tree_set(state, count=jnp.asarray(n, jnp.int32))

    np.testing.assert_allclose(float(at(with_count(0))), 0.2)
    np.testing.assert_allclose(float(at(with_count(5))), 0.1)
    np.testing.assert_allclose(float(at(with_count(10))), 0.0)
    np.testing.assert_allclose(float(at(with_count(20))), 0.0)  # clamped

    constant = learner_lib.entropy_schedule(
        hp._replace(entropy_cost_final=None)
    )
    assert constant(state) is None


def test_donate_argnums_policy_table():
    """Donation policy -> argnums for the (params, opt_state, batch,
    state) signature, incl. the donate_batch extension and the typo'd-
    policy guard (falling through to params donation would be unsafe
    for async drivers whose inference threads hold params refs)."""
    f = learner_lib.donate_argnums_for
    assert f(True) == (0, 1)
    assert f(False) == ()
    assert f("opt_only") == (1,)
    assert f(True, donate_batch=True) == (0, 1, 2, 3)
    assert f("opt_only", donate_batch=True) == (1, 2, 3)
    assert f(False, donate_batch=True) == (2, 3)
    with pytest.raises(ValueError, match="donation policy"):
        f("opt-only")
