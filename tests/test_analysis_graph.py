"""Whole-program layer tests (ISSUE 7): module/call/thread-root graph
construction over synthetic multi-module fixtures, the thread-root
enumeration pinned against a grep-derived ground truth, the repo's real
lock-ordering edge set, the RACE / LOCK-ORDER / HOTPATH-SYNC-XPROC rules
beyond their selftest fixtures, the extended FLAG-PARITY groups in
anger, and the `--diff` mode plumbing."""

import os
import re
import subprocess
import sys

from torchbeast_tpu import analysis
from torchbeast_tpu.analysis import analyze_sources
from torchbeast_tpu.analysis import config as lint_config
from torchbeast_tpu.analysis import graph as graph_mod
from torchbeast_tpu.analysis import summaries as summaries_mod
from torchbeast_tpu.analysis.engine import FileContext, run_rules
from torchbeast_tpu.analysis.rules import (
    CONCURRENCY_RULES,
    FILE_RULES,
    LockOrderRule,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _program(sources):
    return graph_mod.build_program(
        [FileContext(path, src) for path, src in sources.items()]
    )


def _repo_program():
    files = analysis.discover_files(["."], REPO)
    ctxs = [
        c for c in (analysis.load_context(f, REPO) for f in files) if c
    ]
    scoped = [
        c for c in ctxs
        if any(
            c.path.startswith(p + "/") or c.path == p
            for p in lint_config.CONCURRENCY_PATHS
        )
    ]
    return graph_mod.get_program(scoped)


def _rules(report, name):
    return [f for f in report.findings if f.rule == name]


# ---------------------------------------------------------------------------
# Call graph over synthetic multi-module fixtures


class TestCallGraph:
    WORKER = (
        "import threading\n"
        "class Worker:\n"
        "    def __init__(self, loop_fn):\n"
        "        self._loop_fn = loop_fn\n"
        "        self._lock = threading.Lock()\n"
        "    def run(self):\n"
        "        self._loop_fn()\n"
        "    def helper(self):\n"
        "        return 1\n"
    )

    def test_cross_module_method_resolution(self):
        prog = _program({
            "torchbeast_tpu/wk.py": self.WORKER,
            "torchbeast_tpu/drv.py": (
                "from torchbeast_tpu.wk import Worker\n"
                "def main():\n"
                "    w = Worker(None)\n"
                "    w.helper()\n"
            ),
        })
        edges = prog.call_edges.get("torchbeast_tpu/drv.py::main", set())
        assert "torchbeast_tpu/wk.py::Worker.helper" in edges
        assert "torchbeast_tpu/wk.py::Worker.__init__" in edges

    def test_reexport_through_package_init(self):
        prog = _program({
            "torchbeast_tpu/pkg/__init__.py": (
                "from torchbeast_tpu.pkg.impl import Worker\n"
            ),
            "torchbeast_tpu/pkg/impl.py": (
                "class Worker:\n"
                "    def helper(self):\n"
                "        return 1\n"
            ),
            "torchbeast_tpu/drv.py": (
                "import torchbeast_tpu.pkg as pkg\n"
                "def main():\n"
                "    w = pkg.Worker()\n"
                "    w.helper()\n"
            ),
        })
        edges = prog.call_edges.get("torchbeast_tpu/drv.py::main", set())
        assert "torchbeast_tpu/pkg/impl.py::Worker.helper" in edges

    def test_constructor_callable_binding(self):
        """`Worker(serve)` + `__init__` storing the param means
        `self._loop_fn()` dispatches to `serve` — the
        InferenceSupervisor pattern."""
        prog = _program({
            "torchbeast_tpu/wk.py": self.WORKER,
            "torchbeast_tpu/drv.py": (
                "from torchbeast_tpu.wk import Worker\n"
                "def serve():\n"
                "    return 2\n"
                "def main():\n"
                "    w = Worker(serve)\n"
                "    w.run()\n"
            ),
        })
        edges = prog.call_edges.get("torchbeast_tpu/wk.py::Worker.run",
                                    set())
        assert "torchbeast_tpu/drv.py::serve" in edges

    def test_nested_def_and_local_alias(self):
        prog = _program({
            "torchbeast_tpu/drv.py": (
                "def train():\n"
                "    def learner_loop():\n"
                "        return tick()\n"
                "    def tick():\n"
                "        return 1\n"
                "    learner_loop()\n"
            ),
        })
        qual = "torchbeast_tpu/drv.py::train.learner_loop"
        assert "torchbeast_tpu/drv.py::train.tick" in (
            prog.call_edges.get(qual, set())
        )
        assert qual in prog.call_edges.get(
            "torchbeast_tpu/drv.py::train", set()
        )

    def test_getattr_property_dispatch(self):
        prog = _program({
            "torchbeast_tpu/wk.py": (
                "import threading\n"
                "class Table:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
                "        self._t = 1\n"
                "    @property\n"
                "    def poisoned(self):\n"
                "        return self._t is None\n"
            ),
            "torchbeast_tpu/drv.py": (
                "from torchbeast_tpu.wk import Table\n"
                "def main():\n"
                "    t = Table()\n"
                "    return getattr(t, 'poisoned', False)\n"
            ),
        })
        assert "torchbeast_tpu/wk.py::Table.poisoned" in (
            prog.call_edges.get("torchbeast_tpu/drv.py::main", set())
        )


# ---------------------------------------------------------------------------
# Thread roots


class TestThreadRoots:
    def test_spawn_site_and_reachability(self):
        prog = _program({
            "torchbeast_tpu/wk.py": (
                "import threading\n"
                "class Pump:\n"
                "    def __init__(self):\n"
                "        self._thread = threading.Thread("
                "target=self._drain)\n"
                "    def start(self):\n"
                "        self._thread.start()\n"
                "    def _drain(self):\n"
                "        self._step()\n"
                "    def _step(self):\n"
                "        pass\n"
            ),
        })
        [site] = prog.spawn_sites
        assert site.kind == "thread" and not site.multi
        assert site.target == "torchbeast_tpu/wk.py::Pump._drain"
        [root_id] = [
            r for r in prog.roots if r != graph_mod.DRIVER_ROOT
        ]
        reach = {
            q for q, roots in prog.func_roots.items() if root_id in roots
        }
        assert "torchbeast_tpu/wk.py::Pump._step" in reach

    def test_loop_spawn_is_multi_instance(self):
        prog = _program({
            "torchbeast_tpu/wk.py": (
                "import threading\n"
                "class Pool:\n"
                "    def run(self, n):\n"
                "        ts = [\n"
                "            threading.Thread(target=self._loop)\n"
                "            for _ in range(n)\n"
                "        ]\n"
                "        for t in ts:\n"
                "            t.start()\n"
                "    def _loop(self):\n"
                "        pass\n"
            ),
        })
        [site] = prog.spawn_sites
        assert site.multi, "comprehension spawn must be multi-instance"

    def test_process_target_is_a_root(self):
        prog = _program({
            "torchbeast_tpu/wk.py": (
                "import multiprocessing as mp\n"
                "def _serve():\n"
                "    pass\n"
                "def main():\n"
                "    p = mp.get_context('spawn').Process(target=_serve)\n"
                "    p.start()\n"
            ),
        })
        [site] = prog.spawn_sites
        assert site.kind == "process"
        assert site.target == "torchbeast_tpu/wk.py::_serve"

    def test_driver_mains_merge_into_one_root(self):
        """main/train/cli across modules are ONE thread: a process has
        one main thread and two drivers never share a process."""
        prog = _program({
            "torchbeast_tpu/a.py": "def main():\n    pass\n",
            "torchbeast_tpu/b.py": (
                "def train():\n    pass\n"
                "def cli():\n    train()\n"
            ),
        })
        driver_roots = [
            r for r, info in prog.roots.items() if info.kind == "driver"
        ]
        assert driver_roots == [graph_mod.DRIVER_ROOT]

    def test_repo_thread_roots_match_grep_ground_truth(self):
        """ISSUE 7 acceptance: the thread-root graph enumerates EVERY
        `threading.Thread(...)` construction site in runtime/ +
        resilience/ + the drivers, pinned against a grep over the
        sources (so a new spawn idiom the graph misses fails here, not
        silently)."""
        scope_files = []
        for rel in ("torchbeast_tpu/runtime", "torchbeast_tpu/resilience"):
            base = os.path.join(REPO, rel)
            scope_files += [
                os.path.join(base, f) for f in os.listdir(base)
                if f.endswith(".py")
            ]
        for rel in (
            "torchbeast_tpu/polybeast.py",
            "torchbeast_tpu/polybeast_env.py",
            "torchbeast_tpu/monobeast.py",
            "scripts/chaos_run.py",
        ):
            scope_files.append(os.path.join(REPO, rel))
        expected = set()
        for path in scope_files:
            rel = os.path.relpath(path, REPO).replace(os.sep, "/")
            with open(path) as f:
                for lineno, line in enumerate(f, 1):
                    if re.search(r"threading\.Thread\(", line):
                        expected.add((rel, lineno))
        assert expected, "ground truth grep found no spawn sites?"
        prog = _repo_program()
        got = {
            (s.path, s.line) for s in prog.spawn_sites
            if s.kind == "thread"
        }
        missing = expected - got
        assert not missing, (
            f"thread-root graph missed Thread() sites: {sorted(missing)}"
        )


# ---------------------------------------------------------------------------
# RACE — beyond the selftest pair


class TestRaceRule:
    def _analyze(self, src, path="torchbeast_tpu/fixture.py"):
        return analyze_sources({path: src})

    SHARED = (
        "import threading\n"
        "class Pump:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._total = 0{annotation}\n"
        "        self._thread = threading.Thread(target=self._drain)\n"
        "    def start(self):\n"
        "        self._thread.start()\n"
        "    def _drain(self):\n"
        "        while True:\n"
        "            {drain_body}\n"
        "    def snapshot(self):\n"
        "        {snapshot_body}\n"
        "def main():\n"
        "    p = Pump()\n"
        "    p.start()\n"
        "    return p.snapshot()\n"
    )

    def test_cross_root_conflict_flagged_with_guard_hint(self):
        src = self.SHARED.format(
            annotation="",
            drain_body="self._total += 1",
            snapshot_body=(
                "with self._lock:\n            return self._total"
            ),
        )
        found = _rules(self._analyze(src), "RACE")
        assert len(found) == 1
        assert "_lock" in found[0].message  # dominance-inferred guard

    def test_annotation_becomes_crosschecked_assertion(self):
        src = self.SHARED.format(
            annotation="  # guarded-by: self._lock",
            drain_body="self._total += 1",
            snapshot_body=(
                "with self._lock:\n            return self._total"
            ),
        )
        found = _rules(self._analyze(src), "RACE")
        assert len(found) == 1
        assert "annotation claims" in found[0].message

    def test_common_lock_infers_guard_without_annotation(self):
        src = self.SHARED.format(
            annotation="",
            drain_body=(
                "with self._lock:\n                self._total += 1"
            ),
            snapshot_body=(
                "with self._lock:\n            return self._total"
            ),
        )
        assert not _rules(self._analyze(src), "RACE")

    def test_immutable_after_init_exempt(self):
        src = self.SHARED.format(
            annotation="",
            drain_body="use(self._total)",
            snapshot_body="return self._total",
        )
        assert not _rules(self._analyze(src), "RACE")

    def test_spawn_site_writes_before_start_exempt(self):
        """The LearnerWatchdog.start() pattern: a write in the spawning
        method BEFORE .start() happens-before the thread."""
        src = (
            "import threading\n"
            "class Dog:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._seen = 0\n"
            "        self._thread = None\n"
            "    def start(self):\n"
            "        self._seen = 1\n"
            "        self._thread = threading.Thread("
            "target=self._watch)\n"
            "        self._thread.start()\n"
            "    def _watch(self):\n"
            "        return self._seen\n"
            "def main():\n"
            "    Dog().start()\n"
        )
        assert not _rules(self._analyze(src), "RACE")

    def test_multi_instance_root_conflicts_with_itself(self):
        src = (
            "import threading\n"
            "class Pool:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._tick = 0\n"
            "    def run(self):\n"
            "        ts = [\n"
            "            threading.Thread(target=self._loop)\n"
            "            for _ in range(4)\n"
            "        ]\n"
            "        for t in ts:\n"
            "            t.start()\n"
            "    def _loop(self):\n"
            "        self._tick += 1\n"
            "def main():\n"
            "    Pool().run()\n"
        )
        found = _rules(self._analyze(src), "RACE")
        assert len(found) == 1 and "_tick" in found[0].message

    def test_unshared_class_exempt(self):
        """A class with no lock and no thread-root method is
        single-owner by construction (per-connection codecs)."""
        src = (
            "import threading\n"
            "class Codec:\n"
            "    def __init__(self):\n"
            "        self.pos = 0\n"
            "    def bump(self):\n"
            "        self.pos += 1\n"
            "def worker():\n"
            "    Codec().bump()\n"
            "def main():\n"
            "    threading.Thread(target=worker).start()\n"
            "    Codec().bump()\n"
        )
        assert not _rules(self._analyze(src), "RACE")

    def test_module_global_race(self):
        src = (
            "import threading\n"
            "_cache = None\n"
            "def worker():\n"
            "    global _cache\n"
            "    _cache = 1\n"
            "def main():\n"
            "    threading.Thread(target=worker).start()\n"
            "    global _cache\n"
            "    return _cache\n"
        )
        found = _rules(self._analyze(src), "RACE")
        assert len(found) == 1 and "_cache" in found[0].message

    def test_repo_burn_down_is_clean_with_reasoned_suppressions(self):
        """The ISSUE 7 burn-down contract, in anger: repo-wide RACE is
        clean, and the surviving suppressions (the benign interleavings:
        trace-tick sampling, watchdog ping, lazy inits, supervisor
        single-writer fields) all carry reasons."""
        report = analysis.analyze_paths(["."], root=REPO)
        assert not _rules(report, "RACE"), [
            f.render() for f in _rules(report, "RACE")
        ]
        race_sups = [
            (f, s) for f, s in report.suppressed if f.rule == "RACE"
        ]
        assert len(race_sups) >= 5, "burn-down suppressions vanished?"
        assert all(s.reason for _, s in race_sups)
        sup_paths = {f.path for f, _ in race_sups}
        assert "torchbeast_tpu/runtime/actor_pool.py" in sup_paths
        assert "torchbeast_tpu/resilience/supervisor.py" in sup_paths


# ---------------------------------------------------------------------------
# LOCK-ORDER


class TestLockOrderRule:
    def _analyze(self, src, path="torchbeast_tpu/fixture.py"):
        return analyze_sources({path: src})

    def test_interprocedural_cycle_flagged(self):
        """The inversion hides behind a helper call: _worker holds A and
        calls grab_b() (which takes B); main nests B -> A directly."""
        src = (
            "import threading\n"
            "class Mixer:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "        self._thread = threading.Thread("
            "target=self._worker)\n"
            "    def start(self):\n"
            "        self._thread.start()\n"
            "    def grab_b(self):\n"
            "        with self._b:\n"
            "            pass\n"
            "    def _worker(self):\n"
            "        with self._a:\n"
            "            self.grab_b()\n"
            "def main():\n"
            "    m = Mixer()\n"
            "    m.start()\n"
            "    with m._b:\n"
            "        with m._a:\n"
            "            pass\n"
        )
        found = _rules(self._analyze(src), "LOCK-ORDER")
        assert found and "cycle" in found[0].message

    def test_lexical_reacquisition_flagged(self):
        """Directly nesting `with self._lock:` inside itself (no helper
        call in between) is the same guaranteed self-deadlock —
        regression: the lexical self-edge used to be dropped, leaving
        only the via-helper path detected."""
        src = (
            "import threading\n"
            "class Box:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def outer(self):\n"
            "        with self._lock:\n"
            "            with self._lock:\n"
            "                pass\n"
        )
        found = _rules(self._analyze(src), "LOCK-ORDER")
        assert found and "self-deadlock" in found[0].message

    def test_reacquisition_self_deadlock_flagged(self):
        src = (
            "import threading\n"
            "class Box:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def outer(self):\n"
            "        with self._lock:\n"
            "            self.inner()\n"
            "    def inner(self):\n"
            "        with self._lock:\n"
            "            pass\n"
        )
        found = _rules(self._analyze(src), "LOCK-ORDER")
        assert found and "self-deadlock" in found[0].message

    def test_rlock_reacquisition_clean(self):
        src = (
            "import threading\n"
            "class Box:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.RLock()\n"
            "    def outer(self):\n"
            "        with self._lock:\n"
            "            self.inner()\n"
            "    def inner(self):\n"
            "        with self._lock:\n"
            "            pass\n"
        )
        assert not _rules(self._analyze(src), "LOCK-ORDER")

    def test_condition_aliases_to_underlying_lock(self):
        """`with self._not_empty:` HOLDS self._lock (Condition built
        from it): nesting them is reentrant-by-aliasing, not an edge."""
        src = (
            "import threading\n"
            "class Q:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._not_empty = threading.Condition(self._lock)\n"
            "    def drain(self):\n"
            "        with self._not_empty:\n"
            "            pass\n"
            "    def close(self):\n"
            "        with self._lock:\n"
            "            pass\n"
        )
        assert not _rules(self._analyze(src), "LOCK-ORDER")

    def test_repo_lock_order_edges_pinned_and_acyclic(self):
        """The burn-down verdict, pinned: the repo's whole-program
        lock-acquisition graph contains the three REAL nontrivial edges
        (learner donation->state nesting; the inference supervisor's
        recovery acquiring the health and table locks under its own) and
        no cycles — LOCK-ORDER reports zero findings repo-wide. If a
        future change inverts one of these orders, the cycle fails the
        gate."""
        prog = _repo_program()
        trans = graph_mod.transitive_acquires(prog)
        edges = set()
        for e in prog.lock_edges:
            if e.held != e.acquired:
                edges.add((e.held, e.acquired))
        for _, callee, _, _, held in prog.call_sites:
            for h in held:
                for a in trans.get(callee, ()):
                    if a != h:
                        edges.add((h, a))

        def short(lock_id):
            return lock_id.split("::")[-1]

        named = {(short(a), short(b)) for a, b in edges}
        assert ("train.donation_lock", "train.state_lock") in named
        assert (
            "InferenceSupervisor._lock", "PipelineHealth._lock"
        ) in named
        assert (
            "InferenceSupervisor._lock", "DeviceStateTable._lock"
        ) in named
        report = run_rules(
            prog.contexts, [], [LockOrderRule()], root=REPO,
            known_rules=analysis.ALL_RULE_NAMES,
        )
        assert not _rules(report, "LOCK-ORDER"), [
            f.render() for f in _rules(report, "LOCK-ORDER")
        ]


# ---------------------------------------------------------------------------
# HOTPATH-SYNC-XPROC


class TestXprocSync:
    def _analyze(self, src, path="torchbeast_tpu/fixture.py"):
        return analyze_sources({path: src})

    def test_two_hop_device_return_taints_caller(self):
        src = (
            "import jax.numpy as jnp\n"
            "def inner(v):\n"
            "    return jnp.tanh(v)\n"
            "def outer(v):\n"
            "    return inner(v) * 2\n"
            "def to_host(x):\n"
            "    return float(x)\n"
            "# beastlint: hot\n"
            "def act(env):\n"
            "    z = outer(env)\n"
            "    return to_host(z)\n"
        )
        found = _rules(self._analyze(src), "HOTPATH-SYNC-XPROC")
        assert len(found) == 1 and "to_host" in found[0].message

    def test_transitive_param_conversion(self):
        """helper -> helper2 -> .item(): converts_params propagates."""
        src = (
            "import jax.numpy as jnp\n"
            "def leaf(x):\n"
            "    return x.item()\n"
            "def mid(x):\n"
            "    return leaf(x)\n"
            "# beastlint: hot\n"
            "def act(env):\n"
            "    z = jnp.tanh(env)\n"
            "    return mid(z)\n"
        )
        found = _rules(self._analyze(src), "HOTPATH-SYNC-XPROC")
        assert len(found) == 1 and "mid" in found[0].message

    def test_inline_findings_not_duplicated(self):
        """A sync the inline HOTPATH-SYNC rule already flags must not
        double-report through the summaries."""
        src = (
            "import jax.numpy as jnp\n"
            "# beastlint: hot\n"
            "def act(env):\n"
            "    z = jnp.tanh(env)\n"
            "    return float(z)\n"
        )
        report = self._analyze(src)
        assert len(_rules(report, "HOTPATH-SYNC")) == 1
        assert not _rules(report, "HOTPATH-SYNC-XPROC")

    def test_device_get_boundary_clean(self):
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def to_host(x):\n"
            "    return float(x)\n"
            "# beastlint: hot\n"
            "def act(env):\n"
            "    z = jnp.tanh(env)\n"
            "    return to_host(jax.device_get(z))\n"
        )
        assert not _rules(
            self._analyze(src), "HOTPATH-SYNC-XPROC"
        )

    def test_cold_caller_not_flagged(self):
        src = (
            "import jax.numpy as jnp\n"
            "def to_host(x):\n"
            "    return float(x)\n"
            "def summarize(env):\n"
            "    return to_host(jnp.tanh(env))\n"
        )
        assert not _rules(
            self._analyze(src), "HOTPATH-SYNC-XPROC"
        )


# ---------------------------------------------------------------------------
# FLAG-PARITY groups + --diff mode


class TestFlagParityGroups:
    def test_polybeast_env_pair_in_anger(self):
        report = analysis.analyze_paths(
            ["torchbeast_tpu/polybeast.py",
             "torchbeast_tpu/polybeast_env.py"],
            root=REPO,
        )
        found = _rules(report, "FLAG-PARITY")
        assert not found, [f.render() for f in found]

    def test_chaos_run_pair_in_anger(self):
        """The chaos harness's scaled-down defaults are intentional:
        every divergence carries a reasoned inline suppression."""
        report = analysis.analyze_paths(
            ["torchbeast_tpu/polybeast.py", "scripts/chaos_run.py"],
            root=REPO,
        )
        found = _rules(report, "FLAG-PARITY")
        assert not found, [f.render() for f in found]
        suppressed = [
            (f, s) for f, s in report.suppressed
            if f.rule == "FLAG-PARITY"
        ]
        flags = {f.message.split(" ")[1] for f, _ in suppressed}
        assert {"--env", "--total_steps", "--batch_size"} <= flags
        assert all(s.reason for _, s in suppressed)


class TestDiffMode:
    def test_changed_files_runs_against_real_repo(self):
        from torchbeast_tpu.analysis.__main__ import changed_files

        changed = changed_files(REPO, "HEAD")
        assert isinstance(changed, set)
        # ISSUE 10: the diff scope covers Python AND the C++ core, so a
        # csrc-only change still runs the C++ rules.
        assert all(
            p.endswith((".py", ".h", ".hpp", ".cc", ".cpp"))
            for p in changed
        )

    def test_only_paths_filters_findings_but_not_graph(self):
        bad = (
            "# beastlint: hot\n"
            "def act(env):\n"
            "    return env.item()\n"
        )
        clean = "def helper():\n    return 1\n"
        ctxs = [
            FileContext("torchbeast_tpu/bad.py", bad),
            FileContext("torchbeast_tpu/clean.py", clean),
        ]
        full = run_rules(
            ctxs, FILE_RULES, list(CONCURRENCY_RULES), root="/",
            known_rules=analysis.ALL_RULE_NAMES,
        )
        assert _rules(full, "HOTPATH-SYNC")
        filtered = run_rules(
            ctxs, FILE_RULES, list(CONCURRENCY_RULES), root="/",
            known_rules=analysis.ALL_RULE_NAMES,
            only_paths={"torchbeast_tpu/clean.py"},
        )
        assert not filtered.findings

    def test_write_baseline_rejects_diff(self, capsys):
        """Regression: a baseline written from a changed-files-only
        report would drop every grandfathered fingerprint in unchanged
        files — the combination is a usage error."""
        from torchbeast_tpu.analysis import __main__ as cli

        rc = cli.main(["--write-baseline", "--diff", "HEAD"])
        assert rc == 2
        assert "full scan" in capsys.readouterr().err

    def test_json_diff_with_no_changes_emits_json(self, monkeypatch,
                                                  capsys):
        """Regression: the empty-diff early return must honor --json
        (a machine consumer piping stdout to json.loads)."""
        import json as json_mod

        from torchbeast_tpu.analysis import __main__ as cli

        monkeypatch.setattr(cli, "changed_files", lambda root, ref: set())
        rc = cli.main(["--json", "--ci", "--diff", "HEAD"])
        out = capsys.readouterr().out.strip()
        doc = json_mod.loads(out)
        assert rc == 0 and doc["findings"] == [] and doc["ci"] == "PASS"

    def test_cli_diff_mode_passes_on_repo(self):
        """`--diff HEAD` (scripts/lint.sh's mode) runs end-to-end: the
        working tree's own changes must lint clean."""
        proc = subprocess.run(
            [sys.executable, "-m", "torchbeast_tpu.analysis",
             "--ci", "--diff", "HEAD"],
            capture_output=True, text=True, cwd=REPO, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "beastlint-ci: PASS" in proc.stdout
