"""Contract tests for the capture-queue benchmark scripts: each must
print exactly one machine-readable JSON verdict line on a CPU rehearsal
(chip day consumes these outputs unattended — a format drift or import
error must surface here, not mid-capture)."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=420):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    proc = subprocess.run(
        [sys.executable, *args],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=REPO,
    )
    return proc


def test_pallas_smoke_interpret_rehearsal(tmp_path):
    proc = _run([
        "benchmarks/pallas_smoke.py", "--interpret", "--sizes", "test",
    ])
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["bench"] == "pallas_smoke"
    assert out["ok"] is True and out["failures"] == []
    assert out["interpret"] is True
    # A CPU interpreter pass must NOT claim the Mosaic box is checked.
    assert out["mosaic"] is False
    assert {c["case"] for c in out["cases"]} == {
        "attn-test", "pool-test", "vtrace-test", "opt-test",
    }


def test_pallas_smoke_compiled_cpu_fails_cleanly():
    """interpret=False on CPU cannot lower — the contract is a clean
    per-case failure with the verdict line still printed and rc=1 (the
    exact behavior a Mosaic lowering failure must produce on chip)."""
    proc = _run(["benchmarks/pallas_smoke.py", "--sizes", "test"])
    assert proc.returncode == 1
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["ok"] is False
    assert set(out["failures"]) == {
        "attn-test", "pool-test", "vtrace-test", "opt-test",
    }
    for case in out["cases"]:
        assert "error" in case and "traceback" in case


def _validate_telemetry_block(block):
    """Shared schema assertion for bench artifacts' `telemetry` block
    (the same shape tests/test_telemetry.py pins against the in-process
    constructor — this end validates the subprocess artifacts)."""
    from torchbeast_tpu import telemetry

    assert isinstance(block, dict), block
    assert isinstance(block.get("enabled"), bool)
    if block["enabled"]:
        problems = telemetry.validate_snapshot(block["snapshot"])
        assert problems == [], problems


def test_inference_bench_embeds_telemetry(tmp_path):
    """Every inference_bench JSON line must carry a well-formed
    `telemetry` block (artifact schema drift fails here, not at
    chip-measure time)."""
    proc = _run([
        "benchmarks/inference_bench.py", "--actors", "4",
        "--seconds", "1", "--num_inference_threads", "1",
        "--acting_batch", "4", "--acting_collects", "2",
        "--acting_warmup", "1", "--acting_unroll", "5",
        "--acting_pool", "serial",
    ])
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [
        json.loads(ln)
        for ln in proc.stdout.strip().splitlines()
        if ln.startswith("{")
    ]
    assert len(lines) >= 3  # 2+ hot-path configs + acting section
    for result in lines:
        _validate_telemetry_block(result["telemetry"])
    hot = [r for r in lines if r["bench"] == "inference_hot_path"]
    snap = hot[0]["telemetry"]["snapshot"]
    # Batch-size distribution present with percentiles.
    bs = snap["histograms"]["inference.batch_size"]
    assert bs["count"] > 0 and bs["p95"] >= bs["p50"] > 0
    acting = next(r for r in lines if r["bench"] == "acting_path")
    hists = acting["telemetry"]["snapshot"]["histograms"]
    assert "acting.sync.collect_s" in hists
    assert "acting.pipelined.collect_s" in hists


def test_inference_bench_no_telemetry_flag(tmp_path):
    """--no_telemetry: the block must say so (and not explode)."""
    proc = _run([
        "benchmarks/inference_bench.py", "--actors", "2",
        "--seconds", "0.5", "--num_inference_threads", "1",
        "--skip_acting", "--no_telemetry",
    ])
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [
        json.loads(ln)
        for ln in proc.stdout.strip().splitlines()
        if ln.startswith("{")
    ]
    assert lines
    for result in lines:
        assert result["telemetry"]["enabled"] is False


def test_telemetry_selftest_cli():
    """The exporter's --selftest is the cheap CI guard for the whole
    snapshot/delta/jsonl/prometheus stack."""
    proc = _run(["-m", "torchbeast_tpu.telemetry", "--selftest"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["selftest"] == "telemetry" and out["ok"] is True


def test_beastlint_selftest_cli():
    """beastlint's --selftest is the cheap CI guard that every rule
    still catches its seeded violation and stays silent on the clean
    twin, and that the suppression/baseline mechanics hold. Schema
    pinned here so the verdict line can't rot."""
    proc = _run(["-m", "torchbeast_tpu.analysis", "--selftest"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["selftest"] == "beastlint" and out["ok"] is True
    assert set(out["rules"]) == {
        "HOTPATH-SYNC", "JIT-HAZARD", "DONATE-USE", "IMPORT-PURITY",
        "LOCK-DISCIPLINE", "EXCEPT-SWALLOW", "WIRE-PARITY",
        "FLAG-PARITY", "RACE", "LOCK-ORDER", "HOTPATH-SYNC-XPROC",
    }
    for checks in out["rules"].values():
        assert set(checks) == {"positive", "clean", "isolated"}
        assert all(checks.values()), out["rules"]
    assert set(out["mechanics"]) == {
        "suppression", "suppress_reason", "baseline",
    }
    assert all(out["mechanics"].values())


def test_wire_bench_selftest(tmp_path):
    """wire_bench --selftest: structural run of every (payload, leg)
    combination with the artifact schema pinned, so the bench can't rot
    between measurement rounds."""
    out_json = tmp_path / "wire_bench.json"
    proc = _run([
        "benchmarks/wire_bench.py", "--selftest", "--out", str(out_json),
    ])
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["bench"] == "wire_bench"
    assert out["ok"] is True and out["failures"] == []
    assert out["selftest"] is True

    legs = {
        (r["payload"], r["leg"]) for r in out["results"]["encode_send"]
    }
    assert legs == {
        (p, leg)
        for p in ("small", "atari", "atari_raw")
        for leg in ("legacy_tcp", "sg_tcp", "sg_shm")
    }
    for row in out["results"]["encode_send"]:
        assert row["msgs_s"] > 0 and row["frame_bytes"] > 0
        assert row["p99_us"] >= row["p50_us"] > 0
    rtts = {(r["payload"], r["transport"]) for r in out["results"]["rtt"]}
    assert rtts == {
        (p, k) for p in ("small", "atari", "atari_raw")
        for k in ("tcp", "shm")
    }
    for key in ("atari_encode_send_speedup", "atari_shm_over_tcp_send",
                "atari_shm_over_tcp_rtt"):
        assert out["acceptance"][key] > 0
    # Native rows (ISSUE 9): present whenever _tbt_core is built (it is
    # in this repo's CI image; a bare checkout records native_skipped).
    if not out["results"].get("native_skipped"):
        native = {
            (r["payload"], r["transport"])
            for r in out["results"]["rtt_native"]
        }
        assert native == {
            (p, k) for p in ("small", "atari", "atari_raw")
            for k in ("native_tcp", "native_shm")
        }
        for row in out["results"]["rtt_native"]:
            assert row["msgs_s"] > 0 and row["iters"] > 0
        assert out["acceptance"][
            "atari_native_shm_over_python_tcp_rtt"] > 0

    # Telemetry block embedded like inference_bench, with the new wire
    # codec histograms populated (encode from the send legs, decode from
    # the RTT legs' client side).
    _validate_telemetry_block(out["telemetry"])
    hists = out["telemetry"]["snapshot"]["histograms"]
    assert hists["wire.encode_s"]["count"] > 0
    assert hists["wire.decode_s"]["count"] > 0

    # The artifact file carries the same verdict.
    saved = json.loads(out_json.read_text())
    assert saved["bench"] == "wire_bench" and saved["ok"] is True


def test_learner_bench_selftest(tmp_path):
    """learner_bench --selftest: structural run of both configs over
    K in {1, 2} with the artifact schema pinned (telemetry block
    validated, host-sync accounting exact), so the bench can't rot
    between measurement rounds."""
    out_json = tmp_path / "learner_bench.json"
    proc = _run([
        "benchmarks/learner_bench.py", "--selftest",
        "--out", str(out_json),
    ])
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["bench"] == "learner_bench"
    assert out["ok"] is True and out["failures"] == []
    assert out["selftest"] is True

    rows = {(r["config"], r["k"]) for r in out["results"]["configs"]}
    assert rows == {
        (c, k) for c in ("mlp", "lstm") for k in (1, 2)
    }
    for row in out["results"]["configs"]:
        assert row["updates_per_sec"] > 0
        # The host-sync contract: EXACTLY updates / K stats round-trips.
        assert row["host_syncs"] * row["k"] == row["updates"]
    assert out["acceptance"]["mlp_speedup_ktop_vs_k1"] > 0

    # Bytes-moved block (ISSUE 8): full (config, K, precision) matrix
    # with XLA-reported figures, fwd_bwd rows per (config, precision),
    # and the f32/bf16_train reductions surfaced in the acceptance.
    bytes_block = out["results"]["bytes"]
    update_rows = {
        (r["config"], r["k"], r["precision"])
        for r in bytes_block["update"]
    }
    assert update_rows == {
        (c, k, p)
        for c in ("mlp", "lstm")
        for k in (1, 2)
        for p in ("f32", "bf16_train")
    }
    for r in bytes_block["update"] + bytes_block["fwd_bwd"]:
        assert r["bytes_accessed"] is None or r["bytes_accessed"] > 0
    red = bytes_block["reductions"]
    for config in ("mlp", "lstm"):
        assert f"{config}_fwd_bwd_reduction" in red
        assert f"{config}_update_reduction_k1" in red
        # bf16_train must MOVE the metric in the right direction even
        # at the selftest's tiny shape (the >=1.8x/1.7x acceptance
        # floors apply to the full run's flagship shape).
        assert red[f"{config}_fwd_bwd_reduction"] > 1.0
    assert out["acceptance"]["bytes"] == red

    # Fused optimizer tail (ISSUE 13): xla-vs-pallas rows per (config,
    # precision) with the pallas side fusing bytes away even at the
    # selftest shape (the flagship 1.15x floors gate the full run).
    tail = out["results"]["opt_tail"]
    tail_rows = {
        (r["config"], r["precision"], r["opt_impl"])
        for r in tail["update"]
    }
    assert tail_rows == {
        (c, p, i)
        for c in ("mlp", "lstm")
        for p in ("f32", "bf16_train")
        for i in ("xla", "pallas")
    }
    for key in (
        "mlp_update_reduction_bf16", "lstm_update_reduction_bf16",
        "combined_update_reduction_bf16",
    ):
        assert tail["reductions"][key] > 1.0
    assert out["acceptance"]["opt_tail"] == tail["reductions"]

    # Remat-plan matrix (ISSUE 13): {none, all, auto} x precision x K
    # for the lstm config, each row carrying updates/s AND bytes; the
    # auto rows record the planner's chosen assignment, and the main
    # gates (all > none bytes; auto < all) are active even in selftest
    # (remat_failures ran — ok:true above proves they passed).
    remat_rows = out["results"]["remat"]["rows"]
    combos = {
        (r["remat"], r["precision"], r["k"]) for r in remat_rows
    }
    assert combos == {
        (plan, p, k)
        for plan in ("none", "all", "auto")
        for p in ("f32", "bf16_train")
        for k in (1, 2)
    }
    for r in remat_rows:
        assert r["updates_per_sec"] > 0
        assert r["bytes_accessed"] is None or r["bytes_accessed"] > 0
        if r["remat"] == "auto":
            assert r["plan"]["source"] in ("auto", "fallback")
            assert "core" in r["plan"]["assignment"]
    assert out["acceptance"]["remat"]["auto_plans"]

    # Telemetry block embedded like the other benches, with the
    # superstep instrumentation populated.
    _validate_telemetry_block(out["telemetry"])
    snap = out["telemetry"]["snapshot"]
    assert snap["histograms"]["learner.update_dispatch_s"]["count"] > 0
    assert snap["histograms"]["learner.updates_per_dispatch"]["count"] > 0
    assert snap["counters"]["learner.host_syncs"] > 0

    saved = json.loads(out_json.read_text())
    assert saved["bench"] == "learner_bench" and saved["ok"] is True


def test_chaos_run_selftest(tmp_path):
    """chaos_run --selftest: two short poly runs (fault-free + seeded
    4-class fault plan) with the acceptance contract schema-pinned —
    completion, exact recovery-counter accounting, return parity, REAL
    load shedding under the injected learner stall with the
    no-lost-rollout audit (resubmitted == shed + expired), and the
    no-leak sweep — so the chaos harness can't rot between acceptance
    rounds (ISSUE 6, serving tier ISSUE 14)."""
    out_json = tmp_path / "chaos_run.json"
    proc = _run([
        "scripts/chaos_run.py", "--selftest", "--out", str(out_json),
    ])
    assert proc.returncode == 0, proc.stderr[-3000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["bench"] == "chaos_run"
    assert out["selftest"] is True
    assert out["ok"] is True and out["failures"] == []
    assert out["scale"] == 1

    # >= 4 fault classes, every one injected exactly as planned.
    kinds = {f["kind"] for f in out["plan"]["faults"]}
    assert {
        "env_server_sigkill", "transport_sever", "state_table_poison",
        "learner_stall",
    } <= kinds
    chaos = out["results"]["chaos"]
    assert chaos["chaos"]["pending"] == []
    assert chaos["chaos"]["abandoned"] == []

    # The serving-tier audit (ISSUE 14): the learner stall produced
    # real sheds, and every shed was re-submitted — never a lost
    # rollout.
    serving = out["serving"]
    assert set(serving) == {"admitted", "shed", "expired", "resubmitted"}
    assert serving["shed"] + serving["expired"] > 0
    assert serving["resubmitted"] == serving["shed"] + serving["expired"]
    assert serving["admitted"] > 0

    # The exact-accounting contract: every expected counter key is
    # present and equal (chaos.<kind>.injected + the recovery mapping).
    counters = chaos["counters"]
    for name in (
        "recovery.server_restarts", "recovery.actor_reconnects",
        "recovery.inference_restarts", "recovery.table_rebuilds",
    ):
        assert name in out["expected_counters"]
    for name, want in out["expected_counters"].items():
        assert int(counters.get(name, 0)) == want, (name, counters)

    # Both runs completed at parity with zero leaked state.
    for run in out["results"].values():
        assert run["step"] >= out["total_steps"]
        assert run["leaked_processes"] == []
        assert run["leaked_shm"] == []
    assert (
        out["results"]["baseline"]["mean_episode_return"]
        == chaos["mean_episode_return"]
    )

    # The ring-wait block (ISSUE 18, metastability baseline): per-leg
    # doorbell counters plus the pressure level, present even at
    # pressure 0 so every committed verdict carries the contrast.
    ring = out["ring"]
    assert ring["scheduler_pressure"] == 0
    for leg in ("baseline", "chaos"):
        assert set(ring[leg]) == {"doorbell_waits", "recheck_wakeups"}
        # recheck wakeups are the subset of armed waits ended by the
        # bounded recheck instead of the doorbell.
        assert ring[leg]["doorbell_waits"] >= 0
        assert 0 <= ring[leg]["recheck_wakeups"] <= (
            ring[leg]["doorbell_waits"]
        )

    _validate_telemetry_block(out["telemetry"])
    saved = json.loads(out_json.read_text())
    assert saved["bench"] == "chaos_run" and saved["ok"] is True


def test_dryrun_multichip_selftest(tmp_path):
    """dryrun_multichip --selftest (ISSUE 15): one tiny run per row
    family (time-shared baseline + 2-forced-device inference-pinned
    split) with the scaling-curve row schema pinned — every row must
    carry the provenance block (`fresh`, forced topology matching the
    row's device count, jax version) so the committed curve follows
    the fresh:false replay discipline."""
    proc = _run(["benchmarks/dryrun_multichip.py", "--selftest"])
    assert proc.returncode == 0, proc.stderr[-3000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["bench"] == "dryrun_multichip_scaling"
    assert out["selftest"]["ok"] is True
    assert out["selftest"]["schema_ok"] is True
    families = {r["family"] for r in out["rows"]}
    assert families == {"time_shared", "inference_pinned"}
    for row in out["rows"]:
        prov = row["provenance"]
        assert prov["fresh"] is True
        assert prov["topology"]["device_count"] == row["n_devices"]
        assert str(row["n_devices"]) in prov["topology"]["forced"]
        assert prov["jax"]
        assert row["updates_per_s"] > 0
        if row["family"] == "inference_pinned":
            assert row["device_split"] == "inf=1,learn=1"
    # The acceptance block is present with the CPU no-regression bar
    # (the verdict itself is the full curve's job, not the selftest's).
    assert out["acceptance"]["required_min_ratio"] == 0.9


def test_impact_ablation_selftest():
    """impact_ablation --selftest (ISSUE 18): two tiny Mock legs
    (vtrace baseline + impact at the 10x lag budget with replay reuse
    2) with the ablation row schema pinned — final return from the
    tail-mean, the env_sps/learn_sps split, publish accounting
    normalized per update, target-network publish counts, lag
    compliance, and the fresh-provenance block — so the committed
    ablation artifact can't silently lose the columns its acceptance
    gates read."""
    proc = _run(["benchmarks/impact_ablation.py", "--selftest"],
                timeout=540)
    assert proc.returncode == 0, proc.stderr[-3000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["bench"] == "impact_ablation"
    assert out["selftest"]["ok"] is True
    assert out["selftest"]["schema_ok"] is True
    by_loss = {r["loss"]: r for r in out["rows"]}
    assert set(by_loss) == {"vtrace", "impact"}
    for row in out["rows"]:
        assert row["provenance"]["fresh"] is True
        assert row["provenance"]["jax"]
        assert row["final_return"] is not None
        assert row["curve"], row
        assert row["env_sps"] > 0 and row["learn_sps"] > 0
        assert row["updates"] > 0
        assert row["publishes_per_update"] is not None
        assert row["lag_compliant"] is True
    vt, imp = by_loss["vtrace"], by_loss["impact"]
    # The impact leg reuses each batch twice: gradient frames outrun
    # env frames, and its target-network store actually published.
    assert imp["replay_reuse"] == 2 and imp["sample_reuse"] == 2.0
    assert imp["learn_sps"] > imp["env_sps"]
    assert imp["target_snapshots_published"] > 0
    # vtrace publishes every update; impact rides the relaxed default
    # — the per-update cadence gap is what the full run gates >= 5x.
    assert vt["publishes_per_update"] > imp["publishes_per_update"]


def test_capacity_bench_selftest():
    """capacity_bench --selftest (ISSUE 16): one tiny NATIVE
    split+replica run per admission family with the capacity-row schema
    pinned — every row must carry the shm scheduler-health counters
    (`ring.doorbell_waits`/`ring.recheck_wakeups`), live admission
    accounting (the armed deadline makes admitted-requests/s real, not
    zero), BOTH per-slice request counters, and the provenance block —
    so the committed capacity_curve.json can't silently lose a column
    between capture rounds."""
    proc = _run(["benchmarks/capacity_bench.py", "--selftest"],
                timeout=540)
    assert proc.returncode == 0, proc.stderr[-3000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["bench"] == "capacity_curve"
    assert out["selftest"]["ok"] is True
    assert out["selftest"]["schema_ok"] is True
    assert out["device_split"] == "inf=2,learn=rest"
    assert out["workload"]["request_deadline_ms"] > 0
    families = {r["family"] for r in out["rows"]}
    assert families == {"continuous", "depth_gated"}
    for row in out["rows"]:
        prov = row["provenance"]
        assert prov["fresh"] is True
        assert prov["topology"]["device_count"] == 3
        assert prov["jax"]
        assert row["steady_sps"] > 0
        assert row["admitted_per_s"] > 0
        assert row["request_p99_ms"] > 0
        assert set(row["ring"]) == {
            "ring.doorbell_waits", "ring.recheck_wakeups"
        }
        # Both pinned inference slices took traffic (the native
        # SliceRouter fanned the slot hash over slice 0 AND 1).
        slices = row["slices"]
        assert set(slices) == {
            "inference.slice.0.requests", "inference.slice.1.requests"
        }
        assert all(v > 0 for v in slices.values())
        assert row["serving"]["serving.admitted"] > 0
        # Selftest rows run unloaded; the pressure row is full-curve.
        assert row["scheduler_pressure"] is False
    # The acceptance block carries the admitted-SPS gate the full
    # curve enforces (or documents the measured ceiling for).
    assert out["acceptance"]["required_min_ratio"] == 1.1
    assert out["acceptance"]["saturation_actors"] == 2


def test_chaos_run_plan_scaling_rule():
    """The --scale plan-scaling rule, pinned WITHOUT a full run: scale
    N plans N SIGKILLs on servers 0..N-1 and N severs on actors
    N..2N-1 (actor i serves from server i % num_servers, so with
    num_servers >= 2N the sever targets' servers are never killed —
    what keeps reconnect accounting exact), plus exactly one poison
    and one learner_stall, triggers staggered and strictly inside the
    run."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import chaos_run
    finally:
        sys.path.pop(0)

    args = chaos_run.parse_args([])
    args.scale = 3
    args.num_servers, args.num_actors = 6, 12
    plan = chaos_run.build_plan(args)
    by_kind = {}
    for fault in plan["faults"]:
        by_kind.setdefault(fault["kind"], []).append(fault)
    assert len(by_kind["env_server_sigkill"]) == 3
    assert len(by_kind["transport_sever"]) == 3
    assert len(by_kind["state_table_poison"]) == 1
    assert len(by_kind["learner_stall"]) == 1
    kill_servers = {f["target"] for f in by_kind["env_server_sigkill"]}
    sever_actors = {f["target"] for f in by_kind["transport_sever"]}
    assert kill_servers == {0, 1, 2}
    assert sever_actors == {3, 4, 5}
    # Disjointness: a severed actor's server is never a killed one.
    assert not {a % args.num_servers for a in sever_actors} & kill_servers
    steps = [f["at_step"] for f in plan["faults"]]
    assert all(0 < s < args.total_steps for s in steps)
    # Staggered within a class: no two same-class faults share a step.
    for kind in ("env_server_sigkill", "transport_sever"):
        kind_steps = [f["at_step"] for f in by_kind[kind]]
        assert len(set(kind_steps)) == len(kind_steps)
    assert by_kind["learner_stall"][0]["duration_s"] == args.stall_s


def test_vtrace_bench_emits_rows(tmp_path):
    out_md = tmp_path / "vtrace.md"
    proc = _run([
        "benchmarks/vtrace_bench.py", "--steps", "3", "--batch", "4",
        "--out", str(out_md),
    ])
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["bench"] == "vtrace_scan"
    assert [r["T"] for r in out["rows"]] == [80, 1000, 4000]
    for r in out["rows"]:
        assert r["sequential_ms"] > 0 and r["associative_ms"] > 0
        assert r["assoc_speedup"] > 0
    # CPU rows must carry the "chip row decides" caveat; the artifact
    # table is appended with the platform in the header.
    assert out["caveat"] is not None
    assert "| 4000 |" in out_md.read_text()
