"""Fused Pallas optimizer tail (--opt_impl pallas, ops/pallas_opt.py):
parity against the optax chain and the ISSUE 13 bytes-accessed gates.

The parity matrix runs REAL update steps ({MLP, LSTM} x
{f32, bf16_train} x clip active/inactive x momentum) and compares the
full post-update state leaf-for-leaf: resident params, second moment,
momentum trace, schedule count, grad-norm stats, and — under
bf16_train — the master round-trip invariant (resident ==
bf16(master) exactly, the same contract learner._bf16_resident_params
pins). The kernel runs the identical f32 math in the identical order,
so tolerances are one-f32-rounding tight.

The bytes gates lower the flagship T=80/B=32 update for the TPU target
(compiled kernel, not the CPU interpreter — learner_bench's
_pallas_compile_env) and compare XLA's pre-opt bytes-accessed against
the COMMITTED PR 8 baseline rows (benchmarks/artifacts/
learner_bench.json, bytes.update, opt_impl=xla): the LSTM — whose
optimizer tail is ~34% of its update — and the mlp+lstm combined
figure must shrink >= 1.15x (the ISSUE floor); the tiny MLP's tail is
only ~8% of its update, so its full-update ceiling is ~1.08x even at
perfect fusion — gated at 1.03x so a fusion regression still fails
while physics does not.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchbeast_tpu import learner as learner_lib
from torchbeast_tpu import precision as precision_lib
from torchbeast_tpu.models import create_model
from torchbeast_tpu.ops.pallas_opt import FusedTailState

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(
    REPO, "benchmarks", "artifacts", "learner_bench.json"
)

T, B, A = 6, 4, 4
FRAME = (4, 4, 1)


def make_batch(seed=0, t=T, b=B):
    rng = np.random.default_rng(seed)
    return {
        "frame": rng.integers(0, 256, (t + 1, b) + FRAME, dtype=np.uint8),
        "reward": rng.standard_normal((t + 1, b)).astype(np.float32),
        "done": rng.random((t + 1, b)) < 0.1,
        "episode_return": rng.standard_normal((t + 1, b)).astype(
            np.float32
        ),
        "episode_step": rng.integers(0, 200, (t + 1, b)).astype(np.int32),
        "last_action": rng.integers(0, A, (t + 1, b)).astype(np.int32),
        "action": rng.integers(0, A, (t + 1, b)).astype(np.int32),
        "policy_logits": rng.standard_normal((t + 1, b, A)).astype(
            np.float32
        ),
        "baseline": rng.standard_normal((t + 1, b)).astype(np.float32),
    }


def _setup(precision, use_lstm, clip, momentum=0.0):
    pol = precision_lib.get(precision)
    hp = learner_lib.HParams(
        unroll_length=T, batch_size=B, total_steps=100_000,
        opt_state_dtype=pol.opt_state_dtype,
        param_dtype=pol.param_dtype,
        grad_norm_clipping=clip,
        rmsprop_momentum=momentum,
    )
    model = create_model(
        "mlp", num_actions=A, use_lstm=use_lstm,
        dtype=pol.compute_dtype, head_dtype=pol.head_dtype,
    )
    batch = precision_lib.cast_batch(make_batch(), pol.batch_dtype)
    state = precision_lib.cast_batch(
        jax.tree_util.tree_map(
            np.asarray, model.initial_state(B)
        ),
        pol.batch_dtype,
    )
    params = model.init(
        {"params": jax.random.PRNGKey(0), "action": jax.random.PRNGKey(1)},
        make_batch(t=0),
        model.initial_state(B),
    )
    params = precision_lib.cast_params(params, pol)
    return hp, model, params, batch, state


def _run_updates(hp, model, params, batch, state, n=3):
    optimizer = learner_lib.make_optimizer(hp)
    update = learner_lib.make_update_step(
        model, optimizer, hp, donate=False
    )
    p = jax.tree_util.tree_map(jnp.copy, params)
    o = optimizer.init(p)
    stats = None
    for _ in range(n):
        p, o, stats = update(p, o, batch, state)
    return p, o, stats


def _assert_trees_close(a, b, atol, rtol=1e-5):
    # rtol covers f32 reassociation drift on O(1)+ magnitudes (the
    # momentum trace accumulates across updates); atol the near-zero
    # leaves.
    for x, y in zip(
        jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    ):
        np.testing.assert_allclose(
            np.asarray(x, np.float32), np.asarray(y, np.float32),
            atol=atol, rtol=rtol,
        )


# clip=0.05 forces the rescale branch on every update (grad norms here
# are O(1)); clip=1e9 keeps it inactive — both sides of the kernel's
# global-norm select.
@pytest.mark.parametrize("clip", [0.05, 1e9])
@pytest.mark.parametrize("use_lstm", [False, True])
@pytest.mark.parametrize("precision", ["f32", "bf16_train"])
def test_fused_tail_matches_optax(precision, use_lstm, clip):
    hp, model, params, batch, state = _setup(precision, use_lstm, clip)
    p_x, o_x, s_x = _run_updates(
        hp._replace(opt_impl="xla"), model, params, batch, state
    )
    p_p, o_p, s_p = _run_updates(
        hp._replace(opt_impl="pallas"), model, params, batch, state
    )
    assert isinstance(o_p, FusedTailState)
    atol = 1e-6 if precision == "f32" else 0.0
    _assert_trees_close(p_x, p_p, atol=atol)
    # grad-norm stats: same grads both paths, exactly.
    np.testing.assert_allclose(
        float(s_x["grad_norm"]), float(s_p["grad_norm"]), rtol=1e-6
    )
    # Schedule clock ticked once per update on both paths.
    import optax

    assert int(o_p.count) == 3
    assert int(optax.tree_utils.tree_get(o_x, "count")) == 3
    # Second moment parity (storage dtype included).
    nu_x = optax.tree_utils.tree_get(o_x, "nu")
    for x, y in zip(
        jax.tree_util.tree_leaves(nu_x),
        jax.tree_util.tree_leaves(o_p.nu),
    ):
        assert x.dtype == y.dtype
        np.testing.assert_allclose(
            np.asarray(x, np.float32), np.asarray(y, np.float32),
            atol=max(atol, 1e-6), rtol=1e-4,
        )


def test_fused_tail_momentum_matches_trace():
    hp, model, params, batch, state = _setup(
        "f32", use_lstm=False, clip=40.0, momentum=0.9
    )
    p_x, o_x, _ = _run_updates(
        hp._replace(opt_impl="xla"), model, params, batch, state
    )
    p_p, o_p, _ = _run_updates(
        hp._replace(opt_impl="pallas"), model, params, batch, state
    )
    _assert_trees_close(p_x, p_p, atol=1e-6)
    import optax

    trace_x = optax.tree_utils.tree_get(o_x, "trace")
    # The trace accumulates g/(sqrt(nu)+eps) terms: early-training nu
    # is tiny, so a one-ulp nu difference amplifies by ~1/eps into the
    # quotient and the momentum sum compounds it — hence the looser
    # rtol here while the params (scaled by lr=4.8e-4) stay tight.
    _assert_trees_close(trace_x, o_p.mom, atol=1e-5, rtol=1e-3)


def test_bf16_master_round_trip_exact():
    """The resident params ARE bf16(master) after every fused update —
    the kernel's narrowing cast is the one the bf16-resident contract
    pins (rounding never compounds)."""
    hp, model, params, batch, state = _setup(
        "bf16_train", use_lstm=True, clip=40.0
    )
    p, o, _ = _run_updates(
        hp._replace(opt_impl="pallas"), model, params, batch, state
    )
    assert o.master is not None
    for res, mst in zip(
        jax.tree_util.tree_leaves(p),
        jax.tree_util.tree_leaves(o.master),
    ):
        assert res.dtype == jnp.bfloat16
        assert mst.dtype == jnp.float32
        np.testing.assert_array_equal(
            np.asarray(res, np.float32),
            np.asarray(mst.astype(jnp.bfloat16), np.float32),
        )


def test_fused_tail_rejects_factored_state():
    hp = learner_lib.HParams(opt_impl="pallas", opt_factored=True)
    with pytest.raises(ValueError, match="factored"):
        learner_lib.make_optimizer(hp)


def test_entropy_anneal_reads_fused_count():
    """entropy_schedule resolves its clock through the fused state's
    `count` field (same name as the optax chain's, by design)."""
    hp = learner_lib.HParams(
        opt_impl="pallas", entropy_cost=0.01, entropy_cost_final=0.0,
        total_steps=1000, unroll_length=T, batch_size=B,
    )
    optimizer = learner_lib.make_optimizer(hp)
    model = create_model("mlp", num_actions=A)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "action": jax.random.PRNGKey(1)},
        make_batch(t=0),
        (),
    )
    opt_state = optimizer.init(params)
    cost_at = learner_lib.entropy_schedule(hp)
    assert float(cost_at(opt_state)) == pytest.approx(0.01)


def _load_learner_bench():
    spec = importlib.util.spec_from_file_location(
        "learner_bench",
        os.path.join(REPO, "benchmarks", "learner_bench.py"),
    )
    lb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lb)
    return lb


def _committed_baseline(config):
    with open(ARTIFACT) as f:
        art = json.load(f)
    row = next(
        r for r in art["results"]["bytes"]["update"]
        if r["config"] == config and r["k"] == 1
        and r["precision"] == "bf16_train"
    )
    return float(row["bytes_accessed"])


def _pallas_update_bytes(lb, config):
    pol = precision_lib.get("bf16_train")
    hp, model, optimizer, params, rng = lb.build_config(
        lb.CONFIGS[config]["use_lstm"], precision="bf16_train",
        t=lb.BYTES_T, b=lb.BYTES_B, opt_impl="pallas",
    )
    batch = precision_lib.cast_batch(
        lb.make_batch(rng, t=lb.BYTES_T, b=lb.BYTES_B), pol.batch_dtype
    )
    state = precision_lib.cast_batch(
        jax.tree_util.tree_map(
            np.asarray, model.initial_state(lb.BYTES_B)
        ),
        pol.batch_dtype,
    )
    upd = learner_lib.make_update_step(
        model, optimizer, hp, donate=False
    )
    with lb._pallas_compile_env():
        value = lb._bytes_of(lb._lower_for_tpu(
            upd, params, optimizer.init(params), batch, state
        ))
    assert value is not None, "cost analysis unavailable"
    return float(value)


def test_fused_tail_bytes_vs_committed_baseline():
    """The ISSUE 13 acceptance gate on the lowered-HLO accounting at
    the flagship T=80/B=32 shapes under bf16_train, vs the PR 8
    committed baseline (docstring has the per-config floor
    rationale)."""
    lb = _load_learner_bench()
    got = {}
    for config in ("mlp", "lstm"):
        got[config] = (
            _committed_baseline(config), _pallas_update_bytes(lb, config)
        )
    lstm_red = got["lstm"][0] / got["lstm"][1]
    mlp_red = got["mlp"][0] / got["mlp"][1]
    combined = (got["mlp"][0] + got["lstm"][0]) / (
        got["mlp"][1] + got["lstm"][1]
    )
    assert lstm_red >= 1.15, got
    assert combined >= 1.15, got
    assert mlp_red >= 1.03, got
