"""Multi-host fleet plane (ISSUE 17): the --fleet grammar and static
topology rules (jax-free), the TAG_SNAPSHOT policy publication path
(bit-exactness, version skew, truncation robustness — the
tests/test_shm_transport.py contract style), and the control plane
over real sockets (heartbeat health folding, host loss vs the
--min_live_hosts floor, synchronous parameter composition)."""

import socket
import threading
import time

import numpy as np
import pytest

from torchbeast_tpu.fleet import (
    FleetCoordinator,
    FleetSpec,
    apply_snapshot,
    build_snapshot,
    compose_fleet_mesh_devices,
    parse_fleet_spec,
)
from torchbeast_tpu.fleet.topology import CONTROL_PORT_OFFSET
from torchbeast_tpu.resilience.supervisor import (
    DEGRADED,
    HALTED,
    HEALTHY,
    PipelineHealth,
)
from torchbeast_tpu.runtime import wire
from torchbeast_tpu.runtime.placement import fleet_host_for_slot
from torchbeast_tpu.serving.snapshot import PolicySnapshotStore
from torchbeast_tpu.telemetry import MetricsRegistry


# ---------------------------------------------------------------------------
# --fleet grammar


def test_parse_fleet_spec_roundtrip():
    spec = parse_fleet_spec("host=1/4,coord=10.0.0.1:8476")
    assert spec == FleetSpec(1, 4, "10.0.0.1:8476")
    assert not spec.is_lead
    assert parse_fleet_spec("host=0/1,coord=h:2").is_lead
    # Whitespace and ordering are forgiven; meaning is not.
    assert parse_fleet_spec(" coord=h:9 , host=2/3 ") == FleetSpec(
        2, 3, "h:9"
    )


def test_parse_fleet_spec_unset_means_single_host():
    assert parse_fleet_spec(None) is None
    assert parse_fleet_spec("") is None
    assert parse_fleet_spec("   ") is None


@pytest.mark.parametrize("bad", [
    "host=1/4",                      # no coord
    "coord=h:1",                     # no host
    "host=1/4,coord=h:1,host=2/4",   # repeated key
    "host=14,coord=h:1",             # rank not <rank>/<n>
    "host=a/b,coord=h:1",            # non-integer rank
    "host=4/4,coord=h:1",            # rank out of range
    "host=-1/4,coord=h:1",           # negative rank
    "host=0/0,coord=h:1",            # zero hosts
    "host=0/2,coord=nope",           # coord not host:port
    "host=0/2,coord=:123",           # empty host
    "host=0/2,coord=h:port",         # non-integer port
    "host=0/2,coord=h:65535",        # port+1 would not exist
    "host=0/2,coord=h:0",            # port 0
    "host=0/2,clock=h:1",            # unknown key
    "host 0/2",                      # not key=value
])
def test_parse_fleet_spec_rejects(bad):
    with pytest.raises(ValueError):
        parse_fleet_spec(bad)


def test_control_address_is_coord_port_plus_one():
    spec = parse_fleet_spec("host=0/2,coord=10.0.0.1:8476")
    assert spec.control_address == f"10.0.0.1:{8476 + CONTROL_PORT_OFFSET}"
    d = spec.describe()
    assert d["coord"] == "10.0.0.1:8476"
    assert d["control"] == "10.0.0.1:8477"


# ---------------------------------------------------------------------------
# Static actor -> host assignment


def test_fleet_host_for_slot_static_and_in_range():
    for n in (1, 2, 3, 5):
        for slot in range(64):
            h = fleet_host_for_slot(slot, n)
            assert 0 <= h < n
            assert h == fleet_host_for_slot(slot, n)  # process-stable


def test_slots_partition_exactly_across_hosts():
    n_hosts, n_slots = 3, 256
    specs = [FleetSpec(r, n_hosts, "h:1") for r in range(n_hosts)]
    owned = [spec.slots_for_host(n_slots) for spec in specs]
    seen = [s for slots in owned for s in slots]
    assert sorted(seen) == list(range(n_slots))  # disjoint AND covering
    # Salted splitmix64, not round-robin: every host gets a working
    # share (the split can be uneven, but never starves a host).
    assert all(len(slots) > n_slots // (n_hosts * 4) for slots in owned)


def test_fleet_host_hash_decorrelated_from_modulo():
    n_hosts = 2
    assignment = [fleet_host_for_slot(s, n_hosts) for s in range(256)]
    # A salted hash must not reduce to slot % n (which would pile every
    # host's slots onto the same env-server stripe).
    assert assignment != [s % n_hosts for s in range(256)]


# ---------------------------------------------------------------------------
# Mesh composition


class FakeDevice:
    def __init__(self, host, idx):
        self.process_index = host
        self.id = host * 100 + idx

    def __repr__(self):
        return f"dev(h{self.process_index}/{self.id})"


def _fake_fleet_devices(n_hosts, per_host):
    return [
        FakeDevice(h, i) for h in range(n_hosts) for i in range(per_host)
    ]


def test_compose_fleet_mesh_is_host_major():
    fleet = FleetSpec(1, 2, "h:1")
    devices = _fake_fleet_devices(2, 2)
    split, learners = compose_fleet_mesh_devices(
        fleet, "inf=1,learn=rest", devices
    )
    # Each host's split reserves its device 0 for inference; the global
    # learner group is host-major: host 0's learner devices then host 1's.
    assert [d.id for d in learners] == [1, 101]
    assert [d.id for d in split.learner_devices] == [101]
    assert [d.id for d in split.inference_devices] == [100]


def test_compose_fleet_mesh_no_split_whole_hosts_learn():
    fleet = FleetSpec(0, 2, "h:1")
    devices = _fake_fleet_devices(2, 2)
    split, learners = compose_fleet_mesh_devices(fleet, "", devices)
    assert split is None
    assert [d.id for d in learners] == [0, 1, 100, 101]


def test_compose_fleet_mesh_rejects_ragged_and_empty_hosts():
    fleet = FleetSpec(0, 2, "h:1")
    ragged = _fake_fleet_devices(2, 2) + [FakeDevice(1, 9)]
    with pytest.raises(ValueError, match="uniform"):
        compose_fleet_mesh_devices(fleet, "", ragged)
    only_host0 = [FakeDevice(0, 0), FakeDevice(0, 1)]
    with pytest.raises(ValueError, match="no devices"):
        compose_fleet_mesh_devices(fleet, "", only_host0)
    with pytest.raises(ValueError, match="outside"):
        compose_fleet_mesh_devices(fleet, "", [FakeDevice(5, 0)])


# ---------------------------------------------------------------------------
# TAG_SNAPSHOT: the wire-published policy path


def _params():
    rng = np.random.default_rng(11)
    import jax.numpy as jnp

    return {
        "core": {
            "w": jnp.asarray(
                rng.standard_normal((4, 3)).astype(np.float32)
            ),
            "b": jnp.asarray(rng.standard_normal(3).astype(np.float32)),
        },
        "steps": jnp.asarray(np.int32(7)),
    }


def _leaf_bytes(tree):
    import jax

    return [
        np.asarray(a).tobytes()
        for a in jax.tree_util.tree_leaves(tree)
    ]


def test_snapshot_wire_roundtrip_bit_exact_vs_local_publish():
    """A remote slice serving a wire-delivered snapshot must hand out
    bit-identical bytes to a local replica at the same version: wire
    encode -> decode -> apply_snapshot -> latest_on equals a plain
    local publish."""
    import jax

    params = _params()
    local = PolicySnapshotStore(1, registry=MetricsRegistry())
    assert local.publish(3, params)

    remote = PolicySnapshotStore(1, registry=MetricsRegistry())
    snap = wire.decode(wire.encode(build_snapshot(3, params))[4:])
    assert isinstance(snap, wire.PolicySnapshot)
    assert apply_snapshot(remote, snap, template=params)

    device = jax.local_devices()[0]
    v_local, tree_local = local.latest_on(device)
    v_remote, tree_remote = remote.latest_on(device)
    assert v_local == v_remote == 3
    assert (
        jax.tree_util.tree_structure(tree_local)
        == jax.tree_util.tree_structure(tree_remote)
    )
    for lo, re_ in zip(_leaf_bytes(tree_local), _leaf_bytes(tree_remote)):
        assert lo == re_  # bit-exact, not allclose
    # Dtypes restored to the ORIGINAL param dtypes on both sides.
    assert [
        np.asarray(a).dtype
        for a in jax.tree_util.tree_leaves(tree_remote)
    ] == [
        np.asarray(a).dtype for a in jax.tree_util.tree_leaves(params)
    ]


def test_snapshot_encoders_agree():
    snap = build_snapshot(9, _params())
    assert bytes(wire.encode_legacy(snap)) == wire.encode(snap)


def test_snapshot_version_skew_stale_rejected():
    params = _params()
    store = PolicySnapshotStore(1, registry=MetricsRegistry())
    reg = MetricsRegistry()
    stale = reg.counter("fleet.snapshots_stale_dropped")
    assert apply_snapshot(store, build_snapshot(5, params), params,
                          stale_counter=stale)
    assert store.version == 5
    # Same version re-delivered and an older one: both dropped, counted,
    # store untouched.
    assert not apply_snapshot(store, build_snapshot(5, params), params,
                              stale_counter=stale)
    assert not apply_snapshot(store, build_snapshot(3, params), params,
                              stale_counter=stale)
    assert store.version == 5
    assert stale.value() == 2
    # Fresh version still lands.
    assert apply_snapshot(store, build_snapshot(6, params), params,
                          stale_counter=stale)
    assert store.version == 6


def test_snapshot_template_mismatch_is_wire_error():
    params = _params()
    store = PolicySnapshotStore(1, registry=MetricsRegistry())
    snap = build_snapshot(1, params)
    with pytest.raises(wire.WireError, match="leaf"):
        apply_snapshot(store, snap, template={"just_one": params["steps"]})
    with pytest.raises(wire.WireError, match="PolicySnapshot"):
        apply_snapshot(store, {"not": "a snapshot"}, template=params)


def test_snapshot_truncation_fuzz_raises_wire_error():
    """Every truncation point of an encoded TAG_SNAPSHOT payload must
    surface as WireError (the one exception connection teardown
    catches), never struct.error/ValueError."""
    payload = bytes(wire.encode_legacy(build_snapshot(2, _params())))[4:]
    cuts = set(range(0, min(len(payload), 64)))
    cuts.update(np.random.default_rng(3).integers(
        0, len(payload), size=80
    ).tolist())
    for cut in sorted(cuts):
        with pytest.raises(wire.WireError):
            wire.decode(payload[:cut])


def test_snapshot_negative_version_rejected_at_build():
    with pytest.raises(wire.WireError):
        wire.PolicySnapshot(-1, [], [])


# ---------------------------------------------------------------------------
# Control plane over real sockets


def _free_port_pair():
    for _ in range(50):
        s1, s2 = socket.socket(), socket.socket()
        try:
            s1.bind(("127.0.0.1", 0))
            port = s1.getsockname()[1]
            try:
                s2.bind(("127.0.0.1", port + 1))
            except OSError:
                continue
            return port
        finally:
            s1.close()
            s2.close()
    raise RuntimeError("no adjacent free ports")


def _pair(min_live_hosts=1, heartbeat_s=0.05, sync_timeout_s=5.0):
    """(lead, remote) coordinators connected over a loopback pair, each
    with its own health plane and registry."""
    port = _free_port_pair()
    coord = f"127.0.0.1:{port}"
    lead = FleetCoordinator(
        FleetSpec(0, 2, coord), PipelineHealth(registry=MetricsRegistry()),
        "wire", min_live_hosts=min_live_hosts, heartbeat_s=heartbeat_s,
        connect_timeout_s=10.0, sync_timeout_s=sync_timeout_s,
        registry=MetricsRegistry(),
    )
    remote = FleetCoordinator(
        FleetSpec(1, 2, coord), PipelineHealth(registry=MetricsRegistry()),
        "wire", min_live_hosts=min_live_hosts, heartbeat_s=heartbeat_s,
        connect_timeout_s=10.0, sync_timeout_s=sync_timeout_s,
        registry=MetricsRegistry(),
    )
    # start() on the lead blocks until the remote dials in.
    lead_started = threading.Thread(target=lead.start, daemon=True)
    lead_started.start()
    remote.start()
    lead_started.join(timeout=10.0)
    assert not lead_started.is_alive(), "lead never saw the remote hello"
    return lead, remote


def _wait(predicate, timeout_s=5.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


def test_coordinator_heartbeat_folds_sticky_degradation():
    lead, remote = _pair()
    try:
        assert lead.live_hosts() == 2
        # A healthy heartbeat folds nothing.
        _wait(lambda: 1 in lead.remote_stats(), what="first heartbeat")
        assert lead._health.state == HEALTHY
        # A recovered incident (restarts > 0, state back to HEALTHY)
        # still leaves a permanent fleet.host1 mark on the lead.
        remote.set_stats_source(
            lambda: {"updates": 42, "restarts": 2, "reconnects": 3}
        )
        remote.set_gauges_source(
            lambda: {"inference.slice.0.depth": 1.5}
        )
        _wait(lambda: lead._health.state == DEGRADED,
              what="fold on the lead")
        assert any(
            r.startswith("fleet.host1") for _, r in lead._health.reasons()
        )
        _wait(
            lambda: lead.remote_gauges().get(1, {}).get(
                "inference.slice.0.depth"
            ) == 1.5,
            what="remote gauges in heartbeats",
        )
        assert lead.remote_stats()[1]["updates"] == 42
        # Sticky: the remote going quiet-and-healthy cannot clear it.
        remote.set_stats_source(
            lambda: {"updates": 50, "restarts": 0, "reconnects": 0}
        )
        time.sleep(0.2)
        assert lead._health.state == DEGRADED
    finally:
        remote.shutdown()
        lead.shutdown()


def test_coordinator_snapshot_delivery_and_skew():
    lead, remote = _pair()
    try:
        params = _params()
        store = PolicySnapshotStore(1, registry=MetricsRegistry())
        remote.attach_snapshot_store(store, params)
        assert lead.publish_snapshot(4, params) == 1
        _wait(lambda: store.version == 4, what="snapshot v4 applied")
        # Re-publishing the same version is dropped as stale remotely.
        assert lead.publish_snapshot(4, params) == 1
        _wait(
            lambda: remote._c_snap_stale.value() == 1,
            what="stale drop counted",
        )
        assert store.version == 4
        assert lead.publish_snapshot(7, params) == 1
        _wait(lambda: store.version == 7, what="snapshot v7 applied")
    finally:
        remote.shutdown()
        lead.shutdown()


def test_coordinator_param_sync_means_across_hosts():
    lead, remote = _pair()
    try:
        tree_lead = {"w": np.full((3,), 1.0, np.float32)}
        tree_remote = {"w": np.full((3,), 3.0, np.float32)}
        out = {}

        def remote_side():
            out["remote"] = remote.sync_params(tree_remote)

        t = threading.Thread(target=remote_side, daemon=True)
        t.start()
        out["lead"] = lead.sync_params(tree_lead)
        t.join(timeout=10.0)
        assert not t.is_alive()
        for side in ("lead", "remote"):
            got = out[side]
            assert got is not None, f"{side} sync degraded"
            np.testing.assert_array_equal(
                np.asarray(got["w"]), np.full((3,), 2.0, np.float32)
            )
            assert np.asarray(got["w"]).dtype == np.float32
    finally:
        remote.shutdown()
        lead.shutdown()


def test_coordinator_lead_sync_degrades_after_remote_done():
    lead, remote = _pair(sync_timeout_s=1.0)
    try:
        remote.learner_done()
        _wait(lambda: 1 in lead._done, what="done registered")
        # The lead no longer waits on host 1: a solo round returns its
        # own params (mean of one) instead of timing out.
        tree = {"w": np.full((2,), 5.0, np.float32)}
        t0 = time.monotonic()
        got = lead.sync_params(tree)
        assert time.monotonic() - t0 < 0.9
        np.testing.assert_array_equal(np.asarray(got["w"]), tree["w"])
    finally:
        remote.shutdown()
        lead.shutdown()


def test_coordinator_host_loss_above_floor_is_sticky_degraded():
    lead, remote = _pair(min_live_hosts=1)
    try:
        # Abrupt death: close the remote's socket without a bye.
        with remote._lock:
            conn = remote._conns.pop(0)
            remote._send_locks.pop(0, None)
        conn.close()
        _wait(lambda: lead.live_hosts() == 1, what="loss detected")
        assert lead._health.state == DEGRADED
        assert any(
            r.startswith("fleet.host1_lost")
            for _, r in lead._health.reasons()
        )
    finally:
        remote._closing.set()
        lead.shutdown()


def test_coordinator_host_loss_below_floor_halts_fleet():
    lead, remote = _pair(min_live_hosts=2)
    try:
        with remote._lock:
            conn = remote._conns.pop(0)
            remote._send_locks.pop(0, None)
        conn.close()
        _wait(lambda: lead._health.state == HALTED,
              what="floor-crossing halt")
        assert any(
            "min_live_hosts" in r for _, r in lead._health.reasons()
        )
    finally:
        remote._closing.set()
        lead.shutdown()


def test_coordinator_remote_halts_when_lead_lost_uncleanly():
    lead, remote = _pair()
    try:
        with lead._lock:
            conn = lead._conns.pop(1)
            lead._send_locks.pop(1, None)
        conn.close()
        _wait(lambda: remote._health.state == HALTED,
              what="remote halt on lead loss")
        assert remote.live_hosts() == 0
    finally:
        lead._closing.set()
        remote.shutdown()


def test_coordinator_clean_shutdown_is_not_a_loss():
    lead, remote = _pair()
    try:
        remote.shutdown()  # sends bye
        _wait(lambda: 1 in lead._done, what="clean departure recorded")
        time.sleep(0.1)
        assert lead._health.state == HEALTHY  # no loss, no fold
        assert lead.live_hosts() == 2  # departed cleanly, never "lost"
    finally:
        lead.shutdown()


def test_coordinator_remote_sync_bails_after_clean_lead_exit():
    lead, remote = _pair(sync_timeout_s=5.0)
    try:
        lead.shutdown()  # clean bye to the remote
        _wait(
            lambda: remote._lead_gone, what="lead departure seen",
        )
        t0 = time.monotonic()
        got = remote.sync_params({"w": np.zeros(2, np.float32)})
        assert got is None  # degraded round: caller keeps its params
        assert time.monotonic() - t0 < 1.0  # without burning the timeout
        assert remote._health.state == HEALTHY  # clean exit != fault
    finally:
        remote.shutdown()


def test_coordinator_rejects_bad_floor():
    with pytest.raises(ValueError):
        FleetCoordinator(
            FleetSpec(0, 2, "h:1"),
            PipelineHealth(registry=MetricsRegistry()),
            "wire", min_live_hosts=3, registry=MetricsRegistry(),
        )
    with pytest.raises(ValueError):
        FleetCoordinator(
            FleetSpec(0, 2, "h:1"),
            PipelineHealth(registry=MetricsRegistry()),
            "wire", min_live_hosts=0, registry=MetricsRegistry(),
        )
