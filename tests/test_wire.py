"""Wire protocol roundtrips, including the reference's non-contiguous-array
regression (tests/contiguous_arrays_test.py: transposed arrays must survive
the wire intact)."""

import numpy as np
import pytest

from torchbeast_tpu.runtime import wire


def roundtrip(value):
    framed = wire.encode(value)
    length = int.from_bytes(framed[:4], "little")
    assert length == len(framed) - 4
    return wire.decode(framed[4:])


def test_scalars_and_strings():
    assert roundtrip(None) is None
    assert roundtrip(True) is True
    assert roundtrip(False) is False
    assert roundtrip(42) == 42
    assert roundtrip(-1) == -1
    assert roundtrip(2.5) == 2.5
    assert roundtrip("héllo") == "héllo"


@pytest.mark.parametrize(
    "dtype", [np.uint8, np.int32, np.int64, np.float32, np.float64, np.bool_]
)
def test_array_roundtrip(dtype):
    rng = np.random.default_rng(0)
    arr = (rng.random((3, 4, 5)) * 100).astype(dtype)
    out = roundtrip(arr)
    assert out.dtype == arr.dtype
    np.testing.assert_array_equal(out, arr)


def test_non_contiguous_array_survives():
    # The reference had a bug here (rpcenv.cc:166-170): transposed numpy
    # arrays are not C-contiguous and must be normalized before the wire.
    arr = np.arange(12).reshape(3, 4).T
    assert not arr.flags["C_CONTIGUOUS"]
    out = roundtrip(arr)
    np.testing.assert_array_equal(out, arr)
    assert out.shape == (4, 3)


def test_nested_structures():
    value = {
        "step": {
            "frame": np.zeros((2, 2), np.uint8),
            "reward": 1.5,
            "done": False,
        },
        "list": [np.ones(3, np.float32), "x", None, 7],
    }
    out = roundtrip(value)
    np.testing.assert_array_equal(out["step"]["frame"], value["step"]["frame"])
    assert out["step"]["reward"] == 1.5
    assert out["step"]["done"] is False
    np.testing.assert_array_equal(out["list"][0], value["list"][0])
    assert out["list"][1:] == ["x", None, 7]


def test_empty_containers():
    assert roundtrip([]) == []
    assert roundtrip({}) == {}


def test_zero_size_array():
    out = roundtrip(np.zeros((0, 5), np.float32))
    assert out.shape == (0, 5)


def test_zero_dim_array_keeps_shape():
    # np.ascontiguousarray promotes 0-d to 1-d; the codec must not.
    out = roundtrip(np.asarray(np.float32(1.5)))
    assert out.shape == ()
    assert out.dtype == np.float32
    assert float(out) == 1.5


def test_trailing_garbage_rejected():
    framed = wire.encode(1)
    with pytest.raises(wire.WireError):
        wire.decode(framed[4:] + b"\x00")


def test_unknown_tag_rejected():
    with pytest.raises(wire.WireError):
        wire.decode(b"\xff")


def test_decoded_arrays_are_views():
    # Zero-copy on decode: the array's memory belongs to the payload.
    arr = np.arange(10, dtype=np.int64)
    framed = wire.encode(arr)
    out = wire.decode(framed[4:])
    assert not out.flags["OWNDATA"]
