"""Wire protocol roundtrips, including the reference's non-contiguous-array
regression (tests/contiguous_arrays_test.py: transposed arrays must survive
the wire intact)."""

import struct

import numpy as np
import pytest

from torchbeast_tpu.runtime import wire


def roundtrip(value):
    framed = wire.encode(value)
    length = int.from_bytes(framed[:4], "little")
    assert length == len(framed) - 4
    return wire.decode(framed[4:])


def test_scalars_and_strings():
    assert roundtrip(None) is None
    assert roundtrip(True) is True
    assert roundtrip(False) is False
    assert roundtrip(42) == 42
    assert roundtrip(-1) == -1
    assert roundtrip(2.5) == 2.5
    assert roundtrip("héllo") == "héllo"


@pytest.mark.parametrize(
    "dtype", [np.uint8, np.int32, np.int64, np.float32, np.float64, np.bool_]
)
def test_array_roundtrip(dtype):
    rng = np.random.default_rng(0)
    arr = (rng.random((3, 4, 5)) * 100).astype(dtype)
    out = roundtrip(arr)
    assert out.dtype == arr.dtype
    np.testing.assert_array_equal(out, arr)


def test_non_contiguous_array_survives():
    # The reference had a bug here (rpcenv.cc:166-170): transposed numpy
    # arrays are not C-contiguous and must be normalized before the wire.
    arr = np.arange(12).reshape(3, 4).T
    assert not arr.flags["C_CONTIGUOUS"]
    out = roundtrip(arr)
    np.testing.assert_array_equal(out, arr)
    assert out.shape == (4, 3)


def test_nested_structures():
    value = {
        "step": {
            "frame": np.zeros((2, 2), np.uint8),
            "reward": 1.5,
            "done": False,
        },
        "list": [np.ones(3, np.float32), "x", None, 7],
    }
    out = roundtrip(value)
    np.testing.assert_array_equal(out["step"]["frame"], value["step"]["frame"])
    assert out["step"]["reward"] == 1.5
    assert out["step"]["done"] is False
    np.testing.assert_array_equal(out["list"][0], value["list"][0])
    assert out["list"][1:] == ["x", None, 7]


def test_empty_containers():
    assert roundtrip([]) == []
    assert roundtrip({}) == {}


def test_zero_size_array():
    out = roundtrip(np.zeros((0, 5), np.float32))
    assert out.shape == (0, 5)


def test_zero_dim_array_keeps_shape():
    # np.ascontiguousarray promotes 0-d to 1-d; the codec must not.
    out = roundtrip(np.asarray(np.float32(1.5)))
    assert out.shape == ()
    assert out.dtype == np.float32
    assert float(out) == 1.5


def test_trailing_garbage_rejected():
    framed = wire.encode(1)
    with pytest.raises(wire.WireError):
        wire.decode(framed[4:] + b"\x00")


def test_unknown_tag_rejected():
    with pytest.raises(wire.WireError):
        wire.decode(b"\xff")


def _array_header(code: int, *dims: int) -> bytes:
    return (
        bytes([wire.TAG_ARRAY, code, len(dims)])
        + b"".join(struct.pack("<q", d) for d in dims)
    )


def test_malformed_array_frames_raise_wire_error():
    """Adversarial frames off the socket must fail as WireError (the
    connection-teardown exception), never ValueError/struct.error."""
    cases = [
        _array_header(4, -8),  # negative dim
        _array_header(4, 1 << 62, 1 << 62) + b"\x00",  # product wraps
        _array_header(5, 1 << 61),  # numel*itemsize overflows
        _array_header(4, 7),  # size exceeds payload (no data bytes)
        bytes([wire.TAG_ARRAY, 4, 3]),  # truncated shape
        bytes([wire.TAG_ARRAY, 0x7F, 0]),  # unknown dtype code
        bytes([wire.TAG_STRING]) + struct.pack("<I", 0xFFFFFFFF),  # huge len
        bytes([wire.TAG_INT]) + b"\x01",  # truncated i64
    ]
    for payload in cases:
        with pytest.raises(wire.WireError):
            wire.decode(payload)


@pytest.mark.parametrize("shape", [(0, 5), (5, 0), (3, 0, 1 << 40)])
def test_zero_dim_in_shape_still_decodes(shape):
    # A zero dim anywhere makes the array empty — the validator must not
    # demand bytes for the nonzero dims around it.
    arr = wire.decode(_array_header(4, *shape))
    assert arr.shape == shape and arr.size == 0


def test_fuzz_random_nests_roundtrip():
    """Randomized structures/dtypes/shapes survive the wire bit-exact."""
    rng = np.random.default_rng(2024)
    dtypes = sorted(wire._DTYPE_CODES, key=str)  # every supported dtype

    def random_value(depth=0):
        kind = rng.integers(0, 9 if depth < 3 else 6)
        if kind == 0:
            return None
        if kind == 1:
            return bool(rng.integers(0, 2))
        if kind == 2:
            return int(rng.integers(-(2 ** 40), 2 ** 40))
        if kind == 3:
            return float(rng.random() * 1e6 - 5e5)
        if kind == 4:
            return "".join(chr(rng.integers(32, 1000)) for _ in range(8))
        if kind == 5:
            shape = tuple(rng.integers(0, 4, size=rng.integers(0, 4)))
            dt = dtypes[rng.integers(0, len(dtypes))]
            # np.asarray: rng.random(()) yields a numpy SCALAR, which the
            # codec intentionally encodes as a scalar tag; arrays only here.
            return np.asarray((rng.random(shape) * 100).astype(dt))
        if kind == 6:
            return [random_value(depth + 1) for _ in range(rng.integers(0, 4))]
        return {
            f"k{i}": random_value(depth + 1)
            for i in range(rng.integers(0, 4))
        }

    def check(a, b):
        if isinstance(a, np.ndarray):
            assert a.dtype == b.dtype and a.shape == b.shape
            np.testing.assert_array_equal(a, b)
        elif isinstance(a, list):
            assert isinstance(b, list) and len(a) == len(b)
            for x, y in zip(a, b):
                check(x, y)
        elif isinstance(a, dict):
            assert set(a) == set(b)
            for k in a:
                check(a[k], b[k])
        else:
            # type-exact: bool must not come back as int, int not as float
            assert type(a) is type(b) and a == b

    for _ in range(200):
        value = random_value()
        check(value, roundtrip(value))


def test_decoded_arrays_are_views():
    # Zero-copy on decode: the array's memory belongs to the payload.
    arr = np.arange(10, dtype=np.int64)
    framed = wire.encode(arr)
    out = wire.decode(framed[4:])
    assert not out.flags["OWNDATA"]
