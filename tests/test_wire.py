"""Wire protocol roundtrips, including the reference's non-contiguous-array
regression (tests/contiguous_arrays_test.py: transposed arrays must survive
the wire intact) — plus the zero-copy transport contracts (ISSUE 3): the
scatter-gather encoder is pinned byte-identical to the legacy encoder, the
RecvBuffer receive path is allocation-free at steady state, corrupt frames
of every flavor surface as WireError, and oversized frames are rejected
before allocation."""

import socket
import struct
import threading
import tracemalloc

import numpy as np
import pytest

from torchbeast_tpu.runtime import wire


def random_nest(rng, depth=0):
    """Shared fuzz generator: random structures/dtypes/shapes over every
    supported dtype (including bf16 when ml_dtypes is present)."""
    dtypes = sorted(wire._DTYPE_CODES, key=str)
    kind = rng.integers(0, 9 if depth < 3 else 6)
    if kind == 0:
        return None
    if kind == 1:
        return bool(rng.integers(0, 2))
    if kind == 2:
        return int(rng.integers(-(2 ** 40), 2 ** 40))
    if kind == 3:
        return float(rng.random() * 1e6 - 5e5)
    if kind == 4:
        return "".join(chr(rng.integers(32, 1000)) for _ in range(8))
    if kind == 5:
        # Shapes up to ~2k elements so some arrays cross the
        # scatter-gather threshold (>= _GATHER_MIN_BYTES) and some don't.
        shape = tuple(rng.integers(0, 14, size=rng.integers(0, 4)))
        dt = dtypes[rng.integers(0, len(dtypes))]
        return np.asarray((rng.random(shape) * 100).astype(dt))
    if kind == 6:
        return [random_nest(rng, depth + 1) for _ in range(rng.integers(0, 4))]
    return {
        f"k{i}": random_nest(rng, depth + 1)
        for i in range(rng.integers(0, 4))
    }


def assert_nest_equal(a, b):
    if isinstance(a, np.ndarray):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)
    elif isinstance(a, list):
        assert isinstance(b, list) and len(a) == len(b)
        for x, y in zip(a, b):
            assert_nest_equal(x, y)
    elif isinstance(a, dict):
        assert set(a) == set(b)
        for k in a:
            assert_nest_equal(a[k], b[k])
    else:
        # type-exact: bool must not come back as int, int not as float
        assert type(a) is type(b) and a == b


def roundtrip(value):
    framed = wire.encode(value)
    length = int.from_bytes(framed[:4], "little")
    assert length == len(framed) - 4
    return wire.decode(framed[4:])


def test_scalars_and_strings():
    assert roundtrip(None) is None
    assert roundtrip(True) is True
    assert roundtrip(False) is False
    assert roundtrip(42) == 42
    assert roundtrip(-1) == -1
    assert roundtrip(2.5) == 2.5
    assert roundtrip("héllo") == "héllo"


@pytest.mark.parametrize(
    "dtype", [np.uint8, np.int32, np.int64, np.float32, np.float64, np.bool_]
)
def test_array_roundtrip(dtype):
    rng = np.random.default_rng(0)
    arr = (rng.random((3, 4, 5)) * 100).astype(dtype)
    out = roundtrip(arr)
    assert out.dtype == arr.dtype
    np.testing.assert_array_equal(out, arr)


def test_non_contiguous_array_survives():
    # The reference had a bug here (rpcenv.cc:166-170): transposed numpy
    # arrays are not C-contiguous and must be normalized before the wire.
    arr = np.arange(12).reshape(3, 4).T
    assert not arr.flags["C_CONTIGUOUS"]
    out = roundtrip(arr)
    np.testing.assert_array_equal(out, arr)
    assert out.shape == (4, 3)


def test_nested_structures():
    value = {
        "step": {
            "frame": np.zeros((2, 2), np.uint8),
            "reward": 1.5,
            "done": False,
        },
        "list": [np.ones(3, np.float32), "x", None, 7],
    }
    out = roundtrip(value)
    np.testing.assert_array_equal(out["step"]["frame"], value["step"]["frame"])
    assert out["step"]["reward"] == 1.5
    assert out["step"]["done"] is False
    np.testing.assert_array_equal(out["list"][0], value["list"][0])
    assert out["list"][1:] == ["x", None, 7]


def test_empty_containers():
    assert roundtrip([]) == []
    assert roundtrip({}) == {}


def test_zero_size_array():
    out = roundtrip(np.zeros((0, 5), np.float32))
    assert out.shape == (0, 5)


def test_zero_dim_array_keeps_shape():
    # np.ascontiguousarray promotes 0-d to 1-d; the codec must not.
    out = roundtrip(np.asarray(np.float32(1.5)))
    assert out.shape == ()
    assert out.dtype == np.float32
    assert float(out) == 1.5


def test_trailing_garbage_rejected():
    framed = wire.encode(1)
    with pytest.raises(wire.WireError):
        wire.decode(framed[4:] + b"\x00")


def test_unknown_tag_rejected():
    with pytest.raises(wire.WireError):
        wire.decode(b"\xff")


def _array_header(code: int, *dims: int) -> bytes:
    return (
        bytes([wire.TAG_ARRAY, code, len(dims)])
        + b"".join(struct.pack("<q", d) for d in dims)
    )


def test_malformed_array_frames_raise_wire_error():
    """Adversarial frames off the socket must fail as WireError (the
    connection-teardown exception), never ValueError/struct.error."""
    cases = [
        _array_header(4, -8),  # negative dim
        _array_header(4, 1 << 62, 1 << 62) + b"\x00",  # product wraps
        _array_header(5, 1 << 61),  # numel*itemsize overflows
        _array_header(4, 7),  # size exceeds payload (no data bytes)
        bytes([wire.TAG_ARRAY, 4, 3]),  # truncated shape
        bytes([wire.TAG_ARRAY, 0x7F, 0]),  # unknown dtype code
        bytes([wire.TAG_STRING]) + struct.pack("<I", 0xFFFFFFFF),  # huge len
        bytes([wire.TAG_INT]) + b"\x01",  # truncated i64
    ]
    for payload in cases:
        with pytest.raises(wire.WireError):
            wire.decode(payload)


@pytest.mark.parametrize("shape", [(0, 5), (5, 0), (3, 0, 1 << 40)])
def test_zero_dim_in_shape_still_decodes(shape):
    # A zero dim anywhere makes the array empty — the validator must not
    # demand bytes for the nonzero dims around it.
    arr = wire.decode(_array_header(4, *shape))
    assert arr.shape == shape and arr.size == 0


def test_fuzz_random_nests_roundtrip():
    """Randomized structures/dtypes/shapes survive the wire bit-exact."""
    rng = np.random.default_rng(2024)
    dtypes = sorted(wire._DTYPE_CODES, key=str)  # every supported dtype

    def random_value(depth=0):
        kind = rng.integers(0, 9 if depth < 3 else 6)
        if kind == 0:
            return None
        if kind == 1:
            return bool(rng.integers(0, 2))
        if kind == 2:
            return int(rng.integers(-(2 ** 40), 2 ** 40))
        if kind == 3:
            return float(rng.random() * 1e6 - 5e5)
        if kind == 4:
            return "".join(chr(rng.integers(32, 1000)) for _ in range(8))
        if kind == 5:
            shape = tuple(rng.integers(0, 4, size=rng.integers(0, 4)))
            dt = dtypes[rng.integers(0, len(dtypes))]
            # np.asarray: rng.random(()) yields a numpy SCALAR, which the
            # codec intentionally encodes as a scalar tag; arrays only here.
            return np.asarray((rng.random(shape) * 100).astype(dt))
        if kind == 6:
            return [random_value(depth + 1) for _ in range(rng.integers(0, 4))]
        return {
            f"k{i}": random_value(depth + 1)
            for i in range(rng.integers(0, 4))
        }

    def check(a, b):
        if isinstance(a, np.ndarray):
            assert a.dtype == b.dtype and a.shape == b.shape
            np.testing.assert_array_equal(a, b)
        elif isinstance(a, list):
            assert isinstance(b, list) and len(a) == len(b)
            for x, y in zip(a, b):
                check(x, y)
        elif isinstance(a, dict):
            assert set(a) == set(b)
            for k in a:
                check(a[k], b[k])
        else:
            # type-exact: bool must not come back as int, int not as float
            assert type(a) is type(b) and a == b

    for _ in range(200):
        value = random_value()
        check(value, roundtrip(value))


def test_decoded_arrays_are_views():
    # Zero-copy on decode: the array's memory belongs to the payload.
    arr = np.arange(10, dtype=np.int64)
    framed = wire.encode(arr)
    out = wire.decode(framed[4:])
    assert not out.flags["OWNDATA"]


# ---------------------------------------------------------------------------
# Scatter-gather encode (format pin + iovec semantics)


def test_encode_matches_legacy_fuzz():
    """FORMAT PIN: the scatter-gather encoder must be byte-identical to
    the legacy BytesIO encoder on arbitrary nests (csrc/wire.h interop
    depends on it), and the iovec list must concatenate to the same
    frame with the advertised total."""
    rng = np.random.default_rng(7)
    buf = wire.SendBuffer()
    for _ in range(300):
        value = random_nest(rng)
        legacy = wire.encode_legacy(value)
        assert wire.encode(value) == legacy
        views, total = wire.encode_into(value, buf)
        assert b"".join(views) == legacy
        assert total == len(legacy)


def test_encode_matches_legacy_numpy_scalars():
    # np scalars ride the slow isinstance chain; semantics must not drift
    # from the legacy encoder (np.bool_ -> BOOL, np.int32 -> INT, ...).
    for value in [np.bool_(True), np.int32(-5), np.int64(9), np.uint8(7),
                  np.float32(1.5), np.float64(2.5), (1, 2, "x"),
                  {"k": np.float16(0.5)}]:
        assert wire.encode(value) == wire.encode_legacy(value)


def test_encode_into_gathers_large_arrays_zero_copy():
    """Arrays >= the gather threshold must ride their own iovec aliasing
    the source numpy buffer (no copy); small arrays land in scratch."""
    big = np.arange(4096, dtype=np.uint8)
    small = np.arange(16, dtype=np.uint8)
    buf = wire.SendBuffer()
    views, total = wire.encode_into({"big": big, "small": small}, buf)
    gathered = [
        v for v in views
        if v.nbytes == big.nbytes and v.obj is not buf.scratch
    ]
    assert len(gathered) == 1
    # Mutating the source array mutates the iovec: proof there is no copy
    # (and why the no-mutation-until-sent lifetime rule exists).
    big[0] = 123
    assert gathered[0][0] == 123


def test_send_message_scatter_gather_roundtrip():
    """send_message(buf=SendBuffer) <-> recv_message_sized(buf=RecvBuffer)
    over a real socket, message sizes varying both directions so both
    buffers grow and shrink usage across messages."""
    rng = np.random.default_rng(11)
    a, b = socket.socketpair()
    send_buf, recv_buf = wire.SendBuffer(), wire.RecvBuffer(initial_bytes=64)
    try:
        sizes = [10, 5000, 3, 80000, 200, 12000, 0]
        for n in sizes:
            value = {"arr": np.arange(n, dtype=np.int32), "n": n}
            sender = threading.Thread(
                target=wire.send_message, args=(a, value),
                kwargs={"buf": send_buf},
            )
            sender.start()
            out, nbytes = wire.recv_message_sized(b, buf=recv_buf)
            sender.join()
            assert nbytes == len(wire.encode_legacy(value))
            assert out["n"] == n
            np.testing.assert_array_equal(
                np.asarray(out["arr"]).copy(), np.arange(n, dtype=np.int32)
            )
    finally:
        a.close()
        b.close()


def test_sendmsg_all_handles_partial_sends():
    """_sendmsg_all must reassemble correctly when the kernel accepts
    arbitrary prefixes (forced with a fake socket capping bytes/call)."""

    class ThrottledSock:
        def __init__(self, cap):
            self.cap = cap
            self.sent = bytearray()

        def sendmsg(self, views):
            budget = self.cap
            for v in views:
                take = min(len(v), budget)
                self.sent += bytes(v[:take])
                budget -= take
                if not budget:
                    break
            return self.cap - budget

        def sendall(self, data):  # IOV_MAX fallback
            self.sent += bytes(data)

    rng = np.random.default_rng(3)
    value = {"a": np.arange(5000, dtype=np.int64), "b": "tail",
             "c": np.arange(2000, dtype=np.uint8)}
    frame = wire.encode_legacy(value)
    for cap in (1, 7, 1000, 4096, 1 << 20):
        sock = ThrottledSock(cap)
        views, total = wire.encode_into(value, wire.SendBuffer())
        wire._sendmsg_all(sock, views, total)
        assert bytes(sock.sent) == frame, f"cap={cap}"


def test_sendmsg_iov_max_fallback_roundtrip():
    # > _IOV_MAX gathered arrays: the joined-sendall fallback must still
    # produce one well-formed frame.
    value = [np.full(1024, i % 250, np.uint8) for i in range(600)]
    a, b = socket.socketpair()
    try:
        result = {}
        recv = threading.Thread(
            target=lambda: result.update(out=wire.recv_message(b))
        )
        recv.start()
        wire.send_message(a, value, buf=wire.SendBuffer())
        recv.join()
        out = result["out"]
        assert len(out) == 600
        np.testing.assert_array_equal(out[599], value[599])
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# Frame length bound (--max_frame_bytes)


def _send_raw_header(sock, length):
    sock.sendall(struct.pack("<I", length))


@pytest.mark.parametrize("use_recv_buffer", [False, True])
def test_oversized_frame_rejected_before_allocation(use_recv_buffer):
    """A corrupt 4-byte header demanding gigabytes must fail as WireError
    BEFORE the payload allocation, on both receive paths."""
    a, b = socket.socketpair()
    try:
        _send_raw_header(a, 0xF0000000)  # ~3.75 GiB claim
        buf = wire.RecvBuffer() if use_recv_buffer else None
        with pytest.raises(wire.WireError, match="max_frame_bytes"):
            wire.recv_message_sized(b, buf=buf)
        assert buf is None or buf.capacity < (1 << 20)
    finally:
        a.close()
        b.close()


def test_max_frame_bytes_custom_limit():
    frame = wire.encode(np.zeros(8192, np.uint8))
    a, b = socket.socketpair()
    try:
        a.sendall(frame)
        with pytest.raises(wire.WireError, match="max_frame_bytes"):
            wire.recv_message_sized(b, max_frame_bytes=1024)
    finally:
        a.close()
        b.close()
    # The default limit admits the same frame (fresh socket: the
    # rejected frame's payload is still queued on the old one — the
    # production paths tear the connection down on WireError).
    a, b = socket.socketpair()
    try:
        a.sendall(frame)
        out, nbytes = wire.recv_message_sized(b)
        assert nbytes == len(frame)
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# bfloat16 (wire code 12)


try:
    import ml_dtypes
except ImportError:  # pragma: no cover - ml_dtypes ships with jax here
    ml_dtypes = None

needs_bf16 = pytest.mark.skipif(
    ml_dtypes is None, reason="ml_dtypes not installed"
)


@needs_bf16
def test_bfloat16_roundtrip():
    bf16 = np.dtype(ml_dtypes.bfloat16)
    assert wire._DTYPE_CODES[bf16] == 12  # pinned: csrc/array.h kBF16
    arr = (np.arange(12).reshape(3, 4) / 4).astype(bf16)
    framed = wire.encode(arr)
    assert framed == wire.encode_legacy(arr)
    out = wire.decode(framed[4:])
    assert out.dtype == bf16
    np.testing.assert_array_equal(
        np.asarray(out, np.float32), np.asarray(arr, np.float32)
    )


@needs_bf16
def test_bfloat16_zero_dim_and_empty():
    bf16 = np.dtype(ml_dtypes.bfloat16)
    for arr in [np.zeros((), bf16), np.zeros((0, 3), bf16),
                np.zeros((2000,), bf16)]:  # last one crosses gather cutoff
        framed = wire.encode(arr)
        assert framed == wire.encode_legacy(arr)
        out = wire.decode(framed[4:])
        assert out.dtype == bf16 and out.shape == arr.shape


# ---------------------------------------------------------------------------
# RecvBuffer: allocation-free steady state + buffer-reuse lifetime


def _socket_stream(frames):
    """Write `frames` (pre-encoded) into one end of a socketpair from a
    thread; return the read end."""
    a, b = socket.socketpair()

    def pump():
        for f in frames:
            a.sendall(f)
        a.close()

    t = threading.Thread(target=pump)
    t.start()
    return b, t


def test_recv_buffer_zero_steady_state_allocations():
    """The RecvBuffer receive path must do no payload-sized allocations
    at steady state: 50 receives of ~256 KiB frames may allocate less
    than one frame's worth of memory IN TOTAL (small constant per-recv
    object churn only — no chunk lists, no b''.join, no growth)."""
    frame = wire.encode({"frame": np.zeros(256 * 1024, np.uint8), "t": 1})
    buf = wire.RecvBuffer()
    warm, t = _socket_stream([frame] * 5)
    for _ in range(5):
        wire.recv_message_sized(warm, buf=buf)  # buffer reaches max size
    t.join()
    warm.close()
    capacity = buf.capacity

    b, t = _socket_stream([frame] * 50)
    tracemalloc.start()
    before = tracemalloc.take_snapshot()
    for _ in range(50):
        value, nbytes = wire.recv_message_sized(b, buf=buf)
        assert nbytes == len(frame)
    after = tracemalloc.take_snapshot()
    tracemalloc.stop()
    t.join()
    b.close()
    assert buf.capacity == capacity  # no regrowth
    grown = sum(
        d.size_diff for d in after.compare_to(before, "filename")
        if d.size_diff > 0
    )
    # 50 x 256KiB frames moved; anything payload-proportional would be
    # ~13 MB. Allow generous slack for interpreter noise.
    assert grown < 128 * 1024, f"receive path allocated {grown} bytes"


def test_recv_buffer_reuse_lifetime_rule():
    """Decoded nests alias the RecvBuffer: the next recv on the same
    buffer OVERWRITES them (this is the documented contract consumers
    like ActorPool must copy under)."""
    f1 = wire.encode(np.full(2048, 1, np.uint8))
    f2 = wire.encode(np.full(2048, 2, np.uint8))
    buf = wire.RecvBuffer(initial_bytes=8192)
    b, t = _socket_stream([f1, f2])
    first, _ = wire.recv_message_sized(b, buf=buf)
    assert int(first[0]) == 1
    second, _ = wire.recv_message_sized(b, buf=buf)
    t.join()
    b.close()
    # Same-size successor overwrote the first nest in place.
    assert int(first[0]) == 2
    assert int(second[0]) == 2
    with pytest.raises((ValueError, TypeError)):
        first[0] = 9  # views into the buffer are read-only


def test_recv_buffer_growth_preserves_triggering_message():
    """Growth allocates a FRESH buffer, so the message that caused the
    growth stays valid while the old (smaller) buffer's views die."""
    small = wire.encode(np.full(64, 7, np.uint8))
    big = wire.encode(np.full(1 << 16, 9, np.uint8))
    buf = wire.RecvBuffer(initial_bytes=4096)
    b, t = _socket_stream([small, big])
    first, _ = wire.recv_message_sized(b, buf=buf)
    second, _ = wire.recv_message_sized(b, buf=buf)  # forces growth
    t.join()
    b.close()
    assert int(first[0]) == 7  # old buffer alive via the view
    assert int(second[0]) == 9 and second.shape == (1 << 16,)


# ---------------------------------------------------------------------------
# Corruption fuzz: every malformed frame fails as WireError, never
# struct.error/ValueError (the connection-teardown contract)


def test_truncated_frames_always_raise_wire_error():
    rng = np.random.default_rng(13)
    for _ in range(40):
        payload = wire.encode(random_nest(rng))[4:]
        if not len(payload):
            continue
        for cut in sorted({int(c) for c in rng.integers(
                0, len(payload), size=8)}):
            try:
                wire.decode(payload[:cut])
            except wire.WireError:
                pass  # the only acceptable failure
            # a clean decode of a strict prefix is impossible: the
            # trailing-garbage check requires full consumption, so a
            # successful return means cut == len(payload)


def test_bitflipped_frames_raise_wire_error_or_decode():
    """Random single-byte corruption: decode may succeed (flips inside
    array payloads are just different data) but any failure must be
    WireError."""
    rng = np.random.default_rng(17)
    for _ in range(60):
        payload = bytearray(wire.encode(random_nest(rng))[4:])
        if not payload:
            continue
        pos = int(rng.integers(0, len(payload)))
        payload[pos] ^= 1 << int(rng.integers(0, 8))
        try:
            wire.decode(bytes(payload))
        except wire.WireError:
            pass
