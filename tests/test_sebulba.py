"""Sebulba device split (ISSUE 15): placement parsing/hashing, per-slice
table pinning under jax.transfer_guard, static hash-by-connection
routing stability, DP-sharded superstep accounting on a 2-device learner
mesh, device-to-device snapshot publication parity, and the async driver
end to end with `--device_split`.

Multi-device cases run on the conftest's 8 forced host CPU devices and
SKIP visibly (tests/jax_caps.has_multi_device_cpu) where the
`--xla_force_host_platform_device_count` flag is unsupported.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tests import jax_caps
from torchbeast_tpu.runtime.placement import (
    DeviceSplit,
    parse_device_split,
    resolve_device_split,
)

multi_device = pytest.mark.skipif(
    not jax_caps.has_multi_device_cpu(2),
    reason="needs >= 2 jax devices "
           "(xla_force_host_platform_device_count unsupported here)",
)


class _FakeDevice:
    def __init__(self, i):
        self.id = i

    def __repr__(self):
        return f"dev{self.id}"


def _fake_devices(n):
    return [_FakeDevice(i) for i in range(n)]


class TestDeviceSplitSpec:
    def test_parse_grammar(self):
        assert parse_device_split(None) is None
        assert parse_device_split("") is None
        assert parse_device_split("  ") is None
        assert parse_device_split("auto") == {"inf": "auto",
                                              "learn": "rest"}
        assert parse_device_split("inf=2,learn=rest") == {
            "inf": 2, "learn": "rest"
        }
        assert parse_device_split("inf=1,learn=3") == {
            "inf": 1, "learn": 3
        }
        assert parse_device_split("inf=3") == {"inf": 3, "learn": "rest"}

    @pytest.mark.parametrize("bad", [
        "garbage", "inf=x", "inf=0", "learn=2", "inf=1,learn=0",
        "inf=1,learn=q", "inf=1,inf=2", "inf=1,weird=2",
    ])
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            parse_device_split(bad)

    def test_resolve_auto_fraction(self):
        split = resolve_device_split("auto", _fake_devices(8))
        assert split.n_slices == 2  # 8 // 4
        assert len(split.learner_devices) == 6
        split = resolve_device_split("auto", _fake_devices(2))
        assert split.n_slices == 1  # floor, min 1
        assert len(split.learner_devices) == 1

    def test_resolve_explicit(self):
        split = resolve_device_split("inf=1,learn=rest", _fake_devices(4))
        assert split.n_slices == 1
        assert len(split.learner_devices) == 3
        # Explicit learn=M leaves surplus devices idle.
        split = resolve_device_split("inf=2,learn=2", _fake_devices(8))
        assert [d.id for d in split.inference_devices] == [0, 1]
        assert [d.id for d in split.learner_devices] == [2, 3]

    def test_resolve_rejects_overcommit(self):
        with pytest.raises(ValueError):
            resolve_device_split("inf=4,learn=rest", _fake_devices(4))
        with pytest.raises(ValueError):
            resolve_device_split("inf=3,learn=2", _fake_devices(4))

    def test_single_device_degrades_to_time_shared(self):
        assert resolve_device_split("auto", _fake_devices(1)) is None
        assert (
            resolve_device_split("inf=1,learn=rest", _fake_devices(1))
            is None
        )

    def test_describe_is_json_shaped(self):
        import json

        split = resolve_device_split("inf=2,learn=rest", _fake_devices(4))
        desc = json.loads(json.dumps(split.describe()))
        assert desc["inference_slices"] == 2
        assert desc["learner_devices"] == 2

    def test_slot_hash_static_and_process_stable(self):
        """The actor->slice assignment is a pure function of the slot
        id: identical across DeviceSplit instances (reconnects build
        nothing new) and across processes (splitmix64, not Python's
        salted hash). The literal expectation pins the mapping — a
        hash-function change would silently migrate every deployed
        run's slot tables."""
        a = resolve_device_split("inf=2,learn=rest", _fake_devices(4))
        b = resolve_device_split("inf=2,learn=rest", _fake_devices(4))
        assignment = [a.slice_for_slot(i) for i in range(16)]
        assert assignment == [b.slice_for_slot(i) for i in range(16)]
        assert assignment[:8] == [1, 1, 0, 1, 0, 0, 0, 1]
        # Every slice serves someone (no dead device) at real actor
        # counts.
        assert set(assignment) == {0, 1}

    def test_needs_both_sides(self):
        with pytest.raises(ValueError):
            DeviceSplit("x", (), tuple(_fake_devices(2)))
        with pytest.raises(ValueError):
            DeviceSplit("x", tuple(_fake_devices(2)), ())


# --- multi-device matrix ------------------------------------------------


def _lstm_like_act(ctx, env_outputs, agent_state):
    """A tiny traced act body with the production shape: reads the
    params ctx, advances the [1, B, H] state, returns [1, B] outputs."""
    params, key = ctx
    h = agent_state["h"]
    x = env_outputs["obs"]  # [1, B, D]
    new_h = jnp.tanh(h + x.mean(-1, keepdims=True) * params["w"])
    out = {"action": new_h.sum(-1)[...]}  # [1, B]
    return out, {"h": new_h}


def _make_store(device=None):
    from torchbeast_tpu.serving import PolicySnapshotStore
    from torchbeast_tpu import telemetry

    store = PolicySnapshotStore(1, registry=telemetry.MetricsRegistry())
    params = {"w": jnp.full((4,), 0.5, jnp.float32)}
    if device is not None:
        params = jax.device_put(params, device)
    store.note_update(0)
    store.publish(0, params)
    return store


def _build_serving(split, store, num_slots=8):
    from torchbeast_tpu import telemetry
    from torchbeast_tpu.parallel.sebulba import build_sebulba_serving

    return build_sebulba_serving(
        split,
        store,
        num_slots=num_slots,
        max_batch_size=4,
        timeout_ms=20,
        max_policy_lag=10,
        initial_state={"h": np.zeros((1, 1, 4), np.float32)},
        table_act_fn=_lstm_like_act,
        registry=telemetry.MetricsRegistry(),
    )


def _the_device(x):
    devices = list(x.devices()) if hasattr(x, "devices") else [x.device]
    assert len(devices) == 1
    return devices[0]


@multi_device
class TestSlicePinning:
    def test_slice_tables_and_outputs_pinned(self):
        """Every slice's table lives (and stays) on its own device, a
        full step runs under jax.transfer_guard('disallow') — only
        EXPLICIT transfers on the serving path — and the advanced
        state never appears on another slice's device."""
        devices = jax.devices()
        split = resolve_device_split("inf=2,learn=rest", devices[:3])
        store = _make_store()
        serving = _build_serving(split, store)
        env = {"obs": np.ones((1, 4, 3), np.float32)}
        for stack in serving.stacks:
            table = stack.state_table
            # Warm the hooks' lazy rng OUTSIDE the guard (PRNGKey
            # construction is an ordinary host->device transfer); the
            # steady-state serving path below runs fully guarded.
            stack.hooks.begin_batch()
            with jax.transfer_guard("disallow"):
                ctx, _ = stack.hooks.begin_batch()
                out = table.step(
                    np.arange(4, dtype=np.int32),
                    np.ones(4, bool),
                    env,
                    context=ctx,
                )
                fetched = table.fetch(out, 4)
            assert fetched["action"].shape == (1, 4)
            for leaf in jax.tree_util.tree_leaves(table._table):
                assert _the_device(leaf) == stack.device
        # Cross-slice isolation: the two tables occupy DIFFERENT
        # devices (a shared default placement would pass the per-slice
        # check above while time-sharing one chip).
        assert serving.stacks[0].device != serving.stacks[1].device

    def test_sharded_facade_routes_by_slot(self):
        devices = jax.devices()
        split = resolve_device_split("inf=2,learn=rest", devices[:3])
        store = _make_store()
        serving = _build_serving(split, store)
        tables = serving.state_tables
        assert tables.num_slots == 8
        for slot in range(8):
            expected = serving.stacks[
                split.slice_for_slot(slot)
            ].state_table
            assert tables.table_for_slot(slot) is expected
            # Boundary reads come back from the owning slice, shaped
            # like initial_state.
            state = tables.read_slot(slot)
            assert state["h"].shape == (1, 1, 4)
        # reset groups by slice and touches only the owning tables.
        tables.reset(list(range(8)))
        # Poison/rebuild fan out (the supervisor's one-event contract).
        tables.poison()
        assert tables.poisoned
        tables.rebuild()
        assert not tables.poisoned

    def test_router_static_hash_stable_across_reconnects(self):
        """Routing is a pure function of the slot id: the same slot
        lands on the same slice across repeated requests (reconnects
        re-enter compute with the same slot), across router rebuilds,
        and matches the split's published assignment."""
        devices = jax.devices()
        split = resolve_device_split("inf=2,learn=rest", devices[:3])

        class FakeBatcher:
            def __init__(self):
                self.seen = []

            def compute(self, inputs, trace=None):
                self.seen.append(int(inputs["slot"][0, 0]))
                return {"ok": True}

            def size(self):
                return 0

            def is_closed(self):
                return False

        from torchbeast_tpu import telemetry
        from torchbeast_tpu.parallel.sebulba import SliceRouter, SliceStack

        def build_router():
            stacks = [
                SliceStack(i, d, FakeBatcher(), None, None, lambda: None)
                for i, d in enumerate(split.inference_devices)
            ]
            return stacks, SliceRouter(
                split, stacks, registry=telemetry.MetricsRegistry()
            )

        stacks_a, router_a = build_router()
        stacks_b, router_b = build_router()
        for _ in range(3):  # repeated requests == reconnect re-entries
            for slot in range(8):
                req = {"slot": np.full((1, 1), slot, np.int32)}
                router_a.compute(req)
                router_b.compute(req)
        for slot in range(8):
            want = split.slice_for_slot(slot)
            for stacks in (stacks_a, stacks_b):
                for i, stack in enumerate(stacks):
                    if i == want:
                        assert stack.batcher.seen.count(slot) == 3
                    else:
                        assert slot not in stack.batcher.seen

    def test_router_round_robins_stateless(self):
        """Slot-less (stateless-model) requests have no resident state
        to pin; they spread across slices."""
        devices = jax.devices()
        split = resolve_device_split("inf=2,learn=rest", devices[:3])

        from torchbeast_tpu import telemetry
        from torchbeast_tpu.parallel.sebulba import SliceRouter, SliceStack

        class FakeBatcher:
            def __init__(self):
                self.n = 0

            def compute(self, inputs, trace=None):
                self.n += 1
                return {}

            def size(self):
                return 0

            def is_closed(self):
                return False

        stacks = [
            SliceStack(i, d, FakeBatcher(), None, None, lambda: None)
            for i, d in enumerate(split.inference_devices)
        ]
        router = SliceRouter(
            split, stacks, registry=telemetry.MetricsRegistry()
        )
        for _ in range(10):
            router.compute({"env": {}})
        assert stacks[0].batcher.n == 5
        assert stacks[1].batcher.n == 5


@multi_device
class TestSnapshotDeviceToDevice:
    def test_publish_and_latest_on_version_parity(self):
        """The cross-slice publication path: publish on one device,
        place on another — version parity with latest(), leaves
        committed to the target device, values equal to the bf16
        round-trip, and the per-device cache refreshing on republish.
        The whole path runs under jax.transfer_guard('disallow'):
        zero implicit host round-trips."""
        from torchbeast_tpu import telemetry
        from torchbeast_tpu.serving import PolicySnapshotStore

        devices = jax.devices()
        src, dst = devices[0], devices[1]
        store = PolicySnapshotStore(
            1, registry=telemetry.MetricsRegistry()
        )
        params = jax.device_put(
            {"w": jnp.arange(8, dtype=jnp.float32) / 7.0,
             "b": jnp.ones((3,), jnp.bfloat16)},
            src,
        )
        with jax.transfer_guard("disallow"):
            store.note_update(0)
            store.publish(0, params)
            version, placed = store.latest_on(dst)
        assert version == store.latest()[0] == 0
        for leaf in jax.tree_util.tree_leaves(placed):
            assert _the_device(leaf) == dst
        # Values match the bf16 publication round-trip; dtypes restore.
        assert placed["w"].dtype == jnp.float32
        np.testing.assert_array_equal(
            np.asarray(placed["w"]),
            np.asarray(params["w"].astype(jnp.bfloat16)
                       .astype(jnp.float32)),
        )
        # Cache: same version returns the identical placed tree.
        assert store.latest_on(dst)[1] is placed
        # Republish invalidates per-device caches.
        params2 = jax.device_put({"w": params["w"] * 2.0,
                                  "b": params["b"]}, src)
        store.note_update(5)
        with jax.transfer_guard("disallow"):
            store.publish(5, params2)
            version2, placed2 = store.latest_on(dst)
        assert version2 == 5
        assert placed2 is not placed

    def test_hooks_ctx_lands_on_slice_device(self):
        from torchbeast_tpu import telemetry
        from torchbeast_tpu.serving import ReplicaServingHooks

        devices = jax.devices()
        store = _make_store(device=devices[0])
        hooks = ReplicaServingHooks(
            store, max_policy_lag=4, registry=telemetry.MetricsRegistry(),
            device=devices[1], health_key="slice1_lag",
        )
        (params, key), annotate = hooks.begin_batch()
        for leaf in jax.tree_util.tree_leaves(params) + [key]:
            assert _the_device(leaf) == devices[1]
        out = annotate({"action": np.zeros((1, 3))}, 3)
        np.testing.assert_array_equal(
            out["policy_lag"], np.zeros((1, 3), np.int32)
        )


@multi_device
class TestSplitSuperstepAccounting:
    def test_k1_vs_k2_on_two_device_mesh(self):
        """K=2 superstep over the split's 2-device DP learner mesh ==
        two K=1 dispatches over the same mesh: params and the
        [K]-stacked stats agree (the MLP family is bit-stable under
        scan fusion — the same contract test_learner_superstep pins
        single-device)."""
        from torchbeast_tpu import learner as learner_lib
        from torchbeast_tpu.models import create_model
        from torchbeast_tpu.parallel import (
            create_mesh,
            make_parallel_update_step,
            replicate,
            shard_batch,
        )

        devices = jax.devices()
        mesh = create_mesh(devices=list(devices[1:3]))  # learner devices
        T, B, A, K = 4, 4, 3, 2
        model = create_model("mlp", num_actions=A)

        def make_batch(seed):
            r = np.random.default_rng(seed)
            return {
                "frame": r.integers(
                    0, 255, (T + 1, B, 4, 4, 1), dtype=np.uint8
                ),
                "reward": r.standard_normal((T + 1, B)).astype(np.float32),
                "done": r.random((T + 1, B)) < 0.1,
                "episode_return": np.zeros((T + 1, B), np.float32),
                "episode_step": np.zeros((T + 1, B), np.int32),
                "last_action": r.integers(0, A, (T + 1, B)).astype(np.int32),
                "action": r.integers(0, A, (T + 1, B)).astype(np.int32),
                "policy_logits": r.standard_normal(
                    (T + 1, B, A)
                ).astype(np.float32),
                "baseline": r.standard_normal((T + 1, B)).astype(np.float32),
            }

        batches = [make_batch(i) for i in range(K)]
        hp = learner_lib.HParams(batch_size=B, unroll_length=T)
        optimizer = learner_lib.make_optimizer(hp)
        init = model.init(
            {"params": jax.random.PRNGKey(0),
             "action": jax.random.PRNGKey(1)},
            batches[0],
            (),
        )

        # K=1 twice.
        step1 = make_parallel_update_step(
            model, optimizer, hp, mesh, donate=False
        )
        params1 = replicate(mesh, init)
        opt1 = optimizer.init(params1)
        stats_seq = []
        for b in batches:
            bs, ss = shard_batch(mesh, b, ())
            params1, opt1, stats = step1(params1, opt1, bs, ss)
            stats_seq.append(jax.device_get(stats))

        # One K=2 superstep over the same mesh.
        step2 = make_parallel_update_step(
            model, optimizer, hp, mesh, donate=False, superstep_k=K
        )
        params2 = replicate(mesh, init)
        opt2 = optimizer.init(params2)
        stacked = {
            k: np.stack([b[k] for b in batches]) for k in batches[0]
        }
        bs, ss = shard_batch(mesh, stacked, (), leading_axes=1)
        params2, opt2, stats2 = step2(params2, opt2, bs, ss)
        stats2 = jax.device_get(stats2)

        for a, b in zip(
            jax.tree_util.tree_leaves(params1),
            jax.tree_util.tree_leaves(params2),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # [K]-stacked stats row k == the k-th sequential dispatch.
        for key in ("total_loss", "grad_norm"):
            got = np.asarray(stats2[key]).reshape(K)
            want = np.asarray([s[key] for s in stats_seq]).reshape(K)
            np.testing.assert_allclose(got, want, rtol=1e-6)


@multi_device
def test_polybeast_device_split_e2e(tmp_path):
    """The async driver end to end with --device_split inf=1,learn=rest
    on the forced host devices: trains to completion, telemetry carries
    the per-slice gauges + learner.mesh_shape on every line, and the
    snapshot publication really ran."""
    import json
    import os

    from torchbeast_tpu import polybeast, telemetry

    reg = telemetry.get_registry()
    published_before = int(
        reg.counter("serving.snapshots_published").value()
    )
    argv = [
        "--env", "Mock",
        "--num_servers", "2",
        "--batch_size", "2",
        "--unroll_length", "5",
        "--total_steps", "60",
        "--savedir", str(tmp_path),
        "--xpid", "poly-split",
        "--model", "mlp",
        "--use_lstm",
        "--pipes_basename", f"unix:{tmp_path}/pipes",
        "--num_inference_threads", "1",
        "--max_inference_batch_size", "4",
        "--checkpoint_interval_s", "100000",
        "--device_split", "inf=1,learn=2",
        "--num_learner_devices", "2",
    ]
    flags = polybeast.make_parser().parse_args(argv)
    stats = polybeast.train(flags)
    assert stats["step"] >= 60
    assert np.isfinite(stats["total_loss"])
    published = (
        int(reg.counter("serving.snapshots_published").value())
        - published_before
    )
    assert published >= 1  # v0 at minimum
    tpath = os.path.join(str(tmp_path), "poly-split", "telemetry.jsonl")
    lines = [json.loads(line) for line in open(tpath)]
    assert lines
    for line in lines:
        assert line["learner.mesh_shape"] == {"data": 2, "model": 1}
        assert line["device_split"]["inference_slices"] == 1
        assert "inference.slice.0.depth" in line.get("gauges", {})
