"""Pallas max-pool backward (ops/pallas_pool.py): parity with the
autodiff (SelectAndScatter) gradient, run in interpret mode on CPU.

f32 random inputs are tie-free almost surely, so parity is exact. bf16's
8-bit mantissa makes within-window ties common; on ties this kernel (like
the CPU tap-sum VJP) credits every tying position where SelectAndScatter
credits one, so bf16 is compared only at positions with a unique window
max."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from torchbeast_tpu.ops.pallas_pool import pool_bwd, supports

SHAPES = [
    (6, 84, 84, 16),  # trunk stage 1
    (3, 42, 42, 32),  # trunk stage 2
    (5, 21, 21, 32),  # trunk stage 3 (odd H/W)
    (2, 11, 13, 8),   # odd + non-square + ragged N vs block_n
]


def _fwd(x):
    return lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
        ((0, 0), (1, 1), (1, 1), (0, 0)),
    )


@pytest.mark.parametrize("shape", SHAPES)
def test_f32_matches_autodiff(shape):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    y, vjp = jax.vjp(_fwd, x)
    g = jnp.asarray(rng.standard_normal(y.shape), jnp.float32)
    gx_ref = vjp(g)[0]
    gx = pool_bwd(x, y, g, interpret=True)
    np.testing.assert_allclose(
        np.asarray(gx), np.asarray(gx_ref), rtol=1e-6, atol=1e-6
    )


def test_bf16_matches_on_unique_argmax_positions():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((4, 84, 84, 16)), jnp.bfloat16)
    y, vjp = jax.vjp(_fwd, x)
    g = jnp.asarray(rng.standard_normal(y.shape), jnp.bfloat16)
    gx_ref = np.asarray(vjp(g)[0], np.float32)
    gx = np.asarray(pool_bwd(x, y, g, interpret=True), np.float32)

    # Tie map: how many positions in each window equal its max. A position
    # is "safe" if every window that reaches it has exactly one winner.
    xf = np.asarray(x, np.float32)
    yf = np.asarray(y, np.float32)
    N, H, W, C = xf.shape
    Ho, Wo = yf.shape[1], yf.shape[2]
    xp = np.pad(xf, ((0, 0), (1, 1), (1, 1), (0, 0)),
                constant_values=-np.inf)
    counts = np.zeros_like(yf)
    for kh in range(3):
        for kw in range(3):
            tap = xp[:, kh : kh + 2 * Ho : 2, kw : kw + 2 * Wo : 2, :]
            counts += (tap == yf)
    # windows with a unique winner
    unique = counts == 1
    # input positions touched only by unique-winner windows
    safe = np.ones_like(xf, bool)
    for kh in range(3):
        for kw in range(3):
            tap_unique = np.ones((N, H + 2, W + 2, C), bool)
            sl_h = slice(kh, kh + 2 * Ho, 2)
            sl_w = slice(kw, kw + 2 * Wo, 2)
            tap_unique[:, sl_h, sl_w, :] = unique
            safe &= tap_unique[:, 1 : 1 + H, 1 : 1 + W, :]
    assert safe.mean() > 0.5  # the comparison is not vacuous
    np.testing.assert_allclose(gx[safe], gx_ref[safe], rtol=0.05, atol=0.05)


def test_supports_gate():
    x = jnp.zeros((2, 8, 8, 4), jnp.float32)
    assert supports(x, (3, 3), (2, 2), ((1, 1), (1, 1)))
    assert not supports(x, (2, 2), (2, 2), ((0, 0), (0, 0)))
    assert not supports(x, (3, 3), (1, 1), ((1, 1), (1, 1)))
    assert not supports(
        jnp.zeros((2, 8, 8, 4), jnp.int32), (3, 3), (2, 2), ((1, 1), (1, 1))
    )


def test_block_n_does_not_change_result():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((7, 21, 21, 8)), jnp.float32)
    y = _fwd(x)
    g = jnp.asarray(rng.standard_normal(y.shape), jnp.float32)
    a = pool_bwd(x, y, g, block_n=2, interpret=True)
    b = pool_bwd(x, y, g, block_n=7, interpret=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
