"""Benchmark: learner-update throughput in env frames/sec/chip.

Measures the flagship IMPALA learner step (deep ResNet + LSTM, unroll T=80,
batch B=32 — the reference's beefy-machine unroll with its canonical
large-scale batch, BASELINE.md) as a single jitted XLA program with donated
state, on whatever accelerator the ambient JAX sees (the real TPU chip under
the driver).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "frames/sec/chip", "vs_baseline": N}
where `value`/`vs_baseline` are the f32 learner step (apples-to-apples with
the f32 torch baseline), plus diagnostic fields: platform/device, step_ms,
bf16_value + bf16_vs_baseline (accelerator only — the mixed-precision
number, reported separately precisely because it is NOT numerics-identical
to the baseline), per-dtype achieved TFLOP/s from XLA's own cost analysis,
mfu (bf16 achieved vs the chip's bf16 peak), inference_steps_per_sec
(largest act bucket), and anakin_sps (the fully-on-device Podracer trainer
on Catch).

vs_baseline compares against the torch-CPU reference-equivalent learner step
measured by benchmarks/torch_baseline.py on this machine (stored in
BASELINE_measured.json). The reference repo publishes no numbers
(BASELINE.md), so the baseline is measured, not copied.

Robustness: backend init runs in a watchdog subprocess first and is retried
with backoff (the TPU tunnel can wedge for long stretches); only after all
probes fail does the bench fall back to CPU, and it says so in the
"platform" field rather than hanging the driver. The XLA compile cache is
keyed per host CPU so an AOT result built on one machine is never loaded on
another (SIGILL risk).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

T = 80
B = 32
STEPS = 10
WARMUP = 2

# Probe schedule: (timeout_s, sleep_after_failure_s). Total worst case
# ~33 min before the CPU fallback — the tunnel has been observed wedging
# for long stretches, and a real-TPU number is worth the wait (a CPU
# fallback line is close to worthless as a TPU benchmark).
PROBE_SCHEDULE = ((120, 30), (300, 60), (300, 120), (300, 300), (300, 0))

# Peak bf16 TFLOP/s per chip by device kind (public figures). MFU is
# best-effort: unknown kinds report achieved TFLOP/s with mfu=null.
PEAK_BF16_TFLOPS = {
    "v2": 45.0,
    "v3": 123.0,
    "v4": 275.0,
    "v5 lite": 197.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0,
    "v6e": 918.0,
}

# Peak HBM bandwidth GB/s per chip (public figures). The IMPALA trunk's
# 16/32-channel convs are ~28 FLOP/byte — far below the ~240 FLOP/byte a
# v5e needs to saturate the MXU from HBM — so the step is bandwidth-bound
# and HBM roofline utilization, not MFU, is the number that says whether
# the program is near the hardware ceiling.
PEAK_HBM_GBPS = {
    "v2": 700.0,
    "v3": 900.0,
    "v4": 1228.0,
    "v5 lite": 819.0,
    "v5e": 819.0,
    "v5p": 2765.0,
    "v6 lite": 1640.0,
    "v6e": 1640.0,
}


def _probe_backend(timeout_s: int):
    """Ask a watchdog subprocess what the ambient backend is.

    Returns (platform, device_kind) or None if init hung/failed.
    """
    code = (
        "import jax; d = jax.devices()[0]; "
        "print('PROBE', d.platform, '|', d.device_kind)"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return None
    for line in out.stdout.splitlines():
        if line.startswith("PROBE "):
            rest = line[len("PROBE "):]
            plat, _, kind = rest.partition(" | ")
            return plat.strip(), kind.strip()
    return None


def _acquire_backend():
    """Fight for the accelerator: probe with retries/backoff before giving
    up and falling back to CPU."""
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        return None
    for i, (timeout_s, sleep_s) in enumerate(PROBE_SCHEDULE):
        probe = _probe_backend(timeout_s)
        if probe is not None:
            return probe
        sys.stderr.write(
            f"bench: backend probe {i + 1}/{len(PROBE_SCHEDULE)} timed out "
            f"after {timeout_s}s\n"
        )
        if sleep_s:
            time.sleep(sleep_s)
    return None


def _cache_dir() -> str:
    """Per-host-CPU compile cache (shared helper; a cache shared across
    hosts can load foreign AOT results and SIGILL)."""
    from torchbeast_tpu.utils.xla_cache import host_keyed_cache_dir

    return host_keyed_cache_dir()


def _peak_for(kind: str, table):
    """Chip-kind -> peak figure by substring match; None if unknown."""
    return next((p for name, p in table.items() if name in kind), None)


def _cost_analysis(jitted, *args):
    """(flops, bytes_accessed) per call from XLA's own cost analysis of
    the optimized HLO (best-effort; bytes are a post-fusion proxy for
    HBM traffic)."""
    try:
        analysis = jitted.lower(*args).compile().cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0]
        flops = float(analysis.get("flops", 0.0))
        nbytes = float(analysis.get("bytes accessed", 0.0))
        return (flops if flops > 0 else None,
                nbytes if nbytes > 0 else None)
    except Exception:
        return None, None


def run_bench():
    import jax

    # Persistent compilation cache: repeat bench runs skip the multi-minute
    # XLA compile of the deep net.
    jax.config.update("jax_compilation_cache_dir", _cache_dir())

    from torchbeast_tpu import learner as learner_lib

    device = jax.devices()[0]
    platform = device.platform
    on_accel = platform != "cpu"
    steps, warmup = (STEPS, WARMUP) if on_accel else (3, 1)

    # Same flagship construction the driver compile-checks (one source of
    # truth for the model/batch schema).
    import __graft_entry__
    import jax.numpy as jnp

    # Timing sync: fetch the final loss to HOST (device_get) rather than
    # block_until_ready — on the remote-TPU tunnel backend the latter has
    # been observed returning before compute finishes (a run "measured"
    # 0.79 ms for a 72 ms step); a host fetch of a scalar that data-depends
    # on the whole chained loop cannot lie. A per-step fetch would add a
    # full tunnel round-trip (~50 ms) to every step, so fetch once at the
    # end — unless the plausibility guard below trips, in which case
    # re-measure with the per-step fetch and report that (conservative)
    # number.
    def measure(dtype, sync_each=False):
        model, params, batch, state = __graft_entry__._flagship(
            batch_size=B, t=T, dtype=dtype
        )
        hp = learner_lib.HParams(batch_size=B, unroll_length=T)
        optimizer = learner_lib.make_optimizer(hp)
        opt_state = optimizer.init(params)
        update_step = learner_lib.make_update_step(model, optimizer, hp)

        batch_d = jax.device_put(batch)
        state_d = jax.device_put(state)

        flops, hbm_bytes = _cost_analysis(
            update_step, params, opt_state, batch_d, state_d
        )

        for _ in range(warmup):
            params, opt_state, stats = update_step(
                params, opt_state, batch_d, state_d
            )
        float(stats["total_loss"])

        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, stats = update_step(
                params, opt_state, batch_d, state_d
            )
            if sync_each:
                float(stats["total_loss"])
        float(stats["total_loss"])
        elapsed = time.perf_counter() - t0
        return (T * B * steps / elapsed, 1000 * elapsed / steps, flops,
                hbm_bytes)

    def measure_plausible(dtype):
        """measure(), re-run with per-step sync if the implied TFLOP/s
        exceeds this chip's physical peak (i.e. the async timing lied)."""
        fps, ms, flops, hbm_bytes = measure(dtype)
        kind = device.device_kind.lower()
        peak = (
            _peak_for(kind, PEAK_BF16_TFLOPS)
            or max(PEAK_BF16_TFLOPS.values())
        )
        if dtype == jnp.float32:
            peak /= 2  # TPU f32 peak is ~half the bf16 figure
        if flops and flops / (ms / 1000) / 1e12 > peak:
            sys.stderr.write(
                f"bench: implausible {ms:.2f} ms/step (> {peak} TFLOP/s); "
                "re-measuring with per-step host sync\n"
            )
            fps, ms, flops, hbm_bytes = measure(dtype, sync_each=True)
        return fps, ms, flops, hbm_bytes

    frames_per_sec, step_ms, flops, hbm_bytes = measure_plausible(
        jnp.float32
    )
    # bf16 trunk variant: only worth the extra compile on an accelerator.
    bf16_frames_per_sec = bf16_step_ms = bf16_flops = bf16_hbm_bytes = None
    if on_accel:
        (bf16_frames_per_sec, bf16_step_ms, bf16_flops,
         bf16_hbm_bytes) = measure_plausible(jnp.bfloat16)

    # Per-dtype achieved TFLOP/s; MFU only for the bf16 run against the
    # chip's bf16 peak (comparing an f32 run to a bf16 peak would
    # understate utilization ~2x).
    def tflops(ms, fl):
        return fl / (ms / 1000) / 1e12 if ms and fl else None

    f32_tflops = tflops(step_ms, flops)
    bf16_tflops = tflops(bf16_step_ms, bf16_flops)
    mfu = None
    if bf16_tflops:
        peak = _peak_for(device.device_kind.lower(), PEAK_BF16_TFLOPS)
        if peak:
            mfu = bf16_tflops / peak

    # HBM roofline: the trunk's arithmetic intensity (~28 FLOP/byte) is
    # far under the chip's balance point, so bandwidth utilization is the
    # meaningful ceiling metric for this model — MFU cannot approach 1
    # no matter how good the program is.
    def hbm_gbps(ms, nbytes):
        return nbytes / (ms / 1000) / 1e9 if ms and nbytes else None

    f32_hbm_gbps = hbm_gbps(step_ms, hbm_bytes)
    bf16_hbm_gbps = hbm_gbps(bf16_step_ms, bf16_hbm_bytes)
    hbm_util = None
    if bf16_hbm_gbps:
        peak = _peak_for(device.device_kind.lower(), PEAK_HBM_GBPS)
        if peak:
            hbm_util = bf16_hbm_gbps / peak

    # Inference throughput at the largest bucket (the actor-side hot path).
    def measure_inference(batch_size=64, n=20):
        model, params, batch, _ = __graft_entry__._flagship(
            batch_size=batch_size, t=0
        )
        act_step = learner_lib.make_act_step(model)
        env_output = {
            k: jax.device_put(batch[k][0])
            for k in ("frame", "reward", "done", "last_action")
        }
        state = jax.device_put(model.initial_state(batch_size))
        key = jax.random.PRNGKey(0)
        out, state = act_step(params, key, env_output, state)  # compile
        np.asarray(out.action)
        t0 = time.perf_counter()
        for _ in range(n):
            out, state = act_step(params, key, env_output, state)
            # The act path's real contract is actions-on-host every call
            # (the DynamicBatcher replies to blocked actors), so the
            # per-call fetch IS the workload, not measurement overhead.
            np.asarray(out.action)
        return batch_size * n / (time.perf_counter() - t0)

    inference_sps = measure_inference(n=20 if on_accel else 3)

    # Anakin (fully-on-device Podracer, Catch): the purest chip-utilization
    # story — env, policy, and update all inside one XLA program.
    def measure_anakin(batch_size=256, unroll=16, n=20):
        from torchbeast_tpu.anakin import initial_carry, make_train_step
        from torchbeast_tpu.envs.jax_env import create_jax_env
        from torchbeast_tpu.models import create_model

        env = create_jax_env("Catch")
        hp = learner_lib.HParams(batch_size=batch_size, unroll_length=unroll)
        model = create_model(
            "mlp", num_actions=env.num_actions, use_lstm=False
        )
        optimizer = learner_lib.make_optimizer(hp)
        params, carry = initial_carry(
            env, model, batch_size, jax.random.PRNGKey(0)
        )
        opt_state = optimizer.init(params)
        train_step = make_train_step(env, model, optimizer, hp)
        params, opt_state, carry, stats = train_step(
            params, opt_state, carry
        )  # compile
        float(stats["total_loss"])
        t0 = time.perf_counter()
        for _ in range(n):
            params, opt_state, carry, stats = train_step(
                params, opt_state, carry
            )
        float(stats["total_loss"])  # host fetch: honest sync (see measure)
        return batch_size * unroll * n / (time.perf_counter() - t0)

    try:
        anakin_sps = measure_anakin(n=50 if on_accel else 10)
    except Exception as e:  # diagnostic field only — never sink the bench
        sys.stderr.write(f"bench: anakin measurement failed: {e}\n")
        anakin_sps = None

    baseline = None
    baseline_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BASELINE_measured.json"
    )
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baseline = json.load(f).get("torch_cpu_frames_per_sec")

    result = {
        "metric": (
            "IMPALA learner update throughput "
            f"(deep ResNet+LSTM, T={T}, B={B})"
        ),
        "value": round(frames_per_sec, 1),
        "unit": "frames/sec/chip",
        "vs_baseline": (
            round(frames_per_sec / baseline, 2) if baseline else None
        ),
        "platform": platform,
        "device_kind": device.device_kind,
        "step_ms": round(step_ms, 2),
        "bf16_value": (
            round(bf16_frames_per_sec, 1) if bf16_frames_per_sec else None
        ),
        "bf16_step_ms": round(bf16_step_ms, 2) if bf16_step_ms else None,
        "bf16_vs_baseline": (
            round(bf16_frames_per_sec / baseline, 2)
            if bf16_frames_per_sec and baseline
            else None
        ),
        "f32_achieved_tflops": round(f32_tflops, 2) if f32_tflops else None,
        "bf16_achieved_tflops": (
            round(bf16_tflops, 2) if bf16_tflops else None
        ),
        "mfu": round(mfu, 4) if mfu else None,
        "f32_hbm_gbps": round(f32_hbm_gbps, 1) if f32_hbm_gbps else None,
        "bf16_hbm_gbps": (
            round(bf16_hbm_gbps, 1) if bf16_hbm_gbps else None
        ),
        "hbm_roofline_util": round(hbm_util, 4) if hbm_util else None,
        "inference_steps_per_sec": round(inference_sps, 1),
        "anakin_sps": round(anakin_sps, 1) if anakin_sps else None,
    }
    if not on_accel:
        # A CPU fallback is close to worthless as a TPU benchmark — say
        # so, and point at the last recorded real-TPU measurement so the
        # reader doesn't mistake this line for the framework's ceiling.
        result["note"] = (
            "CPU FALLBACK (TPU tunnel unreachable through the full probe "
            "schedule); last recorded real-TPU numbers: "
            "benchmarks/artifacts/tpu_v5e_numbers.md"
        )
    print(json.dumps(result))


def main():
    if os.environ.get("_TB_BENCH_CHILD") != "1":
        # Watchdog: probe the ambient (TPU) backend with retries; fall back
        # to CPU only after the whole schedule fails.
        probe = _acquire_backend()
        if probe is None:
            os.environ["JAX_PLATFORMS"] = "cpu"
            sys.stderr.write(
                "bench: accelerator backend unreachable after "
                f"{len(PROBE_SCHEDULE)} probes; falling back to CPU\n"
            )
        else:
            sys.stderr.write(
                f"bench: backend ready: {probe[0]} ({probe[1]})\n"
            )
        os.environ["_TB_BENCH_CHILD"] = "1"
        os.execv(sys.executable, [sys.executable] + sys.argv)

    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    run_bench()


if __name__ == "__main__":
    main()
