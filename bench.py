"""Benchmark: learner-update throughput in env frames/sec/chip.

Measures the flagship IMPALA learner step (deep ResNet + LSTM, unroll T=80,
batch B=32 — the reference's beefy-machine unroll with its canonical
large-scale batch, BASELINE.md) as a single jitted XLA program with donated
state, on whatever accelerator the ambient JAX sees (the real TPU chip under
the driver).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "frames/sec/chip", "vs_baseline": N}

vs_baseline compares against the torch-CPU reference-equivalent learner step
measured by benchmarks/torch_baseline.py on this machine (stored in
BASELINE_measured.json). The reference repo publishes no numbers
(BASELINE.md), so the baseline is measured, not copied.

Robustness: backend init runs in a watchdog subprocess first; if the TPU
tunnel is unreachable the benchmark falls back to CPU and says so in the
"platform" field rather than hanging the driver.
"""

import json
import os
import subprocess
import sys
import time

T = 80
B = 32
STEPS = 10
WARMUP = 2


def _probe_backend(timeout_s: int = 120) -> bool:
    """Can the ambient backend produce devices? (subprocess watchdog)"""
    code = "import jax; jax.devices(); print('ok')"
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
        return out.returncode == 0 and "ok" in out.stdout
    except subprocess.TimeoutExpired:
        return False


def run_bench():
    import jax

    # Persistent compilation cache: repeat bench runs skip the multi-minute
    # XLA compile of the deep net.
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.expanduser("~/.cache/torchbeast_tpu_xla"),
    )

    from torchbeast_tpu import learner as learner_lib

    platform = jax.devices()[0].platform
    steps, warmup = (STEPS, WARMUP) if platform != "cpu" else (2, 1)

    # Same flagship construction the driver compile-checks (one source of
    # truth for the model/batch schema).
    import __graft_entry__

    def measure(dtype):
        model, params, batch, state = __graft_entry__._flagship(
            batch_size=B, t=T, dtype=dtype
        )
        hp = learner_lib.HParams(batch_size=B, unroll_length=T)
        optimizer = learner_lib.make_optimizer(hp)
        opt_state = optimizer.init(params)
        update_step = learner_lib.make_update_step(model, optimizer, hp)

        batch_d = jax.device_put(batch)
        state_d = jax.device_put(state)

        for _ in range(warmup):
            params, opt_state, stats = update_step(
                params, opt_state, batch_d, state_d
            )
        jax.block_until_ready(stats["total_loss"])

        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, stats = update_step(
                params, opt_state, batch_d, state_d
            )
        jax.block_until_ready(stats["total_loss"])
        elapsed = time.perf_counter() - t0
        return T * B * steps / elapsed, 1000 * elapsed / steps

    import jax.numpy as jnp

    frames_per_sec, step_ms = measure(jnp.float32)
    # bf16 trunk variant: only worth the extra compile on an accelerator.
    bf16_frames_per_sec = None
    if platform != "cpu":
        bf16_frames_per_sec, _ = measure(jnp.bfloat16)

    # Inference throughput at the largest bucket (the actor-side hot path).
    def measure_inference(batch_size=64, n=20):
        model, params, batch, _ = __graft_entry__._flagship(
            batch_size=batch_size, t=0
        )
        act_step = learner_lib.make_act_step(model)
        env_output = {
            k: jax.device_put(batch[k][0])
            for k in ("frame", "reward", "done", "last_action")
        }
        state = jax.device_put(model.initial_state(batch_size))
        key = jax.random.PRNGKey(0)
        out, state = act_step(params, key, env_output, state)  # compile
        jax.block_until_ready(out.action)
        t0 = time.perf_counter()
        for i in range(n):
            out, state = act_step(params, key, env_output, state)
        jax.block_until_ready(out.action)
        return batch_size * n / (time.perf_counter() - t0)

    inference_sps = measure_inference(n=20 if platform != "cpu" else 3)

    baseline = None
    baseline_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BASELINE_measured.json"
    )
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baseline = json.load(f).get("torch_cpu_frames_per_sec")

    result = {
        "metric": (
            "IMPALA learner update throughput "
            f"(deep ResNet+LSTM, T={T}, B={B})"
        ),
        "value": round(frames_per_sec, 1),
        "unit": "frames/sec/chip",
        "vs_baseline": (
            round(frames_per_sec / baseline, 2) if baseline else None
        ),
        "platform": platform,
        "step_ms": round(step_ms, 2),
        "bf16_value": (
            round(bf16_frames_per_sec, 1) if bf16_frames_per_sec else None
        ),
        "inference_steps_per_sec": round(inference_sps, 1),
    }
    print(json.dumps(result))


def main():
    if os.environ.get("_TB_BENCH_CHILD") != "1":
        # Watchdog: if the ambient (TPU) backend hangs, retry on CPU.
        if not _probe_backend():
            os.environ["JAX_PLATFORMS"] = "cpu"
            sys.stderr.write(
                "bench: accelerator backend unreachable; falling back to "
                "CPU\n"
            )
        os.environ["_TB_BENCH_CHILD"] = "1"
        os.execv(sys.executable, [sys.executable] + sys.argv)

    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    run_bench()


if __name__ == "__main__":
    main()
