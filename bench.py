"""Benchmark: learner-update throughput in env frames/sec/chip.

Measures the flagship IMPALA learner step (deep ResNet + LSTM, unroll T=80,
batch B=32 — the reference's beefy-machine unroll with its canonical
large-scale batch, BASELINE.md) as a single jitted XLA program with donated
state, on whatever accelerator the ambient JAX sees (the real TPU chip under
the driver).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "frames/sec/chip", "vs_baseline": N}
where `value`/`vs_baseline` are the f32 learner step (apples-to-apples with
the f32 torch baseline), plus diagnostic fields: platform/device, step_ms,
bf16_value + bf16_vs_baseline (accelerator only — the mixed-precision
number, reported separately precisely because it is NOT numerics-identical
to the baseline), per-dtype achieved TFLOP/s from XLA's own cost analysis,
mfu (bf16 achieved vs the chip's bf16 peak), HBM roofline fields
(f32/bf16_hbm_gbps, hbm_roofline_util — the meaningful ceiling metric for
this bandwidth-bound model), inference_steps_per_sec (largest act bucket),
and anakin_sps (the fully-on-device Podracer trainer on Catch).

vs_baseline compares against the torch-CPU reference-equivalent learner step
measured by benchmarks/torch_baseline.py on this machine (stored in
BASELINE_measured.json). The reference repo publishes no numbers
(BASELINE.md), so the baseline is measured, not copied.

Robustness contract (the invariant, learned the hard way in round 2 when a
wedged tunnel produced rc=124 and an empty record): **a JSON line is emitted
before the driver's deadline, every time.** The supervisor process owns a
hard total budget (BENCH_BUDGET_S, default 780 s); probing the flaky TPU
tunnel is best-effort within it (max ~5 min), the measurement itself runs in
a child with a timeout, and if anything fails or overruns, the supervisor
replays the last committed real-TPU result
(benchmarks/artifacts/last_tpu_bench.json) with provenance instead of
hanging or printing nothing. A successful accelerator run refreshes that
artifact, so the fallback always carries the newest chip numbers. Every
line carries machine-readable staleness fields — `fresh` (was this
measured by THIS run) and `measured_age_days` (age of the numbers) — so
a replay can never be mistaken for a measurement without parsing prose;
and a child that *crashes* while the tunnel is up emits a value-null
error record rather than replaying (a crash is a code regression the
caller must see, not a wedge to paper over). The XLA
compile cache is keyed per host CPU so an AOT result built on one machine is
never loaded on another (SIGILL risk).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

T = 80
B = 32
STEPS = 10
WARMUP = 2

# Total wall-clock budget for the whole bench (supervisor-enforced). The
# driver's capture timeout is ~20-30 min; staying well inside it is the
# whole point.
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "780"))

# Reserved at the end of the budget for the replay fallback print.
RESERVE_S = 45.0

# Probe schedule: (timeout_s, sleep_after_failure_s). Worst case 315 s.
# Probing longer is NOT worth it: an empty record (rc=124) is strictly
# worse than a replayed last-known-TPU line with provenance.
PROBE_SCHEDULE = ((60, 15), (90, 30), (120, 0))

# A connection-failure substring only counts as "tunnel dropped" when
# it is attributable to the device transport via one of these markers
# (lowercased match). Generic EOFError/Broken-pipe lines from the
# repo's own IPC must not trigger a replay.
_TRANSPORT_MARKERS = (
    "jaxlib", "jax.errors", "xlaruntimeerror", "pjrt", "axon",
    "grpc", "xla_bridge", "libtpu",
)

_CONNECTION_SIGNATURES = (
    "ConnectionRefused", "ConnectionReset", "Connection reset",
    "Connection refused", "UNAVAILABLE", "DEADLINE_EXCEEDED",
    "Socket closed", "Broken pipe", "EOFError",
)


def _is_transport_connection_error(stderr: str) -> bool:
    """True when a connection-failure signature in `stderr` is
    attributable to the device transport.

    Attribution accepts a marker on the signature line itself (the
    single-line `jax.errors.JaxRuntimeError: UNAVAILABLE: ...` form) OR
    on a line of the enclosing traceback block (a drop surfacing as a
    bare `ConnectionResetError:` whose `File ".../axon/..."` frames
    carry the marker). Markers elsewhere in stderr do NOT count —
    routine jaxlib/xla_bridge warnings appear in every run's stderr and
    must not turn the repo's own IPC EOFErrors into a replay.
    """
    block = None  # lines of the currently-open traceback block
    for line in stderr.splitlines():
        # glog FATAL lines ("F0730 12:34:56... ] Socket closed") kill
        # the process from inside the C++ transport — no Python
        # traceback exists, so the F-line itself attributes. E-level
        # glog lines deliberately do NOT: grpc/TSL log benign
        # "recvmsg: Connection reset by peer" noise during ordinary
        # channel teardown, and attributing those would let any code
        # crash whose shutdown emits one replay stale chip numbers.
        if (
            len(line) > 5 and line[0] == "F" and line[1:5].isdigit()
            and any(sig in line for sig in _CONNECTION_SIGNATURES)
        ):
            return True
        if line.startswith("Traceback (most recent call last):"):
            block = [line]
            continue
        if block is not None:
            block.append(line)
        if any(sig in line for sig in _CONNECTION_SIGNATURES):
            # Attribution scope: the enclosing traceback block when one
            # is open, else the signature line alone — NEVER arbitrary
            # preceding stderr (routine warning lines carry markers).
            scope = block if block is not None else [line]
            if any(
                m in bl.lower()
                for bl in scope
                for m in _TRANSPORT_MARKERS
            ):
                return True
        if block is not None and not line.startswith((" ", "\t")):
            # A non-indented line is the exception line that terminates
            # the traceback (chained tracebacks reopen with their own
            # header); markers from this block must not leak onto later
            # unrelated signatures.
            block = None
    return False

_REPO = os.path.dirname(os.path.abspath(__file__))
LAST_TPU_PATH = os.path.join(
    _REPO, "benchmarks", "artifacts", "last_tpu_bench.json"
)

# Peak bf16 TFLOP/s per chip by device kind (public figures). MFU is
# best-effort: unknown kinds report achieved TFLOP/s with mfu=null.
PEAK_BF16_TFLOPS = {
    "v2": 45.0,
    "v3": 123.0,
    "v4": 275.0,
    "v5 lite": 197.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0,
    "v6e": 918.0,
}

# Peak HBM bandwidth GB/s per chip (public figures). The IMPALA trunk's
# 16/32-channel convs are ~28 FLOP/byte — far below the ~240 FLOP/byte a
# v5e needs to saturate the MXU from HBM — so the step is bandwidth-bound
# and HBM roofline utilization, not MFU, is the number that says whether
# the program is near the hardware ceiling.
PEAK_HBM_GBPS = {
    "v2": 700.0,
    "v3": 900.0,
    "v4": 1228.0,
    "v5 lite": 819.0,
    "v5e": 819.0,
    "v5p": 2765.0,
    "v6 lite": 1640.0,
    "v6e": 1640.0,
}


def _probe_backend(timeout_s: float):
    """Ask a watchdog subprocess what the ambient backend is.

    Returns (platform, device_kind) or None if init hung/failed.
    """
    code = (
        "import jax; d = jax.devices()[0]; "
        "print('PROBE', d.platform, '|', d.device_kind)"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return None
    for line in out.stdout.splitlines():
        if line.startswith("PROBE "):
            rest = line[len("PROBE "):]
            plat, _, kind = rest.partition(" | ")
            return plat.strip(), kind.strip()
    return None


def _base_result(**extra):
    """The metric-line skeleton every emit site shares (final result,
    preliminary child line, replay fallback, forced-CPU failure) — one
    definition so the schema cannot drift between them.

    `fresh` / `measured_age_days` are first-class staleness fields: a
    dashboard must not need to parse `platform`/`note` prose to tell a
    replayed line from a measurement. Defaults are the conservative
    not-a-fresh-measurement values; live emit sites pass
    `**_live_fields()` to override.
    """
    result = {
        "metric": (
            "IMPALA learner update throughput "
            f"(deep ResNet+LSTM, T={T}, B={B})"
        ),
        "value": None,
        "unit": "frames/sec/chip",
        "vs_baseline": None,
        "fresh": False,
        "measured_age_days": None,
    }
    result.update(extra)
    return result


def _live_fields():
    """Staleness fields for a measurement made in THIS process, now."""
    return {"fresh": True, "measured_age_days": 0}


def _strip_staleness(result: dict) -> dict:
    """The persisted last_tpu artifact must not assert fresh:true on
    numbers that age in git — its measured_at stamp is the only truth,
    and every consumer (replay included) derives staleness from that."""
    return {
        k: v
        for k, v in result.items()
        if k not in ("fresh", "measured_age_days")
    }


def _age_days(measured_at: str):
    """Days since a `%Y-%m-%d[ %H:%M:%S]` stamp; None if unparseable."""
    for fmt in ("%Y-%m-%d %H:%M:%S", "%Y-%m-%d"):
        try:
            t = time.mktime(time.strptime(measured_at, fmt))
        except (ValueError, TypeError):
            continue
        return max(0.0, round((time.time() - t) / 86400, 1))
    return None


def _load_last_tpu():
    try:
        with open(LAST_TPU_PATH) as f:
            return json.load(f)
    except Exception:
        return None


def _replay_fallback(reason: str) -> None:
    """Emit the one JSON line from the last committed real-TPU result.

    This is the terminal fallback: it never probes, never imports jax,
    and cannot block. `value`/`vs_baseline` carry the chip's last known
    numbers (with provenance) rather than nothing at all.
    """
    data = _load_last_tpu()
    if data and isinstance(data.get("result"), dict):
        result = dict(data["result"])
        result["platform"] = "tpu(replayed)"
        # Machine-readable staleness: the stored result carries the
        # fresh=True stamped when it was measured; a replay is, by
        # definition, not fresh, and its age is however old the
        # artifact's measurement stamp is.
        result["fresh"] = False
        result["measured_age_days"] = _age_days(
            data.get("measured_at", "")
        )
        result["note"] = (
            f"REPLAYED from benchmarks/artifacts/last_tpu_bench.json "
            f"(measured {data.get('measured_at', 'unknown date')}): "
            f"{reason}. No fresh accelerator measurement was possible "
            "inside this run's budget; these are the last recorded "
            "real-TPU numbers from this same bench."
        )
    else:
        result = _base_result(
            platform="none",
            note=f"{reason}; no last_tpu artifact available to replay",
        )
    print(json.dumps(result))
    sys.stdout.flush()


def _cache_dir() -> str:
    """Per-host-CPU compile cache (shared helper; a cache shared across
    hosts can load foreign AOT results and SIGILL)."""
    from torchbeast_tpu.utils.xla_cache import host_keyed_cache_dir

    return host_keyed_cache_dir()


def _peak_for(kind: str, table):
    """Chip-kind -> peak figure by substring match; None if unknown."""
    return next((p for name, p in table.items() if name in kind), None)


def _cost_analysis(jitted, *args):
    """(flops, bytes_accessed) per call from XLA's own cost analysis of
    the optimized HLO (best-effort; bytes are a post-fusion proxy for
    HBM traffic)."""
    try:
        analysis = jitted.lower(*args).compile().cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0]
        flops = float(analysis.get("flops", 0.0))
        nbytes = float(analysis.get("bytes accessed", 0.0))
        return (flops if flops > 0 else None,
                nbytes if nbytes > 0 else None)
    except Exception:
        return None, None


def run_bench(child_deadline: float):
    """The measurement child. `child_deadline` is a time.monotonic()
    instant; optional phases (bf16/inference/anakin) are skipped when the
    remaining budget can't cover them, so the mandatory f32 line always
    lands. The supervisor's subprocess timeout is the backstop."""

    def remaining() -> float:
        return child_deadline - time.monotonic()

    import jax

    # Persistent compilation cache: repeat bench runs skip the multi-minute
    # XLA compile of the deep net.
    jax.config.update("jax_compilation_cache_dir", _cache_dir())

    from torchbeast_tpu import learner as learner_lib

    device = jax.devices()[0]
    platform = device.platform
    on_accel = platform != "cpu"
    steps, warmup = (STEPS, WARMUP) if on_accel else (3, 1)

    baseline = None
    baseline_path = os.path.join(_REPO, "BASELINE_measured.json")
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baseline = json.load(f).get("torch_cpu_frames_per_sec")

    # Same flagship construction the driver compile-checks (one source of
    # truth for the model/batch schema).
    import __graft_entry__
    import jax.numpy as jnp

    # Timing sync: fetch the final loss to HOST (device_get) rather than
    # block_until_ready — on the remote-TPU tunnel backend the latter has
    # been observed returning before compute finishes (a run "measured"
    # 0.79 ms for a 72 ms step); a host fetch of a scalar that data-depends
    # on the whole chained loop cannot lie. A per-step fetch would add a
    # full tunnel round-trip (~50 ms) to every step, so fetch once at the
    # end — unless the plausibility guard below trips, in which case
    # re-measure with the per-step fetch and report that (conservative)
    # number.
    def measure(dtype, sync_each=False):
        model, params, batch, state = __graft_entry__._flagship(
            batch_size=B, t=T, dtype=dtype
        )
        hp = learner_lib.HParams(batch_size=B, unroll_length=T)
        optimizer = learner_lib.make_optimizer(hp)
        opt_state = optimizer.init(params)
        update_step = learner_lib.make_update_step(model, optimizer, hp)

        batch_d = jax.device_put(batch)
        state_d = jax.device_put(state)

        flops, hbm_bytes = _cost_analysis(
            update_step, params, opt_state, batch_d, state_d
        )

        for _ in range(warmup):
            params, opt_state, stats = update_step(
                params, opt_state, batch_d, state_d
            )
        float(stats["total_loss"])

        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, stats = update_step(
                params, opt_state, batch_d, state_d
            )
            if sync_each:
                float(stats["total_loss"])
        float(stats["total_loss"])
        elapsed = time.perf_counter() - t0
        return (T * B * steps / elapsed, 1000 * elapsed / steps, flops,
                hbm_bytes)

    def measure_plausible(dtype):
        """measure(), re-run with per-step sync if the implied TFLOP/s
        exceeds this chip's physical peak (i.e. the async timing lied)."""
        fps, ms, flops, hbm_bytes = measure(dtype)
        kind = device.device_kind.lower()
        peak = (
            _peak_for(kind, PEAK_BF16_TFLOPS)
            or max(PEAK_BF16_TFLOPS.values())
        )
        if dtype == jnp.float32:
            peak /= 2  # TPU f32 peak is ~half the bf16 figure
        if flops and flops / (ms / 1000) / 1e12 > peak:
            sys.stderr.write(
                f"bench: implausible {ms:.2f} ms/step (> {peak} TFLOP/s); "
                "re-measuring with per-step host sync\n"
            )
            fps, ms, flops, hbm_bytes = measure(dtype, sync_each=True)
        return fps, ms, flops, hbm_bytes

    frames_per_sec, step_ms, flops, hbm_bytes = measure_plausible(
        jnp.float32
    )
    # The headline number is now in hand: emit a preliminary JSON line
    # immediately so a tunnel wedge during any LATER phase can't discard
    # it (the supervisor keeps the LAST matching line, and scans partial
    # stdout on child timeout).
    print(json.dumps(_base_result(
        value=round(frames_per_sec, 1),
        vs_baseline=(
            round(frames_per_sec / baseline, 2) if baseline else None
        ),
        platform=platform,
        device_kind=device.device_kind,
        step_ms=round(step_ms, 2),
        note="preliminary (f32 only; later phases pending)",
        **_live_fields(),
    )))
    sys.stdout.flush()
    # bf16 trunk variant: only worth the extra compile on an accelerator,
    # and only if the budget still covers roughly another measurement
    # round (compile is cached; steps dominate).
    bf16_frames_per_sec = bf16_step_ms = bf16_flops = bf16_hbm_bytes = None
    if on_accel and remaining() > 150:
        (bf16_frames_per_sec, bf16_step_ms, bf16_flops,
         bf16_hbm_bytes) = measure_plausible(jnp.bfloat16)
    elif on_accel:
        sys.stderr.write(
            f"bench: skipping bf16 phase ({remaining():.0f}s left)\n"
        )

    # Per-dtype achieved TFLOP/s; MFU only for the bf16 run against the
    # chip's bf16 peak (comparing an f32 run to a bf16 peak would
    # understate utilization ~2x).
    def tflops(ms, fl):
        return fl / (ms / 1000) / 1e12 if ms and fl else None

    f32_tflops = tflops(step_ms, flops)
    bf16_tflops = tflops(bf16_step_ms, bf16_flops)
    mfu = None
    if bf16_tflops:
        peak = _peak_for(device.device_kind.lower(), PEAK_BF16_TFLOPS)
        if peak:
            mfu = bf16_tflops / peak

    # HBM roofline: the trunk's arithmetic intensity (~28 FLOP/byte) is
    # far under the chip's balance point, so bandwidth utilization is the
    # meaningful ceiling metric for this model — MFU cannot approach 1
    # no matter how good the program is.
    def hbm_gbps(ms, nbytes):
        return nbytes / (ms / 1000) / 1e9 if ms and nbytes else None

    f32_hbm_gbps = hbm_gbps(step_ms, hbm_bytes)
    bf16_hbm_gbps = hbm_gbps(bf16_step_ms, bf16_hbm_bytes)
    hbm_util = None
    if bf16_hbm_gbps:
        peak = _peak_for(device.device_kind.lower(), PEAK_HBM_GBPS)
        if peak:
            hbm_util = bf16_hbm_gbps / peak

    # Inference throughput at the largest bucket (the actor-side hot path).
    def measure_inference(batch_size=64, n=20):
        model, params, batch, _ = __graft_entry__._flagship(
            batch_size=batch_size, t=0
        )
        act_step = learner_lib.make_act_step(model)
        env_output = {
            k: jax.device_put(batch[k][0])
            for k in ("frame", "reward", "done", "last_action")
        }
        state = jax.device_put(model.initial_state(batch_size))
        key = jax.random.PRNGKey(0)
        out, state = act_step(params, key, env_output, state)  # compile
        np.asarray(out.action)
        t0 = time.perf_counter()
        for _ in range(n):
            out, state = act_step(params, key, env_output, state)
            # The act path's real contract is actions-on-host every call
            # (the DynamicBatcher replies to blocked actors), so the
            # per-call fetch IS the workload, not measurement overhead.
            np.asarray(out.action)
        return batch_size * n / (time.perf_counter() - t0)

    inference_sps = None
    if remaining() > 60:
        try:
            inference_sps = measure_inference(n=20 if on_accel else 3)
        except Exception as e:  # diagnostic only — never sink the bench
            sys.stderr.write(f"bench: inference measurement failed: {e}\n")
    else:
        sys.stderr.write(
            f"bench: skipping inference phase ({remaining():.0f}s left)\n"
        )

    # Anakin (fully-on-device Podracer, Catch): the purest chip-utilization
    # story — env, policy, and update all inside one XLA program.
    def measure_anakin(batch_size=256, unroll=16, n=20):
        from torchbeast_tpu.anakin import initial_carry, make_train_step
        from torchbeast_tpu.envs.jax_env import create_jax_env
        from torchbeast_tpu.models import create_model

        env = create_jax_env("Catch")
        hp = learner_lib.HParams(batch_size=batch_size, unroll_length=unroll)
        model = create_model(
            "mlp", num_actions=env.num_actions, use_lstm=False
        )
        optimizer = learner_lib.make_optimizer(hp)
        params, carry = initial_carry(
            env, model, batch_size, jax.random.PRNGKey(0)
        )
        opt_state = optimizer.init(params)
        train_step = make_train_step(env, model, optimizer, hp)
        params, opt_state, carry, stats = train_step(
            params, opt_state, carry
        )  # compile
        float(stats["total_loss"])
        t0 = time.perf_counter()
        for _ in range(n):
            params, opt_state, carry, stats = train_step(
                params, opt_state, carry
            )
        float(stats["total_loss"])  # host fetch: honest sync (see measure)
        return batch_size * unroll * n / (time.perf_counter() - t0)

    anakin_sps = None
    if remaining() > 60:
        try:
            anakin_sps = measure_anakin(n=50 if on_accel else 10)
        except Exception as e:  # diagnostic only — never sink the bench
            sys.stderr.write(f"bench: anakin measurement failed: {e}\n")
    else:
        sys.stderr.write(
            f"bench: skipping anakin phase ({remaining():.0f}s left)\n"
        )

    # benchmarks/learner_bench.py is loaded by path (the benchmarks dir
    # is not a package) and memoized: three measurement phases below
    # share ONE module execution.
    _lb_cache = []

    def _load_learner_bench():
        if not _lb_cache:
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "learner_bench",
                os.path.join(_REPO, "benchmarks", "learner_bench.py"),
            )
            lb = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(lb)
            _lb_cache.append(lb)
        return _lb_cache[0]

    # Learner superstep throughput (ISSUE 4): the small-MLP K=8 fused
    # dispatch — the dispatch-amortization metric the superstep work
    # moves. ONE measurement implementation, shared with the committed
    # artifact.
    def measure_learner_superstep(k=8, n_updates=32):
        lb = _load_learner_bench()
        hp, model, optimizer, params, lrng = lb.build_config(
            use_lstm=False
        )
        row = lb.measure_updates_per_sec(
            hp, model, optimizer, params, lrng, k, n_updates
        )
        return row["updates_per_sec"]

    learner_updates_sps = None
    if remaining() > 45:
        try:
            learner_updates_sps = measure_learner_superstep(
                n_updates=32 if on_accel else 16
            )
        except Exception as e:  # diagnostic only — never sink the bench
            sys.stderr.write(
                f"bench: learner superstep measurement failed: {e}\n"
            )
    else:
        sys.stderr.write(
            f"bench: skipping learner superstep phase "
            f"({remaining():.0f}s left)\n"
        )

    # Learner bytes-moved accounting (ISSUE 8): XLA-reported bytes
    # accessed per update, f32 vs --precision bf16_train, from the
    # dtype-faithful lowered HLO (lowering-only — no compile, cheap on
    # any host; methodology in benchmarks/learner_bench.py). ONE
    # measurement implementation, shared with the committed artifact.
    def measure_learner_bytes():
        lb = _load_learner_bench()
        rows, _ = lb.measure_bytes(
            "mlp", ks=[1], t=lb.BYTES_T, b=lb.BYTES_B
        )
        by_prec = {
            r["precision"]: r["bytes_accessed"]
            for r in rows
            if r["k"] == 1 and r["bytes_accessed"]
        }
        f32_b = by_prec.get("f32")
        bf16_b = by_prec.get("bf16_train")
        reduction = f32_b / bf16_b if f32_b and bf16_b else None
        return f32_b, bf16_b, reduction

    hbm_f32 = hbm_bf16 = hbm_reduction = None
    if remaining() > 30:
        try:
            hbm_f32, hbm_bf16, hbm_reduction = measure_learner_bytes()
        except Exception as e:  # diagnostic only — never sink the bench
            sys.stderr.write(
                f"bench: learner bytes measurement failed: {e}\n"
            )
    else:
        sys.stderr.write(
            f"bench: skipping learner bytes phase "
            f"({remaining():.0f}s left)\n"
        )

    # Fused optimizer tail (ISSUE 13): xla-vs-pallas full-update bytes
    # on the flagship LSTM under bf16_train (the shape whose tail is
    # large enough to carry the 1.15x acceptance), same lowered-HLO
    # accounting and same _prev/_delta convention as the hbm keys. ONE
    # measurement implementation, shared with the committed artifact.
    def measure_opt_tail_reduction():
        lb = _load_learner_bench()
        rows = lb.measure_opt_tail("lstm", lb.BYTES_T, lb.BYTES_B)
        by_impl = {
            r["opt_impl"]: r["bytes_accessed"]
            for r in rows
            if r["precision"] == "bf16_train" and r["bytes_accessed"]
        }
        x, p = by_impl.get("xla"), by_impl.get("pallas")
        return x / p if x and p else None

    opt_tail_reduction = None
    if remaining() > 30:
        try:
            opt_tail_reduction = measure_opt_tail_reduction()
        except Exception as e:  # diagnostic only — never sink the bench
            sys.stderr.write(
                f"bench: opt-tail bytes measurement failed: {e}\n"
            )
    else:
        sys.stderr.write(
            f"bench: skipping opt-tail bytes phase "
            f"({remaining():.0f}s left)\n"
        )

    result = _base_result(**_live_fields())
    result.update({
        "value": round(frames_per_sec, 1),
        "vs_baseline": (
            round(frames_per_sec / baseline, 2) if baseline else None
        ),
        "platform": platform,
        "device_kind": device.device_kind,
        "step_ms": round(step_ms, 2),
        "bf16_value": (
            round(bf16_frames_per_sec, 1) if bf16_frames_per_sec else None
        ),
        "bf16_step_ms": round(bf16_step_ms, 2) if bf16_step_ms else None,
        "bf16_vs_baseline": (
            round(bf16_frames_per_sec / baseline, 2)
            if bf16_frames_per_sec and baseline
            else None
        ),
        "f32_achieved_tflops": round(f32_tflops, 2) if f32_tflops else None,
        "bf16_achieved_tflops": (
            round(bf16_tflops, 2) if bf16_tflops else None
        ),
        "mfu": round(mfu, 4) if mfu else None,
        "f32_hbm_gbps": round(f32_hbm_gbps, 1) if f32_hbm_gbps else None,
        "bf16_hbm_gbps": (
            round(bf16_hbm_gbps, 1) if bf16_hbm_gbps else None
        ),
        "hbm_roofline_util": round(hbm_util, 4) if hbm_util else None,
        "inference_steps_per_sec": (
            round(inference_sps, 1) if inference_sps else None
        ),
        "anakin_sps": round(anakin_sps, 1) if anakin_sps else None,
    })
    # Acting-path regression visibility: the delta vs the last committed
    # chip artifact's inference number (the metric the device-resident
    # state table / lag-1 dispatch work moves). Cross-platform deltas
    # are meaningless, so a CPU run reports the previous chip number
    # with delta null.
    last = _load_last_tpu()
    prev_result = last.get("result") if last else None
    prev_inference = (
        prev_result.get("inference_steps_per_sec")
        if isinstance(prev_result, dict)
        else None
    )
    result["inference_steps_per_sec_prev"] = prev_inference
    result["inference_steps_per_sec_delta_pct"] = (
        round(100.0 * (inference_sps - prev_inference) / prev_inference, 1)
        if inference_sps and prev_inference and on_accel
        else None
    )
    # Learner superstep regression visibility (ISSUE 4), mirroring the
    # inference convention: delta vs the committed learner_bench
    # artifact's small-MLP K=8 number — but only when the platforms
    # match (the committed artifact records where it was measured;
    # CPU-vs-TPU deltas are meaningless).
    result["learner_updates_per_sec"] = (
        round(learner_updates_sps, 1) if learner_updates_sps else None
    )
    prev_learner = prev_learner_platform = None
    try:
        with open(os.path.join(
            _REPO, "benchmarks", "artifacts", "learner_bench.json"
        )) as f:
            lb_art = json.load(f)
        prev_learner = lb_art.get("acceptance", {}).get(
            "mlp_updates_per_sec_ktop"
        )
        prev_learner_platform = lb_art.get("platform")
    except Exception:
        pass
    result["learner_updates_per_sec_prev"] = (
        round(prev_learner, 1) if prev_learner else None
    )
    result["learner_updates_per_sec_delta_pct"] = (
        round(
            100.0 * (learner_updates_sps - prev_learner) / prev_learner,
            1,
        )
        if learner_updates_sps and prev_learner
        and prev_learner_platform == platform
        else None
    )
    # Bytes-moved regression visibility (ISSUE 8), same _prev/_delta
    # convention against the committed learner_bench artifact's
    # small-MLP K=1 reduction. The lowered-HLO figure is platform-
    # neutral (no platform match required): a delta here means the
    # learner's byte diet itself changed, not the machine.
    result["learner_hbm_bytes_per_update"] = hbm_f32
    result["learner_hbm_bytes_per_update_bf16"] = hbm_bf16
    result["learner_hbm_bytes_reduction"] = (
        round(hbm_reduction, 3) if hbm_reduction else None
    )
    prev_hbm = None
    try:
        prev_hbm = lb_art.get("acceptance", {}).get("bytes", {}).get(
            "mlp_update_reduction_k1"
        )
    except Exception:
        pass
    result["learner_hbm_bytes_reduction_prev"] = (
        round(prev_hbm, 3) if prev_hbm else None
    )
    result["learner_hbm_bytes_reduction_delta_pct"] = (
        round(100.0 * (hbm_reduction - prev_hbm) / prev_hbm, 1)
        if hbm_reduction and prev_hbm
        else None
    )
    # Fused-tail regression visibility (ISSUE 13), platform-neutral
    # like the hbm reduction: flagship-LSTM bf16_train xla/pallas
    # full-update bytes vs the committed learner_bench artifact.
    result["learner_opt_tail_bytes_reduction"] = (
        round(opt_tail_reduction, 3) if opt_tail_reduction else None
    )
    prev_tail = None
    try:
        prev_tail = lb_art.get("acceptance", {}).get(
            "opt_tail", {}
        ).get("lstm_update_reduction_bf16")
    except Exception:
        pass
    result["learner_opt_tail_bytes_reduction_prev"] = (
        round(prev_tail, 3) if prev_tail else None
    )
    result["learner_opt_tail_bytes_reduction_delta_pct"] = (
        round(
            100.0 * (opt_tail_reduction - prev_tail) / prev_tail, 1
        )
        if opt_tail_reduction and prev_tail
        else None
    )
    if not on_accel:
        # A CPU fallback is close to worthless as a TPU benchmark — say
        # so, and point at the last recorded real-TPU measurement so the
        # reader doesn't mistake this line for the framework's ceiling.
        result["note"] = (
            "CPU run; last recorded real-TPU numbers: "
            "benchmarks/artifacts/last_tpu_bench.json"
        )
    elif all(
        result[k] is not None
        for k in ("bf16_value", "inference_steps_per_sec", "anakin_sps")
    ):
        # Refresh the committed fallback artifact so future wedged-tunnel
        # rounds replay THESE numbers rather than older ones. Only a
        # COMPLETE run refreshes: a budget-truncated run (skipped
        # bf16/inference/anakin) must not overwrite recorded numbers
        # with nulls that every later replay would then serve.
        stored = _strip_staleness(result)
        try:
            with open(LAST_TPU_PATH, "w") as f:
                json.dump(
                    {
                        "measured_at": time.strftime("%Y-%m-%d %H:%M:%S"),
                        "source": (
                            "bench.py fresh accelerator run "
                            "(auto-refreshed on success)"
                        ),
                        "result": stored,
                    },
                    f,
                    indent=2,
                )
                f.write("\n")
        except Exception as e:
            sys.stderr.write(f"bench: could not refresh last_tpu: {e}\n")
    print(json.dumps(result))
    sys.stdout.flush()


def main():
    if os.environ.get("_TB_BENCH_CHILD") == "1":
        if os.environ.get("JAX_PLATFORMS"):
            import jax

            jax.config.update(
                "jax_platforms", os.environ["JAX_PLATFORMS"]
            )
        budget = float(os.environ.get("BENCH_CHILD_BUDGET_S", "600"))
        run_bench(time.monotonic() + budget)
        return

    # --- Supervisor: owns the hard deadline; always prints a JSON line ---
    t0 = time.monotonic()
    deadline = t0 + BUDGET_S
    child_env = dict(os.environ)
    force_cpu = os.environ.get("BENCH_FORCE_CPU") == "1"

    def fail(reason: str) -> None:
        """Terminal failure: replay the last TPU record — except under
        BENCH_FORCE_CPU, where serving TPU numbers for an explicitly
        CPU-only run would mislead the caller."""
        if force_cpu:
            print(json.dumps(_base_result(
                platform="cpu",
                note=f"BENCH_FORCE_CPU run failed: {reason}",
            )))
            sys.stdout.flush()
        else:
            _replay_fallback(reason)

    def last_metric_line(text) -> str:
        if not text:
            return None
        if isinstance(text, bytes):
            text = text.decode(errors="replace")
        return next(
            (
                ln
                for ln in reversed(text.splitlines())
                if ln.startswith('{"metric"')
            ),
            None,
        )

    if force_cpu:
        child_env["JAX_PLATFORMS"] = "cpu"
        sys.stderr.write("bench: BENCH_FORCE_CPU=1, skipping probe\n")
    else:
        probe = None
        for i, (timeout_s, sleep_s) in enumerate(PROBE_SCHEDULE):
            # Never let probing overrun the supervisor deadline: cap each
            # probe at what's left after the fallback reserve, and stop
            # probing entirely once that is exhausted.
            probe_budget = deadline - time.monotonic() - RESERVE_S
            if probe_budget < 10:
                break
            probe = _probe_backend(min(timeout_s, probe_budget))
            if probe is not None:
                sys.stderr.write(
                    f"bench: backend ready: {probe[0]} ({probe[1]})\n"
                )
                break
            sys.stderr.write(
                f"bench: backend probe {i + 1}/{len(PROBE_SCHEDULE)} timed "
                f"out after {timeout_s}s\n"
            )
            if sleep_s:
                time.sleep(
                    min(sleep_s, max(0, deadline - time.monotonic()))
                )
        if probe is None:
            fail(
                "TPU tunnel unreachable through the probe schedule "
                f"(max ~{sum(t + s for t, s in PROBE_SCHEDULE)}s, "
                "deadline-capped)"
            )
            return

    child_budget = deadline - time.monotonic() - RESERVE_S
    if child_budget < 60:
        fail(
            "no budget left for the measurement child "
            f"(BENCH_BUDGET_S={BUDGET_S:.0f}s minus probing/reserve)"
        )
        return

    child_env["_TB_BENCH_CHILD"] = "1"
    child_env["BENCH_CHILD_BUDGET_S"] = str(int(child_budget))
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            timeout=child_budget,
            env=child_env,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired as e:
        if e.stderr:
            sys.stderr.write(
                e.stderr.decode() if isinstance(e.stderr, bytes)
                else e.stderr
            )
        # The child prints a preliminary line right after the mandatory
        # f32 phase — a wedge during a later optional phase must not
        # discard that fresh measurement in favor of a stale replay.
        line = last_metric_line(e.stdout)
        if line:
            sys.stderr.write(
                "bench: child timed out after the headline measurement; "
                "emitting its preliminary line\n"
            )
            print(line)
            sys.stdout.flush()
        else:
            fail(
                f"measurement child exceeded its {int(child_budget)}s "
                "budget (tunnel likely wedged mid-run)"
            )
        return

    sys.stderr.write(proc.stderr)
    line = last_metric_line(proc.stdout)
    if line and (proc.returncode == 0 or '"step_ms"' in line):
        # rc != 0 with a metric line still counts: the headline phase
        # finished before the child died in a later phase.
        if proc.returncode != 0:
            sys.stderr.write(
                f"bench: child exited rc={proc.returncode} after the "
                "headline measurement; emitting its last line\n"
            )
        print(line)
        sys.stdout.flush()
    elif force_cpu:
        fail(f"measurement child failed (rc={proc.returncode})")
    elif _is_transport_connection_error(proc.stderr or ""):
        # The child's own stderr shows a connection failure
        # ATTRIBUTABLE TO THE DEVICE TRANSPORT (jaxlib/XLA/PJRT/axon/
        # grpc — see _is_transport_connection_error for the attribution
        # rule): the tunnel dropped mid-run, even if it has already
        # RECOVERED by the time we could reprobe (round-3 logs show
        # intermittent blips). Infra, not code — replay. The same
        # substrings on unattributed lines (e.g. a runtime queue/IPC
        # bug raising EOFError, or an env-server pipe broken by a
        # learner crash) do NOT qualify; those fall through to the
        # reprobe arms below, so a code regression is never silently
        # replayed as last-known-good chip numbers.
        fail(
            f"measurement child failed (rc={proc.returncode}) with a "
            "connection error in stderr — tunnel dropped mid-run"
        )
    elif (
        reprobe := _probe_backend(
            min(30.0, max(5.0, deadline - time.monotonic() - 10.0))
        )
    ) is None or reprobe[0] != probe[0]:
        # The child died with no measurement line AND the backend is no
        # longer what it was: either nothing answers, or the probe now
        # sees a DIFFERENT platform — when the tunnel drops fast (conn
        # refused rather than hang), jax falls back to the cpu platform,
        # so a non-None answer alone does not mean the accelerator is
        # still there. Either way the tunnel dropped mid-run (a drop can
        # raise inside the child rather than hang it): an infra failure,
        # not a code regression — replay applies.
        fail(
            f"measurement child failed (rc={proc.returncode}) and the "
            f"backend changed ({probe[0]} -> "
            f"{reprobe[0] if reprobe else 'no answer'}) — tunnel "
            "dropped mid-run"
        )
    else:
        # The backend probe SUCCEEDED before AND after the child's
        # failure, and the child produced no measurement line: that is
        # a code crash, not a tunnel wedge. Replaying last-known-good
        # chip numbers here would report a genuinely broken bench as
        # success indefinitely — emit an unmistakable error record
        # instead (value null, fresh false). Replay stays reserved for
        # probe failures, mid-run timeouts, and tunnel drops, where the
        # measurement was impossible rather than broken.
        tail = "; ".join(
            (proc.stderr or "").strip().splitlines()[-3:]
        )
        print(json.dumps(_base_result(
            platform="error",
            error=(
                f"measurement child crashed (rc={proc.returncode}) "
                "after a successful backend probe"
            ),
            note=(
                "no replay: a crash with the tunnel up is a code "
                "regression, not a wedge; last recorded chip numbers "
                "remain in benchmarks/artifacts/last_tpu_bench.json. "
                f"stderr tail: {tail}"
            ),
        )))
        sys.stdout.flush()


if __name__ == "__main__":
    main()
