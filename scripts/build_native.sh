#!/bin/bash
# Build + test the native runtime: C++ unit tests then the Python extension.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== C++ core tests"
g++ -std=c++17 -O2 -Wall -pthread csrc/test_core.cc -o build/test_core \
    2>&1 | head -30 || { mkdir -p build; g++ -std=c++17 -O2 -Wall -pthread \
    csrc/test_core.cc -o build/test_core; }
./build/test_core

echo "== Python extension"
touch csrc/pymodule.cc  # setuptools doesn't track header deps
python setup.py build_ext --inplace --build-temp build/ext
python -c "import _tbt_core; print('extension OK:', _tbt_core.__file__)"
