#!/bin/bash
# Build + test the native runtime: C++ unit tests then the Python extension.
#
# Modes:
#   (no args)                 O2 build, run all C++ tests, build extension
#   --tsan                    additionally build+run under ThreadSanitizer
#                             (kept for backward compatibility)
#   --sanitize=address        build+run ONLY the sanitized test binary
#   --sanitize=undefined      (address|undefined|thread); skips the O2
#   --sanitize=thread         build and the Python extension
#   --filter=SUBSTR           pass a test-name substring filter through to
#                             every test_core run (e.g. --filter=wire
#                             skips the socket tests in sandboxes that
#                             cannot run them). Applies to the plain,
#                             --tsan, and --sanitize runs alike.
#
# The sanitized binaries land in build/test_core_<sanitizer>; the slow
# smoke test in tests/test_native.py drives --sanitize=address/undefined
# with --filter=wire when a toolchain is present.
set -euo pipefail
cd "$(dirname "$0")/.."
mkdir -p build

SANITIZE=""
FILTER=""
TSAN=0
for arg in "$@"; do
    case "$arg" in
        --tsan) TSAN=1 ;;
        --sanitize=*) SANITIZE="${arg#--sanitize=}" ;;
        --filter=*) FILTER="${arg#--filter=}" ;;
        *)
            echo "unknown argument: $arg" >&2
            exit 2
            ;;
    esac
done

if [[ -n "$SANITIZE" ]]; then
    case "$SANITIZE" in
        address|undefined|thread) ;;
        *)
            echo "--sanitize must be address, undefined, or thread" >&2
            exit 2
            ;;
    esac
    echo "== C++ core tests (${SANITIZE} sanitizer)"
    EXTRA=()
    if [[ "$SANITIZE" == "undefined" ]]; then
        # Turn UB findings into hard failures instead of log lines.
        EXTRA+=(-fno-sanitize-recover=undefined)
    fi
    g++ -std=c++17 -O1 -g -Wall -pthread "-fsanitize=${SANITIZE}" \
        "${EXTRA[@]+"${EXTRA[@]}"}" \
        csrc/test_core.cc -o "build/test_core_${SANITIZE}"
    "./build/test_core_${SANITIZE}" ${FILTER:+"$FILTER"}
    exit 0
fi

echo "== C++ core tests"
g++ -std=c++17 -O2 -Wall -pthread csrc/test_core.cc -o build/test_core
./build/test_core ${FILTER:+"$FILTER"}

if [[ "$TSAN" == 1 ]]; then
    echo "== C++ core tests (ThreadSanitizer)"
    g++ -std=c++17 -O1 -g -Wall -pthread -fsanitize=thread \
        csrc/test_core.cc -o build/test_core_tsan
    ./build/test_core_tsan ${FILTER:+"$FILTER"}
fi

echo "== Python extension"
touch csrc/pymodule.cc  # setuptools doesn't track header deps
python setup.py build_ext --inplace --build-temp build/ext
python -c "import _tbt_core; print('extension OK:', _tbt_core.__file__)"
