#!/bin/bash
# Build + test the native runtime: C++ unit tests then the Python extension.
# --tsan additionally runs the C++ tests under ThreadSanitizer (the
# reference ships no race detection at all, SURVEY.md §5.2).
set -euo pipefail
cd "$(dirname "$0")/.."
mkdir -p build

echo "== C++ core tests"
g++ -std=c++17 -O2 -Wall -pthread csrc/test_core.cc -o build/test_core
./build/test_core

if [[ "${1:-}" == "--tsan" ]]; then
    echo "== C++ core tests (ThreadSanitizer)"
    g++ -std=c++17 -O1 -g -Wall -pthread -fsanitize=thread \
        csrc/test_core.cc -o build/test_core_tsan
    ./build/test_core_tsan
fi

echo "== Python extension"
touch csrc/pymodule.cc  # setuptools doesn't track header deps
python setup.py build_ext --inplace --build-temp build/ext
python -c "import _tbt_core; print('extension OK:', _tbt_core.__file__)"
