#!/bin/bash
# Build + test the native runtime: C++ unit tests then the Python extension.
#
# Modes:
#   (no args)                 O2 build, run all C++ tests, build extension
#   --tsan                    additionally build+run under ThreadSanitizer
#                             (kept for backward compatibility)
#   --sanitize=address        build+run ONLY the sanitized test binary
#   --sanitize=undefined      (address|undefined|thread); skips the O2
#   --sanitize=thread         build and the Python extension
#   --filter=SUBSTR           pass a test-name substring filter through to
#                             every test_core run (e.g. --filter=wire
#                             skips the socket tests in sandboxes that
#                             cannot run them). REPEATABLE: each filter
#                             gets its own run of the binary, so the
#                             documented TSan lane
#                             --sanitize=thread --filter={queue,atch,ring}
#                             (brace expansion = three --filter args)
#                             covers all three suites. Applies to the
#                             plain, --tsan, and --sanitize runs alike.
#   --smoke                   the native-parity CI lane in one command:
#                             build + run the filtered suites (queue,
#                             atch, ring, wire, array, nest) plain AND
#                             under TSan, then build the extension.
#
# The sanitized binaries land in build/test_core_<sanitizer>; the slow
# smoke test in tests/test_native.py drives --sanitize=address/undefined
# with --filter=wire when a toolchain is present.
set -euo pipefail
cd "$(dirname "$0")/.."
mkdir -p build

# shm_open/shm_unlink live in librt on this image's glibc (<2.34); newer
# glibcs keep an empty librt, so linking it is portable both ways.
LIBS=(-lrt)

SANITIZE=""
FILTERS=()
TSAN=0
SMOKE=0
for arg in "$@"; do
    case "$arg" in
        --tsan) TSAN=1 ;;
        --smoke) SMOKE=1 ;;
        --sanitize=*) SANITIZE="${arg#--sanitize=}" ;;
        --filter=*) FILTERS+=("${arg#--filter=}") ;;
        *)
            echo "unknown argument: $arg" >&2
            exit 2
            ;;
    esac
done

run_filtered() {
    # Run $1 once per filter (or once unfiltered when none given).
    local binary="$1"
    if [[ ${#FILTERS[@]} -eq 0 ]]; then
        "$binary"
    else
        for f in "${FILTERS[@]}"; do
            "$binary" "$f"
        done
    fi
}

if [[ "$SMOKE" == 1 ]]; then
    # One-command native-parity lane: every suite that runs in a plain
    # sandbox (the env_server socket suite needs a working accept(),
    # which some sandboxes lack), plain + TSan, then the extension.
    # "batcher" (not "atch"): strstr filtering makes "atch" also match
    # the batching_queue tests, which "queue" already runs.
    FILTERS=(queue batcher ring wire array nest routing)
    echo "== C++ core tests (smoke)"
    g++ -std=c++17 -O2 -Wall -pthread csrc/test_core.cc -o build/test_core \
        "${LIBS[@]}"
    run_filtered ./build/test_core
    echo "== C++ core tests (smoke, ThreadSanitizer)"
    g++ -std=c++17 -O1 -g -Wall -pthread -fsanitize=thread \
        csrc/test_core.cc -o build/test_core_tsan "${LIBS[@]}"
    run_filtered ./build/test_core_tsan
    echo "== Python extension"
    touch csrc/pymodule.cc  # setuptools doesn't track header deps
    python setup.py build_ext --inplace --build-temp build/ext
    python -c "import _tbt_core; print('extension OK:', _tbt_core.__file__)"
    exit 0
fi

if [[ -n "$SANITIZE" ]]; then
    case "$SANITIZE" in
        address|undefined|thread) ;;
        *)
            echo "--sanitize must be address, undefined, or thread" >&2
            exit 2
            ;;
    esac
    echo "== C++ core tests (${SANITIZE} sanitizer)"
    EXTRA=()
    if [[ "$SANITIZE" == "undefined" ]]; then
        # Turn UB findings into hard failures instead of log lines.
        EXTRA+=(-fno-sanitize-recover=undefined)
    fi
    g++ -std=c++17 -O1 -g -Wall -pthread "-fsanitize=${SANITIZE}" \
        "${EXTRA[@]+"${EXTRA[@]}"}" \
        csrc/test_core.cc -o "build/test_core_${SANITIZE}" "${LIBS[@]}"
    run_filtered "./build/test_core_${SANITIZE}"
    exit 0
fi

echo "== C++ core tests"
g++ -std=c++17 -O2 -Wall -pthread csrc/test_core.cc -o build/test_core \
    "${LIBS[@]}"
run_filtered ./build/test_core

if [[ "$TSAN" == 1 ]]; then
    echo "== C++ core tests (ThreadSanitizer)"
    g++ -std=c++17 -O1 -g -Wall -pthread -fsanitize=thread \
        csrc/test_core.cc -o build/test_core_tsan "${LIBS[@]}"
    run_filtered ./build/test_core_tsan
fi

echo "== Python extension"
touch csrc/pymodule.cc  # setuptools doesn't track header deps
python setup.py build_ext --inplace --build-temp build/ext
python -c "import _tbt_core; print('extension OK:', _tbt_core.__file__)"
