#!/usr/bin/env bash
# Watch the axon TPU tunnel; the moment it answers, capture the round's
# TPU evidence in one serial pass (the chip is single-tenant):
#   1. bench.py              — fresh headline numbers + HBM roofline
#                              (auto-refreshes last_tpu_bench.json)
#   2. pallas_smoke.py       — Mosaic lowering + parity for both
#                              Pallas kernels (fail fast, 5 min cap)
#   3. vtrace_bench.py       — sequential vs associative V-trace at
#                              long T (the O(log T) claim's chip row)
#   4. profile_step.py bf16  — op-level trace + roofline evidence
#   5. profile_step.py f32
#   6. mfu_ablation.py       — trunk share + channel/batch scaling
#   7. tpu_e2e_async.py      — full async driver system SPS + queues
#   8. monobeast overlap A/B — zero-lag vs --overlap_collect timings
# Everything lands under $OUT; summarize into repo artifacts by hand
# afterwards (this script never writes to benchmarks/artifacts itself,
# except bench.py's own last_tpu refresh).
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
OUT="${OUT:-/tmp/tpu_capture}"
mkdir -p "$OUT"
DEADLINE=$(( $(date +%s) + ${WATCH_BUDGET_S:-21600} ))  # default 6 h

probe() {
  timeout 60 python -c \
    "import jax; d=jax.devices()[0]; print(d.platform, d.device_kind)" \
    2>/dev/null
}

cd "$REPO"
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if P=$(probe); then
    echo "$(date -Is) tunnel UP: $P" | tee -a "$OUT/watch.log"
    echo "=== bench ===" >> "$OUT/watch.log"
    BENCH_BUDGET_S=900 timeout 960 python bench.py \
      > "$OUT/bench.json" 2> "$OUT/bench.err"
    echo "bench rc=$?" >> "$OUT/watch.log"
    echo "=== pallas smoke ===" >> "$OUT/watch.log"
    # Mosaic lowering check BEFORE the long captures: the kernels have
    # only ever run under the interpreter on CPU; a block-shape or
    # memory-space lowering failure should cost 5 minutes, not the
    # whole capture budget.
    timeout 300 python benchmarks/pallas_smoke.py \
      > "$OUT/pallas_smoke.json" 2> "$OUT/pallas_smoke.err"
    echo "pallas smoke rc=$?" >> "$OUT/watch.log"
    echo "=== vtrace scan bench ===" >> "$OUT/watch.log"
    # Sequential vs associative V-trace at T in {80, 1000, 4000}: the
    # O(log T) depth claim in --vtrace_impl's help text is decided by
    # this chip row (CPU rows only bound overhead).
    # --no_artifact: this script's contract is that nothing lands in
    # benchmarks/artifacts except bench.py's last_tpu refresh; the row
    # is recoverable from $OUT/vtrace_bench.json.
    timeout 300 python benchmarks/vtrace_bench.py --no_artifact \
      > "$OUT/vtrace_bench.json" 2> "$OUT/vtrace_bench.err"
    echo "vtrace bench rc=$?" >> "$OUT/watch.log"
    echo "=== profile bf16 ===" >> "$OUT/watch.log"
    timeout 600 python benchmarks/profile_step.py --dtype bf16 \
      --steps 10 --out "$OUT/trace_bf16" \
      > "$OUT/profile_bf16.json" 2> "$OUT/profile_bf16.err"
    echo "profile bf16 rc=$?" >> "$OUT/watch.log"
    echo "=== profile f32 ===" >> "$OUT/watch.log"
    timeout 600 python benchmarks/profile_step.py --dtype f32 \
      --steps 10 --out "$OUT/trace_f32" \
      > "$OUT/profile_f32.json" 2> "$OUT/profile_f32.err"
    echo "profile f32 rc=$?" >> "$OUT/watch.log"
    echo "=== mfu ablation ===" >> "$OUT/watch.log"
    timeout 1300 python benchmarks/mfu_ablation.py --full \
      --budget_s 1200 \
      > "$OUT/mfu_ablation.json" 2> "$OUT/mfu_ablation.err"
    echo "mfu ablation rc=$?" >> "$OUT/watch.log"
    echo "=== e2e async ===" >> "$OUT/watch.log"
    timeout 1300 python benchmarks/tpu_e2e_async.py \
      --total_steps 200000 --timeout_s 1200 --out "$OUT/e2e.log" \
      > "$OUT/e2e.json" 2> "$OUT/e2e.err"
    echo "e2e rc=$?" >> "$OUT/watch.log"
    echo "=== mono overlap A/B ===" >> "$OUT/watch.log"
    for mode in off on; do
      extra=""; [ "$mode" = on ] && extra="--overlap_collect"
      timeout 700 python -m torchbeast_tpu.monobeast --env Mock \
        --model deep --use_lstm --num_actors 8 --batch_size 8 \
        --unroll_length 5 --total_steps 12000 --serial_envs \
        --savedir /tmp/tpu_ovl --xpid "ovl-$mode" $extra \
        > "$OUT/mono_overlap_$mode.log" 2>&1
      echo "overlap $mode rc=$?" >> "$OUT/watch.log"
    done
    echo "$(date -Is) capture COMPLETE" | tee -a "$OUT/watch.log"
    # Persist the raw capture into the repo tree immediately: a fire in
    # the round's last minutes must not strand the only chip evidence in
    # /tmp (the driver's end-of-round commit picks up the working tree).
    # JSON summaries, stderr, and driver logs only — the multi-MB xplane
    # trace dirs stay in $OUT.
    RAW="$REPO/benchmarks/artifacts/tpu_capture_raw"
    mkdir -p "$RAW"
    cp "$OUT"/*.json "$OUT"/*.err "$OUT"/*.log "$RAW/" 2>/dev/null
    echo "$(date -Is) raw capture persisted to $RAW" >> "$OUT/watch.log"
    cp "$OUT/watch.log" "$RAW/" 2>/dev/null
    exit 0
  fi
  echo "$(date -Is) tunnel down" >> "$OUT/watch.log"
  sleep "${PROBE_INTERVAL_S:-240}"
done
echo "$(date -Is) watch budget exhausted; tunnel never came up" \
  | tee -a "$OUT/watch.log"
exit 3
