#!/usr/bin/env python
"""Chaos acceptance harness (ISSUE 6, scaled + overload-aware in
ISSUE 14): run polybeast under a seeded multi-fault plan and PROVE
recovery, not just survival.

Two in-process polybeast runs on the same config:

  1. baseline — fault-free,
  2. chaos    — a seeded FaultPlan firing >=4 fault classes mid-run
                (env-server SIGKILL x scale, transport sever x scale,
                state-table poison, learner stall by default),

then assert:

  - the chaos run completes (reaches --total_steps, health != HALTED),
  - learning is intact: final mean episode return matches the
    fault-free baseline within --return_tol,
  - recovery telemetry counters EXACTLY equal the injected fault
    counts (server restarts == SIGKILLs, actor reconnects ==
    SIGKILLs x actors-per-server + severs, inference restarts ==
    table rebuilds == poisons),
  - load shedding is real AND lossless: with the admission gate armed
    (--request_deadline_ms) and a learner stall planned, the serving
    tier sheds (serving.shed + serving.expired > 0) and every shed was
    re-submitted (serving.resubmitted == shed + expired — a shed is
    never a lost rollout),
  - nothing leaked: no live child processes, no new /dev/shm segments.

`--scale N` multiplies the actor/server fleet AND the fault plan
together (N SIGKILLs on distinct servers, N severs on distinct actors
disjoint from the killed servers' actors, staggered triggers), so the
10x acceptance run (scale 10 on the 16-actor/8-server base = 160/80)
exercises the same exact accounting as the CI selftest.

`--selftest` is the CPU CI gate (Mock env, short run, schema-pinned in
tests/test_bench_scripts.py; scripts/check.sh runs it at --scale 2);
the default mode is the Catch acceptance run whose artifact is
committed under benchmarks/artifacts/.

Usage:
  python scripts/chaos_run.py --selftest
  python scripts/chaos_run.py --selftest --scale 2
  python scripts/chaos_run.py --out benchmarks/artifacts/chaos_run.json
  python scripts/chaos_run.py --native --scale 10 --num_servers 8 \\
      --num_actors 16 --batch_size 16 --request_deadline_ms 2000 \\
      --out benchmarks/artifacts/chaos_run_10x.json
"""

import argparse
import json
import multiprocessing as mp
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

SHM_DIR = "/dev/shm"


def parse_args(argv=None):
    # The harness deliberately scales the driver's flags DOWN (small
    # env, short run, tiny batch) so two full polybeast runs fit a CI
    # budget — each shared-name divergence below is that intent, spelled
    # out per flag for beastlint's FLAG-PARITY cross-driver check.
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--selftest", action="store_true",
                   help="Short structural run on Mock (the CI gate).")
    p.add_argument("--native", action="store_true",
                   help="Run both legs with --native_runtime (the C++ "
                        "pool; needs the _tbt_core extension, "
                        "scripts/build_native.sh). Transport faults "
                        "then ride the pool's C++ FaultHooks instead "
                        "of the Python FaultingTransport wrap — the "
                        "same plan, the same exact accounting "
                        "(ISSUE 12). Without this flag both legs pin "
                        "--no_native_runtime: the harness's "
                        "interposition accounting must know which "
                        "runtime it audits, not inherit the driver "
                        "default.")
    p.add_argument("--scale", type=int, default=1,
                   help="Scale knob (ISSUE 14): multiplies "
                        "num_actors/num_servers AND the fault plan "
                        "together — scale N plans N env-server "
                        "SIGKILLs (distinct servers) and N transport "
                        "severs (distinct actors, disjoint from the "
                        "killed servers' actors), staggered across "
                        "the run. Requires num_servers >= 2*scale "
                        "so the two target sets stay disjoint.")
    # beastlint: disable=FLAG-PARITY  armed by default here: the chaos harness's whole point is exercising the shed path; the driver default (0 = off) preserves pre-ISSUE-14 behavior
    p.add_argument("--request_deadline_ms", type=float, default=300.0,
                   help="Forwarded to both legs: arms the admission "
                        "gate so the planned learner_stall produces "
                        "real sheds (asserted > 0). 0 disarms it "
                        "(and the shed assertions).")
    p.add_argument("--stall_s", type=float, default=3.0,
                   help="learner_stall fault duration: how long the "
                        "learner AND serving threads freeze (the "
                        "shared-chip overload model). Must exceed "
                        "request_deadline_ms for deterministic "
                        "expiry sheds.")
    # Replica serving knobs forwarded to BOTH legs verbatim (same
    # type/default as polybeast, FLAG-PARITY-checked): 0 = central
    # serving only; set --replica_refresh_updates to chaos-test the
    # snapshot/lag machinery too (Python runtime only).
    p.add_argument("--replica_refresh_updates", type=int, default=0)
    p.add_argument("--max_policy_lag", type=int, default=20)
    # Continuous-batching depth knob forwarded verbatim (same
    # type/default as polybeast, FLAG-PARITY-checked): the admission
    # gate's queue bound as a multiple of max_inference_batch_size.
    p.add_argument("--admission_depth_factor", type=int, default=4)
    # Resilience knobs forwarded to BOTH legs: re-declared here (same
    # type/default as polybeast) so beastlint FLAG-PARITY keeps the
    # chaos harness from drifting away from the driver's resilience
    # surface.
    p.add_argument("--min_live_actors", type=int, default=1,
                   help="Graceful degradation floor: the run "
                        "continues DEGRADED while at least this "
                        "many actor loops are alive, and "
                        "checkpoints-then-exits cleanly (health "
                        "HALTED) below it — instead of hanging on "
                        "a starved learner queue.")
    p.add_argument("--inference_restart_budget", type=int, default=3,
                   help="How many times the inference supervisor "
                        "may rebuild a poisoned DeviceStateTable "
                        "and restart the serving threads before "
                        "the pipeline goes HALTED "
                        "(checkpoint-and-exit).")
    p.add_argument("--max_actor_reconnects", type=int, default=3,
                   help="Elastic actors: reconnect (with jittered "
                        "exponential backoff) up to N times per "
                        "actor on env-server transport failure or "
                        "a failed inference batch; the budget "
                        "refills after a full recovered unroll. "
                        "Nonzero by default — a single env-server "
                        "blip must not permanently retire an actor "
                        "(with external unsupervised servers the "
                        "backoff bounds what a truly dead address "
                        "costs). 0 = fail fast, like the "
                        "reference. App-level env errors are never "
                        "absorbed either way.")
    # beastlint: disable=FLAG-PARITY  a wedged chaos run should fail THIS harness in a minute, not after the driver's 5-minute stall deadline
    p.add_argument("--learner_stall_timeout_s", type=float, default=60.0,
                   help="Learner stall watchdog deadline forwarded to "
                        "both legs (shortened vs the driver default).")
    # beastlint: disable=FLAG-PARITY  Catch solves in minutes on CPU; the chaos harness needs a LEARNABLE short run, not Pong
    p.add_argument("--env", default="Catch")
    # beastlint: disable=FLAG-PARITY  two full runs per invocation: 60k steps keeps the acceptance pass under a CI budget
    p.add_argument("--total_steps", type=int, default=60000)
    p.add_argument("--num_servers", type=int, default=4)
    # beastlint: disable=FLAG-PARITY  pinned to num_servers (1:1 topology) so reconnect accounting is exact; polybeast's None means "derive from servers"
    p.add_argument("--num_actors", type=int, default=4,
                   help="Keep == num_servers: the 1:1 actor/server "
                        "topology is what makes reconnect accounting "
                        "exact (1 per SIGKILL).")
    # beastlint: disable=FLAG-PARITY  small batch matches the 4-actor chaos topology, not the beefy-machine default
    p.add_argument("--batch_size", type=int, default=4)
    # beastlint: disable=FLAG-PARITY  short unrolls make the injected faults land mid-rollout within the short run
    p.add_argument("--unroll_length", type=int, default=20)
    # beastlint: disable=FLAG-PARITY  higher LR so Catch converges inside the shortened run
    p.add_argument("--learning_rate", type=float, default=2e-3)
    # beastlint: disable=FLAG-PARITY  higher exploration bonus for the short Catch run, same reason as the LR
    p.add_argument("--entropy_cost", type=float, default=0.01)
    # beastlint: disable=FLAG-PARITY  the committed chaos artifact is reproduced from THIS seed; it feeds the FaultPlan, not just the env
    p.add_argument("--seed", type=int, default=7,
                   help="FaultPlan seed + --env_seed for both runs.")
    p.add_argument("--return_tol", type=float, default=0.2,
                   help="Allowed |chaos - baseline| final-return gap.")
    p.add_argument("--scheduler_pressure", type=int, default=0,
                   help="Induced-scheduler-pressure mode (ROADMAP "
                        "metastability debt): run the CHAOS leg with N "
                        "spinner subprocesses competing for every core "
                        "(capacity_bench's pressure trick) and record "
                        "the ring.doorbell_waits / "
                        "ring.recheck_wakeups contrast between the "
                        "unpressured baseline leg and the pressured "
                        "chaos leg in the verdict's \"ring\" block — "
                        "the counter baseline needed to localize the "
                        "doorbell root cause. 0 = off (both legs "
                        "unpressured; the ring block is still "
                        "recorded).")
    # Multi-host fleet lane (ISSUE 17): --hosts 2 runs ONE fleet
    # (in-process lead + subprocess remote) instead of the
    # baseline/chaos pair, SIGKILLs the remote's whole env-server
    # fleet mid-run, and asserts the remote's exact reconnect
    # accounting plus the STICKY fleet.host1 degradation folded on the
    # surviving lead.
    p.add_argument("--hosts", type=int, default=1,
                   help="2 = the fleet chaos lane (lead in-process, "
                        "host 1 a polybeast subprocess joined via "
                        "--fleet over a free loopback port). 1 = the "
                        "classic baseline/chaos pair.")
    p.add_argument("--fleet", default=None,
                   help="Declared for driver parity and rejected when "
                        "set: the harness composes the fleet spec "
                        "itself from --hosts.")
    p.add_argument("--min_live_hosts", type=int, default=1,
                   help="Fleet degradation floor (--fleet runs): "
                        "losing a host marks the fleet DEGRADED "
                        "(sticky fleet.host<r>_lost) while at "
                        "least this many hosts stay live; "
                        "forwarded to both fleet hosts.")
    # beastlint: disable=FLAG-PARITY  None means "fresh temp dir per run": chaos artifacts must never land in the training logdir
    p.add_argument("--savedir", default=None,
                   help="Default: a fresh temp dir.")
    p.add_argument("--out", default=None,
                   help="Also write the JSON verdict here.")
    return p.parse_args(argv)


def build_plan(args) -> dict:
    """>=4 fault classes, step-triggered at fractions of the run so the
    pipeline is warm at injection time, SCALED with --scale (the plan
    grows with the fleet, ISSUE 14).

    The plan-scaling rule (schema-pinned in tests/test_bench_scripts):
    scale N plans N `env_server_sigkill` on servers 0..N-1 and N
    `transport_sever` on actors N..2N-1 — actor i connects to server
    i % num_servers, so with num_servers >= 2N the severed actors'
    servers are never killed and each fault maps to EXACTLY one
    recovery: reconnects == kills * (num_actors // num_servers) +
    severs. One state-table poison and one learner_stall (duration
    --stall_s) round out the classes; triggers stagger across
    [0.15, 0.65] of the run so recoveries do not overlap their own
    class's next injection."""
    t, n = args.total_steps, args.scale
    faults = []
    for i in range(n):
        faults.append({
            "kind": "env_server_sigkill",
            "at_step": int(t * (0.15 + 0.4 * i / n)),
            "target": i,
        })
        faults.append({
            "kind": "transport_sever",
            "at_step": int(t * (0.25 + 0.4 * i / n)),
            "target": n + i,
        })
    faults.append({"kind": "learner_stall", "at_step": int(t * 0.5),
                   "duration_s": args.stall_s})
    faults.append({"kind": "state_table_poison", "at_step": int(t * 0.7)})
    return {"seed": args.seed, "faults": faults}


def make_argv(args, savedir, xpid, chaos_plan_path=None,
              fleet_spec=None):
    argv = [
        "--env", args.env,
        "--model", "mlp",
        "--use_lstm",  # the state table only exists for recurrent models
        "--num_servers", str(args.num_servers),
        "--num_actors", str(args.num_actors),
        "--batch_size", str(args.batch_size),
        "--unroll_length", str(args.unroll_length),
        "--total_steps", str(args.total_steps),
        "--learning_rate", str(args.learning_rate),
        "--entropy_cost", str(args.entropy_cost),
        "--env_seed", str(args.seed),
        "--savedir", savedir,
        "--xpid", xpid,
        # shm rings so the SIGKILL class also exercises the segment
        # sweep (the no-leak assertion below would catch a regression).
        "--pipes_basename", f"shm:{savedir}/pipes-{xpid}",
        "--num_inference_threads", "1",
        "--max_inference_batch_size", "4",
        "--checkpoint_interval_s", "100000",
        "--min_live_actors", str(args.min_live_actors),
        "--inference_restart_budget", str(args.inference_restart_budget),
        "--max_actor_reconnects", str(args.max_actor_reconnects),
        "--learner_stall_timeout_s", str(args.learner_stall_timeout_s),
        "--request_deadline_ms", str(args.request_deadline_ms),
        "--admission_depth_factor", str(args.admission_depth_factor),
        "--replica_refresh_updates", str(args.replica_refresh_updates),
        "--max_policy_lag", str(args.max_policy_lag),
    ]
    # The runtime is pinned explicitly either way: the harness's fault
    # interposition accounting (FaultHooks vs FaultingTransport) must
    # audit the runtime it CHOSE, not inherit the driver's default.
    if getattr(args, "native", False):
        argv += ["--native_runtime"]
    else:
        argv += ["--no_native_runtime"]
    if chaos_plan_path is not None:
        argv += ["--chaos_plan", chaos_plan_path]
    if fleet_spec is not None:
        argv += ["--fleet", fleet_spec,
                 "--min_live_hosts", str(args.min_live_hosts)]
    return argv


def make_flags(args, savedir, xpid, chaos_plan_path=None,
               fleet_spec=None):
    from torchbeast_tpu import polybeast

    return polybeast.make_parser().parse_args(
        make_argv(args, savedir, xpid, chaos_plan_path, fleet_spec)
    )


def final_return(savedir, xpid):
    """Last non-empty mean_episode_return from the run's logs.csv (the
    in-memory stats dict can miss it when the final flush window closed
    no episode)."""
    import csv

    path = os.path.join(savedir, xpid, "logs.csv")
    last = None
    with open(path) as f:
        for row in csv.DictReader(f):
            val = row.get("mean_episode_return")
            if val:
                last = float(val)
    return last


def _shm_entries():
    if not os.path.isdir(SHM_DIR):
        return set()
    return {n for n in os.listdir(SHM_DIR) if n.startswith("psm_")}


def _live_children():
    return {p.pid for p in mp.active_children() if p.is_alive()}


class _SchedulerPressure:
    """Spinner subprocesses competing for every core while the chaos
    leg runs — the same induced-pressure contrast as
    benchmarks/capacity_bench.py, here paired with the ring-wait
    counters so the verdict carries a pressured-vs-unpressured
    baseline for the doorbell metastability investigation. n=0 is a
    no-op (spawns nothing), so the harness can wrap the leg
    unconditionally."""

    def __init__(self, n: int):
        self._n = max(0, int(n))
        self._procs = []

    def __enter__(self):
        import subprocess

        for _ in range(self._n):
            self._procs.append(subprocess.Popen(
                [sys.executable, "-c", "while True: pass"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                start_new_session=True,
            ))
        return self

    def __exit__(self, *exc):
        import signal

        for proc in self._procs:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                proc.kill()
            proc.wait()
        self._procs = []
        return False


def run_one(args, savedir, xpid, chaos_plan_path=None, fleet_spec=None):
    """One polybeast run with leak accounting and a counter delta."""
    from torchbeast_tpu import polybeast, telemetry

    shm_before = _shm_entries()
    procs_before = _live_children()
    snap_before = telemetry.snapshot()
    t0 = time.monotonic()
    flags = make_flags(args, savedir, xpid, chaos_plan_path, fleet_spec)
    stats = polybeast.train(flags)
    elapsed = time.monotonic() - t0
    counters = telemetry.delta(telemetry.snapshot(), snap_before).get(
        "counters", {}
    )
    return {
        "xpid": xpid,
        "elapsed_s": round(elapsed, 1),
        "step": stats.get("step", 0),
        "health": stats.get("health"),
        "mean_episode_return": final_return(savedir, xpid),
        "server_restarts": stats.get("server_restarts", 0),
        "actor_reconnects": stats.get("actor_reconnects", 0),
        "inference_restarts": stats.get("inference_restarts", 0),
        "health_reasons": stats.get("health_reasons"),
        "chaos": stats.get("chaos"),
        "counters": counters,
        "leaked_processes": sorted(_live_children() - procs_before),
        "leaked_shm": sorted(_shm_entries() - shm_before),
    }


def _free_coord_port():
    """A loopback port P with P+1 also free (rendezvous + control
    plane, fleet/topology.py CONTROL_PORT_OFFSET)."""
    import socket as socketlib

    for _ in range(50):
        s1 = socketlib.socket()
        s2 = socketlib.socket()
        try:
            s1.bind(("127.0.0.1", 0))
            port = s1.getsockname()[1]
            try:
                s2.bind(("127.0.0.1", port + 1))
            except OSError:
                continue
            return port
        finally:
            s1.close()
            s2.close()
    raise RuntimeError("no free adjacent port pair for --fleet coord")


def build_fleet_plan(args) -> dict:
    """The remote host's plan: SIGKILL its ENTIRE env-server fleet,
    staggered across [0.15, 0.55] of the run — one whole host's
    serving substrate churns while the lead host rides through
    untouched. Each kill maps to exactly actors-per-server reconnects
    on THAT host (the same accounting rule as the single-host plan)."""
    t, n = args.total_steps, args.num_servers
    faults = [
        {
            "kind": "env_server_sigkill",
            "at_step": int(t * (0.15 + 0.4 * i / n)),
            "target": i,
        }
        for i in range(n)
    ]
    return {"seed": args.seed, "faults": faults}


def run_fleet(args, savedir) -> int:
    """--hosts 2 lane (ISSUE 17): one fleet run — in-process lead +
    subprocess remote joined over a free loopback coord port — with the
    remote's whole env-server fleet SIGKILLed mid-run. Asserts the
    remote recovered with EXACT accounting, the lead folded a STICKY
    fleet.host1 degradation, and nobody halted."""
    import signal
    import subprocess

    from torchbeast_tpu import telemetry
    from torchbeast_tpu.resilience.chaos import FaultPlan

    xpid = "chaos-fleet"
    n_hosts = args.hosts
    plan_dict = build_fleet_plan(args)
    plan = FaultPlan.from_dict(plan_dict)
    plan_path = os.path.join(savedir, "fault_plan_host1.json")
    with open(plan_path, "w") as f:
        json.dump(plan_dict, f, indent=2)

    coord = f"127.0.0.1:{_free_coord_port()}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + ":" + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    # Remote host 1 launches first (it Backoff-dials the lead's control
    # plane) and carries the fault plan; its own process group so a
    # timeout kill also reaps its env-server children.
    remote_log = os.path.join(savedir, "host1.log")
    remote_argv = make_argv(
        args, savedir, xpid, plan_path,
        fleet_spec=f"host=1/{n_hosts},coord={coord}",
    )
    with open(remote_log, "w") as logf:
        remote = subprocess.Popen(
            [sys.executable, "-m", "torchbeast_tpu.polybeast"]
            + remote_argv,
            env=env, stdout=logf, stderr=subprocess.STDOUT, cwd=repo,
            start_new_session=True,
        )
        try:
            lead = run_one(
                args, savedir, xpid,
                fleet_spec=f"host=0/{n_hosts},coord={coord}",
            )
            try:
                remote_rc = remote.wait(timeout=120)
            except subprocess.TimeoutExpired:
                remote_rc = None  # killed below; fails the rc check
        finally:
            try:
                os.killpg(remote.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            remote.wait()

    remote_snaps = telemetry.read_jsonl(
        os.path.join(savedir, f"{xpid}-host1", "telemetry.jsonl")
    )
    remote_snap = remote_snaps[-1] if remote_snaps else {}
    remote_counters = remote_snap.get("counters", {})

    failures = []
    # -- completion on BOTH hosts (degraded, never halted) ----------------
    if lead["step"] < args.total_steps:
        failures.append(
            f"lead stopped at step {lead['step']} < {args.total_steps} "
            f"(health {lead['health']})"
        )
    if lead["health"] == "HALTED":
        failures.append("lead ended HALTED (floor is 1: the surviving "
                        "host must degrade, not abort)")
    if remote_rc != 0:
        failures.append(f"remote host exited rc={remote_rc} "
                        f"(log {remote_log})")
    # -- remote host identity on its telemetry stream ---------------------
    if remote_snap.get("host_rank") != 1:
        failures.append(
            f"remote host_rank static: got {remote_snap.get('host_rank')}"
            ", want 1"
        )
    if remote_snap.get("fleet_size") != n_hosts:
        failures.append(
            f"remote fleet_size static: got "
            f"{remote_snap.get('fleet_size')}, want {n_hosts}"
        )
    # -- exact recovery accounting on the faulted host --------------------
    n_kill = plan.counts().get("env_server_sigkill", 0)
    actors_per_server = args.num_actors // args.num_servers
    expected = {
        "chaos.env_server_sigkill.injected": n_kill,
        "recovery.server_restarts": n_kill,
        "recovery.actor_reconnects": n_kill * actors_per_server,
    }
    for name, want in expected.items():
        got = int(remote_counters.get(name, 0))
        if got != want:
            failures.append(
                f"remote counter {name}: got {got}, want {want}"
            )
    # -- the lead folded the incident as a STICKY degradation -------------
    reasons = lead.get("health_reasons") or []
    if not any(r.startswith("fleet.host1") for _, r in reasons):
        failures.append(
            "no fleet.host1 degradation folded on the lead "
            f"(reasons: {reasons})"
        )
    if lead["health"] != "DEGRADED":
        failures.append(
            f"lead health {lead['health']}: the remote's recovered "
            "SIGKILLs must leave a sticky DEGRADED mark"
        )

    verdict = {
        "bench": "chaos_run",
        "selftest": bool(args.selftest),
        "native": bool(args.native),
        "hosts": n_hosts,
        "scale": args.scale,
        "num_actors": args.num_actors,
        "num_servers": args.num_servers,
        "ok": not failures,
        "failures": failures,
        "env": args.env,
        "total_steps": args.total_steps,
        "plan": plan_dict,
        "expected_counters": expected,
        "results": {
            "lead": lead,
            "remote": {
                "rc": remote_rc,
                "telemetry_lines": len(remote_snaps),
                "counters": {
                    k: v for k, v in remote_counters.items()
                    if k.startswith(("chaos.", "recovery.", "fleet."))
                },
                "log": remote_log,
            },
        },
        "telemetry": telemetry.telemetry_block(),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(verdict, f, indent=2)
            f.write("\n")
    print(json.dumps(verdict))
    return 0 if verdict["ok"] else 1


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.selftest:
        # Short structural gate: Mock's return is deterministic (200.0
        # per episode regardless of policy), so return parity is exact
        # and the whole thing fits a CI budget.
        args.env = "Mock"
        args.total_steps = 2400
        args.num_servers = args.num_actors = 2
        args.batch_size = 2
        args.return_tol = 1e-6
        # Short stall, same contract: it still exceeds the deadline so
        # expiry sheds fire deterministically.
        args.stall_s = min(args.stall_s, 1.5)

    if args.scale < 1:
        print("--scale must be >= 1", file=sys.stderr)
        return 2
    if args.fleet:
        print(
            "--fleet is composed internally from --hosts; do not set "
            "it on the harness",
            file=sys.stderr,
        )
        return 2
    if args.hosts not in (1, 2):
        print("--hosts must be 1 or 2 (the fleet lane pins one remote "
              "host)", file=sys.stderr)
        return 2
    if args.hosts > 1 and args.scheduler_pressure:
        print(
            "--scheduler_pressure is a single-host mode: it wraps the "
            "chaos leg of the baseline/chaos pair, which the fleet "
            "lane replaces",
            file=sys.stderr,
        )
        return 2
    if args.hosts > 1 and args.batch_size % args.hosts != 0:
        print(
            f"--batch_size {args.batch_size} (global) must be "
            f"divisible by --hosts {args.hosts}",
            file=sys.stderr,
        )
        return 2
    # The scale knob multiplies the fleet AND the plan together.
    args.num_servers *= args.scale
    args.num_actors *= args.scale
    if args.num_actors % args.num_servers != 0:
        print(
            f"num_actors {args.num_actors} must be a multiple of "
            f"num_servers {args.num_servers} (uniform actors-per-server "
            "is what keeps reconnect accounting exact)",
            file=sys.stderr,
        )
        return 2
    if args.num_servers < 2 * args.scale:
        print(
            f"num_servers {args.num_servers} must be >= 2*scale "
            f"{2 * args.scale} (kill and sever target sets must stay "
            "disjoint for exact accounting)",
            file=sys.stderr,
        )
        return 2
    if (
        args.request_deadline_ms > 0
        and args.stall_s * 1000 <= args.request_deadline_ms
    ):
        print(
            "--stall_s must exceed --request_deadline_ms or the stall "
            "cannot produce deterministic expiry sheds",
            file=sys.stderr,
        )
        return 2

    if args.native:
        # gap_reason, not available(): a stale extension would make the
        # driver fall back to the Python pool and this harness would
        # silently audit the WRONG runtime into a "native": true
        # artifact.
        from torchbeast_tpu.runtime.native import gap_reason

        reason = gap_reason()
        if reason is not None:
            print(f"chaos_run --native: {reason}", file=sys.stderr)
            return 2

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    # The harness calls train() directly, so it owns logging config —
    # without this the driver's step/health/chaos lines are invisible.
    from torchbeast_tpu import polybeast as _polybeast

    _polybeast._configure_logging()

    from torchbeast_tpu import telemetry
    from torchbeast_tpu.resilience.chaos import FaultPlan

    savedir = args.savedir
    if savedir is None:
        import tempfile

        savedir = tempfile.mkdtemp(prefix="chaos_run_")
    if args.hosts >= 2:
        return run_fleet(args, savedir)

    plan_dict = build_plan(args)
    plan = FaultPlan.from_dict(plan_dict)  # validates kinds/triggers
    plan_path = os.path.join(savedir, "fault_plan.json")
    with open(plan_path, "w") as f:
        json.dump(plan_dict, f, indent=2)

    failures = []
    baseline = run_one(args, savedir, "chaos-baseline")
    # Only the chaos leg runs under induced scheduler pressure: the
    # unpressured baseline leg is the contrast the ring block needs.
    with _SchedulerPressure(args.scheduler_pressure):
        chaos = run_one(args, savedir, "chaos-faulted", plan_path)

    # -- completion --------------------------------------------------------
    if chaos["step"] < args.total_steps:
        failures.append(
            f"chaos run stopped at step {chaos['step']} < "
            f"{args.total_steps} (health {chaos['health']})"
        )
    if chaos["health"] == "HALTED":
        failures.append("chaos run ended HALTED")

    # -- learning intact ---------------------------------------------------
    base_ret, chaos_ret = (
        baseline["mean_episode_return"], chaos["mean_episode_return"]
    )
    if base_ret is None or chaos_ret is None:
        failures.append(
            f"missing episode returns (baseline {base_ret}, "
            f"chaos {chaos_ret})"
        )
    elif abs(base_ret - chaos_ret) > args.return_tol:
        failures.append(
            f"return drift: baseline {base_ret} vs chaos {chaos_ret} "
            f"(tol {args.return_tol})"
        )

    # -- exact recovery accounting ----------------------------------------
    injected = (chaos.get("chaos") or {}).get("injected", {})
    plan_counts = plan.counts()
    if injected != plan_counts:
        failures.append(
            f"injected {injected} != planned {plan_counts} "
            "(a fault never fired)"
        )
    n_kill = plan_counts.get("env_server_sigkill", 0)
    n_sever = plan_counts.get("transport_sever", 0)
    n_poison = plan_counts.get("state_table_poison", 0)
    # Uniform fan-in (validated above): a killed server drops ALL its
    # actors' streams, so each SIGKILL accounts for actors-per-server
    # reconnects (1 at the classic 1:1 topology).
    actors_per_server = args.num_actors // args.num_servers
    counters = chaos["counters"]
    expected = {
        # every chaos.<kind>.injected counter must match the plan...
        **{
            f"chaos.{kind}.injected": n
            for kind, n in plan_counts.items()
        },
        # ...and each fault class maps to its recovery counter exactly:
        # 1 respawn per SIGKILL, actors-per-server reconnects per
        # SIGKILL + 1 per sever, 1 rebuild+restart per poison.
        "recovery.server_restarts": n_kill,
        "recovery.actor_reconnects": (
            n_kill * actors_per_server + n_sever
        ),
        "recovery.inference_restarts": n_poison,
        "recovery.table_rebuilds": n_poison,
    }
    for name, want in expected.items():
        got = int(counters.get(name, 0))
        if got != want:
            failures.append(f"counter {name}: got {got}, want {want}")

    # -- load shedding: real AND lossless (ISSUE 14) ----------------------
    serving = {
        key: int(counters.get(f"serving.{key}", 0))
        for key in ("admitted", "shed", "expired", "resubmitted")
    }
    shed_total = serving["shed"] + serving["expired"]
    n_stall = plan_counts.get("learner_stall", 0)
    if serving["resubmitted"] != shed_total:
        failures.append(
            f"shed accounting broken: resubmitted {serving['resubmitted']}"
            f" != shed {serving['shed']} + expired {serving['expired']} "
            "(a shed was a lost request)"
        )
    if args.request_deadline_ms > 0 and n_stall > 0 and shed_total == 0:
        failures.append(
            "learner stall injected with the admission gate armed but "
            "nothing was shed (the overload path was not exercised)"
        )

    # -- no leaks ----------------------------------------------------------
    for run in (baseline, chaos):
        if run["leaked_processes"]:
            failures.append(
                f"{run['xpid']}: leaked processes "
                f"{run['leaked_processes']}"
            )
        if run["leaked_shm"]:
            failures.append(
                f"{run['xpid']}: leaked /dev/shm segments "
                f"{run['leaked_shm']}"
            )

    # -- ring-wait contrast (doorbell metastability baseline) --------------
    # Per-leg ring.doorbell_waits / ring.recheck_wakeups, with only the
    # chaos leg pressured when --scheduler_pressure > 0: a
    # recheck-heavy pressured leg against a doorbell-quiet baseline is
    # the signature the metastability investigation needs.
    ring = {
        "scheduler_pressure": args.scheduler_pressure,
        "baseline": {
            "doorbell_waits": int(
                baseline["counters"].get("ring.doorbell_waits", 0)
            ),
            "recheck_wakeups": int(
                baseline["counters"].get("ring.recheck_wakeups", 0)
            ),
        },
        "chaos": {
            "doorbell_waits": int(
                counters.get("ring.doorbell_waits", 0)
            ),
            "recheck_wakeups": int(
                counters.get("ring.recheck_wakeups", 0)
            ),
        },
    }

    verdict = {
        "bench": "chaos_run",
        "selftest": bool(args.selftest),
        "native": bool(args.native),
        "scale": args.scale,
        "num_actors": args.num_actors,
        "num_servers": args.num_servers,
        "request_deadline_ms": args.request_deadline_ms,
        "ok": not failures,
        "failures": failures,
        "env": args.env,
        "total_steps": args.total_steps,
        "plan": plan_dict,
        "expected_counters": expected,
        "serving": serving,
        "ring": ring,
        "results": {"baseline": baseline, "chaos": chaos},
        "telemetry": telemetry.telemetry_block(),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(verdict, f, indent=2)
            f.write("\n")
    print(json.dumps(verdict))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
