#!/bin/bash
# beastlint pre-commit wrapper: lint only the files changed vs a git ref
# (default HEAD — staged + unstaged + untracked), with the whole-program
# graph and parity anchors still built repo-wide. The changed-file
# filter covers Python AND the C++ core (csrc/*.h, *.cc — ISSUE 10):
# a csrc-only change runs the C++ rules (GIL-DISCIPLINE, ATOMIC-ORDER,
# CXX-LOCK-DISCIPLINE) instead of silently skipping the lint.
#
#   scripts/lint.sh              # lint your working-tree changes
#   scripts/lint.sh origin/main  # lint everything since origin/main
#
# Wire it as a pre-commit hook with:
#   ln -s ../../scripts/lint.sh .git/hooks/pre-commit
#
# Exit codes match the analyzer: 0 clean, 1 findings, 2 internal error.
set -euo pipefail
# rev-parse, not dirname: invoked as .git/hooks/pre-commit (a symlink),
# $0's directory is .git/hooks/ and dirname does not resolve symlinks.
cd "$(git rev-parse --show-toplevel)"
# --timing: per-rule wall-clock after the report, so a rule whose cost
# regresses shows up in the pre-commit output instead of silently
# eating the CI budget.
exec python -m torchbeast_tpu.analysis --ci --timing --diff "${1:-HEAD}"
