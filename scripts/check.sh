#!/bin/bash
# The one-command merge gate (ISSUE 10): native build + C++ test suites
# (plain AND under TSan) + the Python extension, then the full static
# analysis lane — repo-wide beastlint in CI mode (18 rules incl. the
# C++ frontend and the fleet/telemetry tier), the rule-fixture
# selftest, and the exhaustive model checks for both protocol specs
# (shm ring + doorbell, and the fleet control plane; shipped specs
# verify, seeded mutants must produce counterexample traces).
#
#   scripts/check.sh            # everything
#   scripts/check.sh --fast     # skip the native build (analysis only)
#
# Exit: nonzero on the first failing stage; each stage prints its own
# verdict line.
set -euo pipefail
cd "$(git -C "$(dirname "$0")" rev-parse --show-toplevel)"

FAST=0
for arg in "$@"; do
    case "$arg" in
        --fast) FAST=1 ;;
        *)
            echo "unknown argument: $arg" >&2
            exit 2
            ;;
    esac
done

if [[ "$FAST" -eq 0 ]]; then
    echo "== check: native smoke (build + C++ tests, plain + TSan, extension)"
    bash scripts/build_native.sh --smoke
fi

echo "== check: beastlint --ci (repo-wide, C++ frontend active)"
ci_start=$(date +%s)
python -m torchbeast_tpu.analysis --ci
ci_elapsed=$(( $(date +%s) - ci_start ))
# The CI-budget pin (ISSUE 10, re-anchored ISSUE 12): the full
# static-analysis lane must stay under 20s or it stops being a
# pre-commit-speed gate.
if [[ "$ci_elapsed" -gt 20 ]]; then
    echo "beastlint --ci took ${ci_elapsed}s (> 20s CI budget)" >&2
    exit 1
fi

echo "== check: beastlint --selftest (rule fixtures)"
python -m torchbeast_tpu.analysis --selftest

echo "== check: protocol model check (shm ring + doorbell)"
python -m torchbeast_tpu.analysis --check-protocol

echo "== check: fleet protocol model check (control plane under crash/wedge)"
python -m torchbeast_tpu.analysis --check-fleet

if [[ "$FAST" -eq 0 ]]; then
    echo "== check: chaos selftest, scaled (x2 fleet + x2 fault plan, shed audit)"
    JAX_PLATFORMS=cpu python scripts/chaos_run.py --selftest --scale 2

    echo "== check: IMPACT smoke (Catch, lag budget 10x, replay reuse 2)"
    # The lag-tolerant learner end to end (ISSUE 18): --loss impact
    # must LEARN Catch with the policy-lag budget at 10x the default
    # (replicas on the impact-relaxed refresh-every-10 cadence) while
    # reusing every batch twice — and the throughput/cadence accounting
    # that justifies the mode must be in the telemetry: the
    # env_sps/learn_sps split at the configured reuse factor, and the
    # target-network store publishing on its own cadence.
    JAX_PLATFORMS=cpu python -m torchbeast_tpu.polybeast \
        --env Catch --total_steps 40000 --num_servers 2 --num_actors 4 \
        --batch_size 4 --unroll_length 20 \
        --learning_rate 2e-3 --entropy_cost 0.01 \
        --loss impact --replay_reuse 2 --target_refresh_updates 8 \
        --max_policy_lag 200 --env_seed 1 \
        --xpid impact-smoke --savedir /tmp/tbt_impact_smoke \
        > /tmp/tbt_impact_smoke.log 2>&1 \
        || { tail -20 /tmp/tbt_impact_smoke.log; exit 1; }
    python - <<'EOF'
import csv, json
run = "/tmp/tbt_impact_smoke/impact-smoke"
ret = None
for row in csv.DictReader(open(run + "/logs.csv")):
    if row.get("mean_episode_return"):
        ret = float(row["mean_episode_return"])
assert ret is not None and ret >= 0.5, f"impact Catch final return {ret} < 0.5"
snap = json.loads(open(run + "/telemetry.jsonl").read().strip().splitlines()[-1])
g, c = snap["gauges"], snap["counters"]
assert g.get("learner.sample_reuse") == 2.0, g.get("learner.sample_reuse")
env_sps, learn_sps = g.get("learner.env_sps"), g.get("learner.learn_sps")
assert env_sps and learn_sps and learn_sps > env_sps, (env_sps, learn_sps)
assert c.get("learner.target.snapshots_published", 0) >= 1, \
    c.get("learner.target.snapshots_published")
assert c.get("learner.target.snapshot_bytes_published", 0) > 0
assert c.get("serving.snapshots_published", 0) >= 1, \
    c.get("serving.snapshots_published")
print("impact-smoke: PASS (return", ret, "env_sps", round(env_sps, 1),
      "learn_sps", round(learn_sps, 1), ")")
EOF

    echo "== check: Sebulba split smoke (2 forced host devices, inf=1,learn=rest)"
    # The async driver end to end with the device split on a forced
    # 2-device CPU topology (ISSUE 15): per-slice serving + the
    # DP-pinned learner mesh must train a short Mock run to completion.
    python benchmarks/tpu_e2e_async.py \
        --device_split inf=1,learn=rest --xla_device_count 2 \
        --model mlp --use_lstm --num_servers 2 --num_actors 4 \
        --batch_size 4 --unroll_length 10 --total_steps 4000 \
        --timeout_s 240 --out /tmp/tbt_split_smoke.log \
        > /tmp/tbt_split_smoke.json
    python - <<'EOF'
import json
summary = json.load(open("/tmp/tbt_split_smoke.json"))
assert "error" not in summary, summary
snap = summary["telemetry"]["snapshot"]
assert snap["device_split"]["inference_slices"] == 1, snap["device_split"]
assert snap["learner.mesh_shape"] == {"data": 1, "model": 1}
assert "inference.slice.0.depth" in snap["gauges"]
print("sebulba-smoke: PASS (steady sps", summary["steady_sps_mean"], ")")
EOF

    echo "== check: multi-host fleet smoke (2 forced-CPU hosts, wire-composed learner)"
    # Two polybeast processes composed into one fleet over a loopback
    # coord port (ISSUE 17): the lead must report the cross-host
    # learner mesh and the remote's folded slice gauges; the remote
    # must serve from wire-delivered snapshots (its store never
    # publishes past v0, so any version > 0 IS wire delivery) with
    # non-zero policy lag observed on the serving path.
    python benchmarks/tpu_e2e_async.py --fleet_hosts 2 \
        --device_split inf=1,learn=rest --xla_device_count 2 \
        --model mlp --use_lstm --num_servers 2 --num_actors 4 \
        --batch_size 4 --unroll_length 10 --total_steps 4000 \
        --timeout_s 300 --out /tmp/tbt_fleet_smoke.log \
        > /tmp/tbt_fleet_smoke.json
    python - <<'EOF'
import json
summary = json.load(open("/tmp/tbt_fleet_smoke.json"))
assert "error" not in summary, summary
snap = summary["telemetry"]["snapshot"]
assert snap["host_rank"] == 0 and snap["fleet_size"] == 2, snap
assert snap["learner.mesh_shape"] == {"data": 2, "model": 1}, \
    snap["learner.mesh_shape"]
assert "host1.inference.slice.0.depth" in snap["gauges"], \
    sorted(k for k in snap["gauges"] if k.startswith("host1."))
remote = summary["remote_hosts"]["1"]
assert remote["rc"] == 0, remote
rsnap = remote["snapshot"]
assert rsnap["host_rank"] == 1 and rsnap["fleet_size"] == 2, rsnap
assert rsnap["gauges"]["serving.snapshot_version"] > 0, \
    rsnap["gauges"].get("serving.snapshot_version")
assert rsnap["counters"]["fleet.snapshots_received"] > 0
lag = rsnap["histograms"]["serving.policy_lag"]
assert lag["count"] > 0 and lag["max"] > 0, lag
print("fleet-smoke: PASS (remote served", int(lag["count"]),
      "batches at snapshot v%d," % rsnap["gauges"]["serving.snapshot_version"],
      "max policy lag", lag["max"], ")")
EOF

    echo "== check: native capacity smoke (C++ slice+replica routing, admission armed)"
    # The NATIVE serving plane end to end, scaled down (ISSUE 16): one
    # tiny split+replica run per admission family (continuous vs
    # depth-gated) over shm rings, with the capacity-row schema —
    # per-slice request counters on BOTH slices, live admitted
    # accounting, ring-wait counters, policy-lag stamps — asserted by
    # the bench's own selftest verdict.
    JAX_PLATFORMS=cpu python benchmarks/capacity_bench.py --selftest \
        > /tmp/tbt_capacity_smoke.json
    python - <<'EOF'
import json
out = json.loads(open("/tmp/tbt_capacity_smoke.json").read().strip().splitlines()[-1])
assert out["selftest"]["ok"] is True, out["selftest"]
rows = {r["family"]: r for r in out["rows"]}
print("capacity-smoke: PASS (admitted/s continuous",
      rows["continuous"]["admitted_per_s"],
      "depth_gated", rows["depth_gated"]["admitted_per_s"], ")")
EOF
fi

echo "== check: PASS"
