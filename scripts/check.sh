#!/bin/bash
# The one-command merge gate (ISSUE 10): native build + C++ test suites
# (plain AND under TSan) + the Python extension, then the full static
# analysis lane — repo-wide beastlint in CI mode (14 rules incl. the
# C++ frontend), the rule-fixture selftest, and the exhaustive
# shm-protocol model check (shipped spec verifies; seeded mutants must
# produce counterexample traces).
#
#   scripts/check.sh            # everything
#   scripts/check.sh --fast     # skip the native build (analysis only)
#
# Exit: nonzero on the first failing stage; each stage prints its own
# verdict line.
set -euo pipefail
cd "$(git -C "$(dirname "$0")" rev-parse --show-toplevel)"

FAST=0
for arg in "$@"; do
    case "$arg" in
        --fast) FAST=1 ;;
        *)
            echo "unknown argument: $arg" >&2
            exit 2
            ;;
    esac
done

if [[ "$FAST" -eq 0 ]]; then
    echo "== check: native smoke (build + C++ tests, plain + TSan, extension)"
    bash scripts/build_native.sh --smoke
fi

echo "== check: beastlint --ci (repo-wide, C++ frontend active)"
ci_start=$(date +%s)
python -m torchbeast_tpu.analysis --ci
ci_elapsed=$(( $(date +%s) - ci_start ))
# The CI-budget pin (ISSUE 10, re-anchored ISSUE 12): the full
# static-analysis lane must stay under 20s or it stops being a
# pre-commit-speed gate.
if [[ "$ci_elapsed" -gt 20 ]]; then
    echo "beastlint --ci took ${ci_elapsed}s (> 20s CI budget)" >&2
    exit 1
fi

echo "== check: beastlint --selftest (rule fixtures)"
python -m torchbeast_tpu.analysis --selftest

echo "== check: protocol model check (shm ring + doorbell)"
python -m torchbeast_tpu.analysis --check-protocol

if [[ "$FAST" -eq 0 ]]; then
    echo "== check: chaos selftest, scaled (x2 fleet + x2 fault plan, shed audit)"
    JAX_PLATFORMS=cpu python scripts/chaos_run.py --selftest --scale 2
fi

echo "== check: PASS"
