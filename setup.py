"""Build the native runtime extension:  python setup.py build_ext --inplace

Builds `_tbt_core` (csrc/pymodule.cc + headers) with the CPython/numpy C
API — no pybind11, no torch, no gRPC (the reference's CMake stack,
/root/reference/CMakeLists.txt, pulled all three; this runtime needs none).
The pure-Python package works without the extension; runtime/native.py
picks it up when present.
"""

import numpy
from setuptools import Extension, setup

setup(
    name="torchbeast_tpu",
    version="0.1.0",
    packages=[
        "torchbeast_tpu",
        "torchbeast_tpu.envs",
        "torchbeast_tpu.models",
        "torchbeast_tpu.ops",
        "torchbeast_tpu.parallel",
        "torchbeast_tpu.runtime",
        "torchbeast_tpu.utils",
    ],
    ext_modules=[
        Extension(
            "_tbt_core",
            sources=["csrc/pymodule.cc"],
            include_dirs=["csrc", numpy.get_include()],
            extra_compile_args=["-std=c++17", "-O2", "-Wall", "-pthread"],
            language="c++",
        )
    ],
)
