"""Build the native runtime extension:  python setup.py build_ext --inplace

Builds `_tbt_core` (csrc/pymodule.cc + headers) with the CPython/numpy C
API — no pybind11, no torch, no gRPC (the reference's CMake stack,
/root/reference/CMakeLists.txt, pulled all three; this runtime needs none).
The pure-Python package works without the extension; runtime/native.py
picks it up when present.
"""

import numpy
from setuptools import Extension, setup

# Name/version/packages/scripts live in pyproject.toml; this file only adds
# what declarative metadata can't: the C extension.
setup(
    ext_modules=[
        Extension(
            "_tbt_core",
            sources=["csrc/pymodule.cc"],
            include_dirs=["csrc", numpy.get_include()],
            extra_compile_args=["-std=c++17", "-O2", "-Wall", "-pthread"],
            # shm_open/shm_unlink live in librt on this image's glibc
            # (< 2.34; newer glibcs keep an empty librt, so this is
            # portable both ways).
            libraries=["rt"],
            language="c++",
        )
    ],
)
