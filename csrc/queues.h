// BatchingQueue + DynamicBatcher: the native learner-queue and inference
// batcher (reference components N3/N4, /root/reference/src/cc/actorpool.cc
// 57-340 — re-designed torch-free over tbt::Array nests; semantics match
// the Python implementations in torchbeast_tpu/runtime/queues.py, which
// carry the test surface).

#pragma once

#include <array>
#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <deque>
#include <functional>
#include <future>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <stdexcept>
#include <vector>

#include "array.h"
#include "nest.h"

namespace tbt {

using ArrayNest = Nest<Array>;

// ------------------------------------------------------------ telemetry
// Log-bucket histogram accumulator with the SAME bucket geometry as
// torchbeast_tpu/telemetry/metrics.py (LO=1e-9, growth 2^0.25), so the
// Python driver can fold native snapshots straight into registry
// histograms bucket-for-bucket. Mutex-guarded: observations here happen
// at batch cadence (or per request on ms-scale operations), so a ~100ns
// lock is noise — and snapshot(reset=true) hands the driver exact
// interval aggregates without a torn read.
inline int telemetry_bucket_index(double value) {
  constexpr double kLo = 1e-9;
  static const double kLogGrowth = std::log(std::pow(2.0, 0.25));
  if (value <= kLo) return 0;
  return 1 + static_cast<int>(std::log(value / kLo) / kLogGrowth);
}

struct HistSnapshot {
  int64_t count = 0;
  double total = 0.0;
  double total_sq = 0.0;
  double min = 0.0;
  double max = 0.0;
  std::map<int, int64_t> buckets;
};

class HistAccum {
 public:
  void observe(double value) {
    std::lock_guard<std::mutex> lock(mu_);
    ++count_;
    total_ += value;
    total_sq_ += value * value;
    if (count_ == 1 || value < min_) min_ = value;
    if (count_ == 1 || value > max_) max_ = value;
    ++buckets_[telemetry_bucket_index(value)];
  }

  // Interval aggregate; reset=true starts a fresh interval (the
  // driver's monitor-tick fold — the registry owns the cumulative view).
  HistSnapshot snapshot(bool reset = false) {
    std::lock_guard<std::mutex> lock(mu_);
    HistSnapshot out{count_, total_, total_sq_, min_, max_, buckets_};
    if (reset) {
      count_ = 0;
      total_ = total_sq_ = min_ = max_ = 0.0;
      buckets_.clear();
    }
    return out;
  }

 private:
  mutable std::mutex mu_;
  int64_t count_ = 0;
  double total_ = 0.0, total_sq_ = 0.0, min_ = 0.0, max_ = 0.0;
  std::map<int, int64_t> buckets_;
};

// Sampled request->trace cadence, matching the Python pool's
// actor_pool.py _TRACE_EVERY so native and Python runs trace at the
// same density; the span buffer is bounded so an idle driver (nobody
// draining trace_spans) never grows memory.
constexpr int64_t kTraceEvery = 256;
constexpr size_t kTraceSpanCap = 1024;

// Per-request pipeline stamps (ISSUE 2 parity): enqueue -> batch ->
// reply. Shared by the batcher and its in-flight Batches.
struct BatcherTelemetry {
  std::atomic<int64_t> batches{0};
  std::atomic<int64_t> rows{0};
  HistAccum batch_size;
  HistAccum request_wait_s;  // enqueue -> picked into a batch
  HistAccum request_rtt_s;   // enqueue -> outputs distributed
  // Admission-gate accounting (ISSUE 14): same semantics as the Python
  // serving/admission.py series the driver folds these into —
  // admitted (accepted at enqueue), shed (rejected at the depth
  // bound), expired (deadline passed in-queue, failed at dequeue),
  // slo_breaches (served RTT above the SLO target).
  std::atomic<int64_t> admitted{0};
  std::atomic<int64_t> shed{0};
  std::atomic<int64_t> expired{0};
  std::atomic<int64_t> slo_breaches{0};
  // Continuous-batching accounting (ISSUE 16): requests rolled into an
  // already-forming dispatch window by the top-up pass. Pure
  // observability — rolled requests are ordinary admitted requests and
  // take no part in the shed/expired audit.
  std::atomic<int64_t> rolled{0};
  HistAccum queue_delay_s;  // enqueue -> dequeue, served AND expired
  // Sampled per-request spans (ISSUE 12): 1-in-kTraceEvery computes
  // records its (enqueued, batched, replied) steady-clock stamps here;
  // the driver drains them each monitor tick and folds them into
  // tracer StageTraces under the same actor.request.* names the
  // Python pool emits (runtime/native.py NativeTelemetryFolder).
  std::atomic<int64_t> trace_tick{0};
  std::mutex trace_mu;
  std::vector<std::array<double, 3>> trace_spans;  // guarded-by: trace_mu
};

class ClosedBatchingQueue : public std::runtime_error {
 public:
  using std::runtime_error::runtime_error;
};
class QueueStopped : public std::runtime_error {
 public:
  using std::runtime_error::runtime_error;
};
class AsyncError : public std::runtime_error {
 public:
  using std::runtime_error::runtime_error;
};
// The typed shed reply (ISSUE 14; Python twin:
// torchbeast_tpu/runtime/errors.ShedError). Derives from AsyncError so
// a catch site that only knows the base still treats a shed as an
// inference-side condition, but the actor pool catches EXACTLY this
// type and re-submits the same env step after backoff — a shed is flow
// control, never a retired actor or a lost rollout.
class ShedError : public AsyncError {
 public:
  using AsyncError::AsyncError;
};

// Concatenate structurally-equal nests leaf-wise along batch_dim.
inline ArrayNest batch_nests(const std::vector<ArrayNest>& nests,
                             int64_t batch_dim) {
  return Nest<Array>::zip(nests).map(
      [batch_dim](const std::vector<Array>& leaves) {
        return concatenate(leaves, batch_dim);
      });
}

// What the actor pool's request path sees (ISSUE 16): a DynamicBatcher,
// or a routing facade over several (csrc/routing.h SliceRouter /
// ReplicaRouter). The pool only ever computes, polls closure, and
// closes — keeping the seam this narrow is what lets the routers drop
// in without the pool knowing the serving topology.
class InferenceClient {
 public:
  virtual ~InferenceClient() = default;
  virtual ArrayNest compute(ArrayNest inputs, int64_t timeout_s = 600) = 0;
  virtual int64_t size() const = 0;
  virtual bool is_closed() const = 0;
  virtual void close() = 0;
};

template <typename Payload>
class BatchingQueue {
 public:
  struct Item {
    ArrayNest inputs;
    Payload payload;
    int64_t rows;
  };

  BatchingQueue(int64_t batch_dim, int64_t min_batch_size,
                int64_t max_batch_size, std::optional<int64_t> timeout_ms,
                std::optional<int64_t> max_queue_size, bool check_inputs)
      : batch_dim_(batch_dim),
        min_(min_batch_size),
        max_(max_batch_size),
        timeout_ms_(timeout_ms),
        max_queue_(max_queue_size),
        check_inputs_(check_inputs) {
    if (min_ < 1) throw std::invalid_argument("Min batch size must be >= 1");
    if (max_ < min_)
      throw std::invalid_argument("Max batch size must be >= min batch size");
    if (max_queue_ && *max_queue_ < 1)
      throw std::invalid_argument("Max queue size must be >= 1");
  }

  int64_t size() const {
    std::unique_lock<std::mutex> lock(mu_);
    return static_cast<int64_t>(deque_.size());
  }

  bool is_closed() const {
    std::unique_lock<std::mutex> lock(mu_);
    return closed_;
  }

  void enqueue(ArrayNest inputs, Payload payload) {
    int64_t rows = 1;
    if (check_inputs_) {
      bool any = false;
      inputs.for_each([&](const Array& a) {
        if (a.ndim() <= batch_dim_)
          throw std::invalid_argument(
              "Enqueued array has too few dims for batch_dim");
        any = true;
      });
      if (!any)
        throw std::invalid_argument("Cannot enqueue empty vector of arrays");
    }
    if (!inputs.empty()) rows = inputs.front().dim(batch_dim_);

    std::unique_lock<std::mutex> lock(mu_);
    if (closed_) throw ClosedBatchingQueue("Enqueue to closed batching queue");
    while (max_queue_ && static_cast<int64_t>(deque_.size()) >= *max_queue_) {
      can_enqueue_.wait(lock);
      if (closed_)
        throw ClosedBatchingQueue("Enqueue to closed batching queue");
    }
    deque_.push_back(Item{std::move(inputs), std::move(payload), rows});
    ++num_enqueued_;
    can_dequeue_.notify_one();
  }

  // Blocks for >= min rows (or any after timeout). Throws QueueStopped when
  // closed and drained.
  std::pair<ArrayNest, std::vector<Payload>> dequeue_many() {
    auto t0 = std::chrono::steady_clock::now();
    std::vector<Item> items;
    {
      std::unique_lock<std::mutex> lock(mu_);
      // The timeout bounds the wait for a FULL minimum batch; an empty
      // queue always blocks untimed for the first item — wait_for in a
      // loop with an expired (e.g. zero) timeout would busy-spin.
      std::optional<std::chrono::steady_clock::time_point> deadline;
      if (timeout_ms_)
        deadline = std::chrono::steady_clock::now() +
                   std::chrono::milliseconds(*timeout_ms_);
      while (true) {
        int64_t rows = 0;
        for (const Item& it : deque_) rows += it.rows;
        if (rows >= min_) break;
        if (closed_) throw QueueStopped("queue closed");
        if (deadline && std::chrono::steady_clock::now() >= *deadline) {
          if (!deque_.empty()) break;
          can_dequeue_.wait(lock);
        } else if (deadline) {
#if defined(__SANITIZE_THREAD__)
          // TSan builds only: a steady_clock wait_until lowers to
          // pthread_cond_clockwait (glibc >= 2.30), which GCC 10's
          // libtsan does not intercept — TSan then never sees the mutex
          // released inside the wait and reports bogus double-locks/
          // races on every subsequent queue op (observed: ~90 reports
          // on the dynamic-batcher suite). Wait against a system-clock
          // deadline there (pthread_cond_timedwait, which TSan models);
          // the steady deadline above stays authoritative, and the
          // wall-clock jump sensitivity this introduces is acceptable
          // in a sanitizer lane.
          can_dequeue_.wait_until(
              lock, std::chrono::system_clock::now() +
                        (*deadline - std::chrono::steady_clock::now()));
#else
          can_dequeue_.wait_until(lock, *deadline);
#endif
        } else {
          can_dequeue_.wait(lock);
        }
      }
      items.push_back(std::move(deque_.front()));
      deque_.pop_front();
      int64_t rows = items.front().rows;
      while (!deque_.empty() && rows + deque_.front().rows <= max_) {
        rows += deque_.front().rows;
        items.push_back(std::move(deque_.front()));
        deque_.pop_front();
      }
      can_enqueue_.notify_all();
    }
    dequeue_wait_s_.observe(
        std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
            .count());
    std::vector<ArrayNest> inputs;
    std::vector<Payload> payloads;
    inputs.reserve(items.size());
    payloads.reserve(items.size());
    int64_t total_rows = 0;
    for (Item& it : items) {
      total_rows += it.rows;
      inputs.push_back(std::move(it.inputs));
      payloads.push_back(std::move(it.payload));
    }
    batch_size_.observe(static_cast<double>(total_rows));
    return {batch_nests(inputs, batch_dim_), std::move(payloads)};
  }

  // One raw (inputs, rows) item in FIFO order, blocking until an item
  // arrives; QueueStopped once the queue is closed and drained. The
  // BatchArena's intake (runtime/queues.py dequeue_item): assembly
  // happens by write-through column copy straight into the host arena,
  // so this path skips dequeue_many's min-batch wait and batch forming.
  std::pair<ArrayNest, int64_t> dequeue_item() {
    auto t0 = std::chrono::steady_clock::now();
    std::unique_lock<std::mutex> lock(mu_);
    while (deque_.empty()) {
      if (closed_) throw QueueStopped("queue closed");
      can_dequeue_.wait(lock);
    }
    Item item = std::move(deque_.front());
    deque_.pop_front();
    can_enqueue_.notify_all();
    lock.unlock();
    dequeue_wait_s_.observe(
        std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
            .count());
    return {std::move(item.inputs), item.rows};
  }

  // Non-blocking drain of whole items that fit under `max_rows` — the
  // continuous-batching top-up (ISSUE 16): a forming dispatch window
  // rolls in requests that arrived after dequeue_many released the
  // lock. Returns possibly-empty; never waits.
  std::vector<Item> try_dequeue_upto(int64_t max_rows) {
    std::vector<Item> items;
    std::unique_lock<std::mutex> lock(mu_);
    int64_t rows = 0;
    while (!deque_.empty() && rows + deque_.front().rows <= max_rows) {
      rows += deque_.front().rows;
      items.push_back(std::move(deque_.front()));
      deque_.pop_front();
    }
    if (!items.empty()) can_enqueue_.notify_all();
    return items;
  }

  int64_t num_enqueued() const {
    std::unique_lock<std::mutex> lock(mu_);
    return num_enqueued_;
  }

  // Interval telemetry for the Python driver's native fold.
  HistSnapshot dequeue_wait_snapshot(bool reset) {
    return dequeue_wait_s_.snapshot(reset);
  }
  HistSnapshot batch_size_snapshot(bool reset) {
    return batch_size_.snapshot(reset);
  }

  // Returns leftover items; their payloads, so callers can fail promises.
  std::vector<Payload> close() {
    std::unique_lock<std::mutex> lock(mu_);
    if (closed_) throw std::runtime_error("Queue was closed already");
    closed_ = true;
    std::vector<Payload> leftover;
    for (Item& it : deque_) leftover.push_back(std::move(it.payload));
    deque_.clear();
    can_dequeue_.notify_all();
    can_enqueue_.notify_all();
    return leftover;
  }

  int64_t batch_dim() const { return batch_dim_; }
  int64_t max_batch_size() const { return max_; }

 private:
  const int64_t batch_dim_, min_, max_;
  const std::optional<int64_t> timeout_ms_, max_queue_;
  const bool check_inputs_;

  mutable std::mutex mu_;
  std::condition_variable can_dequeue_, can_enqueue_;
  std::deque<Item> deque_;
  bool closed_ = false;
  int64_t num_enqueued_ = 0;
  HistAccum dequeue_wait_s_;
  HistAccum batch_size_;
};

class DynamicBatcher : public InferenceClient {
 public:
  struct Request {
    std::shared_ptr<std::promise<ArrayNest>> promise;
    int64_t rows;
    // Stage stamps (enqueue -> batch -> reply): set at compute(), read
    // when the batch forms and when outputs are distributed.
    std::chrono::steady_clock::time_point enqueued_at;
    // Trace sampling (ISSUE 12): this request records a full span.
    bool traced = false;
    std::chrono::steady_clock::time_point batched_at;
    // Deadline gate (ISSUE 14): absolute expiry; unset when admission
    // control is disarmed.
    std::optional<std::chrono::steady_clock::time_point> deadline;
  };

  class Batch {
   public:
    Batch(int64_t batch_dim, ArrayNest inputs, std::vector<Request> requests,
          std::shared_ptr<BatcherTelemetry> telemetry = nullptr)
        : batch_dim_(batch_dim),
          inputs_(std::move(inputs)),
          requests_(std::move(requests)),
          telemetry_(std::move(telemetry)) {}

    ~Batch() {
      if (!outputs_set_) {
        for (Request& r : requests_) {
          r.promise->set_exception(std::make_exception_ptr(
              AsyncError("Batch died before outputs were set")));
        }
      }
    }

    int64_t size() const {
      int64_t n = 0;
      for (const Request& r : requests_) n += r.rows;
      return n;
    }

    const ArrayNest& inputs() const { return inputs_; }

    void set_slo_target(std::optional<double> target_s) {
      slo_target_s_ = target_s;
    }

    void set_outputs(const ArrayNest& outputs) {
      if (outputs_set_) throw std::runtime_error("set_outputs called twice");
      int64_t expected = size();
      bool any = false;
      outputs.for_each([&](const Array& a) {
        if (a.ndim() <= batch_dim_)
          throw std::invalid_argument("output has too few dims");
        if (a.dim(batch_dim_) != expected)
          throw std::invalid_argument("output batch size mismatch");
        any = true;
      });
      if (!any) throw std::invalid_argument("empty output");
      outputs_set_ = true;
      auto now = std::chrono::steady_clock::now();
      int64_t offset = 0;
      for (Request& r : requests_) {
        int64_t start = offset, count = r.rows;
        ArrayNest mine = outputs.map([&](const Array& a) {
          return slice(a, batch_dim_, start, count);
        });
        if (telemetry_) {
          double rtt =
              std::chrono::duration<double>(now - r.enqueued_at).count();
          telemetry_->request_rtt_s.observe(rtt);
          // SLO breach accounting (ISSUE 14): the C++ pool has no
          // Python-side request path, so served-RTT-over-target is
          // counted here and folded into slo.rtt_breaches.
          if (slo_target_s_ && rtt > *slo_target_s_)
            telemetry_->slo_breaches.fetch_add(1);
          if (r.traced) {
            auto to_s = [](std::chrono::steady_clock::time_point tp) {
              return std::chrono::duration<double>(tp.time_since_epoch())
                  .count();
            };
            std::lock_guard<std::mutex> lock(telemetry_->trace_mu);
            if (telemetry_->trace_spans.size() < kTraceSpanCap)
              telemetry_->trace_spans.push_back(
                  {to_s(r.enqueued_at), to_s(r.batched_at), to_s(now)});
          }
        }
        r.promise->set_value(std::move(mine));
        offset += count;
      }
    }

    void fail(const std::string& message) {
      if (outputs_set_) return;
      outputs_set_ = true;
      for (Request& r : requests_) {
        r.promise->set_exception(
            std::make_exception_ptr(AsyncError(message)));
      }
    }

   private:
    int64_t batch_dim_;
    ArrayNest inputs_;
    std::vector<Request> requests_;
    std::shared_ptr<BatcherTelemetry> telemetry_;
    std::optional<double> slo_target_s_;
    bool outputs_set_ = false;
  };

  // Admission control (ISSUE 14): `shed_max_queue_depth` bounds the
  // queued-request count at enqueue (over it -> ShedError at the
  // caller), `deadline_ms` arms the dequeue-side expiry, and
  // `slo_target_ms` arms served-RTT breach counting. All optional —
  // disarmed, the batcher behaves exactly as before.
  //
  // `continuous` (ISSUE 16) switches the overload posture from
  // depth-gating to continuous batching: the caller passes the FALLBACK
  // hard bound as shed_max_queue_depth (a multiple of the old
  // depth-factor gate — polybeast keeps --admission_depth_factor as
  // that bound) and get_batch() rolls requests that arrive while a
  // dispatch window is forming into that window (try_dequeue_upto)
  // instead of leaving them for the next batch. Latency stays guarded
  // by the dequeue-side deadline expiry, which runs AFTER the top-up
  // merge so rolled requests face exactly the same gate — the
  // resubmitted == shed + expired audit is unchanged.
  DynamicBatcher(int64_t batch_dim, int64_t min_batch_size,
                 int64_t max_batch_size, std::optional<int64_t> timeout_ms,
                 std::optional<int64_t> shed_max_queue_depth = std::nullopt,
                 std::optional<double> deadline_ms = std::nullopt,
                 std::optional<double> slo_target_ms = std::nullopt,
                 bool continuous = false)
      : batch_dim_(batch_dim),
        queue_(batch_dim, min_batch_size, max_batch_size, timeout_ms,
               std::nullopt, /*check_inputs=*/true),
        telemetry_(std::make_shared<BatcherTelemetry>()),
        shed_max_queue_depth_(shed_max_queue_depth),
        deadline_ms_(deadline_ms),
        slo_target_ms_(slo_target_ms),
        continuous_(continuous) {
    if (shed_max_queue_depth_ && *shed_max_queue_depth_ < 1)
      throw std::invalid_argument("shed_max_queue_depth must be >= 1");
  }

  int64_t size() const override { return queue_.size(); }
  bool is_closed() const override { return queue_.is_closed(); }

  // Interval snapshot for the Python driver's native-telemetry fold.
  std::shared_ptr<BatcherTelemetry> telemetry() { return telemetry_; }

  ArrayNest compute(ArrayNest inputs,
                    int64_t timeout_s = 600 /* reference: 10 min */) override {
    int64_t rows = inputs.front().dim(batch_dim_);
    if (rows > queue_.max_batch_size())
      throw std::invalid_argument("compute() exceeds maximum_batch_size");
    // Enqueue-side admission gate (ISSUE 14): shed while the queue is
    // at the depth bound — the caller's retry path re-submits after
    // backoff. Racy-by-design against concurrent producers (the bound
    // is flow control, not an invariant); counted BEFORE the throw so
    // shed accounting is exact.
    if (shed_max_queue_depth_ && queue_.size() >= *shed_max_queue_depth_) {
      telemetry_->shed.fetch_add(1);
      throw ShedError(
          "admission gate: inference queue at its depth bound; "
          "re-submit after backoff");
    }
    // admitted counts only under an armed gate, mirroring the Python
    // AdmissionController (disarmed runs report no serving.* series).
    if (shed_max_queue_depth_ || deadline_ms_)
      telemetry_->admitted.fetch_add(1);
    Request req{std::make_shared<std::promise<ArrayNest>>(), rows,
                std::chrono::steady_clock::now()};
    if (deadline_ms_)
      req.deadline = req.enqueued_at +
                     std::chrono::microseconds(
                         static_cast<int64_t>(*deadline_ms_ * 1000.0));
    // Sampled tracing (1-in-kTraceEvery, like the Python pool): N
    // racing actors may interleave ticks, which only shifts WHICH
    // request gets traced.
    req.traced =
        (telemetry_->trace_tick.fetch_add(1) + 1) % kTraceEvery == 0;
    auto future = req.promise->get_future();
    queue_.enqueue(std::move(inputs), std::move(req));
    if (future.wait_for(std::chrono::seconds(timeout_s)) ==
        std::future_status::timeout) {
      throw std::runtime_error("Compute response not ready after timeout");
    }
    return future.get();
  }

  // Blocks; throws QueueStopped when closed.
  std::unique_ptr<Batch> get_batch() {
    while (true) {
      auto [inputs, requests] = queue_.dequeue_many();
      // Continuous batching (ISSUE 16): roll requests that landed
      // between dequeue_many's drain and now into THIS dispatch window
      // (up to max batch size) instead of parking them for the next
      // one. The merge happens BEFORE the deadline pass below, so a
      // rolled request meets the exact same expiry gate as any other.
      if (continuous_) {
        int64_t have = 0;
        for (const Request& r : requests) have += r.rows;
        int64_t room = queue_.max_batch_size() - have;
        if (room > 0) {
          auto extra = queue_.try_dequeue_upto(room);
          if (!extra.empty()) {
            std::vector<ArrayNest> pieces;
            pieces.reserve(extra.size() + 1);
            pieces.push_back(std::move(inputs));
            for (auto& it : extra) {
              pieces.push_back(std::move(it.inputs));
              requests.push_back(std::move(it.payload));
            }
            inputs = batch_nests(pieces, batch_dim_);
            telemetry_->rolled.fetch_add(
                static_cast<int64_t>(extra.size()));
          }
        }
      }
      auto now = std::chrono::steady_clock::now();
      if (deadline_ms_) {
        // Dequeue-side deadline gate (ISSUE 14): fail requests that
        // sat in the queue past their deadline with the typed
        // ShedError and cut their rows out of the batch (the queue
        // concatenated them already — re-slice the survivors). A
        // fully-expired batch loops back for the next one. First pass
        // marks expired requests (promise reset() after the exception
        // = the expiry mark); the rebuild pass only runs — and only
        // moves survivors out — when something actually expired.
        int64_t n_expired = 0;
        for (Request& r : requests) {
          telemetry_->queue_delay_s.observe(
              std::chrono::duration<double>(now - r.enqueued_at).count());
          if (r.deadline && now > *r.deadline) {
            ++n_expired;
            if (r.traced) {
              // A sampled request shed here must still land in the
              // trace export (the Python twin stamps "shed" and
              // finishes): record (enqueued, shed, shed) — the batch
              // stage shows the queue wait that killed it, the reply
              // stage is zero-length. Dropping it would blind trace
              // analysis to exactly the overload traffic the gate
              // exists to observe.
              auto to_s = [](std::chrono::steady_clock::time_point tp) {
                return std::chrono::duration<double>(tp.time_since_epoch())
                    .count();
              };
              std::lock_guard<std::mutex> lock(telemetry_->trace_mu);
              if (telemetry_->trace_spans.size() < kTraceSpanCap)
                telemetry_->trace_spans.push_back(
                    {to_s(r.enqueued_at), to_s(now), to_s(now)});
            }
            r.promise->set_exception(std::make_exception_ptr(ShedError(
                "deadline expired in queue: the reply would land past "
                "the request's deadline budget; re-submit after "
                "backoff")));
            r.promise.reset();
          }
        }
        if (n_expired > 0) {
          telemetry_->expired.fetch_add(n_expired);
          std::vector<Request> live;
          std::vector<std::pair<int64_t, int64_t>> live_spans;  // start,count
          int64_t offset = 0;
          for (Request& r : requests) {
            int64_t start = offset;
            offset += r.rows;
            if (!r.promise) continue;  // expired above
            live_spans.emplace_back(start, r.rows);
            live.push_back(std::move(r));
          }
          if (live.empty()) continue;
          inputs = inputs.map([&](const Array& a) {
            std::vector<Array> pieces;
            pieces.reserve(live_spans.size());
            for (const auto& [start, count] : live_spans)
              pieces.push_back(slice(a, batch_dim_, start, count));
            return concatenate(pieces, batch_dim_);
          });
          requests = std::move(live);
        }
      }
      // (Disarmed, queue delay == request_wait_s below; the serving.*
      // delay series only exists under an armed gate, like Python.)
      int64_t rows = 0;
      for (Request& r : requests) {
        rows += r.rows;
        r.batched_at = now;
        telemetry_->request_wait_s.observe(
            std::chrono::duration<double>(now - r.enqueued_at).count());
      }
      telemetry_->batches.fetch_add(1);
      telemetry_->rows.fetch_add(rows);
      telemetry_->batch_size.observe(static_cast<double>(rows));
      auto batch = std::make_unique<Batch>(batch_dim_, std::move(inputs),
                                           std::move(requests), telemetry_);
      if (slo_target_ms_) batch->set_slo_target(*slo_target_ms_ / 1000.0);
      return batch;
    }
  }

  void close() override {
    std::vector<Request> pending = queue_.close();
    for (Request& r : pending) {
      r.promise->set_exception(std::make_exception_ptr(
          AsyncError("Batcher closed with pending requests")));
    }
  }

 private:
  int64_t batch_dim_;
  BatchingQueue<Request> queue_;
  std::shared_ptr<BatcherTelemetry> telemetry_;
  const std::optional<int64_t> shed_max_queue_depth_;
  const std::optional<double> deadline_ms_;
  const std::optional<double> slo_target_ms_;
  const bool continuous_;
};

}  // namespace tbt
