// Assert-based tests for the native core (no gtest in this image).
// Mirrors the reference's C++ test coverage (actorpool_test.cc: queue
// construct/close/enqueue/dequeue semantics; nest_serialize_test.cc:
// codec roundtrips) plus batcher promise semantics and a threaded stress.

#include <atomic>
#include <cassert>
#include <cstdio>
#include <cstring>
#include <set>
#include <thread>
#include <vector>

#include <sys/socket.h>

#include "array.h"
#include "client.h"
#include "env_server.h"
#include "nest.h"
#include "queues.h"
#include "routing.h"
#include "shm.h"
#include "wire.h"

using namespace tbt;

#define CHECK(cond)                                                         \
  do {                                                                      \
    if (!(cond)) {                                                          \
      std::fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,         \
                   __LINE__, #cond);                                        \
      std::abort();                                                         \
    }                                                                       \
  } while (0)

#define CHECK_THROWS(expr, ExceptionType)                                   \
  do {                                                                      \
    bool caught = false;                                                    \
    try {                                                                   \
      expr;                                                                 \
    } catch (const ExceptionType&) {                                        \
      caught = true;                                                        \
    }                                                                       \
    CHECK(caught);                                                          \
  } while (0)

static Array make_array(DType dtype, std::vector<int64_t> shape,
                        int64_t fill) {
  Array a(dtype, shape);
  if (dtype == DType::kI64) {
    int64_t* p = reinterpret_cast<int64_t*>(a.mutable_data());
    for (int64_t i = 0; i < a.numel(); ++i) p[i] = fill;
  } else if (dtype == DType::kF32) {
    float* p = reinterpret_cast<float*>(a.mutable_data());
    for (int64_t i = 0; i < a.numel(); ++i) p[i] = static_cast<float>(fill);
  } else {
    std::memset(a.mutable_data(), static_cast<int>(fill), a.nbytes());
  }
  return a;
}

static void test_array_concat_slice() {
  Array a = make_array(DType::kI64, {1, 2}, 1);
  Array b = make_array(DType::kI64, {1, 2}, 2);
  Array cat0 = concatenate({a, b}, 0);
  CHECK(cat0.shape() == (std::vector<int64_t>{2, 2}));
  const int64_t* p = reinterpret_cast<const int64_t*>(cat0.data());
  CHECK(p[0] == 1 && p[1] == 1 && p[2] == 2 && p[3] == 2);

  Array cat1 = concatenate({a, b}, 1);
  CHECK(cat1.shape() == (std::vector<int64_t>{1, 4}));
  p = reinterpret_cast<const int64_t*>(cat1.data());
  CHECK(p[0] == 1 && p[1] == 1 && p[2] == 2 && p[3] == 2);

  Array s = slice(cat0, 0, 1, 1);
  CHECK(s.shape() == (std::vector<int64_t>{1, 2}));
  CHECK(reinterpret_cast<const int64_t*>(s.data())[0] == 2);

  Array s1 = slice(cat1, 1, 1, 2);
  CHECK(s1.shape() == (std::vector<int64_t>{1, 2}));
  p = reinterpret_cast<const int64_t*>(s1.data());
  CHECK(p[0] == 1 && p[1] == 2);

  CHECK_THROWS(concatenate({a, make_array(DType::kF32, {1, 2}, 0)}, 0),
               std::invalid_argument);
  std::printf("array concat/slice ok\n");
}

static void test_nest_ops() {
  ArrayNest::Dict d;
  d.emplace("x", ArrayNest(make_array(DType::kI64, {2}, 5)));
  d.emplace("y", ArrayNest(ArrayNest::List{
                     ArrayNest(make_array(DType::kI64, {1}, 7))}));
  ArrayNest nest(d);

  CHECK(!nest.empty());
  CHECK(nest.front().dim(0) == 2);
  CHECK(nest.flatten().size() == 2);

  ArrayNest doubled = nest.map([](const Array& a) {
    Array out = a.clone();
    int64_t* p = reinterpret_cast<int64_t*>(out.mutable_data());
    for (int64_t i = 0; i < out.numel(); ++i) p[i] *= 2;
    return out;
  });
  CHECK(reinterpret_cast<const int64_t*>(doubled.front().data())[0] == 10);

  // pack_as roundtrip
  auto flat = doubled.flatten();
  ArrayNest packed = nest.pack_as(flat);
  CHECK(reinterpret_cast<const int64_t*>(packed.front().data())[0] == 10);
  CHECK_THROWS(nest.pack_as(std::vector<Array>{}), std::invalid_argument);

  // map2 structure mismatch
  CHECK_THROWS(
      ArrayNest::map2([](const Array& a, const Array&) { return a; }, nest,
                      ArrayNest(make_array(DType::kI64, {1}, 0))),
      std::invalid_argument);
  std::printf("nest ops ok\n");
}

static void test_wire_roundtrip() {
  wire::ValueNest::Dict msg;
  msg.emplace("type", wire::ValueNest(wire::Value::of_string("step")));
  msg.emplace("reward", wire::ValueNest(wire::Value::of(
                            make_array(DType::kF32, {}, 3))));
  msg.emplace("frame", wire::ValueNest(wire::Value::of(
                           make_array(DType::kU8, {2, 2, 1}, 9))));
  wire::ValueNest::List lst;
  lst.push_back(wire::ValueNest(wire::Value::of_int(-42)));
  lst.push_back(wire::ValueNest(wire::Value{}));
  msg.emplace("extras", wire::ValueNest(std::move(lst)));

  std::vector<uint8_t> framed = wire::encode(wire::ValueNest(msg));
  uint32_t length = framed[0] | (framed[1] << 8) | (framed[2] << 16) |
                    (framed[3] << 24);
  CHECK(length == framed.size() - 4);

  auto payload = std::make_shared<std::vector<uint8_t>>(framed.begin() + 4,
                                                        framed.end());
  wire::ValueNest out =
      wire::decode(payload->data(), payload->size(), payload);
  const auto& dict = out.dict();
  CHECK(dict.at("type").leaf().s == "step");
  const Array& frame = dict.at("frame").leaf().array;
  CHECK(frame.shape() == (std::vector<int64_t>{2, 2, 1}));
  CHECK(frame.data()[0] == 9);
  const Array& reward = dict.at("reward").leaf().array;
  CHECK(reward.ndim() == 0);  // 0-d survives (the Python-side regression)
  CHECK(dict.at("extras").list()[0].leaf().i == -42);
  CHECK(dict.at("extras").list()[1].leaf().kind ==
        wire::Value::Kind::kNone);

  // Truncated payload raises.
  CHECK_THROWS(wire::decode(payload->data(), payload->size() - 1, payload),
               wire::WireError);
  std::printf("wire roundtrip ok\n");
}

// Adversarial frames: the decoder sees untrusted bytes straight off a TCP
// socket, so dimension fields that would wrap the size computation must be
// rejected, not used to index out of bounds.
static void test_wire_malformed() {
  auto decode_bytes = [](std::vector<uint8_t> bytes) {
    auto payload = std::make_shared<std::vector<uint8_t>>(std::move(bytes));
    return wire::decode(payload->data(), payload->size(), payload);
  };
  auto put_i64 = [](std::vector<uint8_t>* buf, int64_t x) {
    for (int i = 0; i < 8; ++i)
      buf->push_back((static_cast<uint64_t>(x) >> (8 * i)) & 0xff);
  };

  // Negative dim: i64 dims are attacker-controlled.
  {
    std::vector<uint8_t> b{wire::kTagArray, 4 /* f32 */, 1 /* ndim */};
    put_i64(&b, -8);
    CHECK_THROWS(decode_bytes(b), wire::WireError);
  }
  // Two dims whose product wraps size_t back to something tiny.
  {
    std::vector<uint8_t> b{wire::kTagArray, 4, 2};
    put_i64(&b, int64_t{1} << 62);
    put_i64(&b, int64_t{1} << 62);
    b.push_back(0);  // a little "payload" so a wrapped size could "fit"
    CHECK_THROWS(decode_bytes(b), wire::WireError);
  }
  // Single dim so large that numel*itemsize overflows.
  {
    std::vector<uint8_t> b{wire::kTagArray, 5 /* f64 */, 1};
    put_i64(&b, int64_t{1} << 61);
    CHECK_THROWS(decode_bytes(b), wire::WireError);
  }
  // Unknown dtype byte.
  {
    std::vector<uint8_t> b{wire::kTagArray, 0x7f, 0};
    CHECK_THROWS(decode_bytes(b), std::invalid_argument);
  }
  // Huge string length must not wrap the bounds check.
  {
    std::vector<uint8_t> b{wire::kTagString, 0xff, 0xff, 0xff, 0xff};
    CHECK_THROWS(decode_bytes(b), wire::WireError);
  }
  // Huge list/dict counts must be rejected before any allocation.
  {
    std::vector<uint8_t> b{wire::kTagList, 0xff, 0xff, 0xff, 0xff};
    CHECK_THROWS(decode_bytes(b), wire::WireError);
  }
  {
    std::vector<uint8_t> b{wire::kTagDict, 0xff, 0xff, 0xff, 0xff};
    CHECK_THROWS(decode_bytes(b), wire::WireError);
  }
  // Zero-sized dims stay legal: shape (0, 5) decodes to an empty array,
  // and a LATER zero dim must not demand bytes for the earlier dims.
  {
    std::vector<uint8_t> b{wire::kTagArray, 4, 2};
    put_i64(&b, 0);
    put_i64(&b, 5);
    wire::ValueNest out = decode_bytes(b);
    CHECK(out.leaf().array.shape() == (std::vector<int64_t>{0, 5}));
  }
  {
    std::vector<uint8_t> b{wire::kTagArray, 4, 2};
    put_i64(&b, 5);
    put_i64(&b, 0);
    wire::ValueNest out = decode_bytes(b);
    CHECK(out.leaf().array.shape() == (std::vector<int64_t>{5, 0}));
  }
  std::printf("wire malformed-frame rejection ok\n");
}

static void test_batching_queue() {
  CHECK_THROWS(BatchingQueue<int>(0, 0, 1, {}, {}, true),
               std::invalid_argument);
  CHECK_THROWS(BatchingQueue<int>(0, 4, 2, {}, {}, true),
               std::invalid_argument);

  BatchingQueue<int> queue(0, 3, 8, {}, {}, true);
  for (int i = 0; i < 3; ++i) {
    queue.enqueue(ArrayNest(make_array(DType::kI64, {1, 2}, i)), i);
  }
  auto [batch, payloads] = queue.dequeue_many();
  CHECK(batch.front().shape() == (std::vector<int64_t>{3, 2}));
  CHECK(payloads == (std::vector<int>{0, 1, 2}));

  queue.close();
  CHECK_THROWS(queue.enqueue(ArrayNest(make_array(DType::kI64, {1}, 0)), 0),
               ClosedBatchingQueue);
  CHECK_THROWS(queue.dequeue_many(), QueueStopped);
  CHECK_THROWS(queue.close(), std::runtime_error);
  std::printf("batching queue ok\n");
}

// timeout_ms=0: an immediate timeout returns whatever rows exist, and an
// EMPTY queue must block idle for the first item instead of busy-spinning
// wait_for(0) in a loop (regression: pegged a core until an enqueue).
static void test_batching_queue_timeout_zero() {
  {
    BatchingQueue<int> queue(0, 4, 8, int64_t{0}, {}, true);
    queue.enqueue(ArrayNest(make_array(DType::kI64, {1, 2}, 7)), 7);
    auto [batch, payloads] = queue.dequeue_many();  // partial, no wait
    CHECK(payloads == (std::vector<int>{7}));
  }
  {
    BatchingQueue<int> queue(0, 4, 8, int64_t{0}, {}, true);
    timespec cpu0{}, cpu1{};
    std::thread consumer([&] {
      clock_gettime(CLOCK_THREAD_CPUTIME_ID, &cpu0);
      auto [batch, payloads] = queue.dequeue_many();
      clock_gettime(CLOCK_THREAD_CPUTIME_ID, &cpu1);
      CHECK(payloads == (std::vector<int>{1}));
    });
    std::this_thread::sleep_for(std::chrono::milliseconds(200));
    queue.enqueue(ArrayNest(make_array(DType::kI64, {1, 2}, 1)), 1);
    consumer.join();
    double cpu_ms = (cpu1.tv_sec - cpu0.tv_sec) * 1e3 +
                    (cpu1.tv_nsec - cpu0.tv_nsec) / 1e6;
    // 200ms wall blocked on an empty queue must cost ~0 CPU; a busy-spin
    // burns the full 200ms.
    CHECK(cpu_ms < 100.0);
  }
  std::printf("batching queue timeout-zero ok\n");
}

static void test_queue_stress() {
  BatchingQueue<int64_t> queue(0, 1, 16, {}, {}, true);
  constexpr int kProducers = 8, kItems = 200;
  std::vector<std::thread> producers;
  std::atomic<int64_t> total{0};
  std::set<int64_t> seen;
  std::mutex seen_mu;

  std::vector<std::thread> consumers;
  for (int c = 0; c < 4; ++c) {
    consumers.emplace_back([&] {
      while (true) {
        try {
          auto [batch, payloads] = queue.dequeue_many();
          std::lock_guard<std::mutex> lock(seen_mu);
          for (int64_t p : payloads) seen.insert(p);
          total += static_cast<int64_t>(payloads.size());
        } catch (const QueueStopped&) {
          return;
        }
      }
    });
  }
  for (int p = 0; p < kProducers; ++p) {
    producers.emplace_back([&queue, p] {
      for (int i = 0; i < kItems; ++i) {
        queue.enqueue(ArrayNest(make_array(DType::kI64, {1}, i)),
                      static_cast<int64_t>(p) * kItems + i);
      }
    });
  }
  for (auto& t : producers) t.join();
  while (queue.size() > 0)
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  queue.close();
  for (auto& t : consumers) t.join();
  CHECK(total == kProducers * kItems);
  CHECK(seen.size() == kProducers * kItems);
  std::printf("queue stress ok (%lld items)\n",
              static_cast<long long>(total.load()));
}

static void test_dynamic_batcher() {
  DynamicBatcher batcher(/*batch_dim=*/0, 1, 64, /*timeout_ms=*/20);

  std::thread producer([&batcher] {
    ArrayNest out = batcher.compute(ArrayNest(make_array(DType::kI64, {1, 2}, 3)));
    const Array& a = out.front();
    CHECK(a.shape() == (std::vector<int64_t>{1, 2}));
    CHECK(reinterpret_cast<const int64_t*>(a.data())[0] == 6);
  });

  auto batch = batcher.get_batch();
  CHECK(batch->size() == 1);
  ArrayNest outputs = batch->inputs().map([](const Array& a) {
    Array out = a.clone();
    int64_t* p = reinterpret_cast<int64_t*>(out.mutable_data());
    for (int64_t i = 0; i < out.numel(); ++i) p[i] *= 2;
    return out;
  });
  batch->set_outputs(outputs);
  CHECK_THROWS(batch->set_outputs(outputs), std::runtime_error);
  producer.join();

  // Dropped batch breaks the promise.
  std::thread victim([&batcher] {
    CHECK_THROWS(
        batcher.compute(ArrayNest(make_array(DType::kI64, {1, 1}, 0))),
        AsyncError);
  });
  batcher.get_batch().reset();  // drop without outputs
  victim.join();

  // close() wakes pending compute callers.
  std::thread pending([&batcher] {
    CHECK_THROWS(
        batcher.compute(ArrayNest(make_array(DType::kI64, {1, 1}, 0))),
        AsyncError);
  });
  std::this_thread::sleep_for(std::chrono::milliseconds(50));
  batcher.close();
  pending.join();
  std::printf("dynamic batcher ok\n");
}


// Raw-item FIFO intake (the BatchArena path: --superstep_k native).
static void test_batching_queue_dequeue_item() {
  BatchingQueue<int> queue(1, 1, 8, {}, {}, true);
  for (int i = 0; i < 3; ++i) {
    queue.enqueue(ArrayNest(make_array(DType::kI64, {2, 1}, i)), i);
  }
  for (int i = 0; i < 3; ++i) {
    auto [inputs, rows] = queue.dequeue_item();
    CHECK(rows == 1);  // rows along batch_dim=1
    CHECK(reinterpret_cast<const int64_t*>(inputs.front().data())[0] == i);
  }
  queue.close();
  CHECK_THROWS(queue.dequeue_item(), QueueStopped);
  std::printf("batching queue dequeue_item ok\n");
}

// Batcher stage stamps: request_wait/rtt/batch_size accumulate and
// snapshot(reset) starts a fresh interval.
static void test_batcher_telemetry() {
  DynamicBatcher batcher(0, 1, 64, 20);
  std::thread producer([&batcher] {
    batcher.compute(ArrayNest(make_array(DType::kI64, {1, 2}, 3)));
  });
  auto batch = batcher.get_batch();
  batch->set_outputs(batch->inputs());
  producer.join();
  auto telemetry = batcher.telemetry();
  CHECK(telemetry->batches.load() == 1);
  CHECK(telemetry->rows.load() == 1);
  HistSnapshot wait = telemetry->request_wait_s.snapshot(true);
  CHECK(wait.count == 1);
  CHECK(wait.total >= 0.0);
  CHECK(telemetry->request_wait_s.snapshot(false).count == 0);  // reset
  HistSnapshot rtt = telemetry->request_rtt_s.snapshot(false);
  CHECK(rtt.count == 1);
  CHECK(rtt.total >= wait.total);
  // Bucket geometry matches telemetry/metrics.py: 1e-3 lands in bucket
  // 1 + floor(log(1e-3/1e-9)/log(2^0.25)) = 80.
  CHECK(telemetry_bucket_index(1e-3) == 80);
  CHECK(telemetry_bucket_index(0.0) == 0);
  batcher.close();
  std::printf("batcher telemetry ok\n");
}

// splitmix64 slice hash (ISSUE 16): the well-known finalizer vector for
// input 0 pins the constants; slot routing must be deterministic, in
// range, and wrap negative ids exactly like the Python `& (2**64-1)`.
static void test_routing_hash() {
  // splitmix64 state 0 -> first output (the published reference vector;
  // tests/test_native_routing.py checks the same value against
  // placement._mix64 for the cross-language bit-identity pin).
  CHECK(splitmix64(0) == 0xE220A8397B1DCDAFULL);
  for (int64_t slot = 0; slot < 1000; ++slot) {
    int64_t s = slice_for_slot(slot, 3);
    CHECK(s >= 0 && s < 3);
    CHECK(s == slice_for_slot(slot, 3));  // stable
  }
  // Negative ids wrap through uint64, not UB.
  CHECK(slice_for_slot(-1, 5) ==
        static_cast<int64_t>(splitmix64(~uint64_t{0}) % 5));
  CHECK_THROWS(slice_for_slot(0, 0), std::invalid_argument);
  // All slices earn traffic over a modest slot range (the hash is a
  // finalizer, not a permutation — but 256 slots over 4 slices missing
  // one entirely would mean a broken constant).
  std::set<int64_t> hit;
  for (int64_t slot = 0; slot < 256; ++slot) hit.insert(slice_for_slot(slot, 4));
  CHECK(hit.size() == 4);
  std::printf("routing hash ok\n");
}

// SliceRouter: slot-framed requests land on the hash-assigned slice's
// batcher (same reply identity the Python router guarantees); slot-less
// requests round-robin; counters and close semantics match.
static void test_slice_router() {
  auto b0 = std::make_shared<DynamicBatcher>(0, 1, 64, 20);
  auto b1 = std::make_shared<DynamicBatcher>(0, 1, 64, 20);
  SliceRouter router({b0, b1});
  CHECK(router.n_slices() == 2);

  // Each slice's consumer echoes inputs with the slice index added, so
  // a reply proves which batcher served it.
  std::atomic<bool> stop{false};
  auto consumer = [&stop](std::shared_ptr<DynamicBatcher> b, int64_t tag) {
    while (true) {
      try {
        auto batch = b->get_batch();
        ArrayNest out = batch->inputs().dict().at("env").map(
            [tag](const Array& a) {
              Array o = a.clone();
              int64_t* p = reinterpret_cast<int64_t*>(o.mutable_data());
              for (int64_t i = 0; i < o.numel(); ++i) p[i] += tag;
              return o;
            });
        ArrayNest::Dict reply;
        reply.emplace("outputs", std::move(out));
        batch->set_outputs(ArrayNest(std::move(reply)));
      } catch (const QueueStopped&) {
        return;
      }
    }
    (void)stop;
  };
  std::thread c0(consumer, b0, 1000);
  std::thread c1(consumer, b1, 2000);

  constexpr int kSlots = 16;
  std::vector<std::thread> producers;
  for (int slot = 0; slot < kSlots; ++slot) {
    producers.emplace_back([&router, slot] {
      ArrayNest::Dict inputs;
      inputs.emplace("env",
                     ArrayNest(make_array(DType::kI64, {1, 1}, slot)));
      Array slot_arr(DType::kI32, {1, 1});
      *reinterpret_cast<int32_t*>(slot_arr.mutable_data()) =
          static_cast<int32_t>(slot);
      inputs.emplace("slot", ArrayNest(std::move(slot_arr)));
      ArrayNest out = router.compute(ArrayNest(std::move(inputs)));
      int64_t value = *reinterpret_cast<const int64_t*>(
          out.dict().at("outputs").front().data());
      int64_t expect_tag = slice_for_slot(slot, 2) == 0 ? 1000 : 2000;
      CHECK(value == slot + expect_tag);
    });
  }
  for (auto& t : producers) t.join();

  std::vector<int64_t> counts = router.request_counts();
  CHECK(counts.size() == 2);
  CHECK(counts[0] + counts[1] == kSlots);
  int64_t expect0 = 0;
  for (int slot = 0; slot < kSlots; ++slot)
    if (slice_for_slot(slot, 2) == 0) ++expect0;
  CHECK(counts[0] == expect0);

  // Slot-less requests round-robin across both slices.
  std::vector<std::thread> rr;
  for (int i = 0; i < 4; ++i) {
    rr.emplace_back([&router] {
      ArrayNest::Dict inputs;
      inputs.emplace("env", ArrayNest(make_array(DType::kI64, {1, 1}, 7)));
      router.compute(ArrayNest(std::move(inputs)));
    });
  }
  for (auto& t : rr) t.join();
  counts = router.request_counts();
  CHECK(counts[0] + counts[1] == kSlots + 4);

  CHECK(router.size() == 0);
  CHECK(!router.is_closed());
  router.close();
  CHECK(router.is_closed());
  router.close();  // second close swallows (driver closers also close slices)
  c0.join();
  c1.join();
  std::printf("slice router ok\n");
}

// ReplicaRouter: serving flag routes replica-first, falls back to
// central on replica failure/closure, propagates sheds, and counts each
// request in exactly one series.
static void test_replica_router() {
  auto central = std::make_shared<DynamicBatcher>(0, 1, 64, 20);
  auto replica = std::make_shared<DynamicBatcher>(0, 1, 64, 20);
  auto router = std::make_shared<ReplicaRouter>(central, replica);

  auto serve_one = [](std::shared_ptr<DynamicBatcher> b, int64_t tag) {
    auto batch = b->get_batch();
    ArrayNest out = batch->inputs().map([tag](const Array& a) {
      Array o = a.clone();
      int64_t* p = reinterpret_cast<int64_t*>(o.mutable_data());
      for (int64_t i = 0; i < o.numel(); ++i) p[i] += tag;
      return o;
    });
    batch->set_outputs(out);
  };

  // Degraded (flag down, the boot state): requests go central.
  CHECK(!router->serving());
  std::thread p1([&router] {
    ArrayNest out =
        router->compute(ArrayNest(make_array(DType::kI64, {1, 1}, 1)));
    CHECK(*reinterpret_cast<const int64_t*>(out.front().data()) == 101);
  });
  serve_one(central, 100);
  p1.join();
  CHECK(router->central_requests() == 1);
  CHECK(router->replica_requests() == 0);

  // Healthy: requests go replica.
  router->set_serving(true);
  std::thread p2([&router] {
    ArrayNest out =
        router->compute(ArrayNest(make_array(DType::kI64, {1, 1}, 2)));
    CHECK(*reinterpret_cast<const int64_t*>(out.front().data()) == 202);
  });
  serve_one(replica, 200);
  p2.join();
  CHECK(router->replica_requests() == 1);

  // Replica-side serving failure (dropped batch -> AsyncError): the
  // request falls back to central and lands in ONE series.
  std::thread p3([&router] {
    ArrayNest out =
        router->compute(ArrayNest(make_array(DType::kI64, {1, 1}, 3)));
    CHECK(*reinterpret_cast<const int64_t*>(out.front().data()) == 103);
  });
  replica->get_batch().reset();  // drop without outputs -> AsyncError
  serve_one(central, 100);
  p3.join();
  CHECK(router->replica_requests() == 1);
  CHECK(router->central_requests() == 2);

  // A closed replica with the flag still up also falls back.
  replica->close();
  std::thread p4([&router] {
    ArrayNest out =
        router->compute(ArrayNest(make_array(DType::kI64, {1, 1}, 4)));
    CHECK(*reinterpret_cast<const int64_t*>(out.front().data()) == 104);
  });
  serve_one(central, 100);
  p4.join();
  CHECK(router->central_requests() == 3);

  CHECK(!router->is_closed());  // central still open
  router->close();  // replica already closed: swallowed
  CHECK(router->is_closed());
  std::printf("replica router ok\n");
}

// Replica sheds propagate to the caller (the actor's retry contract)
// instead of silently falling back — central fallback on a shed would
// defeat the admission gate exactly when it matters.
static void test_replica_router_shed() {
  auto central = std::make_shared<DynamicBatcher>(0, 1, 64, 20);
  auto replica = std::make_shared<DynamicBatcher>(
      0, 1, 64, 20, /*shed_max_queue_depth=*/1);
  ReplicaRouter router(central, replica);
  router.set_serving(true);
  // Fill the replica queue to its bound, then the next compute sheds.
  std::thread filler([&replica] {
    CHECK_THROWS(
        replica->compute(ArrayNest(make_array(DType::kI64, {1, 1}, 0)), 1),
        std::runtime_error);  // compute timeout — nobody serves it
  });
  while (replica->size() < 1)
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  CHECK_THROWS(
      router.compute(ArrayNest(make_array(DType::kI64, {1, 1}, 1))),
      ShedError);
  CHECK(router.central_requests() == 0);
  filler.join();
  replica->close();
  central->close();
  std::printf("replica router shed ok\n");
}

// try_dequeue_upto: non-blocking, row-budgeted, FIFO.
static void test_try_dequeue_upto() {
  BatchingQueue<int> queue(0, 1, 8, {}, {}, true);
  for (int i = 0; i < 3; ++i)
    queue.enqueue(ArrayNest(make_array(DType::kI64, {1, 1}, i)), i);
  auto two = queue.try_dequeue_upto(2);
  CHECK(two.size() == 2);
  CHECK(two[0].payload == 0 && two[1].payload == 1);
  auto rest = queue.try_dequeue_upto(10);
  CHECK(rest.size() == 1 && rest[0].payload == 2);
  CHECK(queue.try_dequeue_upto(5).empty());  // empty: returns, never waits
  queue.close();
  std::printf("try_dequeue_upto ok\n");
}

// Continuous batching (ISSUE 16): under producer pressure every request
// is served or shed/expired EXACTLY — resubmitted == shed + expired —
// and the top-up path (rolled) keeps admitted requests flowing.
static void test_continuous_batcher() {
  DynamicBatcher batcher(0, 1, 4, /*timeout_ms=*/5,
                         /*shed_max_queue_depth=*/4,
                         /*deadline_ms=*/50.0,
                         /*slo_target_ms=*/std::nullopt,
                         /*continuous=*/true);
  constexpr int kProducers = 4, kRequests = 50;
  std::atomic<int64_t> served{0}, resubmitted{0};
  std::vector<std::thread> producers;
  for (int p = 0; p < kProducers; ++p) {
    producers.emplace_back([&batcher, &served, &resubmitted] {
      for (int i = 0; i < kRequests; ++i) {
        try {
          batcher.compute(ArrayNest(make_array(DType::kI64, {1, 1}, i)));
          served.fetch_add(1);
        } catch (const ShedError&) {
          // No retry here: the test counts one shed reply per request
          // so the audit below is exact without retry bookkeeping.
          resubmitted.fetch_add(1);
        }
      }
    });
  }
  std::thread consumer([&batcher] {
    while (true) {
      try {
        auto batch = batcher.get_batch();
        // A slow-ish consumer: lets the queue build so the deadline
        // gate and the top-up both see real traffic.
        std::this_thread::sleep_for(std::chrono::milliseconds(2));
        batch->set_outputs(batch->inputs());
      } catch (const QueueStopped&) {
        return;
      }
    }
  });
  for (auto& t : producers) t.join();
  while (batcher.size() > 0)
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  batcher.close();
  consumer.join();
  auto telemetry = batcher.telemetry();
  int64_t shed = telemetry->shed.load();
  int64_t expired = telemetry->expired.load();
  // The exactness invariant the chaos harness audits, on the
  // continuous path: every rejected request is accounted once.
  CHECK(resubmitted.load() == shed + expired);
  CHECK(served.load() + resubmitted.load() == kProducers * kRequests);
  CHECK(telemetry->admitted.load() == served.load() + expired);
  CHECK(telemetry->rolled.load() >= 0);
  std::printf(
      "continuous batcher ok (served=%lld shed=%lld expired=%lld "
      "rolled=%lld)\n",
      static_cast<long long>(served.load()), static_cast<long long>(shed),
      static_cast<long long>(expired),
      static_cast<long long>(telemetry->rolled.load()));
}

// SPSC ring: frame roundtrip, wrap at the segment end, inline marker,
// ring-eligibility cap.
static void test_shm_ring_roundtrip() {
  shm::ShmRing ring = shm::ShmRing::create(256);
  CHECK(ring.capacity() == 256);
  CHECK(ring.max_frame_bytes() == 256 / 2 - 4);

  // Attach sees the same bytes.
  shm::ShmRing peer = shm::ShmRing::attach(ring.name());
  CHECK(peer.capacity() == 256);

  auto write = [&](const std::vector<uint8_t>& payload) {
    ring.write_frame(payload.data(), payload.size(), nullptr);
  };
  auto read_check = [&](const std::vector<uint8_t>& expected) {
    CHECK(peer.has_frame());
    shm::ShmRing::Frame f = peer.read_frame();
    CHECK(!f.is_inline);
    CHECK(f.size == expected.size());
    CHECK(std::memcmp(f.data, expected.data(), f.size) == 0);
    peer.release(f.advance);
  };

  // Enough frames to wrap several times.
  for (int round = 0; round < 40; ++round) {
    std::vector<uint8_t> payload(37 + (round % 50));
    for (size_t i = 0; i < payload.size(); ++i)
      payload[i] = static_cast<uint8_t>(round + i);
    write(payload);
    read_check(payload);
  }

  // Inline marker holds the order slot.
  std::vector<uint8_t> small{1, 2, 3};
  write(small);
  ring.write_inline_marker(nullptr);
  write(small);
  read_check(small);
  shm::ShmRing::Frame f = peer.read_frame();
  CHECK(f.is_inline);
  peer.release(f.advance);
  read_check(small);
  CHECK(!peer.has_frame());

  // Over-capacity frames are rejected outright.
  std::vector<uint8_t> huge(300);
  CHECK_THROWS(ring.write_frame(huge.data(), huge.size(), nullptr),
               wire::WireError);
  peer.close();
  ring.close();
  std::printf("shm ring roundtrip ok\n");
}

// Adaptive recheck policy (ISSUE 12): a recheck-heavy window tightens
// the bound toward the floor, quiet windows relax it to the cap, and a
// mixed window inside the hysteresis band holds it.
static void test_shm_ring_adaptive_recheck() {
  shm::AdaptiveRecheck policy;
  CHECK(policy.bound_ms() == shm::kWakeRecheckMs);
  for (int i = 0; i < shm::kRecheckWindow; ++i) policy.record(true);
  CHECK(policy.bound_ms() == shm::kWakeRecheckMs / 2);
  for (int i = 0; i < 8 * shm::kRecheckWindow; ++i) policy.record(true);
  CHECK(policy.bound_ms() == shm::kRecheckMinMs);
  for (int i = 0; i < 12 * shm::kRecheckWindow; ++i) policy.record(false);
  CHECK(policy.bound_ms() == shm::kRecheckMaxMs);
  shm::AdaptiveRecheck held;
  for (int i = 0; i < shm::kRecheckWindow; ++i)
    held.record(i < shm::kRecheckTighten - 1);
  CHECK(held.bound_ms() == shm::kWakeRecheckMs);
  std::printf("shm ring adaptive recheck ok\n");
}

// Chaos ring-poke hook (ISSUE 12): header corruption observably lands
// in the queued frame (tail-stability contract) and the reader's next
// read_frame deterministically rejects it; an empty ring reports retry.
static void test_shm_ring_corrupt() {
  shm::ShmRing ring = shm::ShmRing::create(256);
  shm::ShmRing peer = shm::ShmRing::attach(ring.name());
  CHECK(peer.corrupt_tail_frame(/*header=*/true) == 0);  // empty: retry
  std::vector<uint8_t> payload(24, 0x42);
  ring.write_frame(payload.data(), payload.size(), nullptr);
  CHECK(peer.corrupt_tail_frame(/*header=*/true) == 1);
  CHECK_THROWS(peer.read_frame(), wire::WireError);
  peer.close();
  ring.close();
  std::printf("shm ring corrupt ok\n");
}

static wire::ValueNest step_like_message(int64_t tag, int64_t frame_cells) {
  wire::ValueNest::Dict d;
  d.emplace("type", wire::ValueNest(wire::Value::of_string("step")));
  d.emplace("frame", wire::ValueNest(wire::Value::of(
                         make_array(DType::kU8, {frame_cells}, tag & 0xff))));
  d.emplace("reward", wire::ValueNest(wire::Value::of(
                          make_array(DType::kF32, {}, tag))));
  d.emplace("count", wire::ValueNest(wire::Value::of_int(tag)));
  return wire::ValueNest(std::move(d));
}

// Full transport pair over a socketpair doorbell: ordering and contents
// across ring frames AND oversized inline frames, both directions.
static void test_shm_ring_transport() {
  int fds[2];
  CHECK(::socketpair(AF_UNIX, SOCK_STREAM, 0, fds) == 0);
  // Small rings force wraps and route big frames inline.
  shm::ShmRing s2c = shm::ShmRing::create(4096);
  shm::ShmRing c2s = shm::ShmRing::create(1024);
  shm::ShmRing s2c_peer = shm::ShmRing::attach(s2c.name());
  shm::ShmRing c2s_peer = shm::ShmRing::attach(c2s.name());
  shm::ShmTransport server(fds[0], std::move(s2c), std::move(c2s));
  shm::ShmTransport client(fds[1], std::move(c2s_peer), std::move(s2c_peer));

  constexpr int kMessages = 200;
  std::thread server_thread([&server] {
    for (int i = 0; i < kMessages; ++i) {
      // Every 7th frame is bigger than the obs ring allows -> inline.
      int64_t cells = (i % 7 == 6) ? 8192 : 64 + i;
      server.send(step_like_message(i, cells));
      wire::ValueNest action = server.recv();
      CHECK(action.dict().at("action").leaf().i == i);
    }
  });
  for (int i = 0; i < kMessages; ++i) {
    wire::ValueNest step = client.recv();
    const auto& dict = step.dict();
    CHECK(dict.at("count").leaf().i == i);
    int64_t cells = (i % 7 == 6) ? 8192 : 64 + i;
    const Array& frame = dict.at("frame").leaf().array;
    CHECK(frame.numel() == cells);
    CHECK(frame.data()[0] == (i & 0xff));
    wire::ValueNest::Dict a;
    a.emplace("type", wire::ValueNest(wire::Value::of_string("action")));
    a.emplace("action", wire::ValueNest(wire::Value::of_int(i)));
    client.send(wire::ValueNest(std::move(a)));
  }
  server_thread.join();
  // EOF surfaces as SocketError once the peer closes.
  server.close();
  CHECK_THROWS(client.recv(), SocketError);
  client.close();
  std::printf("shm ring transport ok (%d messages)\n", kMessages);
}

// Threaded stress at a rate-matched cadence: the coalesced-doorbell
// waiting-flag handshake must neither deadlock nor reorder. (TSan lane:
// build_native.sh --sanitize=thread --filter=ring.)
static void test_shm_ring_stress() {
  int fds[2];
  CHECK(::socketpair(AF_UNIX, SOCK_STREAM, 0, fds) == 0);
  shm::ShmRing a = shm::ShmRing::create(2048);
  shm::ShmRing b = shm::ShmRing::create(2048);
  shm::ShmRing a_peer = shm::ShmRing::attach(a.name());
  shm::ShmRing b_peer = shm::ShmRing::attach(b.name());
  shm::ShmTransport left(fds[0], std::move(a), std::move(b));
  shm::ShmTransport right(fds[1], std::move(b_peer), std::move(a_peer));

  constexpr int kMessages = 2000;
  std::thread producer([&left] {
    for (int i = 0; i < kMessages; ++i) {
      left.send(step_like_message(i, 16 + (i % 113)));
    }
  });
  for (int i = 0; i < kMessages; ++i) {
    wire::ValueNest step = right.recv();
    CHECK(step.dict().at("count").leaf().i == i);
  }
  producer.join();
  left.close();
  right.close();
  std::printf("shm ring stress ok (%d messages)\n", kMessages);
}

void test_env_server() {
  // Counting "env" implemented as hooks: initial -> step 0; each action
  // increments by the action value. A throwing step produces an error
  // frame. stop() severs live streams mid-recv.
  std::string address = "unix:/tmp/tbt_test_env_server";
  auto factory = [] {
    auto count = std::make_shared<int64_t>(0);
    StreamHooks hooks;
    hooks.initial = [count] {
      wire::ValueNest::Dict d;
      d.emplace("type", wire::ValueNest(wire::Value::of_string("step")));
      d.emplace("count", wire::ValueNest(wire::Value::of_int(*count)));
      return wire::ValueNest(std::move(d));
    };
    hooks.step = [count](const wire::ValueNest& msg) {
      const auto& dict = msg.dict();
      int64_t action = dict.at("action").leaf().i;
      if (action < 0) throw std::runtime_error("negative action");
      *count += action;
      wire::ValueNest::Dict d;
      d.emplace("type", wire::ValueNest(wire::Value::of_string("step")));
      d.emplace("count", wire::ValueNest(wire::Value::of_int(*count)));
      return wire::ValueNest(std::move(d));
    };
    hooks.close = [] {};
    return hooks;
  };
  EnvServer server(address, factory);
  std::thread server_thread([&server] { server.run(); });

  auto send_action = [](FramedSocket& sock, int64_t a) {
    wire::ValueNest::Dict d;
    d.emplace("type", wire::ValueNest(wire::Value::of_string("action")));
    d.emplace("action", wire::ValueNest(wire::Value::of_int(a)));
    sock.send(wire::ValueNest(std::move(d)));
  };

  {
    FramedSocket sock;
    sock.connect(address, 10.0);
    wire::ValueNest initial = sock.recv();
    CHECK(initial.dict().at("count").leaf().i == 0);
    send_action(sock, 5);
    CHECK(sock.recv().dict().at("count").leaf().i == 5);
    send_action(sock, 2);
    CHECK(sock.recv().dict().at("count").leaf().i == 7);
  }
  {
    // Fresh stream gets a fresh env (count resets).
    FramedSocket sock;
    sock.connect(address, 10.0);
    CHECK(sock.recv().dict().at("count").leaf().i == 0);
    // Error path: hook throws -> error frame.
    send_action(sock, -1);
    wire::ValueNest err = sock.recv();
    CHECK(err.dict().at("type").leaf().s == "error");
    CHECK(err.dict().at("message").leaf().s.find("negative action") !=
          std::string::npos);
  }
  {
    // stop() severs a live stream blocked in recv.
    FramedSocket sock;
    sock.connect(address, 10.0);
    CHECK(sock.recv().dict().at("count").leaf().i == 0);
    std::thread stopper([&server] {
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
      server.stop();
    });
    CHECK_THROWS(sock.recv(), SocketError);
    stopper.join();
  }
  server_thread.join();
  server.join_all();
  std::printf("env server ok\n");
}

int main(int argc, char** argv) {
  // Optional substring filter (argv[1]): run only matching tests. Lets
  // the sanitizer smoke tests exercise the codec/queue paths in
  // sandboxes where the socket tests cannot run (scripts/build_native.sh
  // --sanitize=... --filter=...; tests/test_native.py uses it).
  const char* filter = argc > 1 ? argv[1] : nullptr;
  auto want = [filter](const char* name) {
    return filter == nullptr || std::strstr(name, filter) != nullptr;
  };
  int ran = 0;
  if (want("array")) { test_array_concat_slice(); ++ran; }
  if (want("nest")) { test_nest_ops(); ++ran; }
  if (want("wire_roundtrip")) { test_wire_roundtrip(); ++ran; }
  if (want("wire_malformed")) { test_wire_malformed(); ++ran; }
  if (want("batching_queue")) { test_batching_queue(); ++ran; }
  if (want("batching_queue_timeout")) { test_batching_queue_timeout_zero(); ++ran; }
  if (want("batching_queue_dequeue_item")) { test_batching_queue_dequeue_item(); ++ran; }
  if (want("queue_stress")) { test_queue_stress(); ++ran; }
  if (want("dynamic_batcher")) { test_dynamic_batcher(); ++ran; }
  if (want("batcher_telemetry")) { test_batcher_telemetry(); ++ran; }
  if (want("routing_hash")) { test_routing_hash(); ++ran; }
  if (want("routing_slice")) { test_slice_router(); ++ran; }
  if (want("routing_replica")) { test_replica_router(); ++ran; }
  if (want("routing_replica_shed")) { test_replica_router_shed(); ++ran; }
  if (want("queue_try_dequeue")) { test_try_dequeue_upto(); ++ran; }
  if (want("batcher_continuous")) { test_continuous_batcher(); ++ran; }
  if (want("shm_ring_roundtrip")) { test_shm_ring_roundtrip(); ++ran; }
  if (want("shm_ring_adaptive_recheck")) { test_shm_ring_adaptive_recheck(); ++ran; }
  if (want("shm_ring_corrupt")) { test_shm_ring_corrupt(); ++ran; }
  if (want("shm_ring_transport")) { test_shm_ring_transport(); ++ran; }
  if (want("shm_ring_stress")) { test_shm_ring_stress(); ++ran; }
  if (want("env_server")) { test_env_server(); ++ran; }
  if (ran == 0) {
    std::fprintf(stderr, "no tests match filter '%s'\n", filter);
    return 1;
  }
  if (filter == nullptr) {
    std::printf("ALL NATIVE CORE TESTS PASSED\n");
  } else {
    std::printf("%d FILTERED NATIVE CORE TESTS PASSED (filter '%s')\n",
                ran, filter);
  }
  return 0;
}
