// _tbt_core: CPython bindings for the native runtime (reference components
// N2/N9, /root/reference/nest/nest/nest_pybind.cc + src/cc/libtorchbeast.cc
// — written against the raw CPython/numpy C API since pybind11 is not in
// this image).
//
// Exposes BatchingQueue, DynamicBatcher (+Batch), ActorPool. Conversions:
//   python -> C++: dict/list/tuple -> Nest, numpy array -> Array wrapping
//     the numpy buffer zero-copy (a shared_ptr owner decrefs under the GIL)
//   C++ -> python: Array -> numpy array wrapping the C++ buffer zero-copy
//     (a capsule owner keeps the shared_ptr alive)
// All blocking calls release the GIL, so C++ actor threads and Python
// inference/learner threads interleave freely.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <numpy/arrayobject.h>

#include <memory>
#include <string>
#include <vector>

#include "actor_pool.h"
#include "queues.h"

namespace {

using tbt::Array;
using tbt::ArrayNest;
using tbt::DType;

PyObject* ClosedBatchingQueueError;
PyObject* AsyncErrorError;

// ---------------------------------------------------------------- dtypes
int dtype_to_npy(DType d) {
  switch (d) {
    case DType::kU8: return NPY_UINT8;
    case DType::kI8: return NPY_INT8;
    case DType::kI32: return NPY_INT32;
    case DType::kI64: return NPY_INT64;
    case DType::kF32: return NPY_FLOAT32;
    case DType::kF64: return NPY_FLOAT64;
    case DType::kBool: return NPY_BOOL;
    case DType::kU16: return NPY_UINT16;
    case DType::kI16: return NPY_INT16;
    case DType::kU32: return NPY_UINT32;
    case DType::kU64: return NPY_UINT64;
    case DType::kF16: return NPY_FLOAT16;
  }
  return -1;
}

bool npy_to_dtype(int npy, DType* out) {
  switch (npy) {
    case NPY_UINT8: *out = DType::kU8; return true;
    case NPY_INT8: *out = DType::kI8; return true;
    case NPY_INT32: *out = DType::kI32; return true;
    case NPY_INT64: *out = DType::kI64; return true;
    case NPY_FLOAT32: *out = DType::kF32; return true;
    case NPY_FLOAT64: *out = DType::kF64; return true;
    case NPY_BOOL: *out = DType::kBool; return true;
    case NPY_UINT16: *out = DType::kU16; return true;
    case NPY_INT16: *out = DType::kI16; return true;
    case NPY_UINT32: *out = DType::kU32; return true;
    case NPY_UINT64: *out = DType::kU64; return true;
    case NPY_FLOAT16: *out = DType::kF16; return true;
    default: return false;
  }
}

// ------------------------------------------------- python -> C++ nest
// Decref-under-GIL owner for buffers borrowed from numpy.
std::shared_ptr<void> py_owner(PyObject* obj) {
  Py_INCREF(obj);
  return std::shared_ptr<void>(obj, [](void* p) {
    PyGILState_STATE gil = PyGILState_Ensure();
    Py_DECREF(static_cast<PyObject*>(p));
    PyGILState_Release(gil);
  });
}

bool nest_from_py(PyObject* obj, ArrayNest* out) {
  if (PyDict_Check(obj)) {
    ArrayNest::Dict dict;
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(obj, &pos, &key, &value)) {
      if (!PyUnicode_Check(key)) {
        PyErr_SetString(PyExc_TypeError, "nest dict keys must be str");
        return false;
      }
      ArrayNest sub;
      if (!nest_from_py(value, &sub)) return false;
      dict.emplace(PyUnicode_AsUTF8(key), std::move(sub));
    }
    *out = ArrayNest(std::move(dict));
    return true;
  }
  if (PyList_Check(obj) || PyTuple_Check(obj)) {
    PyObject* seq = PySequence_Fast(obj, "expected sequence");
    if (!seq) return false;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    ArrayNest::List list;
    list.reserve(n);
    for (Py_ssize_t i = 0; i < n; ++i) {
      ArrayNest sub;
      if (!nest_from_py(PySequence_Fast_GET_ITEM(seq, i), &sub)) {
        Py_DECREF(seq);
        return false;
      }
      list.push_back(std::move(sub));
    }
    Py_DECREF(seq);
    *out = ArrayNest(std::move(list));
    return true;
  }
  // Leaf: coerce to a C-contiguous numpy array, zero-copy when possible.
  PyArrayObject* arr = reinterpret_cast<PyArrayObject*>(
      PyArray_FROM_OF(obj, NPY_ARRAY_C_CONTIGUOUS | NPY_ARRAY_ALIGNED));
  if (!arr) return false;
  DType dtype;
  if (!npy_to_dtype(PyArray_TYPE(arr), &dtype)) {
    PyErr_Format(PyExc_TypeError, "unsupported array dtype %d",
                 PyArray_TYPE(arr));
    Py_DECREF(arr);
    return false;
  }
  std::vector<int64_t> shape(PyArray_NDIM(arr));
  for (int i = 0; i < PyArray_NDIM(arr); ++i) shape[i] = PyArray_DIM(arr, i);
  *out = ArrayNest(Array(dtype, std::move(shape), PyArray_DATA(arr),
                         py_owner(reinterpret_cast<PyObject*>(arr))));
  Py_DECREF(arr);
  return true;
}

// ------------------------------------------------- C++ -> python nest
PyObject* array_to_py(const Array& a) {
  std::vector<npy_intp> dims(a.shape().begin(), a.shape().end());
  // The capsule keeps a heap-allocated Array (sharing the buffer) alive.
  Array* keeper = new Array(a);
  PyObject* capsule = PyCapsule_New(
      keeper, nullptr,
      [](PyObject* cap) {
        delete static_cast<Array*>(PyCapsule_GetPointer(cap, nullptr));
      });
  if (!capsule) {
    delete keeper;
    return nullptr;
  }
  PyObject* arr = PyArray_SimpleNewFromData(
      static_cast<int>(dims.size()), dims.data(), dtype_to_npy(a.dtype()),
      const_cast<uint8_t*>(keeper->data()));
  if (!arr) {
    Py_DECREF(capsule);
    return nullptr;
  }
  if (PyArray_SetBaseObject(reinterpret_cast<PyArrayObject*>(arr), capsule) <
      0) {
    Py_DECREF(arr);
    return nullptr;
  }
  return arr;
}

PyObject* nest_to_py(const ArrayNest& nest) {
  if (nest.is_leaf()) return array_to_py(nest.leaf());
  if (nest.is_list()) {
    PyObject* tuple = PyTuple_New(nest.list().size());
    if (!tuple) return nullptr;
    for (size_t i = 0; i < nest.list().size(); ++i) {
      PyObject* item = nest_to_py(nest.list()[i]);
      if (!item) {
        Py_DECREF(tuple);
        return nullptr;
      }
      PyTuple_SET_ITEM(tuple, i, item);
    }
    return tuple;
  }
  PyObject* dict = PyDict_New();
  if (!dict) return nullptr;
  for (const auto& [key, sub] : nest.dict()) {
    PyObject* item = nest_to_py(sub);
    if (!item || PyDict_SetItemString(dict, key.c_str(), item) < 0) {
      Py_XDECREF(item);
      Py_DECREF(dict);
      return nullptr;
    }
    Py_DECREF(item);
  }
  return dict;
}

void set_py_error();

// Run fn with the GIL released, catching C++ exceptions INSIDE the no-GIL
// region (an exception unwinding past Py_END_ALLOW_THREADS would skip the
// GIL re-acquire and corrupt the interpreter). Returns false with the
// Python error set on failure.
template <typename F>
bool call_nogil(F&& fn) {
  std::exception_ptr err;
  Py_BEGIN_ALLOW_THREADS
  try {
    fn();
  } catch (...) {
    err = std::current_exception();
  }
  Py_END_ALLOW_THREADS
  if (err) {
    try {
      std::rethrow_exception(err);
    } catch (...) {
      set_py_error();
    }
    return false;
  }
  return true;
}

// Translate in-flight C++ exceptions to Python exceptions.
void set_py_error() {
  try {
    throw;
  } catch (const tbt::ClosedBatchingQueue& e) {
    PyErr_SetString(ClosedBatchingQueueError, e.what());
  } catch (const tbt::QueueStopped&) {
    PyErr_SetNone(PyExc_StopIteration);
  } catch (const tbt::AsyncError& e) {
    PyErr_SetString(AsyncErrorError, e.what());
  } catch (const std::invalid_argument& e) {
    PyErr_SetString(PyExc_ValueError, e.what());
  } catch (const std::out_of_range& e) {
    PyErr_SetString(PyExc_IndexError, e.what());
  } catch (const std::exception& e) {
    PyErr_SetString(PyExc_RuntimeError, e.what());
  } catch (...) {
    PyErr_SetString(PyExc_RuntimeError, "unknown C++ exception");
  }
}

// ---------------------------------------------------------------- Queue
using LearnerQueue = tbt::ActorPool::LearnerQueue;

struct PyBatchingQueue {
  PyObject_HEAD
  std::shared_ptr<LearnerQueue> queue;
};

struct PyDynamicBatcher {
  PyObject_HEAD
  std::shared_ptr<tbt::DynamicBatcher> batcher;
};

struct PyBatch {
  PyObject_HEAD
  std::unique_ptr<tbt::DynamicBatcher::Batch> batch;
};

struct PyActorPool {
  PyObject_HEAD
  std::shared_ptr<tbt::ActorPool> pool;
};

extern PyTypeObject PyBatchType;

// --- BatchingQueue
int queue_init(PyBatchingQueue* self, PyObject* args, PyObject* kwargs) {
  static const char* kwlist[] = {"batch_dim",      "minimum_batch_size",
                                 "maximum_batch_size", "timeout_ms",
                                 "maximum_queue_size", "check_inputs",
                                 nullptr};
  long long batch_dim = 0, min_bs = 1;
  PyObject *max_bs_obj = Py_None, *timeout_obj = Py_None,
           *max_queue_obj = Py_None;
  int check_inputs = 1;
  if (!PyArg_ParseTupleAndKeywords(
          args, kwargs, "|LLOOOp", const_cast<char**>(kwlist), &batch_dim,
          &min_bs, &max_bs_obj, &timeout_obj, &max_queue_obj, &check_inputs))
    return -1;
  try {
    int64_t max_bs = max_bs_obj == Py_None
                         ? std::numeric_limits<int64_t>::max()
                         : PyLong_AsLongLong(max_bs_obj);
    std::optional<int64_t> timeout_ms, max_queue;
    if (timeout_obj != Py_None)
      timeout_ms = static_cast<int64_t>(PyFloat_AsDouble(timeout_obj));
    if (max_queue_obj != Py_None)
      max_queue = PyLong_AsLongLong(max_queue_obj);
    if (PyErr_Occurred()) return -1;
    self->queue = std::make_shared<LearnerQueue>(
        batch_dim, min_bs, max_bs, timeout_ms, max_queue, check_inputs != 0);
    return 0;
  } catch (...) {
    set_py_error();
    return -1;
  }
}

PyObject* queue_enqueue(PyBatchingQueue* self, PyObject* arg) {
  ArrayNest nest;
  if (!nest_from_py(arg, &nest)) return nullptr;
  auto queue = self->queue;
  if (!call_nogil([&] { queue->enqueue(std::move(nest), 0); }))
    return nullptr;
  Py_RETURN_NONE;
}

PyObject* queue_dequeue_many(PyBatchingQueue* self, PyObject*) {
  std::pair<ArrayNest, std::vector<int>> result;
  auto queue = self->queue;
  if (!call_nogil([&] { result = queue->dequeue_many(); })) return nullptr;
  PyObject* nest = nest_to_py(result.first);
  if (!nest) return nullptr;
  return Py_BuildValue("(Nn)", nest,
                       static_cast<Py_ssize_t>(result.second.size()));
}

PyObject* queue_close(PyBatchingQueue* self, PyObject*) {
  try {
    self->queue->close();
  } catch (...) {
    set_py_error();
    return nullptr;
  }
  Py_RETURN_NONE;
}

PyObject* queue_size(PyBatchingQueue* self, PyObject*) {
  return PyLong_FromLongLong(self->queue->size());
}

PyObject* queue_is_closed(PyBatchingQueue* self, PyObject*) {
  return PyBool_FromLong(self->queue->is_closed());
}

PyObject* queue_iter(PyObject* self) {
  Py_INCREF(self);
  return self;
}

PyObject* queue_iternext(PyBatchingQueue* self) {
  std::pair<ArrayNest, std::vector<int>> result;
  auto queue = self->queue;
  if (!call_nogil([&] { result = queue->dequeue_many(); })) return nullptr;
  return nest_to_py(result.first);
}

void queue_dealloc(PyBatchingQueue* self) {
  self->queue.~shared_ptr();
  Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

PyObject* queue_new(PyTypeObject* type, PyObject*, PyObject*) {
  PyBatchingQueue* self =
      reinterpret_cast<PyBatchingQueue*>(type->tp_alloc(type, 0));
  if (self) new (&self->queue) std::shared_ptr<LearnerQueue>();
  return reinterpret_cast<PyObject*>(self);
}

PyMethodDef queue_methods[] = {
    {"enqueue", reinterpret_cast<PyCFunction>(queue_enqueue), METH_O, nullptr},
    {"dequeue_many", reinterpret_cast<PyCFunction>(queue_dequeue_many),
     METH_NOARGS, nullptr},
    {"close", reinterpret_cast<PyCFunction>(queue_close), METH_NOARGS,
     nullptr},
    {"size", reinterpret_cast<PyCFunction>(queue_size), METH_NOARGS, nullptr},
    {"is_closed", reinterpret_cast<PyCFunction>(queue_is_closed), METH_NOARGS,
     nullptr},
    {nullptr, nullptr, 0, nullptr}};

PyTypeObject PyBatchingQueueType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// --- Batch
PyObject* batch_get_inputs(PyBatch* self, PyObject*) {
  if (!self->batch) {
    PyErr_SetString(PyExc_RuntimeError, "Batch already consumed");
    return nullptr;
  }
  return nest_to_py(self->batch->inputs());
}

PyObject* batch_set_outputs(PyBatch* self, PyObject* arg) {
  if (!self->batch) {
    PyErr_SetString(PyExc_RuntimeError, "Batch already consumed");
    return nullptr;
  }
  ArrayNest nest;
  if (!nest_from_py(arg, &nest)) return nullptr;
  try {
    // Deep-copy outputs: promises may outlive the numpy arrays.
    ArrayNest owned = nest.map([](const Array& a) { return a.clone(); });
    self->batch->set_outputs(owned);
  } catch (...) {
    set_py_error();
    return nullptr;
  }
  Py_RETURN_NONE;
}

PyObject* batch_fail(PyBatch* self, PyObject* arg) {
  if (!self->batch) Py_RETURN_NONE;
  const char* message = PyUnicode_Check(arg) ? PyUnicode_AsUTF8(arg)
                                             : "inference failed";
  self->batch->fail(message ? message : "inference failed");
  Py_RETURN_NONE;
}

Py_ssize_t batch_len(PyBatch* self) {
  return self->batch ? static_cast<Py_ssize_t>(self->batch->size()) : 0;
}

void batch_dealloc(PyBatch* self) {
  self->batch.~unique_ptr();
  Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

PyMethodDef batch_methods[] = {
    {"get_inputs", reinterpret_cast<PyCFunction>(batch_get_inputs),
     METH_NOARGS, nullptr},
    {"set_outputs", reinterpret_cast<PyCFunction>(batch_set_outputs), METH_O,
     nullptr},
    {"fail", reinterpret_cast<PyCFunction>(batch_fail), METH_O, nullptr},
    {nullptr, nullptr, 0, nullptr}};

PySequenceMethods batch_as_sequence = {
    reinterpret_cast<lenfunc>(batch_len),  // sq_length
};

PyTypeObject PyBatchType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// --- DynamicBatcher
int batcher_init(PyDynamicBatcher* self, PyObject* args, PyObject* kwargs) {
  static const char* kwlist[] = {"batch_dim", "minimum_batch_size",
                                 "maximum_batch_size", "timeout_ms", nullptr};
  long long batch_dim = 1, min_bs = 1;
  PyObject *max_bs_obj = Py_None, *timeout_obj = Py_None;
  if (!PyArg_ParseTupleAndKeywords(args, kwargs, "|LLOO",
                                   const_cast<char**>(kwlist), &batch_dim,
                                   &min_bs, &max_bs_obj, &timeout_obj))
    return -1;
  try {
    int64_t max_bs = max_bs_obj == Py_None
                         ? std::numeric_limits<int64_t>::max()
                         : PyLong_AsLongLong(max_bs_obj);
    std::optional<int64_t> timeout_ms;
    if (timeout_obj != Py_None)
      timeout_ms = static_cast<int64_t>(PyFloat_AsDouble(timeout_obj));
    if (PyErr_Occurred()) return -1;
    self->batcher = std::make_shared<tbt::DynamicBatcher>(
        batch_dim, min_bs, max_bs, timeout_ms);
    return 0;
  } catch (...) {
    set_py_error();
    return -1;
  }
}

PyObject* batcher_compute(PyDynamicBatcher* self, PyObject* arg) {
  ArrayNest nest;
  if (!nest_from_py(arg, &nest)) return nullptr;
  ArrayNest result;
  auto batcher = self->batcher;
  if (!call_nogil([&] { result = batcher->compute(std::move(nest)); }))
    return nullptr;
  return nest_to_py(result);
}

PyObject* batcher_iternext(PyDynamicBatcher* self) {
  std::unique_ptr<tbt::DynamicBatcher::Batch> batch;
  auto batcher = self->batcher;
  if (!call_nogil([&] { batch = batcher->get_batch(); })) return nullptr;
  PyBatch* out =
      reinterpret_cast<PyBatch*>(PyBatchType.tp_alloc(&PyBatchType, 0));
  if (!out) return nullptr;
  new (&out->batch)
      std::unique_ptr<tbt::DynamicBatcher::Batch>(std::move(batch));
  return reinterpret_cast<PyObject*>(out);
}

PyObject* batcher_close(PyDynamicBatcher* self, PyObject*) {
  try {
    self->batcher->close();
  } catch (...) {
    set_py_error();
    return nullptr;
  }
  Py_RETURN_NONE;
}

PyObject* batcher_size(PyDynamicBatcher* self, PyObject*) {
  return PyLong_FromLongLong(self->batcher->size());
}

PyObject* batcher_is_closed(PyDynamicBatcher* self, PyObject*) {
  return PyBool_FromLong(self->batcher->is_closed());
}

void batcher_dealloc(PyDynamicBatcher* self) {
  self->batcher.~shared_ptr();
  Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

PyObject* batcher_new(PyTypeObject* type, PyObject*, PyObject*) {
  PyDynamicBatcher* self =
      reinterpret_cast<PyDynamicBatcher*>(type->tp_alloc(type, 0));
  if (self) new (&self->batcher) std::shared_ptr<tbt::DynamicBatcher>();
  return reinterpret_cast<PyObject*>(self);
}

PyMethodDef batcher_methods[] = {
    {"compute", reinterpret_cast<PyCFunction>(batcher_compute), METH_O,
     nullptr},
    {"close", reinterpret_cast<PyCFunction>(batcher_close), METH_NOARGS,
     nullptr},
    {"size", reinterpret_cast<PyCFunction>(batcher_size), METH_NOARGS,
     nullptr},
    {"is_closed", reinterpret_cast<PyCFunction>(batcher_is_closed),
     METH_NOARGS, nullptr},
    {nullptr, nullptr, 0, nullptr}};

PyTypeObject PyDynamicBatcherType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// --- ActorPool
int pool_init(PyActorPool* self, PyObject* args, PyObject* kwargs) {
  static const char* kwlist[] = {
      "unroll_length",     "learner_queue", "inference_batcher",
      "env_server_addresses", "initial_agent_state", "connect_timeout_s",
      "max_reconnects", nullptr};
  long long unroll_length = 0, max_reconnects = 0;
  PyObject *queue_obj, *batcher_obj, *addresses_obj, *state_obj;
  double connect_timeout_s = 600;
  if (!PyArg_ParseTupleAndKeywords(
          args, kwargs, "LO!O!OO|dL", const_cast<char**>(kwlist),
          &unroll_length, &PyBatchingQueueType, &queue_obj,
          &PyDynamicBatcherType, &batcher_obj, &addresses_obj, &state_obj,
          &connect_timeout_s, &max_reconnects))
    return -1;
  std::vector<std::string> addresses;
  PyObject* seq = PySequence_Fast(addresses_obj, "addresses must be a sequence");
  if (!seq) return -1;
  for (Py_ssize_t i = 0; i < PySequence_Fast_GET_SIZE(seq); ++i) {
    PyObject* item = PySequence_Fast_GET_ITEM(seq, i);
    if (!PyUnicode_Check(item)) {
      Py_DECREF(seq);
      PyErr_SetString(PyExc_TypeError, "addresses must be strings");
      return -1;
    }
    addresses.push_back(PyUnicode_AsUTF8(item));
  }
  Py_DECREF(seq);
  ArrayNest state;
  if (!nest_from_py(state_obj, &state)) return -1;
  try {
    // Deep-copy the state: actor threads use it GIL-free.
    ArrayNest owned = state.map([](const Array& a) { return a.clone(); });
    self->pool = std::make_shared<tbt::ActorPool>(
        unroll_length,
        reinterpret_cast<PyBatchingQueue*>(queue_obj)->queue,
        reinterpret_cast<PyDynamicBatcher*>(batcher_obj)->batcher,
        std::move(addresses), std::move(owned), connect_timeout_s,
        max_reconnects);
    return 0;
  } catch (...) {
    set_py_error();
    return -1;
  }
}

PyObject* pool_run(PyActorPool* self, PyObject*) {
  auto pool = self->pool;
  if (!call_nogil([&] { pool->run(); })) return nullptr;
  Py_RETURN_NONE;
}

PyObject* pool_count(PyActorPool* self, PyObject*) {
  return PyLong_FromLongLong(self->pool->count());
}

PyObject* pool_reconnect_count(PyActorPool* self, PyObject*) {
  return PyLong_FromLongLong(self->pool->reconnect_count());
}

PyObject* pool_first_error_message(PyActorPool* self, PyObject*) {
  std::string msg = self->pool->first_error_message();
  if (msg.empty()) Py_RETURN_NONE;
  return PyUnicode_FromString(msg.c_str());
}

void pool_dealloc(PyActorPool* self) {
  self->pool.~shared_ptr();
  Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

PyObject* pool_new(PyTypeObject* type, PyObject*, PyObject*) {
  PyActorPool* self = reinterpret_cast<PyActorPool*>(type->tp_alloc(type, 0));
  if (self) new (&self->pool) std::shared_ptr<tbt::ActorPool>();
  return reinterpret_cast<PyObject*>(self);
}

PyMethodDef pool_methods[] = {
    {"run", reinterpret_cast<PyCFunction>(pool_run), METH_NOARGS, nullptr},
    {"count", reinterpret_cast<PyCFunction>(pool_count), METH_NOARGS,
     nullptr},
    {"first_error_message",
     reinterpret_cast<PyCFunction>(pool_first_error_message), METH_NOARGS,
     nullptr},
    {"reconnect_count", reinterpret_cast<PyCFunction>(pool_reconnect_count),
     METH_NOARGS, nullptr},
    {nullptr, nullptr, 0, nullptr}};

PyTypeObject PyActorPoolType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// ---------------------------------------------------------------- module
PyModuleDef module_def = {
    PyModuleDef_HEAD_INIT, "_tbt_core",
    "Native runtime core (queues, dynamic batcher, actor pool)", -1, nullptr,
};

void init_type(PyTypeObject* type, const char* name, size_t basicsize,
               newfunc tp_new, initproc tp_init, destructor tp_dealloc,
               PyMethodDef* methods, getiterfunc tp_iter,
               iternextfunc tp_iternext, PySequenceMethods* as_seq) {
  type->tp_name = name;
  type->tp_basicsize = static_cast<Py_ssize_t>(basicsize);
  type->tp_flags = Py_TPFLAGS_DEFAULT;
  type->tp_new = tp_new;
  type->tp_init = tp_init;
  type->tp_dealloc = tp_dealloc;
  type->tp_methods = methods;
  type->tp_iter = tp_iter;
  type->tp_iternext = tp_iternext;
  type->tp_as_sequence = as_seq;
}

}  // namespace

PyMODINIT_FUNC PyInit__tbt_core(void) {
  import_array();

  init_type(&PyBatchingQueueType, "_tbt_core.BatchingQueue",
            sizeof(PyBatchingQueue), queue_new,
            reinterpret_cast<initproc>(queue_init),
            reinterpret_cast<destructor>(queue_dealloc), queue_methods,
            queue_iter, reinterpret_cast<iternextfunc>(queue_iternext),
            nullptr);
  init_type(&PyBatchType, "_tbt_core.Batch", sizeof(PyBatch), nullptr,
            nullptr, reinterpret_cast<destructor>(batch_dealloc),
            batch_methods, nullptr, nullptr, &batch_as_sequence);
  init_type(&PyDynamicBatcherType, "_tbt_core.DynamicBatcher",
            sizeof(PyDynamicBatcher), batcher_new,
            reinterpret_cast<initproc>(batcher_init),
            reinterpret_cast<destructor>(batcher_dealloc), batcher_methods,
            queue_iter, reinterpret_cast<iternextfunc>(batcher_iternext),
            nullptr);
  init_type(&PyActorPoolType, "_tbt_core.ActorPool", sizeof(PyActorPool),
            pool_new, reinterpret_cast<initproc>(pool_init),
            reinterpret_cast<destructor>(pool_dealloc), pool_methods, nullptr,
            nullptr, nullptr);

  if (PyType_Ready(&PyBatchingQueueType) < 0 ||
      PyType_Ready(&PyBatchType) < 0 ||
      PyType_Ready(&PyDynamicBatcherType) < 0 ||
      PyType_Ready(&PyActorPoolType) < 0)
    return nullptr;

  PyObject* module = PyModule_Create(&module_def);
  if (!module) return nullptr;

  ClosedBatchingQueueError = PyErr_NewException(
      "_tbt_core.ClosedBatchingQueue", PyExc_RuntimeError, nullptr);
  AsyncErrorError =
      PyErr_NewException("_tbt_core.AsyncError", PyExc_RuntimeError, nullptr);

  Py_INCREF(&PyBatchingQueueType);
  Py_INCREF(&PyBatchType);
  Py_INCREF(&PyDynamicBatcherType);
  Py_INCREF(&PyActorPoolType);
  PyModule_AddObject(module, "BatchingQueue",
                     reinterpret_cast<PyObject*>(&PyBatchingQueueType));
  PyModule_AddObject(module, "Batch",
                     reinterpret_cast<PyObject*>(&PyBatchType));
  PyModule_AddObject(module, "DynamicBatcher",
                     reinterpret_cast<PyObject*>(&PyDynamicBatcherType));
  PyModule_AddObject(module, "ActorPool",
                     reinterpret_cast<PyObject*>(&PyActorPoolType));
  PyModule_AddObject(module, "ClosedBatchingQueue", ClosedBatchingQueueError);
  PyModule_AddObject(module, "AsyncError", AsyncErrorError);
  return module;
}
