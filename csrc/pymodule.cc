// _tbt_core: CPython bindings for the native runtime (reference components
// N2/N9, /root/reference/nest/nest/nest_pybind.cc + src/cc/libtorchbeast.cc
// — written against the raw CPython/numpy C API since pybind11 is not in
// this image).
//
// Exposes BatchingQueue, DynamicBatcher (+Batch), ActorPool. Conversions:
//   python -> C++: dict/list/tuple -> Nest, numpy array -> Array wrapping
//     the numpy buffer zero-copy (a shared_ptr owner decrefs under the GIL)
//   C++ -> python: Array -> numpy array wrapping the C++ buffer zero-copy
//     (a capsule owner keeps the shared_ptr alive)
// All blocking calls release the GIL, so C++ actor threads and Python
// inference/learner threads interleave freely.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <numpy/arrayobject.h>
#include <numpy/arrayscalars.h>

#include <memory>
#include <string>
#include <vector>

#include "actor_pool.h"
#include "env_server.h"
#include "queues.h"
#include "routing.h"
#include "shm.h"

namespace {

using tbt::Array;
using tbt::ArrayNest;
using tbt::DType;

PyObject* ClosedBatchingQueueError;
PyObject* AsyncErrorError;
PyObject* ShedErrorError;

// ---------------------------------------------------------------- dtypes
// bfloat16 (wire code 12, csrc/array.h kBF16) is a numpy USER dtype
// registered by ml_dtypes (a jax dependency), so its type number is
// dynamic — resolved once, under the GIL. -1 = ml_dtypes unavailable:
// converting a bf16 array then fails loudly instead of mislabeling it.
int bf16_typenum = -1;
bool bf16_resolved = false;

int resolve_bf16_typenum() {
  if (bf16_resolved) return bf16_typenum;
  bf16_resolved = true;
  PyObject* mod = PyImport_ImportModule("ml_dtypes");
  if (!mod) {
    PyErr_Clear();
    return bf16_typenum;
  }
  PyObject* bf = PyObject_GetAttrString(mod, "bfloat16");
  Py_DECREF(mod);
  if (!bf) {
    PyErr_Clear();
    return bf16_typenum;
  }
  PyArray_Descr* descr = nullptr;
  if (PyArray_DescrConverter(bf, &descr) && descr) {
    bf16_typenum = descr->type_num;
    Py_DECREF(descr);
  } else {
    PyErr_Clear();
  }
  Py_DECREF(bf);
  return bf16_typenum;
}

int dtype_to_npy(DType d) {
  switch (d) {
    case DType::kU8: return NPY_UINT8;
    case DType::kI8: return NPY_INT8;
    case DType::kI32: return NPY_INT32;
    case DType::kI64: return NPY_INT64;
    case DType::kF32: return NPY_FLOAT32;
    case DType::kF64: return NPY_FLOAT64;
    case DType::kBool: return NPY_BOOL;
    case DType::kU16: return NPY_UINT16;
    case DType::kI16: return NPY_INT16;
    case DType::kU32: return NPY_UINT32;
    case DType::kU64: return NPY_UINT64;
    case DType::kF16: return NPY_FLOAT16;
    case DType::kBF16: return resolve_bf16_typenum();
  }
  return -1;
}

bool npy_to_dtype(int npy, DType* out) {
  switch (npy) {
    case NPY_UINT8: *out = DType::kU8; return true;
    case NPY_INT8: *out = DType::kI8; return true;
    case NPY_INT32: *out = DType::kI32; return true;
    case NPY_INT64: *out = DType::kI64; return true;
    case NPY_FLOAT32: *out = DType::kF32; return true;
    case NPY_FLOAT64: *out = DType::kF64; return true;
    case NPY_BOOL: *out = DType::kBool; return true;
    case NPY_UINT16: *out = DType::kU16; return true;
    case NPY_INT16: *out = DType::kI16; return true;
    case NPY_UINT32: *out = DType::kU32; return true;
    case NPY_UINT64: *out = DType::kU64; return true;
    case NPY_FLOAT16: *out = DType::kF16; return true;
    default:
      if (npy >= 0 && npy == resolve_bf16_typenum()) {
        *out = DType::kBF16;
        return true;
      }
      return false;
  }
}

// ------------------------------------------------- python -> C++ nest
// Decref-under-GIL owner for buffers borrowed from numpy.
std::shared_ptr<void> py_owner(PyObject* obj) {
  Py_INCREF(obj);
  return std::shared_ptr<void>(obj, [](void* p) {
    PyGILState_STATE gil = PyGILState_Ensure();
    Py_DECREF(static_cast<PyObject*>(p));
    PyGILState_Release(gil);
  });
}

bool nest_from_py(PyObject* obj, ArrayNest* out) {
  if (PyDict_Check(obj)) {
    ArrayNest::Dict dict;
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(obj, &pos, &key, &value)) {
      if (!PyUnicode_Check(key)) {
        PyErr_SetString(PyExc_TypeError, "nest dict keys must be str");
        return false;
      }
      ArrayNest sub;
      if (!nest_from_py(value, &sub)) return false;
      dict.emplace(PyUnicode_AsUTF8(key), std::move(sub));
    }
    *out = ArrayNest(std::move(dict));
    return true;
  }
  if (PyList_Check(obj) || PyTuple_Check(obj)) {
    PyObject* seq = PySequence_Fast(obj, "expected sequence");
    if (!seq) return false;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    ArrayNest::List list;
    list.reserve(n);
    for (Py_ssize_t i = 0; i < n; ++i) {
      ArrayNest sub;
      if (!nest_from_py(PySequence_Fast_GET_ITEM(seq, i), &sub)) {
        Py_DECREF(seq);
        return false;
      }
      list.push_back(std::move(sub));
    }
    Py_DECREF(seq);
    *out = ArrayNest(std::move(list));
    return true;
  }
  // Leaf: coerce to a C-contiguous numpy array, zero-copy when possible.
  PyArrayObject* arr = reinterpret_cast<PyArrayObject*>(
      PyArray_FROM_OF(obj, NPY_ARRAY_C_CONTIGUOUS | NPY_ARRAY_ALIGNED));
  if (!arr) return false;
  DType dtype;
  if (!npy_to_dtype(PyArray_TYPE(arr), &dtype)) {
    PyErr_Format(PyExc_TypeError, "unsupported array dtype %d",
                 PyArray_TYPE(arr));
    Py_DECREF(arr);
    return false;
  }
  std::vector<int64_t> shape(PyArray_NDIM(arr));
  for (int i = 0; i < PyArray_NDIM(arr); ++i) shape[i] = PyArray_DIM(arr, i);
  *out = ArrayNest(Array(dtype, std::move(shape), PyArray_DATA(arr),
                         py_owner(reinterpret_cast<PyObject*>(arr))));
  Py_DECREF(arr);
  return true;
}

// ------------------------------------------------- C++ -> python nest
PyObject* array_to_py(const Array& a) {
  std::vector<npy_intp> dims(a.shape().begin(), a.shape().end());
  // The capsule keeps a heap-allocated Array (sharing the buffer) alive.
  Array* keeper = new Array(a);
  PyObject* capsule = PyCapsule_New(
      keeper, nullptr,
      [](PyObject* cap) {
        delete static_cast<Array*>(PyCapsule_GetPointer(cap, nullptr));
      });
  if (!capsule) {
    delete keeper;
    return nullptr;
  }
  PyObject* arr = PyArray_SimpleNewFromData(
      static_cast<int>(dims.size()), dims.data(), dtype_to_npy(a.dtype()),
      const_cast<uint8_t*>(keeper->data()));
  if (!arr) {
    Py_DECREF(capsule);
    return nullptr;
  }
  if (PyArray_SetBaseObject(reinterpret_cast<PyArrayObject*>(arr), capsule) <
      0) {
    Py_DECREF(arr);
    return nullptr;
  }
  return arr;
}

PyObject* nest_to_py(const ArrayNest& nest) {
  if (nest.is_leaf()) return array_to_py(nest.leaf());
  if (nest.is_list()) {
    PyObject* tuple = PyTuple_New(nest.list().size());
    if (!tuple) return nullptr;
    for (size_t i = 0; i < nest.list().size(); ++i) {
      PyObject* item = nest_to_py(nest.list()[i]);
      if (!item) {
        Py_DECREF(tuple);
        return nullptr;
      }
      PyTuple_SET_ITEM(tuple, i, item);
    }
    return tuple;
  }
  PyObject* dict = PyDict_New();
  if (!dict) return nullptr;
  for (const auto& [key, sub] : nest.dict()) {
    PyObject* item = nest_to_py(sub);
    if (!item || PyDict_SetItemString(dict, key.c_str(), item) < 0) {
      Py_XDECREF(item);
      Py_DECREF(dict);
      return nullptr;
    }
    Py_DECREF(item);
  }
  return dict;
}

void set_py_error();

// ------------------------------------------------- telemetry snapshots
// HistSnapshot -> {"count", "total", "total_sq", "min", "max",
// "buckets": {index: count}} — the shape runtime/native.py's fold feeds
// into telemetry.metrics.Histogram.observe_aggregate (same log-bucket
// geometry; csrc/queues.h telemetry_bucket_index).
PyObject* hist_to_py(const tbt::HistSnapshot& h) {
  PyObject* buckets = PyDict_New();
  if (!buckets) return nullptr;
  for (const auto& [index, count] : h.buckets) {
    PyObject* key = PyLong_FromLong(index);
    PyObject* value = PyLong_FromLongLong(count);
    if (!key || !value || PyDict_SetItem(buckets, key, value) < 0) {
      Py_XDECREF(key);
      Py_XDECREF(value);
      Py_DECREF(buckets);
      return nullptr;
    }
    Py_DECREF(key);
    Py_DECREF(value);
  }
  return Py_BuildValue("{s:L,s:d,s:d,s:d,s:d,s:N}", "count", h.count,
                       "total", h.total, "total_sq", h.total_sq, "min",
                       h.min, "max", h.max, "buckets", buckets);
}

// ------------------------------------------------- wire value <-> python
// Full-fidelity converters between Python values and wire::ValueNest —
// scalars stay scalars (unlike the ArrayNest converters, which coerce
// everything to arrays). Powers the _tbt_core.wire_encode/wire_decode
// cross-language codec pins (tests/test_native.py) and the handshake-free
// bench helpers.
bool py_to_value(PyObject* obj, tbt::wire::ValueNest* out) {
  namespace wire = tbt::wire;
  // Ordering matches wire.py _encode_value: None, bool BEFORE int,
  // int, float, str, ndarray, list/tuple, dict.
  if (obj == Py_None) {
    *out = wire::ValueNest(wire::Value{});
    return true;
  }
  if (PyBool_Check(obj) || PyArray_IsScalar(obj, Bool)) {
    wire::Value v;
    v.kind = wire::Value::Kind::kBool;
    v.b = PyObject_IsTrue(obj) == 1;
    *out = wire::ValueNest(std::move(v));
    return true;
  }
  if ((PyLong_Check(obj) || PyArray_IsScalar(obj, Integer)) &&
      !PyArray_Check(obj)) {
    long long x = PyLong_Check(obj) ? PyLong_AsLongLong(obj) : 0;
    if (!PyLong_Check(obj)) {
      PyObject* as_int = PyNumber_Long(obj);
      if (!as_int) return false;
      x = PyLong_AsLongLong(as_int);
      Py_DECREF(as_int);
    }
    if (PyErr_Occurred()) return false;
    *out = wire::ValueNest(wire::Value::of_int(x));
    return true;
  }
  if (PyFloat_Check(obj) || PyArray_IsScalar(obj, Floating)) {
    double x = PyFloat_Check(obj) ? PyFloat_AsDouble(obj) : 0.0;
    if (!PyFloat_Check(obj)) {
      PyObject* as_float = PyNumber_Float(obj);
      if (!as_float) return false;
      x = PyFloat_AsDouble(as_float);
      Py_DECREF(as_float);
    }
    if (PyErr_Occurred()) return false;
    wire::Value v;
    v.kind = wire::Value::Kind::kFloat;
    v.f = x;
    *out = wire::ValueNest(std::move(v));
    return true;
  }
  if (PyUnicode_Check(obj)) {
    const char* s = PyUnicode_AsUTF8(obj);
    if (!s) return false;
    *out = wire::ValueNest(wire::Value::of_string(s));
    return true;
  }
  if (PyArray_Check(obj)) {
    PyArrayObject* arr = reinterpret_cast<PyArrayObject*>(
        PyArray_FROM_OF(obj, NPY_ARRAY_C_CONTIGUOUS | NPY_ARRAY_ALIGNED));
    if (!arr) return false;
    DType dtype;
    if (!npy_to_dtype(PyArray_TYPE(arr), &dtype)) {
      PyErr_Format(PyExc_TypeError, "unsupported array dtype %d",
                   PyArray_TYPE(arr));
      Py_DECREF(arr);
      return false;
    }
    std::vector<int64_t> shape(PyArray_NDIM(arr));
    for (int i = 0; i < PyArray_NDIM(arr); ++i)
      shape[i] = PyArray_DIM(arr, i);
    // Deep copy: wire values may outlive the GIL scope.
    Array a(dtype, std::move(shape));
    std::memcpy(a.mutable_data(), PyArray_DATA(arr), a.nbytes());
    Py_DECREF(arr);
    *out = wire::ValueNest(wire::Value::of(std::move(a)));
    return true;
  }
  if (PyList_Check(obj) || PyTuple_Check(obj)) {
    PyObject* seq = PySequence_Fast(obj, "expected sequence");
    if (!seq) return false;
    wire::ValueNest::List list;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    list.reserve(n);
    for (Py_ssize_t i = 0; i < n; ++i) {
      wire::ValueNest sub;
      if (!py_to_value(PySequence_Fast_GET_ITEM(seq, i), &sub)) {
        Py_DECREF(seq);
        return false;
      }
      list.push_back(std::move(sub));
    }
    Py_DECREF(seq);
    *out = wire::ValueNest(std::move(list));
    return true;
  }
  if (PyDict_Check(obj)) {
    wire::ValueNest::Dict dict;
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(obj, &pos, &key, &value)) {
      PyObject* key_str = PyObject_Str(key);
      if (!key_str) return false;
      wire::ValueNest sub;
      if (!py_to_value(value, &sub)) {
        Py_DECREF(key_str);
        return false;
      }
      const char* key_utf8 = PyUnicode_AsUTF8(key_str);
      if (!key_utf8) {  // e.g. lone surrogates: raises, returns NULL
        Py_DECREF(key_str);
        return false;
      }
      dict.emplace(key_utf8, std::move(sub));
      Py_DECREF(key_str);
    }
    *out = wire::ValueNest(std::move(dict));
    return true;
  }
  PyErr_Format(PyExc_TypeError, "cannot serialize %s to the wire",
               Py_TYPE(obj)->tp_name);
  return false;
}

PyObject* array_to_py(const Array& a);

PyObject* value_to_py(const tbt::wire::ValueNest& nest) {
  namespace wire = tbt::wire;
  if (nest.is_leaf()) {
    const wire::Value& v = nest.leaf();
    switch (v.kind) {
      case wire::Value::Kind::kNone:
        Py_RETURN_NONE;
      case wire::Value::Kind::kBool:
        return PyBool_FromLong(v.b);
      case wire::Value::Kind::kInt:
        return PyLong_FromLongLong(v.i);
      case wire::Value::Kind::kFloat:
        return PyFloat_FromDouble(v.f);
      case wire::Value::Kind::kString:
        return PyUnicode_FromStringAndSize(v.s.data(), v.s.size());
      case wire::Value::Kind::kArray:
        return array_to_py(v.array);
    }
    PyErr_SetString(PyExc_RuntimeError, "bad wire value kind");
    return nullptr;
  }
  if (nest.is_list()) {
    // Lists, matching wire.py decode (nest_to_py uses tuples).
    PyObject* list = PyList_New(nest.list().size());
    if (!list) return nullptr;
    for (size_t i = 0; i < nest.list().size(); ++i) {
      PyObject* item = value_to_py(nest.list()[i]);
      if (!item) {
        Py_DECREF(list);
        return nullptr;
      }
      PyList_SET_ITEM(list, i, item);
    }
    return list;
  }
  PyObject* dict = PyDict_New();
  if (!dict) return nullptr;
  for (const auto& [key, sub] : nest.dict()) {
    PyObject* item = value_to_py(sub);
    if (!item || PyDict_SetItemString(dict, key.c_str(), item) < 0) {
      Py_XDECREF(item);
      Py_DECREF(dict);
      return nullptr;
    }
    Py_DECREF(item);
  }
  return dict;
}

// Run fn with the GIL released, catching C++ exceptions INSIDE the no-GIL
// region (an exception unwinding past Py_END_ALLOW_THREADS would skip the
// GIL re-acquire and corrupt the interpreter). Returns false with the
// Python error set on failure.
template <typename F>
bool call_nogil(F&& fn) {
  std::exception_ptr err;
  Py_BEGIN_ALLOW_THREADS
  try {
    fn();
  } catch (...) {
    err = std::current_exception();
  }
  Py_END_ALLOW_THREADS
  if (err) {
    try {
      std::rethrow_exception(err);
    } catch (...) {
      set_py_error();
    }
    return false;
  }
  return true;
}

// Translate in-flight C++ exceptions to Python exceptions.
void set_py_error() {
  try {
    throw;
  } catch (const tbt::ClosedBatchingQueue& e) {
    PyErr_SetString(ClosedBatchingQueueError, e.what());
  } catch (const tbt::QueueStopped&) {
    PyErr_SetNone(PyExc_StopIteration);
  } catch (const tbt::ShedError& e) {
    // Before AsyncError (its base): the typed shed reply must reach
    // Python as the retryable ShedError, not a generic batch failure.
    PyErr_SetString(ShedErrorError, e.what());
  } catch (const tbt::AsyncError& e) {
    PyErr_SetString(AsyncErrorError, e.what());
  } catch (const std::invalid_argument& e) {
    PyErr_SetString(PyExc_ValueError, e.what());
  } catch (const std::out_of_range& e) {
    PyErr_SetString(PyExc_IndexError, e.what());
  } catch (const std::exception& e) {
    PyErr_SetString(PyExc_RuntimeError, e.what());
  } catch (...) {
    PyErr_SetString(PyExc_RuntimeError, "unknown C++ exception");
  }
}

// ---------------------------------------------------------------- Queue
using LearnerQueue = tbt::ActorPool::LearnerQueue;

struct PyBatchingQueue {
  PyObject_HEAD
  std::shared_ptr<LearnerQueue> queue;
};

struct PyDynamicBatcher {
  PyObject_HEAD
  std::shared_ptr<tbt::DynamicBatcher> batcher;
};

struct PyBatch {
  PyObject_HEAD
  std::unique_ptr<tbt::DynamicBatcher::Batch> batch;
};

struct PyActorPool {
  PyObject_HEAD
  std::shared_ptr<tbt::ActorPool> pool;
};

struct PySliceRouter {
  PyObject_HEAD
  std::shared_ptr<tbt::SliceRouter> router;
};

struct PyReplicaRouter {
  PyObject_HEAD
  std::shared_ptr<tbt::ReplicaRouter> router;
};

extern PyTypeObject PyDynamicBatcherType;
extern PyTypeObject PySliceRouterType;
extern PyTypeObject PyReplicaRouterType;

// Any native InferenceClient the pool (or a router) can serve through:
// a plain batcher, a slice fan-out, or a replica/central pair. Raises
// TypeError (returns nullptr) for anything else.
std::shared_ptr<tbt::InferenceClient> client_from(PyObject* obj,
                                                  const char* param) {
  if (PyObject_TypeCheck(obj, &PyDynamicBatcherType))
    return reinterpret_cast<PyDynamicBatcher*>(obj)->batcher;
  if (PyObject_TypeCheck(obj, &PySliceRouterType))
    return reinterpret_cast<PySliceRouter*>(obj)->router;
  if (PyObject_TypeCheck(obj, &PyReplicaRouterType))
    return reinterpret_cast<PyReplicaRouter*>(obj)->router;
  PyErr_Format(PyExc_TypeError,
               "%s must be a DynamicBatcher, SliceRouter or ReplicaRouter",
               param);
  return nullptr;
}

extern PyTypeObject PyBatchType;

// --- BatchingQueue
int queue_init(PyBatchingQueue* self, PyObject* args, PyObject* kwargs) {
  static const char* kwlist[] = {"batch_dim",      "minimum_batch_size",
                                 "maximum_batch_size", "timeout_ms",
                                 "maximum_queue_size", "check_inputs",
                                 nullptr};
  long long batch_dim = 0, min_bs = 1;
  PyObject *max_bs_obj = Py_None, *timeout_obj = Py_None,
           *max_queue_obj = Py_None;
  int check_inputs = 1;
  if (!PyArg_ParseTupleAndKeywords(
          args, kwargs, "|LLOOOp", const_cast<char**>(kwlist), &batch_dim,
          &min_bs, &max_bs_obj, &timeout_obj, &max_queue_obj, &check_inputs))
    return -1;
  try {
    int64_t max_bs = max_bs_obj == Py_None
                         ? std::numeric_limits<int64_t>::max()
                         : PyLong_AsLongLong(max_bs_obj);
    std::optional<int64_t> timeout_ms, max_queue;
    if (timeout_obj != Py_None)
      timeout_ms = static_cast<int64_t>(PyFloat_AsDouble(timeout_obj));
    if (max_queue_obj != Py_None)
      max_queue = PyLong_AsLongLong(max_queue_obj);
    if (PyErr_Occurred()) return -1;
    self->queue = std::make_shared<LearnerQueue>(
        batch_dim, min_bs, max_bs, timeout_ms, max_queue, check_inputs != 0);
    return 0;
  } catch (...) {
    set_py_error();
    return -1;
  }
}

PyObject* queue_enqueue(PyBatchingQueue* self, PyObject* arg) {
  ArrayNest nest;
  if (!nest_from_py(arg, &nest)) return nullptr;
  auto queue = self->queue;
  if (!call_nogil([&] { queue->enqueue(std::move(nest), 0); }))
    return nullptr;
  Py_RETURN_NONE;
}

PyObject* queue_dequeue_many(PyBatchingQueue* self, PyObject*) {
  std::pair<ArrayNest, std::vector<int>> result;
  auto queue = self->queue;
  if (!call_nogil([&] { result = queue->dequeue_many(); })) return nullptr;
  PyObject* nest = nest_to_py(result.first);
  if (!nest) return nullptr;
  return Py_BuildValue("(Nn)", nest,
                       static_cast<Py_ssize_t>(result.second.size()));
}

// Raw-item intake for the host BatchArena (runtime/queues.py contract):
// one FIFO (inputs, rows) pair, blocking; StopIteration once closed —
// what lets --superstep_k > 1 drain native rollouts straight into the
// preallocated [K, T+1, B, ...] arena columns.
PyObject* queue_dequeue_item(PyBatchingQueue* self, PyObject*) {
  std::pair<ArrayNest, int64_t> result;
  auto queue = self->queue;
  if (!call_nogil([&] { result = queue->dequeue_item(); })) return nullptr;
  PyObject* nest = nest_to_py(result.first);
  if (!nest) return nullptr;
  return Py_BuildValue("(NL)", nest,
                       static_cast<long long>(result.second));
}

PyObject* queue_telemetry(PyBatchingQueue* self, PyObject*) {
  auto queue = self->queue;
  tbt::HistSnapshot wait = queue->dequeue_wait_snapshot(/*reset=*/true);
  tbt::HistSnapshot sizes = queue->batch_size_snapshot(/*reset=*/true);
  PyObject* wait_py = hist_to_py(wait);
  if (!wait_py) return nullptr;
  PyObject* sizes_py = hist_to_py(sizes);
  if (!sizes_py) {
    Py_DECREF(wait_py);
    return nullptr;
  }
  return Py_BuildValue("{s:L,s:L,s:N,s:N}", "items_in",
                       static_cast<long long>(queue->num_enqueued()),
                       "depth", static_cast<long long>(queue->size()),
                       "dequeue_wait_s", wait_py, "batch_size", sizes_py);
}

PyObject* queue_close(PyBatchingQueue* self, PyObject*) {
  try {
    self->queue->close();
  } catch (...) {
    set_py_error();
    return nullptr;
  }
  Py_RETURN_NONE;
}

PyObject* queue_size(PyBatchingQueue* self, PyObject*) {
  return PyLong_FromLongLong(self->queue->size());
}

PyObject* queue_is_closed(PyBatchingQueue* self, PyObject*) {
  return PyBool_FromLong(self->queue->is_closed());
}

PyObject* queue_iter(PyObject* self) {
  Py_INCREF(self);
  return self;
}

PyObject* queue_iternext(PyBatchingQueue* self) {
  std::pair<ArrayNest, std::vector<int>> result;
  auto queue = self->queue;
  if (!call_nogil([&] { result = queue->dequeue_many(); })) return nullptr;
  return nest_to_py(result.first);
}

void queue_dealloc(PyBatchingQueue* self) {
  self->queue.~shared_ptr();
  Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

PyObject* queue_new(PyTypeObject* type, PyObject*, PyObject*) {
  PyBatchingQueue* self =
      reinterpret_cast<PyBatchingQueue*>(type->tp_alloc(type, 0));
  if (self) new (&self->queue) std::shared_ptr<LearnerQueue>();
  return reinterpret_cast<PyObject*>(self);
}

PyMethodDef queue_methods[] = {
    {"enqueue", reinterpret_cast<PyCFunction>(queue_enqueue), METH_O, nullptr},
    {"dequeue_many", reinterpret_cast<PyCFunction>(queue_dequeue_many),
     METH_NOARGS, nullptr},
    {"dequeue_item", reinterpret_cast<PyCFunction>(queue_dequeue_item),
     METH_NOARGS, nullptr},
    {"telemetry", reinterpret_cast<PyCFunction>(queue_telemetry),
     METH_NOARGS, nullptr},
    {"close", reinterpret_cast<PyCFunction>(queue_close), METH_NOARGS,
     nullptr},
    {"size", reinterpret_cast<PyCFunction>(queue_size), METH_NOARGS, nullptr},
    {"is_closed", reinterpret_cast<PyCFunction>(queue_is_closed), METH_NOARGS,
     nullptr},
    {nullptr, nullptr, 0, nullptr}};

PyTypeObject PyBatchingQueueType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// --- Batch
PyObject* batch_get_inputs(PyBatch* self, PyObject*) {
  if (!self->batch) {
    PyErr_SetString(PyExc_RuntimeError, "Batch already consumed");
    return nullptr;
  }
  return nest_to_py(self->batch->inputs());
}

PyObject* batch_set_outputs(PyBatch* self, PyObject* arg) {
  if (!self->batch) {
    PyErr_SetString(PyExc_RuntimeError, "Batch already consumed");
    return nullptr;
  }
  ArrayNest nest;
  if (!nest_from_py(arg, &nest)) return nullptr;
  try {
    // Deep-copy outputs: promises may outlive the numpy arrays.
    ArrayNest owned = nest.map([](const Array& a) { return a.clone(); });
    self->batch->set_outputs(owned);
  } catch (...) {
    set_py_error();
    return nullptr;
  }
  Py_RETURN_NONE;
}

PyObject* batch_fail(PyBatch* self, PyObject* arg) {
  if (!self->batch) Py_RETURN_NONE;
  const char* message = PyUnicode_Check(arg) ? PyUnicode_AsUTF8(arg)
                                             : "inference failed";
  self->batch->fail(message ? message : "inference failed");
  Py_RETURN_NONE;
}

Py_ssize_t batch_len(PyBatch* self) {
  return self->batch ? static_cast<Py_ssize_t>(self->batch->size()) : 0;
}

void batch_dealloc(PyBatch* self) {
  self->batch.~unique_ptr();
  Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

PyMethodDef batch_methods[] = {
    {"get_inputs", reinterpret_cast<PyCFunction>(batch_get_inputs),
     METH_NOARGS, nullptr},
    {"set_outputs", reinterpret_cast<PyCFunction>(batch_set_outputs), METH_O,
     nullptr},
    {"fail", reinterpret_cast<PyCFunction>(batch_fail), METH_O, nullptr},
    {nullptr, nullptr, 0, nullptr}};

PySequenceMethods batch_as_sequence = {
    reinterpret_cast<lenfunc>(batch_len),  // sq_length
};

PyTypeObject PyBatchType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// --- DynamicBatcher
int batcher_init(PyDynamicBatcher* self, PyObject* args, PyObject* kwargs) {
  static const char* kwlist[] = {"batch_dim", "minimum_batch_size",
                                 "maximum_batch_size", "timeout_ms",
                                 "shed_max_queue_depth",
                                 "request_deadline_ms", "slo_target_ms",
                                 "continuous", nullptr};
  long long batch_dim = 1, min_bs = 1;
  PyObject *max_bs_obj = Py_None, *timeout_obj = Py_None;
  PyObject *shed_depth_obj = Py_None, *deadline_obj = Py_None,
           *slo_obj = Py_None;
  int continuous = 0;
  if (!PyArg_ParseTupleAndKeywords(args, kwargs, "|LLOOOOOp",
                                   const_cast<char**>(kwlist), &batch_dim,
                                   &min_bs, &max_bs_obj, &timeout_obj,
                                   &shed_depth_obj, &deadline_obj, &slo_obj,
                                   &continuous))
    return -1;
  try {
    int64_t max_bs = max_bs_obj == Py_None
                         ? std::numeric_limits<int64_t>::max()
                         : PyLong_AsLongLong(max_bs_obj);
    std::optional<int64_t> timeout_ms;
    if (timeout_obj != Py_None)
      timeout_ms = static_cast<int64_t>(PyFloat_AsDouble(timeout_obj));
    // Admission-gate kwargs (ISSUE 14); None / <= 0 disarm each gate.
    std::optional<int64_t> shed_depth;
    if (shed_depth_obj != Py_None) {
      long long depth = PyLong_AsLongLong(shed_depth_obj);
      if (depth > 0) shed_depth = depth;
    }
    std::optional<double> deadline_ms;
    if (deadline_obj != Py_None) {
      double v = PyFloat_AsDouble(deadline_obj);
      if (v > 0) deadline_ms = v;
    }
    std::optional<double> slo_ms;
    if (slo_obj != Py_None) {
      double v = PyFloat_AsDouble(slo_obj);
      if (v > 0) slo_ms = v;
    }
    if (PyErr_Occurred()) return -1;
    self->batcher = std::make_shared<tbt::DynamicBatcher>(
        batch_dim, min_bs, max_bs, timeout_ms, shed_depth, deadline_ms,
        slo_ms, continuous != 0);
    return 0;
  } catch (...) {
    set_py_error();
    return -1;
  }
}

PyObject* batcher_compute(PyDynamicBatcher* self, PyObject* arg) {
  ArrayNest nest;
  if (!nest_from_py(arg, &nest)) return nullptr;
  ArrayNest result;
  auto batcher = self->batcher;
  if (!call_nogil([&] { result = batcher->compute(std::move(nest)); }))
    return nullptr;
  return nest_to_py(result);
}

PyObject* batcher_iternext(PyDynamicBatcher* self) {
  std::unique_ptr<tbt::DynamicBatcher::Batch> batch;
  auto batcher = self->batcher;
  if (!call_nogil([&] { batch = batcher->get_batch(); })) return nullptr;
  PyBatch* out =
      reinterpret_cast<PyBatch*>(PyBatchType.tp_alloc(&PyBatchType, 0));
  if (!out) return nullptr;
  new (&out->batch)
      std::unique_ptr<tbt::DynamicBatcher::Batch>(std::move(batch));
  return reinterpret_cast<PyObject*>(out);
}

PyObject* batcher_close(PyDynamicBatcher* self, PyObject*) {
  try {
    self->batcher->close();
  } catch (...) {
    set_py_error();
    return nullptr;
  }
  Py_RETURN_NONE;
}

PyObject* batcher_size(PyDynamicBatcher* self, PyObject*) {
  return PyLong_FromLongLong(self->batcher->size());
}

PyObject* batcher_is_closed(PyDynamicBatcher* self, PyObject*) {
  return PyBool_FromLong(self->batcher->is_closed());
}

// Interval snapshot of the per-request stage stamps (enqueue -> batch ->
// reply) — resets the accumulators, so each call returns THIS interval's
// aggregates for the driver's monitor-tick fold (runtime/native.py).
PyObject* batcher_telemetry(PyDynamicBatcher* self, PyObject*) {
  auto telemetry = self->batcher->telemetry();
  tbt::HistSnapshot wait = telemetry->request_wait_s.snapshot(true);
  tbt::HistSnapshot rtt = telemetry->request_rtt_s.snapshot(true);
  tbt::HistSnapshot sizes = telemetry->batch_size.snapshot(true);
  tbt::HistSnapshot delay = telemetry->queue_delay_s.snapshot(true);
  PyObject* wait_py = hist_to_py(wait);
  PyObject* rtt_py = wait_py ? hist_to_py(rtt) : nullptr;
  PyObject* sizes_py = rtt_py ? hist_to_py(sizes) : nullptr;
  PyObject* delay_py = sizes_py ? hist_to_py(delay) : nullptr;
  if (!delay_py) {
    Py_XDECREF(wait_py);
    Py_XDECREF(rtt_py);
    Py_XDECREF(sizes_py);
    return nullptr;
  }
  return Py_BuildValue(
      "{s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:N,s:N,s:N,s:N}", "batches",
      static_cast<long long>(telemetry->batches.load()), "rows",
      static_cast<long long>(telemetry->rows.load()), "admitted",
      static_cast<long long>(telemetry->admitted.load()), "shed",
      static_cast<long long>(telemetry->shed.load()), "expired",
      static_cast<long long>(telemetry->expired.load()), "slo_breaches",
      static_cast<long long>(telemetry->slo_breaches.load()), "rolled",
      static_cast<long long>(telemetry->rolled.load()),
      "request_wait_s", wait_py, "request_rtt_s", rtt_py, "batch_size",
      sizes_py, "queue_delay_s", delay_py);
}

// Drain the sampled (enqueued, batched, replied) stamp triples (ISSUE
// 12): {"now": <steady-clock seconds>, "spans": [(e, b, r), ...]}.
// "now" lets the Python fold rebase the steady-clock stamps onto its
// perf_counter timebase before emitting tracer spans.
PyObject* batcher_trace_spans(PyDynamicBatcher* self, PyObject*) {
  auto telemetry = self->batcher->telemetry();
  std::vector<std::array<double, 3>> spans;
  {
    std::lock_guard<std::mutex> lock(telemetry->trace_mu);
    spans.swap(telemetry->trace_spans);
  }
  double now = std::chrono::duration<double>(
                   std::chrono::steady_clock::now().time_since_epoch())
                   .count();
  PyObject* list = PyList_New(static_cast<Py_ssize_t>(spans.size()));
  if (!list) return nullptr;
  for (size_t i = 0; i < spans.size(); ++i) {
    PyObject* t =
        Py_BuildValue("(ddd)", spans[i][0], spans[i][1], spans[i][2]);
    if (!t) {
      Py_DECREF(list);
      return nullptr;
    }
    PyList_SET_ITEM(list, static_cast<Py_ssize_t>(i), t);
  }
  return Py_BuildValue("{s:d,s:N}", "now", now, "spans", list);
}

void batcher_dealloc(PyDynamicBatcher* self) {
  self->batcher.~shared_ptr();
  Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

PyObject* batcher_new(PyTypeObject* type, PyObject*, PyObject*) {
  PyDynamicBatcher* self =
      reinterpret_cast<PyDynamicBatcher*>(type->tp_alloc(type, 0));
  if (self) new (&self->batcher) std::shared_ptr<tbt::DynamicBatcher>();
  return reinterpret_cast<PyObject*>(self);
}

PyMethodDef batcher_methods[] = {
    {"compute", reinterpret_cast<PyCFunction>(batcher_compute), METH_O,
     nullptr},
    {"telemetry", reinterpret_cast<PyCFunction>(batcher_telemetry),
     METH_NOARGS, nullptr},
    {"trace_spans", reinterpret_cast<PyCFunction>(batcher_trace_spans),
     METH_NOARGS, nullptr},
    {"close", reinterpret_cast<PyCFunction>(batcher_close), METH_NOARGS,
     nullptr},
    {"size", reinterpret_cast<PyCFunction>(batcher_size), METH_NOARGS,
     nullptr},
    {"is_closed", reinterpret_cast<PyCFunction>(batcher_is_closed),
     METH_NOARGS, nullptr},
    {nullptr, nullptr, 0, nullptr}};

PyTypeObject PyDynamicBatcherType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// --- SliceRouter (ISSUE 16): slot-hash fan-out over per-slice batchers.
// The router only holds shared_ptrs to the slices' C++ objects, so the
// Python batcher wrappers need not outlive it.
int slice_router_init(PySliceRouter* self, PyObject* args, PyObject* kwargs) {
  static const char* kwlist[] = {"slices", nullptr};
  PyObject* slices_obj;
  if (!PyArg_ParseTupleAndKeywords(args, kwargs, "O",
                                   const_cast<char**>(kwlist), &slices_obj))
    return -1;
  PyObject* seq = PySequence_Fast(slices_obj, "slices must be a sequence");
  if (!seq) return -1;
  std::vector<std::shared_ptr<tbt::InferenceClient>> slices;
  for (Py_ssize_t i = 0; i < PySequence_Fast_GET_SIZE(seq); ++i) {
    auto client = client_from(PySequence_Fast_GET_ITEM(seq, i), "slices[i]");
    if (!client) {
      Py_DECREF(seq);
      return -1;
    }
    slices.push_back(std::move(client));
  }
  Py_DECREF(seq);
  try {
    self->router = std::make_shared<tbt::SliceRouter>(std::move(slices));
    return 0;
  } catch (...) {
    set_py_error();
    return -1;
  }
}

PyObject* slice_router_compute(PySliceRouter* self, PyObject* arg) {
  ArrayNest nest;
  if (!nest_from_py(arg, &nest)) return nullptr;
  ArrayNest result;
  auto router = self->router;
  if (!call_nogil([&] { result = router->compute(std::move(nest)); }))
    return nullptr;
  return nest_to_py(result);
}

// Cumulative per-slice routed counts: {"requests": [c0, c1, ...]}. The
// driver folds deltas into "inference.slice.<i>.requests" (the series
// name the Python SliceRouter publishes — pinned by ROUTE-PARITY).
PyObject* slice_router_telemetry(PySliceRouter* self, PyObject*) {
  std::vector<int64_t> counts = self->router->request_counts();
  PyObject* list = PyList_New(static_cast<Py_ssize_t>(counts.size()));
  if (!list) return nullptr;
  for (size_t i = 0; i < counts.size(); ++i) {
    PyObject* n = PyLong_FromLongLong(counts[i]);
    if (!n) {
      Py_DECREF(list);
      return nullptr;
    }
    PyList_SET_ITEM(list, static_cast<Py_ssize_t>(i), n);
  }
  return Py_BuildValue("{s:N}", "requests", list);
}

PyObject* slice_router_n_slices(PySliceRouter* self, PyObject*) {
  return PyLong_FromLongLong(self->router->n_slices());
}

PyObject* slice_router_close(PySliceRouter* self, PyObject*) {
  auto router = self->router;
  if (!call_nogil([&] { router->close(); })) return nullptr;
  Py_RETURN_NONE;
}

PyObject* slice_router_size(PySliceRouter* self, PyObject*) {
  return PyLong_FromLongLong(self->router->size());
}

PyObject* slice_router_is_closed(PySliceRouter* self, PyObject*) {
  return PyBool_FromLong(self->router->is_closed());
}

void slice_router_dealloc(PySliceRouter* self) {
  self->router.~shared_ptr();
  Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

PyObject* slice_router_new(PyTypeObject* type, PyObject*, PyObject*) {
  PySliceRouter* self =
      reinterpret_cast<PySliceRouter*>(type->tp_alloc(type, 0));
  if (self) new (&self->router) std::shared_ptr<tbt::SliceRouter>();
  return reinterpret_cast<PyObject*>(self);
}

PyMethodDef slice_router_methods[] = {
    {"compute", reinterpret_cast<PyCFunction>(slice_router_compute), METH_O,
     nullptr},
    {"telemetry", reinterpret_cast<PyCFunction>(slice_router_telemetry),
     METH_NOARGS, nullptr},
    {"n_slices", reinterpret_cast<PyCFunction>(slice_router_n_slices),
     METH_NOARGS, nullptr},
    {"close", reinterpret_cast<PyCFunction>(slice_router_close), METH_NOARGS,
     nullptr},
    {"size", reinterpret_cast<PyCFunction>(slice_router_size), METH_NOARGS,
     nullptr},
    {"is_closed", reinterpret_cast<PyCFunction>(slice_router_is_closed),
     METH_NOARGS, nullptr},
    {nullptr, nullptr, 0, nullptr}};

PyTypeObject PySliceRouterType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// --- ReplicaRouter (ISSUE 16): replica-first with central fallback.
// Health is pushed from the Python serving hooks via set_serving() — the
// actor threads never take the GIL to route.
int replica_router_init(PyReplicaRouter* self, PyObject* args,
                        PyObject* kwargs) {
  static const char* kwlist[] = {"central", "replica", nullptr};
  PyObject *central_obj, *replica_obj;
  if (!PyArg_ParseTupleAndKeywords(args, kwargs, "OO",
                                   const_cast<char**>(kwlist), &central_obj,
                                   &replica_obj))
    return -1;
  auto central = client_from(central_obj, "central");
  if (!central) return -1;
  auto replica = client_from(replica_obj, "replica");
  if (!replica) return -1;
  try {
    self->router = std::make_shared<tbt::ReplicaRouter>(std::move(central),
                                                        std::move(replica));
    return 0;
  } catch (...) {
    set_py_error();
    return -1;
  }
}

PyObject* replica_router_compute(PyReplicaRouter* self, PyObject* arg) {
  ArrayNest nest;
  if (!nest_from_py(arg, &nest)) return nullptr;
  ArrayNest result;
  auto router = self->router;
  if (!call_nogil([&] { result = router->compute(std::move(nest)); }))
    return nullptr;
  return nest_to_py(result);
}

PyObject* replica_router_set_serving(PyReplicaRouter* self, PyObject* arg) {
  int truth = PyObject_IsTrue(arg);
  if (truth < 0) return nullptr;
  self->router->set_serving(truth == 1);
  Py_RETURN_NONE;
}

PyObject* replica_router_serving(PyReplicaRouter* self, PyObject*) {
  return PyBool_FromLong(self->router->serving());
}

PyObject* replica_router_telemetry(PyReplicaRouter* self, PyObject*) {
  return Py_BuildValue(
      "{s:L,s:L}", "replica_requests",
      static_cast<long long>(self->router->replica_requests()),
      "central_requests",
      static_cast<long long>(self->router->central_requests()));
}

PyObject* replica_router_close(PyReplicaRouter* self, PyObject*) {
  auto router = self->router;
  if (!call_nogil([&] { router->close(); })) return nullptr;
  Py_RETURN_NONE;
}

PyObject* replica_router_size(PyReplicaRouter* self, PyObject*) {
  return PyLong_FromLongLong(self->router->size());
}

PyObject* replica_router_is_closed(PyReplicaRouter* self, PyObject*) {
  return PyBool_FromLong(self->router->is_closed());
}

void replica_router_dealloc(PyReplicaRouter* self) {
  self->router.~shared_ptr();
  Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

PyObject* replica_router_new(PyTypeObject* type, PyObject*, PyObject*) {
  PyReplicaRouter* self =
      reinterpret_cast<PyReplicaRouter*>(type->tp_alloc(type, 0));
  if (self) new (&self->router) std::shared_ptr<tbt::ReplicaRouter>();
  return reinterpret_cast<PyObject*>(self);
}

PyMethodDef replica_router_methods[] = {
    {"compute", reinterpret_cast<PyCFunction>(replica_router_compute),
     METH_O, nullptr},
    {"set_serving", reinterpret_cast<PyCFunction>(replica_router_set_serving),
     METH_O, nullptr},
    {"serving", reinterpret_cast<PyCFunction>(replica_router_serving),
     METH_NOARGS, nullptr},
    {"telemetry", reinterpret_cast<PyCFunction>(replica_router_telemetry),
     METH_NOARGS, nullptr},
    {"close", reinterpret_cast<PyCFunction>(replica_router_close),
     METH_NOARGS, nullptr},
    {"size", reinterpret_cast<PyCFunction>(replica_router_size), METH_NOARGS,
     nullptr},
    {"is_closed", reinterpret_cast<PyCFunction>(replica_router_is_closed),
     METH_NOARGS, nullptr},
    {nullptr, nullptr, 0, nullptr}};

PyTypeObject PyReplicaRouterType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// --- ActorPool

// Slot hooks (slot framing, ISSUE 9): the C++ loops drive the SAME
// Python DeviceStateTable the Python pool uses, taking the GIL only at
// stream (re)connect (reset) and once per unroll boundary (read_slot) —
// never per step. Conversion borrows the returned numpy buffers
// refcounted (py_owner), so no copy is paid either. Errors cross the
// boundary TYPED (throw_py_error_typed): a StateTablePoisonedError
// becomes tbt::StateTableError so the actor rides its budgeted retry
// path while the supervisor rebuilds, instead of retiring (ISSUE 12).
[[noreturn]] void throw_py_error();
[[noreturn]] void throw_py_error_typed();

tbt::ActorPool::SlotHook make_slot_reset(std::shared_ptr<void> table_ref) {
  return [table_ref](int64_t slot) -> ArrayNest {
    PyGILState_STATE gil = PyGILState_Ensure();
    ArrayNest out;
    try {
      PyObject* table = static_cast<PyObject*>(table_ref.get());
      PyObject* ids = Py_BuildValue("[L]", static_cast<long long>(slot));
      if (!ids) throw_py_error_typed();
      PyObject* r = PyObject_CallMethod(table, "reset", "O", ids);
      Py_DECREF(ids);
      if (!r) throw_py_error_typed();
      Py_DECREF(r);
      PyObject* initial =
          PyObject_GetAttrString(table, "initial_state_host");
      if (!initial) throw_py_error_typed();
      ArrayNest nest;
      bool ok = nest_from_py(initial, &nest);
      Py_DECREF(initial);
      if (!ok) throw_py_error_typed();
      out = std::move(nest);
    } catch (...) {
      PyGILState_Release(gil);
      throw;
    }
    PyGILState_Release(gil);
    return out;
  };
}

tbt::ActorPool::SlotHook make_slot_read(std::shared_ptr<void> table_ref) {
  return [table_ref](int64_t slot) -> ArrayNest {
    PyGILState_STATE gil = PyGILState_Ensure();
    ArrayNest out;
    try {
      PyObject* table = static_cast<PyObject*>(table_ref.get());
      PyObject* piece = PyObject_CallMethod(
          table, "read_slot", "L", static_cast<long long>(slot));
      if (!piece) throw_py_error_typed();
      ArrayNest nest;
      bool ok = nest_from_py(piece, &nest);
      Py_DECREF(piece);
      if (!ok) throw_py_error_typed();
      out = std::move(nest);
    } catch (...) {
      PyGILState_Release(gil);
      throw;
    }
    PyGILState_Release(gil);
    return out;
  };
}

int pool_init(PyActorPool* self, PyObject* args, PyObject* kwargs) {
  static const char* kwlist[] = {
      "unroll_length",     "learner_queue", "inference_batcher",
      "env_server_addresses", "initial_agent_state", "connect_timeout_s",
      "max_reconnects", "state_table", "max_frame_bytes", "fault_hooks",
      "record_policy_lag", nullptr};
  long long unroll_length = 0, max_reconnects = 0;
  PyObject *queue_obj, *batcher_obj, *addresses_obj, *state_obj;
  PyObject* table_obj = Py_None;
  PyObject* max_frame_obj = Py_None;
  double connect_timeout_s = 600;
  int fault_hooks = 0;
  int record_policy_lag = 0;
  // inference_batcher is any native InferenceClient (DynamicBatcher,
  // SliceRouter, ReplicaRouter) — dispatched by client_from below, so
  // the pool serves through whatever topology the driver assembled.
  if (!PyArg_ParseTupleAndKeywords(
          args, kwargs, "LO!OOO|dLOOpp", const_cast<char**>(kwlist),
          &unroll_length, &PyBatchingQueueType, &queue_obj,
          &batcher_obj, &addresses_obj, &state_obj,
          &connect_timeout_s, &max_reconnects, &table_obj, &max_frame_obj,
          &fault_hooks, &record_policy_lag))
    return -1;
  std::shared_ptr<tbt::InferenceClient> batcher =
      client_from(batcher_obj, "inference_batcher");
  if (!batcher) return -1;
  std::vector<std::string> addresses;
  PyObject* seq = PySequence_Fast(addresses_obj, "addresses must be a sequence");
  if (!seq) return -1;
  for (Py_ssize_t i = 0; i < PySequence_Fast_GET_SIZE(seq); ++i) {
    PyObject* item = PySequence_Fast_GET_ITEM(seq, i);
    if (!PyUnicode_Check(item)) {
      Py_DECREF(seq);
      PyErr_SetString(PyExc_TypeError, "addresses must be strings");
      return -1;
    }
    addresses.push_back(PyUnicode_AsUTF8(item));
  }
  Py_DECREF(seq);
  size_t max_frame_bytes = tbt::wire::kMaxFrameBytes;
  if (max_frame_obj != Py_None) {
    long long n = PyLong_AsLongLong(max_frame_obj);
    if (PyErr_Occurred()) return -1;
    // Honor any explicit value, like wire._frame_limit: 0 (or negative,
    // clamped to 0 here) rejects every frame, surfacing the
    // misconfiguration instead of silently running with the default.
    max_frame_bytes = n > 0 ? static_cast<size_t>(n) : 0;
  }
  ArrayNest state;
  if (!nest_from_py(state_obj, &state)) return -1;
  try {
    // Deep-copy the state: actor threads use it GIL-free.
    ArrayNest owned = state.map([](const Array& a) { return a.clone(); });
    bool use_slots = table_obj != Py_None;
    tbt::ActorPool::SlotHook slot_reset, slot_read;
    if (use_slots) {
      // Same guard as the Python pool (actor_pool.py): actor i owns
      // slot i, so an undersized table would silently alias slots
      // (jax gather clamps / scatter drops out-of-bounds indices).
      PyObject* num_slots_obj = PyObject_GetAttrString(table_obj, "num_slots");
      if (!num_slots_obj) return -1;
      long long num_slots = PyLong_AsLongLong(num_slots_obj);
      Py_DECREF(num_slots_obj);
      if (PyErr_Occurred()) return -1;
      if (num_slots < static_cast<long long>(addresses.size())) {
        PyErr_Format(PyExc_ValueError,
                     "state table has %lld slots for %zd actors", num_slots,
                     addresses.size());
        return -1;
      }
      // The hooks share one owning reference to the table, dropped
      // (under the GIL) when the pool itself is destroyed.
      std::shared_ptr<void> table_ref = py_owner(table_obj);
      slot_reset = make_slot_reset(table_ref);
      slot_read = make_slot_read(table_ref);
    }
    self->pool = std::make_shared<tbt::ActorPool>(
        unroll_length,
        reinterpret_cast<PyBatchingQueue*>(queue_obj)->queue,
        std::move(batcher),
        std::move(addresses), std::move(owned), connect_timeout_s,
        max_reconnects, use_slots, std::move(slot_reset),
        std::move(slot_read), max_frame_bytes, fault_hooks != 0,
        record_policy_lag != 0);
    return 0;
  } catch (...) {
    set_py_error();
    return -1;
  }
}

PyObject* pool_run(PyActorPool* self, PyObject*) {
  auto pool = self->pool;
  if (!call_nogil([&] { pool->run(); })) return nullptr;
  Py_RETURN_NONE;
}

PyObject* pool_count(PyActorPool* self, PyObject*) {
  return PyLong_FromLongLong(self->pool->count());
}

PyObject* pool_reconnect_count(PyActorPool* self, PyObject*) {
  return PyLong_FromLongLong(self->pool->reconnect_count());
}

PyObject* pool_live_actors(PyActorPool* self, PyObject*) {
  return PyLong_FromLongLong(self->pool->live_actors());
}

// Retired-actor error messages, oldest first — the same `.errors`
// surface the Python pool exposes (strings here: the C++ exceptions
// have no Python identity), read by the driver's health monitor.
PyObject* pool_errors_getter(PyActorPool* self, void*) {
  std::vector<std::string> msgs = self->pool->error_messages();
  PyObject* list = PyList_New(static_cast<Py_ssize_t>(msgs.size()));
  if (!list) return nullptr;
  for (size_t i = 0; i < msgs.size(); ++i) {
    PyObject* s =
        PyUnicode_FromStringAndSize(msgs[i].data(), msgs[i].size());
    if (!s) {
      Py_DECREF(list);
      return nullptr;
    }
    PyList_SET_ITEM(list, static_cast<Py_ssize_t>(i), s);
  }
  return list;
}

// --- chaos entry points (resilience/chaos.py ChaosController, native
// path): each returns True when the fault observably landed, False when
// the target is momentarily un-injectable (the controller retries on a
// later tick, keeping injected counts exact). ValueError when the pool
// was built without fault_hooks=True — a miswired driver should fail
// loudly, not silently abandon every fault.
tbt::FaultHooks* pool_hooks_or_raise(PyActorPool* self) {
  tbt::FaultHooks* hooks = self->pool->fault_hooks();
  if (!hooks)
    PyErr_SetString(PyExc_ValueError,
                    "ActorPool was built without fault_hooks=True");
  return hooks;
}

PyObject* pool_chaos_sever(PyActorPool* self, PyObject* arg) {
  long long actor = PyLong_AsLongLong(arg);
  if (PyErr_Occurred()) return nullptr;
  tbt::FaultHooks* hooks = pool_hooks_or_raise(self);
  if (!hooks) return nullptr;
  bool ok = false;
  if (!call_nogil([&] { ok = hooks->sever(actor); })) return nullptr;
  return PyBool_FromLong(ok);
}

PyObject* pool_chaos_window(PyActorPool* self, PyObject* args,
                            PyObject* kwargs) {
  static const char* kwlist[] = {"actor", "kind", "duration_s", "delay_s",
                                 nullptr};
  long long actor = 0;
  const char* kind = nullptr;
  double duration_s = 1.0, delay_s = 0.05;
  if (!PyArg_ParseTupleAndKeywords(args, kwargs, "Ls|dd",
                                   const_cast<char**>(kwlist), &actor,
                                   &kind, &duration_s, &delay_s))
    return nullptr;
  bool is_delay;
  if (std::strcmp(kind, "transport_delay") == 0) {
    is_delay = true;
  } else if (std::strcmp(kind, "transport_blackhole") == 0) {
    is_delay = false;
  } else {
    PyErr_Format(PyExc_ValueError, "unknown window kind %s", kind);
    return nullptr;
  }
  tbt::FaultHooks* hooks = pool_hooks_or_raise(self);
  if (!hooks) return nullptr;
  bool ok = false;
  if (!call_nogil(
          [&] { ok = hooks->arm_window(actor, is_delay, duration_s,
                                       delay_s); }))
    return nullptr;
  return PyBool_FromLong(ok);
}

PyObject* pool_chaos_corrupt_ring(PyActorPool* self, PyObject* args,
                                  PyObject* kwargs) {
  static const char* kwlist[] = {"actor", "header", nullptr};
  long long actor = 0;
  int header = 1;
  if (!PyArg_ParseTupleAndKeywords(args, kwargs, "L|p",
                                   const_cast<char**>(kwlist), &actor,
                                   &header))
    return nullptr;
  tbt::FaultHooks* hooks = pool_hooks_or_raise(self);
  if (!hooks) return nullptr;
  bool ok = false;
  if (!call_nogil(
          [&] { ok = hooks->corrupt_recv_ring(actor, header != 0); }))
    return nullptr;
  return PyBool_FromLong(ok);
}

// Cumulative wire/step counters — the driver folds tick deltas into the
// telemetry registry (runtime/native.py NativeTelemetryFolder).
PyObject* pool_telemetry(PyActorPool* self, PyObject*) {
  tbt::ActorPool::Telemetry t = self->pool->telemetry();
  return Py_BuildValue(
      "{s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L}", "env_steps",
      static_cast<long long>(t.env_steps), "connects",
      static_cast<long long>(t.connects), "reconnects",
      static_cast<long long>(t.reconnects), "batch_retries",
      static_cast<long long>(t.batch_retries), "shed_resubmits",
      static_cast<long long>(t.shed_resubmits), "bytes_up",
      static_cast<long long>(t.bytes_up), "bytes_down",
      static_cast<long long>(t.bytes_down), "ring_doorbell_waits",
      static_cast<long long>(t.ring_doorbell_waits), "ring_recheck_wakeups",
      static_cast<long long>(t.ring_recheck_wakeups));
}

PyObject* pool_first_error_message(PyActorPool* self, PyObject*) {
  std::string msg = self->pool->first_error_message();
  if (msg.empty()) Py_RETURN_NONE;
  return PyUnicode_FromString(msg.c_str());
}

void pool_dealloc(PyActorPool* self) {
  self->pool.~shared_ptr();
  Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

PyObject* pool_new(PyTypeObject* type, PyObject*, PyObject*) {
  PyActorPool* self = reinterpret_cast<PyActorPool*>(type->tp_alloc(type, 0));
  if (self) new (&self->pool) std::shared_ptr<tbt::ActorPool>();
  return reinterpret_cast<PyObject*>(self);
}

PyMethodDef pool_methods[] = {
    {"run", reinterpret_cast<PyCFunction>(pool_run), METH_NOARGS, nullptr},
    {"count", reinterpret_cast<PyCFunction>(pool_count), METH_NOARGS,
     nullptr},
    {"first_error_message",
     reinterpret_cast<PyCFunction>(pool_first_error_message), METH_NOARGS,
     nullptr},
    {"reconnect_count", reinterpret_cast<PyCFunction>(pool_reconnect_count),
     METH_NOARGS, nullptr},
    {"live_actors", reinterpret_cast<PyCFunction>(pool_live_actors),
     METH_NOARGS, nullptr},
    {"chaos_sever", reinterpret_cast<PyCFunction>(pool_chaos_sever),
     METH_O, nullptr},
    {"chaos_window",
     reinterpret_cast<PyCFunction>(
         reinterpret_cast<void (*)()>(pool_chaos_window)),
     METH_VARARGS | METH_KEYWORDS, nullptr},
    {"chaos_corrupt_ring",
     reinterpret_cast<PyCFunction>(
         reinterpret_cast<void (*)()>(pool_chaos_corrupt_ring)),
     METH_VARARGS | METH_KEYWORDS, nullptr},
    {"telemetry", reinterpret_cast<PyCFunction>(pool_telemetry),
     METH_NOARGS, nullptr},
    {nullptr, nullptr, 0, nullptr}};

PyGetSetDef pool_getset[] = {
    {"errors", reinterpret_cast<getter>(pool_errors_getter), nullptr,
     nullptr, nullptr},
    {nullptr, nullptr, nullptr, nullptr, nullptr}};

PyTypeObject PyActorPoolType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// --- EnvServer
// C++ socket/threading mechanics (csrc/env_server.h) + Python hooks that
// take the GIL only around env calls, mirroring the reference's embedding
// of Python envs in a C++ gRPC server (rpcenv.cc:36-156, GIL handling at
// 47/95). Wraps each raw env in the same torchbeast_tpu Environment
// adapter the Python server uses, so episode accounting and auto-reset
// semantics are literally shared code.

namespace wire = tbt::wire;

// RAII GIL for hook bodies running on C++ server threads.
struct GILGuard {
  PyGILState_STATE state;
  GILGuard() : state(PyGILState_Ensure()) {}
  ~GILGuard() { PyGILState_Release(state); }
};

// RAII owned reference: decrefs on every exit path (hook bodies throw
// through C++ exceptions, which would skip manual Py_DECREFs).
struct PyRef {
  PyObject* p;
  explicit PyRef(PyObject* p) : p(p) {}
  ~PyRef() { Py_XDECREF(p); }
  PyRef(const PyRef&) = delete;
  PyRef& operator=(const PyRef&) = delete;
  explicit operator bool() const { return p != nullptr; }
};

// Fetch + clear the pending Python error; returns "Type: message" and
// reports the exception type's name through *type_name.
std::string fetch_py_error(std::string* type_name) {
  PyObject *ptype = nullptr, *pvalue = nullptr, *ptraceback = nullptr;
  PyErr_Fetch(&ptype, &pvalue, &ptraceback);
  std::string msg = "python error";
  if (ptype) {
    PyObject* name = PyObject_GetAttrString(ptype, "__name__");
    if (name && PyUnicode_Check(name)) {
      msg = PyUnicode_AsUTF8(name);
      *type_name = msg;
    }
    Py_XDECREF(name);
  }
  if (pvalue) {
    PyObject* str = PyObject_Str(pvalue);
    if (str && PyUnicode_Check(str)) {
      msg += ": ";
      msg += PyUnicode_AsUTF8(str);
    }
    Py_XDECREF(str);
  }
  Py_XDECREF(ptype);
  Py_XDECREF(pvalue);
  Py_XDECREF(ptraceback);
  PyErr_Clear();
  return msg;
}

// Raise the pending Python error as a C++ exception (the server reports
// it to the client as an error frame).
[[noreturn]] void throw_py_error() {
  std::string type_name;
  throw std::runtime_error(fetch_py_error(&type_name));
}

// Slot-hook variant (ISSUE 12): the DeviceStateTable's typed poison
// error crosses the GIL boundary as tbt::StateTableError so the C++
// actor loop distinguishes "the table is mid-rebuild, retry under
// budget" from a real actor bug (csrc/actor_pool.h guarded_loop).
[[noreturn]] void throw_py_error_typed() {
  std::string type_name;
  std::string msg = fetch_py_error(&type_name);
  if (type_name == "StateTablePoisonedError")
    throw tbt::StateTableError(msg);
  throw std::runtime_error(msg);
}

// Copy a numpy-coercible Python value into an owned wire Array (a deep
// copy: the result outlives the GIL scope, so it must not borrow numpy
// buffers the way nest_from_py does).
Array array_copy_from_py(PyObject* obj) {
  PyArrayObject* arr = reinterpret_cast<PyArrayObject*>(
      PyArray_FROM_OF(obj, NPY_ARRAY_C_CONTIGUOUS | NPY_ARRAY_ALIGNED));
  if (!arr) throw_py_error();
  DType dtype;
  if (!npy_to_dtype(PyArray_TYPE(arr), &dtype)) {
    int t = PyArray_TYPE(arr);
    Py_DECREF(arr);
    throw std::invalid_argument("unsupported step dtype " +
                                std::to_string(t));
  }
  std::vector<int64_t> shape(PyArray_NDIM(arr));
  for (int i = 0; i < PyArray_NDIM(arr); ++i) shape[i] = PyArray_DIM(arr, i);
  Array out(dtype, std::move(shape));
  std::memcpy(out.mutable_data(), PyArray_DATA(arr), out.nbytes());
  Py_DECREF(arr);
  return out;
}

// Step dict (from Environment.initial()/step()) -> wire message. Adds
// type="step" and, when non-negative, num_actions (the initial Step
// doubles as the env spec, matching runtime/env_server.py).
// Borrows `dict` (caller keeps ownership; safe against throws).
wire::ValueNest step_to_wire(PyObject* dict, int64_t num_actions) {
  if (!PyDict_Check(dict)) {
    throw std::invalid_argument("env step must return a dict");
  }
  wire::ValueNest::Dict out;
  out.emplace("type", wire::ValueNest(wire::Value::of_string("step")));
  if (num_actions >= 0)
    out.emplace("num_actions",
                wire::ValueNest(wire::Value::of_int(num_actions)));
  PyObject *key, *value;
  Py_ssize_t pos = 0;
  while (PyDict_Next(dict, &pos, &key, &value)) {
    if (!PyUnicode_Check(key))
      throw std::invalid_argument("step dict keys must be str");
    out.emplace(PyUnicode_AsUTF8(key),
                wire::ValueNest(wire::Value::of(array_copy_from_py(value))));
  }
  return wire::ValueNest(std::move(out));
}

int64_t action_from_wire(const wire::ValueNest& msg) {
  if (!msg.is_dict()) throw std::invalid_argument("expected action dict");
  const auto& dict = msg.dict();
  auto type_it = dict.find("type");
  if (type_it == dict.end() || !type_it->second.is_leaf() ||
      type_it->second.leaf().kind != wire::Value::Kind::kString ||
      type_it->second.leaf().s != "action")
    throw std::invalid_argument("expected an action message");
  auto it = dict.find("action");
  if (it == dict.end() || !it->second.is_leaf())
    throw std::invalid_argument("action message missing 'action'");
  const wire::Value& v = it->second.leaf();
  if (v.kind == wire::Value::Kind::kInt) return v.i;
  if (v.kind == wire::Value::Kind::kArray) {
    const Array& a = v.array;
    if (a.numel() != 1)
      throw std::invalid_argument("action array must have one element");
    switch (a.dtype()) {
      case DType::kI32:
        return *reinterpret_cast<const int32_t*>(a.data());
      case DType::kI64:
        return *reinterpret_cast<const int64_t*>(a.data());
      default:
        throw std::invalid_argument("action array must be int32/int64");
    }
  }
  throw std::invalid_argument("action must be an int");
}

// Per-stream Python state: the Environment adapter instance.
struct PyStreamState {
  PyObject* env = nullptr;
};

tbt::StreamHooks make_py_hooks(PyObject* env_init) {
  auto state = std::make_shared<PyStreamState>();
  tbt::StreamHooks hooks;
  hooks.initial = [env_init, state]() -> wire::ValueNest {
    GILGuard gil;
    PyObject* raw = PyObject_CallNoArgs(env_init);
    if (!raw) throw_py_error();
    PyObject* envs_mod = PyImport_ImportModule("torchbeast_tpu.envs");
    if (!envs_mod) {
      Py_DECREF(raw);
      throw_py_error();
    }
    PyObject* na =
        PyObject_CallMethod(envs_mod, "num_actions_of", "O", raw);
    Py_DECREF(envs_mod);
    if (!na) {
      Py_DECREF(raw);
      throw_py_error();
    }
    int64_t num_actions = PyLong_AsLongLong(na);
    Py_DECREF(na);
    PyObject* env_mod =
        PyImport_ImportModule("torchbeast_tpu.envs.environment");
    if (!env_mod) {
      Py_DECREF(raw);
      throw_py_error();
    }
    PyObject* env =
        PyObject_CallMethod(env_mod, "Environment", "O", raw);
    Py_DECREF(env_mod);
    Py_DECREF(raw);
    if (!env) throw_py_error();
    state->env = env;
    PyRef step(PyObject_CallMethod(env, "initial", nullptr));
    if (!step) throw_py_error();
    return step_to_wire(step.p, num_actions);
  };
  hooks.step = [state](const wire::ValueNest& msg) -> wire::ValueNest {
    int64_t action = action_from_wire(msg);  // no GIL needed
    GILGuard gil;
    PyRef step(PyObject_CallMethod(
        state->env, "step", "L", static_cast<long long>(action)));
    if (!step) throw_py_error();
    return step_to_wire(step.p, -1);
  };
  hooks.close = [state] {
    if (!state->env) return;
    GILGuard gil;
    PyObject* r = PyObject_CallMethod(state->env, "close", nullptr);
    if (r)
      Py_DECREF(r);
    else
      PyErr_Clear();
    Py_DECREF(state->env);
    state->env = nullptr;
  };
  return hooks;
}

struct PyEnvServer {
  PyObject_HEAD
  std::shared_ptr<tbt::EnvServer> server;
  PyObject* env_init;
};

PyTypeObject PyEnvServerType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

int env_server_init(PyEnvServer* self, PyObject* args, PyObject* kwargs) {
  static const char* kwlist[] = {"env_init", "address", nullptr};
  PyObject* env_init;
  const char* address;
  if (!PyArg_ParseTupleAndKeywords(args, kwargs, "Os",
                                   const_cast<char**>(kwlist), &env_init,
                                   &address))
    return -1;
  if (!PyCallable_Check(env_init)) {
    PyErr_SetString(PyExc_TypeError, "env_init must be callable");
    return -1;
  }
  Py_INCREF(env_init);
  self->env_init = env_init;
  try {
    self->server = std::make_shared<tbt::EnvServer>(
        address, [env_init] { return make_py_hooks(env_init); });
    return 0;
  } catch (...) {
    set_py_error();
    return -1;
  }
}

PyObject* env_server_run(PyEnvServer* self, PyObject*) {
  auto server = self->server;
  if (!call_nogil([&] { server->run(); })) return nullptr;
  // run() returns after stop(); make sure stream threads are gone before
  // the caller proceeds to tear anything down.
  if (!call_nogil([&] { server->join_all(); })) return nullptr;
  Py_RETURN_NONE;
}

PyObject* env_server_stop(PyEnvServer* self, PyObject*) {
  auto server = self->server;
  if (!call_nogil([&] { server->stop(); })) return nullptr;
  Py_RETURN_NONE;
}

void env_server_dealloc(PyEnvServer* self) {
  // EnvServer's destructor stops and JOINS stream threads, whose hooks
  // take the GIL — joining while holding it would deadlock.
  auto release = [&] { self->server.reset(); };
  if (self->server) call_nogil(release);
  self->server.~shared_ptr();
  Py_XDECREF(self->env_init);
  Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

PyObject* env_server_new(PyTypeObject* type, PyObject*, PyObject*) {
  PyEnvServer* self =
      reinterpret_cast<PyEnvServer*>(type->tp_alloc(type, 0));
  if (self) {
    new (&self->server) std::shared_ptr<tbt::EnvServer>();
    self->env_init = nullptr;
  }
  return reinterpret_cast<PyObject*>(self);
}

PyMethodDef env_server_methods[] = {
    {"run", reinterpret_cast<PyCFunction>(env_server_run), METH_NOARGS,
     nullptr},
    {"stop", reinterpret_cast<PyCFunction>(env_server_stop), METH_NOARGS,
     nullptr},
    {nullptr, nullptr, 0, nullptr}};

// ------------------------------------------------- module functions
// Cross-language codec pins: encode/decode through the C++ wire codec,
// full frame bytes (u32 header included). tests/test_native.py asserts
// wire_encode(x) == wire.encode(x) and wire.decode round-trips both
// ways, which pins tags/dtypes/layout in ANGER (beastlint WIRE-PARITY
// pins them textually).
PyObject* py_wire_encode(PyObject*, PyObject* arg) {
  tbt::wire::ValueNest value;
  if (!py_to_value(arg, &value)) return nullptr;
  try {
    std::vector<uint8_t> framed = tbt::wire::encode(value);
    return PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(framed.data()),
        static_cast<Py_ssize_t>(framed.size()));
  } catch (...) {
    set_py_error();
    return nullptr;
  }
}

PyObject* py_wire_decode(PyObject*, PyObject* arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_CONTIG_RO) != 0) return nullptr;
  PyObject* out = nullptr;
  try {
    const uint8_t* data = static_cast<const uint8_t*>(view.buf);
    size_t size = static_cast<size_t>(view.len);
    if (size < 4) throw tbt::wire::WireError("wire: truncated frame");
    uint32_t length = tbt::shm::load_u32le(data);
    if (length != size - 4)
      throw tbt::wire::WireError("wire: frame length mismatch");
    // Deep-copy into an owned buffer so decoded arrays outlive `arg`.
    auto payload = std::make_shared<std::vector<uint8_t>>(
        data + 4, data + size);
    tbt::wire::ValueNest value =
        tbt::wire::decode(payload->data(), payload->size(), payload);
    out = value_to_py(value);
  } catch (...) {
    set_py_error();
  }
  PyBuffer_Release(&view);
  return out;
}

// Native-transport RTT bench (benchmarks/wire_bench.py native rows): the
// C++ client stack end to end — connect (tcp/unix/shm incl. handshake),
// read the initial step, then action-down/step-up round trips for
// `seconds`, entirely GIL-free. Returns (iters, elapsed_s).
PyObject* py_bench_client_rtt(PyObject*, PyObject* args, PyObject* kwargs) {
  static const char* kwlist[] = {"address", "seconds", "warmup", nullptr};
  const char* address;
  double seconds = 1.0;
  long long warmup = 50;
  if (!PyArg_ParseTupleAndKeywords(args, kwargs, "s|dL",
                                   const_cast<char**>(kwlist), &address,
                                   &seconds, &warmup))
    return nullptr;
  long long iters = 0;
  double elapsed = 0.0;
  bool ok = call_nogil([&] {
    auto t = tbt::shm::connect_transport(address, 30.0);
    t->recv();  // initial step
    tbt::wire::ValueNest::Dict action;
    action.emplace("type",
                   tbt::wire::ValueNest(tbt::wire::Value::of_string("action")));
    action.emplace("action",
                   tbt::wire::ValueNest(tbt::wire::Value::of_int(1)));
    tbt::wire::ValueNest action_msg(std::move(action));
    for (long long i = 0; i < warmup; ++i) {
      t->send(action_msg);
      t->recv();
    }
    auto t0 = std::chrono::steady_clock::now();
    auto deadline = t0 + std::chrono::duration_cast<
                             std::chrono::steady_clock::duration>(
                             std::chrono::duration<double>(seconds));
    while (std::chrono::steady_clock::now() < deadline) {
      t->send(action_msg);
      t->recv();
      ++iters;
    }
    elapsed = std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - t0)
                  .count();
    t->unlink_segments();
    t->close();
  });
  if (!ok) return nullptr;
  return Py_BuildValue("(Ld)", iters, elapsed);
}

// Adaptive-recheck policy simulator (tests/test_native.py): drive the
// C++ AdaptiveRecheck with a sequence of wait outcomes (truthy = ended
// by the recheck timeout) and return the bound (ms) after each record —
// pins the tighten/relax behavior without standing up a live ring.
PyObject* py_adaptive_recheck_sim(PyObject*, PyObject* arg) {
  PyObject* seq = PySequence_Fast(arg, "expected a sequence of outcomes");
  if (!seq) return nullptr;
  tbt::shm::AdaptiveRecheck policy;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  PyObject* out = PyList_New(n);
  if (!out) {
    Py_DECREF(seq);
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    int truth = PyObject_IsTrue(PySequence_Fast_GET_ITEM(seq, i));
    if (truth < 0) {
      Py_DECREF(seq);
      Py_DECREF(out);
      return nullptr;
    }
    policy.record(truth == 1);
    PyObject* bound = PyLong_FromLong(policy.bound_ms());
    if (!bound) {
      Py_DECREF(seq);
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, i, bound);
  }
  Py_DECREF(seq);
  return out;
}

// Routing-hash pins (ISSUE 16): the C++ splitmix64 finalizer and the
// slot->slice map, exposed so tests/test_native_routing.py can assert
// bit-identity against runtime/placement.py _mix64 in ANGER (beastlint
// ROUTE-PARITY pins the constants textually).
PyObject* py_splitmix64(PyObject*, PyObject* arg) {
  // Mask conversion wraps negatives mod 2^64 — Python's `& (2**64-1)`.
  unsigned long long x = PyLong_AsUnsignedLongLongMask(arg);
  if (PyErr_Occurred()) return nullptr;
  return PyLong_FromUnsignedLongLong(tbt::splitmix64(x));
}

PyObject* py_slice_for_slot(PyObject*, PyObject* args, PyObject* kwargs) {
  static const char* kwlist[] = {"slot", "n_slices", nullptr};
  long long slot = 0, n_slices = 0;
  if (!PyArg_ParseTupleAndKeywords(args, kwargs, "LL",
                                   const_cast<char**>(kwlist), &slot,
                                   &n_slices))
    return nullptr;
  try {
    return PyLong_FromLongLong(tbt::slice_for_slot(slot, n_slices));
  } catch (...) {
    set_py_error();
    return nullptr;
  }
}

// ---------------------------------------------------------------- module
PyMethodDef module_functions[] = {
    {"wire_encode", reinterpret_cast<PyCFunction>(py_wire_encode), METH_O,
     nullptr},
    {"wire_decode", reinterpret_cast<PyCFunction>(py_wire_decode), METH_O,
     nullptr},
    {"adaptive_recheck_sim",
     reinterpret_cast<PyCFunction>(py_adaptive_recheck_sim), METH_O,
     nullptr},
    {"bench_client_rtt",
     reinterpret_cast<PyCFunction>(
         reinterpret_cast<void (*)()>(py_bench_client_rtt)),
     METH_VARARGS | METH_KEYWORDS, nullptr},
    {"splitmix64", reinterpret_cast<PyCFunction>(py_splitmix64), METH_O,
     nullptr},
    {"slice_for_slot",
     reinterpret_cast<PyCFunction>(
         reinterpret_cast<void (*)()>(py_slice_for_slot)),
     METH_VARARGS | METH_KEYWORDS, nullptr},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef module_def = {
    PyModuleDef_HEAD_INIT, "_tbt_core",
    "Native runtime core (queues, dynamic batcher, actor pool)", -1,
    module_functions,
};

void init_type(PyTypeObject* type, const char* name, size_t basicsize,
               newfunc tp_new, initproc tp_init, destructor tp_dealloc,
               PyMethodDef* methods, getiterfunc tp_iter,
               iternextfunc tp_iternext, PySequenceMethods* as_seq) {
  type->tp_name = name;
  type->tp_basicsize = static_cast<Py_ssize_t>(basicsize);
  type->tp_flags = Py_TPFLAGS_DEFAULT;
  type->tp_new = tp_new;
  type->tp_init = tp_init;
  type->tp_dealloc = tp_dealloc;
  type->tp_methods = methods;
  type->tp_iter = tp_iter;
  type->tp_iternext = tp_iternext;
  type->tp_as_sequence = as_seq;
}

}  // namespace

PyMODINIT_FUNC PyInit__tbt_core(void) {
  import_array();

  init_type(&PyBatchingQueueType, "_tbt_core.BatchingQueue",
            sizeof(PyBatchingQueue), queue_new,
            reinterpret_cast<initproc>(queue_init),
            reinterpret_cast<destructor>(queue_dealloc), queue_methods,
            queue_iter, reinterpret_cast<iternextfunc>(queue_iternext),
            nullptr);
  init_type(&PyBatchType, "_tbt_core.Batch", sizeof(PyBatch), nullptr,
            nullptr, reinterpret_cast<destructor>(batch_dealloc),
            batch_methods, nullptr, nullptr, &batch_as_sequence);
  init_type(&PyDynamicBatcherType, "_tbt_core.DynamicBatcher",
            sizeof(PyDynamicBatcher), batcher_new,
            reinterpret_cast<initproc>(batcher_init),
            reinterpret_cast<destructor>(batcher_dealloc), batcher_methods,
            queue_iter, reinterpret_cast<iternextfunc>(batcher_iternext),
            nullptr);
  init_type(&PySliceRouterType, "_tbt_core.SliceRouter",
            sizeof(PySliceRouter), slice_router_new,
            reinterpret_cast<initproc>(slice_router_init),
            reinterpret_cast<destructor>(slice_router_dealloc),
            slice_router_methods, nullptr, nullptr, nullptr);
  init_type(&PyReplicaRouterType, "_tbt_core.ReplicaRouter",
            sizeof(PyReplicaRouter), replica_router_new,
            reinterpret_cast<initproc>(replica_router_init),
            reinterpret_cast<destructor>(replica_router_dealloc),
            replica_router_methods, nullptr, nullptr, nullptr);
  init_type(&PyActorPoolType, "_tbt_core.ActorPool", sizeof(PyActorPool),
            pool_new, reinterpret_cast<initproc>(pool_init),
            reinterpret_cast<destructor>(pool_dealloc), pool_methods, nullptr,
            nullptr, nullptr);
  PyActorPoolType.tp_getset = pool_getset;
  init_type(&PyEnvServerType, "_tbt_core.EnvServer", sizeof(PyEnvServer),
            env_server_new, reinterpret_cast<initproc>(env_server_init),
            reinterpret_cast<destructor>(env_server_dealloc),
            env_server_methods, nullptr, nullptr, nullptr);

  if (PyType_Ready(&PyBatchingQueueType) < 0 ||
      PyType_Ready(&PyBatchType) < 0 ||
      PyType_Ready(&PyDynamicBatcherType) < 0 ||
      PyType_Ready(&PySliceRouterType) < 0 ||
      PyType_Ready(&PyReplicaRouterType) < 0 ||
      PyType_Ready(&PyActorPoolType) < 0 ||
      PyType_Ready(&PyEnvServerType) < 0)
    return nullptr;

  PyObject* module = PyModule_Create(&module_def);
  if (!module) return nullptr;

  ClosedBatchingQueueError = PyErr_NewException(
      "_tbt_core.ClosedBatchingQueue", PyExc_RuntimeError, nullptr);
  AsyncErrorError =
      PyErr_NewException("_tbt_core.AsyncError", PyExc_RuntimeError, nullptr);
  // ShedError bases: the C++ AsyncError twin AND (when importable) the
  // Python runtime's typed ShedError, so `except ShedError` in
  // torchbeast_tpu code catches sheds from either runtime with one
  // clause. The extension stays importable standalone (tests build it
  // without the package on sys.path) — the extra base is best-effort.
  {
    PyObject* bases = nullptr;
    PyObject* mod = PyImport_ImportModule("torchbeast_tpu.runtime.errors");
    if (mod) {
      PyObject* py_shed = PyObject_GetAttrString(mod, "ShedError");
      Py_DECREF(mod);
      if (py_shed) {
        bases = PyTuple_Pack(2, AsyncErrorError, py_shed);
        Py_DECREF(py_shed);
      }
    }
    if (!bases) {
      PyErr_Clear();
      bases = PyTuple_Pack(1, AsyncErrorError);
    }
    ShedErrorError =
        PyErr_NewException("_tbt_core.ShedError", bases, nullptr);
    Py_XDECREF(bases);
  }

  Py_INCREF(&PyBatchingQueueType);
  Py_INCREF(&PyBatchType);
  Py_INCREF(&PyDynamicBatcherType);
  Py_INCREF(&PySliceRouterType);
  Py_INCREF(&PyReplicaRouterType);
  Py_INCREF(&PyActorPoolType);
  PyModule_AddObject(module, "BatchingQueue",
                     reinterpret_cast<PyObject*>(&PyBatchingQueueType));
  PyModule_AddObject(module, "Batch",
                     reinterpret_cast<PyObject*>(&PyBatchType));
  PyModule_AddObject(module, "DynamicBatcher",
                     reinterpret_cast<PyObject*>(&PyDynamicBatcherType));
  PyModule_AddObject(module, "SliceRouter",
                     reinterpret_cast<PyObject*>(&PySliceRouterType));
  PyModule_AddObject(module, "ReplicaRouter",
                     reinterpret_cast<PyObject*>(&PyReplicaRouterType));
  PyModule_AddObject(module, "ActorPool",
                     reinterpret_cast<PyObject*>(&PyActorPoolType));
  Py_INCREF(&PyEnvServerType);
  PyModule_AddObject(module, "EnvServer",
                     reinterpret_cast<PyObject*>(&PyEnvServerType));
  PyModule_AddObject(module, "ClosedBatchingQueue", ClosedBatchingQueueError);
  PyModule_AddObject(module, "AsyncError", AsyncErrorError);
  PyModule_AddObject(module, "ShedError", ShedErrorError);
  // Extension API generation (runtime/native.py REQUIRED_API_VERSION):
  // 1 = the ISSUE 14 shed protocol; 2 = the ISSUE 16 serving plane
  // (routers, continuous batching, record_policy_lag). The default-on
  // native runtime refuses stale builds instead of silently serving
  // central-only without admission control.
  PyModule_AddIntConstant(module, "API_VERSION", 2);
  return module;
}
