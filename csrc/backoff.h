// Decorrelated-jitter exponential backoff — the C++ port of
// torchbeast_tpu/resilience/backoff.py's Backoff, for the native actor
// pool's reconnect loop (ISSUE 12): a dead env-server address must not
// be re-dialed in a tight loop, and a mass server restart must not
// thundering-herd the fresh listener. Same schedule as the Python
// class: next delay = uniform(base_s, min(cap_s, prev * 3)), reset on
// proven recovery.

#pragma once

#include <algorithm>
#include <chrono>
#include <functional>
#include <random>
#include <thread>

namespace tbt {

class Backoff {
 public:
  explicit Backoff(double base_s = 0.1, double cap_s = 2.0,
                   unsigned seed = std::random_device{}())
      : base_s_(base_s), cap_s_(cap_s), rng_(seed) {}

  // The next jittered delay (advances the schedule, no sleeping).
  double next_delay() {
    double hi = std::max(base_s_, std::min(cap_s_, prev_ * 3.0));
    std::uniform_real_distribution<double> dist(base_s_, hi);
    double delay = dist(rng_);
    prev_ = delay;
    return delay;
  }

  // Sleep the next jittered delay in short slices so `abort` (pipeline
  // shutdown) cuts the wait short — the C++ twin of
  // Backoff.sleep(wake=Event). Returns the delay drawn.
  double sleep(const std::function<bool()>& abort = nullptr) {
    double delay = next_delay();
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration_cast<
                        std::chrono::steady_clock::duration>(
                        std::chrono::duration<double>(delay));
    while (std::chrono::steady_clock::now() < deadline) {
      if (abort && abort()) break;
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
    }
    return delay;
  }

  // Re-arm after proven recovery: the next delay starts from base_s.
  void reset() { prev_ = 0.0; }

 private:
  const double base_s_;
  const double cap_s_;
  double prev_ = 0.0;
  std::mt19937 rng_;
};

}  // namespace tbt
