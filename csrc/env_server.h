// EnvServer: hosts environment streams behind the framed-socket wire
// protocol, mechanics in C++ (the reference embeds Python envs in a C++
// gRPC server the same way, rpcenv.cc:36-156).
//
// The header is Python-free: per-stream behavior is injected as hooks
// (initial / step / close). The Python binding (pymodule.cc) supplies
// hooks that take the GIL only around the env calls, so all socket I/O
// and wire codec work runs GIL-free — the reason to host the server in
// C++ at all (reference: gil_scoped_acquire only around Python calls,
// rpcenv.cc:47,95).

#pragma once

#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <atomic>
#include <cstring>
#include <functional>
#include <mutex>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

#include "client.h"
#include "shm.h"
#include "wire.h"

namespace tbt {

// Per-stream behavior. Hooks may throw; the server reports the error to
// the client as an error frame and drops the stream. close() always runs.
struct StreamHooks {
  std::function<wire::ValueNest()> initial;
  std::function<wire::ValueNest(const wire::ValueNest&)> step;
  std::function<void()> close;
};

class EnvServer {
 public:
  EnvServer(std::string address, std::function<StreamHooks()> hook_factory)
      : address_(std::move(address)),
        hook_factory_(std::move(hook_factory)) {}

  ~EnvServer() {
    stop();
    join_all();
  }

  EnvServer(const EnvServer&) = delete;
  EnvServer& operator=(const EnvServer&) = delete;

  // Bind + accept loop; blocks until stop() (reference Server::run,
  // rpcenv.cc:142-156). Each accepted connection gets its own thread and
  // a fresh hook set (fresh env per stream, rpcenv.cc:72).
  void run() {
    bind_and_listen();
    running_.store(true);
    while (running_.load()) {
      int listen_fd = listen_fd_.load();
      if (listen_fd < 0) break;
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (!running_.load()) break;
        continue;  // transient accept failure (EINTR etc.)
      }
      std::lock_guard<std::mutex> lock(mu_);
      if (!running_.load()) {
        ::close(fd);
        break;
      }
      conn_fds_.push_back(fd);
      reap_finished_locked();
      threads_.emplace_back([this, fd] {
        serve_stream(fd);
        std::lock_guard<std::mutex> l(mu_);
        finished_.push_back(std::this_thread::get_id());
      });
    }
  }

  // Close the listen socket and sever live streams. Idempotent; safe to
  // call concurrently with run() (the fd hand-off is an atomic exchange).
  void stop() {
    running_.store(false);
    int fd = listen_fd_.exchange(-1);
    if (fd >= 0) {
      ::shutdown(fd, SHUT_RDWR);
      ::close(fd);
      if (!unix_path_.empty()) ::unlink(unix_path_.c_str());
    }
    std::lock_guard<std::mutex> lock(mu_);
    for (int conn : conn_fds_) ::shutdown(conn, SHUT_RDWR);
  }

  void join_all() {
    std::vector<std::thread> threads;
    {
      std::lock_guard<std::mutex> lock(mu_);
      threads.swap(threads_);
    }
    for (auto& t : threads)
      if (t.joinable()) t.join();
  }

 private:
  void bind_and_listen() {
    int fd = -1;
    if (address_.rfind("unix:", 0) == 0 || shm::is_shm_address(address_)) {
      // shm addresses resolve to their unix doorbell socket; the
      // per-connection rings are created at accept time
      // (shm_server_transport), names exchanged in the handshake —
      // same protocol as runtime/transport.py server_transport.
      // beastlint: disable=CXX-LOCK-DISCIPLINE  write-before-spawn: stream threads that read shm_ are created after bind_and_listen returns, by the same thread
      shm_ = shm::is_shm_address(address_);
      // beastlint: disable=CXX-LOCK-DISCIPLINE  atomic handoff: stop() reads unix_path_ only after listen_fd_.exchange() observed the fd stored after this write
      unix_path_ = shm_ ? shm::shm_socket_path(address_)
                        : address_.substr(5);
      ::unlink(unix_path_.c_str());
      fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
      if (fd < 0) throw SocketError("socket() failed");
      sockaddr_un addr{};
      addr.sun_family = AF_UNIX;
      if (unix_path_.size() >= sizeof(addr.sun_path))
        throw SocketError("unix path too long: " + unix_path_);
      std::strncpy(addr.sun_path, unix_path_.c_str(),
                   sizeof(addr.sun_path) - 1);
      if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
        throw SocketError("bind failed for " + address_);
    } else {
      auto colon = address_.rfind(':');
      if (colon == std::string::npos)
        throw SocketError("address must be unix:/path or host:port");
      std::string host = address_.substr(0, colon);
      int port = std::stoi(address_.substr(colon + 1));
      if (host.empty()) host = "127.0.0.1";
      fd = ::socket(AF_INET, SOCK_STREAM, 0);
      if (fd < 0) throw SocketError("socket() failed");
      int one = 1;
      ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_port = htons(static_cast<uint16_t>(port));
      if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1)
        throw SocketError("bad host " + host);
      if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
        throw SocketError("bind failed for " + address_);
    }
    if (::listen(fd, 16) != 0)
      throw SocketError("listen failed for " + address_);
    listen_fd_.store(fd);
  }

  void serve_stream(int fd) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::unique_ptr<Transport> sock;
    StreamHooks hooks;
    bool have_hooks = false;
    try {
      if (shm_) {
        // Rings + handshake BEFORE the env hooks run, so a client that
        // never acks can't leak an env instance (matches the Python
        // server's ordering). Ring teardown: the transport owns its
        // created segments and unlinks them at close.
        sock = shm::shm_server_transport(FramedSocket::adopt(fd));
      } else {
        sock = std::make_unique<FramedSocket>(FramedSocket::adopt(fd));
      }
      hooks = hook_factory_();
      have_hooks = true;
      sock->send(hooks.initial());
      while (true) {
        wire::ValueNest action = sock->recv();
        sock->send(hooks.step(action));
      }
    } catch (const SocketError&) {
      // client hung up / stop(): normal end of stream
    } catch (const std::exception& e) {
      // env/hook raised: report to the client, then drop the stream
      // (reference: grpc INTERNAL status, rpcenv.cc:76-81)
      try {
        wire::ValueNest::Dict err;
        err.emplace("type",
                    wire::ValueNest(wire::Value::of_string("error")));
        err.emplace("message",
                    wire::ValueNest(wire::Value::of_string(e.what())));
        if (sock) sock->send(wire::ValueNest(std::move(err)));
      } catch (const SocketError&) {
      } catch (const wire::WireError&) {
      }
    }
    if (have_hooks && hooks.close) hooks.close();
    std::lock_guard<std::mutex> lock(mu_);
    for (auto it = conn_fds_.begin(); it != conn_fds_.end(); ++it) {
      if (*it == fd) {
        conn_fds_.erase(it);
        break;
      }
    }
  }

  // Join threads whose streams already ended so the vector stays
  // bounded under reconnect-heavy workloads (the Python server prunes
  // the same way). A finished id's thread is at worst a few
  // instructions from returning, so these joins are effectively
  // instant and never wait on a live stream.
  // beastlint: holds mu_
  void reap_finished_locked() {
    for (std::thread::id id : finished_) {
      for (auto it = threads_.begin(); it != threads_.end(); ++it) {
        if (it->get_id() == id) {
          it->join();
          threads_.erase(it);
          break;
        }
      }
    }
    finished_.clear();
  }

  std::string address_;
  std::function<StreamHooks()> hook_factory_;
  // unix_path_ / shm_ are written once by bind_and_listen (run()'s
  // thread) and then only read: stream threads spawn strictly after
  // bind_and_listen returns (write-before-spawn), and stop() touches
  // unix_path_ only after listen_fd_.exchange() returned a valid fd —
  // a seq_cst handoff that happens-after the store publishing the path.
  std::string unix_path_;
  bool shm_ = false;
  std::atomic<int> listen_fd_{-1};
  std::atomic<bool> running_{false};
  std::mutex mu_;
  std::vector<int> conn_fds_;     // guarded-by: mu_
  std::vector<std::thread> threads_;  // guarded-by: mu_
  std::vector<std::thread::id> finished_;  // guarded-by: mu_
};

}  // namespace tbt
