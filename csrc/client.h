// Framed-socket client: connect (with retry-to-deadline), send/recv wire
// messages. The C++ side of the env-stream transport (reference: gRPC
// channel + WaitForConnected, actorpool.cc:354-381).

#pragma once

#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <arpa/inet.h>
#include <netdb.h>

#include <chrono>
#include <cstring>
#include <memory>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

#include "wire.h"

namespace tbt {

class SocketError : public std::runtime_error {
 public:
  using std::runtime_error::runtime_error;
};

// One framed-message stream, whatever the data plane: the plain socket
// (FramedSocket) or the shared-memory rings (shm.h ShmTransport). The
// actor pool and env server speak only this interface, so both sides
// accept every address scheme the Python runtime does.
class Transport {
 public:
  virtual ~Transport() = default;
  // Returns the framed byte count (header included) for wire telemetry.
  virtual size_t send(const wire::ValueNest& value) = 0;
  // (value, framed byte count); throws SocketError on EOF — the env
  // stream should outlive the actor loop.
  virtual std::pair<wire::ValueNest, size_t> recv_sized() = 0;
  wire::ValueNest recv() { return recv_sized().first; }
  // shm crash sweep; no-op for socket transports.
  virtual void unlink_segments() {}
  // Chaos hooks (csrc/chaos.h / ISSUE 12): sever the stream from
  // another thread — shutdown(SHUT_RDWR) so a parked recv wakes with
  // the same EOF a real cable cut produces; corrupt the shm recv ring's
  // queued frame (1 = observably landed, 0 = momentarily empty, retry;
  // -1 = not an shm transport). No-ops on transports without the
  // underlying surface; the FaultingTransport parity contract lives in
  // resilience/chaos.py.
  virtual void shutdown_stream() {}
  virtual int corrupt_recv_ring(bool /*header*/) { return -1; }
  virtual void close() = 0;
};

class FramedSocket : public Transport {
 public:
  FramedSocket() = default;
  ~FramedSocket() override { close(); }

  FramedSocket(const FramedSocket&) = delete;
  FramedSocket& operator=(const FramedSocket&) = delete;
  FramedSocket(FramedSocket&& other) noexcept
      : fd_(other.fd_), max_frame_bytes_(other.max_frame_bytes_) {
    other.fd_ = -1;
  }

  // Wrap an already-connected fd (server-accepted stream); takes
  // ownership (closes it in the destructor).
  static FramedSocket adopt(int fd) {
    FramedSocket s;
    s.fd_ = fd;
    return s;
  }

  // Borrow the fd (e.g. for setsockopt) without giving up ownership.
  int fd() const { return fd_; }

  // Hand the fd off (e.g. to a ShmTransport after the handshake); the
  // destructor then leaves it alone.
  int release() {
    int fd = fd_;
    fd_ = -1;
    return fd;
  }

  // Per-connection frame bound (--max_frame_bytes); defaults to the
  // codec-wide kMaxFrameBytes.
  void set_max_frame_bytes(size_t n) { max_frame_bytes_ = n; }

  // "unix:/path" or "host:port", retrying until deadline_s.
  void connect(const std::string& address, double deadline_s) {
    auto deadline =
        std::chrono::steady_clock::now() +
        std::chrono::duration_cast<std::chrono::steady_clock::duration>(
            std::chrono::duration<double>(deadline_s));
    std::string last_error = "unknown";
    while (std::chrono::steady_clock::now() < deadline) {
      if (try_connect(address, &last_error)) return;
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
    }
    throw SocketError("WaitForConnected() timed out for " + address + ": " +
                      last_error);
  }

  void close() override {
    if (fd_ >= 0) {
      ::close(fd_);
      fd_ = -1;
    }
  }

  // Chaos sever: called from the injector thread while the owning actor
  // may be blocked in recv — shutdown (not close) keeps the fd valid
  // until the owner tears down, so there is no fd-reuse race.
  void shutdown_stream() override {
    if (fd_ >= 0) ::shutdown(fd_, SHUT_RDWR);
  }

  size_t send(const wire::ValueNest& value) override {
    std::vector<uint8_t> framed = wire::encode(value);
    size_t sent = 0;
    while (sent < framed.size()) {
      ssize_t n = ::send(fd_, framed.data() + sent, framed.size() - sent, 0);
      if (n <= 0) throw SocketError("send failed");
      sent += static_cast<size_t>(n);
    }
    return framed.size();
  }

  // Throws SocketError on EOF (the stream should outlive the actor loop).
  std::pair<wire::ValueNest, size_t> recv_sized() override {
    uint8_t header[4];
    recv_exact(header, 4);
    uint32_t length = 0;
    for (int i = 0; i < 4; ++i)
      length |= static_cast<uint32_t>(header[i]) << (8 * i);
    if (length > max_frame_bytes_)
      throw wire::WireError("wire: frame length " + std::to_string(length) +
                            " exceeds max_frame_bytes " +
                            std::to_string(max_frame_bytes_));
    auto payload = std::make_shared<std::vector<uint8_t>>(length);
    recv_exact(payload->data(), length);
    return {wire::decode(payload->data(), length, payload),
            4 + static_cast<size_t>(length)};
  }

 private:
  bool try_connect(const std::string& address, std::string* error) {
    int fd = -1;
    if (address.rfind("unix:", 0) == 0) {
      fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
      if (fd < 0) {
        *error = std::strerror(errno);
        return false;
      }
      sockaddr_un addr{};
      addr.sun_family = AF_UNIX;
      std::string path = address.substr(5);
      if (path.size() >= sizeof(addr.sun_path)) {
        ::close(fd);
        *error = "unix path too long";
        return false;
      }
      std::strncpy(addr.sun_path, path.c_str(), sizeof(addr.sun_path) - 1);
      if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
          0) {
        *error = std::strerror(errno);
        ::close(fd);
        return false;
      }
    } else {
      auto colon = address.rfind(':');
      if (colon == std::string::npos) {
        *error = "bad address";
        return false;
      }
      std::string host = address.substr(0, colon);
      if (host.empty()) host = "127.0.0.1";
      std::string port = address.substr(colon + 1);
      addrinfo hints{};
      hints.ai_family = AF_UNSPEC;
      hints.ai_socktype = SOCK_STREAM;
      addrinfo* res = nullptr;
      if (::getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0) {
        *error = "getaddrinfo failed";
        return false;
      }
      for (addrinfo* rp = res; rp; rp = rp->ai_next) {
        fd = ::socket(rp->ai_family, rp->ai_socktype, rp->ai_protocol);
        if (fd < 0) continue;
        if (::connect(fd, rp->ai_addr, rp->ai_addrlen) == 0) break;
        *error = std::strerror(errno);
        ::close(fd);
        fd = -1;
      }
      ::freeaddrinfo(res);
      if (fd < 0) return false;
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    }
    fd_ = fd;
    return true;
  }

  void recv_exact(uint8_t* out, size_t n) {
    size_t got = 0;
    while (got < n) {
      ssize_t r = ::recv(fd_, out + got, n - got, 0);
      if (r == 0) throw SocketError("connection closed by peer");
      if (r < 0) throw SocketError(std::string("recv failed: ") +
                                   std::strerror(errno));
      got += static_cast<size_t>(r);
    }
  }

  int fd_ = -1;
  size_t max_frame_bytes_ = wire::kMaxFrameBytes;
};

}  // namespace tbt
