// Nest<T>: recursive container of leaves, sequences, and string maps.
//
// The C++ counterpart of JAX pytrees for the native runtime layers —
// capability parity with the reference's standalone nest library
// (/root/reference/nest/nest/nest.h: map/map2/flatten/pack_as/for_each/
// front), written fresh around std::variant with free-function visitors
// and sorted-key map traversal (matching the Python side's pytree order,
// torchbeast_tpu/nest.py).

#pragma once

#include <functional>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <utility>
#include <variant>
#include <vector>

namespace tbt {

template <typename T>
class Nest {
 public:
  using List = std::vector<Nest<T>>;
  using Dict = std::map<std::string, Nest<T>>;  // sorted keys
  using Value = std::variant<T, List, Dict>;

  Nest() : value_(List{}) {}
  /* implicit */ Nest(T leaf) : value_(std::move(leaf)) {}
  /* implicit */ Nest(List list) : value_(std::move(list)) {}
  /* implicit */ Nest(Dict dict) : value_(std::move(dict)) {}

  bool is_leaf() const { return std::holds_alternative<T>(value_); }
  bool is_list() const { return std::holds_alternative<List>(value_); }
  bool is_dict() const { return std::holds_alternative<Dict>(value_); }

  const T& leaf() const { return std::get<T>(value_); }
  T& leaf() { return std::get<T>(value_); }
  const List& list() const { return std::get<List>(value_); }
  List& list() { return std::get<List>(value_); }
  const Dict& dict() const { return std::get<Dict>(value_); }
  Dict& dict() { return std::get<Dict>(value_); }

  bool empty() const {
    if (is_leaf()) return false;
    if (is_list()) {
      for (const auto& n : list())
        if (!n.empty()) return false;
      return true;
    }
    for (const auto& [k, n] : dict())
      if (!n.empty()) return false;
    return true;
  }

  // Depth-first leaf visit.
  void for_each(const std::function<void(const T&)>& fn) const {
    if (is_leaf()) {
      fn(leaf());
    } else if (is_list()) {
      for (const auto& n : list()) n.for_each(fn);
    } else {
      for (const auto& [k, n] : dict()) n.for_each(fn);
    }
  }

  // First leaf in depth-first order; throws on empty.
  const T& front() const {
    const T* found = nullptr;
    try_front(&found);
    if (!found) throw std::invalid_argument("front() on empty nest");
    return *found;
  }

  std::vector<T> flatten() const {
    std::vector<T> out;
    for_each([&out](const T& t) { out.push_back(t); });
    return out;
  }

  // Structure-preserving unary transform.
  template <typename F>
  auto map(const F& fn) const -> Nest<decltype(fn(std::declval<T>()))> {
    using U = decltype(fn(std::declval<T>()));
    if (is_leaf()) return Nest<U>(fn(leaf()));
    if (is_list()) {
      typename Nest<U>::List out;
      out.reserve(list().size());
      for (const auto& n : list()) out.push_back(n.map(fn));
      return Nest<U>(std::move(out));
    }
    typename Nest<U>::Dict out;
    for (const auto& [k, n] : dict()) out.emplace(k, n.map(fn));
    return Nest<U>(std::move(out));
  }

  // Structure-checked binary transform.
  template <typename F>
  static Nest<T> map2(const F& fn, const Nest<T>& a, const Nest<T>& b) {
    if (a.is_leaf() && b.is_leaf()) return Nest<T>(fn(a.leaf(), b.leaf()));
    if (a.is_list() && b.is_list()) {
      if (a.list().size() != b.list().size())
        throw std::invalid_argument("map2: list length mismatch");
      List out;
      out.reserve(a.list().size());
      for (size_t i = 0; i < a.list().size(); ++i)
        out.push_back(map2(fn, a.list()[i], b.list()[i]));
      return Nest<T>(std::move(out));
    }
    if (a.is_dict() && b.is_dict()) {
      if (a.dict().size() != b.dict().size())
        throw std::invalid_argument("map2: dict size mismatch");
      Dict out;
      auto ita = a.dict().begin();
      auto itb = b.dict().begin();
      for (; ita != a.dict().end(); ++ita, ++itb) {
        if (ita->first != itb->first)
          throw std::invalid_argument("map2: dict key mismatch");
        out.emplace(ita->first, map2(fn, ita->second, itb->second));
      }
      return Nest<T>(std::move(out));
    }
    throw std::invalid_argument("map2: structure mismatch");
  }

  // Rebuild this structure from a flat leaf vector (inverse of flatten).
  Nest<T> pack_as(const std::vector<T>& flat) const {
    size_t index = 0;
    Nest<T> out = pack_from(flat, &index);
    if (index != flat.size())
      throw std::invalid_argument("pack_as: too many leaves");
    return out;
  }

  // Zip N structurally-equal nests into one nest of leaf-vectors — the
  // batch former's building block.
  static Nest<std::vector<T>> zip(const std::vector<Nest<T>>& nests) {
    if (nests.empty()) throw std::invalid_argument("zip: empty input");
    const Nest<T>& head = nests.front();
    if (head.is_leaf()) {
      std::vector<T> leaves;
      leaves.reserve(nests.size());
      for (const auto& n : nests) {
        if (!n.is_leaf()) throw std::invalid_argument("zip: structure mismatch");
        leaves.push_back(n.leaf());
      }
      return Nest<std::vector<T>>(std::move(leaves));
    }
    if (head.is_list()) {
      typename Nest<std::vector<T>>::List out;
      for (size_t i = 0; i < head.list().size(); ++i) {
        std::vector<Nest<T>> column;
        column.reserve(nests.size());
        for (const auto& n : nests) {
          if (!n.is_list() || n.list().size() != head.list().size())
            throw std::invalid_argument("zip: structure mismatch");
          column.push_back(n.list()[i]);
        }
        out.push_back(zip(column));
      }
      return Nest<std::vector<T>>(std::move(out));
    }
    typename Nest<std::vector<T>>::Dict out;
    for (const auto& [key, sub] : head.dict()) {
      std::vector<Nest<T>> column;
      column.reserve(nests.size());
      for (const auto& n : nests) {
        if (!n.is_dict()) throw std::invalid_argument("zip: structure mismatch");
        auto it = n.dict().find(key);
        if (it == n.dict().end() || n.dict().size() != head.dict().size())
          throw std::invalid_argument("zip: dict key mismatch");
        column.push_back(it->second);
      }
      out.emplace(key, zip(column));
    }
    return Nest<std::vector<T>>(std::move(out));
  }

 private:
  void try_front(const T** found) const {
    if (*found) return;
    if (is_leaf()) {
      *found = &leaf();
    } else if (is_list()) {
      for (const auto& n : list()) {
        n.try_front(found);
        if (*found) return;
      }
    } else {
      for (const auto& [k, n] : dict()) {
        n.try_front(found);
        if (*found) return;
      }
    }
  }

  Nest<T> pack_from(const std::vector<T>& flat, size_t* index) const {
    if (is_leaf()) {
      if (*index >= flat.size())
        throw std::invalid_argument("pack_as: too few leaves");
      return Nest<T>(flat[(*index)++]);
    }
    if (is_list()) {
      List out;
      out.reserve(list().size());
      for (const auto& n : list()) out.push_back(n.pack_from(flat, index));
      return Nest<T>(std::move(out));
    }
    Dict out;
    for (const auto& [k, n] : dict()) out.emplace(k, n.pack_from(flat, index));
    return Nest<T>(std::move(out));
  }

  Value value_;
};

}  // namespace tbt
