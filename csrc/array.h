// Array: the framework-neutral dense array carried through the C++ runtime.
//
// Plays the role torch::Tensor plays inside the reference's libtorchbeast
// (SURVEY.md §2.1 N3-N5) without the torch dependency: the C++ layers only
// ever move, concatenate, and slice contiguous buffers; all math happens in
// XLA. Buffers are shared_ptr-owned so queue hand-offs are refcount bumps.

#pragma once

#include <cstdint>
#include <cstring>
#include <memory>
#include <numeric>
#include <stdexcept>
#include <string>
#include <vector>

namespace tbt {

// Dtype codes shared with the Python codec (torchbeast_tpu/runtime/wire.py).
enum class DType : uint8_t {
  kU8 = 0,
  kI8 = 1,
  kI32 = 2,
  kI64 = 3,
  kF32 = 4,
  kF64 = 5,
  kBool = 6,
  kU16 = 7,
  kI16 = 8,
  kU32 = 9,
  kU64 = 10,
  kF16 = 11,
  // bfloat16 (truncated f32; TPU-native activations). No host math ever
  // touches the payload here — the runtime only moves bytes — so no
  // bf16 arithmetic support is needed, just the itemsize.
  kBF16 = 12,
};

inline size_t itemsize(DType dtype) {
  switch (dtype) {
    case DType::kU8:
    case DType::kI8:
    case DType::kBool:
      return 1;
    case DType::kU16:
    case DType::kI16:
    case DType::kF16:
    case DType::kBF16:
      return 2;
    case DType::kI32:
    case DType::kU32:
    case DType::kF32:
      return 4;
    case DType::kI64:
    case DType::kU64:
    case DType::kF64:
      return 8;
  }
  throw std::invalid_argument("unknown dtype");
}

class Array {
 public:
  Array() : dtype_(DType::kU8) {}

  // Owns a fresh zeroed buffer.
  Array(DType dtype, std::vector<int64_t> shape)
      : dtype_(dtype), shape_(std::move(shape)) {
    storage_ = std::make_shared<std::vector<uint8_t>>(nbytes());
    data_ = storage_->data();
  }

  // Wraps external memory kept alive by `owner`.
  Array(DType dtype, std::vector<int64_t> shape, void* data,
        std::shared_ptr<void> owner)
      : dtype_(dtype),
        shape_(std::move(shape)),
        owner_(std::move(owner)),
        data_(static_cast<uint8_t*>(data)) {}

  DType dtype() const { return dtype_; }
  const std::vector<int64_t>& shape() const { return shape_; }
  int64_t ndim() const { return static_cast<int64_t>(shape_.size()); }
  int64_t dim(int64_t i) const { return shape_.at(i); }

  int64_t numel() const {
    return std::accumulate(shape_.begin(), shape_.end(), int64_t{1},
                           std::multiplies<int64_t>());
  }
  size_t nbytes() const { return static_cast<size_t>(numel()) * itemsize(dtype_); }

  const uint8_t* data() const { return data_; }
  uint8_t* mutable_data() { return data_; }

  // Deep copy into freshly-owned memory.
  Array clone() const {
    Array out(dtype_, shape_);
    std::memcpy(out.mutable_data(), data_, nbytes());
    return out;
  }

 private:
  DType dtype_;
  std::vector<int64_t> shape_;
  std::shared_ptr<std::vector<uint8_t>> storage_;  // when self-owned
  std::shared_ptr<void> owner_;                    // when wrapping
  uint8_t* data_ = nullptr;
};

// Concatenate along `dim`. All inputs must agree on dtype and on every
// other dimension (the queue-side batch former; the reference used
// torch::cat, actorpool.cc:49-55).
inline Array concatenate(const std::vector<Array>& arrays, int64_t dim) {
  if (arrays.empty()) throw std::invalid_argument("concatenate: no arrays");
  const Array& first = arrays.front();
  if (dim < 0 || dim >= first.ndim())
    throw std::out_of_range("concatenate: bad dim");

  std::vector<int64_t> out_shape = first.shape();
  int64_t cat_size = 0;
  for (const Array& a : arrays) {
    if (a.dtype() != first.dtype())
      throw std::invalid_argument("concatenate: dtype mismatch");
    if (a.ndim() != first.ndim())
      throw std::invalid_argument("concatenate: rank mismatch");
    for (int64_t d = 0; d < first.ndim(); ++d) {
      if (d != dim && a.dim(d) != first.dim(d))
        throw std::invalid_argument("concatenate: shape mismatch");
    }
    cat_size += a.dim(dim);
  }
  out_shape[dim] = cat_size;
  Array out(first.dtype(), out_shape);

  // Contiguous layout: view every array as [outer, inner_bytes] where
  // inner spans dims >= dim; interleave the blocks.
  int64_t outer = 1;
  for (int64_t d = 0; d < dim; ++d) outer *= first.dim(d);
  const size_t unit = itemsize(first.dtype());
  size_t out_inner = out.nbytes() / (outer ? outer : 1);
  size_t offset = 0;
  for (const Array& a : arrays) {
    size_t a_inner = outer ? a.nbytes() / outer : 0;
    for (int64_t o = 0; o < outer; ++o) {
      std::memcpy(out.mutable_data() + o * out_inner + offset,
                  a.data() + o * a_inner, a_inner);
    }
    offset += a_inner;
  }
  (void)unit;
  return out;
}

// View rows [start, start+count) along `dim` — zero-copy when dim==0,
// copying otherwise.
inline Array slice(const Array& a, int64_t dim, int64_t start, int64_t count) {
  if (dim < 0 || dim >= a.ndim()) throw std::out_of_range("slice: bad dim");
  if (start < 0 || start + count > a.dim(dim))
    throw std::out_of_range("slice: out of range");
  std::vector<int64_t> out_shape = a.shape();
  out_shape[dim] = count;

  int64_t outer = 1;
  for (int64_t d = 0; d < dim; ++d) outer *= a.dim(d);
  int64_t inner = 1;
  for (int64_t d = dim + 1; d < a.ndim(); ++d) inner *= a.dim(d);
  const size_t unit = itemsize(a.dtype());
  const size_t row = inner * unit;

  Array out(a.dtype(), out_shape);
  for (int64_t o = 0; o < outer; ++o) {
    std::memcpy(out.mutable_data() + o * count * row,
                a.data() + (o * a.dim(dim) + start) * row, count * row);
  }
  return out;
}

}  // namespace tbt
