// Native serving-plane routing (ISSUE 16): the slot-hash slice router
// and the replica/central fallback router, ported from the Python
// request path (torchbeast_tpu/parallel/sebulba.py SliceRouter,
// torchbeast_tpu/serving/replica.py ReplicaRouter) so the C++ actor
// pool's compute() path never touches Python to pick a batcher.
//
// The routing hash is the splitmix64 finalizer from
// torchbeast_tpu/runtime/placement.py _mix64 — the STATIC actor->slice
// assignment that keeps each actor's device-resident state-table slot
// on one inference slice for the life of the run. The constants below
// are literal-pinned cross-language by beastlint ROUTE-PARITY
// (analysis/parity.py): a drift on either side would silently re-shard
// every deployed slot table, so the lint gate fails before the drift
// can ship.
//
// Thread-safety: routers are constructed on the driver thread before
// actor loops start and are immutable afterwards except for the atomic
// counters and the replica health flag; every method here is called
// concurrently from N actor threads with no lock.

#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

#include "array.h"
#include "nest.h"
#include "queues.h"

namespace tbt {

// splitmix64 finalizer constants (runtime/placement.py _mix64; pinned
// by beastlint ROUTE-PARITY — edit BOTH sides and the lint spec
// together or deployed slot tables re-shard).
constexpr uint64_t kSplitMix64Gamma = 0x9E3779B97F4A7C15ULL;
constexpr uint64_t kSplitMix64Mul1 = 0xBF58476D1CE4E5B9ULL;
constexpr uint64_t kSplitMix64Mul2 = 0x94D049BB133111EBULL;
constexpr int kSplitMix64Shift1 = 30;
constexpr int kSplitMix64Shift2 = 27;
constexpr int kSplitMix64Shift3 = 31;

// Per-slice telemetry series prefix — the native fold
// (runtime/native.py NativeTelemetryFolder) publishes this router's
// counters as "<prefix><i>.requests", matching the Python
// SliceRouter's registry series exactly (pinned by ROUTE-PARITY).
constexpr const char kSliceSeriesPrefix[] = "inference.slice.";

inline uint64_t splitmix64(uint64_t x) {
  x += kSplitMix64Gamma;
  x = (x ^ (x >> kSplitMix64Shift1)) * kSplitMix64Mul1;
  x = (x ^ (x >> kSplitMix64Shift2)) * kSplitMix64Mul2;
  return x ^ (x >> kSplitMix64Shift3);
}

// slot -> slice, bit-identical to DeviceSplit.slice_for_slot: the
// uint64 cast wraps negative ids exactly like Python's `& (2**64-1)`.
inline int64_t slice_for_slot(int64_t slot, int64_t n_slices) {
  if (n_slices < 1) throw std::invalid_argument("n_slices must be >= 1");
  return static_cast<int64_t>(splitmix64(static_cast<uint64_t>(slot)) %
                              static_cast<uint64_t>(n_slices));
}

namespace detail {
// The slot leaf is a [1, 1] integer array (actor_pool.h slot framing).
inline int64_t read_slot_scalar(const Array& a) {
  switch (a.dtype()) {
    case DType::kI32:
      return *reinterpret_cast<const int32_t*>(a.data());
    case DType::kI64:
      return *reinterpret_cast<const int64_t*>(a.data());
    default:
      throw std::invalid_argument("slot leaf must be integer typed");
  }
}
}  // namespace detail

// Fans actor requests into N per-slice DynamicBatchers by the static
// slot hash; slot-less requests (legacy framing) round-robin so every
// slice still earns traffic. Semantics mirror the Python SliceRouter
// (parallel/sebulba.py) minus its advisory serving_ok() poke — on the
// native path per-slice health rides the replica routers/hooks, not
// the fan-out.
class SliceRouter : public InferenceClient {
 public:
  explicit SliceRouter(std::vector<std::shared_ptr<InferenceClient>> slices)
      : slices_(std::move(slices)), requests_(slices_.size()) {
    if (slices_.empty())
      throw std::invalid_argument("SliceRouter needs >= 1 slice");
  }

  int64_t n_slices() const { return static_cast<int64_t>(slices_.size()); }

  const std::shared_ptr<InferenceClient>& slice(int64_t i) const {
    return slices_.at(static_cast<size_t>(i));
  }

  // Cumulative per-slice routed-request counts (folded by the driver
  // into the "inference.slice.<i>.requests" series).
  std::vector<int64_t> request_counts() const {
    std::vector<int64_t> out;
    out.reserve(requests_.size());
    for (const auto& c : requests_) out.push_back(c.load());
    return out;
  }

  ArrayNest compute(ArrayNest inputs, int64_t timeout_s = 600) override {
    size_t idx = route(inputs);
    // Counted at routing time like the Python router: the series
    // answers "where is traffic going", sheds included.
    requests_[idx].fetch_add(1);
    return slices_[idx]->compute(std::move(inputs), timeout_s);
  }

  int64_t size() const override {
    int64_t total = 0;
    for (const auto& s : slices_) total += s->size();
    return total;
  }

  // One close() closes every slice, so the pool's shutting_down() poll
  // (which only sees this router) observes the whole plane; the Python
  // router's is_closed checks slice 0 for the same reason.
  bool is_closed() const override { return slices_.front()->is_closed(); }

  void close() override {
    for (const auto& s : slices_) {
      try {
        s->close();
      } catch (const std::runtime_error&) {
        // already closed (driver shutdown closes slices individually
        // too) — same swallow as the Python close_all.
      }
    }
  }

 private:
  size_t route(const ArrayNest& inputs) {
    if (inputs.is_dict()) {
      const auto& d = inputs.dict();
      auto it = d.find("slot");
      if (it != d.end() && it->second.is_leaf()) {
        int64_t slot = detail::read_slot_scalar(it->second.leaf());
        return static_cast<size_t>(
            slice_for_slot(slot, static_cast<int64_t>(slices_.size())));
      }
    }
    // Legacy (slot-less) framing: round-robin keeps the slices evenly
    // loaded; the atomic tick makes concurrent producers collision-free.
    return static_cast<size_t>(rr_.fetch_add(1)) % slices_.size();
  }

  const std::vector<std::shared_ptr<InferenceClient>> slices_;
  std::vector<std::atomic<int64_t>> requests_;  // per-slice routed count
  std::atomic<uint64_t> rr_{0};  // slot-less round-robin cursor
};

// Replica-first routing with central fallback — the native twin of
// serving/replica.py ReplicaRouter. The lag/health gate is a plain
// atomic flag flipped from the Python side (the replica serving loop's
// hooks own the PolicySnapshotStore and the health machine; they call
// set_serving() on every begin_batch and monitor tick), so the actor
// threads' routing decision costs one relaxed load instead of a GIL
// round-trip per request.
class ReplicaRouter : public InferenceClient {
 public:
  ReplicaRouter(std::shared_ptr<InferenceClient> central,
                std::shared_ptr<InferenceClient> replica)
      : central_(std::move(central)), replica_(std::move(replica)) {
    if (!central_ || !replica_)
      throw std::invalid_argument("ReplicaRouter needs central and replica");
  }

  void set_serving(bool ok) { serving_ok_.store(ok); }
  bool serving() const { return serving_ok_.load(); }

  int64_t replica_requests() const { return replica_requests_.load(); }
  int64_t central_requests() const { return central_requests_.load(); }

  ArrayNest compute(ArrayNest inputs, int64_t timeout_s = 600) override {
    if (serving_ok_.load() && !replica_->is_closed()) {
      try {
        // `inputs` stays intact for the fallback leg: nest copies are
        // shallow (leaves share buffers), so this costs pointers.
        ArrayNest out = replica_->compute(inputs, timeout_s);
        // Counted on SUCCESS only: a fallen-back request must land in
        // exactly one routing series, or the two sum past the total —
        // the Python router's accounting contract.
        replica_requests_.fetch_add(1);
        return out;
      } catch (const ShedError&) {
        throw;  // sheds keep their actor-side retry contract
      } catch (const ClosedBatchingQueue&) {
        // dying/closing replica path: fall through to central
      } catch (const AsyncError&) {
        // replica-side serving failure: fall through to central
      }
    }
    central_requests_.fetch_add(1);
    return central_->compute(std::move(inputs), timeout_s);
  }

  int64_t size() const override {
    return central_->size() + replica_->size();
  }

  bool is_closed() const override { return central_->is_closed(); }

  void close() override {
    // Central first: the pool's shutting_down() keys off it, so actor
    // threads stop producing before the replica drains.
    for (const auto& c : {central_, replica_}) {
      try {
        c->close();
      } catch (const std::runtime_error&) {
        // already closed by the driver's own closer list
      }
    }
  }

 private:
  const std::shared_ptr<InferenceClient> central_;
  const std::shared_ptr<InferenceClient> replica_;
  std::atomic<bool> serving_ok_{false};  // flipped by the Python hooks
  std::atomic<int64_t> replica_requests_{0};
  std::atomic<int64_t> central_requests_{0};
};

}  // namespace tbt
