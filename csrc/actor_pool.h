// ActorPool: pure-C++ actor loops — the reference's hottest native
// component (N5, /root/reference/src/cc/actorpool.cc:342-564), re-designed
// for the framed-socket transport.
//
// Each loop: connect to an env server, read the initial Step, then repeat
// {inference via DynamicBatcher::compute -> send Action -> recv Step},
// assembling unroll_length+1-step rollouts with the on-policy invariants
// (overlap-by-one, agent-output pairing, agent-state carry; see
// torchbeast_tpu/rollout.py for the invariant spec shared with the Python
// implementation). No Python in the loop: the GIL is only touched by the
// inference/learner threads that drain the queues from the Python side.

#pragma once

#include <atomic>
#include <exception>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "client.h"
#include "queues.h"
#include "wire.h"

namespace tbt {

inline const std::vector<std::string>& env_keys() {
  static const std::vector<std::string> keys = {
      "frame",        "reward",       "done",
      "episode_step", "episode_return", "last_action"};
  return keys;
}

class ActorPool {
 public:
  using LearnerQueue = BatchingQueue<int>;  // payload unused

  ActorPool(int64_t unroll_length, std::shared_ptr<LearnerQueue> learner_queue,
            std::shared_ptr<DynamicBatcher> inference_batcher,
            std::vector<std::string> addresses, ArrayNest initial_agent_state,
            double connect_timeout_s = 600, int64_t max_reconnects = 0)
      : unroll_length_(unroll_length),
        learner_queue_(std::move(learner_queue)),
        inference_batcher_(std::move(inference_batcher)),
        addresses_(std::move(addresses)),
        initial_agent_state_(std::move(initial_agent_state)),
        connect_timeout_s_(connect_timeout_s),
        max_reconnects_(max_reconnects) {}

  int64_t count() const { return count_.load(); }
  int64_t reconnect_count() const { return reconnect_count_.load(); }

  // Blocks until every loop exits; rethrows the first error.
  void run() {
    std::vector<std::thread> threads;
    threads.reserve(addresses_.size());
    for (const std::string& address : addresses_) {
      threads.emplace_back([this, address] { guarded_loop(address); });
    }
    for (auto& t : threads) t.join();
    std::lock_guard<std::mutex> lock(error_mu_);
    if (first_error_) std::rethrow_exception(first_error_);
  }

  std::string first_error_message() const {
    std::lock_guard<std::mutex> lock(error_mu_);
    if (!first_error_) return "";
    try {
      std::rethrow_exception(first_error_);
    } catch (const std::exception& e) {
      return e.what();
    } catch (...) {
      return "unknown error";
    }
  }

 private:
  void guarded_loop(const std::string& address) {
    int64_t reconnects = 0;
    int64_t progress = 0;  // this actor's env steps across reconnects
    while (true) {
      int64_t steps_at_connect = progress;
      try {
        loop(address, &progress);
        return;
      } catch (const ClosedBatchingQueue&) {
        return;  // clean shutdown
      } catch (const QueueStopped&) {
        return;  // clean shutdown
      } catch (const AsyncError&) {
        // Clean ONLY when the pipeline is shutting down; a broken promise
        // mid-training (inference failure) is a real error.
        if (!inference_batcher_->is_closed() &&
            !learner_queue_->is_closed()) {
          std::lock_guard<std::mutex> lock(error_mu_);
          if (!first_error_) first_error_ = std::current_exception();
        }
        return;
      } catch (const SocketError&) {
        // Transport failure (env-server death / stream cut): optionally
        // reconnect with a fresh env + reset agent state. During pipeline
        // shutdown exit cleanly; a full recovery (>= one unroll streamed
        // since the last connect) earns the budget back.
        if (inference_batcher_->is_closed() || learner_queue_->is_closed())
          return;
        if (progress - steps_at_connect >= unroll_length_) reconnects = 0;
        if (reconnects < max_reconnects_) {
          ++reconnects;
          reconnect_count_.fetch_add(1);
          continue;
        }
        std::lock_guard<std::mutex> lock(error_mu_);
        if (!first_error_) first_error_ = std::current_exception();
        return;
      } catch (...) {
        std::lock_guard<std::mutex> lock(error_mu_);
        if (!first_error_) first_error_ = std::current_exception();
        return;
      }
    }
  }

  // Step message -> env-output nest with [T=1, B=1] leading dims.
  static ArrayNest env_outputs_from(const wire::ValueNest& msg) {
    if (!msg.is_dict()) throw SocketError("expected dict Step message");
    const auto& dict = msg.dict();
    auto type_it = dict.find("type");
    if (type_it != dict.end() && type_it->second.is_leaf() &&
        type_it->second.leaf().kind == wire::Value::Kind::kString &&
        type_it->second.leaf().s == "error") {
      auto m = dict.find("message");
      throw std::runtime_error(
          "Env server error: " +
          (m != dict.end() && m->second.is_leaf() ? m->second.leaf().s : ""));
    }
    ArrayNest::Dict out;
    for (const std::string& key : env_keys()) {
      auto it = dict.find(key);
      if (it == dict.end() || !it->second.is_leaf() ||
          it->second.leaf().kind != wire::Value::Kind::kArray)
        throw SocketError("Step message missing array field: " + key);
      const Array& a = it->second.leaf().array;
      std::vector<int64_t> shape = {1, 1};
      shape.insert(shape.end(), a.shape().begin(), a.shape().end());
      // Clone: the wire buffer is reused per message; rollout storage
      // must own its bytes.
      Array expanded(a.dtype(), shape);
      std::memcpy(expanded.mutable_data(), a.data(), a.nbytes());
      out.emplace(key, ArrayNest(std::move(expanded)));
    }
    return ArrayNest(std::move(out));
  }

  struct StepPair {
    ArrayNest env;
    ArrayNest agent;
  };

  void loop(const std::string& address, int64_t* progress) {
    FramedSocket sock;
    sock.connect(address, connect_timeout_s_);

    ArrayNest env_outputs = env_outputs_from(sock.recv());
    ArrayNest agent_state = initial_agent_state_;

    auto compute = [this](const ArrayNest& env, const ArrayNest& state) {
      ArrayNest::Dict inputs;
      inputs.emplace("agent_state", state);
      inputs.emplace("env", env);
      ArrayNest result = inference_batcher_->compute(ArrayNest(inputs));
      const auto& d = result.dict();
      return std::make_pair(d.at("outputs"), d.at("agent_state"));
    };

    // Prime the boundary agent output (state advance discarded — the first
    // in-rollout compute re-consumes this env output for real).
    auto [agent_outputs, discard] = compute(env_outputs, agent_state);
    (void)discard;

    std::vector<StepPair> rollout;
    rollout.push_back({env_outputs, agent_outputs});
    ArrayNest rollout_initial_state = agent_state;

    while (true) {
      auto [outputs, new_state] = compute(env_outputs, agent_state);
      agent_outputs = outputs;
      agent_state = new_state;

      // Extract the scalar action from outputs["action"] ([1,1]).
      const Array& action_arr =
          agent_outputs.dict().at("action").front();
      int64_t action = read_scalar_i64(action_arr);

      wire::ValueNest::Dict action_msg;
      action_msg.emplace("type",
                         wire::ValueNest(wire::Value::of_string("action")));
      action_msg.emplace("action",
                         wire::ValueNest(wire::Value::of_int(action)));
      sock.send(wire::ValueNest(std::move(action_msg)));

      env_outputs = env_outputs_from(sock.recv());
      ++(*progress);
      count_.fetch_add(1);
      rollout.push_back({env_outputs, agent_outputs});

      if (static_cast<int64_t>(rollout.size()) == unroll_length_ + 1) {
        enqueue_rollout(rollout, rollout_initial_state);
        rollout.erase(rollout.begin(), rollout.end() - 1);  // overlap-by-one
        rollout_initial_state = agent_state;
      }
    }
  }

  static int64_t read_scalar_i64(const Array& a) {
    switch (a.dtype()) {
      case DType::kI32:
        return *reinterpret_cast<const int32_t*>(a.data());
      case DType::kI64:
        return *reinterpret_cast<const int64_t*>(a.data());
      case DType::kU8:
        return *a.data();
      default:
        throw std::invalid_argument("action must be integer typed");
    }
  }

  void enqueue_rollout(const std::vector<StepPair>& rollout,
                       const ArrayNest& initial_state) {
    std::vector<ArrayNest> envs, agents;
    envs.reserve(rollout.size());
    agents.reserve(rollout.size());
    for (const StepPair& p : rollout) {
      envs.push_back(p.env);
      agents.push_back(p.agent);
    }
    // Stack along time dim 0 -> [T+1, 1, ...].
    ArrayNest env_stack = batch_nests(envs, 0);
    ArrayNest agent_stack = batch_nests(agents, 0);

    ArrayNest::Dict batch = env_stack.dict();
    for (const auto& [k, v] : agent_stack.dict()) batch.emplace(k, v);

    ArrayNest::Dict item;
    item.emplace("batch", ArrayNest(std::move(batch)));
    item.emplace("initial_agent_state", initial_state);
    learner_queue_->enqueue(ArrayNest(std::move(item)), 0);
  }

  const int64_t unroll_length_;
  std::shared_ptr<LearnerQueue> learner_queue_;
  std::shared_ptr<DynamicBatcher> inference_batcher_;
  const std::vector<std::string> addresses_;
  const ArrayNest initial_agent_state_;
  const double connect_timeout_s_;
  const int64_t max_reconnects_;

  std::atomic<int64_t> count_{0};
  std::atomic<int64_t> reconnect_count_{0};
  mutable std::mutex error_mu_;
  std::exception_ptr first_error_;
};

}  // namespace tbt
