// ActorPool: pure-C++ actor loops — the reference's hottest native
// component (N5, /root/reference/src/cc/actorpool.cc:342-564), re-designed
// for the framed transports (tcp/unix sockets and shm rings, client.h /
// shm.h).
//
// Each loop: connect to an env server, read the initial Step, then repeat
// {inference via DynamicBatcher::compute -> send Action -> recv Step},
// assembling unroll_length+1-step rollouts with the on-policy invariants
// (overlap-by-one, agent-output pairing, agent-state carry; see
// torchbeast_tpu/rollout.py for the invariant spec shared with the Python
// implementation). No Python in the loop: the GIL is only touched by the
// inference/learner threads that drain the queues from the Python side —
// plus, in slot mode, the once-per-unroll slot hooks (pymodule.cc), which
// drive the SAME device-resident state table the Python pool uses.
//
// Two framings (runtime/actor_pool.py wire contract):
// - legacy: requests carry {"env", "agent_state"}; replies carry
//   {"outputs", "agent_state"} and the boundary state rides every reply.
// - slot (use_slots): requests carry {"env", "slot", "advance"} ([1,1]
//   leaves, batchable like any other); replies carry {"outputs"} only.
//   Recurrent state lives in the Python DeviceStateTable; the hooks
//   reset a slot at (re)connect and read it once per unroll boundary.

#pragma once

#include <atomic>
#include <chrono>
#include <cstring>
#include <exception>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "backoff.h"
#include "chaos.h"
#include "client.h"
#include "queues.h"
#include "shm.h"
#include "wire.h"

namespace tbt {

// A slot-hook failure that is the DeviceStateTable's poison window, not
// an actor bug (runtime/errors.StateTablePoisonedError crossing the GIL
// boundary, pymodule.cc throw_py_error_typed). Derives from AsyncError
// so ONE catch handler covers both inference-side failure classes —
// the same shape as the Python pool's single
// `except (AsyncError, StateTablePoisonedError)` clause: both ride the
// budgeted retry path instead of retiring the actor while the
// supervisor rebuilds the table concurrently (ISSUE 6 contract).
class StateTableError : public AsyncError {
 public:
  using AsyncError::AsyncError;
};

inline const std::vector<std::string>& env_keys() {
  static const std::vector<std::string> keys = {
      "frame",        "reward",       "done",
      "episode_step", "episode_return", "last_action"};
  return keys;
}

class ActorPool {
 public:
  using LearnerQueue = BatchingQueue<int>;  // payload unused
  // Slot hooks (slot mode only; pymodule.cc binds them to the Python
  // DeviceStateTable under the GIL): reset(slot) -> initial state host
  // copy, read(slot) -> the slot's current state host copy.
  using SlotHook = std::function<ArrayNest(int64_t)>;

  struct Telemetry {
    int64_t env_steps = 0;
    int64_t connects = 0;
    int64_t reconnects = 0;
    int64_t batch_retries = 0;
    // Sheds absorbed by the in-place retry (ISSUE 14): one per
    // ShedError received, so the Python fold's serving.resubmitted ==
    // serving.shed + serving.expired audit is exact on this runtime
    // too.
    int64_t shed_resubmits = 0;
    int64_t bytes_up = 0;    // env server -> this process
    int64_t bytes_down = 0;  // actions back out
    // shm doorbell-wait counters (process-wide, csrc/shm.h
    // ring_wait_counters — cumulative like the fields above).
    int64_t ring_doorbell_waits = 0;
    int64_t ring_recheck_wakeups = 0;
  };

  // `inference_batcher` is any InferenceClient: a plain DynamicBatcher
  // (central serving) or a routing facade (csrc/routing.h SliceRouter /
  // ReplicaRouter — ISSUE 16); the pool is topology-blind either way.
  // `record_policy_lag` normalizes replies missing a policy_lag leaf to
  // zeros — the Python pool's _normalize_lag contract, needed when the
  // serving plane mixes replica replies (stamped) with central ones
  // (unstamped) so rollout nests stay structurally uniform.
  ActorPool(int64_t unroll_length, std::shared_ptr<LearnerQueue> learner_queue,
            std::shared_ptr<InferenceClient> inference_batcher,
            std::vector<std::string> addresses, ArrayNest initial_agent_state,
            double connect_timeout_s = 600, int64_t max_reconnects = 0,
            bool use_slots = false, SlotHook slot_reset = nullptr,
            SlotHook slot_read = nullptr,
            size_t max_frame_bytes = wire::kMaxFrameBytes,
            bool enable_fault_hooks = false, bool record_policy_lag = false)
      : unroll_length_(unroll_length),
        learner_queue_(std::move(learner_queue)),
        inference_batcher_(std::move(inference_batcher)),
        addresses_(std::move(addresses)),
        initial_agent_state_(std::move(initial_agent_state)),
        connect_timeout_s_(connect_timeout_s),
        max_reconnects_(max_reconnects),
        use_slots_(use_slots),
        slot_reset_(std::move(slot_reset)),
        slot_read_(std::move(slot_read)),
        max_frame_bytes_(max_frame_bytes),
        record_policy_lag_(record_policy_lag) {
    if (use_slots_ && (!slot_reset_ || !slot_read_))
      throw std::invalid_argument(
          "slot framing needs slot_reset and slot_read hooks");
    // Chaos interposition (csrc/chaos.h): constructed only when armed —
    // unarmed pools never wrap a transport, so the hot path pays zero.
    if (enable_fault_hooks) fault_hooks_ = std::make_unique<FaultHooks>();
  }

  int64_t count() const { return count_.load(); }
  // COMPLETED recoveries (the stream re-established AND delivering
  // again), not granted retry attempts — the Python pool's contract,
  // which is what lets chaos_run assert reconnects == injected faults
  // exactly on both runtimes (ISSUE 12 satellite).
  int64_t reconnect_count() const { return reconnect_count_.load(); }

  // Actor loops still running; the driver's health machine runs
  // DEGRADED while this stays >= --min_live_actors and halts (clean
  // checkpoint-and-exit) below it — same contract as the Python pool.
  int64_t live_actors() const {
    return static_cast<int64_t>(addresses_.size()) - dead_.load();
  }

  std::vector<std::string> error_messages() const {
    std::lock_guard<std::mutex> lock(error_mu_);
    return error_messages_;
  }

  // The chaos entry points' target (null when not armed).
  FaultHooks* fault_hooks() { return fault_hooks_.get(); }

  Telemetry telemetry() const {
    Telemetry t;
    t.env_steps = count_.load();
    t.connects = connects_.load();
    t.reconnects = reconnect_count_.load();
    t.batch_retries = batch_retries_.load();
    t.shed_resubmits = shed_resubmits_.load();
    t.bytes_up = bytes_up_.load();
    t.bytes_down = bytes_down_.load();
    t.ring_doorbell_waits =
        shm::ring_wait_counters().doorbell_waits.load();
    t.ring_recheck_wakeups =
        shm::ring_wait_counters().recheck_wakeups.load();
    return t;
  }

  // Blocks until every loop exits; rethrows the first error.
  void run() {
    std::vector<std::thread> threads;
    threads.reserve(addresses_.size());
    for (size_t i = 0; i < addresses_.size(); ++i) {
      const std::string& address = addresses_[i];
      int64_t index = static_cast<int64_t>(i);
      threads.emplace_back(
          [this, index, address] { guarded_loop(index, address); });
    }
    for (auto& t : threads) t.join();
    std::lock_guard<std::mutex> lock(error_mu_);
    if (first_error_) std::rethrow_exception(first_error_);
  }

  std::string first_error_message() const {
    std::lock_guard<std::mutex> lock(error_mu_);
    if (!first_error_) return "";
    try {
      std::rethrow_exception(first_error_);
    } catch (const std::exception& e) {
      return e.what();
    } catch (...) {
      return "unknown error";
    }
  }

 private:
  // Record inside a catch block (std::current_exception must be live).
  void record_error(const std::string& message) {
    std::lock_guard<std::mutex> lock(error_mu_);
    error_messages_.push_back(message);
    if (!first_error_) first_error_ = std::current_exception();
  }

  bool shutting_down() const {
    return inference_batcher_->is_closed() || learner_queue_->is_closed();
  }

  void guarded_loop(int64_t index, const std::string& address) {
    // ANY exit — clean shutdown or a burned budget — retires this
    // actor; live_actors() feeds the driver's health machine (the
    // Python pool's _guarded_loop finally-block contract).
    struct Retire {
      ActorPool* pool;
      ~Retire() { pool->dead_.fetch_add(1); }
    } retire{this};
    // One budget for BOTH failure classes (transport failures and
    // failed inference batches), refilled by a full recovered unroll —
    // mirroring the Python pool's _recovering_loop. Retries ride the
    // decorrelated-jitter Backoff (csrc/backoff.h) so a dead address
    // is never re-dialed in a tight loop and a mass server restart
    // never thundering-herds the fresh listener.
    int64_t failures = 0;
    int64_t progress = 0;  // this actor's env steps across reconnects
    bool reconnect_pending = false;
    Backoff backoff(0.1, 2.0);
    auto abort_sleep = [this] { return shutting_down(); };
    while (true) {
      int64_t steps_at_connect = progress;
      // Grant a budgeted retry (false during shutdown or once the
      // budget is burned). Sleeps the jittered backoff before the
      // caller retries the stream; a shutdown landing MID-SLEEP also
      // denies the grant — the retry would otherwise re-dial a reaped
      // env server for up to connect_timeout_s.
      auto grant_retry = [&]() -> bool {
        if (shutting_down()) return false;
        if (progress - steps_at_connect >= unroll_length_) {
          failures = 0;
          backoff.reset();
        }
        if (failures >= max_reconnects_) return false;
        ++failures;
        backoff.sleep(abort_sleep);
        return !shutting_down();
      };
      try {
        loop(index, address, &progress, &reconnect_pending);
        return;
      } catch (const ClosedBatchingQueue&) {
        return;  // clean shutdown
      } catch (const QueueStopped&) {
        return;  // clean shutdown
      } catch (const AsyncError& e) {
        // A broken inference promise mid-training — or, via the
        // StateTableError subclass, a DIRECT slot-hook call
        // (connect-time reset, unroll-boundary read) landing inside
        // the poison-to-rebuild window. Either may come from a
        // RECOVERING serving thread (state-table rebuild) — discard
        // the partial rollout and retry the stream under the same
        // budget/backoff as a reconnect (the PR 6 Python contract),
        // instead of retiring the actor for good.
        if (grant_retry()) {
          batch_retries_.fetch_add(1);
          continue;
        }
        // Re-checked AFTER the failed grant: shutdown landing during
        // the backoff sleep must exit cleanly, not record an error.
        if (shutting_down()) return;
        record_error(e.what());
        return;
      } catch (const SocketError& e) {
        // Transport failure (env-server death / stream cut): reconnect
        // with a fresh env + reset agent state. The reconnect is
        // COUNTED only once the new stream delivers (loop() clears
        // reconnect_pending after the initial step) — attempts that
        // fail before streaming are budget, not recoveries.
        if (grant_retry()) {
          reconnect_pending = true;
          continue;
        }
        if (shutting_down()) return;
        record_error(e.what());
        return;
      } catch (const wire::WireError& e) {
        // A corrupt frame (bit-flipped tcp stream, stomped shm ring) is
        // a per-connection failure, not a pool failure — same
        // reconnect contract as the Python pool.
        if (grant_retry()) {
          reconnect_pending = true;
          continue;
        }
        if (shutting_down()) return;
        record_error(e.what());
        return;
      } catch (const std::exception& e) {
        record_error(e.what());
        return;
      } catch (...) {
        record_error("unknown error");
        return;
      }
    }
  }

  // Step message -> env-output nest with [T=1, B=1] leading dims.
  static ArrayNest env_outputs_from(const wire::ValueNest& msg) {
    if (!msg.is_dict()) throw SocketError("expected dict Step message");
    const auto& dict = msg.dict();
    auto type_it = dict.find("type");
    if (type_it != dict.end() && type_it->second.is_leaf() &&
        type_it->second.leaf().kind == wire::Value::Kind::kString &&
        type_it->second.leaf().s == "error") {
      auto m = dict.find("message");
      throw std::runtime_error(
          "Env server error: " +
          (m != dict.end() && m->second.is_leaf() ? m->second.leaf().s : ""));
    }
    ArrayNest::Dict out;
    for (const std::string& key : env_keys()) {
      auto it = dict.find(key);
      if (it == dict.end() || !it->second.is_leaf() ||
          it->second.leaf().kind != wire::Value::Kind::kArray)
        throw SocketError("Step message missing array field: " + key);
      const Array& a = it->second.leaf().array;
      std::vector<int64_t> shape = {1, 1};
      shape.insert(shape.end(), a.shape().begin(), a.shape().end());
      // Clone: the wire buffer is reused per message (RecvBuffer / shm
      // ring slot); rollout storage must own its bytes.
      Array expanded(a.dtype(), shape);
      std::memcpy(expanded.mutable_data(), a.data(), a.nbytes());
      out.emplace(key, ArrayNest(std::move(expanded)));
    }
    return ArrayNest(std::move(out));
  }

  struct StepPair {
    ArrayNest env;
    ArrayNest agent;
  };

  template <typename T>
  static Array scalar_array(DType dtype, T value) {
    Array a(dtype, {1, 1});
    std::memcpy(a.mutable_data(), &value, sizeof(T));
    return a;
  }

  ArrayNest recv_step(Transport* t) {
    auto [msg, nbytes] = t->recv_sized();
    bytes_up_.fetch_add(static_cast<int64_t>(nbytes));
    return env_outputs_from(msg);
  }

  void loop(int64_t index, const std::string& address, int64_t* progress,
            bool* reconnect_pending) {
    std::unique_ptr<Transport> sock =
        shm::connect_transport(address, connect_timeout_s_, max_frame_bytes_);
    if (fault_hooks_) {
      // Chaos interposition: every (re)connection gets wrapped, so
      // injected faults see post-reconnect streams too (the Python
      // pool's transport_wrap contract).
      sock = std::make_unique<ChaosTransport>(std::move(sock), index,
                                              fault_hooks_.get());
    }
    connects_.fetch_add(1);
    // shm connections: sweep the ring segments on EVERY teardown — a
    // SIGKILL'd env server can't clean up its own, and for a live
    // server this only pre-empts its own unlink (segments are
    // per-connection, never re-attached).
    struct Sweep {
      Transport* t;
      ~Sweep() { t->unlink_segments(); }
    } sweep{sock.get()};

    // Fresh stream => fresh recurrent state. In slot mode this resets
    // the actor's table slot (covers reconnects: the partial rollout
    // was discarded, so the slot must restart from the initial state)
    // and fetches the host copy for the rollout boundary.
    ArrayNest initial_agent_state =
        use_slots_ ? slot_reset_(index) : initial_agent_state_;

    ArrayNest env_outputs = recv_step(sock.get());
    // The stream is re-established AND delivering: a granted reconnect
    // retry counts as a completed recovery now — not at grant time, so
    // attempts that die before streaming (a stale socket file, a
    // mid-respawn handshake) never inflate the count past the faults.
    if (*reconnect_pending) {
      *reconnect_pending = false;
      reconnect_count_.fetch_add(1);
    }
    ArrayNest agent_state = initial_agent_state;

    // Shed contract (ISSUE 14): a ShedError from compute() is FLOW
    // CONTROL — re-submit the SAME request after a jittered backoff,
    // outside the reconnect budget, so a shed can never retire this
    // actor or lose the rollout. The backoff starts smaller than the
    // reconnect one (overload drains in batches, not server-restart
    // time) and resets after every served request. Counted at catch
    // time, making the resubmitted == shed + expired audit exact.
    Backoff shed_backoff(0.05, 1.0);
    auto abort_shed = [this] { return shutting_down(); };
    auto shed_compute = [&](ArrayNest inputs) {
      while (true) {
        try {
          ArrayNest result = inference_batcher_->compute(inputs);
          shed_backoff.reset();
          return result;
        } catch (const ShedError&) {
          shed_resubmits_.fetch_add(1);
          if (shutting_down())
            throw QueueStopped("shutdown during shed retry");
          shed_backoff.sleep(abort_shed);
        }
      }
    };

    auto compute = [this, index, &shed_compute](
                       const ArrayNest& env, ArrayNest* state,
                       bool advance) {
      ArrayNest::Dict inputs;
      inputs.emplace("env", env);
      if (use_slots_) {
        inputs.emplace("slot", ArrayNest(scalar_array<int32_t>(
                                   DType::kI32, static_cast<int32_t>(index))));
        inputs.emplace("advance", ArrayNest(scalar_array<uint8_t>(
                                      DType::kBool, advance ? 1 : 0)));
        ArrayNest result = shed_compute(ArrayNest(inputs));
        return normalize_lag(result.dict().at("outputs"));
      }
      inputs.emplace("agent_state", *state);
      ArrayNest result = shed_compute(ArrayNest(inputs));
      const auto& d = result.dict();
      if (advance) *state = d.at("agent_state");
      return normalize_lag(d.at("outputs"));
    };

    // Prime the boundary agent output (state advance discarded — the
    // first in-rollout compute re-consumes this env output for real).
    ArrayNest agent_outputs = compute(env_outputs, &agent_state,
                                      /*advance=*/false);

    std::vector<StepPair> rollout;
    rollout.push_back({env_outputs, agent_outputs});
    ArrayNest rollout_initial_state = initial_agent_state;

    while (true) {
      agent_outputs = compute(env_outputs, &agent_state, /*advance=*/true);

      // Extract the scalar action from outputs["action"] ([1,1]).
      const Array& action_arr =
          agent_outputs.dict().at("action").front();
      int64_t action = read_scalar_i64(action_arr);

      wire::ValueNest::Dict action_msg;
      action_msg.emplace("type",
                         wire::ValueNest(wire::Value::of_string("action")));
      action_msg.emplace("action",
                         wire::ValueNest(wire::Value::of_int(action)));
      bytes_down_.fetch_add(
          static_cast<int64_t>(sock->send(wire::ValueNest(std::move(action_msg)))));

      env_outputs = recv_step(sock.get());
      ++(*progress);
      count_.fetch_add(1);
      rollout.push_back({env_outputs, agent_outputs});

      if (static_cast<int64_t>(rollout.size()) == unroll_length_ + 1) {
        enqueue_rollout(rollout, rollout_initial_state);
        rollout.erase(rollout.begin(), rollout.end() - 1);  // overlap-by-one
        // Boundary state for the NEXT rollout: slot mode fetches it
        // from the device table once per unroll (the only time agent
        // state crosses the host boundary); legacy mode carries it
        // from the last reply.
        rollout_initial_state = use_slots_ ? slot_read_(index) : agent_state;
      }
    }
  }

  // The Python pool's _normalize_lag (runtime/actor_pool.py): central
  // replies carry no policy_lag leaf (their params rebind every update
  // — lag is definitionally 0); replica replies stamp the real lag.
  // Rollout stacking needs one structure, so the missing leaf becomes
  // explicit zeros. Off (the default) this is a single branch.
  ArrayNest normalize_lag(ArrayNest outputs) const {
    if (!record_policy_lag_ || !outputs.is_dict()) return outputs;
    ArrayNest::Dict d = outputs.dict();
    if (d.find("policy_lag") != d.end()) return outputs;
    d.emplace("policy_lag",
              ArrayNest(scalar_array<int32_t>(DType::kI32, 0)));
    return ArrayNest(std::move(d));
  }

  static int64_t read_scalar_i64(const Array& a) {
    switch (a.dtype()) {
      case DType::kI32:
        return *reinterpret_cast<const int32_t*>(a.data());
      case DType::kI64:
        return *reinterpret_cast<const int64_t*>(a.data());
      case DType::kU8:
        return *a.data();
      default:
        throw std::invalid_argument("action must be integer typed");
    }
  }

  void enqueue_rollout(const std::vector<StepPair>& rollout,
                       const ArrayNest& initial_state) {
    std::vector<ArrayNest> envs, agents;
    envs.reserve(rollout.size());
    agents.reserve(rollout.size());
    for (const StepPair& p : rollout) {
      envs.push_back(p.env);
      agents.push_back(p.agent);
    }
    // Stack along time dim 0 -> [T+1, 1, ...].
    ArrayNest env_stack = batch_nests(envs, 0);
    ArrayNest agent_stack = batch_nests(agents, 0);

    ArrayNest::Dict batch = env_stack.dict();
    for (const auto& [k, v] : agent_stack.dict()) batch.emplace(k, v);

    ArrayNest::Dict item;
    item.emplace("batch", ArrayNest(std::move(batch)));
    item.emplace("initial_agent_state", initial_state);
    learner_queue_->enqueue(ArrayNest(std::move(item)), 0);
  }

  const int64_t unroll_length_;
  std::shared_ptr<LearnerQueue> learner_queue_;
  std::shared_ptr<InferenceClient> inference_batcher_;
  const std::vector<std::string> addresses_;
  const ArrayNest initial_agent_state_;
  const double connect_timeout_s_;
  const int64_t max_reconnects_;
  const bool use_slots_;
  const SlotHook slot_reset_;
  const SlotHook slot_read_;
  const size_t max_frame_bytes_;
  const bool record_policy_lag_;

  std::atomic<int64_t> count_{0};
  std::atomic<int64_t> reconnect_count_{0};
  std::atomic<int64_t> batch_retries_{0};
  std::atomic<int64_t> shed_resubmits_{0};
  std::atomic<int64_t> connects_{0};
  std::atomic<int64_t> dead_{0};  // retired actor loops (live_actors())
  std::atomic<int64_t> bytes_up_{0};
  std::atomic<int64_t> bytes_down_{0};
  std::unique_ptr<FaultHooks> fault_hooks_;  // non-null only when armed
  mutable std::mutex error_mu_;
  std::exception_ptr first_error_;  // guarded-by: error_mu_
  std::vector<std::string> error_messages_;  // guarded-by: error_mu_
};

}  // namespace tbt
