// Shared-memory SPSC ring transport — the C++ side of runtime/transport.py's
// shm data plane. Byte-level layout parity with the Python ShmRing is a
// hard contract (beastlint WIRE-PARITY pins it): a Python env server and a
// C++ actor loop attach the SAME segments, so every constant below must
// match transport.py exactly.
//
// Ring layout (u64 little-endian words at the segment head):
//   [0:8)  head      (monotonic byte counter, producer-owned)
//   [8:16) tail      (monotonic byte counter, consumer-owned)
//   [16:24) capacity
//   [24:32) waiting  (consumer's blocked flag, the coalesced-doorbell latch)
//   data at [kRingHeaderBytes, kRingHeaderBytes + capacity)
//
// Frames are contiguous [u32 length][bytes]; a u32 wrap marker (or < 4
// bytes of tail room) skips the remainder at the segment end; an inline
// marker reserves a message's ORDER SLOT while its bytes ride the doorbell
// socket (too big for the ring). Only frames <= capacity/2 - 4 ever enter
// the ring (bigger ones can be position-dependently unplaceable forever).
//
// The doorbell socket is the blocking primitive and the crash detector:
// the sender rings the 1-byte bell only when the reader's waiting flag is
// set (futex-style coalescing); peer death closes the socket, which
// surfaces as SocketError — the same teardown contract as tcp.

#pragma once

#include <fcntl.h>
#include <poll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/time.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstring>
#include <functional>
#include <memory>
#include <string>
#include <thread>
#include <vector>

#include "client.h"
#include "wire.h"

namespace tbt {
namespace shm {

// --- Layout constants (WIRE-PARITY pins these against transport.py) ---
constexpr size_t kRingHeaderBytes = 64;
// u64-word indices into the header (transport.py _HEAD/_TAIL/_CAP/_WAITING).
constexpr size_t kRingHeadWord = 0;
constexpr size_t kRingTailWord = 1;
constexpr size_t kRingCapacityWord = 2;
constexpr size_t kRingWaitingWord = 3;
// In-ring u32 markers (transport.py _WRAP/_INLINE).
constexpr uint32_t kRingWrapMarker = 0xFFFFFFFF;
constexpr uint32_t kRingInlineMarker = 0xFFFFFFFE;
// Doorbell control bytes (transport.py _DOORBELL_WAKE/_DOORBELL_INLINE).
constexpr uint8_t kDoorbellWake = 0x01;
constexpr uint8_t kDoorbellInline = 0x02;
// Default per-direction capacities (transport.py DEFAULT_*_RING_BYTES).
constexpr size_t kDefaultObsRingBytes = 4 * 1024 * 1024;
constexpr size_t kDefaultActRingBytes = 256 * 1024;

// Reader-side wait tuning (matches transport.py's rationale; the exact
// values are latency knobs, not wire format).
// The INITIAL bound on the (fence-less) lost-wakeup stall. 20ms like
// transport.py's _WAKE_RECHECK_S: under scheduler pressure a doorbell
// hop can be late or lost, and a tight recheck caps that stall at one
// scheduling quantum; an idle connection pays only 50 wakeups/s for it.
constexpr int kWakeRecheckMs = 20;
// Adaptive recheck policy (ISSUE 12): per-connection, the bound walks
// within [kRecheckMinMs, kRecheckMaxMs] driven by the
// ring.doorbell_waits / ring.recheck_wakeups counters' local window —
// a recheck-heavy window (>= kRecheckTighten of kRecheckWindow waits
// ended by the timeout: doorbells are being lost/late, the ROADMAP
// metastability signature) HALVES the bound so each stall costs less;
// a quiescent window (<= kRecheckRelax) DOUBLES it back toward idle
// cheapness. All five constants are pinned cross-language against
// analysis/protocol.py (ATOMIC-ORDER _check_recheck); the model
// checker's timeout transition covers any bound in the range (it only
// needs the recheck to stay FINITE — kRecheckMinMs > 0).
constexpr int kRecheckMinMs = 5;
constexpr int kRecheckMaxMs = 100;
constexpr int kRecheckWindow = 32;
constexpr int kRecheckTighten = 16;
constexpr int kRecheckRelax = 4;
constexpr double kEmptySpinS = 100e-6;  // rate-matched pairs stay syscall-free

// Per-connection adaptive recheck state (single-threaded like the
// transport that owns it). record(true) = a wait ended by the bounded
// poll timeout instead of a doorbell byte.
class AdaptiveRecheck {
 public:
  int bound_ms() const { return bound_ms_; }

  void record(bool recheck) {
    ++waits_;
    if (recheck) ++rechecks_;
    if (waits_ < kRecheckWindow) return;
    if (rechecks_ >= kRecheckTighten) {
      bound_ms_ = bound_ms_ / 2 < kRecheckMinMs ? kRecheckMinMs
                                                : bound_ms_ / 2;
    } else if (rechecks_ <= kRecheckRelax) {
      bound_ms_ = bound_ms_ * 2 > kRecheckMaxMs ? kRecheckMaxMs
                                                : bound_ms_ * 2;
    }
    waits_ = rechecks_ = 0;
  }

 private:
  int bound_ms_ = kWakeRecheckMs;
  int waits_ = 0;
  int rechecks_ = 0;
};

inline uint32_t load_u32le(const uint8_t* p) {
  uint32_t x = 0;
  std::memcpy(&x, p, 4);  // little-endian hosts only, like the codec
  return x;
}

// Doorbell-wait observability (mirrors transport.py's
// ring.doorbell_waits / ring.recheck_wakeups counters; the Python
// driver folds these into the telemetry registry via
// NativeTelemetryFolder). Process-wide because transports are
// per-connection and may die before a telemetry tick — cumulative
// counters survive the connection. doorbell_waits counts every
// armed+blocked wait; recheck_wakeups the subset ended by the bounded
// kWakeRecheckMs poll timeout instead of a doorbell byte. A growing
// recheck share is the ROADMAP metastability signature.
struct RingWaitCounters {
  std::atomic<int64_t> doorbell_waits{0};
  std::atomic<int64_t> recheck_wakeups{0};
};

inline RingWaitCounters& ring_wait_counters() {
  static RingWaitCounters counters;
  return counters;
}

// One mapped SPSC ring. Move-only; the mapping is shared with the peer
// process, so head/tail/waiting go through atomics (the Python side's
// plain u64 stores are single aligned stores; release/acquire here gives
// the C++ threads the same data-then-head publish ordering x86 gives
// Python for free, and keeps TSan clean).
class ShmRing {
 public:
  ShmRing() = default;
  ShmRing(const ShmRing&) = delete;
  ShmRing& operator=(const ShmRing&) = delete;
  ShmRing(ShmRing&& other) noexcept { *this = std::move(other); }
  ShmRing& operator=(ShmRing&& other) noexcept {
    close();
    base_ = other.base_;
    map_bytes_ = other.map_bytes_;
    capacity_ = other.capacity_;
    publish_head_ = other.publish_head_;
    owner_ = other.owner_;
    name_ = std::move(other.name_);
    other.base_ = nullptr;
    other.map_bytes_ = 0;
    return *this;
  }
  ~ShmRing() { close(); }

  static ShmRing create(size_t capacity) {
    static std::atomic<uint64_t> counter{0};
    std::string name;
    int fd = -1;
    for (int attempt = 0; attempt < 64; ++attempt) {
      name = "tbtring_" + std::to_string(::getpid()) + "_" +
             std::to_string(counter.fetch_add(1)) + "_" +
             std::to_string(std::chrono::steady_clock::now()
                                .time_since_epoch()
                                .count() &
                            0xffff);
      fd = ::shm_open(("/" + name).c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
      if (fd >= 0) break;
      if (errno != EEXIST) break;  // only name collisions are retryable
    }
    if (fd < 0)
      throw SocketError(std::string("shm_open(create) failed: ") +
                        ::strerror(errno));
    ShmRing ring;
    ring.name_ = name;
    ring.owner_ = true;
    ring.map_bytes_ = kRingHeaderBytes + capacity;
    if (::ftruncate(fd, static_cast<off_t>(ring.map_bytes_)) != 0) {
      ::close(fd);
      ::shm_unlink(("/" + name).c_str());
      throw SocketError("ftruncate failed for shm ring");
    }
    ring.base_ = static_cast<uint8_t*>(::mmap(
        nullptr, ring.map_bytes_, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0));
    ::close(fd);
    if (ring.base_ == MAP_FAILED) {
      ring.base_ = nullptr;
      ::shm_unlink(("/" + name).c_str());
      throw SocketError("mmap failed for shm ring");
    }
    ring.capacity_ = capacity;
    ring.word(kRingHeadWord)->store(0, std::memory_order_relaxed);
    ring.word(kRingTailWord)->store(0, std::memory_order_relaxed);
    ring.word(kRingCapacityWord)->store(capacity, std::memory_order_relaxed);
    ring.word(kRingWaitingWord)->store(0, std::memory_order_release);
    return ring;
  }

  // Attach a segment the peer created. Python's SharedMemory names come
  // over the handshake without the leading "/" shm_open requires.
  static ShmRing attach(const std::string& name) {
    std::string path = name.empty() || name[0] == '/' ? name : "/" + name;
    int fd = ::shm_open(path.c_str(), O_RDWR, 0);
    if (fd < 0) throw SocketError("shm_open(attach) failed for " + name);
    struct stat st{};
    if (::fstat(fd, &st) != 0) {
      ::close(fd);
      throw SocketError("fstat failed for shm ring " + name);
    }
    ShmRing ring;
    ring.name_ = name;
    ring.owner_ = false;
    ring.map_bytes_ = static_cast<size_t>(st.st_size);
    ring.base_ = static_cast<uint8_t*>(::mmap(
        nullptr, ring.map_bytes_, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0));
    ::close(fd);
    if (ring.base_ == MAP_FAILED) {
      ring.base_ = nullptr;
      throw SocketError("mmap failed for shm ring " + name);
    }
    uint64_t capacity =
        ring.word(kRingCapacityWord)->load(std::memory_order_acquire);
    if (capacity == 0 || kRingHeaderBytes + capacity > ring.map_bytes_) {
      ring.close();
      throw wire::WireError("shm ring " + name + ": bad capacity " +
                            std::to_string(capacity));
    }
    ring.capacity_ = static_cast<size_t>(capacity);
    return ring;
  }

  const std::string& name() const { return name_; }
  size_t capacity() const { return capacity_; }
  bool valid() const { return base_ != nullptr; }

  // Largest frame routed through the ring; bigger frames ride the inline
  // socket path (same capacity/2 - 4 bound as transport.py — a frame
  // needing a wrap skip can demand skip + frame > capacity free bytes,
  // position-dependently unsatisfiable forever).
  size_t max_frame_bytes() const { return capacity_ / 2 - 4; }

  // -- producer --------------------------------------------------------
  void write_frame(const uint8_t* frame, size_t n,
                   const std::function<void()>& peer_check) {
    size_t need = 4 + n;
    if (need > capacity_)
      throw wire::WireError("Frame of " + std::to_string(n) +
                            " bytes exceeds ring capacity " +
                            std::to_string(capacity_));
    size_t pos = reserve(need, peer_check);
    uint32_t len = static_cast<uint32_t>(n);
    std::memcpy(data() + pos, &len, 4);
    std::memcpy(data() + pos + 4, frame, n);
    word(kRingHeadWord)->store(publish_head_, std::memory_order_release);
  }

  void write_inline_marker(const std::function<void()>& peer_check) {
    size_t pos = reserve(4, peer_check);
    uint32_t marker = kRingInlineMarker;
    std::memcpy(data() + pos, &marker, 4);
    word(kRingHeadWord)->store(publish_head_, std::memory_order_release);
  }

  bool reader_waiting() const {
    return word(kRingWaitingWord)->load(std::memory_order_acquire) != 0;
  }

  // -- consumer --------------------------------------------------------
  bool has_frame() const {
    return word(kRingHeadWord)->load(std::memory_order_acquire) !=
           word(kRingTailWord)->load(std::memory_order_relaxed);
  }

  void set_waiting(bool value) {
    word(kRingWaitingWord)
        ->store(value ? 1 : 0, std::memory_order_seq_cst);
  }

  struct Frame {
    const uint8_t* data;  // nullptr for an inline marker
    size_t size;
    size_t advance;
    bool is_inline;
  };

  Frame read_frame() {
    uint64_t tail = word(kRingTailWord)->load(std::memory_order_relaxed);
    uint64_t head = word(kRingHeadWord)->load(std::memory_order_acquire);
    if (head - tail < 4) throw wire::WireError("shm ring: read without a frame");
    size_t pos = tail % capacity_;
    size_t skipped = 0;
    size_t tail_room = capacity_ - pos;
    uint32_t length = 0;
    if (tail_room < 4) {
      skipped = tail_room;
      pos = 0;
    } else {
      length = load_u32le(data() + pos);
      if (length == kRingWrapMarker) {
        skipped = tail_room;
        pos = 0;
      }
    }
    if (skipped) length = load_u32le(data() + pos);
    if (length == kRingInlineMarker) return {nullptr, 0, skipped + 4, true};
    if (length > capacity_ - 4 || skipped + 4 + length > head - tail)
      throw wire::WireError("shm ring: bad frame length " +
                            std::to_string(length) + " at " +
                            std::to_string(pos));
    return {data() + pos + 4, length, skipped + 4 + length, false};
  }

  void release(size_t advance) {
    uint64_t tail = word(kRingTailWord)->load(std::memory_order_relaxed);
    word(kRingTailWord)->store(tail + advance, std::memory_order_release);
  }

  // -- chaos hook -------------------------------------------------------
  // Stomp the frame queued at tail — poke parity with the Python
  // ShmRing.poke path in resilience/chaos._corrupt_ring, byte for byte:
  // header mode writes an impossible length (0xDEADBEEF) the reader's
  // next read_frame deterministically rejects as WireError; payload
  // mode flips <= 4 bytes clamped to the payload AND the data region.
  // Returns 1 when the stomp observably landed (tail stable: the frame
  // was not consumed mid-stomp), 0 when the ring is momentarily empty /
  // the frame is a marker / the reader raced us — the injector retries.
  // Never called on a healthy path.
  int corrupt_tail_frame(bool header) {
    uint64_t tail = word(kRingTailWord)->load(std::memory_order_acquire);
    uint64_t head = word(kRingHeadWord)->load(std::memory_order_acquire);
    if (head - tail < 8) return 0;  // need a real frame, not just a marker
    size_t pos = tail % capacity_;
    if (capacity_ - pos < 4) pos = 0;  // implicit wrap: frame starts at base
    if (header) {
      // Not WRAP/INLINE, way past any sane length. (Stomping a WRAP
      // marker is equally observable: the reader decodes the bogus
      // length and rejects it.)
      uint32_t poison = 0xDEADBEEF;
      std::memcpy(data() + pos, &poison, 4);
    } else {
      uint32_t length = load_u32le(data() + pos);
      if (length >= kRingInlineMarker) return 0;  // marker: no payload here
      size_t n = 4;
      if (static_cast<size_t>(length) < n) n = length;
      if (capacity_ - pos - 4 < n) n = capacity_ - pos - 4;
      if (n == 0) return 0;
      static const uint8_t pat[4] = {0xa5, 0x5a, 0xa5, 0x5a};
      std::memcpy(data() + pos + 4, pat, n);
    }
    // If the reader consumed the frame while we were stomping, the
    // bytes landed in free space the producer will overwrite — the
    // fault did NOT observably fire; report failure so the caller
    // retries (same tail-stability contract as the Python injector).
    return word(kRingTailWord)->load(std::memory_order_seq_cst) == tail
               ? 1
               : 0;
  }

  // -- teardown --------------------------------------------------------
  // Best-effort unlink regardless of ownership — the crash sweep for a
  // dead owner (mirrors ShmRing.unlink in transport.py; existing
  // mappings stay valid until unmapped).
  void unlink() {
    if (name_.empty()) return;
    std::string path = name_[0] == '/' ? name_ : "/" + name_;
    ::shm_unlink(path.c_str());
  }

  void close() {
    if (base_ != nullptr) {
      ::munmap(base_, map_bytes_);
      base_ = nullptr;
      if (owner_) unlink();
    }
  }

 private:
  std::atomic<uint64_t>* word(size_t i) const {
    return reinterpret_cast<std::atomic<uint64_t>*>(base_ + 8 * i);
  }
  uint8_t* data() const { return base_ + kRingHeaderBytes; }

  size_t reserve(size_t need, const std::function<void()>& peer_check) {
    uint64_t head = word(kRingHeadWord)->load(std::memory_order_relaxed);
    size_t pos = head % capacity_;
    size_t tail_room = capacity_ - pos;
    if (need > tail_room) {
      wait_free(head, tail_room + need, peer_check);
      if (tail_room >= 4) {
        uint32_t marker = kRingWrapMarker;
        std::memcpy(data() + pos, &marker, 4);
      }
      head += tail_room;
      pos = 0;
    } else {
      wait_free(head, need, peer_check);
    }
    publish_head_ = head + need;
    return pos;
  }

  void wait_free(uint64_t head, size_t need,
                 const std::function<void()>& peer_check) {
    auto deadline = std::chrono::steady_clock::time_point::min();
    int64_t ticks = 0;
    while (capacity_ -
               (head - word(kRingTailWord)->load(std::memory_order_acquire)) <
           need) {
      if (deadline == std::chrono::steady_clock::time_point::min()) {
        deadline = std::chrono::steady_clock::now() +
                   std::chrono::seconds(120);
      } else if (std::chrono::steady_clock::now() > deadline) {
        throw wire::WireError("shm ring full for 120s (reader stalled?)");
      }
      ++ticks;
      if (peer_check && ticks % 200 == 0) peer_check();  // ~every 20ms
      std::this_thread::sleep_for(std::chrono::microseconds(100));
    }
  }

  uint8_t* base_ = nullptr;
  size_t map_bytes_ = 0;
  size_t capacity_ = 0;
  uint64_t publish_head_ = 0;
  bool owner_ = false;
  std::string name_;
};

// Framed messages over a ring pair + doorbell socket; same contract as
// transport.py's ShmTransport: the rings are the data plane AND the
// ordering authority, the socket is the blocking primitive, the crash
// detector, and the inline carrier for oversized frames. Single-threaded
// per connection (one actor loop), like every transport here.
class ShmTransport : public Transport {
 public:
  ShmTransport(int fd, ShmRing send_ring, ShmRing recv_ring,
               size_t max_frame_bytes = wire::kMaxFrameBytes)
      : fd_(fd),
        send_ring_(std::move(send_ring)),
        recv_ring_(std::move(recv_ring)),
        max_frame_bytes_(max_frame_bytes) {}

  ~ShmTransport() override { close(); }

  size_t send(const wire::ValueNest& value) override {
    std::vector<uint8_t> framed = wire::encode(value);
    auto peer_check = [this] { check_peer_alive(); };
    if (framed.size() <= send_ring_.max_frame_bytes()) {
      send_ring_.write_frame(framed.data(), framed.size(), peer_check);
      if (send_ring_.reader_waiting()) send_doorbell(kDoorbellWake);
    } else {
      send_ring_.write_inline_marker(peer_check);
      if (send_ring_.reader_waiting()) send_doorbell(kDoorbellWake);
      send_doorbell(kDoorbellInline);
      send_all(framed.data(), framed.size());
    }
    return framed.size();
  }

  std::pair<wire::ValueNest, size_t> recv_sized() override {
    if (pending_release_) {
      recv_ring_.release(pending_release_);
      pending_release_ = 0;
    }
    if (!wait_for_frame())
      throw SocketError("connection closed by peer");
    ShmRing::Frame f = recv_ring_.read_frame();
    pending_release_ = f.advance;
    if (f.is_inline) return recv_inline_frame();
    if (f.size < 4) throw wire::WireError("shm ring: truncated frame header");
    uint32_t payload_len = load_u32le(f.data);
    if (payload_len != f.size - 4)
      throw wire::WireError("shm ring: header says " +
                            std::to_string(payload_len) + ", frame has " +
                            std::to_string(f.size - 4));
    if (payload_len > max_frame_bytes_)
      throw wire::WireError("Frame length " + std::to_string(payload_len) +
                            " exceeds max_frame_bytes");
    // Zero-copy decode out of the mapped ring; the slot is released at
    // the NEXT recv (same buffer-reuse lifetime rule as the Python
    // transport — the actor pool clones env fields per step anyway).
    return {wire::decode(f.data + 4, payload_len, nullptr),
            f.size};
  }

  // Crash sweep: unlink both segments regardless of ownership (a
  // SIGKILL'd owner can't; for a live one this only pre-empts its own
  // unlink — rings are per-connection and never re-attached).
  void unlink_segments() override {
    send_ring_.unlink();
    recv_ring_.unlink();
  }

  // Chaos hooks (csrc/chaos.h): sever the doorbell — peer-death
  // semantics for both sides (a blocked reader's poll wakes to EOF, a
  // blocked writer's peer probe fails) — and the ring-poke injector.
  void shutdown_stream() override {
    if (fd_ >= 0) ::shutdown(fd_, SHUT_RDWR);
  }

  int corrupt_recv_ring(bool header) override {
    return recv_ring_.corrupt_tail_frame(header);
  }

  void close() override {
    if (fd_ >= 0) {
      ::close(fd_);
      fd_ = -1;
    }
    send_ring_.close();
    recv_ring_.close();
  }

 private:
  void send_doorbell(uint8_t byte) { send_all(&byte, 1); }

  void send_all(const uint8_t* p, size_t n) {
    size_t sent = 0;
    while (sent < n) {
      ssize_t r = ::send(fd_, p + sent, n - sent, 0);
      if (r <= 0) throw SocketError("shm doorbell send failed");
      sent += static_cast<size_t>(r);
    }
  }

  // Probe the doorbell while a send is blocked on ring space: a DEAD
  // peer must fail the send promptly. Queued stale WAKE bytes are
  // consumed so they can't mask the EOF behind them (wakeups are only
  // needed while this end is blocked in wait_for_frame; the transport
  // is single-threaded per connection, so any 0x01 queued during a send
  // is stale by definition). An inline 0x02 is left for recv_sized.
  void check_peer_alive() {
    // A consumed 0x02 whose frame bytes are still queued proves the
    // peer alive AND makes the socket head payload, not doorbell —
    // probing now could eat a payload byte that happens to be 0x01.
    if (inline_consumed_) return;
    while (true) {
      uint8_t b = 0;
      ssize_t r = ::recv(fd_, &b, 1, MSG_PEEK | MSG_DONTWAIT);
      if (r < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
          return;  // alive; nothing queued
        throw SocketError("shm peer connection lost during ring wait");
      }
      if (r == 0) throw SocketError("shm peer closed during ring wait");
      if (b == kDoorbellWake) {
        ::recv(fd_, &b, 1, MSG_DONTWAIT);
        continue;  // re-probe: EOF may hide behind stale wakeups
      }
      return;  // inline traffic queued: peer alive, leave it alone
    }
  }

  // Block until the recv ring has a frame; false on clean EOF with a
  // drained ring. The waiting-flag dance keeps a busy pair syscall-free;
  // the bounded poll re-checks the ring against the fence-less
  // lost-wakeup window (same recovery as transport.py).
  bool wait_for_frame() {
    while (true) {
      if (recv_ring_.has_frame()) return true;
      auto spin_until = std::chrono::steady_clock::now() +
                        std::chrono::duration_cast<
                            std::chrono::steady_clock::duration>(
                            std::chrono::duration<double>(kEmptySpinS));
      while (std::chrono::steady_clock::now() < spin_until) {
        if (recv_ring_.has_frame()) return true;
      }
      recv_ring_.set_waiting(true);
      if (recv_ring_.has_frame()) {
        recv_ring_.set_waiting(false);
        continue;
      }
      ring_wait_counters().doorbell_waits.fetch_add(
          1, std::memory_order_relaxed);
      struct pollfd p {fd_, POLLIN, 0};
      // Adaptive bound (ISSUE 12): recheck-heavy windows tighten it,
      // quiescent ones relax it — see AdaptiveRecheck above.
      int pr = ::poll(&p, 1, recheck_.bound_ms());
      if (pr == 0) {
        recv_ring_.set_waiting(false);
        ring_wait_counters().recheck_wakeups.fetch_add(
            1, std::memory_order_relaxed);
        recheck_.record(true);
        continue;  // re-check the ring (lost-wakeup guard)
      }
      if (pr < 0) {
        recv_ring_.set_waiting(false);
        if (errno == EINTR) continue;
        throw SocketError("shm doorbell poll failed");
      }
      uint8_t b = 0;
      ssize_t r = ::recv(fd_, &b, 1, 0);
      recv_ring_.set_waiting(false);
      if (r > 0) recheck_.record(false);  // a byte ended this wait
      if (r == 0) {
        // Peer closed. Frames already in the ring stay deliverable;
        // EOF surfaces once it drains.
        return recv_ring_.has_frame();
      }
      if (r < 0) {
        if (errno == EINTR) continue;
        throw SocketError("shm doorbell recv failed");
      }
      if (b == kDoorbellInline) {
        // The fence-less waiting-flag race can land the inline byte on
        // a blocked reader before the WAKE was seen; the send syscall
        // fences the sender's marker publish, so the marker must be in
        // the ring by now.
        if (!recv_ring_.has_frame())
          throw wire::WireError("shm: inline byte with an empty ring");
        inline_consumed_ = true;
        return true;
      }
      if (b != kDoorbellWake)
        throw wire::WireError("Bad doorbell byte " + std::to_string(b));
      // Stale wakeup: loop and re-check the ring.
    }
  }

  // The ring said this message rides the socket: skip stale wakeups up
  // to the 0x02 byte (unless wait_for_frame already consumed it), then
  // read one framed message off the socket.
  std::pair<wire::ValueNest, size_t> recv_inline_frame() {
    while (!inline_consumed_) {
      uint8_t b = 0;
      ssize_t r = ::recv(fd_, &b, 1, 0);
      if (r == 0)
        throw wire::WireError("Connection closed before inline frame");
      if (r < 0) {
        if (errno == EINTR) continue;
        throw SocketError("shm doorbell recv failed");
      }
      if (b == kDoorbellInline) break;
      if (b != kDoorbellWake)
        throw wire::WireError("Bad doorbell byte " + std::to_string(b));
    }
    inline_consumed_ = false;
    uint8_t header[4];
    recv_exact(header, 4);
    uint32_t length = load_u32le(header);
    if (length > max_frame_bytes_)
      throw wire::WireError("wire: frame length " + std::to_string(length) +
                            " exceeds max_frame_bytes");
    auto payload = std::make_shared<std::vector<uint8_t>>(length);
    recv_exact(payload->data(), length);
    return {wire::decode(payload->data(), length, payload),
            4 + static_cast<size_t>(length)};
  }

  void recv_exact(uint8_t* out, size_t n) {
    size_t got = 0;
    while (got < n) {
      ssize_t r = ::recv(fd_, out + got, n - got, 0);
      if (r == 0) throw SocketError("connection closed by peer");
      if (r < 0) {
        if (errno == EINTR) continue;
        throw SocketError("recv failed");
      }
      got += static_cast<size_t>(r);
    }
  }

  int fd_ = -1;
  ShmRing send_ring_;
  ShmRing recv_ring_;
  size_t max_frame_bytes_;
  size_t pending_release_ = 0;
  bool inline_consumed_ = false;
  AdaptiveRecheck recheck_;
};

// -- handshake (both roles) -------------------------------------------
// Same protocol as transport.py: the server creates the per-connection
// rings and sends {"type": "shm_handshake", "version": 1, "s2c": name,
// "c2s": name}; the client attaches and acks {"type": "shm_ok"}.

inline std::string handshake_string(const wire::ValueNest& msg,
                                    const std::string& key) {
  if (!msg.is_dict()) throw wire::WireError("Bad shm handshake message");
  const auto& dict = msg.dict();
  auto it = dict.find(key);
  if (it == dict.end() || !it->second.is_leaf() ||
      it->second.leaf().kind != wire::Value::Kind::kString)
    throw wire::WireError("shm handshake missing " + key);
  return it->second.leaf().s;
}

// Client role: the doorbell socket is already connected; complete the
// handshake and return the transport (send ring = c2s, recv = s2c).
inline std::unique_ptr<Transport> shm_client_transport(
    FramedSocket&& sock, size_t max_frame_bytes = wire::kMaxFrameBytes) {
  wire::ValueNest hs = sock.recv();
  if (handshake_string(hs, "type") != "shm_handshake")
    throw wire::WireError("Expected shm handshake");
  ShmRing s2c = ShmRing::attach(handshake_string(hs, "s2c"));
  ShmRing c2s = ShmRing::attach(handshake_string(hs, "c2s"));
  wire::ValueNest::Dict ack;
  ack.emplace("type", wire::ValueNest(wire::Value::of_string("shm_ok")));
  sock.send(wire::ValueNest(std::move(ack)));
  int fd = sock.release();
  return std::make_unique<ShmTransport>(fd, std::move(c2s), std::move(s2c),
                                        max_frame_bytes);
}

// Server role: create the rings, send the handshake, wait for the ack
// (send ring = s2c, recv = c2s). The created rings are owner-unlinked at
// transport close, so a clean stream end leaves /dev/shm empty.
inline std::unique_ptr<Transport> shm_server_transport(
    FramedSocket&& sock, size_t obs_ring_bytes = kDefaultObsRingBytes,
    size_t act_ring_bytes = kDefaultActRingBytes,
    size_t max_frame_bytes = wire::kMaxFrameBytes) {
  ShmRing s2c = ShmRing::create(obs_ring_bytes);
  ShmRing c2s = ShmRing::create(act_ring_bytes);
  wire::ValueNest::Dict hs;
  hs.emplace("type",
             wire::ValueNest(wire::Value::of_string("shm_handshake")));
  hs.emplace("version", wire::ValueNest(wire::Value::of_int(1)));
  hs.emplace("s2c", wire::ValueNest(wire::Value::of_string(s2c.name())));
  hs.emplace("c2s", wire::ValueNest(wire::Value::of_string(c2s.name())));
  sock.send(wire::ValueNest(std::move(hs)));
  // Bounded ack wait, matching transport.py's handshake_timeout_s: a
  // peer that connects but never acks (crashed mid-handshake, stray
  // prober) must not pin the serve thread. The timeout makes recv
  // throw, and stack unwind owner-unlinks both just-created rings.
  struct timeval tv = {};
  tv.tv_sec = 30;
  ::setsockopt(sock.fd(), SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  wire::ValueNest ack = sock.recv();
  tv.tv_sec = 0;  // back to blocking before the fd becomes the doorbell
  ::setsockopt(sock.fd(), SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  if (handshake_string(ack, "type") != "shm_ok")
    throw wire::WireError("Bad shm handshake ack");
  int fd = sock.release();
  return std::make_unique<ShmTransport>(fd, std::move(s2c), std::move(c2s),
                                        max_frame_bytes);
}

// Address helpers (transport.py shm_socket_path): "shm:/p" and
// "shm:///p" -> "/p", the unix doorbell socket path.
inline bool is_shm_address(const std::string& address) {
  return address.rfind("shm:", 0) == 0;
}

inline std::string shm_socket_path(const std::string& address) {
  std::string path = address.substr(4);
  if (path.rfind("//", 0) == 0) path = path.substr(2);
  if (path.empty()) throw SocketError("Empty shm address: " + address);
  return path;
}

// The client-side factory the actor pool uses: SocketTransport semantics
// for unix:/host:port, handshaken ShmTransport for shm: addresses.
inline std::unique_ptr<Transport> connect_transport(
    const std::string& address, double deadline_s,
    size_t max_frame_bytes = wire::kMaxFrameBytes) {
  FramedSocket sock;
  if (is_shm_address(address)) {
    sock.connect("unix:" + shm_socket_path(address), deadline_s);
    return shm_client_transport(std::move(sock), max_frame_bytes);
  }
  sock.connect(address, deadline_s);
  sock.set_max_frame_bytes(max_frame_bytes);
  return std::make_unique<FramedSocket>(std::move(sock));
}

}  // namespace shm
}  // namespace tbt
