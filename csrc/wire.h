// Framed binary codec — byte-compatible with torchbeast_tpu/runtime/wire.py
// (see that module for the format spec). Values are Nest<Array> plus
// scalar leaves folded into a tagged Message struct; decode is zero-copy:
// arrays alias the shared payload buffer.

#pragma once

#include <cstring>
#include <memory>
#include <optional>
#include <stdexcept>
#include <string>
#include <vector>

#include "array.h"
#include "nest.h"

namespace tbt {
namespace wire {

constexpr uint8_t kTagArray = 0x01;
constexpr uint8_t kTagList = 0x02;
constexpr uint8_t kTagDict = 0x03;
constexpr uint8_t kTagNone = 0x04;
constexpr uint8_t kTagInt = 0x05;
constexpr uint8_t kTagFloat = 0x06;
constexpr uint8_t kTagBool = 0x07;
constexpr uint8_t kTagString = 0x08;
// Versioned policy snapshot (fleet control plane, ISSUE 17). Python is
// the only publisher (fleet/snapshot_wire.py builds wire.PolicySnapshot
// messages); the C++ side decodes the frame into a reserved-key dict —
// {"__snapshot__": version, "params": nest, "dtypes": nest} — so a
// native observer on a control-plane socket never trips "unknown tag"
// on fleet traffic. Mirrors wire.py TAG_SNAPSHOT (WIRE-PARITY).
constexpr uint8_t kTagSnapshot = 0x09;

class WireError : public std::runtime_error {
 public:
  using std::runtime_error::runtime_error;
};

// Upper bound on a frame's payload length. A corrupt 4-byte header must
// surface as WireError before anyone allocates what it claims (a flipped
// bit can demand 4 GiB otherwise). Matches wire.py's
// DEFAULT_MAX_FRAME_BYTES; enforced at the frame readers (client.h).
constexpr size_t kMaxFrameBytes = 256ull * 1024 * 1024;

// A decoded wire value: arrays, strings, ints... The runtime only needs
// arrays + strings + ints for Step/Action messages, so the leaf is a small
// tagged struct rather than a full dynamic type.
struct Value {
  enum class Kind { kNone, kArray, kInt, kFloat, kBool, kString } kind =
      Kind::kNone;
  Array array;
  int64_t i = 0;
  double f = 0.0;
  bool b = false;
  std::string s;

  static Value of(Array a) {
    Value v;
    v.kind = Kind::kArray;
    v.array = std::move(a);
    return v;
  }
  static Value of_int(int64_t x) {
    Value v;
    v.kind = Kind::kInt;
    v.i = x;
    return v;
  }
  static Value of_string(std::string x) {
    Value v;
    v.kind = Kind::kString;
    v.s = std::move(x);
    return v;
  }
};

using ValueNest = Nest<Value>;

namespace detail {

inline void put_u32(std::vector<uint8_t>* buf, uint32_t x) {
  buf->push_back(x & 0xff);
  buf->push_back((x >> 8) & 0xff);
  buf->push_back((x >> 16) & 0xff);
  buf->push_back((x >> 24) & 0xff);
}

inline void put_i64(std::vector<uint8_t>* buf, int64_t x) {
  for (int i = 0; i < 8; ++i) buf->push_back((static_cast<uint64_t>(x) >> (8 * i)) & 0xff);
}

inline void put_bytes(std::vector<uint8_t>* buf, const void* p, size_t n) {
  const uint8_t* b = static_cast<const uint8_t*>(p);
  buf->insert(buf->end(), b, b + n);
}

struct Reader {
  const uint8_t* data;
  size_t size;
  size_t pos = 0;
  std::shared_ptr<void> owner;  // keeps the payload alive for array views

  uint8_t u8() {
    need(1);
    return data[pos++];
  }
  uint32_t u32() {
    need(4);
    uint32_t x = 0;
    for (int i = 0; i < 4; ++i) x |= static_cast<uint32_t>(data[pos + i]) << (8 * i);
    pos += 4;
    return x;
  }
  int64_t i64() {
    need(8);
    uint64_t x = 0;
    for (int i = 0; i < 8; ++i) x |= static_cast<uint64_t>(data[pos + i]) << (8 * i);
    pos += 8;
    return static_cast<int64_t>(x);
  }
  const uint8_t* bytes(size_t n) {
    need(n);
    const uint8_t* p = data + pos;
    pos += n;
    return p;
  }
  void need(size_t n) const {
    // Written as a subtraction so a huge `n` cannot wrap `pos + n` back
    // into range; `pos <= size` is an invariant of every advance above.
    if (n > size - pos) throw WireError("wire: truncated payload");
  }
};

}  // namespace detail

inline void encode_value(std::vector<uint8_t>* buf, const ValueNest& nest) {
  if (nest.is_leaf()) {
    const Value& v = nest.leaf();
    switch (v.kind) {
      case Value::Kind::kNone:
        buf->push_back(kTagNone);
        return;
      case Value::Kind::kBool:
        buf->push_back(kTagBool);
        buf->push_back(v.b ? 1 : 0);
        return;
      case Value::Kind::kInt:
        buf->push_back(kTagInt);
        detail::put_i64(buf, v.i);
        return;
      case Value::Kind::kFloat: {
        buf->push_back(kTagFloat);
        double d = v.f;
        detail::put_bytes(buf, &d, 8);
        return;
      }
      case Value::Kind::kString:
        buf->push_back(kTagString);
        detail::put_u32(buf, static_cast<uint32_t>(v.s.size()));
        detail::put_bytes(buf, v.s.data(), v.s.size());
        return;
      case Value::Kind::kArray: {
        const Array& a = v.array;
        buf->push_back(kTagArray);
        buf->push_back(static_cast<uint8_t>(a.dtype()));
        buf->push_back(static_cast<uint8_t>(a.ndim()));
        for (int64_t d : a.shape()) detail::put_i64(buf, d);
        detail::put_bytes(buf, a.data(), a.nbytes());
        return;
      }
    }
    throw WireError("wire: bad value kind");
  }
  if (nest.is_list()) {
    buf->push_back(kTagList);
    detail::put_u32(buf, static_cast<uint32_t>(nest.list().size()));
    for (const auto& n : nest.list()) encode_value(buf, n);
    return;
  }
  buf->push_back(kTagDict);
  detail::put_u32(buf, static_cast<uint32_t>(nest.dict().size()));
  for (const auto& [key, n] : nest.dict()) {
    uint16_t klen = static_cast<uint16_t>(key.size());
    buf->push_back(klen & 0xff);
    buf->push_back((klen >> 8) & 0xff);
    detail::put_bytes(buf, key.data(), key.size());
    encode_value(buf, n);
  }
}

// Full frame: u32 length prefix + payload.
inline std::vector<uint8_t> encode(const ValueNest& nest) {
  std::vector<uint8_t> payload;
  encode_value(&payload, nest);
  std::vector<uint8_t> framed;
  framed.reserve(payload.size() + 4);
  detail::put_u32(&framed, static_cast<uint32_t>(payload.size()));
  framed.insert(framed.end(), payload.begin(), payload.end());
  return framed;
}

inline ValueNest decode_value(detail::Reader* r) {
  uint8_t tag = r->u8();
  switch (tag) {
    case kTagNone:
      return ValueNest(Value{});
    case kTagBool: {
      Value v;
      v.kind = Value::Kind::kBool;
      v.b = r->u8() != 0;
      return ValueNest(std::move(v));
    }
    case kTagInt:
      return ValueNest(Value::of_int(r->i64()));
    case kTagFloat: {
      Value v;
      v.kind = Value::Kind::kFloat;
      std::memcpy(&v.f, r->bytes(8), 8);
      return ValueNest(std::move(v));
    }
    case kTagString: {
      uint32_t n = r->u32();
      const uint8_t* p = r->bytes(n);
      return ValueNest(
          Value::of_string(std::string(reinterpret_cast<const char*>(p), n)));
    }
    case kTagArray: {
      DType dtype = static_cast<DType>(r->u8());
      size_t isize = itemsize(dtype);  // throws on unknown dtype byte
      uint8_t ndim = r->u8();
      std::vector<int64_t> shape(ndim);
      for (auto& d : shape) d = r->i64();
      // Untrusted dims: reject negatives and anything whose byte count
      // could not fit in the frame anyway. The remaining payload bounds
      // the product, so overflow-check against that rather than SIZE_MAX.
      // Any zero dim makes the array empty regardless of the other dims.
      bool empty = false;
      for (int64_t d : shape) {
        if (d < 0) throw WireError("wire: negative array dim");
        if (d == 0) empty = true;
      }
      const size_t remaining = r->size - r->pos;
      size_t nbytes = empty ? 0 : isize;
      if (!empty) {
        for (int64_t d : shape) {
          if (nbytes > remaining / static_cast<size_t>(d))
            throw WireError("wire: array size exceeds payload");
          nbytes *= static_cast<size_t>(d);
        }
      }
      const uint8_t* p = r->bytes(nbytes);
      // Zero-copy: the array aliases the payload buffer via the owner.
      return ValueNest(Value::of(Array(
          dtype, std::move(shape), const_cast<uint8_t*>(p), r->owner)));
    }
    case kTagList: {
      uint32_t n = r->u32();
      // Each element is at least 1 byte, so an honest count is bounded by
      // the remaining payload — reserve() on a raw attacker u32 would be
      // a one-frame multi-GB allocation.
      if (n > r->size - r->pos)
        throw WireError("wire: list count exceeds payload");
      ValueNest::List out;
      out.reserve(n);
      for (uint32_t i = 0; i < n; ++i) out.push_back(decode_value(r));
      return ValueNest(std::move(out));
    }
    case kTagDict: {
      uint32_t n = r->u32();
      if (n > r->size - r->pos)
        throw WireError("wire: dict count exceeds payload");
      ValueNest::Dict out;
      for (uint32_t i = 0; i < n; ++i) {
        uint16_t klen = r->u8();
        klen |= static_cast<uint16_t>(r->u8()) << 8;
        const uint8_t* p = r->bytes(klen);
        std::string key(reinterpret_cast<const char*>(p), klen);
        out.emplace(std::move(key), decode_value(r));
      }
      return ValueNest(std::move(out));
    }
    case kTagSnapshot: {
      // u64le version + params value + dtypes value (wire.py layout).
      // Reuse the i64 reader: snapshot versions are update counts and
      // never approach the sign bit.
      int64_t version = r->i64();
      if (version < 0) throw WireError("wire: negative snapshot version");
      ValueNest params = decode_value(r);
      ValueNest dtypes = decode_value(r);
      ValueNest::Dict out;
      out.emplace("__snapshot__", ValueNest(Value::of_int(version)));
      out.emplace("params", std::move(params));
      out.emplace("dtypes", std::move(dtypes));
      return ValueNest(std::move(out));
    }
    default:
      throw WireError("wire: unknown tag " + std::to_string(tag));
  }
}

// Payload (no length prefix); `owner` must keep `data` alive as long as the
// decoded arrays are used.
inline ValueNest decode(const uint8_t* data, size_t size,
                        std::shared_ptr<void> owner) {
  detail::Reader r{data, size, 0, std::move(owner)};
  ValueNest out = decode_value(&r);
  if (r.pos != r.size) throw WireError("wire: trailing garbage");
  return out;
}

}  // namespace wire
}  // namespace tbt
