// FaultHooks: the C++ side of resilience/chaos.py (ISSUE 12). The
// Python ChaosController cannot wrap the native pool's transports (they
// live in C++ actor threads), so the pool owns ONE FaultHooks instance
// the controller reaches through pymodule entry points
// (chaos_sever/chaos_window/chaos_corrupt_ring on the pool object):
//
//   - transport_sever      -> shutdown(SHUT_RDWR) on the actor's live
//                             transport: a parked recv wakes with the
//                             same EOF a real cable cut produces.
//   - transport_delay /    -> a per-actor perturbation window consulted
//     transport_blackhole     by the actor loop before every send/recv
//                             (ChaosTransport wrapper), sleeping the op
//                             exactly like the Python FaultingTransport.
//   - shm_corrupt_*        -> ShmRing::corrupt_tail_frame through the
//                             transport (poke parity with the Python
//                             ShmRing.poke path, tail-stability checked
//                             so "injected" means OBSERVABLE).
//
// Entry points run on the Python chaos thread (GIL released by
// pymodule's call_nogil); registration/perturbation run on actor
// threads. The hooks mutex serializes injector calls against transport
// teardown: an actor unregisters (under mu_) before destroying its
// transport, so an injector holding mu_ can never touch a freed one.
// Pools without --chaos_plan never construct the wrapper: the hot path
// pays nothing when chaos is unarmed.

#pragma once

#include <chrono>
#include <map>
#include <memory>
#include <mutex>
#include <thread>
#include <utility>

#include "client.h"

namespace tbt {

class FaultHooks {
 public:
  // -- actor-thread side ------------------------------------------------
  void register_transport(int64_t index, Transport* t) {
    std::lock_guard<std::mutex> lock(mu_);
    transports_[index] = t;
  }

  void unregister_transport(int64_t index, Transport* t) {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = transports_.find(index);
    if (it != transports_.end() && it->second == t) transports_.erase(it);
  }

  // Called before every send/recv on a wrapped transport. The window
  // state is copied out under the lock and slept OUTSIDE it (a blackhole
  // must stall the actor, not the injector thread).
  void perturb(int64_t index) {
    bool is_delay = false;
    double delay_s = 0.0;
    std::chrono::steady_clock::time_point until{};
    {
      std::lock_guard<std::mutex> lock(mu_);
      auto it = windows_.find(index);
      if (it == windows_.end()) return;
      is_delay = it->second.is_delay;
      delay_s = it->second.delay_s;
      until = it->second.until;
    }
    auto now = std::chrono::steady_clock::now();
    if (now >= until) {
      std::lock_guard<std::mutex> lock(mu_);
      auto it = windows_.find(index);
      if (it != windows_.end() && it->second.until == until)
        windows_.erase(it);
      return;
    }
    if (is_delay) {
      std::this_thread::sleep_for(std::chrono::duration<double>(delay_s));
    } else {  // blackhole: hold the op until the window heals
      std::this_thread::sleep_until(until);
    }
  }

  // -- injector side (Python chaos thread via pymodule) -----------------
  // False = no live transport for that actor right now (between
  // connections): the controller retries on a later tick, so injected
  // counts stay exact.
  bool sever(int64_t index) {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = transports_.find(index);
    if (it == transports_.end()) return false;
    it->second->shutdown_stream();
    return true;
  }

  bool arm_window(int64_t index, bool is_delay, double duration_s,
                  double delay_s) {
    std::lock_guard<std::mutex> lock(mu_);
    if (transports_.find(index) == transports_.end()) return false;
    windows_[index] = Window{
        is_delay,
        std::chrono::steady_clock::now() +
            std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                std::chrono::duration<double>(duration_s)),
        delay_s};
    return true;
  }

  // True when the stomp observably landed in an unconsumed frame
  // (ShmRing::corrupt_tail_frame's tail-stability check); False when
  // the actor has no shm transport or the ring is momentarily empty —
  // the controller retries next tick, same contract as _corrupt_ring.
  bool corrupt_recv_ring(int64_t index, bool header) {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = transports_.find(index);
    if (it == transports_.end()) return false;
    return it->second->corrupt_recv_ring(header) == 1;
  }

 private:
  struct Window {
    bool is_delay;  // false = blackhole
    std::chrono::steady_clock::time_point until;
    double delay_s;
  };

  std::mutex mu_;
  std::map<int64_t, Transport*> transports_;  // guarded-by: mu_
  std::map<int64_t, Window> windows_;         // guarded-by: mu_
};

// Per-op fault interposition for one actor loop: forwards everything to
// the wrapped transport, consulting the hooks' perturbation window first
// — the C++ twin of chaos.py's FaultingTransport. Registers the INNER
// transport so injectors act on the real stream.
class ChaosTransport : public Transport {
 public:
  ChaosTransport(std::unique_ptr<Transport> inner, int64_t index,
                 FaultHooks* hooks)
      : inner_(std::move(inner)), index_(index), hooks_(hooks) {
    hooks_->register_transport(index_, inner_.get());
  }

  ~ChaosTransport() override {
    // Unregister BEFORE the member destructor frees inner_: an injector
    // holding the hooks mutex must never race transport teardown.
    hooks_->unregister_transport(index_, inner_.get());
  }

  size_t send(const wire::ValueNest& value) override {
    hooks_->perturb(index_);
    return inner_->send(value);
  }

  std::pair<wire::ValueNest, size_t> recv_sized() override {
    hooks_->perturb(index_);
    return inner_->recv_sized();
  }

  void unlink_segments() override { inner_->unlink_segments(); }
  void shutdown_stream() override { inner_->shutdown_stream(); }
  int corrupt_recv_ring(bool header) override {
    return inner_->corrupt_recv_ring(header);
  }
  void close() override { inner_->close(); }

 private:
  std::unique_ptr<Transport> inner_;
  const int64_t index_;
  FaultHooks* const hooks_;
};

}  // namespace tbt
